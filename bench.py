#!/usr/bin/env python3
"""Benchmark: --oneshot label-generation p50 latency.

This is the BASELINE.md target metric ("--oneshot label-generation p50
latency"; the reference publishes no numbers of its own — BASELINE.json
`published` is empty). The baseline constant below is the reference's only
in-repo latency bound: its sleep-loop test asserts a full label pass +
atomic rewrite lands within a 1s interval (gpu-feature-discovery
cmd/gpu-feature-discovery/main_test.go:199,230-242). vs_baseline is
therefore 1000ms / p50ms — higher is better, 1.0 = parity with that bound.

Method: run the shipped binary end-to-end (process spawn -> backend init ->
label generation -> atomic file write) against the hermetic mock backend
with the v5p-128 multi-host fixture (the most label-heavy config), 40 runs,
report the median. Set TFD_BENCH_BACKEND=pjrt|metadata|auto to point the
same end-to-end run at a real backend instead of mock (the mock fixture
and slice strategy flags are dropped; init then costs whatever the real
stack costs).

When a TPU is visible to jax, the measured-silicon probes (tpufd.health,
the --device-health=full payload) also run once and their results ride
along in the same JSON line as tpu_matmul_tflops / tpu_hbm_gbps — the
throughput numbers the reference cannot produce at all (GFD never
exercises the GPU).
"""

import json
import os
import statistics
import subprocess
import sys
import tempfile
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent
BUILD = REPO / "build"
BINARY = BUILD / "tpu-feature-discovery"

BASELINE_MS = 1000.0  # reference main_test.go rewrite-within-1s bound
RUNS = int(os.environ.get("TFD_BENCH_RUNS", "40"))


def ensure_built():
    if BINARY.exists():
        return
    subprocess.run(["cmake", "-S", str(REPO), "-B", str(BUILD), "-G",
                    "Ninja", "-DCMAKE_BUILD_TYPE=Release"],
                   check=True, capture_output=True)
    subprocess.run(["ninja", "-C", str(BUILD)], check=True,
                   capture_output=True)


def one_run(out_file, backend):
    args = [str(BINARY), "--oneshot", f"--backend={backend}",
            "--machine-type-file=/dev/null", f"--output-file={out_file}"]
    if backend == "mock":
        # Hermetic: a stripped env (plus metadata-host poisoning) so the
        # mock run never touches a real GCE metadata server.
        env = {"PATH": "/usr/bin:/bin",
               "GCE_METADATA_HOST": "invalid.localdomain:1"}
        args += [
            "--mock-topology-file="
            f"{REPO / 'tests/fixtures/v5p-128-worker3.yaml'}",
            "--slice-strategy=mixed",
        ]
    else:
        # Real backends need the ambient env (libtpu/GCE vars, proxies).
        env = dict(os.environ)
    start = time.perf_counter()
    proc = subprocess.run(args, env=env, capture_output=True)
    elapsed_ms = (time.perf_counter() - start) * 1000.0
    if proc.returncode != 0:
        sys.stderr.write(proc.stderr.decode())
        raise SystemExit(f"bench run failed: exit {proc.returncode}")
    return elapsed_ms


def tpu_probe_numbers():
    """Measured bf16 matmul TFLOP/s and HBM GB/s on the local chip, when
    one is visible to jax; {} otherwise (or when
    TFD_BENCH_SKIP_TPU_PROBE is set — tests). Differential timing in
    tpufd.health already rides out relay/tunnel quirks."""
    if os.environ.get("TFD_BENCH_SKIP_TPU_PROBE"):
        return {}
    try:
        sys.path.insert(0, str(REPO))
        import jax

        if jax.devices()[0].platform != "tpu":
            return {}
        from tpufd import health

        # Median of 3 independent probe runs: a single differential pair
        # can still catch tunnel jitter and report above chip peak.
        return {
            "tpu_matmul_tflops": round(statistics.median(
                health.matmul_tflops() for _ in range(3)), 1),
            "tpu_hbm_gbps": round(statistics.median(
                health.hbm_gbps() for _ in range(3)), 1),
        }
    except Exception as e:  # noqa: BLE001 — bench must not die on probe
        sys.stderr.write(f"tpu probe skipped: {e}\n")
        return {}


def main():
    ensure_built()
    backend = os.environ.get("TFD_BENCH_BACKEND", "mock")
    with tempfile.TemporaryDirectory() as tmp:
        out_file = str(Path(tmp) / "tfd")
        one_run(out_file, backend)  # warm page cache
        samples = [one_run(out_file, backend) for _ in range(RUNS)]
    p50 = statistics.median(samples)
    record = {
        "metric": "oneshot_label_p50_ms",
        "value": round(p50, 3),
        "unit": "ms",
        "vs_baseline": round(BASELINE_MS / p50, 2),
    }
    if backend != "mock":
        record["backend"] = backend
    record.update(tpu_probe_numbers())
    print(json.dumps(record))


if __name__ == "__main__":
    main()

#!/usr/bin/env python3
"""Benchmark: --oneshot label-generation p50 latency.

This is the BASELINE.md target metric ("--oneshot label-generation p50
latency"; the reference publishes no numbers of its own — BASELINE.json
`published` is empty). The baseline constant below is the reference's only
in-repo latency bound: its sleep-loop test asserts a full label pass +
atomic rewrite lands within a 1s interval (gpu-feature-discovery
cmd/gpu-feature-discovery/main_test.go:199,230-242). vs_baseline is
therefore 1000ms / p50ms — higher is better, 1.0 = parity with that bound.

Method: run the shipped binary end-to-end (process spawn -> backend init ->
label generation -> atomic file write) against the hermetic mock backend
with the v5p-128 multi-host fixture (the most label-heavy config), 40 runs,
report the median. On a machine with a real TPU or GCE metadata the same
binary exercises those paths instead when TFD_BENCH_BACKEND is set.
"""

import json
import statistics
import subprocess
import sys
import tempfile
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent
BUILD = REPO / "build"
BINARY = BUILD / "tpu-feature-discovery"

BASELINE_MS = 1000.0  # reference main_test.go rewrite-within-1s bound
RUNS = 40


def ensure_built():
    if BINARY.exists():
        return
    subprocess.run(["cmake", "-S", str(REPO), "-B", str(BUILD), "-G",
                    "Ninja", "-DCMAKE_BUILD_TYPE=Release"],
                   check=True, capture_output=True)
    subprocess.run(["ninja", "-C", str(BUILD)], check=True,
                   capture_output=True)


def one_run(out_file):
    args = [
        str(BINARY), "--oneshot",
        "--backend=mock",
        f"--mock-topology-file={REPO / 'tests/fixtures/v5p-128-worker3.yaml'}",
        "--slice-strategy=mixed",
        "--machine-type-file=/dev/null",
        f"--output-file={out_file}",
    ]
    env = {"PATH": "/usr/bin:/bin", "GCE_METADATA_HOST": "invalid.localdomain:1"}
    start = time.perf_counter()
    proc = subprocess.run(args, env=env, capture_output=True)
    elapsed_ms = (time.perf_counter() - start) * 1000.0
    if proc.returncode != 0:
        sys.stderr.write(proc.stderr.decode())
        raise SystemExit(f"bench run failed: exit {proc.returncode}")
    return elapsed_ms


def main():
    ensure_built()
    with tempfile.TemporaryDirectory() as tmp:
        out_file = str(Path(tmp) / "tfd")
        one_run(out_file)  # warm page cache
        samples = [one_run(out_file) for _ in range(RUNS)]
    p50 = statistics.median(samples)
    print(json.dumps({
        "metric": "oneshot_label_p50_ms",
        "value": round(p50, 3),
        "unit": "ms",
        "vs_baseline": round(BASELINE_MS / p50, 2),
    }))


if __name__ == "__main__":
    main()

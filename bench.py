#!/usr/bin/env python3
"""Benchmark: --oneshot label-generation p50 latency, per backend.

This is the BASELINE.md target metric ("--oneshot label-generation p50
latency"; the reference publishes no numbers of its own — BASELINE.json
`published` is empty). The baseline constant below is the reference's only
in-repo latency bound: its sleep-loop test asserts a full label pass +
atomic rewrite lands within a 1s interval (gpu-feature-discovery
cmd/gpu-feature-discovery/main_test.go:199,230-242). vs_baseline is
therefore 1000ms / p50ms — higher is better, 1.0 = parity with that bound.

Method: run the shipped binary end-to-end (process spawn -> backend init ->
label generation -> atomic file write) and report medians for every
backend that can run here:
  - mock      (headline): hermetic v5p-128 multi-host fixture, the most
              label-heavy config.
  - metadata  : against the in-process fake GCE metadata server, so the
              p50 includes real HTTP round-trips for accelerator-type,
              tpu-env, worker-id fallbacks, machine type.
  - pjrt      : against the fake PJRT plugin, so the p50 includes the
              real dlopen + GetPjrtApi + client-create + device
              enumeration path AND the init watchdog's fork/JSON-pipe
              overhead (pjrt_watchdog.cc).
  - auto      : the chips-busy PRODUCTION path — --backend=auto with
              PJRT init failing and the metadata fallback serving the
              labels; what a degraded node pays per pass.
  - auto_deadline / auto_deadline_steady : worst case — a WEDGED libtpu,
              measured inside ONE sleep-loop daemon. The first pass burns
              the full --pjrt-init-timeout (1s in the bench; 30s
              production default) before the fallback — deadline-
              inclusive by construction. Passes >=2 ride the failure memo
              (--pjrt-retry-backoff) and price like the metadata path:
              the steady number is what a wedged node actually pays per
              sleep-interval.
  - pjrt_real : the pjrt backend labeling REAL silicon — the directly-
              attached libtpu when one works, else the ambient relay
              PJRT plugin (tunneled-TPU environments; discovered via
              PJRT_LIBRARY_PATH, driven with --pjrt-client-option).
              pjrt_real_source records which. Null only when every
              candidate fails client creation (e.g. chips held by a
              training job — on such nodes the shipped daemon serves
              from the metadata fallback, which the auto p50 prices).
All p50s ride in ONE JSON line; the headline value stays comparable
across rounds (override which backend is the headline with
TFD_BENCH_BACKEND=pjrt|metadata|auto).

When a TPU is visible to jax, the measured-silicon probes (tpufd.health,
the --device-health=full payload) also run once and their results ride
along in the same JSON line as tpu_matmul_tflops / tpu_hbm_gbps — the
throughput numbers the reference cannot produce at all (GFD never
exercises the GPU).
"""

import json
import os
import statistics
import subprocess
import sys
import tempfile
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent
BUILD = REPO / "build"
BINARY = BUILD / "tpu-feature-discovery"
FAKE_PJRT = BUILD / "libtfd_fake_pjrt.so"

BASELINE_MS = 1000.0  # reference main_test.go rewrite-within-1s bound
RUNS = int(os.environ.get("TFD_BENCH_RUNS", "40"))
# Non-headline backends get fewer runs: each sample is a full process +
# backend init, and three extra medians must not dominate bench wall time.
SIDE_RUNS = max(5, RUNS // 4)

# 127.0.0.1:1 fails with an instant connection-refused; a hostname like
# invalid.localdomain would pay resolver latency that varies 5-20ms run
# to run and shows up as a bimodal pjrt p50.
HERMETIC_ENV = {"PATH": "/usr/bin:/bin",
                "GCE_METADATA_HOST": "127.0.0.1:1"}


def ensure_built():
    if BINARY.exists() and FAKE_PJRT.exists():
        return
    subprocess.run(["cmake", "-S", str(REPO), "-B", str(BUILD), "-G",
                    "Ninja", "-DCMAKE_BUILD_TYPE=Release"],
                   check=True, capture_output=True)
    subprocess.run(["ninja", "-C", str(BUILD)], check=True,
                   capture_output=True)


def one_run(out_file, backend, extra_args=(), env=None, check_backend=None):
    """One end-to-end oneshot pass; returns elapsed ms.

    check_backend: when set, the written label file must claim that
    backend — catches a silent fallback that would make the number lie
    about what it measured."""
    args = [str(BINARY), "--oneshot", f"--backend={backend}",
            "--machine-type-file=/dev/null", f"--output-file={out_file}",
            *extra_args]
    if env is None:
        # Real backends need the ambient env (libtpu/GCE vars, proxies).
        env = dict(os.environ)
    start = time.perf_counter()
    proc = subprocess.run(args, env=env, capture_output=True)
    elapsed_ms = (time.perf_counter() - start) * 1000.0
    if proc.returncode != 0:
        sys.stderr.write(proc.stderr.decode())
        raise SystemExit(f"bench run failed: exit {proc.returncode}")
    if check_backend is not None:
        labels = Path(out_file).read_text()
        want = f"google.com/tpu.backend={check_backend}\n"
        if want not in labels:
            raise RuntimeError(
                f"run did not come from the {check_backend} backend")
    return elapsed_ms


def p50_of(runs, out_file, backend, **kwargs):
    one_run(out_file, backend, **kwargs)  # warm (page cache, dlopen cache)
    samples = [one_run(out_file, backend, **kwargs) for _ in range(runs)]
    return round(statistics.median(samples), 3)


def mock_kwargs():
    return {
        "extra_args": [
            "--mock-topology-file="
            f"{REPO / 'tests/fixtures/v5p-128-worker3.yaml'}",
            "--slice-strategy=mixed",
        ],
        # Hermetic: a stripped env (plus metadata-host poisoning) so the
        # mock run never touches a real GCE metadata server.
        "env": dict(HERMETIC_ENV),
    }


def config4_server():
    """The canonical BASELINE config-4 fixture (v5p-128 worker 3) behind
    the fake GCE metadata server — shared by every bench that measures
    the metadata-serving paths so they all price the same config."""
    if str(REPO) not in sys.path:  # repeated callers must not
        sys.path.insert(0, str(REPO))  # stack duplicate entries
    from tpufd.fakes.metadata_server import (FakeMetadataServer,
                                             v5p_128_worker3)

    return FakeMetadataServer(v5p_128_worker3())


def metadata_p50(out_file):
    """p50 against the fake GCE metadata server (BASELINE config 4 data):
    the path a chips-busy node serves labels from."""
    with config4_server() as server:
        env = dict(HERMETIC_ENV, GCE_METADATA_HOST=server.endpoint)
        return p50_of(
            SIDE_RUNS, out_file, "metadata",
            extra_args=[f"--metadata-endpoint={server.endpoint}",
                        "--slice-strategy=mixed"],
            env=env, check_backend="metadata")


def pjrt_fake_p50(out_file):
    """p50 through the real dlopen/PJRT-call path (fake plugin), including
    the init watchdog's forked probe."""
    env = dict(HERMETIC_ENV,
               TFD_FAKE_PJRT_KIND="TPU v5p",
               TFD_FAKE_PJRT_BOUNDS="2,2,1",
               TFD_FAKE_PJRT_HBM_GIB="95")
    return p50_of(
        SIDE_RUNS, out_file, "pjrt",
        extra_args=[f"--libtpu-path={FAKE_PJRT}"],
        env=env, check_backend="pjrt")


def auto_p50(out_file):
    """p50 of the chips-busy PRODUCTION path: --backend=auto with PJRT
    init failing (a training job holds the exclusive chips) and the
    metadata fallback serving the labels — the end-to-end latency a
    degraded node actually pays per pass, the number an SRE sizing
    --sleep-interval needs. --pjrt-retry-backoff=0 forces the probe
    every sample so the number prices a real failed probe, not the
    memo's instant short-circuit."""
    with config4_server() as server:
        env = dict(HERMETIC_ENV, GCE_METADATA_HOST=server.endpoint,
                   TFD_FAKE_PJRT_FAIL="chips busy (held by training job)")
        return p50_of(
            SIDE_RUNS, out_file, "auto",
            extra_args=[f"--libtpu-path={FAKE_PJRT}",
                        f"--metadata-endpoint={server.endpoint}",
                        "--slice-strategy=mixed",
                        "--pjrt-init-timeout=1",
                        "--pjrt-retry-backoff=0"],
            env=env, check_backend="metadata")


def auto_deadline_p50s(out_file):
    """The wedged-libtpu worst case, measured as the DAEMON experiences
    it: one sleep-loop daemon whose fake libtpu hangs (the watchdog burns
    the full --pjrt-init-timeout, 1s here / 30s production default), with
    per-pass wall times parsed from the daemon's own pass log. Returns
    (first_pass_ms, steady_p50_ms): the first pass is deadline-inclusive
    by design; passes >=2 ride the failure memo (--pjrt-retry-backoff)
    and must price like the metadata path, NOT like the deadline — the
    memo exists precisely so a wedged node doesn't pay the deadline every
    sleep-interval."""
    import re

    passes_wanted = 6
    with config4_server() as server:
        # TFD_FORCE_SLOW_PASS keeps this metric measuring what it always
        # measured: the full render+merge+sink cost of a wedged-node
        # pass. Without it passes >=2 are fingerprint-clean no-ops
        # (steady_noop_p50_us prices those) and never log "wrote".
        env = dict(HERMETIC_ENV, GCE_METADATA_HOST=server.endpoint,
                   TFD_FAKE_PJRT_HANG="1", TFD_FORCE_SLOW_PASS="1")
        args = [str(BINARY), "--sleep-interval=1s", "--backend=auto",
                f"--libtpu-path={FAKE_PJRT}",
                f"--metadata-endpoint={server.endpoint}",
                "--slice-strategy=mixed", "--pjrt-init-timeout=1",
                "--machine-type-file=/dev/null",
                f"--output-file={out_file}"]
        proc = subprocess.Popen(args, env=env, stdout=subprocess.DEVNULL,
                                stderr=subprocess.PIPE)
        pass_ms = []
        try:
            # select()-driven read: a daemon wedged BEFORE its first pass
            # line must hit the deadline, not block the bench in readline.
            import select
            fd = proc.stderr.fileno()
            buf = b""
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline and len(pass_ms) < passes_wanted:
                ready, _, _ = select.select([fd], [], [], 1.0)
                if not ready:
                    continue
                chunk = os.read(fd, 65536)
                if not chunk:
                    break
                buf += chunk
                *lines, buf = buf.split(b"\n")
                for line in lines:
                    m = re.search(rb"wrote \d+ labels.* in (\d+)ms", line)
                    if m:
                        pass_ms.append(int(m.group(1)))
            # Self-validate the path like one_run's check_backend — read
            # BEFORE terminate (the daemon removes its file on SIGTERM):
            # the passes must have come from the metadata fallback behind
            # a wedged PJRT, or the numbers measure the wrong thing.
            labels = Path(out_file).read_text()
            if "google.com/tpu.backend=metadata\n" not in labels:
                raise RuntimeError(
                    "daemon passes did not come from the metadata fallback")
        finally:
            proc.terminate()
            proc.wait(timeout=30)
    if len(pass_ms) < 3:
        raise RuntimeError(f"only {len(pass_ms)} daemon passes observed")
    steady = round(statistics.median(pass_ms[1:]), 3)
    return float(pass_ms[0]), steady


def real_libtpu_path():
    try:
        import libtpu  # noqa: PLC0415 — optional, probed at bench time
        base = getattr(libtpu, "__file__", None)
        if not base:
            return None
        path = Path(base).parent / "libtpu.so"
        return str(path) if path.exists() else None
    except Exception:  # noqa: BLE001 — any import oddity means "not here"
        return None


PJRT_REAL_SOURCE = {"value": None}  # which candidate produced pjrt_real


def relay_daemon_flags():
    """Daemon flags for labeling real silicon through the ambient relay
    PJRT plugin (tunneled-TPU environments), or None when none is
    exported. The ONE home of the relay discovery + init-timeout policy:
    pjrt_real_p50 and soak_record must not diverge on it. A cold relay
    claim can take tens of seconds before the steady ~100ms state, hence
    the generous init watchdog deadline."""
    if str(REPO) not in sys.path:  # repeated callers must not
        sys.path.insert(0, str(REPO))  # stack duplicate entries
    from tpufd.relay import relay_pjrt_plugin

    relay = relay_pjrt_plugin()
    if relay is None:
        return None
    so, options = relay
    return [f"--libtpu-path={so}", "--pjrt-init-timeout=120s", *options]


def pjrt_real_p50(out_file):
    """p50 of the shipped pjrt backend labeling REAL silicon: first the
    directly-attached libtpu, then the ambient relay PJRT plugin. None
    when no candidate can create a client (e.g. chips held by a training
    job) — each candidate's exact failure goes to stderr so a null is
    always explained in the bench tail."""
    candidates = []
    libtpu = real_libtpu_path()
    if libtpu is not None:
        candidates.append(("libtpu", [f"--libtpu-path={libtpu}",
                                      "--pjrt-init-timeout=120s"]))
    relay_flags = relay_daemon_flags()
    if relay_flags is not None:
        candidates.append(("relay-plugin", relay_flags))
    if not candidates:
        sys.stderr.write(
            "pjrt_real skipped: no libtpu.so importable and no relay "
            "PJRT plugin exported (PJRT_LIBRARY_PATH unset)\n")
        return None
    for name, flags in candidates:
        try:
            # The cold init cost lands on p50_of's warm run, not in the
            # reported median.
            p50 = p50_of(SIDE_RUNS, out_file, "pjrt",
                         extra_args=flags, check_backend="pjrt")
            PJRT_REAL_SOURCE["value"] = name
            return p50
        except (RuntimeError, SystemExit) as e:
            sys.stderr.write(
                f"pjrt_real via {name} ({flags[0]}) failed: {e}\n")
    return None


def tpu_probe_numbers():
    """Measured bf16 matmul TFLOP/s and HBM GB/s on the local chip, when
    one is visible to jax; {} otherwise (or when
    TFD_BENCH_SKIP_TPU_PROBE is set — tests). Differential timing in
    tpufd.health already rides out relay/tunnel quirks."""
    if os.environ.get("TFD_BENCH_SKIP_TPU_PROBE"):
        return {}
    try:
        if str(REPO) not in sys.path:  # repeated callers must not
            sys.path.insert(0, str(REPO))  # stack duplicate entries
        import jax

        if jax.devices()[0].platform != "tpu":
            return {}
        from tpufd import health

        # health.median_probe is the shared median-of-3 policy (same one
        # the daemon's published labels use).
        tflops = round(health.median_probe(health.matmul_tflops), 1)
        gbps = round(health.median_probe(health.hbm_gbps), 1)
        out = {"tpu_matmul_tflops": tflops, "tpu_hbm_gbps": gbps}
        # DMA-engine bandwidth (pallas HBM→HBM copy) next to the VPU
        # stream: the two agreeing inside the 74-87%-of-rated band is the
        # mechanism-independence proof; sharp disagreement = a sick path.
        # Own try: a Mosaic/pallas failure (e.g. a relay plugin without
        # custom-call support) must not discard the numbers above.
        try:
            out["tpu_dma_copy_gbps"] = round(
                health.median_probe(health.dma_copy_gbps), 1)
        except Exception as e:  # noqa: BLE001
            out["tpu_dma_copy_skip_reason"] = f"probe failed: {e}"
        # ICI all-reduce: measured over a one-axis mesh of all local
        # chips when there are >1; recorded as an EXPLICIT null with the
        # reason on single-chip hosts, so the never-measured-on-silicon
        # gap stays visible in every bench record instead of silent
        # (the probe itself is CPU-mesh tested; tests/test_tpufd.py).
        devices = jax.devices()
        out["tpu_allreduce_gbps"] = None
        if len(devices) > 1:
            # Own try: an ICI probe failure must not discard the matmul/
            # HBM numbers already measured — it becomes the skip reason.
            try:
                from jax.sharding import Mesh
                import numpy as np
                mesh = Mesh(np.array(devices), ("all",))
                out["tpu_allreduce_gbps"] = round(health.median_probe(
                    lambda: health.allreduce_gbps(mesh)), 1)
            except Exception as e:  # noqa: BLE001
                out["tpu_allreduce_skip_reason"] = f"probe failed: {e}"
            # Per-axis ICI sweep when the chips expose a coord grid.
            # Per-axis keys and per-axis failure reasons: an axis-y
            # failure must neither masquerade as an allreduce failure
            # nor silently drop the key.
            try:
                pmesh = health.physical_mesh(devices)
                axes = (pmesh.axis_names
                        if pmesh.axis_names != ("all",) else ())
            except Exception as e:  # noqa: BLE001
                out["tpu_ici_sweep_skip_reason"] = f"mesh failed: {e}"
                axes = ()
            for ax in axes:
                try:
                    out[f"tpu_ici_{ax}_gbps"] = round(
                        health.median_probe(
                            lambda ax=ax: health.ici_axis_gbps(
                                pmesh, ax)), 1)
                except Exception as e:  # noqa: BLE001
                    out[f"tpu_ici_{ax}_skip_reason"] = f"probe failed: {e}"
        else:
            out["tpu_allreduce_skip_reason"] = (
                f"{len(devices)} chip visible: no ICI to measure")
        # Context against the published per-family peaks (the sign-flip
        # stream normally reads 75-90% of rated HBM; see tpufd/health.py).
        # Provenance is pinned (VERDICT r5 weak #5): the headline
        # tpu_*_pct_of_rated keys are ALWAYS the in-process probe's
        # numerator — the daemon-mediated path records its own
        # daemon_tpu_matmul_pct_of_rated key (daemon_silicon_numbers),
        # so round-over-round comparisons never mix numerators.
        family = health.family_of(jax.devices()[0])
        matmul_pct = health.pct_of_rated(
            tflops, family, health.RATED_MATMUL_TFLOPS)
        hbm_pct = health.pct_of_rated(gbps, family, health.RATED_HBM_GBPS)
        if matmul_pct is not None:
            out["tpu_matmul_pct_of_rated"] = matmul_pct
            # Always the fresh in-process numerator; the amortized
            # characterization path records its own perf_pct_of_rated /
            # perf_restored_pct_of_rated keys whose *_source fields say
            # "inprocess-probe" vs "state-restored" (perf_record), so a
            # BENCH record can always tell a cached characterization
            # from a fresh measurement.
            out["pct_of_rated_source"] = "inprocess-probe"
        if hbm_pct is not None:
            out["tpu_hbm_pct_of_rated"] = hbm_pct
        return out
    except Exception as e:  # noqa: BLE001 — bench must not die on probe
        sys.stderr.write(f"tpu probe skipped: {e}\n")
        return {}


def daemon_silicon_numbers(out_file):
    """The SHIPPED BINARY labeling real silicon end-to-end: one oneshot
    pass with --device-health=full execs `python3 -m tpufd health` (the
    production full-health path, deployments/container Dockerfile full
    variant) and merges the measured google.com/tpu.health.* labels into
    its output. This is the daemon-mediated counterpart of the
    in-process tpu_matmul_tflops/tpu_hbm_gbps probes: daemon_health_ok
    proves the exec plumbing + label merge ran against a real chip.
    {} when no TPU is visible (or the probe is skipped for tests)."""
    if os.environ.get("TFD_BENCH_SKIP_TPU_PROBE"):
        return {}
    # Ambient PYTHONPATH is preserved untouched: relay environments
    # register their jax platform plugin through it (e.g. a sitecustomize
    # dir), and REPLACING it breaks backend discovery. The exec'd probe
    # resolves tpufd from cwd (REPO) instead.
    env = dict(os.environ,
               GCE_METADATA_HOST=HERMETIC_ENV["GCE_METADATA_HOST"])
    try:
        # TPU-visibility gate in a SUBPROCESS: TPU access is exclusive,
        # so the gate must not leave an in-process jax client holding
        # the chip while the daemon's exec'd probe tries to grab it
        # (this function therefore also runs before the in-process
        # tpu_probe_numbers).
        gate = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0].platform)"],
            env=env, cwd=str(REPO), capture_output=True, text=True,
            timeout=120)
        if gate.returncode != 0 or gate.stdout.strip() != "tpu":
            return {}
        proc = subprocess.run(
            [str(BINARY), "--oneshot", "--backend=mock",
             "--mock-topology-file="
             f"{REPO / 'tests/fixtures/v5e-4.yaml'}",
             "--machine-type-file=/dev/null", "--device-health=full",
             "--health-exec=python3 -m tpufd health",
             "--health-exec-timeout=240s", f"--output-file={out_file}"],
            env=env, cwd=str(REPO), capture_output=True, timeout=300)
        if proc.returncode != 0:
            sys.stderr.write("daemon silicon probe skipped: daemon exit "
                             f"{proc.returncode}\n")
            return {}
        labels = dict(line.split("=", 1)
                      for line in Path(out_file).read_text().splitlines()
                      if "=" in line)
        if labels.get("google.com/tpu.health.ok") != "true":
            return {"daemon_health_ok": False}
        out = {"daemon_health_ok": True}
        for leaf, key in (("matmul-tflops", "daemon_tpu_matmul_tflops"),
                          ("hbm-gbps", "daemon_tpu_hbm_gbps"),
                          # Daemon-path pct-of-rated under its OWN key
                          # (probe-published): never the headline
                          # tpu_matmul_pct_of_rated, whose numerator is
                          # pinned to the in-process probe.
                          ("matmul-tflops-pct-of-rated",
                           "daemon_tpu_matmul_pct_of_rated"),
                          ("hbm-gbps-pct-of-rated",
                           "daemon_tpu_hbm_pct_of_rated")):
            value = labels.get(f"google.com/tpu.health.{leaf}")
            if value is not None:
                out[key] = float(value)
        return out
    except Exception as e:  # noqa: BLE001 — bench must not die on probe
        sys.stderr.write(f"daemon silicon probe skipped: {e}\n")
        return {}


def steady_pass_durations(out_file, force_slow, passes_wanted=12,
                          deadline_s=60):
    """Per-pass durations of one 1s-cadence mock daemon (the headline
    v5p-128 mixed config), read from the daemon's own flight recorder:
    fast passes journal `pass-shortcircuit` events with duration_us,
    slow passes journal `rewrite` spans with duration_us. Returns
    (noop_durations_us, slow_durations_us, fast_total, slow_total)."""
    import urllib.request

    if str(REPO) not in sys.path:  # repeated callers must not
        sys.path.insert(0, str(REPO))  # stack duplicate entries
    from tpufd.fakes import free_loopback_port

    port = free_loopback_port()
    env = dict(HERMETIC_ENV)
    if force_slow:
        env["TFD_FORCE_SLOW_PASS"] = "1"
    args = [str(BINARY), "--sleep-interval=1s", "--backend=mock",
            # Prices the per-interval pass pipeline (the machinery event
            # mode still runs on every wakeup): legacy loop pinned.
            "--event-driven=false",
            "--mock-topology-file="
            f"{REPO / 'tests/fixtures/v5p-128-worker3.yaml'}",
            "--slice-strategy=mixed", "--machine-type-file=/dev/null",
            f"--output-file={out_file}",
            # The journal ring must hold every pass's events.
            "--journal-capacity=2048",
            f"--introspection-addr=127.0.0.1:{port}"]

    def get(path):
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}{path}", timeout=2) as r:
                return r.read().decode()
        except OSError:
            return None

    proc = subprocess.Popen(args, env=env, stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL)
    try:
        deadline = time.monotonic() + deadline_s
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                raise RuntimeError(
                    f"steady bench daemon died rc={proc.returncode}")
            metrics_text = get("/metrics")
            if metrics_text:
                for line in metrics_text.splitlines():
                    if line.startswith("tfd_rewrites_total "):
                        if float(line.split()[1]) >= passes_wanted:
                            deadline = 0  # collected enough
                        break
            if deadline:
                time.sleep(0.25)
        body = get("/debug/journal?n=4096")
        if body is None:
            raise RuntimeError("journal scrape failed")
        events = json.loads(body)["events"]
    finally:
        proc.terminate()
        proc.wait(timeout=30)
    noop_us = [float(e["fields"]["duration_us"]) for e in events
               if e["type"] == "pass-shortcircuit"]
    slow_us = [float(e["fields"]["duration_us"]) for e in events
               if e["type"] == "rewrite" and "duration_us" in e["fields"]]
    return noop_us, slow_us, len(noop_us), len(slow_us)


def steady_state_record():
    """The ISSUE 7 hot-path metrics: `steady_noop_p50_us` — the p50 of a
    fingerprint-clean pass (plan + skipped sink write; the steady state
    every healthy node lives in), gated < 1000 us by CI — and
    `steady_dirty_p50_ms` — the p50 of a TFD_FORCE_SLOW_PASS=1 full
    render+merge+govern+sink pass (the pre-fast-path per-pass cost,
    gated against regression >25% vs the committed reference)."""
    out = {}
    with tempfile.TemporaryDirectory() as tmp:
        try:
            noop_us, _, fast_n, slow_n = steady_pass_durations(
                str(Path(tmp) / "tfd"), force_slow=False)
            if not noop_us:
                raise RuntimeError("no pass-shortcircuit events journaled")
            out["steady_noop_p50_us"] = round(statistics.median(noop_us), 1)
            out["steady_fast_passes"] = fast_n
            out["steady_slow_passes"] = slow_n
        except Exception as e:  # noqa: BLE001 — bench must not die here
            sys.stderr.write(f"steady noop bench skipped: {e}\n")
            out["steady_noop_p50_us"] = None
        try:
            _, slow_us, _, _ = steady_pass_durations(
                str(Path(tmp) / "tfd-slow"), force_slow=True,
                passes_wanted=8)
            if not slow_us:
                raise RuntimeError("no rewrite spans journaled")
            # First pass carries backend warm-up; steady full passes are
            # the regression-gated number (events arrive in seq order).
            steady = slow_us[1:] or slow_us
            out["steady_dirty_p50_ms"] = round(
                statistics.median(steady) / 1000.0, 3)
        except Exception as e:  # noqa: BLE001
            sys.stderr.write(f"steady dirty bench skipped: {e}\n")
            out["steady_dirty_p50_ms"] = None
    return out


def perf_record():
    """The ISSUE 9 amortization metrics, hermetic (mock backend + a
    millisecond fake measurement exec):

      perf_noop_p50_us            steady no-op pass p50 WITH the perf
                                  source enabled (gated <= 1000us by
                                  bench_gate --perf: characterization
                                  must not tax the hot path);
      perf_measure_rounds         measurement execs journaled across the
                                  steady soak (the amortization
                                  contract: exactly 1);
      perf_restore_ms             warm-restart perf-section restore
                                  latency after kill -9 (gated <= 15ms);
      perf_restored_measure_rounds  measurements after the restart
                                  (must be 0: the restored
                                  characterization is trusted).

    pct-of-rated provenance is recorded NEXT TO each value, so a BENCH
    record can always tell a cached characterization from a fresh one:
    `perf_pct_of_rated` carries perf_pct_of_rated_source=
    "inprocess-probe" (the soak's own measurement round produced it),
    while `perf_restored_pct_of_rated` carries "state-restored" (served
    from the warm-restarted state file with zero re-measurement). The
    headline tpu_*_pct_of_rated keys remain pinned to the real-TPU
    in-process probe (tpu_probe_numbers) and never mix with these."""
    import urllib.request

    if str(REPO) not in sys.path:
        sys.path.insert(0, str(REPO))
    from tpufd.fakes import free_loopback_port

    out = {}
    with tempfile.TemporaryDirectory() as tmp:
        tmp_path = Path(tmp)
        fixture = REPO / "tests/fixtures/v2-8.yaml"
        count = tmp_path / "count"
        values = tmp_path / "values.txt"
        values.write_text("matmul-tflops=44\nhbm-gbps=630\nici-gbps=40\n")
        script = tmp_path / "exec.sh"
        script.write_text(f"echo run >> {count}\ncat {values}\n")
        out_file = tmp_path / "tfd"

        def argv(port):
            return [str(BINARY), "--sleep-interval=1s", "--backend=mock",
                    "--event-driven=false",  # cadence-counted scenario
                    f"--mock-topology-file={fixture}",
                    "--machine-type-file=/dev/null",
                    f"--output-file={out_file}",
                    f"--state-file={tmp_path / 'state'}",
                    "--journal-capacity=2048",
                    "--perf-characterize", f"--perf-exec=sh {script}",
                    "--perf-duty-cycle-pct=50",
                    f"--introspection-addr=127.0.0.1:{port}"]

        def get(port, path):
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}{path}", timeout=2) as r:
                    return r.read().decode()
            except OSError:
                return None

        def events(port, kind):
            body = get(port, f"/debug/journal?n=4096&type={kind}")
            return json.loads(body)["events"] if body else []

        def wait_rewrites(port, proc, n, deadline_s=60):
            deadline = time.monotonic() + deadline_s
            while time.monotonic() < deadline:
                if proc.poll() is not None:
                    raise RuntimeError(
                        f"perf bench daemon died rc={proc.returncode}")
                text = get(port, "/metrics")
                if text:
                    for line in text.splitlines():
                        if line.startswith("tfd_rewrites_total "):
                            if float(line.split()[1]) >= n:
                                return
                            break
                time.sleep(0.25)
            raise RuntimeError(f"never reached {n} rewrites")

        def pct_label():
            try:
                labels = dict(
                    line.split("=", 1)
                    for line in out_file.read_text().splitlines() if line)
                value = labels.get("google.com/tpu.perf.pct-of-rated")
                return float(value) if value is not None else None
            except OSError:
                return None

        port = free_loopback_port()
        proc = subprocess.Popen(argv(port), env=dict(HERMETIC_ENV),
                                stdout=subprocess.DEVNULL,
                                stderr=subprocess.DEVNULL)
        try:
            wait_rewrites(port, proc, 12)
            noop_us = [float(e["fields"]["duration_us"])
                       for e in events(port, "pass-shortcircuit")]
            if not noop_us:
                raise RuntimeError("no pass-shortcircuit events journaled")
            out["perf_noop_p50_us"] = round(statistics.median(noop_us), 1)
            out["perf_measure_rounds"] = len(events(port, "perf-measure"))
            pct = pct_label()
            if pct is not None:
                out["perf_pct_of_rated"] = pct
                out["perf_pct_of_rated_source"] = "inprocess-probe"
            proc.send_signal(9)  # SIGKILL: the warm-restart drill
            proc.wait(timeout=10)

            port = free_loopback_port()
            proc = subprocess.Popen(argv(port), env=dict(HERMETIC_ENV),
                                    stdout=subprocess.DEVNULL,
                                    stderr=subprocess.DEVNULL)
            wait_rewrites(port, proc, 2, deadline_s=30)
            restored = events(port, "perf-restored")
            if not restored:
                raise RuntimeError("perf characterization not restored "
                                   "after kill -9")
            out["perf_restore_ms"] = round(
                float(restored[0]["fields"]["duration_us"]) / 1000.0, 3)
            out["perf_restored_measure_rounds"] = len(
                events(port, "perf-measure"))
            pct = pct_label()
            if pct is not None:
                out["perf_restored_pct_of_rated"] = pct
                out["perf_restored_pct_of_rated_source"] = "state-restored"
        finally:
            if proc.poll() is None:
                proc.terminate()
                proc.wait(timeout=30)
    return out


def soak_record():
    """Daemon steady-state proof via scripts/soak.py: N passes at 1s
    cadence with memory/fd/label-stability/clean-exit checks. Prefers the
    real-silicon path (relay PJRT plugin — first pass inits the chip,
    steady state rides the snapshot cache); falls back to the mock
    fixture so the record exists on chipless CI hosts too. Keys are
    prefixed soak_; soak_ok=false stays in the record rather than
    disappearing — a flaky steady state must be visible."""
    duration = float(os.environ.get("TFD_BENCH_SOAK_S", "15"))
    extra, backend = None, None
    if not os.environ.get("TFD_BENCH_SKIP_TPU_PROBE"):
        try:
            relay_flags = relay_daemon_flags()
            if relay_flags is not None:
                extra = ["--backend=pjrt", *relay_flags]
                backend = "pjrt-relay"
        except Exception as e:  # noqa: BLE001
            sys.stderr.write(f"soak relay discovery failed: {e}\n")
    if extra is None:
        extra = ["--backend=mock",
                 f"--mock-topology-file={REPO}/tests/fixtures/v5e-4.yaml"]
        backend = "mock"
    # The harness's own worst-case budget: init-grace (cold PJRT claim)
    # + the soak itself + the 30s SIGTERM wait, plus slack — the outer
    # timeout must never kill a soak that is within its documented
    # budget (that would read as a steady-state failure).
    init_grace = 180.0
    cmd = [sys.executable, str(REPO / "scripts" / "soak.py"),
           "--binary", str(BINARY), "--duration", str(duration),
           "--init-grace", str(init_grace),
           *(f"--extra-arg={a}" for a in extra)]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=init_grace + duration + 60)
        report = json.loads(proc.stdout.strip().splitlines()[-1])
    except Exception as e:  # noqa: BLE001 — bench must not die on soak
        return {"soak_ok": False, "soak_backend": backend,
                "soak_error": f"harness failed: {e}"}
    out = {"soak_ok": report.pop("ok", False), "soak_backend": backend}
    for key in ("passes", "rss_drift_kb", "fd_start", "fd_end",
                "labels_stable", "rewrite_interval_p50_s", "clean_exit",
                "error"):
        if key in report:
            out[f"soak_{key}"] = report[key]
    out.update(expiry_soak_record())
    return out


def expiry_soak_record():
    """Soak ACROSS the cache-expiry boundaries (VERDICT r5 weak #4): a
    second soak whose --pjrt-refresh-interval and --health-exec-interval
    are both shorter than the window, so the snapshot-refresh and
    health-re-exec paths — the likeliest home of a slow leak or a label
    flap — are exercised in steady state, with the re-probe counts
    asserted from the daemon's own counters. Runs against the fake PJRT
    plugin (the re-probe machinery is identical on real silicon; the
    primary soak covers that path). Note --device-health=full makes
    every PJRT probe a real chip grab by design (per-pass truth), so the
    refresh counter rises at tick rate here; the hermetic tier
    (tests/test_sched.py) additionally proves the pure expiry boundary
    with health off. Keys are prefixed soak_expiry_."""
    duration = float(os.environ.get("TFD_BENCH_SOAK_S", "15"))
    fake = BINARY.parent / "libtfd_fake_pjrt.so"
    if not fake.exists():
        return {"soak_expiry_ok": False,
                "soak_expiry_error": "fake PJRT plugin not built"}
    extra = [
        "--backend=pjrt", f"--libtpu-path={fake}",
        "--pjrt-refresh-interval=3s", "--pjrt-retry-backoff=1s",
        "--device-health=full", "--health-exec-interval=3s",
        # A stub exec: the soak prices the RE-RUN machinery (cadence,
        # caching, label merge), not the silicon probe itself.
        "--health-exec=printf 'google.com/tpu.health.ok=true\\n"
        "google.com/tpu.health.stub=1\\n'",
    ]
    cmd = [sys.executable, str(REPO / "scripts" / "soak.py"),
           "--binary", str(BINARY), "--duration", str(duration),
           "--require-counter", "tfd_pjrt_cache_refreshes_total:2",
           "--require-counter", "tfd_probe_attempts_total{source=health}:2",
           *(f"--extra-arg={a}" for a in extra)]
    env = dict(os.environ, GCE_METADATA_HOST="127.0.0.1:1",
               TFD_FAKE_PJRT_KIND="TPU v5 lite",
               TFD_FAKE_PJRT_BOUNDS="2,2,1")
    try:
        proc = subprocess.run(cmd, env=env, capture_output=True, text=True,
                              timeout=duration + 120)
        report = json.loads(proc.stdout.strip().splitlines()[-1])
    except Exception as e:  # noqa: BLE001 — bench must not die on soak
        return {"soak_expiry_ok": False,
                "soak_expiry_error": f"harness failed: {e}"}
    out = {"soak_expiry_ok": report.pop("ok", False)}
    for key in ("passes", "rss_drift_kb", "labels_stable", "counters",
                "counters_ok", "snapshot_tiers", "error"):
        if key in report:
            out[f"soak_expiry_{key}"] = report[key]
    return out


def main():
    ensure_built()
    headline = os.environ.get("TFD_BENCH_BACKEND", "mock")
    with tempfile.TemporaryDirectory() as tmp:
        out_file = str(Path(tmp) / "tfd")
        p50s = {}
        if headline == "mock":
            p50s["mock"] = p50_of(RUNS, out_file, "mock", **mock_kwargs())
            p50 = p50s["mock"]
        else:
            # Explicit headline override: measure it end-to-end as-is.
            p50 = p50_of(RUNS, out_file, headline)
            p50s[headline] = p50
        for name, fn in (("metadata", metadata_p50),
                         ("pjrt", pjrt_fake_p50),
                         ("auto", auto_p50),
                         ("pjrt_real", pjrt_real_p50)):
            if name in p50s:
                continue
            try:
                p50s[name] = fn(out_file)
            # SystemExit included: one_run raises it on a failed child,
            # and a side metric must never lose the headline record.
            except (Exception, SystemExit) as e:  # noqa: BLE001
                sys.stderr.write(f"{name} p50 skipped: {e}\n")
                p50s[name] = None
        try:
            first, steady = auto_deadline_p50s(out_file)
            # First pass burns the deadline by design; the steady state
            # rides the failure memo and must track the metadata p50.
            p50s["auto_deadline"] = first
            p50s["auto_deadline_steady"] = steady
        except Exception as e:  # noqa: BLE001
            sys.stderr.write(f"auto_deadline skipped: {e}\n")
            p50s["auto_deadline"] = None
            p50s["auto_deadline_steady"] = None
    record = {
        "metric": "oneshot_label_p50_ms",
        "value": p50,
        "unit": "ms",
        "vs_baseline": round(BASELINE_MS / p50, 2),
        "p50_ms": p50s,
    }
    if headline != "mock":
        record["backend"] = headline
    if PJRT_REAL_SOURCE["value"] is not None:
        record["pjrt_real_source"] = PJRT_REAL_SOURCE["value"]
    # Hot-path steady-state metrics (hermetic, mock backend): the no-op
    # fast-pass p50 and the forced-slow full-pass p50.
    record.update(steady_state_record())
    # Amortized perf-characterization metrics (hermetic, mock backend).
    try:
        record.update(perf_record())
    except Exception as e:  # noqa: BLE001 — bench must not die here
        sys.stderr.write(f"perf bench skipped: {e}\n")
    # Daemon-mediated silicon probe FIRST: tpu_probe_numbers leaves an
    # in-process jax client holding the exclusive chip, which would
    # starve the daemon's exec'd probe.
    with tempfile.TemporaryDirectory() as tmp:
        record.update(daemon_silicon_numbers(str(Path(tmp) / "tfd")))
    # Soak before tpu_probe_numbers for the same exclusive-chip reason.
    record.update(soak_record())
    record.update(tpu_probe_numbers())
    print(json.dumps(record))


if __name__ == "__main__":
    main()

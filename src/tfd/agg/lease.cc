#include "tfd/agg/lease.h"

#include <unistd.h>

#include <cctype>
#include <chrono>
#include <cstdlib>

#include "tfd/obs/journal.h"
#include "tfd/slice/coord.h"
#include "tfd/util/logging.h"
#include "tfd/util/time.h"

namespace tfd {
namespace agg {

namespace {
constexpr char kLeaseKey[] = "lease";
}  // namespace

double MonoSeconds() {
  return std::chrono::duration<double>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

std::string HolderIdentity() {
  if (const char* pod = std::getenv("POD_NAME"); pod && *pod) return pod;
  if (const char* node = std::getenv("NODE_NAME"); node && *node) {
    return node;
  }
  char buf[256] = {0};
  if (gethostname(buf, sizeof(buf) - 1) == 0 && buf[0]) return buf;
  return "tfd-aggregator";
}

std::string UrlEncode(const std::string& s) {
  static const char hex[] = "0123456789ABCDEF";
  std::string out;
  for (unsigned char c : s) {
    if (isalnum(c) || c == '-' || c == '_' || c == '.' || c == '~') {
      out.push_back(static_cast<char>(c));
    } else {
      out.push_back('%');
      out.push_back(hex[c >> 4]);
      out.push_back(hex[c & 15]);
    }
  }
  return out;
}

std::string CollectionUrl(const k8s::ClusterConfig& config) {
  return config.apiserver_url + "/apis/nfd.k8s-sigs.io/v1alpha1/namespaces/" +
         config.namespace_ + "/nodefeatures";
}

std::string NodeSelectorQuery() {
  return "labelSelector=" + UrlEncode(kNodeNameLabel);
}

http::RequestOptions BaseOptions(const k8s::ClusterConfig& config) {
  http::RequestOptions options;
  options.ca_file = config.ca_file;
  if (!config.token.empty()) {
    options.headers["Authorization"] = "Bearer " + config.token;
  }
  options.headers["Accept"] = "application/json";
  return options;
}

void LeaseTick(const k8s::ClusterConfig& config,
               const std::string& lease_doc, const std::string& self,
               int lease_duration_s, const std::string& journal_role,
               LeaseState* state) {
  bool server_alive = false;
  Result<k8s::CoordDocResult> doc =
      k8s::GetCoordConfigMap(config, lease_doc, &server_alive, nullptr);
  bool was_leading = state->leading;
  if (!doc.ok()) {
    TFD_LOG_WARNING << journal_role << " lease: " << doc.error();
    // A 429/503-paced server is ALIVE (it answered): the lease doc's
    // truth is intact, only this poll was deferred — never a partition
    // signal. A naked failure, though, means we cannot see the
    // blackboard: a leader keeps leading only while its own lease
    // could still be valid. Past a full lease duration without
    // contact, a standby that CAN see the doc has taken over at
    // expiry — continuing to act would be exactly the double
    // leadership the lease exists to prevent, so step down (the run
    // loop unwinds the leader-only machinery) until contact resumes.
    if (server_alive) {
      state->last_contact_mono = MonoSeconds();
    } else if (state->leading &&
               MonoSeconds() - state->last_contact_mono >
                   static_cast<double>(lease_duration_s)) {
      state->leading = false;
      obs::DefaultJournal().Record(
          journal_role + "-follower", journal_role,
          "stepped down: lease blackboard unreachable for a full lease",
          {{"holder", self},
           {"epoch", std::to_string(state->epoch)}});
    }
    return;
  }
  state->ever_contacted = true;
  state->last_contact_mono = MonoSeconds();
  double now_wall = WallClockSeconds();
  slice::Lease lease;
  bool have_lease = false;
  if (doc->found) {
    auto it = doc->data.find(kLeaseKey);
    if (it != doc->data.end()) {
      if (Result<slice::Lease> parsed = slice::ParseLease(it->second);
          parsed.ok()) {
        lease = *parsed;
        have_lease = true;
      }
    }
  }

  auto write_lease = [&](uint64_t epoch, bool create) {
    slice::Lease next;
    next.holder = self;
    next.epoch = epoch;
    next.renewed_at = now_wall;
    next.duration_s = lease_duration_s;
    bool conflict = false;
    Status wrote = k8s::PatchCoordConfigMap(
        config, lease_doc, {{kLeaseKey, slice::SerializeLease(next)}},
        create ? "" : doc->resource_version, create, &conflict,
        &server_alive, nullptr);
    if (wrote.ok()) {
      state->leading = true;
      state->epoch = epoch;
      return true;
    }
    state->leading = false;
    return false;
  };

  if (!doc->found) {
    write_lease(1, /*create=*/true);
  } else if (have_lease && lease.holder == self &&
             !slice::LeaseExpired(lease, now_wall)) {
    write_lease(lease.epoch, /*create=*/false);  // renew, same epoch
  } else if (!have_lease || slice::LeaseExpired(lease, now_wall)) {
    write_lease(lease.epoch + 1, /*create=*/false);  // take over
  } else {
    state->leading = false;  // someone else holds a live lease
  }

  if (state->leading != was_leading) {
    obs::DefaultJournal().Record(
        state->leading ? journal_role + "-leader"
                       : journal_role + "-follower",
        journal_role,
        state->leading
            ? "acquired the " + journal_role + " lease (epoch " +
                  std::to_string(state->epoch) + ")"
            : "following (lease held by " + lease.holder + ")",
        {{"holder", state->leading ? self : lease.holder},
         {"epoch", std::to_string(state->leading ? state->epoch
                                                 : lease.epoch)}});
  }
}

}  // namespace agg
}  // namespace tfd

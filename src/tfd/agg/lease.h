#ifndef TFD_AGG_LEASE_H_
#define TFD_AGG_LEASE_H_

// The lease discipline shared by every cluster-singleton controller:
// the aggregator (flat, L1 shard, L2 root — agg/runner.cc) and the
// remediation controller (remedy/remedy.cc). One ConfigMap per lease
// doc on the slice-coordination blackboard (k8s/client.h), optimistic
// concurrency via the resourceVersion precondition, epoch fencing on
// takeover — extracted from agg/runner.cc so a second controller could
// not fork the election rules.

#include <cstdint>
#include <string>

#include "tfd/k8s/client.h"
#include "tfd/util/http.h"

namespace tfd {
namespace agg {

// The per-node daemons stamp this metadata label on their CRs; a
// controller's OUTPUT objects deliberately omit it (except L1 partials,
// which carry it so the L2 root's selector watch sees them).
inline constexpr char kNodeNameLabel[] = "nfd.node.kubernetes.io/node-name";

// Monotonic seconds (steady_clock): lease contact ages and flush
// debounce run on this, never the wall clock.
double MonoSeconds();

// Who holds the lease: the pod identity when scheduled as a Deployment,
// the node as a fallback, the hostname last.
std::string HolderIdentity();

// Minimal percent-encoding for a query-parameter value (the
// labelSelector carries '/' and '.').
std::string UrlEncode(const std::string& s);

// The NodeFeature collection URL every singleton watches.
std::string CollectionUrl(const k8s::ClusterConfig& config);

// Selector that keeps a controller's own unlabeled output objects out
// of its own watch (the aggregator's ingest filter; the remediation
// controller deliberately watches WITHOUT it — the inventory CR it
// consumes is exactly such an unlabeled output).
std::string NodeSelectorQuery();

// Base request options: CA, bearer token, JSON accept.
http::RequestOptions BaseOptions(const k8s::ClusterConfig& config);

struct LeaseState {
  bool leading = false;
  uint64_t epoch = 0;
  bool ever_contacted = false;
  // Last successful (or server-alive) blackboard contact, monotonic.
  double last_contact_mono = 0;
};

// One lease tick against `lease_doc`: bootstrap, renew, or take over an
// expired lease. `journal_role` names the controller in the journal
// ("agg" -> agg-leader/agg-follower, "remedy" ->
// remedy-leader/remedy-follower) and in log lines. Role-transition
// gauges are the CALLER's job (each controller owns its own
// tfd_<role>_state family) — this function only moves `state`.
void LeaseTick(const k8s::ClusterConfig& config,
               const std::string& lease_doc, const std::string& self,
               int lease_duration_s, const std::string& journal_role,
               LeaseState* state);

}  // namespace agg
}  // namespace tfd

#endif  // TFD_AGG_LEASE_H_

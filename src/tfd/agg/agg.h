// Cluster inventory aggregator: incremental O(delta) rollups over every
// node's published NodeFeature labels (ROADMAP #3, BASELINE target #5).
//
// The reference GFD stops at per-node labels and leans on an external
// NFD-master for aggregation; a TPU fleet's scheduler needs the
// CLUSTER-scoped view — how many slices exist, how many are healthy,
// how much capacity sits in each perf class, where the fleet's perf
// distribution actually is — and at fleet scale the naive design
// (re-list + recompute every rollup on every node event) is an O(fleet)
// hot loop run O(fleet) times per churn window. This module is the
// incremental-computation core that avoids it, in the style of
// streaming-dataflow view maintenance: every rollup is a sum of
// per-node CONTRIBUTIONS, so a watch delta retires the node's old
// contribution and applies its new one — counters decrement/increment,
// the quantile sketch removes/adds — and the steady-state cost per
// event is O(labels changed on one node), never O(nodes). A full
// recompute exists only as a self-check (RecomputeAll) and a counter
// (`tfd_agg_full_recomputes_total`) proves the steady path never takes
// it: the fleet soak asserts it stays 0 after the initial sync.
//
// Everything here is pure logic (no I/O, caller-supplied time), twinned
// constant-for-constant by tpufd/agg.py — the parity grids pin bucket
// indices, quantiles, and whole rollup label sets on both sides. The
// transport (lease election, collection watch, SSA publish) lives in
// agg/runner.cc.
#pragma once

#include <array>
#include <cstdint>
#include <deque>
#include <map>
#include <string>
#include <utility>
#include <vector>

#include "tfd/lm/labeler.h"

namespace tfd {
namespace agg {

// ---- mergeable quantile sketch -------------------------------------------
//
// Fixed-bin log-bucket digest: bucket 0 holds values <= kSketchMin,
// bucket b>0 holds (kSketchMin*gamma^(b-1), kSketchMin*gamma^b], so the
// relative error is bounded by gamma-1 (10%) across ~0.5..90k — wide
// enough for TFLOP/s and GB/s. Counts make it REMOVABLE (retire a
// node's old value) and mergeable (sum the arrays), which a comparable
// rank-based digest is not. Bucket boundaries are computed by repeated
// IEEE-double multiplication, NOT log()/pow(), so the C++ and Python
// twins bucket every value identically bit-for-bit.

inline constexpr double kSketchMin = 0.5;
inline constexpr double kSketchGamma = 1.1;
inline constexpr int kSketchBuckets = 128;

int SketchBucketIndex(double value);
// The bucket's representative (upper-edge) value; bucket 0 = kSketchMin.
double SketchBucketValue(int bucket);

class QuantileSketch {
 public:
  void Add(double value);
  // Retires one previously-Added value (clamped at zero defensively —
  // the store only ever removes what it admitted).
  void Remove(double value);
  void Merge(const QuantileSketch& other);
  // Retires a previously-Merged sketch (per-bucket, clamped at zero —
  // the inverse the removable design buys; a rank digest has none).
  void Unmerge(const QuantileSketch& other);
  // Deserialization primitive: lands `n` observations directly in
  // `bucket` (out-of-range bucket / non-positive n are ignored).
  void AddBucketCount(int bucket, int64_t n);
  int64_t count() const { return total_; }
  const std::array<int64_t, kSketchBuckets>& bucket_counts() const {
    return counts_;
  }
  // Representative value at quantile q in [0,1]; -1 when empty.
  double Quantile(double q) const;
  // Fraction of the mass whose bucket representative exceeds
  // `threshold` — the over-budget fraction the burn evaluator feeds
  // on. 0 when empty.
  double FractionAbove(double threshold) const;
  void Clear();
  bool operator==(const QuantileSketch& other) const {
    return total_ == other.total_ && counts_ == other.counts_;
  }

 private:
  std::array<int64_t, kSketchBuckets> counts_{};
  int64_t total_ = 0;
};

// ---- stage-latency SLO sketches ------------------------------------------
//
// The fleet SLO engine's vocabulary: each node folds its closed
// changes' per-stage durations (milliseconds) into one windowed sketch
// per stage (obs/slo.h), serializes the set into a CR annotation, and
// the aggregator merges every node's contribution into the fleet view
// it publishes as tpu.obs.stage.* labels and burns against budgets.

inline constexpr const char* kSloStages[] = {"plan", "render", "publish",
                                             "publish-acked"};
inline constexpr int kNumSloStages = 4;

// Node-stage latency budgets (ms). Provenance: derived from the
// cluster protocol budgets (scripts/bench_gate.py
// CLUSTER_STAGE_BUDGETS_MS) — the node pipeline runs inside the
// chain's hold+publish span, so plan and publish each get the chain
// "hold" allowance (1200ms, the governor's local think-time), render
// gets the "fanout" allowance (100ms, pure CPU), and publish-acked —
// which absorbs brownout deferral — gets hold+fanout (1300ms).
// bench_gate --slo re-derives this table from CLUSTER_STAGE_BUDGETS_MS
// and cross-checks the record against it; change one, change all.
std::map<std::string, double> DefaultSloBudgetsMs();

// Budgets with operator overrides applied: `spec` is
// "stage=ms[,stage=ms...]" (the TFD_SLO_BUDGETS_MS env format the
// aggregator accepts; the CI slo-smoke tightens budgets through it).
// Unknown stages and malformed entries are ignored; "" = the defaults.
std::map<std::string, double> SloBudgetsMsFromSpec(const std::string& spec);

using StageSketches = std::map<std::string, QuantileSketch>;

// Compact annotation encoding of a stage-sketch set: stages in
// kSloStages order, empty sketches skipped, sparse ascending
// bucket:count pairs —
//   plan=0:3,5:2;publish=17:1
// Annotation-safe (alnum plus '=' ':' ',' ';' '-'), deterministic,
// byte-identical to the tpufd.agg twin.
std::string SerializeStageSketches(const StageSketches& stages);
// Tolerant inverse: unknown stage names and malformed tokens are
// skipped, never fatal — the annotation arrives from arbitrary nodes.
StageSketches ParseStageSketches(const std::string& text);

// ---- multi-window burn-rate evaluator ------------------------------------
//
// Classic fast+slow burn detection over the merged fleet sketches: at
// each evaluation tick the per-stage over-budget fraction is recorded,
// and a stage starts BURNING when the fast-window mean crosses 1/2
// (the regression is live right now) while the slow-window mean has
// spent at least the 10% error budget (it is not a single blip); it
// clears as soon as the fast-window mean drops back under 1/2. Pure
// logic, caller-supplied time — twinned by tpufd.agg.BurnEvaluator.
class BurnEvaluator {
 public:
  static constexpr double kFastWindowS = 300;    // 5m: is it happening NOW
  static constexpr double kSlowWindowS = 3600;   // 1h: did it spend budget
  static constexpr double kFastThreshold = 0.5;
  static constexpr double kSlowThreshold = 0.1;  // the 10% error budget

  explicit BurnEvaluator(std::map<std::string, double> budgets_ms =
                             DefaultSloBudgetsMs(),
                         double fast_window_s = kFastWindowS,
                         double slow_window_s = kSlowWindowS);

  struct Edge {
    std::string stage;
    bool burning = false;  // true = slo-burn asserted, false = cleared
  };

  // One evaluation tick over the merged fleet sketches. Returns the
  // burn EDGES this tick produced (empty = no verdict changed). A
  // stage absent from the sketches contributes an over-fraction of 0
  // once it has ever been seen; a never-seen stage stays untracked.
  std::vector<Edge> Note(double now, const StageSketches& sketches);

  bool burning(const std::string& stage) const;
  std::vector<std::string> BurningStages() const;
  const std::map<std::string, double>& budgets_ms() const {
    return budgets_;
  }

 private:
  struct StageState {
    std::deque<std::pair<double, double>> samples;  // (ts, over-fraction)
    bool burning = false;
  };
  std::map<std::string, double> budgets_;
  double fast_window_s_;
  double slow_window_s_;
  std::map<std::string, StageState> stages_;
};

// ---- per-node contribution -----------------------------------------------

// What one node's label set contributes to the cluster rollups. Pure
// extraction — two nodes with equal label subsets contribute equally,
// and an equal old/new contribution is how the store detects that a
// watch delta (e.g. a probe-ms bump) cannot move any rollup.
struct NodeContribution {
  std::string slice_id;          // tpu.slice.id ("" = unsliced node)
  bool slice_degraded = false;   // tpu.slice.degraded == "true"
  std::string multislice_group;  // tpu.multislice.slice-id ("" = none)
  std::string perf_class;        // tpu.perf.class ("" = unclassed)
  int chips = 0;                 // tpu.count
  double matmul_tflops = -1;     // tpu.perf.matmul-tflops (-1 = absent)
  double hbm_gbps = -1;          // tpu.perf.hbm-gbps
  bool preempting = false;       // tpu.lifecycle.{preempt-imminent,draining}
  // The node's serialized stage-SLO sketch set, verbatim from the
  // tfd.google.com/stage-slo annotation ("" = none published). Kept
  // raw: string equality is the no-rollup-moved check, and Admit/
  // Retire parse on demand (bounded: <= 4 stages x 128 buckets).
  std::string stage_slo;

  bool operator==(const NodeContribution& other) const;
  bool operator!=(const NodeContribution& other) const {
    return !(*this == other);
  }
};

NodeContribution ExtractContribution(const lm::Labels& labels,
                                     const std::string& stage_slo = "");

// ---- sharded aggregation tree --------------------------------------------
//
// The rollup core was built mergeable/removable precisely so aggregation
// could become a TREE (ROADMAP #3): L1 shards each run the incremental
// store over 1/n of the fleet and publish a PARTIAL — the shard's whole
// aggregate state serialized as counter maps and sparse sketch buckets —
// and the L2 root merges the n partials O(delta) (retire the shard's old
// partial, admit its new one) into an output byte-identical to what a
// flat single aggregator over the same fleet would publish. Bit-identity
// holds because every rollup is a sum of exact integer counters and
// integer-count sketch buckets: addition is associative, so
// (shard sums) summed == flat sum, bucket for bucket.

// Shard assignment: nodes whose textbook-FNV-1a name hash lands in
// shard i of n (k8s::desync::Fnv1a64 — twin-pinned by tpufd.sink).
// shards <= 1 maps everything to shard 0 (the flat topology).
int ShardIndexOf(const std::string& node, int shards);

// One slice's aggregated member counters (the store's former private
// SliceAgg, public now so partials can carry it across tiers).
struct SliceCounts {
  int64_t members = 0;
  int64_t degraded = 0;    // members voting tpu.slice.degraded=true
  int64_t preempting = 0;  // members with a lifecycle preempt/drain label
  bool operator==(const SliceCounts& other) const {
    return members == other.members && degraded == other.degraded &&
           preempting == other.preempting;
  }
};

// The complete aggregate state one tier holds: what an L1 publishes as
// its partial, what the L2 accumulates per shard AND as the merged
// total, and what the flat InventoryStore maintains internally — one
// struct so BuildRollupLabels is shared and byte-compat is structural,
// not coincidental.
struct RollupState {
  int64_t nodes = 0;
  int64_t preempting = 0;
  std::map<std::string, SliceCounts> slices;
  std::map<std::string, int64_t> capacity;    // class bucket -> chips
  std::map<std::string, int64_t> multislice;  // group id -> members
  QuantileSketch matmul;
  QuantileSketch hbm;
  StageSketches stage;

  bool operator==(const RollupState& other) const;
  bool operator!=(const RollupState& other) const {
    return !(*this == other);
  }
};

// The cluster-scoped rollup label set from an aggregate state —
// deterministic, parity-pinned against the Python twin. Every tier's
// output flows through this one function (see InventoryStore::
// BuildOutputLabels / ShardMergeStore::BuildOutputLabels).
lm::Labels BuildRollupLabels(const RollupState& state);

// Sparse sketch wire form: ascending "bucket:count" pairs joined by
// ',' ("" = empty). The inverse is tolerant (malformed pairs skipped).
std::string SerializeSketch(const QuantileSketch& sketch);
QuantileSketch ParseSketch(const std::string& text);

// The partial CR's label payload: the aggregate state under the
// lm::kAgg* keys plus the tier marker and the "i/n" shard spec. Empty
// maps/sketches omit their key. ParsePartialLabels returns false when
// the tier marker is absent (the labels are not a partial); malformed
// fields are skipped, never fatal — the payload arrives from the wire.
lm::Labels SerializePartialLabels(const RollupState& state,
                                  const std::string& shard_spec);
bool ParsePartialLabels(const lm::Labels& labels, RollupState* out);

// The L2 root's store: one RollupState per live shard plus the merged
// total, maintained O(delta per partial) — ApplyPartial retires the
// shard's previous partial (counter subtraction + Sketch::Unmerge) and
// admits the new one; root state is O(shards), never O(nodes).
class ShardMergeStore {
 public:
  // Returns true when the shard's partial CHANGED (some rollup moved
  // and a publish is owed) — equal partials are a no-op, mirroring
  // InventoryStore::Apply.
  bool ApplyPartial(const std::string& shard, const RollupState& partial);
  // Watch DELETED: retires the shard's contribution entirely.
  bool RemovePartial(const std::string& shard);

  size_t shards() const { return partials_.size(); }
  std::vector<std::string> ShardNames() const;
  uint64_t events() const { return events_; }
  uint64_t full_recomputes() const { return full_recomputes_; }

  const RollupState& merged() const { return merged_; }
  lm::Labels BuildOutputLabels() const { return BuildRollupLabels(merged_); }
  const StageSketches& stage_sketches() const { return merged_.stage; }

  // Self-check ONLY (mirrors InventoryStore::RecomputeAll): rebuilds
  // the merged total from the retained partials and bumps
  // full_recomputes — `tfd_agg_full_recomputes_total == 0` on every
  // tier is the acceptance contract.
  void RecomputeAll();
  void Clear();

 private:
  void Retire(const RollupState& p);
  void Admit(const RollupState& p);

  std::map<std::string, RollupState> partials_;
  RollupState merged_;
  uint64_t events_ = 0;
  uint64_t full_recomputes_ = 0;
};

// ---- the incremental inventory store -------------------------------------

class InventoryStore {
 public:
  // Applies one node's current label set (watch ADDED/MODIFIED or a
  // list item) plus its serialized stage-SLO annotation. Returns true
  // when the node's contribution CHANGED — i.e. some rollup moved and
  // a publish is owed. O(changed labels).
  bool Apply(const std::string& node, const lm::Labels& labels,
             const std::string& stage_slo = "");
  // Watch DELETED: retires the node's contribution entirely.
  bool Remove(const std::string& node);

  size_t nodes() const { return nodes_.size(); }
  // Names of every retained node — the re-list reconcile diffs this
  // against the listed set so deletes missed while not watching retire.
  std::vector<std::string> NodeNames() const;
  uint64_t events() const { return events_; }
  uint64_t full_recomputes() const { return full_recomputes_; }

  // The cluster-scoped rollup label set (deterministic from the
  // contributions alone — parity-pinned against the Python twin):
  //   tpu.slice-inventory.{slices,healthy-slices,degraded-slices}
  //   tpu.capacity.{gold,silver,degraded,unclassed,total-chips}
  //   tpu.fleet.{nodes,preempting}
  //   tpu.multislice.groups
  //   tpu.fleet.perf.{matmul-p10,matmul-p50,hbm-p10,hbm-p50} (when known)
  //   tpu.obs.stage.<stage>.{p50,p99}-ms (when any node published SLO)
  lm::Labels BuildOutputLabels() const { return BuildRollupLabels(roll_); }

  // The store's whole aggregate state — what an L1 shard serializes
  // into its partial CR (SerializePartialLabels).
  const RollupState& Partial() const { return roll_; }

  // The merged fleet stage sketches (sum of every node's published
  // contribution) — what the burn evaluator feeds on.
  const StageSketches& stage_sketches() const { return roll_.stage; }

  // Self-check / debug ONLY: rebuilds every rollup from the retained
  // contributions and bumps full_recomputes. The steady path never
  // calls this — `tfd_agg_full_recomputes_total` staying 0 after sync
  // is the incremental-update acceptance contract.
  void RecomputeAll();

  void Clear();

 private:
  void Retire(const NodeContribution& c);
  void Admit(const NodeContribution& c);

  std::map<std::string, NodeContribution> nodes_;
  // Everything the contributions roll up to (roll_.nodes is kept equal
  // to nodes_.size() by Apply/Remove/Clear).
  RollupState roll_;
  uint64_t events_ = 0;
  uint64_t full_recomputes_ = 0;
};

// ---- coalescing publish debounce -----------------------------------------

// Bounded-staleness flush: the FIRST dirtying event opens a window of
// `debounce_s`; every further event inside it rides the same flush, so
// a 1000-node churn burst becomes ONE output write and no rollup is
// ever published more than debounce_s late. (An event landing while a
// window is open never extends it — this is a staleness bound, not a
// quiet-period timer, so a steady event drizzle cannot starve the
// publish forever.)
class FlushController {
 public:
  explicit FlushController(double debounce_s) : debounce_s_(debounce_s) {}

  void NoteDirty(double now) {
    if (dirty_since_ < 0) dirty_since_ = now;
  }
  bool dirty() const { return dirty_since_ >= 0; }
  double dirty_since() const { return dirty_since_; }
  // When the pending flush is owed (clean = +infinity).
  double DueAt() const;
  bool ShouldFlush(double now) const { return dirty() && now >= DueAt(); }
  void NoteFlushed() { dirty_since_ = -1; }
  // Restore a consumed window after a failed publish: the retry owes
  // the ORIGINAL staleness, so an event that dirtied the controller
  // mid-publish never shortens it.
  void ReArm(double since) {
    if (dirty_since_ < 0 || since < dirty_since_) dirty_since_ = since;
  }

 private:
  double debounce_s_;
  double dirty_since_ = -1;
};

}  // namespace agg
}  // namespace tfd

// The tpu-feature-aggregator binary mode (--mode=aggregator): the
// transport around agg/agg.h's incremental rollup core.
//
// One optional cluster singleton (a Deployment, not a DaemonSet),
// lease-elected through the same optimistic-concurrency ConfigMap
// discipline as the slice blackboard (doc "tfd-aggregator"; standbys
// poll at lease/3 and take over at expiry). The leader LISTs every
// NodeFeature CR once (journal `agg-synced`), then holds ONE
// collection-scoped watch stream — bookmarks, clean timeoutSeconds
// rotation, Retry-After-paced reconnects, and a `410 Gone` that
// re-lists exactly once (journal `agg-resync`) — so steady-state
// apiserver load is independent of fleet size: zero LISTs, one parked
// stream, and one lease renewal per lease/3.
//
// Every watch delta updates the rollups in O(labels changed on one
// node) through InventoryStore::Apply; `tfd_agg_full_recomputes_total`
// exists to prove the steady path never recomputes (the fleet soak
// gates it == 0 after sync). Publishes ride the FlushController's
// coalescing debounce (--agg-debounce, default 2s) as ONE server-side
// apply-patch of the whole rollup label set onto the cluster-scoped
// output object (--agg-output-name), so a 1000-node churn burst becomes
// one write.
#pragma once

#include <signal.h>

#include "tfd/config/config.h"

namespace tfd {
namespace agg {

enum class AggOutcome {
  kExit,     // SIGTERM/SIGINT: clean shutdown
  kRestart,  // SIGHUP: reload config and re-enter
  kError,    // unrecoverable startup failure
};

// Runs the aggregator until a signal. `sigmask` is the blocked set the
// caller (main.cc) collects signals from.
AggOutcome RunAggregator(const config::Config& config,
                         const sigset_t& sigmask);

}  // namespace agg
}  // namespace tfd

#include "tfd/agg/runner.h"

#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <cctype>
#include <chrono>
#include <condition_variable>
#include <mutex>
#include <set>
#include <thread>

#include "tfd/agg/agg.h"
#include "tfd/agg/lease.h"
#include "tfd/info/version.h"
#include "tfd/k8s/client.h"
#include "tfd/k8s/desync.h"
#include "tfd/k8s/watch.h"
#include "tfd/lm/schema.h"
#include "tfd/obs/journal.h"
#include "tfd/obs/metrics.h"
#include "tfd/obs/slo.h"
#include "tfd/obs/trace.h"
#include "tfd/obs/server.h"
#include "tfd/slice/coord.h"
#include "tfd/util/http.h"
#include "tfd/util/jsonlite.h"
#include "tfd/util/logging.h"
#include "tfd/util/strings.h"
#include "tfd/util/time.h"

namespace tfd {
namespace agg {

namespace {

constexpr char kLeaseDocName[] = "tfd-aggregator";
constexpr char kCrNamePrefix[] = "tfd-features-for-";
constexpr char kFieldManager[] = "tfd-aggregator";
// The sharded aggregation tree's object names: every L1 partial is
// "tfd-inventory-shard-<i>"; ALL "tfd-inventory-*" names (root and
// partials) are inventory objects, never node contributions.
constexpr char kInventoryNamePrefix[] = "tfd-inventory-";
constexpr char kPartialNamePrefix[] = "tfd-inventory-shard-";

// Which aggregation tier this process runs (tfd_agg_tier gauge values).
enum class Tier {
  kFlat = 0,   // the PR-12 topology: one store over the whole fleet
  kShard = 1,  // L1: 1/n of the fleet -> one partial CR
  kMerge = 2,  // L2 root: n partial CRs -> the cluster inventory
};

// How one watched object participates in a tier's ingest. The
// inventory exclusion comes FIRST: partials deliberately carry the nfd
// node-name label (so the L2's selector watch sees them), which puts
// them in EVERY tier's stream — without the explicit name rule a shard
// would re-ingest inventory as node contributions.
enum class ObjKind {
  kNodeCr,   // a daemon's per-node CR
  kPartial,  // an L1 shard's partial rollup CR
  kOther,    // the root inventory output, or anything else
};

ObjKind ClassifyName(const std::string& name,
                     const std::string& output_name) {
  if (name.rfind(kPartialNamePrefix, 0) == 0) return ObjKind::kPartial;
  if (name.rfind(kInventoryNamePrefix, 0) == 0 || name == output_name) {
    return ObjKind::kOther;
  }
  if (name.rfind(kCrNamePrefix, 0) == 0) return ObjKind::kNodeCr;
  return ObjKind::kOther;
}

// MonoSeconds / HolderIdentity / UrlEncode / CollectionUrl /
// NodeSelectorQuery / BaseOptions / LeaseState / LeaseTick live in
// agg/lease.h now — the lease discipline is shared with the
// remediation controller (remedy/remedy.cc) and must not fork.

obs::Counter* EventCounter(const char* type) {
  return obs::Default().GetCounter(
      "tfd_agg_events_total",
      "NodeFeature watch events consumed by the aggregator, by type "
      "(list items count as 'listed').",
      {{"type", type}});
}

void SetNodesGauge(size_t nodes) {
  obs::Default()
      .GetGauge("tfd_agg_nodes",
                "Nodes currently retained in the aggregator's inventory "
                "store.")
      ->Set(static_cast<double>(nodes));
}

void SetStateGauge(int state) {
  obs::Default()
      .GetGauge("tfd_agg_state",
                "Aggregator role: 0 follower/standby, 1 leader (watching "
                "and publishing).")
      ->Set(state);
}

// Registered at startup so the acceptance contract (== 0 after sync)
// is scrapeable even though the steady path never increments it.
obs::Counter* FullRecomputeCounter() {
  return obs::Default().GetCounter(
      "tfd_agg_full_recomputes_total",
      "Rollup recomputations from scratch. The incremental-update "
      "contract: 0 after the initial sync — every delta retires and "
      "re-applies ONE node's contribution instead.");
}

obs::Gauge* BurnStateGauge(const std::string& stage) {
  return obs::Default().GetGauge(
      "tfd_slo_burn_state",
      "Fleet SLO burn verdict per pipeline stage: 1 while the stage's "
      "fast-window over-budget fraction holds the burn (slo-burn "
      "journaled), 0 otherwise.",
      {{"stage", stage}});
}

// ---- shared state between the watch thread and the lease/flush loop ------

struct Shared {
  std::mutex mu;
  std::condition_variable cv;
  InventoryStore store;      // kFlat / kShard: per-node contributions
  ShardMergeStore merge;     // kMerge: per-shard partials
  FlushController flush;
  // Multi-window burn detection over the merged fleet stage sketches;
  // evaluated on the flush loop's cadence under this mutex.
  BurnEvaluator burn;
  bool synced = false;
  // The latest causal change-id annotation consumed from a node CR
  // (obs::kChangeAnnotation) — echoed onto the inventory object's own
  // annotation at the next flush, so the cluster-scoped rollup joins
  // back to the per-node trace that moved it.
  std::string last_change;
  // Tier topology (fixed at startup, read freely).
  Tier tier = Tier::kFlat;
  int shard_index = 0;  // kShard: this process owns shard_index of
  int shard_count = 0;  //   shard_count (ShardIndexOf assignment)
  std::string output_name;

  Shared(double debounce_s, std::map<std::string, double> budgets_ms)
      : flush(debounce_s), burn(std::move(budgets_ms)) {}

  // The tier's retained-population size for the tfd_agg_nodes gauge —
  // merged node total at the root, store size below it.
  size_t Population() const {
    return tier == Tier::kMerge ? static_cast<size_t>(merge.merged().nodes)
                                : store.nodes();
  }
};

// ---- the collection watcher ----------------------------------------------

// One long-lived list-then-watch over the WHOLE NodeFeature collection.
// Same discipline as k8s::NodeFeatureWatcher (PR 11) at collection
// scope: resourceVersion bookmarks, clean rotation, Retry-After pacing,
// exponential backoff with per-process jitter, 410 -> re-list once.
class CollectionWatcher {
 public:
  CollectionWatcher(k8s::ClusterConfig config, Shared* shared)
      : config_(std::move(config)), shared_(shared) {}
  ~CollectionWatcher() { Stop(); }

  void Start() {
    if (started_) return;
    started_ = true;
    stop_.store(false);
    thread_ = std::thread([this] { RunLoop(); });
  }

  void Stop() {
    if (!started_) return;
    stop_.store(true);
    {
      std::lock_guard<std::mutex> lock(mu_);
      cv_.notify_all();
    }
    int fd = stream_fd_.load();
    if (fd >= 0) shutdown(fd, SHUT_RDWR);
    if (thread_.joinable()) thread_.join();
    started_ = false;
  }

  uint64_t relists() const { return relists_.load(); }

 private:
  bool SleepFor(double seconds) {
    std::unique_lock<std::mutex> lock(mu_);
    cv_.wait_for(lock,
                 std::chrono::milliseconds(
                     static_cast<long long>(seconds * 1000)),
                 [this] { return stop_.load(); });
    return !stop_.load();
  }

  // Applies one object's labels (and its stage-SLO annotation) to the
  // tier's store under the shared lock; notes dirty + wakes the flush
  // loop when a rollup moved. Inventory objects (the root output and
  // every tfd-inventory-* partial) are NEVER node contributions, at
  // any tier — the L2 root consumes the partials, everyone else
  // ignores them.
  void ApplyObject(const std::string& name, const lm::Labels& labels,
                   bool deleted, const std::string& change = "",
                   const std::string& stage_slo = "") {
    ObjKind kind = ClassifyName(name, shared_->output_name);
    std::lock_guard<std::mutex> lock(shared_->mu);
    bool moved = false;
    if (shared_->tier == Tier::kMerge) {
      if (kind != ObjKind::kPartial) return;  // the root merges partials only
      if (deleted) {
        moved = shared_->merge.RemovePartial(name);
      } else {
        RollupState partial;
        // Not (yet) a partial payload — e.g. the CR exists but another
        // writer owns it. Tolerate, never ingest.
        if (!ParsePartialLabels(labels, &partial)) return;
        moved = shared_->merge.ApplyPartial(name, partial);
      }
    } else {
      if (kind != ObjKind::kNodeCr) return;  // satellite rule: excluded
      std::string node = name.substr(sizeof(kCrNamePrefix) - 1);
      if (shared_->tier == Tier::kShard &&
          ShardIndexOf(node, shared_->shard_count) != shared_->shard_index) {
        return;  // another shard's node
      }
      moved = deleted ? shared_->store.Remove(node)
                      : shared_->store.Apply(node, labels, stage_slo);
    }
    SetNodesGauge(shared_->Population());
    if (moved) {
      if (!change.empty()) shared_->last_change = change;
      shared_->flush.NoteDirty(MonoSeconds());
      shared_->cv.notify_all();
    }
  }

  // One collection LIST: applies every item incrementally and retires
  // nodes that vanished while we were not watching. Returns the list's
  // resourceVersion.
  Status ListOnce(std::string* rv) {
    http::RequestOptions options = BaseOptions(config_);
    options.timeout_ms = 15000;
    options.deadline_ms = 30000;
    std::string url = CollectionUrl(config_) + "?" + NodeSelectorQuery();
    Result<http::Response> listed = http::Request("GET", url, "", options);
    if (!listed.ok()) return Status::Error("list failed: " + listed.error());
    if (listed->status == 429 || listed->status == 503) {
      double pause = listed->RetryAfterSeconds();
      return Status::Error("list throttled (HTTP " +
                           std::to_string(listed->status) + ", retry in " +
                           std::to_string(pause) + "s)");
    }
    if (listed->status != 200) {
      return Status::Error("list HTTP " + std::to_string(listed->status));
    }
    Result<jsonlite::ValuePtr> parsed = jsonlite::Parse(listed->body);
    if (!parsed.ok()) {
      return Status::Error("list parse: " + parsed.error());
    }
    if (jsonlite::ValuePtr v =
            (*parsed)->GetPath("metadata.resourceVersion");
        v && v->kind == jsonlite::Value::Kind::kString) {
      *rv = v->string_value;
    }
    std::set<std::string> listed_nodes;
    std::set<std::string> listed_partials;
    jsonlite::ValuePtr items = (*parsed)->Get("items");
    if (items && items->kind == jsonlite::Value::Kind::kArray) {
      for (const jsonlite::ValuePtr& item : items->array_items) {
        if (!item || item->kind != jsonlite::Value::Kind::kObject) continue;
        std::string name;
        if (jsonlite::ValuePtr n = item->GetPath("metadata.name");
            n && n->kind == jsonlite::Value::Kind::kString) {
          name = n->string_value;
        }
        // Only the tier's own ingest kind counts as a listed item; the
        // inventory-name exclusion applies to a LIST exactly as it does
        // to the watch stream.
        ObjKind kind = ClassifyName(name, shared_->output_name);
        if (shared_->tier == Tier::kMerge) {
          if (kind != ObjKind::kPartial) continue;
        } else if (kind != ObjKind::kNodeCr) {
          continue;
        }
        lm::Labels labels;
        if (jsonlite::ValuePtr l = item->GetPath("spec.labels");
            l && l->kind == jsonlite::Value::Kind::kObject) {
          for (const auto& [k, v] : l->object_items) {
            if (v && v->kind == jsonlite::Value::Kind::kString) {
              labels[k] = v->string_value;
            }
          }
        }
        // The node's stage-SLO contribution rides as an annotation next
        // to the change id (obs/slo.h) — a re-list must re-learn it, or
        // the fleet sketches would stay stale until the node's next
        // publish. The change id itself is NOT consumed here: a list is
        // not a label movement, and stamping an arbitrary item's id
        // onto the next flush would mis-join the rollup.
        std::string stage_slo;
        if (jsonlite::ValuePtr annotations =
                item->GetPath("metadata.annotations");
            annotations &&
            annotations->kind == jsonlite::Value::Kind::kObject) {
          if (jsonlite::ValuePtr slo =
                  annotations->Get(obs::kSloAnnotation);
              slo && slo->kind == jsonlite::Value::Kind::kString) {
            stage_slo = slo->string_value;
          }
        }
        if (shared_->tier == Tier::kMerge) {
          listed_partials.insert(name);
        } else {
          listed_nodes.insert(name.substr(sizeof(kCrNamePrefix) - 1));
        }
        EventCounter("listed")->Inc();
        ApplyObject(name, labels, /*deleted=*/false, /*change=*/"",
                    stage_slo);
      }
    }
    // Deletes missed while not watching: every retained node (or
    // partial, at the root) absent from the list retires through the
    // SAME incremental path.
    if (shared_->tier == Tier::kMerge) {
      std::vector<std::string> known;
      {
        std::lock_guard<std::mutex> lock(shared_->mu);
        known = shared_->merge.ShardNames();
      }
      for (const std::string& shard : known) {
        if (listed_partials.count(shard) == 0) {
          ApplyObject(shard, {}, /*deleted=*/true);
        }
      }
    } else {
      std::vector<std::string> known;
      {
        std::lock_guard<std::mutex> lock(shared_->mu);
        known = shared_->store.NodeNames();
      }
      for (const std::string& node : known) {
        if (listed_nodes.count(node) == 0) {
          ApplyObject(kCrNamePrefix + node, {}, /*deleted=*/true);
        }
      }
    }
    relists_.fetch_add(1);
    return Status::Ok();
  }

  void RunLoop() {
    const std::string node_key = HolderIdentity();
    std::string rv;
    int consecutive_failures = 0;

    while (!stop_.load()) {
      if (rv.empty()) {
        Status listed = ListOnce(&rv);
        if (!listed.ok()) {
          consecutive_failures++;
          double pause = std::min(
              30.0, 1.0 * (1 << std::min(consecutive_failures - 1, 10)));
          TFD_LOG_WARNING << "aggregator list: " << listed.message()
                          << "; retrying in ~" << pause << "s";
          if (!SleepFor(k8s::desync::SpreadRetryAfterS(pause, node_key))) {
            return;
          }
          continue;
        }
        consecutive_failures = 0;
        bool first_sync;
        size_t nodes;
        {
          std::lock_guard<std::mutex> lock(shared_->mu);
          first_sync = !shared_->synced;
          shared_->synced = true;
          nodes = shared_->store.nodes();
          // The list itself may have moved rollups: publish them.
          shared_->flush.NoteDirty(MonoSeconds());
          shared_->cv.notify_all();
        }
        obs::DefaultJournal().Record(
            first_sync ? "agg-synced" : "agg-resync", "agg",
            (first_sync ? std::string("initial sync: ")
                        : std::string("re-list after 410: ")) +
                std::to_string(nodes) + " nodes at rv " + rv,
            {{"nodes", std::to_string(nodes)}, {"resource_version", rv}});
      }

      std::string url = CollectionUrl(config_) + "?" + NodeSelectorQuery() +
                        "&watch=true&allowWatchBookmarks=true"
                        "&timeoutSeconds=240";
      if (!rv.empty()) url += "&resourceVersion=" + rv;
      http::RequestOptions stream_options = BaseOptions(config_);
      stream_options.timeout_ms = 300000;
      stream_options.connect_timeout_ms = 5000;

      bool established = false;
      bool resync_gone = false;
      double server_retry_after = 0;
      int stream_status = 0;
      std::string line_buffer;
      http::StreamHandler handler;
      handler.on_connected = [this](int fd) { stream_fd_.store(fd); };
      handler.on_response = [&](const http::Response& head) {
        stream_status = head.status;
        server_retry_after = head.RetryAfterSeconds();
        if (head.status == 200) {
          established = true;
          consecutive_failures = 0;
          return true;
        }
        return false;
      };
      handler.on_data = [&](const char* data, size_t len) {
        if (stop_.load()) return false;
        line_buffer.append(data, len);
        size_t start = 0;
        size_t eol;
        while ((eol = line_buffer.find('\n', start)) != std::string::npos) {
          std::string line = line_buffer.substr(start, eol - start);
          start = eol + 1;
          if (line.empty() || line == "\r") continue;
          k8s::WatchEvent event = k8s::ParseWatchEventLine(line);
          EventCounter(k8s::WatchEventTypeName(event.type))->Inc();
          switch (event.type) {
            case k8s::WatchEvent::Type::kBookmark:
              if (!event.resource_version.empty()) {
                rv = event.resource_version;
              }
              break;
            case k8s::WatchEvent::Type::kError:
              if (event.error_code == 410) {
                resync_gone = true;
                line_buffer.clear();
                return false;
              }
              break;
            case k8s::WatchEvent::Type::kAdded:
            case k8s::WatchEvent::Type::kModified:
            case k8s::WatchEvent::Type::kDeleted:
              if (!event.resource_version.empty()) {
                rv = event.resource_version;
              }
              ApplyObject(event.name, event.labels,
                          event.type == k8s::WatchEvent::Type::kDeleted,
                          event.change, event.stage_slo);
              break;
            case k8s::WatchEvent::Type::kUnknown:
              break;
          }
        }
        line_buffer.erase(0, start);
        if (line_buffer.size() > 1024 * 1024) line_buffer.clear();
        return true;
      };

      Status streamed =
          http::RequestStream("GET", url, "", stream_options, handler);
      stream_fd_.store(-1);
      if (stop_.load()) return;

      if (resync_gone || stream_status == 410) {
        obs::DefaultJournal().Record(
            "agg-resync", "agg",
            "collection watch resourceVersion too old (410 Gone); "
            "re-listing once",
            {{"resource_version", rv}});
        rv.clear();
        continue;
      }
      if (streamed.ok() && established) continue;  // clean rotation
      if (stream_status == 429 || stream_status == 503 ||
          server_retry_after > 0) {
        double pause = server_retry_after > 0 ? server_retry_after : 1.0;
        if (!SleepFor(k8s::desync::SpreadRetryAfterS(pause, node_key))) {
          return;
        }
        continue;
      }
      consecutive_failures++;
      double pause = std::min(
          30.0, 1.0 * (1 << std::min(consecutive_failures - 1, 10)));
      TFD_LOG_WARNING << "aggregator watch dropped ("
                      << (!streamed.ok()
                              ? streamed.message()
                              : "HTTP " + std::to_string(stream_status))
                      << "); reconnecting in ~" << pause << "s";
      if (!SleepFor(k8s::desync::SpreadRetryAfterS(pause, node_key))) {
        return;
      }
    }
  }

  k8s::ClusterConfig config_;
  Shared* shared_;
  std::thread thread_;
  std::atomic<bool> stop_{false};
  std::atomic<int> stream_fd_{-1};
  std::atomic<uint64_t> relists_{0};
  std::mutex mu_;
  std::condition_variable cv_;
  bool started_ = false;
};

// ---- output publish -------------------------------------------------------

// One server-side apply of the full rollup set under the
// "tfd-aggregator" field manager (creates-if-missing, zero GETs); a
// server without SSA (415/405) falls back to GET -> PUT/POST, like the
// sink's ladder, remembered per process.
Status PublishOutput(const k8s::ClusterConfig& config,
                     const std::string& output_name,
                     const lm::Labels& labels, bool* apply_unsupported,
                     const std::string& change = "",
                     const lm::Labels& meta_labels = {}) {
  std::string named_url = CollectionUrl(config) + "/" + output_name;
  std::string meta = std::string("\"name\":") + jsonlite::Quote(output_name);
  if (!meta_labels.empty()) {
    // An L1 partial stamps the nfd node-name METADATA label so the L2
    // root's selector watch sees it (the flat/root output deliberately
    // carries none, staying outside every watch).
    meta += ",\"labels\":" + jsonlite::SerializeStringMap(meta_labels);
  }
  if (!change.empty()) {
    // Echo the latest per-node change id that moved this rollup: the
    // inventory object stays joinable to the origin daemon's trace.
    meta += std::string(",\"annotations\":{\"") + obs::kChangeAnnotation +
            "\":" + jsonlite::Quote(change) + "}";
  }
  std::string body =
      std::string("{\"apiVersion\":\"nfd.k8s-sigs.io/v1alpha1\","
                  "\"kind\":\"NodeFeature\",\"metadata\":{") +
      meta + "},\"spec\":{\"labels\":" +
      jsonlite::SerializeStringMap(labels) + "}}";

  if (!*apply_unsupported) {
    http::RequestOptions options = BaseOptions(config);
    options.headers["Content-Type"] = "application/apply-patch+yaml";
    options.deadline_ms = 15000;
    Result<http::Response> applied = http::Request(
        "PATCH",
        named_url + "?fieldManager=" + std::string(kFieldManager) +
            "&force=true",
        body, options);
    if (!applied.ok()) {
      return Status::Error("apply failed: " + applied.error());
    }
    if (applied->status == 200 || applied->status == 201) {
      return Status::Ok();
    }
    if (applied->status == 415 || applied->status == 405) {
      *apply_unsupported = true;  // demote for the rest of the process
    } else {
      return Status::Error("apply HTTP " +
                           std::to_string(applied->status));
    }
  }

  // Fallback rung: GET -> mutate -> PUT (or POST when absent).
  http::RequestOptions options = BaseOptions(config);
  options.deadline_ms = 15000;
  Result<http::Response> got = http::Request("GET", named_url, "", options);
  if (!got.ok()) return Status::Error("get failed: " + got.error());
  if (got->status == 404) {
    http::RequestOptions post = BaseOptions(config);
    post.headers["Content-Type"] = "application/json";
    post.deadline_ms = 15000;
    Result<http::Response> created =
        http::Request("POST", CollectionUrl(config), body, post);
    if (!created.ok()) {
      return Status::Error("create failed: " + created.error());
    }
    if (created->status == 200 || created->status == 201) {
      return Status::Ok();
    }
    return Status::Error("create HTTP " + std::to_string(created->status));
  }
  if (got->status != 200) {
    return Status::Error("get HTTP " + std::to_string(got->status));
  }
  Result<jsonlite::ValuePtr> parsed = jsonlite::Parse(got->body);
  if (!parsed.ok()) return Status::Error("get parse: " + parsed.error());
  jsonlite::ValuePtr spec = std::make_shared<jsonlite::Value>();
  spec->kind = jsonlite::Value::Kind::kObject;
  spec->Set("labels", jsonlite::FromStringMap(labels));
  (*parsed)->Set("spec", spec);
  if (!meta_labels.empty()) {
    if (jsonlite::ValuePtr metadata = (*parsed)->Get("metadata");
        metadata && metadata->kind == jsonlite::Value::Kind::kObject) {
      metadata->Set("labels", jsonlite::FromStringMap(meta_labels));
    }
  }
  http::RequestOptions put = BaseOptions(config);
  put.headers["Content-Type"] = "application/json";
  put.deadline_ms = 15000;
  Result<http::Response> replaced = http::Request(
      "PUT", named_url, jsonlite::Serialize(**parsed), put);
  if (!replaced.ok()) {
    return Status::Error("put failed: " + replaced.error());
  }
  if (replaced->status == 200) return Status::Ok();
  return Status::Error("put HTTP " + std::to_string(replaced->status));
}

}  // namespace

AggOutcome RunAggregator(const config::Config& config,
                         const sigset_t& sigmask) {
  const config::Flags& flags = config.flags;
  Result<k8s::ClusterConfig> cluster = k8s::LoadInClusterEndpoint();
  if (!cluster.ok()) {
    TFD_LOG_ERROR << "aggregator: " << cluster.error();
    return AggOutcome::kError;
  }
  cluster->request_deadline_ms = flags.sink_request_deadline_s * 1000;
  const std::string self = HolderIdentity();

  std::unique_ptr<obs::IntrospectionServer> server;
  if (!flags.introspection_addr.empty()) {
    obs::ServerOptions options;
    options.addr = flags.introspection_addr;
    options.journal = &obs::DefaultJournal();
    // The aggregator mints no changes of its own (its per-event trace
    // state is the inventory annotation echo), but the server's 404
    // catalogue advertises /debug/trace — serve the (empty) ring
    // rather than 404 on a path we claim to serve.
    options.trace = &obs::DefaultTrace();
    // Ready = the lease loop is making contact; 3 leases of slack.
    options.stale_after_s = std::max(120, 3 * flags.agg_lease_duration_s);
    Result<std::unique_ptr<obs::IntrospectionServer>> started =
        obs::IntrospectionServer::Start(options, &obs::Default());
    if (!started.ok()) {
      TFD_LOG_ERROR << "aggregator introspection server: "
                    << started.error();
      return AggOutcome::kError;
    }
    server = std::move(*started);
    TFD_LOG_INFO << "aggregator introspection on port " << server->port();
  }

  // Tier topology: --agg-shard=i/n -> L1 shard (partial publisher),
  // --agg-merge-shards=n -> L2 root (partial consumer), neither ->
  // the flat PR-12 singleton. Config validated the shard spec shape.
  Tier tier = Tier::kFlat;
  int shard_index = 0;
  int shard_count = 0;
  if (!flags.agg_shard.empty()) {
    size_t slash = flags.agg_shard.find('/');
    ParseNonNegInt(flags.agg_shard.substr(0, slash), &shard_index);
    ParseNonNegInt(flags.agg_shard.substr(slash + 1), &shard_count);
    tier = Tier::kShard;
  } else if (flags.agg_merge_shards > 0) {
    tier = Tier::kMerge;
  }
  // An L1's output is its partial CR and its lease doc is per-shard —
  // each shard's replica pair elects its own leader independently.
  const std::string output_name =
      tier == Tier::kShard
          ? kPartialNamePrefix + std::to_string(shard_index)
          : flags.agg_output_name;
  const std::string lease_doc =
      tier == Tier::kShard
          ? std::string(kLeaseDocName) + "-shard-" +
                std::to_string(shard_index)
          : kLeaseDocName;
  const std::string shard_spec =
      std::to_string(shard_index) + "/" + std::to_string(shard_count);

  TFD_LOG_INFO << "tpu-feature-aggregator " << info::VersionString()
               << " as " << self << " (output " << output_name
               << ", debounce " << flags.agg_debounce_s << "s, lease "
               << flags.agg_lease_duration_s << "s"
               << (tier == Tier::kShard
                       ? ", L1 shard " + flags.agg_shard
                       : tier == Tier::kMerge
                             ? ", L2 root of " +
                                   std::to_string(flags.agg_merge_shards) +
                                   " shards"
                             : std::string())
               << ")";
  FullRecomputeCounter();  // register at 0: the acceptance contract
  SetStateGauge(0);
  obs::Default()
      .GetGauge("tfd_agg_tier",
                 "Aggregation tier this process runs: 0 flat singleton, "
                 "1 L1 shard (partial publisher), 2 L2 merge root.")
      ->Set(static_cast<double>(static_cast<int>(tier)));

  // Stage budgets: the derived defaults (agg.h provenance note), with
  // operator overrides from TFD_SLO_BUDGETS_MS ("stage=ms,..." — the
  // CI slo-smoke tightens budgets through it to trip a burn quickly).
  const char* budget_spec = std::getenv("TFD_SLO_BUDGETS_MS");
  std::map<std::string, double> budgets =
      SloBudgetsMsFromSpec(budget_spec ? budget_spec : "");
  for (const auto& [stage, ms] : budgets) {
    (void)ms;
    BurnStateGauge(stage)->Set(0);  // register: scrape-deterministic
  }

  Shared shared(static_cast<double>(flags.agg_debounce_s),
                std::move(budgets));
  shared.tier = tier;
  shared.shard_index = shard_index;
  shared.shard_count = shard_count;
  shared.output_name = flags.agg_output_name;
  CollectionWatcher watcher(*cluster, &shared);
  LeaseState lease_state;
  bool apply_unsupported = false;
  const double lease_tick_s =
      std::max(1.0, flags.agg_lease_duration_s / 3.0);
  double next_lease_tick = 0;  // immediately
  double flush_retry_at = 0;

  while (true) {
    // Collect pending signals without blocking the flush loop.
    struct timespec zero = {0, 0};
    int sig;
    while ((sig = sigtimedwait(&sigmask, nullptr, &zero)) > 0) {
      if (sig == SIGTERM || sig == SIGINT || sig == SIGQUIT) {
        TFD_LOG_INFO << "aggregator: signal " << sig << ", shutting down";
        watcher.Stop();
        return AggOutcome::kExit;
      }
      if (sig == SIGHUP) {
        TFD_LOG_INFO << "aggregator: SIGHUP, reloading";
        watcher.Stop();
        return AggOutcome::kRestart;
      }
      // SIGUSR1 etc.: nothing mode-specific to dump.
    }

    double now = MonoSeconds();
    if (now >= next_lease_tick) {
      bool was_leading = lease_state.leading;
      LeaseTick(*cluster, lease_doc, self, flags.agg_lease_duration_s,
                "agg", &lease_state);
      SetStateGauge(lease_state.leading ? 1 : 0);
      next_lease_tick = now + lease_tick_s;
      if (server && lease_state.ever_contacted) {
        server->RecordRewrite(true);  // lease contact = liveness
      }
      if (lease_state.leading && !was_leading) {
        watcher.Start();
      } else if (!lease_state.leading && was_leading) {
        // Lost the lease: stop watching and forget — the new leader
        // owns the output; a re-election re-lists from scratch.
        watcher.Stop();
        std::lock_guard<std::mutex> lock(shared.mu);
        shared.store.Clear();
        shared.merge.Clear();
        shared.synced = false;
        shared.flush.NoteFlushed();
      }
    }

    bool flush_now = false;
    lm::Labels output;
    std::string flush_change;
    double staleness_s = 0;
    double flush_dirty_since = 0;
    std::vector<BurnEvaluator::Edge> burn_edges;
    {
      std::unique_lock<std::mutex> lock(shared.mu);
      // A pending retry pushes the dirty flush's due time out to
      // flush_retry_at — without the max() the loop would wake
      // immediately (DueAt already past), fail the retry gate, and
      // busy-spin for the whole retry window during an outage.
      double due = std::min(std::max(shared.flush.DueAt(), flush_retry_at),
                            next_lease_tick);
      double wait_s = std::min(0.2, std::max(0.0, due - MonoSeconds()));
      shared.cv.wait_for(
          lock, std::chrono::milliseconds(
                    static_cast<long long>(wait_s * 1000)));
      now = MonoSeconds();
      if (lease_state.leading && shared.synced && tier != Tier::kShard) {
        // One burn-evaluation tick over the merged fleet sketches —
        // BEFORE the flush decision, so a verdict edge both dirties
        // the window and rides the very flush it triggers. An L1 shard
        // never burns: its sketches cover 1/n of the fleet — the fleet
        // verdict belongs to the tier that merges them.
        burn_edges = shared.burn.Note(
            now, tier == Tier::kMerge ? shared.merge.stage_sketches()
                                      : shared.store.stage_sketches());
        if (!burn_edges.empty()) shared.flush.NoteDirty(now);
      }
      if (lease_state.leading && shared.synced &&
          shared.flush.ShouldFlush(now) && now >= flush_retry_at) {
        flush_now = true;
        if (tier == Tier::kShard) {
          // An L1 publishes its PARTIAL — the whole aggregate as
          // counter maps + sparse sketches, never scalars.
          output = SerializePartialLabels(shared.store.Partial(),
                                          shard_spec);
        } else if (tier == Tier::kMerge) {
          output = shared.merge.BuildOutputLabels();
        } else {
          output = shared.store.BuildOutputLabels();
        }
        // Burning stages ride the rollup as labels: the scheduler (and
        // the soak's assertions) read the fleet burn verdict exactly
        // where the rollups live, no scrape required.
        for (const std::string& stage : shared.burn.BurningStages()) {
          output[std::string(lm::kSloBurnPrefix) + stage + ".burn"] =
              "true";
        }
        flush_change = shared.last_change;
        flush_dirty_since = shared.flush.dirty_since();
        staleness_s = now - flush_dirty_since;
        // Consume the window at CAPTURE time, while the lock still
        // covers the output snapshot above. A rollup that moves during
        // the publish (the root's second partial landing while the
        // first one's flush is in flight) then re-arms a fresh window
        // instead of being erased by a post-publish NoteFlushed — that
        // erasure silently dropped the last delta forever when no
        // later event came to repair it.
        shared.flush.NoteFlushed();
      }
    }

    for (const BurnEvaluator::Edge& edge : burn_edges) {
      BurnStateGauge(edge.stage)->Set(edge.burning ? 1 : 0);
      double budget_ms = 0;
      auto it = shared.burn.budgets_ms().find(edge.stage);
      if (it != shared.burn.budgets_ms().end()) budget_ms = it->second;
      obs::DefaultJournal().Record(
          edge.burning ? "slo-burn" : "slo-clear", "agg",
          edge.burning
              ? "fleet '" + edge.stage + "' stage burning its " +
                    Fixed3(budget_ms) + "ms budget (fast-window mean >= " +
                    Fixed3(BurnEvaluator::kFastThreshold) + ")"
              : "fleet '" + edge.stage + "' stage burn cleared",
          {{"stage", edge.stage}, {"budget_ms", Fixed3(budget_ms)}});
    }

    if (flush_now) {
      auto t0 = std::chrono::steady_clock::now();
      // A partial stamps the nfd node-name metadata label so the L2
      // root's selector watch delivers it; the label's value is the
      // partial's own name (no node owns this object).
      lm::Labels meta_labels;
      if (tier == Tier::kShard) meta_labels[kNodeNameLabel] = output_name;
      Status published =
          PublishOutput(*cluster, output_name, output, &apply_unsupported,
                        flush_change, meta_labels);
      double write_s = obs::SecondsSince(t0);
      if (published.ok()) {
        {
          // The window was consumed at capture time; a NoteDirty that
          // landed while the publish was in flight opened a NEW window
          // that must survive this success path untouched.
          std::lock_guard<std::mutex> lock(shared.mu);
          // The echoed change is consumed by this flush: a later
          // rollup moved only by change-less events must not re-stamp
          // a stale id (a newer change that arrived mid-publish stays
          // for the next flush). A FAILED publish keeps it — the retry
          // still owes the annotation.
          if (shared.last_change == flush_change) {
            shared.last_change.clear();
          }
        }
        flush_retry_at = 0;
        obs::Default()
            .GetCounter("tfd_agg_flushes_total",
                        "Coalesced rollup publishes (one per debounce "
                        "window with changes, regardless of how many "
                        "node deltas rode it).")
            ->Inc();
        obs::Default()
            .GetHistogram(
                "tfd_agg_flush_latency_seconds",
                "Dirty-to-published latency of a rollup flush "
                "(debounce coalescing included).",
                obs::DurationBuckets())
            ->Observe(staleness_s + write_s);
        obs::DefaultJournal().Record(
            "agg-flush", "agg",
            "published " + std::to_string(output.size()) +
                " rollup labels to " + output_name,
            {{"labels", std::to_string(output.size())},
             {"staleness_ms",
              std::to_string(static_cast<long long>(
                  (staleness_s + write_s) * 1000))}});
        if (server) {
          server->RecordRewrite(true);
          std::string json = "{\"output\":" +
                             jsonlite::SerializeStringMap(output) + "}";
          server->SetLabelsJson(json);
        }
      } else {
        // Re-open the consumed window at its ORIGINAL start so the
        // retry still owes the full staleness; retry on a short
        // cadence so a transient write failure costs seconds, not a
        // lost publish.
        {
          std::lock_guard<std::mutex> lock(shared.mu);
          shared.flush.ReArm(flush_dirty_since);
        }
        flush_retry_at = MonoSeconds() + 1.0;
        if (server) server->RecordRewrite(false);
        obs::DefaultJournal().Record(
            "agg-flush-failed", "agg",
            "rollup publish failed: " + published.message(),
            {{"error", published.message()}});
        TFD_LOG_WARNING << "aggregator publish: " << published.message();
      }
    }
  }
}

}  // namespace agg
}  // namespace tfd

#include "tfd/agg/agg.h"

#include <cstdlib>

#include "tfd/k8s/desync.h"
#include "tfd/lm/schema.h"
#include "tfd/util/strings.h"

namespace tfd {
namespace agg {

namespace {

// Strict double parse for a label value ("" / garbage -> fallback).
double ParseLabelDouble(const lm::Labels& labels, const char* key,
                        double fallback) {
  auto it = labels.find(key);
  if (it == labels.end() || it->second.empty()) return fallback;
  char* end = nullptr;
  double v = std::strtod(it->second.c_str(), &end);
  if (end == nullptr || *end != '\0') return fallback;
  return v;
}

int ParseLabelInt(const lm::Labels& labels, const char* key, int fallback) {
  auto it = labels.find(key);
  int out = 0;
  if (it == labels.end() || !ParseNonNegInt(it->second, &out)) {
    return fallback;
  }
  return out;
}

bool LabelTrue(const lm::Labels& labels, const char* key) {
  auto it = labels.find(key);
  return it != labels.end() && it->second == "true";
}

std::string LabelOr(const lm::Labels& labels, const char* key,
                    const char* fallback) {
  auto it = labels.find(key);
  return it == labels.end() ? fallback : it->second;
}

// Capacity bucket for a contribution's perf class: the three published
// classes keep their names; anything else (including "") pools as
// unclassed so the capacity sums always partition total-chips.
std::string CapacityBucket(const std::string& perf_class) {
  if (perf_class == "gold" || perf_class == "silver" ||
      perf_class == "degraded") {
    return perf_class;
  }
  return "unclassed";
}

}  // namespace

// ---- sketch ---------------------------------------------------------------

int SketchBucketIndex(double value) {
  if (!(value > kSketchMin)) return 0;  // NaN and <= min both land in 0
  int idx = 0;
  double edge = kSketchMin;
  // Repeated multiplication, not log(): IEEE doubles make this loop
  // bit-identical in the Python twin, which a libm log() would not be.
  while (idx < kSketchBuckets - 1 && value > edge) {
    edge *= kSketchGamma;
    idx++;
  }
  return idx;
}

double SketchBucketValue(int bucket) {
  if (bucket <= 0) return kSketchMin;
  if (bucket >= kSketchBuckets) bucket = kSketchBuckets - 1;
  double edge = kSketchMin;
  for (int i = 0; i < bucket; i++) edge *= kSketchGamma;
  return edge;
}

void QuantileSketch::Add(double value) {
  counts_[SketchBucketIndex(value)]++;
  total_++;
}

void QuantileSketch::Remove(double value) {
  int idx = SketchBucketIndex(value);
  if (counts_[idx] > 0) {
    counts_[idx]--;
    total_--;
  }
}

void QuantileSketch::Merge(const QuantileSketch& other) {
  for (int i = 0; i < kSketchBuckets; i++) counts_[i] += other.counts_[i];
  total_ += other.total_;
}

void QuantileSketch::Unmerge(const QuantileSketch& other) {
  for (int i = 0; i < kSketchBuckets; i++) {
    int64_t take =
        other.counts_[i] < counts_[i] ? other.counts_[i] : counts_[i];
    counts_[i] -= take;
    total_ -= take;
  }
}

void QuantileSketch::AddBucketCount(int bucket, int64_t n) {
  if (bucket < 0 || bucket >= kSketchBuckets || n <= 0) return;
  counts_[bucket] += n;
  total_ += n;
}

double QuantileSketch::Quantile(double q) const {
  if (total_ <= 0) return -1;
  if (q < 0) q = 0;
  if (q > 1) q = 1;
  // Nearest-rank on the bucketed distribution: the target rank is
  // floor(q * (n-1)), the answer is the bucket holding that rank.
  int64_t target = static_cast<int64_t>(q * (total_ - 1));
  int64_t cumulative = 0;
  for (int i = 0; i < kSketchBuckets; i++) {
    cumulative += counts_[i];
    if (cumulative > target) return SketchBucketValue(i);
  }
  return SketchBucketValue(kSketchBuckets - 1);
}

double QuantileSketch::FractionAbove(double threshold) const {
  if (total_ <= 0) return 0;
  int64_t over = 0;
  for (int i = 0; i < kSketchBuckets; i++) {
    if (counts_[i] > 0 && SketchBucketValue(i) > threshold) {
      over += counts_[i];
    }
  }
  return static_cast<double>(over) / static_cast<double>(total_);
}

void QuantileSketch::Clear() {
  counts_.fill(0);
  total_ = 0;
}

// ---- stage sketches -------------------------------------------------------

std::map<std::string, double> DefaultSloBudgetsMs() {
  return {{"plan", 1200},
          {"render", 100},
          {"publish", 1200},
          {"publish-acked", 1300}};
}

std::map<std::string, double> SloBudgetsMsFromSpec(const std::string& spec) {
  std::map<std::string, double> budgets = DefaultSloBudgetsMs();
  for (const std::string& entry : SplitString(spec, ',')) {
    size_t eq = entry.find('=');
    if (eq == std::string::npos) continue;
    std::string stage = entry.substr(0, eq);
    if (budgets.find(stage) == budgets.end()) continue;
    int ms = 0;
    if (!ParseNonNegInt(entry.substr(eq + 1), &ms) || ms <= 0) continue;
    budgets[stage] = static_cast<double>(ms);
  }
  return budgets;
}

std::string SerializeStageSketches(const StageSketches& stages) {
  std::string out;
  for (const char* name : kSloStages) {
    auto it = stages.find(name);
    if (it == stages.end() || it->second.count() <= 0) continue;
    if (!out.empty()) out += ';';
    out += name;
    out += '=';
    bool first = true;
    const auto& counts = it->second.bucket_counts();
    for (int i = 0; i < kSketchBuckets; i++) {
      if (counts[i] <= 0) continue;
      if (!first) out += ',';
      first = false;
      out += std::to_string(i);
      out += ':';
      out += std::to_string(counts[i]);
    }
  }
  return out;
}

StageSketches ParseStageSketches(const std::string& text) {
  StageSketches out;
  for (const std::string& entry : SplitString(text, ';')) {
    size_t eq = entry.find('=');
    if (eq == std::string::npos) continue;
    std::string stage = entry.substr(0, eq);
    bool known = false;
    for (const char* name : kSloStages) known |= stage == name;
    if (!known) continue;  // a newer (or hostile) node's vocabulary
    QuantileSketch sketch;
    for (const std::string& pair : SplitString(entry.substr(eq + 1), ',')) {
      size_t colon = pair.find(':');
      if (colon == std::string::npos) continue;
      int bucket = 0;
      int n = 0;
      if (!ParseNonNegInt(pair.substr(0, colon), &bucket) ||
          !ParseNonNegInt(pair.substr(colon + 1), &n)) {
        continue;
      }
      sketch.AddBucketCount(bucket, n);
    }
    if (sketch.count() > 0) out[stage].Merge(sketch);
  }
  return out;
}

// ---- burn evaluator -------------------------------------------------------

BurnEvaluator::BurnEvaluator(std::map<std::string, double> budgets_ms,
                             double fast_window_s, double slow_window_s)
    : budgets_(std::move(budgets_ms)),
      fast_window_s_(fast_window_s),
      slow_window_s_(slow_window_s) {}

std::vector<BurnEvaluator::Edge> BurnEvaluator::Note(
    double now, const StageSketches& sketches) {
  std::vector<Edge> edges;
  for (const auto& [stage, budget] : budgets_) {
    auto sk = sketches.find(stage);
    bool have = sk != sketches.end() && sk->second.count() > 0;
    if (!have && stages_.find(stage) == stages_.end()) continue;
    double fraction = have ? sk->second.FractionAbove(budget) : 0.0;
    StageState& state = stages_[stage];
    state.samples.emplace_back(now, fraction);
    while (!state.samples.empty() &&
           state.samples.front().first <= now - slow_window_s_) {
      state.samples.pop_front();
    }
    double fast_sum = 0;
    int64_t fast_n = 0;
    double slow_sum = 0;
    int64_t slow_n = 0;
    for (const auto& [ts, f] : state.samples) {
      slow_sum += f;
      slow_n++;
      if (ts > now - fast_window_s_) {
        fast_sum += f;
        fast_n++;
      }
    }
    double fast = fast_n > 0 ? fast_sum / static_cast<double>(fast_n) : 0;
    double slow = slow_n > 0 ? slow_sum / static_cast<double>(slow_n) : 0;
    if (!state.burning && fast >= kFastThreshold && slow >= kSlowThreshold) {
      state.burning = true;
      edges.push_back({stage, true});
    } else if (state.burning && fast < kFastThreshold) {
      state.burning = false;
      edges.push_back({stage, false});
    }
  }
  return edges;
}

bool BurnEvaluator::burning(const std::string& stage) const {
  auto it = stages_.find(stage);
  return it != stages_.end() && it->second.burning;
}

std::vector<std::string> BurnEvaluator::BurningStages() const {
  std::vector<std::string> out;
  for (const auto& [stage, state] : stages_) {
    if (state.burning) out.push_back(stage);
  }
  return out;
}

// ---- contribution ---------------------------------------------------------

bool NodeContribution::operator==(const NodeContribution& other) const {
  return slice_id == other.slice_id &&
         slice_degraded == other.slice_degraded &&
         multislice_group == other.multislice_group &&
         perf_class == other.perf_class && chips == other.chips &&
         matmul_tflops == other.matmul_tflops &&
         hbm_gbps == other.hbm_gbps && preempting == other.preempting &&
         stage_slo == other.stage_slo;
}

NodeContribution ExtractContribution(const lm::Labels& labels,
                                     const std::string& stage_slo) {
  NodeContribution c;
  c.stage_slo = stage_slo;
  c.slice_id = LabelOr(labels, lm::kSliceId, "");
  c.slice_degraded = LabelTrue(labels, lm::kSliceDegraded);
  c.multislice_group = LabelOr(labels, lm::kMultisliceSliceId, "");
  c.perf_class = LabelOr(labels, lm::kPerfClass, "");
  c.chips = ParseLabelInt(labels, "google.com/tpu.count", 0);
  c.matmul_tflops = ParseLabelDouble(labels, lm::kPerfMatmulTflops, -1);
  c.hbm_gbps = ParseLabelDouble(labels, lm::kPerfHbmGbps, -1);
  c.preempting = LabelTrue(labels, lm::kLifecyclePreemptImminent) ||
                 LabelTrue(labels, lm::kLifecycleDraining);
  return c;
}

// ---- inventory store ------------------------------------------------------

void InventoryStore::Retire(const NodeContribution& c) {
  if (!c.slice_id.empty()) {
    auto it = roll_.slices.find(c.slice_id);
    if (it != roll_.slices.end()) {
      it->second.members--;
      if (c.slice_degraded) it->second.degraded--;
      if (c.preempting) it->second.preempting--;
      if (it->second.members <= 0) roll_.slices.erase(it);
    }
  }
  std::string bucket = CapacityBucket(c.perf_class);
  auto cap = roll_.capacity.find(bucket);
  if (cap != roll_.capacity.end()) {
    cap->second -= c.chips;
    if (cap->second <= 0) roll_.capacity.erase(cap);
  }
  if (!c.multislice_group.empty()) {
    auto ms = roll_.multislice.find(c.multislice_group);
    if (ms != roll_.multislice.end()) {
      ms->second--;
      if (ms->second <= 0) roll_.multislice.erase(ms);
    }
  }
  if (c.preempting) roll_.preempting--;
  if (c.matmul_tflops >= 0) roll_.matmul.Remove(c.matmul_tflops);
  if (c.hbm_gbps >= 0) roll_.hbm.Remove(c.hbm_gbps);
  if (!c.stage_slo.empty()) {
    for (const auto& [stage, sketch] : ParseStageSketches(c.stage_slo)) {
      auto it = roll_.stage.find(stage);
      if (it == roll_.stage.end()) continue;
      it->second.Unmerge(sketch);
      if (it->second.count() <= 0) roll_.stage.erase(it);
    }
  }
}

void InventoryStore::Admit(const NodeContribution& c) {
  if (!c.slice_id.empty()) {
    SliceCounts& agg = roll_.slices[c.slice_id];
    agg.members++;
    if (c.slice_degraded) agg.degraded++;
    if (c.preempting) agg.preempting++;
  }
  roll_.capacity[CapacityBucket(c.perf_class)] += c.chips;
  if (!c.multislice_group.empty()) roll_.multislice[c.multislice_group]++;
  if (c.preempting) roll_.preempting++;
  if (c.matmul_tflops >= 0) roll_.matmul.Add(c.matmul_tflops);
  if (c.hbm_gbps >= 0) roll_.hbm.Add(c.hbm_gbps);
  if (!c.stage_slo.empty()) {
    for (const auto& [stage, sketch] : ParseStageSketches(c.stage_slo)) {
      roll_.stage[stage].Merge(sketch);
    }
  }
}

bool InventoryStore::Apply(const std::string& node, const lm::Labels& labels,
                           const std::string& stage_slo) {
  events_++;
  NodeContribution next = ExtractContribution(labels, stage_slo);
  auto it = nodes_.find(node);
  if (it != nodes_.end()) {
    if (it->second == next) return false;  // e.g. a probe-ms-only delta
    Retire(it->second);
    it->second = next;
  } else {
    nodes_[node] = next;
  }
  Admit(next);
  roll_.nodes = static_cast<int64_t>(nodes_.size());
  return true;
}

std::vector<std::string> InventoryStore::NodeNames() const {
  std::vector<std::string> out;
  out.reserve(nodes_.size());
  for (const auto& [node, c] : nodes_) {
    (void)c;
    out.push_back(node);
  }
  return out;
}

bool InventoryStore::Remove(const std::string& node) {
  events_++;
  auto it = nodes_.find(node);
  if (it == nodes_.end()) return false;
  Retire(it->second);
  nodes_.erase(it);
  roll_.nodes = static_cast<int64_t>(nodes_.size());
  return true;
}

void InventoryStore::RecomputeAll() {
  full_recomputes_++;
  roll_ = RollupState();
  roll_.nodes = static_cast<int64_t>(nodes_.size());
  for (const auto& [node, c] : nodes_) {
    (void)node;
    Admit(c);
  }
}

void InventoryStore::Clear() {
  nodes_.clear();
  roll_ = RollupState();
}

// ---- sharded aggregation tree ---------------------------------------------

int ShardIndexOf(const std::string& node, int shards) {
  if (shards <= 1) return 0;
  // Textbook FNV-1a (desync), NOT util/strings.h Fnv1a64 — the soak's
  // Python twin shards via tpufd.sink.fnv1a64, which pins this one.
  return static_cast<int>(k8s::desync::Fnv1a64(node) %
                          static_cast<uint64_t>(shards));
}

bool RollupState::operator==(const RollupState& other) const {
  return nodes == other.nodes && preempting == other.preempting &&
         slices == other.slices && capacity == other.capacity &&
         multislice == other.multislice && matmul == other.matmul &&
         hbm == other.hbm && stage == other.stage;
}

lm::Labels BuildRollupLabels(const RollupState& state) {
  lm::Labels out;
  int64_t healthy = 0;
  int64_t degraded = 0;
  for (const auto& [id, agg] : state.slices) {
    (void)id;
    if (agg.degraded > 0 || agg.preempting > 0) {
      degraded++;
    } else {
      healthy++;
    }
  }
  out[lm::kInventorySlices] = std::to_string(state.slices.size());
  out[lm::kInventoryHealthySlices] = std::to_string(healthy);
  out[lm::kInventoryDegradedSlices] = std::to_string(degraded);
  int64_t total_chips = 0;
  for (const char* bucket : {"gold", "silver", "degraded", "unclassed"}) {
    auto it = state.capacity.find(bucket);
    int64_t chips = it == state.capacity.end() ? 0 : it->second;
    total_chips += chips;
    out[std::string(lm::kCapacityPrefix) + bucket] = std::to_string(chips);
  }
  out[std::string(lm::kCapacityPrefix) + "total-chips"] =
      std::to_string(total_chips);
  out[lm::kFleetNodes] = std::to_string(state.nodes);
  out[lm::kFleetPreempting] = std::to_string(state.preempting);
  out[lm::kMultisliceGroups] = std::to_string(state.multislice.size());
  if (state.matmul.count() > 0) {
    out[lm::kFleetMatmulP10] = Fixed3(state.matmul.Quantile(0.10));
    out[lm::kFleetMatmulP50] = Fixed3(state.matmul.Quantile(0.50));
  }
  if (state.hbm.count() > 0) {
    out[lm::kFleetHbmP10] = Fixed3(state.hbm.Quantile(0.10));
    out[lm::kFleetHbmP50] = Fixed3(state.hbm.Quantile(0.50));
  }
  for (const char* stage : kSloStages) {
    auto it = state.stage.find(stage);
    if (it == state.stage.end() || it->second.count() <= 0) continue;
    std::string base = std::string(lm::kObsStagePrefix) + stage;
    out[base + ".p50-ms"] = Fixed3(it->second.Quantile(0.50));
    out[base + ".p99-ms"] = Fixed3(it->second.Quantile(0.99));
  }
  return out;
}

std::string SerializeSketch(const QuantileSketch& sketch) {
  std::string out;
  const auto& counts = sketch.bucket_counts();
  for (int i = 0; i < kSketchBuckets; i++) {
    if (counts[i] <= 0) continue;
    if (!out.empty()) out += ',';
    out += std::to_string(i);
    out += ':';
    out += std::to_string(counts[i]);
  }
  return out;
}

QuantileSketch ParseSketch(const std::string& text) {
  QuantileSketch sketch;
  for (const std::string& pair : SplitString(text, ',')) {
    size_t colon = pair.find(':');
    if (colon == std::string::npos) continue;
    int bucket = 0;
    int n = 0;
    if (!ParseNonNegInt(pair.substr(0, colon), &bucket) ||
        !ParseNonNegInt(pair.substr(colon + 1), &n)) {
      continue;
    }
    sketch.AddBucketCount(bucket, n);
  }
  return sketch;
}

namespace {

// "key:v1:v2,..." serializers for the counter maps — deterministic
// (sorted map iteration), annotation-safe, exact-roundtrip (zero
// entries are carried, matching the erase-at-zero store semantics
// where a zero-chip class entry can legitimately exist).
std::string SerializeCounterMap(const std::map<std::string, int64_t>& m) {
  std::string out;
  for (const auto& [key, n] : m) {
    if (!out.empty()) out += ',';
    out += key;
    out += ':';
    out += std::to_string(n);
  }
  return out;
}

void ParseCounterMap(const std::string& text,
                     std::map<std::string, int64_t>* out) {
  for (const std::string& entry : SplitString(text, ',')) {
    size_t colon = entry.find(':');
    if (colon == std::string::npos || colon == 0) continue;
    int n = 0;
    if (!ParseNonNegInt(entry.substr(colon + 1), &n)) continue;
    (*out)[entry.substr(0, colon)] = n;
  }
}

std::string SerializeSliceMap(
    const std::map<std::string, SliceCounts>& slices) {
  std::string out;
  for (const auto& [id, agg] : slices) {
    if (!out.empty()) out += ',';
    out += id;
    out += ':';
    out += std::to_string(agg.members);
    out += ':';
    out += std::to_string(agg.degraded);
    out += ':';
    out += std::to_string(agg.preempting);
  }
  return out;
}

void ParseSliceMap(const std::string& text,
                   std::map<std::string, SliceCounts>* out) {
  for (const std::string& entry : SplitString(text, ',')) {
    std::vector<std::string> parts = SplitString(entry, ':');
    if (parts.size() != 4 || parts[0].empty()) continue;
    int members = 0;
    int degraded = 0;
    int preempting = 0;
    if (!ParseNonNegInt(parts[1], &members) ||
        !ParseNonNegInt(parts[2], &degraded) ||
        !ParseNonNegInt(parts[3], &preempting)) {
      continue;
    }
    (*out)[parts[0]] = SliceCounts{members, degraded, preempting};
  }
}

int64_t ParseCount(const lm::Labels& labels, const char* key) {
  auto it = labels.find(key);
  int n = 0;
  if (it == labels.end() || !ParseNonNegInt(it->second, &n)) return 0;
  return n;
}

}  // namespace

lm::Labels SerializePartialLabels(const RollupState& state,
                                  const std::string& shard_spec) {
  lm::Labels out;
  out[lm::kAggTier] = lm::kAggTierPartial;
  out[lm::kAggShard] = shard_spec;
  out[lm::kAggNodes] = std::to_string(state.nodes);
  out[lm::kAggPreempting] = std::to_string(state.preempting);
  if (!state.slices.empty()) {
    out[lm::kAggSlices] = SerializeSliceMap(state.slices);
  }
  if (!state.capacity.empty()) {
    out[lm::kAggCapacity] = SerializeCounterMap(state.capacity);
  }
  if (!state.multislice.empty()) {
    out[lm::kAggMultislice] = SerializeCounterMap(state.multislice);
  }
  if (state.matmul.count() > 0) {
    out[lm::kAggMatmul] = SerializeSketch(state.matmul);
  }
  if (state.hbm.count() > 0) {
    out[lm::kAggHbm] = SerializeSketch(state.hbm);
  }
  std::string slo = SerializeStageSketches(state.stage);
  if (!slo.empty()) out[lm::kAggStageSlo] = slo;
  return out;
}

bool ParsePartialLabels(const lm::Labels& labels, RollupState* out) {
  auto tier = labels.find(lm::kAggTier);
  if (tier == labels.end() || tier->second != lm::kAggTierPartial) {
    return false;
  }
  *out = RollupState();
  out->nodes = ParseCount(labels, lm::kAggNodes);
  out->preempting = ParseCount(labels, lm::kAggPreempting);
  auto it = labels.find(lm::kAggSlices);
  if (it != labels.end()) ParseSliceMap(it->second, &out->slices);
  it = labels.find(lm::kAggCapacity);
  if (it != labels.end()) ParseCounterMap(it->second, &out->capacity);
  it = labels.find(lm::kAggMultislice);
  if (it != labels.end()) ParseCounterMap(it->second, &out->multislice);
  it = labels.find(lm::kAggMatmul);
  if (it != labels.end()) out->matmul = ParseSketch(it->second);
  it = labels.find(lm::kAggHbm);
  if (it != labels.end()) out->hbm = ParseSketch(it->second);
  it = labels.find(lm::kAggStageSlo);
  if (it != labels.end()) out->stage = ParseStageSketches(it->second);
  return true;
}

void ShardMergeStore::Retire(const RollupState& p) {
  merged_.nodes -= p.nodes;
  merged_.preempting -= p.preempting;
  for (const auto& [id, agg] : p.slices) {
    auto it = merged_.slices.find(id);
    if (it == merged_.slices.end()) continue;
    it->second.members -= agg.members;
    it->second.degraded -= agg.degraded;
    it->second.preempting -= agg.preempting;
    if (it->second.members <= 0) merged_.slices.erase(it);
  }
  for (const auto& [bucket, chips] : p.capacity) {
    auto it = merged_.capacity.find(bucket);
    if (it == merged_.capacity.end()) continue;
    it->second -= chips;
    if (it->second <= 0) merged_.capacity.erase(it);
  }
  for (const auto& [group, members] : p.multislice) {
    auto it = merged_.multislice.find(group);
    if (it == merged_.multislice.end()) continue;
    it->second -= members;
    if (it->second <= 0) merged_.multislice.erase(it);
  }
  merged_.matmul.Unmerge(p.matmul);
  merged_.hbm.Unmerge(p.hbm);
  for (const auto& [stage, sketch] : p.stage) {
    auto it = merged_.stage.find(stage);
    if (it == merged_.stage.end()) continue;
    it->second.Unmerge(sketch);
    if (it->second.count() <= 0) merged_.stage.erase(it);
  }
}

void ShardMergeStore::Admit(const RollupState& p) {
  merged_.nodes += p.nodes;
  merged_.preempting += p.preempting;
  for (const auto& [id, agg] : p.slices) {
    SliceCounts& m = merged_.slices[id];
    m.members += agg.members;
    m.degraded += agg.degraded;
    m.preempting += agg.preempting;
  }
  for (const auto& [bucket, chips] : p.capacity) {
    merged_.capacity[bucket] += chips;
  }
  for (const auto& [group, members] : p.multislice) {
    merged_.multislice[group] += members;
  }
  merged_.matmul.Merge(p.matmul);
  merged_.hbm.Merge(p.hbm);
  for (const auto& [stage, sketch] : p.stage) {
    merged_.stage[stage].Merge(sketch);
  }
}

bool ShardMergeStore::ApplyPartial(const std::string& shard,
                                   const RollupState& partial) {
  events_++;
  auto it = partials_.find(shard);
  if (it != partials_.end()) {
    if (it->second == partial) return false;  // no rollup moved
    Retire(it->second);
    it->second = partial;
  } else {
    partials_[shard] = partial;
  }
  Admit(partial);
  return true;
}

bool ShardMergeStore::RemovePartial(const std::string& shard) {
  events_++;
  auto it = partials_.find(shard);
  if (it == partials_.end()) return false;
  Retire(it->second);
  partials_.erase(it);
  return true;
}

std::vector<std::string> ShardMergeStore::ShardNames() const {
  std::vector<std::string> out;
  out.reserve(partials_.size());
  for (const auto& [shard, p] : partials_) {
    (void)p;
    out.push_back(shard);
  }
  return out;
}

void ShardMergeStore::RecomputeAll() {
  full_recomputes_++;
  merged_ = RollupState();
  for (const auto& [shard, p] : partials_) {
    (void)shard;
    Admit(p);
  }
}

void ShardMergeStore::Clear() {
  partials_.clear();
  merged_ = RollupState();
}

// ---- flush controller -----------------------------------------------------

double FlushController::DueAt() const {
  if (dirty_since_ < 0) return 1e300;
  return dirty_since_ + debounce_s_;
}

}  // namespace agg
}  // namespace tfd

#include "tfd/agg/agg.h"

#include <cstdlib>

#include "tfd/lm/schema.h"
#include "tfd/util/strings.h"

namespace tfd {
namespace agg {

namespace {

// Strict double parse for a label value ("" / garbage -> fallback).
double ParseLabelDouble(const lm::Labels& labels, const char* key,
                        double fallback) {
  auto it = labels.find(key);
  if (it == labels.end() || it->second.empty()) return fallback;
  char* end = nullptr;
  double v = std::strtod(it->second.c_str(), &end);
  if (end == nullptr || *end != '\0') return fallback;
  return v;
}

int ParseLabelInt(const lm::Labels& labels, const char* key, int fallback) {
  auto it = labels.find(key);
  int out = 0;
  if (it == labels.end() || !ParseNonNegInt(it->second, &out)) {
    return fallback;
  }
  return out;
}

bool LabelTrue(const lm::Labels& labels, const char* key) {
  auto it = labels.find(key);
  return it != labels.end() && it->second == "true";
}

std::string LabelOr(const lm::Labels& labels, const char* key,
                    const char* fallback) {
  auto it = labels.find(key);
  return it == labels.end() ? fallback : it->second;
}

// Capacity bucket for a contribution's perf class: the three published
// classes keep their names; anything else (including "") pools as
// unclassed so the capacity sums always partition total-chips.
std::string CapacityBucket(const std::string& perf_class) {
  if (perf_class == "gold" || perf_class == "silver" ||
      perf_class == "degraded") {
    return perf_class;
  }
  return "unclassed";
}

}  // namespace

// ---- sketch ---------------------------------------------------------------

int SketchBucketIndex(double value) {
  if (!(value > kSketchMin)) return 0;  // NaN and <= min both land in 0
  int idx = 0;
  double edge = kSketchMin;
  // Repeated multiplication, not log(): IEEE doubles make this loop
  // bit-identical in the Python twin, which a libm log() would not be.
  while (idx < kSketchBuckets - 1 && value > edge) {
    edge *= kSketchGamma;
    idx++;
  }
  return idx;
}

double SketchBucketValue(int bucket) {
  if (bucket <= 0) return kSketchMin;
  if (bucket >= kSketchBuckets) bucket = kSketchBuckets - 1;
  double edge = kSketchMin;
  for (int i = 0; i < bucket; i++) edge *= kSketchGamma;
  return edge;
}

void QuantileSketch::Add(double value) {
  counts_[SketchBucketIndex(value)]++;
  total_++;
}

void QuantileSketch::Remove(double value) {
  int idx = SketchBucketIndex(value);
  if (counts_[idx] > 0) {
    counts_[idx]--;
    total_--;
  }
}

void QuantileSketch::Merge(const QuantileSketch& other) {
  for (int i = 0; i < kSketchBuckets; i++) counts_[i] += other.counts_[i];
  total_ += other.total_;
}

void QuantileSketch::Unmerge(const QuantileSketch& other) {
  for (int i = 0; i < kSketchBuckets; i++) {
    int64_t take =
        other.counts_[i] < counts_[i] ? other.counts_[i] : counts_[i];
    counts_[i] -= take;
    total_ -= take;
  }
}

void QuantileSketch::AddBucketCount(int bucket, int64_t n) {
  if (bucket < 0 || bucket >= kSketchBuckets || n <= 0) return;
  counts_[bucket] += n;
  total_ += n;
}

double QuantileSketch::Quantile(double q) const {
  if (total_ <= 0) return -1;
  if (q < 0) q = 0;
  if (q > 1) q = 1;
  // Nearest-rank on the bucketed distribution: the target rank is
  // floor(q * (n-1)), the answer is the bucket holding that rank.
  int64_t target = static_cast<int64_t>(q * (total_ - 1));
  int64_t cumulative = 0;
  for (int i = 0; i < kSketchBuckets; i++) {
    cumulative += counts_[i];
    if (cumulative > target) return SketchBucketValue(i);
  }
  return SketchBucketValue(kSketchBuckets - 1);
}

double QuantileSketch::FractionAbove(double threshold) const {
  if (total_ <= 0) return 0;
  int64_t over = 0;
  for (int i = 0; i < kSketchBuckets; i++) {
    if (counts_[i] > 0 && SketchBucketValue(i) > threshold) {
      over += counts_[i];
    }
  }
  return static_cast<double>(over) / static_cast<double>(total_);
}

void QuantileSketch::Clear() {
  counts_.fill(0);
  total_ = 0;
}

// ---- stage sketches -------------------------------------------------------

std::map<std::string, double> DefaultSloBudgetsMs() {
  return {{"plan", 1200},
          {"render", 100},
          {"publish", 1200},
          {"publish-acked", 1300}};
}

std::map<std::string, double> SloBudgetsMsFromSpec(const std::string& spec) {
  std::map<std::string, double> budgets = DefaultSloBudgetsMs();
  for (const std::string& entry : SplitString(spec, ',')) {
    size_t eq = entry.find('=');
    if (eq == std::string::npos) continue;
    std::string stage = entry.substr(0, eq);
    if (budgets.find(stage) == budgets.end()) continue;
    int ms = 0;
    if (!ParseNonNegInt(entry.substr(eq + 1), &ms) || ms <= 0) continue;
    budgets[stage] = static_cast<double>(ms);
  }
  return budgets;
}

std::string SerializeStageSketches(const StageSketches& stages) {
  std::string out;
  for (const char* name : kSloStages) {
    auto it = stages.find(name);
    if (it == stages.end() || it->second.count() <= 0) continue;
    if (!out.empty()) out += ';';
    out += name;
    out += '=';
    bool first = true;
    const auto& counts = it->second.bucket_counts();
    for (int i = 0; i < kSketchBuckets; i++) {
      if (counts[i] <= 0) continue;
      if (!first) out += ',';
      first = false;
      out += std::to_string(i);
      out += ':';
      out += std::to_string(counts[i]);
    }
  }
  return out;
}

StageSketches ParseStageSketches(const std::string& text) {
  StageSketches out;
  for (const std::string& entry : SplitString(text, ';')) {
    size_t eq = entry.find('=');
    if (eq == std::string::npos) continue;
    std::string stage = entry.substr(0, eq);
    bool known = false;
    for (const char* name : kSloStages) known |= stage == name;
    if (!known) continue;  // a newer (or hostile) node's vocabulary
    QuantileSketch sketch;
    for (const std::string& pair : SplitString(entry.substr(eq + 1), ',')) {
      size_t colon = pair.find(':');
      if (colon == std::string::npos) continue;
      int bucket = 0;
      int n = 0;
      if (!ParseNonNegInt(pair.substr(0, colon), &bucket) ||
          !ParseNonNegInt(pair.substr(colon + 1), &n)) {
        continue;
      }
      sketch.AddBucketCount(bucket, n);
    }
    if (sketch.count() > 0) out[stage].Merge(sketch);
  }
  return out;
}

// ---- burn evaluator -------------------------------------------------------

BurnEvaluator::BurnEvaluator(std::map<std::string, double> budgets_ms,
                             double fast_window_s, double slow_window_s)
    : budgets_(std::move(budgets_ms)),
      fast_window_s_(fast_window_s),
      slow_window_s_(slow_window_s) {}

std::vector<BurnEvaluator::Edge> BurnEvaluator::Note(
    double now, const StageSketches& sketches) {
  std::vector<Edge> edges;
  for (const auto& [stage, budget] : budgets_) {
    auto sk = sketches.find(stage);
    bool have = sk != sketches.end() && sk->second.count() > 0;
    if (!have && stages_.find(stage) == stages_.end()) continue;
    double fraction = have ? sk->second.FractionAbove(budget) : 0.0;
    StageState& state = stages_[stage];
    state.samples.emplace_back(now, fraction);
    while (!state.samples.empty() &&
           state.samples.front().first <= now - slow_window_s_) {
      state.samples.pop_front();
    }
    double fast_sum = 0;
    int64_t fast_n = 0;
    double slow_sum = 0;
    int64_t slow_n = 0;
    for (const auto& [ts, f] : state.samples) {
      slow_sum += f;
      slow_n++;
      if (ts > now - fast_window_s_) {
        fast_sum += f;
        fast_n++;
      }
    }
    double fast = fast_n > 0 ? fast_sum / static_cast<double>(fast_n) : 0;
    double slow = slow_n > 0 ? slow_sum / static_cast<double>(slow_n) : 0;
    if (!state.burning && fast >= kFastThreshold && slow >= kSlowThreshold) {
      state.burning = true;
      edges.push_back({stage, true});
    } else if (state.burning && fast < kFastThreshold) {
      state.burning = false;
      edges.push_back({stage, false});
    }
  }
  return edges;
}

bool BurnEvaluator::burning(const std::string& stage) const {
  auto it = stages_.find(stage);
  return it != stages_.end() && it->second.burning;
}

std::vector<std::string> BurnEvaluator::BurningStages() const {
  std::vector<std::string> out;
  for (const auto& [stage, state] : stages_) {
    if (state.burning) out.push_back(stage);
  }
  return out;
}

// ---- contribution ---------------------------------------------------------

bool NodeContribution::operator==(const NodeContribution& other) const {
  return slice_id == other.slice_id &&
         slice_degraded == other.slice_degraded &&
         multislice_group == other.multislice_group &&
         perf_class == other.perf_class && chips == other.chips &&
         matmul_tflops == other.matmul_tflops &&
         hbm_gbps == other.hbm_gbps && preempting == other.preempting &&
         stage_slo == other.stage_slo;
}

NodeContribution ExtractContribution(const lm::Labels& labels,
                                     const std::string& stage_slo) {
  NodeContribution c;
  c.stage_slo = stage_slo;
  c.slice_id = LabelOr(labels, lm::kSliceId, "");
  c.slice_degraded = LabelTrue(labels, lm::kSliceDegraded);
  c.multislice_group = LabelOr(labels, lm::kMultisliceSliceId, "");
  c.perf_class = LabelOr(labels, lm::kPerfClass, "");
  c.chips = ParseLabelInt(labels, "google.com/tpu.count", 0);
  c.matmul_tflops = ParseLabelDouble(labels, lm::kPerfMatmulTflops, -1);
  c.hbm_gbps = ParseLabelDouble(labels, lm::kPerfHbmGbps, -1);
  c.preempting = LabelTrue(labels, lm::kLifecyclePreemptImminent) ||
                 LabelTrue(labels, lm::kLifecycleDraining);
  return c;
}

// ---- inventory store ------------------------------------------------------

void InventoryStore::Retire(const NodeContribution& c) {
  if (!c.slice_id.empty()) {
    auto it = slices_.find(c.slice_id);
    if (it != slices_.end()) {
      it->second.members--;
      if (c.slice_degraded) it->second.degraded_votes--;
      if (c.preempting) it->second.preempting--;
      if (it->second.members <= 0) slices_.erase(it);
    }
  }
  std::string bucket = CapacityBucket(c.perf_class);
  auto cap = capacity_.find(bucket);
  if (cap != capacity_.end()) {
    cap->second -= c.chips;
    if (cap->second <= 0) capacity_.erase(cap);
  }
  if (!c.multislice_group.empty()) {
    auto ms = multislice_.find(c.multislice_group);
    if (ms != multislice_.end()) {
      ms->second--;
      if (ms->second <= 0) multislice_.erase(ms);
    }
  }
  if (c.preempting) preempting_nodes_--;
  if (c.matmul_tflops >= 0) matmul_.Remove(c.matmul_tflops);
  if (c.hbm_gbps >= 0) hbm_.Remove(c.hbm_gbps);
  if (!c.stage_slo.empty()) {
    for (const auto& [stage, sketch] : ParseStageSketches(c.stage_slo)) {
      auto it = stage_.find(stage);
      if (it == stage_.end()) continue;
      it->second.Unmerge(sketch);
      if (it->second.count() <= 0) stage_.erase(it);
    }
  }
}

void InventoryStore::Admit(const NodeContribution& c) {
  if (!c.slice_id.empty()) {
    SliceAgg& agg = slices_[c.slice_id];
    agg.members++;
    if (c.slice_degraded) agg.degraded_votes++;
    if (c.preempting) agg.preempting++;
  }
  capacity_[CapacityBucket(c.perf_class)] += c.chips;
  if (!c.multislice_group.empty()) multislice_[c.multislice_group]++;
  if (c.preempting) preempting_nodes_++;
  if (c.matmul_tflops >= 0) matmul_.Add(c.matmul_tflops);
  if (c.hbm_gbps >= 0) hbm_.Add(c.hbm_gbps);
  if (!c.stage_slo.empty()) {
    for (const auto& [stage, sketch] : ParseStageSketches(c.stage_slo)) {
      stage_[stage].Merge(sketch);
    }
  }
}

bool InventoryStore::Apply(const std::string& node, const lm::Labels& labels,
                           const std::string& stage_slo) {
  events_++;
  NodeContribution next = ExtractContribution(labels, stage_slo);
  auto it = nodes_.find(node);
  if (it != nodes_.end()) {
    if (it->second == next) return false;  // e.g. a probe-ms-only delta
    Retire(it->second);
    it->second = next;
  } else {
    nodes_[node] = next;
  }
  Admit(next);
  return true;
}

std::vector<std::string> InventoryStore::NodeNames() const {
  std::vector<std::string> out;
  out.reserve(nodes_.size());
  for (const auto& [node, c] : nodes_) {
    (void)c;
    out.push_back(node);
  }
  return out;
}

bool InventoryStore::Remove(const std::string& node) {
  events_++;
  auto it = nodes_.find(node);
  if (it == nodes_.end()) return false;
  Retire(it->second);
  nodes_.erase(it);
  return true;
}

lm::Labels InventoryStore::BuildOutputLabels() const {
  lm::Labels out;
  int healthy = 0;
  int degraded = 0;
  for (const auto& [id, agg] : slices_) {
    (void)id;
    if (agg.degraded_votes > 0 || agg.preempting > 0) {
      degraded++;
    } else {
      healthy++;
    }
  }
  out[lm::kInventorySlices] = std::to_string(slices_.size());
  out[lm::kInventoryHealthySlices] = std::to_string(healthy);
  out[lm::kInventoryDegradedSlices] = std::to_string(degraded);
  int64_t total_chips = 0;
  for (const char* bucket : {"gold", "silver", "degraded", "unclassed"}) {
    auto it = capacity_.find(bucket);
    int64_t chips = it == capacity_.end() ? 0 : it->second;
    total_chips += chips;
    out[std::string(lm::kCapacityPrefix) + bucket] = std::to_string(chips);
  }
  out[std::string(lm::kCapacityPrefix) + "total-chips"] =
      std::to_string(total_chips);
  out[lm::kFleetNodes] = std::to_string(nodes_.size());
  out[lm::kFleetPreempting] = std::to_string(preempting_nodes_);
  out[lm::kMultisliceGroups] = std::to_string(multislice_.size());
  if (matmul_.count() > 0) {
    out[lm::kFleetMatmulP10] = Fixed3(matmul_.Quantile(0.10));
    out[lm::kFleetMatmulP50] = Fixed3(matmul_.Quantile(0.50));
  }
  if (hbm_.count() > 0) {
    out[lm::kFleetHbmP10] = Fixed3(hbm_.Quantile(0.10));
    out[lm::kFleetHbmP50] = Fixed3(hbm_.Quantile(0.50));
  }
  for (const char* stage : kSloStages) {
    auto it = stage_.find(stage);
    if (it == stage_.end() || it->second.count() <= 0) continue;
    std::string base = std::string(lm::kObsStagePrefix) + stage;
    out[base + ".p50-ms"] = Fixed3(it->second.Quantile(0.50));
    out[base + ".p99-ms"] = Fixed3(it->second.Quantile(0.99));
  }
  return out;
}

void InventoryStore::RecomputeAll() {
  full_recomputes_++;
  slices_.clear();
  capacity_.clear();
  multislice_.clear();
  preempting_nodes_ = 0;
  matmul_.Clear();
  hbm_.Clear();
  stage_.clear();
  for (const auto& [node, c] : nodes_) {
    (void)node;
    Admit(c);
  }
}

void InventoryStore::Clear() {
  nodes_.clear();
  slices_.clear();
  capacity_.clear();
  multislice_.clear();
  preempting_nodes_ = 0;
  matmul_.Clear();
  hbm_.Clear();
  stage_.clear();
}

// ---- flush controller -----------------------------------------------------

double FlushController::DueAt() const {
  if (dirty_since_ < 0) return 1e300;
  return dirty_since_ + debounce_s_;
}

}  // namespace agg
}  // namespace tfd

#ifndef TFD_REMEDY_REMEDY_H_
#define TFD_REMEDY_REMEDY_H_

// Closed-loop remediation (--mode=remedy): a lease-elected cluster
// singleton that consumes the same label streams the aggregator and
// placement view consume (NodeFeature CRs + the inventory CR), derives
// remediation verdicts from sliding-window evidence, and executes a
// CLOSED action vocabulary:
//
//   cordon            node `spec.unschedulable` merge patch — crash-loop
//                     flap history (>= flap_threshold eligibility
//                     down-flips inside window_s) or gray degradation
//                     (a tpu.perf.chip<N>.class=degraded label while
//                     the node still *looks* placeable)
//   uncordon          automatic rollback once the triggering evidence
//                     is retracted and stays retracted for heal_dwell_s
//   drain-recommend   preempt-imminent lifecycle — label + journal
//                     only, never an eviction
//   rebuild-recommend predicted eligible capacity dropped below queued
//                     demand — journal only
//
// Safety interlocks (evaluated in this order, first hit wins):
//   node-rate-limit    per-node cooldown + exponential backoff with
//                      deterministic fnv1a64 jitter after failed writes
//   slo-burn           a burning tpu.slo.*.burn stage on the inventory
//                      CR defers NEW cordons (the fleet is already
//                      hurting; don't remove capacity mid-burn)
//   disruption-budget  fleet-wide max concurrent cordons
//   domain-cap         per-failure-domain concurrent-cordon cap
//                      (tpu.topology.domain names the rack/power group)
//
// The RemedyEngine is the PURE half: side-effect-free and clock-free —
// the runner feeds observations and a `now`, and executes the returned
// actions (or journals them untouched under --remedy-dry-run, the
// default). Dry-run vs enforce is therefore a *runner* property; the
// engine's state machine is identical in both, which is what makes the
// dry-run journal a faithful preview.
//
// tpufd/remedy.py is the parity-pinned Python twin: the scripted
// scenario in src/tfd/tests/unit_tests.cc TestRemedyParityGolden and
// tests/test_remedy.py compares RenderJson() against ONE shared
// literal. Every semantic change lands in both or the pin fails.

#include <signal.h>

#include <cstdint>
#include <map>
#include <optional>
#include <set>
#include <string>
#include <utility>
#include <vector>

#include "tfd/config/config.h"
#include "tfd/lm/labeler.h"

namespace tfd {
namespace remedy {

// Failure-domain membership (rack/power group). Published by the
// operator/provisioner, consumed by the domain-cap interlock.
inline constexpr char kDomainLabel[] = "google.com/tpu.topology.domain";
// The drain recommendation is a label, not an eviction: schedulers and
// operators act on it; the controller never deletes a pod.
inline constexpr char kDrainLabel[] =
    "google.com/tpu.remedy.drain-recommended";
// Per-chip gray degradation: google.com/tpu.perf.chip<N>.class.
inline constexpr char kChipClassPrefix[] = "google.com/tpu.perf.chip";
inline constexpr char kChipClassSuffix[] = ".class";
// Optional queued-demand bridge label on the inventory CR (chips the
// decision audit stream reports queued); absent keeps the
// rebuild-recommend path idle — the harness twin feeds ObserveDemand
// directly.
inline constexpr char kQueueDemandLabel[] =
    "google.com/tpu.queue.demand-chips";

// Closed vocabularies — gates and metrics iterate these, so a new
// action/interlock must be added HERE (and to the Python twin) or it
// fails loudly.
inline constexpr const char* kActionKinds[] = {
    "cordon", "uncordon", "drain-recommend", "rebuild-recommend"};
inline constexpr const char* kInterlocks[] = {
    "node-rate-limit", "slo-burn", "disruption-budget", "domain-cap"};
// Evidence classes that justify a cordon, in deterministic priority
// order (crash-loop wins when both are active).
inline constexpr const char* kCordonEvidence[] = {"crash-loop", "gray"};

// The scheduler's-eye view of a node (tpufd/cluster.py basic_eligible):
// crash-loop flips are DOWN-flips of this predicate. nullptr = deleted.
bool Eligible(const lm::Labels* labels);

// A chip-level degraded verdict on a node whose headline class is NOT
// degraded: the node still looks placeable, so nothing else in the
// stack will fence it — exactly the case remediation exists for.
bool GrayDegraded(const lm::Labels& labels);

// Deterministic jitter in [0, 1): both twins hash the same key
// ("<node>:<fail_count>" through k8s::desync::Fnv1a64), so a seeded
// soak reproduces byte-identically across languages.
double BackoffJitterUnit(const std::string& node, int fail_count);

// Knobs, each wired through flags/env/helm/static (--remedy-*;
// TFD_REMEDY_*; remedy.* helm values).
struct RemedyConfig {
  double window_s = 60.0;
  int flap_threshold = 3;
  double heal_dwell_s = 10.0;
  double cooldown_s = 5.0;
  double backoff_base_s = 1.0;
  double backoff_max_s = 30.0;
  int max_concurrent_cordons = 3;
  int domain_cap = 1;
  double rebuild_cooldown_s = 30.0;
};

struct Action {
  std::string kind;
  std::string node;      // "" for rebuild-recommend (fleet-scoped)
  std::string evidence;  // crash-loop | gray | preempt | capacity
  double detected_at = 0;
  std::string reason;
};

// (node, interlock) pairs that TRANSITIONED into blocked this tick.
using BlockedEdge = std::pair<std::string, std::string>;

class RemedyEngine {
 public:
  explicit RemedyEngine(RemedyConfig config = {});

  // One NodeFeature CR state (nullptr = deleted). Returns true when
  // any evidence class TRANSITIONED to active (the detect edge).
  bool ObserveNode(const std::string& node, const lm::Labels* labels,
                   double now);
  // The aggregator's inventory CR: a burning tpu.slo.<stage>.burn
  // stage arms the slo-burn interlock.
  void ObserveInventory(const lm::Labels& labels, double now);
  // Queued demand (chips) from the decision audit stream — the
  // rebuild trigger's right-hand side.
  void ObserveDemand(int64_t chips, double now);

  // One decision pass: (actions, newly-blocked edges). Deterministic:
  // nodes visited in sorted order, interlocks evaluated in the
  // documented order; steady blockage is not re-counted.
  std::pair<std::vector<Action>, std::vector<BlockedEdge>> Tick(double now);

  // The runner executed (or dry-ran) an action. Failed writes arm
  // exponential backoff with deterministic jitter; the action stays
  // un-applied and a later tick re-emits it once the backoff expires.
  void NoteActionResult(const std::string& node, const std::string& kind,
                        bool ok, double now);

  // Epoch-fenced step-down mid-batch: the lease is gone, so every
  // in-flight intent is dropped without state change — the next leader
  // re-derives it from the same evidence. Returns intents dropped.
  int AbandonPending();

  std::vector<std::string> CordonedNodes() const;
  // Chips on nodes the fleet can actually count on: eligible, not
  // cordoned (or being cordoned), no active cordon evidence.
  int64_t PredictedCapacityChips(double now) const;
  std::vector<std::string> NodeNames() const;
  size_t nodes() const { return nodes_.size(); }
  bool slo_burning() const { return slo_burning_; }
  int64_t ActionCount(const std::string& kind) const;
  int64_t BlockedCount(const std::string& interlock) const;
  int64_t rollbacks() const { return rollbacks_; }
  int64_t write_failures() const { return write_failures_; }
  const RemedyConfig& config() const { return config_; }

  // Deterministic compact JSON of the engine state — the parity golden
  // surface (byte-identical to tpufd/remedy.py render_json()).
  std::string RenderJson() const;

 private:
  struct Node {
    lm::Labels labels;
    std::optional<bool> eligible;  // unknown until first observation
    std::vector<double> flips;     // eligibility down-flip times
    std::map<std::string, double> evidence;  // class -> active_since
    std::optional<double> clear_since;
    bool cordoned = false;
    std::string cordon_class;
    std::optional<double> cordon_at;
    std::string pending;  // action kind in flight ("" = none)
    std::optional<double> last_action_at;
    int fail_count = 0;
    std::optional<double> backoff_until;
    bool drain_recommended = false;
    std::string domain;
  };

  bool RefreshEvidence(Node* n, double now);
  const char* CordonEvidenceClass(const Node& n) const;
  bool RateLimited(const Node& n, double now) const;

  RemedyConfig config_;
  std::map<std::string, Node> nodes_;
  bool slo_burning_ = false;
  int64_t queued_demand_chips_ = 0;
  std::optional<double> last_rebuild_at_;
  std::map<std::string, int64_t> action_counts_;
  std::map<std::string, int64_t> blocked_counts_;
  int64_t rollbacks_ = 0;
  int64_t write_failures_ = 0;
  std::set<BlockedEdge> blocked_live_;
};

enum class RemedyOutcome {
  kExit,     // SIGTERM/SIGINT: clean shutdown
  kRestart,  // SIGHUP: reload config and re-enter
  kError,    // unrecoverable startup failure
};

// Runs the remediation controller until a signal. Lease doc
// "tfd-remedy" (agg/lease.h discipline, --agg-lease-duration), its own
// unfiltered collection watch (the inventory CR it consumes is exactly
// the unlabeled output the aggregator's selector excludes), a ~1s
// decision tick while leading+synced, epoch-fenced action execution,
// and --remedy-dry-run (default ON) journaling instead of mutating.
RemedyOutcome RunRemedy(const config::Config& config,
                        const sigset_t& sigmask);

}  // namespace remedy
}  // namespace tfd

#endif  // TFD_REMEDY_REMEDY_H_

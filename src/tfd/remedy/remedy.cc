#include "tfd/remedy/remedy.h"

#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <chrono>
#include <cmath>
#include <condition_variable>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <set>
#include <sstream>
#include <thread>

#include "tfd/agg/lease.h"
#include "tfd/info/version.h"
#include "tfd/k8s/client.h"
#include "tfd/k8s/desync.h"
#include "tfd/k8s/watch.h"
#include "tfd/lm/schema.h"
#include "tfd/obs/journal.h"
#include "tfd/obs/metrics.h"
#include "tfd/obs/server.h"
#include "tfd/obs/trace.h"
#include "tfd/util/http.h"
#include "tfd/util/jsonlite.h"
#include "tfd/util/logging.h"
#include "tfd/util/strings.h"
#include "tfd/util/time.h"

namespace tfd {
namespace remedy {

namespace {

constexpr char kLeaseDocName[] = "tfd-remedy";
constexpr char kCrNamePrefix[] = "tfd-features-for-";
constexpr char kFieldManager[] = "tfd-remedy";

bool StartsWith(const std::string& s, const char* prefix) {
  return s.rfind(prefix, 0) == 0;
}

bool EndsWith(const std::string& s, const char* suffix) {
  size_t n = std::strlen(suffix);
  return s.size() >= n && s.compare(s.size() - n, n, suffix) == 0;
}

// "%g" of the value rounded to 3 decimals — the reason strings' number
// format (mirrors the Python twin's `round(x, 3)` + `%g`).
std::string Num(double v) {
  char buf[64];
  std::snprintf(buf, sizeof(buf), "%g", std::round(v * 1000.0) / 1000.0);
  return buf;
}

std::string GetLabel(const lm::Labels& labels, const char* key) {
  auto it = labels.find(key);
  return it == labels.end() ? std::string() : it->second;
}

}  // namespace

// ---- the pure engine ------------------------------------------------------

bool Eligible(const lm::Labels* labels) {
  if (labels == nullptr) return false;
  if (GetLabel(*labels, lm::kPerfClass) == "degraded") return false;
  if (GetLabel(*labels, lm::kSliceDegraded) == "true") return false;
  if (GetLabel(*labels, lm::kSliceClass) == "degraded") return false;
  if (GetLabel(*labels, lm::kLifecyclePreemptImminent) == "true") {
    return false;
  }
  if (GetLabel(*labels, lm::kLifecycleDraining) == "true") return false;
  return true;
}

bool GrayDegraded(const lm::Labels& labels) {
  if (GetLabel(labels, lm::kPerfClass) == "degraded") return false;
  for (const auto& [key, value] : labels) {
    if (StartsWith(key, kChipClassPrefix) &&
        EndsWith(key, kChipClassSuffix) && value == "degraded") {
      return true;
    }
  }
  return false;
}

double BackoffJitterUnit(const std::string& node, int fail_count) {
  return static_cast<double>(
             k8s::desync::Fnv1a64(node + ":" + std::to_string(fail_count)) %
             1000) /
         1000.0;
}

RemedyEngine::RemedyEngine(RemedyConfig config) : config_(config) {
  for (const char* kind : kActionKinds) action_counts_[kind] = 0;
  for (const char* interlock : kInterlocks) blocked_counts_[interlock] = 0;
}

bool RemedyEngine::ObserveNode(const std::string& node,
                               const lm::Labels* labels, double now) {
  if (labels == nullptr) {
    nodes_.erase(node);
    return false;
  }
  Node& n = nodes_[node];
  n.labels = *labels;
  if (auto it = labels->find(kDomainLabel); it != labels->end()) {
    n.domain = it->second;
  }
  bool el = Eligible(labels);
  if (n.eligible.has_value() && *n.eligible && !el) n.flips.push_back(now);
  n.eligible = el;
  return RefreshEvidence(&n, now);
}

void RemedyEngine::ObserveInventory(const lm::Labels& labels, double now) {
  (void)now;
  slo_burning_ = false;
  for (const auto& [key, value] : labels) {
    if (StartsWith(key, lm::kSloBurnPrefix) && EndsWith(key, ".burn") &&
        value == "true") {
      slo_burning_ = true;
      break;
    }
  }
}

void RemedyEngine::ObserveDemand(int64_t chips, double now) {
  (void)now;
  queued_demand_chips_ = chips;
}

bool RemedyEngine::RefreshEvidence(Node* n, double now) {
  const double floor = now - config_.window_s;
  std::vector<double> kept;
  kept.reserve(n->flips.size());
  for (double t : n->flips) {
    if (t > floor) kept.push_back(t);
  }
  n->flips = std::move(kept);
  std::map<std::string, double> active;
  if (static_cast<int>(n->flips.size()) >= config_.flap_threshold) {
    active["crash-loop"] = n->flips[config_.flap_threshold - 1];
  }
  if (GrayDegraded(n->labels)) active["gray"] = now;
  if (GetLabel(n->labels, lm::kLifecyclePreemptImminent) == "true") {
    active["preempt"] = now;
  }
  bool detected = false;
  for (const auto& [cls, since] : active) {
    if (n->evidence.find(cls) == n->evidence.end()) {
      // Evidence stamps first-wins: crash-loop carries the flip that
      // crossed the threshold, the point-in-time classes carry now.
      n->evidence[cls] = since;
      detected = true;
    }
  }
  for (auto it = n->evidence.begin(); it != n->evidence.end();) {
    if (active.find(it->first) == active.end()) {
      it = n->evidence.erase(it);
    } else {
      ++it;
    }
  }
  bool cordon_active = false;
  for (const char* cls : kCordonEvidence) {
    if (n->evidence.count(cls)) cordon_active = true;
  }
  if (cordon_active) {
    n->clear_since.reset();
  } else if (!n->clear_since.has_value()) {
    n->clear_since = now;
  }
  if (n->evidence.count("preempt") == 0) n->drain_recommended = false;
  return detected;
}

const char* RemedyEngine::CordonEvidenceClass(const Node& n) const {
  for (const char* cls : kCordonEvidence) {
    if (n.evidence.count(cls)) return cls;
  }
  return nullptr;
}

bool RemedyEngine::RateLimited(const Node& n, double now) const {
  if (n.backoff_until.has_value() && now < *n.backoff_until) return true;
  if (n.last_action_at.has_value() &&
      now - *n.last_action_at < config_.cooldown_s) {
    return true;
  }
  return false;
}

int64_t RemedyEngine::PredictedCapacityChips(double now) const {
  (void)now;
  int64_t total = 0;
  for (const auto& [name, n] : nodes_) {
    (void)name;
    if (!n.eligible.has_value() || !*n.eligible || n.cordoned ||
        n.pending == "cordon") {
      continue;
    }
    if (CordonEvidenceClass(n) != nullptr) continue;
    std::string count = GetLabel(n.labels, "google.com/tpu.count");
    if (count.empty()) continue;
    char* end = nullptr;
    long long parsed = std::strtoll(count.c_str(), &end, 10);
    if (end != nullptr && *end == '\0' && end != count.c_str()) {
      total += parsed;
    }
  }
  return total;
}

std::pair<std::vector<Action>, std::vector<BlockedEdge>> RemedyEngine::Tick(
    double now) {
  const RemedyConfig& cfg = config_;
  std::vector<Action> actions;
  std::set<BlockedEdge> blocked_now;
  // Re-age crash-loop windows even without fresh observations.
  for (auto& [name, n] : nodes_) {
    (void)name;
    RefreshEvidence(&n, now);
  }
  int active_cordons = 0;
  std::map<std::string, int> domain_cordons;
  for (const auto& [name, n] : nodes_) {
    (void)name;
    if (n.cordoned || n.pending == "cordon") {
      active_cordons++;
      if (!n.domain.empty()) domain_cordons[n.domain]++;
    }
  }
  for (auto& [node, n] : nodes_) {
    if (!n.pending.empty()) continue;
    const char* ev = CordonEvidenceClass(n);
    if (n.cordoned) {
      if (ev == nullptr && n.clear_since.has_value() &&
          now - *n.clear_since >= cfg.heal_dwell_s &&
          !RateLimited(n, now)) {
        n.pending = "uncordon";
        actions.push_back({"uncordon", node, n.cordon_class, *n.clear_since,
                           "evidence retracted for " +
                               Num(now - *n.clear_since) + "s"});
      }
    } else if (ev != nullptr) {
      if (RateLimited(n, now)) {
        blocked_now.insert({node, "node-rate-limit"});
      } else if (slo_burning_) {
        blocked_now.insert({node, "slo-burn"});
      } else if (active_cordons >= cfg.max_concurrent_cordons) {
        blocked_now.insert({node, "disruption-budget"});
      } else if (!n.domain.empty() &&
                 domain_cordons[n.domain] >= cfg.domain_cap) {
        blocked_now.insert({node, "domain-cap"});
      } else {
        n.pending = "cordon";
        n.cordon_class = ev;
        active_cordons++;
        if (!n.domain.empty()) domain_cordons[n.domain]++;
        actions.push_back({"cordon", node, ev, n.evidence[ev],
                           std::string("evidence ") + ev +
                               " active since " + Num(n.evidence[ev])});
      }
    }
    if (n.evidence.count("preempt") && !n.drain_recommended &&
        !RateLimited(n, now)) {
      n.drain_recommended = true;
      actions.push_back({"drain-recommend", node, "preempt",
                         n.evidence["preempt"],
                         "preempt-imminent lifecycle"});
      action_counts_["drain-recommend"]++;
    }
  }
  if (queued_demand_chips_ > 0) {
    int64_t capacity = PredictedCapacityChips(now);
    if (capacity < queued_demand_chips_ &&
        (!last_rebuild_at_.has_value() ||
         now - *last_rebuild_at_ >= cfg.rebuild_cooldown_s)) {
      last_rebuild_at_ = now;
      actions.push_back({"rebuild-recommend", "", "capacity", now,
                         "predicted capacity " + std::to_string(capacity) +
                             " chips < queued demand " +
                             std::to_string(queued_demand_chips_)});
      action_counts_["rebuild-recommend"]++;
    }
  }
  std::vector<BlockedEdge> newly_blocked;
  for (const BlockedEdge& edge : blocked_now) {
    if (blocked_live_.count(edge) == 0) newly_blocked.push_back(edge);
  }
  for (const BlockedEdge& edge : newly_blocked) {
    blocked_counts_[edge.second]++;
  }
  blocked_live_ = std::move(blocked_now);
  return {std::move(actions), std::move(newly_blocked)};
}

void RemedyEngine::NoteActionResult(const std::string& node,
                                    const std::string& kind, bool ok,
                                    double now) {
  auto it = nodes_.find(node);
  if (it == nodes_.end()) return;
  Node& n = it->second;
  n.pending.clear();
  n.last_action_at = now;
  if (ok) {
    n.fail_count = 0;
    n.backoff_until.reset();
    if (kind == "cordon") {
      n.cordoned = true;
      n.cordon_at = now;
      action_counts_["cordon"]++;
    } else if (kind == "uncordon") {
      n.cordoned = false;
      n.cordon_at.reset();
      action_counts_["uncordon"]++;
      rollbacks_++;
    }
  } else {
    n.fail_count++;
    write_failures_++;
    double backoff =
        std::min(config_.backoff_base_s *
                     std::pow(2.0, static_cast<double>(n.fail_count - 1)),
                 config_.backoff_max_s);
    double jitter = BackoffJitterUnit(node, n.fail_count);
    n.backoff_until = now + backoff * (1.0 + 0.5 * jitter);
  }
}

int RemedyEngine::AbandonPending() {
  int dropped = 0;
  for (auto& [name, n] : nodes_) {
    (void)name;
    if (!n.pending.empty()) {
      n.pending.clear();
      dropped++;
    }
  }
  return dropped;
}

std::vector<std::string> RemedyEngine::CordonedNodes() const {
  std::vector<std::string> out;
  for (const auto& [name, n] : nodes_) {
    if (n.cordoned) out.push_back(name);
  }
  return out;
}

std::vector<std::string> RemedyEngine::NodeNames() const {
  std::vector<std::string> out;
  out.reserve(nodes_.size());
  for (const auto& [name, n] : nodes_) {
    (void)n;
    out.push_back(name);
  }
  return out;
}

int64_t RemedyEngine::ActionCount(const std::string& kind) const {
  auto it = action_counts_.find(kind);
  return it == action_counts_.end() ? 0 : it->second;
}

int64_t RemedyEngine::BlockedCount(const std::string& interlock) const {
  auto it = blocked_counts_.find(interlock);
  return it == blocked_counts_.end() ? 0 : it->second;
}

std::string RemedyEngine::RenderJson() const {
  std::ostringstream out;
  out << "{\"actions\":{";
  bool first = true;
  for (const auto& [kind, count] : action_counts_) {
    if (!first) out << ",";
    first = false;
    out << "\"" << kind << "\":" << count;
  }
  out << "},\"blocked\":{";
  first = true;
  for (const auto& [interlock, count] : blocked_counts_) {
    if (!first) out << ",";
    first = false;
    out << "\"" << interlock << "\":" << count;
  }
  out << "},\"cordoned\":[";
  first = true;
  for (const std::string& node : CordonedNodes()) {
    if (!first) out << ",";
    first = false;
    out << "\"" << node << "\"";
  }
  out << "],\"nodes\":{";
  first = true;
  for (const auto& [name, n] : nodes_) {
    if (!first) out << ",";
    first = false;
    out << "\"" << name << "\":{\"cordoned\":"
        << (n.cordoned ? "true" : "false") << ",\"domain\":\"" << n.domain
        << "\",\"evidence\":[";
    bool first_ev = true;
    for (const auto& [cls, since] : n.evidence) {
      (void)since;
      if (!first_ev) out << ",";
      first_ev = false;
      out << "\"" << cls << "\"";
    }
    out << "],\"flips\":" << n.flips.size() << "}";
  }
  out << "},\"rollbacks\":" << rollbacks_
      << ",\"write_failures\":" << write_failures_ << "}";
  return out.str();
}

// ---- the runner -----------------------------------------------------------

namespace {

obs::Counter* EventCounter(const char* type) {
  return obs::Default().GetCounter(
      "tfd_remedy_events_total",
      "NodeFeature watch events consumed by the remediation controller, "
      "by type (list items count as 'listed').",
      {{"type", type}});
}

void SetStateGauge(int state) {
  obs::Default()
      .GetGauge("tfd_remedy_state",
                "Remediation controller role: 0 follower/standby, 1 "
                "leader (watching and acting).")
      ->Set(state);
}

void SetCordonsActiveGauge(size_t cordons) {
  obs::Default()
      .GetGauge("tfd_remedy_cordons_active",
                "Nodes the controller currently holds cordoned (dry-run "
                "counts intended cordons; the disruption budget caps "
                "this).")
      ->Set(static_cast<double>(cordons));
}

obs::Counter* ActionCounter(const std::string& kind) {
  return obs::Default().GetCounter(
      "tfd_remedy_actions_total",
      "Remediation actions executed (or journaled under dry-run), by "
      "action kind from the closed vocabulary.",
      {{"action", kind}});
}

obs::Counter* BlockedCounter(const std::string& interlock) {
  return obs::Default().GetCounter(
      "tfd_remedy_blocked_total",
      "Remediation intents newly blocked by a safety interlock, by "
      "interlock (transition edges, not steady blockage).",
      {{"interlock", interlock}});
}

obs::Counter* RollbacksCounter() {
  return obs::Default().GetCounter(
      "tfd_remedy_rollbacks_total",
      "Automatic rollbacks (un-cordons) after the triggering evidence "
      "was retracted for the full heal dwell.");
}

obs::Counter* WriteFailuresCounter() {
  return obs::Default().GetCounter(
      "tfd_remedy_write_failures_total",
      "Failed remediation writes; each arms per-node exponential "
      "backoff with deterministic jitter before the retry.");
}

// Shared state between the watch thread and the lease/decision loop.
struct Shared {
  std::mutex mu;
  std::condition_variable cv;
  RemedyEngine engine;
  bool synced = false;
  // node -> monotonic time the latest evidence class transitioned to
  // active (the detect edge); consumed by the first action on the node
  // for the detect->decide stage decomposition.
  std::map<std::string, double> detect_at;
  std::string output_name;  // the inventory CR to consume

  explicit Shared(RemedyConfig cfg) : engine(std::move(cfg)) {}
};

// One long-lived list-then-watch over the WHOLE NodeFeature collection
// — deliberately WITHOUT the aggregator's node-name labelSelector: the
// inventory CR this controller consumes is exactly the unlabeled
// output object that selector exists to exclude. Same stream
// discipline as agg/runner.cc's CollectionWatcher (bookmarks, clean
// rotation, Retry-After pacing, exponential backoff, 410 -> re-list).
class RemedyWatcher {
 public:
  RemedyWatcher(k8s::ClusterConfig config, Shared* shared)
      : config_(std::move(config)), shared_(shared) {}
  ~RemedyWatcher() { Stop(); }

  void Start() {
    if (started_) return;
    started_ = true;
    stop_.store(false);
    thread_ = std::thread([this] { RunLoop(); });
  }

  void Stop() {
    if (!started_) return;
    stop_.store(true);
    {
      std::lock_guard<std::mutex> lock(mu_);
      cv_.notify_all();
    }
    int fd = stream_fd_.load();
    if (fd >= 0) shutdown(fd, SHUT_RDWR);
    if (thread_.joinable()) thread_.join();
    started_ = false;
  }

 private:
  bool SleepFor(double seconds) {
    std::unique_lock<std::mutex> lock(mu_);
    cv_.wait_for(lock,
                 std::chrono::milliseconds(
                     static_cast<long long>(seconds * 1000)),
                 [this] { return stop_.load(); });
    return !stop_.load();
  }

  // Routes one object into the engine under the shared lock: node CRs
  // feed ObserveNode (detect edges noted for the stage decomposition),
  // the inventory CR feeds ObserveInventory (+ the optional queued-
  // demand bridge label); everything else — partial rollups, foreign
  // CRs — is ignored.
  void ApplyObject(const std::string& name, const lm::Labels& labels,
                   bool deleted) {
    double now = agg::MonoSeconds();
    std::lock_guard<std::mutex> lock(shared_->mu);
    if (StartsWith(name, kCrNamePrefix)) {
      std::string node = name.substr(sizeof(kCrNamePrefix) - 1);
      bool detect = shared_->engine.ObserveNode(
          node, deleted ? nullptr : &labels, now);
      if (detect) shared_->detect_at[node] = now;
      shared_->cv.notify_all();
    } else if (name == shared_->output_name) {
      shared_->engine.ObserveInventory(deleted ? lm::Labels{} : labels,
                                       now);
      if (!deleted) {
        if (auto it = labels.find(kQueueDemandLabel); it != labels.end()) {
          char* end = nullptr;
          long long chips = std::strtoll(it->second.c_str(), &end, 10);
          if (end != nullptr && *end == '\0' &&
              end != it->second.c_str()) {
            shared_->engine.ObserveDemand(chips, now);
          }
        }
      }
      shared_->cv.notify_all();
    }
  }

  Status ListOnce(std::string* rv) {
    http::RequestOptions options = agg::BaseOptions(config_);
    options.timeout_ms = 15000;
    options.deadline_ms = 30000;
    Result<http::Response> listed =
        http::Request("GET", agg::CollectionUrl(config_), "", options);
    if (!listed.ok()) return Status::Error("list failed: " + listed.error());
    if (listed->status == 429 || listed->status == 503) {
      return Status::Error("list throttled (HTTP " +
                           std::to_string(listed->status) + ")");
    }
    if (listed->status != 200) {
      return Status::Error("list HTTP " + std::to_string(listed->status));
    }
    Result<jsonlite::ValuePtr> parsed = jsonlite::Parse(listed->body);
    if (!parsed.ok()) {
      return Status::Error("list parse: " + parsed.error());
    }
    if (jsonlite::ValuePtr v =
            (*parsed)->GetPath("metadata.resourceVersion");
        v && v->kind == jsonlite::Value::Kind::kString) {
      *rv = v->string_value;
    }
    std::set<std::string> listed_nodes;
    jsonlite::ValuePtr items = (*parsed)->Get("items");
    if (items && items->kind == jsonlite::Value::Kind::kArray) {
      for (const jsonlite::ValuePtr& item : items->array_items) {
        if (!item || item->kind != jsonlite::Value::Kind::kObject) continue;
        std::string name;
        if (jsonlite::ValuePtr n = item->GetPath("metadata.name");
            n && n->kind == jsonlite::Value::Kind::kString) {
          name = n->string_value;
        }
        lm::Labels labels;
        if (jsonlite::ValuePtr l = item->GetPath("spec.labels");
            l && l->kind == jsonlite::Value::Kind::kObject) {
          for (const auto& [k, v] : l->object_items) {
            if (v && v->kind == jsonlite::Value::Kind::kString) {
              labels[k] = v->string_value;
            }
          }
        }
        if (StartsWith(name, kCrNamePrefix)) {
          listed_nodes.insert(name.substr(sizeof(kCrNamePrefix) - 1));
        }
        EventCounter("listed")->Inc();
        ApplyObject(name, labels, /*deleted=*/false);
      }
    }
    // Deletes missed while not watching retire through the same path.
    std::vector<std::string> known;
    {
      std::lock_guard<std::mutex> lock(shared_->mu);
      known = shared_->engine.NodeNames();
    }
    for (const std::string& node : known) {
      if (listed_nodes.count(node) == 0) {
        ApplyObject(kCrNamePrefix + node, {}, /*deleted=*/true);
      }
    }
    return Status::Ok();
  }

  void RunLoop() {
    const std::string node_key = agg::HolderIdentity();
    std::string rv;
    int consecutive_failures = 0;

    while (!stop_.load()) {
      if (rv.empty()) {
        Status listed = ListOnce(&rv);
        if (!listed.ok()) {
          consecutive_failures++;
          double pause = std::min(
              30.0, 1.0 * (1 << std::min(consecutive_failures - 1, 10)));
          TFD_LOG_WARNING << "remedy list: " << listed.message()
                          << "; retrying in ~" << pause << "s";
          if (!SleepFor(k8s::desync::SpreadRetryAfterS(pause, node_key))) {
            return;
          }
          continue;
        }
        consecutive_failures = 0;
        bool first_sync;
        size_t nodes;
        {
          std::lock_guard<std::mutex> lock(shared_->mu);
          first_sync = !shared_->synced;
          shared_->synced = true;
          nodes = shared_->engine.nodes();
          shared_->cv.notify_all();
        }
        obs::DefaultJournal().Record(
            first_sync ? "remedy-synced" : "remedy-resync", "remedy",
            (first_sync ? std::string("initial sync: ")
                        : std::string("re-list after 410: ")) +
                std::to_string(nodes) + " nodes at rv " + rv,
            {{"nodes", std::to_string(nodes)},
             {"resource_version", rv}});
      }

      std::string url = agg::CollectionUrl(config_) +
                        "?watch=true&allowWatchBookmarks=true"
                        "&timeoutSeconds=240";
      if (!rv.empty()) url += "&resourceVersion=" + rv;
      http::RequestOptions stream_options = agg::BaseOptions(config_);
      stream_options.timeout_ms = 300000;
      stream_options.connect_timeout_ms = 5000;

      bool established = false;
      bool resync_gone = false;
      double server_retry_after = 0;
      int stream_status = 0;
      std::string line_buffer;
      http::StreamHandler handler;
      handler.on_connected = [this](int fd) { stream_fd_.store(fd); };
      handler.on_response = [&](const http::Response& head) {
        stream_status = head.status;
        server_retry_after = head.RetryAfterSeconds();
        if (head.status == 200) {
          established = true;
          consecutive_failures = 0;
          return true;
        }
        return false;
      };
      handler.on_data = [&](const char* data, size_t len) {
        if (stop_.load()) return false;
        line_buffer.append(data, len);
        size_t start = 0;
        size_t eol;
        while ((eol = line_buffer.find('\n', start)) != std::string::npos) {
          std::string line = line_buffer.substr(start, eol - start);
          start = eol + 1;
          if (line.empty() || line == "\r") continue;
          k8s::WatchEvent event = k8s::ParseWatchEventLine(line);
          EventCounter(k8s::WatchEventTypeName(event.type))->Inc();
          switch (event.type) {
            case k8s::WatchEvent::Type::kBookmark:
              if (!event.resource_version.empty()) {
                rv = event.resource_version;
              }
              break;
            case k8s::WatchEvent::Type::kError:
              if (event.error_code == 410) {
                resync_gone = true;
                line_buffer.clear();
                return false;
              }
              break;
            case k8s::WatchEvent::Type::kAdded:
            case k8s::WatchEvent::Type::kModified:
            case k8s::WatchEvent::Type::kDeleted:
              if (!event.resource_version.empty()) {
                rv = event.resource_version;
              }
              ApplyObject(event.name, event.labels,
                          event.type == k8s::WatchEvent::Type::kDeleted);
              break;
            case k8s::WatchEvent::Type::kUnknown:
              break;
          }
        }
        line_buffer.erase(0, start);
        if (line_buffer.size() > 1024 * 1024) line_buffer.clear();
        return true;
      };

      Status streamed =
          http::RequestStream("GET", url, "", stream_options, handler);
      stream_fd_.store(-1);
      if (stop_.load()) return;

      if (resync_gone || stream_status == 410) {
        obs::DefaultJournal().Record(
            "remedy-resync", "remedy",
            "collection watch resourceVersion too old (410 Gone); "
            "re-listing once",
            {{"resource_version", rv}});
        rv.clear();
        continue;
      }
      if (streamed.ok() && established) continue;  // clean rotation
      if (stream_status == 429 || stream_status == 503 ||
          server_retry_after > 0) {
        double pause = server_retry_after > 0 ? server_retry_after : 1.0;
        if (!SleepFor(k8s::desync::SpreadRetryAfterS(pause, node_key))) {
          return;
        }
        continue;
      }
      consecutive_failures++;
      double pause = std::min(
          30.0, 1.0 * (1 << std::min(consecutive_failures - 1, 10)));
      TFD_LOG_WARNING << "remedy watch dropped ("
                      << (!streamed.ok()
                              ? streamed.message()
                              : "HTTP " + std::to_string(stream_status))
                      << "); reconnecting in ~" << pause << "s";
      if (!SleepFor(k8s::desync::SpreadRetryAfterS(pause, node_key))) {
        return;
      }
    }
  }

  k8s::ClusterConfig config_;
  Shared* shared_;
  std::thread thread_;
  std::atomic<bool> stop_{false};
  std::atomic<int> stream_fd_{-1};
  std::mutex mu_;
  std::condition_variable cv_;
  bool started_ = false;
};

// The drain recommendation: ONE server-side apply of the drain label
// onto the node's own NodeFeature CR under the "tfd-remedy" field
// manager — this controller owns exactly that key and nothing the
// daemon published itself. No merge-patch/PUT ladder: an apiserver
// without SSA simply fails the recommendation (it is advisory).
Status ApplyDrainLabel(const k8s::ClusterConfig& config,
                       const std::string& node) {
  std::string name = std::string(kCrNamePrefix) + node;
  std::string url = agg::CollectionUrl(config) + "/" + name +
                    "?fieldManager=" + kFieldManager + "&force=true";
  std::string body =
      std::string("{\"apiVersion\":\"nfd.k8s-sigs.io/v1alpha1\","
                  "\"kind\":\"NodeFeature\",\"metadata\":{\"name\":") +
      jsonlite::Quote(name) + "},\"spec\":{\"labels\":{" +
      jsonlite::Quote(kDrainLabel) + ":\"true\"}}}";
  http::RequestOptions options = agg::BaseOptions(config);
  options.headers["Content-Type"] = "application/apply-patch+yaml";
  options.deadline_ms = 15000;
  Result<http::Response> applied =
      http::Request("PATCH", url, body, options);
  if (!applied.ok()) {
    return Status::Error("drain label apply: " + applied.error());
  }
  if (applied->status == 200 || applied->status == 201) return Status::Ok();
  return Status::Error("drain label apply HTTP " +
                       std::to_string(applied->status));
}

}  // namespace

RemedyOutcome RunRemedy(const config::Config& config,
                        const sigset_t& sigmask) {
  const config::Flags& flags = config.flags;
  Result<k8s::ClusterConfig> cluster = k8s::LoadInClusterEndpoint();
  if (!cluster.ok()) {
    TFD_LOG_ERROR << "remedy: " << cluster.error();
    return RemedyOutcome::kError;
  }
  cluster->request_deadline_ms = flags.sink_request_deadline_s * 1000;
  const std::string self = agg::HolderIdentity();

  RemedyConfig engine_cfg;
  engine_cfg.window_s = flags.remedy_window_s;
  engine_cfg.flap_threshold = flags.remedy_flap_threshold;
  engine_cfg.heal_dwell_s = flags.remedy_heal_dwell_s;
  engine_cfg.cooldown_s = flags.remedy_node_cooldown_s;
  engine_cfg.max_concurrent_cordons = flags.remedy_max_concurrent_cordons;
  engine_cfg.domain_cap = flags.remedy_domain_cap;

  std::unique_ptr<obs::IntrospectionServer> server;
  if (!flags.introspection_addr.empty()) {
    obs::ServerOptions options;
    options.addr = flags.introspection_addr;
    options.journal = &obs::DefaultJournal();
    options.trace = &obs::DefaultTrace();
    options.stale_after_s = std::max(120, 3 * flags.agg_lease_duration_s);
    Result<std::unique_ptr<obs::IntrospectionServer>> started =
        obs::IntrospectionServer::Start(options, &obs::Default());
    if (!started.ok()) {
      TFD_LOG_ERROR << "remedy introspection server: " << started.error();
      return RemedyOutcome::kError;
    }
    server = std::move(*started);
    TFD_LOG_INFO << "remedy introspection on port " << server->port();
  }

  TFD_LOG_INFO << "tpu-feature-remedy " << info::VersionString() << " as "
               << self << " ("
               << (flags.remedy_dry_run ? "DRY-RUN" : "ENFORCE")
               << ", budget " << flags.remedy_max_concurrent_cordons
               << " cordons, domain cap " << flags.remedy_domain_cap
               << ", window " << flags.remedy_window_s << "s, lease "
               << flags.agg_lease_duration_s << "s)";

  // Register the whole metric surface at 0: scrape-deterministic.
  SetStateGauge(0);
  SetCordonsActiveGauge(0);
  for (const char* kind : kActionKinds) ActionCounter(kind);
  for (const char* interlock : kInterlocks) BlockedCounter(interlock);
  RollbacksCounter();
  WriteFailuresCounter();

  Shared shared(engine_cfg);
  shared.output_name = flags.agg_output_name;
  RemedyWatcher watcher(*cluster, &shared);
  agg::LeaseState lease_state;
  const double lease_tick_s =
      std::max(1.0, flags.agg_lease_duration_s / 3.0);
  double next_lease_tick = 0;    // immediately
  double next_decision_tick = 0;
  bool watcher_running = false;

  // Refreshes the lease when due; returns false when leadership (or
  // the epoch) moved away from `fence_epoch` — the epoch fence every
  // in-flight action batch checks BEFORE each write.
  auto fence_holds = [&](uint64_t fence_epoch) {
    double now = agg::MonoSeconds();
    if (now >= next_lease_tick) {
      agg::LeaseTick(*cluster, kLeaseDocName, self,
                     flags.agg_lease_duration_s, "remedy", &lease_state);
      SetStateGauge(lease_state.leading ? 1 : 0);
      next_lease_tick = now + lease_tick_s;
      if (server && lease_state.ever_contacted) server->RecordRewrite(true);
    }
    return lease_state.leading && lease_state.epoch == fence_epoch;
  };

  auto abandon = [&](const char* why) {
    int dropped;
    {
      std::lock_guard<std::mutex> lock(shared.mu);
      dropped = shared.engine.AbandonPending();
    }
    if (dropped > 0) {
      obs::DefaultJournal().Record(
          "remedy-abandoned", "remedy",
          std::string(why) + ": dropped " + std::to_string(dropped) +
              " in-flight intents (the next leader re-derives them)",
          {{"dropped", std::to_string(dropped)},
           {"epoch", std::to_string(lease_state.epoch)}});
    }
  };

  while (true) {
    struct timespec zero = {0, 0};
    int sig;
    while ((sig = sigtimedwait(&sigmask, nullptr, &zero)) > 0) {
      if (sig == SIGTERM || sig == SIGINT || sig == SIGQUIT) {
        TFD_LOG_INFO << "remedy: signal " << sig << ", shutting down";
        watcher.Stop();
        return RemedyOutcome::kExit;
      }
      if (sig == SIGHUP) {
        TFD_LOG_INFO << "remedy: SIGHUP, reloading";
        watcher.Stop();
        return RemedyOutcome::kRestart;
      }
    }

    double now = agg::MonoSeconds();
    if (now >= next_lease_tick) {
      agg::LeaseTick(*cluster, kLeaseDocName, self,
                     flags.agg_lease_duration_s, "remedy", &lease_state);
      SetStateGauge(lease_state.leading ? 1 : 0);
      next_lease_tick = now + lease_tick_s;
      if (server && lease_state.ever_contacted) server->RecordRewrite(true);
    }
    // Level-triggered (not edge-triggered) watcher reconciliation: the
    // epoch fence may observe the lease loss mid-batch, so the
    // transition is not guaranteed to surface HERE first.
    if (lease_state.leading && !watcher_running) {
      watcher.Start();
      watcher_running = true;
    } else if (!lease_state.leading && watcher_running) {
      // Lost the lease: stop watching, drop every in-flight intent
      // (epoch fence), and forget sync — a re-election re-lists.
      watcher.Stop();
      watcher_running = false;
      abandon("lease lost");
      std::lock_guard<std::mutex> lock(shared.mu);
      shared.synced = false;
    }

    {
      std::unique_lock<std::mutex> lock(shared.mu);
      double due = std::min(next_decision_tick, next_lease_tick);
      double wait_s = std::min(0.2, std::max(0.0, due - agg::MonoSeconds()));
      shared.cv.wait_for(
          lock, std::chrono::milliseconds(
                    static_cast<long long>(wait_s * 1000)));
    }

    now = agg::MonoSeconds();
    if (now < next_decision_tick) continue;
    next_decision_tick = now + 1.0;

    std::vector<Action> actions;
    std::vector<BlockedEdge> blocked;
    std::map<std::string, double> detect_at;
    bool ready = false;
    {
      std::lock_guard<std::mutex> lock(shared.mu);
      ready = lease_state.leading && shared.synced;
      if (ready) {
        auto result = shared.engine.Tick(now);
        actions = std::move(result.first);
        blocked = std::move(result.second);
        detect_at = shared.detect_at;
      }
    }
    if (!ready) continue;

    for (const BlockedEdge& edge : blocked) {
      BlockedCounter(edge.second)->Inc();
      obs::DefaultJournal().Record(
          "remedy-budget-blocked", "remedy",
          "cordon of " + edge.first + " blocked by the " + edge.second +
              " interlock",
          {{"node", edge.first}, {"interlock", edge.second}});
    }

    const uint64_t fence_epoch = lease_state.epoch;
    const double decide_mono = now;
    for (const Action& action : actions) {
      if (!fence_holds(fence_epoch)) {
        abandon("epoch fence tripped mid-batch");
        break;
      }
      uint64_t change = obs::DefaultTrace().Mint(
          "remedy", action.kind,
          action.node.empty() ? action.reason
                              : action.node + ": " + action.reason);
      double t_act = agg::MonoSeconds();
      obs::DefaultTrace().Stage("act");
      bool ok = true;
      std::string error;
      if (!flags.remedy_dry_run) {
        if (action.kind == "cordon" || action.kind == "uncordon") {
          Status s = k8s::PatchNodeUnschedulable(
              *cluster, action.node, action.kind == "cordon", nullptr,
              nullptr);
          ok = s.ok();
          if (!ok) error = s.message();
        } else if (action.kind == "drain-recommend") {
          Status s = ApplyDrainLabel(*cluster, action.node);
          ok = s.ok();
          if (!ok) error = s.message();
        }
        // rebuild-recommend mutates nothing: journal only.
      }
      double t_acked = agg::MonoSeconds();
      {
        std::lock_guard<std::mutex> lock(shared.mu);
        shared.engine.NoteActionResult(action.node, action.kind, ok,
                                       t_acked);
        shared.detect_at.erase(action.node);
      }
      // The remedy stage-budget decomposition (detect -> decide -> act
      // -> acked) rides the journal: detect is the watch thread's
      // evidence edge, decide the tick that emitted the action.
      double t_detect = decide_mono;
      if (auto it = detect_at.find(action.node); it != detect_at.end()) {
        t_detect = std::min(it->second, decide_mono);
      }
      std::vector<std::pair<std::string, std::string>> attrs = {
          {"change", std::to_string(change)},
          {"node", action.node},
          {"action", action.kind},
          {"evidence", action.evidence},
          {"dry_run", flags.remedy_dry_run ? "true" : "false"},
          {"decide_ms", Fixed3((decide_mono - t_detect) * 1000)},
          {"act_ms", Fixed3((t_act - decide_mono) * 1000)},
          {"acked_ms", Fixed3((t_acked - t_act) * 1000)}};
      if (ok) {
        const char* kind = action.kind == "cordon" ? "remedy-cordon"
                           : action.kind == "uncordon" ? "remedy-rollback"
                           : action.kind == "drain-recommend"
                               ? "remedy-drain"
                               : "remedy-rebuild";
        obs::DefaultJournal().Record(
            kind, "remedy",
            (flags.remedy_dry_run ? std::string("[dry-run] ")
                                  : std::string()) +
                action.kind +
                (action.node.empty() ? "" : " " + action.node) + ": " +
                action.reason,
            attrs);
        ActionCounter(action.kind)->Inc();
        if (action.kind == "uncordon") RollbacksCounter()->Inc();
        obs::DefaultTrace().MarkPublished(0, -1, change);
      } else {
        attrs.emplace_back("error", error);
        obs::DefaultJournal().Record(
            "remedy-write-failed", "remedy",
            action.kind + " of " + action.node + " failed: " + error +
                " (exponential backoff armed; the next tick re-emits "
                "once it expires)",
            attrs);
        WriteFailuresCounter()->Inc();
        TFD_LOG_WARNING << "remedy write: " << error;
      }
    }

    size_t cordons;
    std::string state_json;
    {
      std::lock_guard<std::mutex> lock(shared.mu);
      cordons = shared.engine.CordonedNodes().size();
      state_json = shared.engine.RenderJson();
    }
    SetCordonsActiveGauge(cordons);
    if (server) server->SetLabelsJson("{\"remedy\":" + state_json + "}");
  }
}

}  // namespace remedy
}  // namespace tfd

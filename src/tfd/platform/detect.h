// Platform detection: which TPU stack (if any) is present on this node.
//
// Reference parity: go-nvlib info.Interface (vendor info/info.go:53-88 —
// HasNvml via dlopen probe, IsTegraSystem via sysfs files) feeding the
// backend factory (internal/resource/factory.go:41-73). The TPU probes:
//   - HasLibtpu:      can dlopen libtpu.so (searching standard locations)
//   - HasAccelDevice: /dev/accel* or /dev/vfio/* TPU device nodes exist
//   - OnGce:          DMI product name is "Google Compute Engine" (or the
//                     metadata server answers)
#pragma once

#include <string>
#include <vector>

namespace tfd {
namespace platform {

// Candidate libtpu.so paths, in search order. `override_path` (from
// --libtpu-path / TPU_LIBRARY_PATH) wins when non-empty.
std::vector<std::string> LibtpuSearchPaths(const std::string& override_path);

// True if libtpu.so can be dlopen'd; fills `resolved_path` with the path
// that loaded. Never keeps the library loaded (probe only).
bool HasLibtpu(const std::string& override_path, std::string* resolved_path);

// True if TPU device nodes exist (/dev/accel0... or /dev/vfio entries).
bool HasAccelDevice();

// True if this machine looks like a GCE VM (DMI product name).
bool OnGce(const std::string& dmi_product_file =
               "/sys/class/dmi/id/product_name");

// True when a metadata server is plausibly reachable: an explicit
// endpoint (--metadata-endpoint), a GCE_METADATA_HOST override, or a GCE
// VM. Gates every metadata-touching path (labelers in main.cc, the PJRT
// watchdog's pinning plan) so bare-metal nodes never pay connection
// timeouts.
bool MetadataPlausible(const std::string& endpoint);

}  // namespace platform
}  // namespace tfd

#include "tfd/platform/detect.h"

#include <dlfcn.h>

#include <cstdlib>
#include <filesystem>

#include "tfd/util/file.h"
#include "tfd/util/strings.h"

namespace tfd {
namespace platform {

namespace fs = std::filesystem;

std::vector<std::string> LibtpuSearchPaths(const std::string& override_path) {
  std::vector<std::string> paths;
  if (!override_path.empty()) {
    paths.push_back(override_path);
    return paths;
  }
  if (const char* env = std::getenv("TPU_LIBRARY_PATH")) {
    if (*env) paths.push_back(env);
  }
  // Standard TPU-VM locations, then the bare soname for ld.so search.
  paths.push_back("/usr/lib/libtpu/libtpu.so");
  paths.push_back("/usr/local/lib/libtpu/libtpu.so");
  paths.push_back("/lib/libtpu.so");
  paths.push_back("libtpu.so");
  return paths;
}

bool HasLibtpu(const std::string& override_path, std::string* resolved_path) {
  for (const std::string& path : LibtpuSearchPaths(override_path)) {
    // RTLD_LAZY keeps the probe cheap; the PJRT backend re-opens for real
    // (same pattern as the reference's dlopen probe, info/info.go:53-62).
    void* handle = dlopen(path.c_str(), RTLD_LAZY | RTLD_LOCAL);
    if (handle != nullptr) {
      if (resolved_path != nullptr) *resolved_path = path;
      dlclose(handle);
      return true;
    }
  }
  return false;
}

bool HasAccelDevice() {
  std::error_code ec;
  for (int i = 0; i < 8; i++) {
    if (FileExists("/dev/accel" + std::to_string(i))) return true;
  }
  // VFIO-based TPU attachment (newer TPU VMs). A bound IOMMU group alone
  // is not evidence of a TPU — any passthrough host has those — so only
  // trust it on a GCE VM, where VFIO groups mean accelerators.
  fs::path vfio("/dev/vfio");
  if (fs::is_directory(vfio, ec) && OnGce()) {
    for (const auto& entry : fs::directory_iterator(vfio, ec)) {
      std::string name = entry.path().filename().string();
      if (name != "vfio") return true;  // a bound IOMMU group node
    }
  }
  return false;
}

bool OnGce(const std::string& dmi_product_file) {
  Result<std::string> product = ReadFile(dmi_product_file);
  if (!product.ok()) return false;
  std::string p = ToLower(TrimSpace(*product));
  return p.find("google") != std::string::npos;
}

bool MetadataPlausible(const std::string& endpoint) {
  return !endpoint.empty() || std::getenv("GCE_METADATA_HOST") != nullptr ||
         OnGce();
}

}  // namespace platform
}  // namespace tfd

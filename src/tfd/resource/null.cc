#include "tfd/resource/types.h"

namespace tfd {
namespace resource {

namespace {

class NullManager : public Manager {
 public:
  Status Init() override { return Status::Ok(); }
  void Shutdown() override {}

  Result<std::vector<DevicePtr>> GetDevices() override {
    return std::vector<DevicePtr>{};
  }

  Result<std::string> GetLibtpuVersion() override {
    return Result<std::string>::Error(
        "cannot get libtpu version from the null manager");
  }

  Result<std::string> GetRuntimeVersion() override {
    return Result<std::string>::Error(
        "cannot get runtime version from the null manager");
  }

  Result<TopologyInfo> GetTopology() override {
    return Result<TopologyInfo>::Error(
        "cannot get topology from the null manager");
  }

  std::string Name() const override { return "null"; }
  bool TouchesDevices() const override { return false; }
};

}  // namespace

ManagerPtr NewNullManager() { return std::make_shared<NullManager>(); }

}  // namespace resource
}  // namespace tfd

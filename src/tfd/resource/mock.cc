// Mock backend: a Manager configured entirely from a yamllite fixture file.
//
// The reference generates moq mocks (internal/resource/manager_mock.go,
// device_mock.go) and wraps them in fixture builders
// (testing/resource-testing.go:31-134: NewFullGPU, NewMigDevice, ...,
// WithErrorOnInit). Those only work in-process from Go tests. This build
// makes the mock a real backend selectable with --backend=mock
// --mock-topology-file=..., so golden tests exercise the *shipped binary*
// end-to-end with no hardware — the hermetic-harness improvement SURVEY.md
// §4 calls for.
//
// Fixture format (see tests/fixtures/*.yaml):
//   libtpuVersion: 0.0.34
//   runtimeVersion: "0.68"
//   acceleratorType: v5litepod-16   # optional
//   topology: 4x4                   # optional (default from type)
//   chipsPerHost: 4                 # optional
//   numHosts: 4                     # optional
//   workerId: 0                     # optional
//   wraparound: false               # optional
//   initError: "boom"               # optional: Init() fails
//   chips:
//   - kind: TPU v5 lite
//     count: 4                      # expands to N identical chips
//     memoryMiB: 16384              # optional; default from family table
#include "tfd/config/yamllite.h"
#include "tfd/resource/types.h"
#include "tfd/slice/topology.h"
#include "tfd/util/file.h"

namespace tfd {
namespace resource {

namespace {

class MockDevice : public Device {
 public:
  MockDevice(std::string kind, slice::FamilySpec spec, long long memory_mib)
      : kind_(std::move(kind)), spec_(std::move(spec)),
        memory_mib_(memory_mib) {}

  Result<std::string> GetKind() override { return kind_; }
  Result<std::string> GetProduct() override { return spec_.product; }
  Result<long long> GetTotalMemoryMiB() override { return memory_mib_; }
  Result<int> GetCoreCount() override { return spec_.cores_per_chip; }
  Result<int> GetGeneration() override { return spec_.generation; }

 private:
  std::string kind_;
  slice::FamilySpec spec_;
  long long memory_mib_;
};

class MockManager : public Manager {
 public:
  Status Init() override {
    if (!init_error_.empty()) return Status::Error(init_error_);
    return Status::Ok();
  }
  void Shutdown() override {}

  Result<std::vector<DevicePtr>> GetDevices() override { return devices_; }

  Result<std::string> GetLibtpuVersion() override {
    if (libtpu_version_.empty()) {
      return Result<std::string>::Error("mock: no libtpu version configured");
    }
    return libtpu_version_;
  }

  Result<std::string> GetRuntimeVersion() override {
    if (runtime_version_.empty()) {
      return Result<std::string>::Error(
          "mock: no runtime version configured");
    }
    return runtime_version_;
  }

  Result<TopologyInfo> GetTopology() override { return topology_; }

  std::string Name() const override { return "mock"; }
  bool TouchesDevices() const override { return true; }

  std::string init_error_;
  std::string libtpu_version_;
  std::string runtime_version_;
  TopologyInfo topology_;
  std::vector<DevicePtr> devices_;
};

Result<std::string> GetString(const yamllite::Node& root,
                              const std::string& key,
                              const std::string& dflt) {
  yamllite::NodePtr n = root.Get(key);
  if (!n || n->IsNull()) return dflt;
  return n->AsString();
}

Result<long long> GetInt(const yamllite::Node& root, const std::string& key,
                         long long dflt) {
  yamllite::NodePtr n = root.Get(key);
  if (!n || n->IsNull()) return dflt;
  return n->AsInt();
}

}  // namespace

Result<ManagerPtr> NewMockManager(const std::string& fixture_path) {
  if (fixture_path.empty()) {
    return Result<ManagerPtr>::Error(
        "mock backend requires --mock-topology-file");
  }
  Result<std::string> text = ReadFile(fixture_path);
  if (!text.ok()) return Result<ManagerPtr>::Error(text.error());
  Result<yamllite::NodePtr> parsed = yamllite::Parse(*text);
  if (!parsed.ok()) {
    return Result<ManagerPtr>::Error("mock fixture " + fixture_path + ": " +
                                     parsed.error());
  }
  const yamllite::Node& root = **parsed;

  auto mgr = std::make_shared<MockManager>();

#define TFD_MOCK_STR(field, key, dflt)                              \
  {                                                                 \
    Result<std::string> v = GetString(root, key, dflt);             \
    if (!v.ok()) return Result<ManagerPtr>::Error(v.error());       \
    field = *v;                                                     \
  }
#define TFD_MOCK_INT(field, key, dflt)                              \
  {                                                                 \
    Result<long long> v = GetInt(root, key, dflt);                  \
    if (!v.ok()) return Result<ManagerPtr>::Error(v.error());       \
    field = static_cast<int>(*v);                                   \
  }

  TFD_MOCK_STR(mgr->init_error_, "initError", "");
  TFD_MOCK_STR(mgr->libtpu_version_, "libtpuVersion", "");
  TFD_MOCK_STR(mgr->runtime_version_, "runtimeVersion", "");
  TFD_MOCK_STR(mgr->topology_.accelerator_type, "acceleratorType", "");
  TFD_MOCK_STR(mgr->topology_.topology, "topology", "");
  TFD_MOCK_INT(mgr->topology_.chips_per_host, "chipsPerHost", 0);
  TFD_MOCK_INT(mgr->topology_.num_hosts, "numHosts", 0);
  TFD_MOCK_INT(mgr->topology_.worker_id, "workerId", -1);
#undef TFD_MOCK_STR
#undef TFD_MOCK_INT
  {
    yamllite::NodePtr n = root.Get("wraparound");
    if (n && !n->IsNull()) {
      Result<bool> v = n->AsBool();
      if (!v.ok()) return Result<ManagerPtr>::Error(v.error());
      mgr->topology_.has_wraparound = *v;
    }
  }

  yamllite::NodePtr chips = root.Get("chips");
  if (chips && chips->kind == yamllite::Node::Kind::kList) {
    for (const yamllite::NodePtr& item : chips->list_items) {
      Result<std::string> kind = GetString(*item, "kind", "");
      if (!kind.ok()) return Result<ManagerPtr>::Error(kind.error());
      if (kind->empty()) {
        return Result<ManagerPtr>::Error(
            "mock fixture: every chips[] entry needs a 'kind'");
      }
      Result<slice::FamilySpec> spec = slice::FamilyFromDeviceKind(*kind);
      if (!spec.ok()) return Result<ManagerPtr>::Error(spec.error());
      Result<long long> memory = GetInt(*item, "memoryMiB", spec->hbm_mib);
      if (!memory.ok()) return Result<ManagerPtr>::Error(memory.error());
      Result<long long> count = GetInt(*item, "count", 1);
      if (!count.ok()) return Result<ManagerPtr>::Error(count.error());
      for (long long i = 0; i < *count; i++) {
        mgr->devices_.push_back(
            std::make_shared<MockDevice>(*kind, *spec, *memory));
      }
    }
  }

  if (mgr->topology_.chips_per_host == 0) {
    mgr->topology_.chips_per_host =
        static_cast<int>(mgr->devices_.size());
  }
  return ManagerPtr(mgr);
}

}  // namespace resource
}  // namespace tfd

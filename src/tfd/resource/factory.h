// Backend factory: picks the hardware backend for this node.
//
// Reference parity: internal/resource/factory.go:26-73 — NVML present →
// NVML manager; Tegra → CUDA manager; neither → Null manager; wrapped in
// the fallback-to-null decorator unless fail-on-init-error. The TPU
// selection order: libtpu or TPU device nodes → PJRT backend; GCE VM with
// a TPU accelerator-type in metadata → metadata backend (the degraded
// CUDA-backend analogue: chip facts from the family table, no device
// handles); neither → Null.
#pragma once

#include <functional>
#include <string>
#include <vector>

#include "tfd/config/config.h"
#include "tfd/resource/types.h"

namespace tfd {
namespace resource {

// One backend the node could be labeled from. `make` builds a FRESH
// manager per call (Init is one-shot per object); construction-shaped
// errors (missing fixture, bad flags) surface through the Result. The
// probe scheduler (sched/sources.cc) maps each candidate to a probe
// source and the render ladder (cmd/) replaces the old synchronous
// NewManager/fallback-chain entry point; the chain decorators below
// remain as tested building blocks.
struct BackendCandidate {
  std::string name;  // pjrt | metadata | mock | null
  std::function<Result<ManagerPtr>()> make;
};

// The ordered candidate list for this node (preferred first), mirroring
// the old auto-selection: TPU stack -> pjrt (metadata-enriched on GCE),
// GCE -> metadata, neither -> null. Explicit --backend values yield the
// single matching candidate. Never empty. Platform detection (and its
// log lines) runs here, once per call.
std::vector<BackendCandidate> BackendCandidates(const config::Config& config);

// Drops the PJRT watchdog's process-global snapshot cache and failure
// memo (pjrt_watchdog.cc). Called on SIGHUP: a config regen must not
// serve device facts probed under the previous configuration.
void InvalidatePjrtProbeCaches();

// The PJRT (libtpu) backend. A watchdog manager (pjrt_watchdog.cc): init
// runs in a forked child under flags.pjrt_init_timeout_s so a blocking
// PJRT_Client_Create (multi-host rendezvous, wedged driver) degrades into
// a clean Init error instead of hanging the daemon. On detected
// multi-host slices (unless flags.pjrt_multihost) the child pins client
// creation to this host and slice-wide topology is overlaid from GCE
// metadata.
ManagerPtr NewPjrtManager(const config::Config& config);

// The raw in-process PJRT backend (pjrt_manager.cc): dlopen + client
// create on the calling thread, no deadline. Runs inside the watchdog's
// probe child; selectable directly via pjrt-init-timeout=0.
ManagerPtr NewPjrtInProcessManager(
    const std::string& libtpu_path,
    const std::vector<std::string>& client_options = {});

// The metadata backend — chip inventory derived from the GCE metadata
// accelerator-type, for nodes where libtpu is absent or busy.
ManagerPtr NewMetadataManager(const std::string& metadata_endpoint);

// Decorator filling topology gaps (accelerator-type, worker id) from GCE
// metadata; used around the PJRT backend on GCE — see enrich.cc.
ManagerPtr NewMetadataEnrichedManager(ManagerPtr inner,
                                      const std::string& endpoint);

}  // namespace resource
}  // namespace tfd

// Backend factory: picks the hardware backend for this node.
//
// Reference parity: internal/resource/factory.go:26-73 — NVML present →
// NVML manager; Tegra → CUDA manager; neither → Null manager; wrapped in
// the fallback-to-null decorator unless fail-on-init-error. The TPU
// selection order: libtpu or TPU device nodes → PJRT backend; GCE VM with
// a TPU accelerator-type in metadata → metadata backend (the degraded
// CUDA-backend analogue: chip facts from the family table, no device
// handles); neither → Null.
#pragma once

#include "tfd/config/config.h"
#include "tfd/resource/types.h"

namespace tfd {
namespace resource {

Result<ManagerPtr> NewManager(const config::Config& config);

// The PJRT (libtpu) backend — implemented in pjrt_manager.cc.
ManagerPtr NewPjrtManager(const std::string& libtpu_path);

// The metadata backend — chip inventory derived from the GCE metadata
// accelerator-type, for nodes where libtpu is absent or busy.
ManagerPtr NewMetadataManager(const std::string& metadata_endpoint);

// Decorator filling topology gaps (accelerator-type, worker id) from GCE
// metadata; used around the PJRT backend on GCE — see enrich.cc.
ManagerPtr NewMetadataEnrichedManager(ManagerPtr inner,
                                      const std::string& endpoint);

}  // namespace resource
}  // namespace tfd

// Resource abstraction: the device layer every labeler sits on.
//
// Reference parity: internal/resource/types.go:22-42 defines
// Manager{Init,Shutdown,GetDevices,GetDriverVersion,GetCudaDriverVersion}
// and Device{IsMigEnabled,...,GetCudaComputeCapability}. The TPU interfaces
// are re-sized for TPU hardware: chips instead of GPUs, HBM MiB, TPU
// generation instead of CUDA compute capability, and a first-class
// TopologyInfo (slice shape / hosts / worker id) — which NVML hands out
// per-device but TPU stacks expose per-slice. MIG-isms (parent handles,
// GPU-instance slices) are deliberately dropped; their role is played by the
// slice-shape strategies in tfd/lm/slice_strategy.
#pragma once

#include <memory>
#include <string>
#include <vector>

#include "tfd/util/status.h"

namespace tfd {
namespace resource {

// Per-slice topology, as known to this host.
struct TopologyInfo {
  std::string accelerator_type;  // e.g. "v5litepod-16" ("" if unknown)
  std::string topology;          // e.g. "4x4" / "2x2x2" ("" if unknown)
  int chips_per_host = 0;        // chips attached to this host
  int num_hosts = 0;             // hosts in the slice (1 for single-host)
  int worker_id = -1;            // this host's index in the slice (-1 unknown)
  bool has_wraparound = false;   // ICI torus wrap links present
};

// One TPU chip attached to this host.
class Device {
 public:
  virtual ~Device() = default;

  // Raw device kind as reported by the backend (e.g. "TPU v5 lite").
  virtual Result<std::string> GetKind() = 0;
  // Normalized product name for labels (e.g. "tpu-v5e").
  virtual Result<std::string> GetProduct() = 0;
  // HBM capacity in MiB.
  virtual Result<long long> GetTotalMemoryMiB() = 0;
  // TensorCores on this chip.
  virtual Result<int> GetCoreCount() = 0;
  // TPU generation (e.g. 5 for v5e/v5p) — the compute-capability analogue
  // (reference device.GetCudaComputeCapability, types.go:40).
  virtual Result<int> GetGeneration() = 0;
};

using DevicePtr = std::shared_ptr<Device>;

// A hardware backend. Init() is where the native library boundary is
// crossed (reference nvml-lib.go:82-88); everything else must be callable
// only between Init and Shutdown.
class Manager {
 public:
  virtual ~Manager() = default;

  virtual Status Init() = 0;
  virtual void Shutdown() = 0;

  virtual Result<std::vector<DevicePtr>> GetDevices() = 0;

  // libtpu library version (driver-version analogue,
  // reference Manager.GetDriverVersion types.go:27).
  virtual Result<std::string> GetLibtpuVersion() = 0;
  // PJRT C-API version "major.minor" (CUDA-driver-version analogue,
  // reference Manager.GetCudaDriverVersion types.go:28).
  virtual Result<std::string> GetRuntimeVersion() = 0;

  // Slice topology as known to this backend. May be empty (single host,
  // unknown shape) — labelers degrade gracefully.
  virtual Result<TopologyInfo> GetTopology() = 0;

  // Short backend name for logs and the tpu.backend label
  // (e.g. "pjrt", "metadata", "mock", "null").
  virtual std::string Name() const = 0;

  // Whether this backend exercises the device stack itself (dlopen'd
  // libtpu, device nodes) rather than describing it from the control
  // plane. Only device-touching backends may vouch for device health.
  virtual bool TouchesDevices() const = 0;
};

using ManagerPtr = std::shared_ptr<Manager>;

// Optional mixin for managers that serve a pre-probed snapshot view
// (sched/sources.cc): reports how long the probe that produced the
// snapshot actually took, so health probe-ms reflects the real
// init+enumeration latency rather than a no-op snapshot Init.
class ProbeTimed {
 public:
  virtual ~ProbeTimed() = default;
  virtual double ProbeSeconds() const = 0;
};

// Null manager: no devices; version queries error
// (reference internal/resource/null.go:30-57).
ManagerPtr NewNullManager();

// Decorator: if Init() fails, log a warning and degrade to the null manager
// (reference internal/resource/fallback.go:29-64).
ManagerPtr NewFallbackToNullOnInitError(ManagerPtr wrapped);

// Decorator: tries each backend's Init() in order, settling on the first
// that succeeds; Init() fails only if every candidate fails. Used by
// --backend=auto so a busy-chip PJRT failure falls back to the metadata
// backend (no reference analogue — GFD picks a single winner up front).
ManagerPtr NewFallbackChain(std::vector<ManagerPtr> candidates);

// Mock manager configured from a yamllite fixture file — the moq-mock +
// fixture-builder analogue (reference internal/resource/manager_mock.go and
// testing/resource-testing.go:31-134), driven by data instead of codegen so
// integration tests can exercise the real binary hermetically.
Result<ManagerPtr> NewMockManager(const std::string& fixture_path);

}  // namespace resource
}  // namespace tfd

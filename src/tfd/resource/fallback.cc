#include "tfd/resource/types.h"
#include "tfd/util/logging.h"

namespace tfd {
namespace resource {

namespace {

// On Init() failure the wrapped backend is replaced by the null manager so a
// non-TPU (or broken-driver) node still gets its machine-type-only labels
// instead of a crash loop (reference fallback.go:37-44; BASELINE config 1).
class FallbackManager : public Manager {
 public:
  explicit FallbackManager(ManagerPtr wrapped)
      : active_(std::move(wrapped)) {}

  Status Init() override {
    Status s = active_->Init();
    if (!s.ok()) {
      TFD_LOG_WARNING << "failed to initialize " << active_->Name()
                      << " backend: " << s.message()
                      << "; falling back to the null backend";
      active_ = NewNullManager();
    }
    return Status::Ok();
  }

  void Shutdown() override { active_->Shutdown(); }

  Result<std::vector<DevicePtr>> GetDevices() override {
    return active_->GetDevices();
  }
  Result<std::string> GetLibtpuVersion() override {
    return active_->GetLibtpuVersion();
  }
  Result<std::string> GetRuntimeVersion() override {
    return active_->GetRuntimeVersion();
  }
  Result<TopologyInfo> GetTopology() override {
    return active_->GetTopology();
  }
  std::string Name() const override { return active_->Name(); }
  bool TouchesDevices() const override { return active_->TouchesDevices(); }

 private:
  ManagerPtr active_;
};

// Tries candidates in order until one Init()s (used by --backend=auto).
class FallbackChainManager : public Manager {
 public:
  explicit FallbackChainManager(std::vector<ManagerPtr> candidates)
      : candidates_(std::move(candidates)), active_(NewNullManager()) {}

  Status Init() override {
    std::string errors;
    for (ManagerPtr& candidate : candidates_) {
      Status s = candidate->Init();
      if (s.ok()) {
        active_ = candidate;
        return Status::Ok();
      }
      TFD_LOG_WARNING << "backend " << candidate->Name()
                      << " failed to initialize: " << s.message()
                      << (candidate == candidates_.back()
                              ? ""
                              : "; trying the next backend");
      if (!errors.empty()) errors += "; ";
      errors += candidate->Name() + ": " + s.message();
    }
    return Status::Error("all backends failed to initialize (" + errors +
                         ")");
  }

  void Shutdown() override { active_->Shutdown(); }

  Result<std::vector<DevicePtr>> GetDevices() override {
    return active_->GetDevices();
  }
  Result<std::string> GetLibtpuVersion() override {
    return active_->GetLibtpuVersion();
  }
  Result<std::string> GetRuntimeVersion() override {
    return active_->GetRuntimeVersion();
  }
  Result<TopologyInfo> GetTopology() override {
    return active_->GetTopology();
  }
  std::string Name() const override { return active_->Name(); }
  bool TouchesDevices() const override { return active_->TouchesDevices(); }

 private:
  std::vector<ManagerPtr> candidates_;
  ManagerPtr active_;
};

}  // namespace

ManagerPtr NewFallbackToNullOnInitError(ManagerPtr wrapped) {
  return std::make_shared<FallbackManager>(std::move(wrapped));
}

ManagerPtr NewFallbackChain(std::vector<ManagerPtr> candidates) {
  return std::make_shared<FallbackChainManager>(std::move(candidates));
}

}  // namespace resource
}  // namespace tfd

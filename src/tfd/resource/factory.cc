#include "tfd/resource/factory.h"

#include "tfd/platform/detect.h"
#include "tfd/util/logging.h"

namespace tfd {
namespace resource {

namespace {

Result<ManagerPtr> SelectManager(const config::Config& config) {
  const config::Flags& f = config.flags;
  if (f.backend == "null") return NewNullManager();
  if (f.backend == "mock") return NewMockManager(f.mock_topology_file);
  if (f.backend == "pjrt") return NewPjrtManager(config);
  if (f.backend == "metadata") return NewMetadataManager(f.metadata_endpoint);

  // auto (reference getManager, factory.go:41-73). Unlike the reference's
  // single-winner probe, auto builds a *fallback chain*: a TPU VM whose
  // chips are already held by a training job makes PJRT client creation
  // fail, but the metadata backend can still label the node fully — so
  // PJRT falls back to metadata (on GCE) before giving up.
  std::string libtpu_path;
  bool has_libtpu = platform::HasLibtpu(f.libtpu_path, &libtpu_path);
  bool has_accel = platform::HasAccelDevice();
  bool on_gce = platform::OnGce();
  std::vector<ManagerPtr> chain;
  if (has_libtpu || has_accel) {
    TFD_LOG_INFO << "detected TPU stack (libtpu="
                 << (has_libtpu ? libtpu_path : "no")
                 << ", accel-devices=" << (has_accel ? "yes" : "no")
                 << "); trying the PJRT backend first";
    ManagerPtr pjrt = NewPjrtManager(config);
    if (on_gce || !f.metadata_endpoint.empty()) {
      pjrt = NewMetadataEnrichedManager(pjrt, f.metadata_endpoint);
    }
    chain.push_back(std::move(pjrt));
  }
  if (on_gce || !f.metadata_endpoint.empty()) {
    chain.push_back(NewMetadataManager(f.metadata_endpoint));
  }
  if (chain.empty()) {
    TFD_LOG_INFO << "no TPU stack detected; using the null backend";
    return NewNullManager();
  }
  if (chain.size() == 1) return chain[0];
  return NewFallbackChain(std::move(chain));
}

}  // namespace

Result<ManagerPtr> NewManager(const config::Config& config) {
  Result<ManagerPtr> manager = SelectManager(config);
  if (!manager.ok()) return manager;
  // WithConfig (reference factory.go:32-38): without fail-on-init-error,
  // degrade to null on Init failure instead of crash-looping.
  if (!config.flags.fail_on_init_error) {
    return ManagerPtr(NewFallbackToNullOnInitError(*manager));
  }
  return manager;
}

}  // namespace resource
}  // namespace tfd

#include "tfd/resource/factory.h"

#include "tfd/platform/detect.h"
#include "tfd/util/logging.h"

namespace tfd {
namespace resource {

std::vector<BackendCandidate> BackendCandidates(
    const config::Config& config) {
  const config::Flags& f = config.flags;
  std::vector<BackendCandidate> out;
  if (f.backend == "null") {
    out.push_back({"null", [] {
                     return Result<ManagerPtr>(NewNullManager());
                   }});
    return out;
  }
  if (f.backend == "mock") {
    std::string fixture = f.mock_topology_file;
    out.push_back(
        {"mock", [fixture] { return NewMockManager(fixture); }});
    return out;
  }
  if (f.backend == "pjrt") {
    config::Config captured = config;
    out.push_back({"pjrt", [captured] {
                     return Result<ManagerPtr>(NewPjrtManager(captured));
                   }});
    return out;
  }
  if (f.backend == "metadata") {
    std::string endpoint = f.metadata_endpoint;
    out.push_back({"metadata", [endpoint] {
                     return Result<ManagerPtr>(
                         NewMetadataManager(endpoint));
                   }});
    return out;
  }

  // auto (reference getManager, factory.go:41-73). Unlike the reference's
  // single-winner probe, auto yields a *candidate ladder*: a TPU VM whose
  // chips are already held by a training job makes PJRT client creation
  // fail, but the metadata backend can still label the node fully — so
  // PJRT degrades to metadata (on GCE) before giving up.
  std::string libtpu_path;
  bool has_libtpu = platform::HasLibtpu(f.libtpu_path, &libtpu_path);
  bool has_accel = platform::HasAccelDevice();
  bool on_gce = platform::OnGce();
  if (has_libtpu || has_accel) {
    TFD_LOG_INFO << "detected TPU stack (libtpu="
                 << (has_libtpu ? libtpu_path : "no")
                 << ", accel-devices=" << (has_accel ? "yes" : "no")
                 << "); trying the PJRT backend first";
    config::Config captured = config;
    bool enrich = on_gce || !f.metadata_endpoint.empty();
    std::string endpoint = f.metadata_endpoint;
    out.push_back({"pjrt", [captured, enrich, endpoint] {
                     ManagerPtr pjrt = NewPjrtManager(captured);
                     if (enrich) {
                       pjrt = NewMetadataEnrichedManager(pjrt, endpoint);
                     }
                     return Result<ManagerPtr>(std::move(pjrt));
                   }});
  }
  if (on_gce || !f.metadata_endpoint.empty()) {
    std::string endpoint = f.metadata_endpoint;
    out.push_back({"metadata", [endpoint] {
                     return Result<ManagerPtr>(
                         NewMetadataManager(endpoint));
                   }});
  }
  if (out.empty()) {
    TFD_LOG_INFO << "no TPU stack detected; using the null backend";
    out.push_back({"null", [] {
                     return Result<ManagerPtr>(NewNullManager());
                   }});
  }
  return out;
}

}  // namespace resource
}  // namespace tfd

// Topology-enrichment decorator: fills the gaps PJRT cannot see from GCE
// instance metadata.
//
// PJRT knows the physical slice (chips, coords, hosts) but not the GCE
// accelerator-type string ("v5p-128") or the scheduler-facing worker id;
// the metadata server knows those but not live device state. The decorator
// composes them: inner (PJRT) wins, metadata fills blanks. No reference
// analogue — NVML alone answers everything for GPUs; on TPU VMs identity is
// split across libtpu and the metadata server (SURVEY.md §7 "hard part b").
#include "tfd/gce/metadata.h"
#include "tfd/resource/factory.h"
#include "tfd/util/strings.h"

namespace tfd {
namespace resource {

namespace {

class EnrichedManager : public Manager {
 public:
  EnrichedManager(ManagerPtr inner, const std::string& endpoint)
      : inner_(std::move(inner)), client_(endpoint) {}

  Status Init() override { return inner_->Init(); }
  void Shutdown() override { inner_->Shutdown(); }
  Result<std::vector<DevicePtr>> GetDevices() override {
    return inner_->GetDevices();
  }
  Result<std::string> GetLibtpuVersion() override {
    return inner_->GetLibtpuVersion();
  }
  Result<std::string> GetRuntimeVersion() override {
    return inner_->GetRuntimeVersion();
  }
  std::string Name() const override { return inner_->Name(); }
  bool TouchesDevices() const override { return inner_->TouchesDevices(); }

  Result<TopologyInfo> GetTopology() override {
    Result<TopologyInfo> topo = inner_->GetTopology();
    if (!topo.ok()) return topo;
    if (!enriched_) {
      if (topo->accelerator_type.empty()) {
        Result<std::string> at = client_.AcceleratorType();
        if (at.ok()) accelerator_type_ = TrimSpace(*at);
      }
      if (topo->worker_id < 0) {
        Result<std::map<std::string, std::string>> env = client_.TpuEnv();
        if (env.ok()) {
          auto it = env->find("WORKER_ID");
          int worker_id = 0;
          if (it != env->end() &&
              ParseNonNegInt(TrimSpace(it->second), &worker_id)) {
            worker_id_ = worker_id;
          }
        }
      }
      enriched_ = true;
    }
    if (topo->accelerator_type.empty()) {
      topo->accelerator_type = accelerator_type_;
    }
    if (topo->worker_id < 0) topo->worker_id = worker_id_;
    return topo;
  }

 private:
  ManagerPtr inner_;
  gce::MetadataClient client_;
  bool enriched_ = false;
  std::string accelerator_type_;
  int worker_id_ = -1;
};

}  // namespace

ManagerPtr NewMetadataEnrichedManager(ManagerPtr inner,
                                      const std::string& endpoint) {
  return std::make_shared<EnrichedManager>(std::move(inner), endpoint);
}

}  // namespace resource
}  // namespace tfd

// Metadata backend: chip inventory derived from GCE instance metadata.
//
// The structural analogue of the reference's CUDA backend
// (internal/resource/cuda-lib.go, cuda-device.go): the degraded path used
// when the primary native library is unavailable. On a TPU VM whose chips
// are held by another process (libtpu is single-tenant!) or whose libtpu is
// missing, the accelerator identity is still fully determined by the
// metadata server: accelerator-type + tpu-env give the chip count, family,
// topology, and worker index. Versions are unknown here, exactly as the
// CUDA backend reports "unknown.unknown.unknown" (cuda-lib.go:68-70).
#include <cstdlib>

#include "tfd/gce/metadata.h"
#include "tfd/resource/factory.h"
#include "tfd/slice/topology.h"
#include "tfd/util/logging.h"
#include "tfd/util/strings.h"

namespace tfd {
namespace resource {

namespace {

class MetadataDevice : public Device {
 public:
  explicit MetadataDevice(slice::FamilySpec spec) : spec_(std::move(spec)) {}

  Result<std::string> GetKind() override {
    return "TPU " + spec_.family;  // synthesized; no PJRT handle here
  }
  Result<std::string> GetProduct() override { return spec_.product; }
  Result<long long> GetTotalMemoryMiB() override { return spec_.hbm_mib; }
  Result<int> GetCoreCount() override { return spec_.cores_per_chip; }
  Result<int> GetGeneration() override { return spec_.generation; }

 private:
  slice::FamilySpec spec_;
};

// Product of a comma-separated bounds string like "2,2,1" (tpu-env
// CHIPS_PER_HOST_BOUNDS / HOST_BOUNDS). 0 on parse failure; every part
// must be all digits (ParseNonNegInt) so "2x,2" cannot half-parse.
int BoundsProduct(const std::string& bounds) {
  long long product = 1;
  for (const std::string& part : SplitString(TrimSpace(bounds), ',')) {
    int v = 0;
    if (!ParseNonNegInt(TrimSpace(part), &v) || v < 1) return 0;
    product *= v;
    // A bounds product is a host/chip count; anything past int range is
    // garbage metadata (and would overflow the int return).
    if (product > 2147483647LL) return 0;
  }
  return static_cast<int>(product);
}

class MetadataManager : public Manager {
 public:
  explicit MetadataManager(const std::string& endpoint)
      : client_(endpoint) {}

  Status Init() override {
    Result<std::string> accel_type = client_.AcceleratorType();
    if (!accel_type.ok() || accel_type->empty()) {
      // GKE TPU node pools (BASELINE config 5's substrate) don't carry
      // the Cloud-TPU-VM attributes (accelerator-type / tpu-env); their
      // TPU identity is in the ct* machine type and the kube-labels
      // attribute instead. Try that surface before giving up.
      Status gke = GkeInit();
      if (gke.ok()) return gke;
      return Status::Error(
          "no TPU accelerator-type in instance metadata and no GKE TPU "
          "machine type (endpoint " + client_.endpoint() + "): " +
          gke.message());
    }
    Result<slice::AcceleratorType> parsed =
        slice::ParseAcceleratorType(*accel_type);
    if (!parsed.ok()) return Status::Error(parsed.error());
    accel_ = *parsed;

    topology_.accelerator_type = accel_.raw;
    topology_.num_hosts = 1;
    int local_chips = std::min(accel_.num_chips,
                               accel_.spec.max_chips_per_host);

    Result<std::map<std::string, std::string>> env = client_.TpuEnv();
    if (env.ok()) {
      auto get = [&](const char* key) -> std::string {
        auto it = env->find(key);
        return it == env->end() ? "" : it->second;
      };
      if (int v = BoundsProduct(get("CHIPS_PER_HOST_BOUNDS"))) {
        local_chips = v;
      }
      if (int v = BoundsProduct(get("HOST_BOUNDS"))) topology_.num_hosts = v;
      std::string topology = get("TOPOLOGY");
      if (!topology.empty()) {
        topology_.topology = ToLower(topology);
      }
      std::string worker = TrimSpace(get("WORKER_ID"));
      int worker_id = 0;
      if (ParseNonNegInt(worker, &worker_id)) {
        topology_.worker_id = worker_id;
      }
    } else if (accel_.num_chips > accel_.spec.max_chips_per_host) {
      // Multi-host slice without tpu-env: derive the host count.
      topology_.num_hosts =
          (accel_.num_chips + local_chips - 1) / local_chips;
    }
    topology_.chips_per_host = local_chips;

    FillWorkerIdFallbacks();

    if (topology_.topology.empty()) {
      Result<slice::Shape> shape =
          slice::DefaultTopology(accel_.spec, accel_.num_chips);
      if (shape.ok()) topology_.topology = shape->ToString();
    }
    // ICI wraparound from the ACTUAL slice shape (tpu-env TOPOLOGY may be
    // a custom non-default layout), per the published cube/full-pod rule
    // (slice::ComputeIciWrap). Unknown shape → no wrap claimed.
    topology_.has_wraparound = false;
    if (!topology_.topology.empty()) {
      Result<slice::Shape> shape = slice::ParseShape(topology_.topology);
      if (shape.ok()) {
        topology_.has_wraparound =
            slice::ComputeIciWrap(accel_.spec, *shape);
      }
    }

    for (int i = 0; i < local_chips; i++) {
      devices_.push_back(std::make_shared<MetadataDevice>(accel_.spec));
    }
    return Status::Ok();
  }

  // Worker-id fallback ladder, shared by the Cloud-TPU-VM and GKE paths:
  // the agent-worker-number attribute (seen on nodes where the TPU
  // runtime agent rewrote tpu-env, and on GKE), then the "-w-<N>"
  // hostname suffix GCE gives every multi-host TPU-VM worker. Without
  // this the byte-for-byte v5p-128 golden (slice.worker-id) could not
  // match on the metadata-only path — the exact fallback used when a
  // training job holds the chips and PJRT init fails.
  void FillWorkerIdFallbacks() {
    if (topology_.worker_id < 0) {
      Result<std::string> agent_number =
          client_.Get("instance/attributes/agent-worker-number");
      int worker_id = 0;
      if (agent_number.ok() &&
          ParseNonNegInt(TrimSpace(*agent_number), &worker_id)) {
        topology_.worker_id = worker_id;
      }
    }
    if (topology_.worker_id < 0) {
      Result<std::string> hostname = client_.Get("instance/hostname");
      if (hostname.ok()) {
        // First DNS label of e.g. "t1v-n-abc123-w-3.us-central2-b...".
        std::string label = TrimSpace(*hostname);
        size_t dot = label.find('.');
        if (dot != std::string::npos) label = label.substr(0, dot);
        // Strict all-digit suffix: a nonstandard hostname like
        // "...-w-3x" must not silently yield worker id 3.
        size_t w = label.rfind("-w-");
        int worker_id = 0;
        if (w != std::string::npos &&
            ParseNonNegInt(label.substr(w + 3), &worker_id)) {
          topology_.worker_id = worker_id;
        }
      }
    }
  }

  // The GKE lookup ladder (GKE docs "TPUs in GKE"; no Cloud-TPU-VM
  // attributes exist on these nodes):
  //   chips + family   <- the ct* machine type (ct5lp-hightpu-4t = v5e,
  //                       4 chips on this host)
  //   slice topology   <- cloud.google.com/gke-tpu-topology node label,
  //                       surfaced through the kube-labels attribute
  //   family crosscheck<- cloud.google.com/gke-tpu-accelerator label
  //   worker id        <- TPU_WORKER_ID env (the GKE TPU webhook injects
  //                       it into TPU-requesting pods; present only when
  //                       the operator wires it through)
  // The GCE accelerator-type string ("v5litepod-16") does not exist on
  // GKE, so the tpu.accelerator-type label is honestly absent here.
  Status GkeInit() {
    Result<std::string> machine_type = client_.MachineType();
    if (!machine_type.ok()) {
      return Status::Error("no machine type: " + machine_type.error());
    }
    Result<slice::GkeMachineType> parsed =
        slice::ParseGkeMachineType(*machine_type);
    if (!parsed.ok()) return Status::Error(parsed.error());
    slice::FamilySpec spec = parsed->spec;
    int local_chips = parsed->chips_per_host;

    std::map<std::string, std::string> kube_labels;
    Result<std::string> raw = client_.Get("instance/attributes/kube-labels");
    if (raw.ok()) {
      // kube-labels is "k1=v1,k2=v2,..." (the node labels configured on
      // the node pool).
      for (const std::string& pair : SplitString(TrimSpace(*raw), ',')) {
        size_t eq = pair.find('=');
        if (eq == std::string::npos) continue;
        kube_labels[TrimSpace(pair.substr(0, eq))] =
            TrimSpace(pair.substr(eq + 1));
      }
    }
    auto label = [&kube_labels](const char* key) -> std::string {
      auto it = kube_labels.find(key);
      return it == kube_labels.end() ? "" : it->second;
    };
    std::string accel = label("cloud.google.com/gke-tpu-accelerator");
    if (!accel.empty()) {
      Result<slice::FamilySpec> from_label =
          slice::FamilyFromGkeAccelerator(accel);
      if (from_label.ok() && from_label->family != spec.family) {
        TFD_LOG_WARNING << "gke-tpu-accelerator label (" << accel
                        << ") disagrees with machine type ("
                        << *machine_type << "); trusting the machine type";
      }
    }

    topology_.chips_per_host = local_chips;
    topology_.num_hosts = 1;
    std::string topo = label("cloud.google.com/gke-tpu-topology");
    if (!topo.empty()) {
      Result<slice::Shape> shape = slice::ParseShape(ToLower(topo));
      if (shape.ok()) {
        topology_.topology = shape->ToString();
        int slice_chips = shape->NumChips();
        if (local_chips > 0 && slice_chips >= local_chips) {
          topology_.num_hosts = slice_chips / local_chips;
        }
        topology_.has_wraparound = slice::ComputeIciWrap(spec, *shape);
      }
    }
    const char* worker = std::getenv("TPU_WORKER_ID");
    int worker_id = 0;
    if (worker != nullptr && ParseNonNegInt(TrimSpace(worker), &worker_id)) {
      topology_.worker_id = worker_id;
    }
    // Same metadata-side ladder as the Cloud-TPU-VM path. The
    // authoritative GKE rung is TPU_WORKER_ID above (the GKE TPU webhook
    // injects it into TPU-requesting pods — GKE "TPUs in GKE" docs); the
    // agent-worker-number attribute and "-w-<N>" hostname suffix are
    // Cloud-TPU-VM conventions that are UNVERIFIED on GKE nodes — kept
    // because they are only consulted when TPU_WORKER_ID is absent, and
    // a node that does carry them is better labeled than not.
    FillWorkerIdFallbacks();

    for (int i = 0; i < local_chips; i++) {
      devices_.push_back(std::make_shared<MetadataDevice>(spec));
    }
    TFD_LOG_INFO << "GKE TPU node: " << *machine_type << " ("
                 << spec.product << " x" << local_chips
                 << (topology_.topology.empty()
                         ? std::string(", slice topology unknown")
                         : ", slice " + topology_.topology)
                 << ")";
    return Status::Ok();
  }

  void Shutdown() override {}

  Result<std::vector<DevicePtr>> GetDevices() override { return devices_; }

  Result<std::string> GetLibtpuVersion() override {
    return Result<std::string>::Error(
        "libtpu version unavailable from the metadata backend");
  }
  Result<std::string> GetRuntimeVersion() override {
    return Result<std::string>::Error(
        "runtime version unavailable from the metadata backend");
  }
  Result<TopologyInfo> GetTopology() override { return topology_; }

  std::string Name() const override { return "metadata"; }
  bool TouchesDevices() const override { return false; }

 private:
  gce::MetadataClient client_;
  slice::AcceleratorType accel_;
  TopologyInfo topology_;
  std::vector<DevicePtr> devices_;
};

}  // namespace

ManagerPtr NewMetadataManager(const std::string& metadata_endpoint) {
  return std::make_shared<MetadataManager>(metadata_endpoint);
}

}  // namespace resource
}  // namespace tfd

// Versioned device-state snapshot cache with per-source staleness tiers.
//
// The store is the handoff point between the probe scheduler
// (sched/broker.h) and the label-rendering loop: each probe source
// (PJRT enumeration, GCE metadata, device-health exec) publishes its
// latest result here, and the main loop renders labels from whatever the
// store holds — it never calls a backend directly, so a wedged or slow
// probe can no longer stall the rewrite cadence (VERDICT weak #2: the
// first pass on a busy node used to burn the full 30s PJRT init
// deadline before ANY label reached the node).
//
// Staleness tiers drive the degradation ladder (cmd/ RenderDecision):
//   fresh        — the probe is keeping up; serve at full trust.
//   stale-usable — the probe has missed its cadence (chips busy, probe
//                  wedged) but the facts are recent enough to serve,
//                  marked with snapshot-age + degraded labels.
//   expired      — too old to trust; the ladder falls to the next
//                  source, and /readyz reports not-ready when EVERY
//                  source is expired ("degraded-but-serving is ready;
//                  expired-everything is not").
//
// Thread model: probe workers write (PutOk/PutError), the single
// rendering thread reads; one mutex guards all state, and a condvar
// lets the first rewrite wait briefly for the initial probe round to
// settle instead of racing it.
#pragma once

#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <functional>
#include <map>
#include <mutex>
#include <optional>
#include <string>
#include <vector>

#include "tfd/lm/labeler.h"
#include "tfd/resource/types.h"
#include "tfd/util/status.h"

namespace tfd {
namespace sched {

enum class Tier { kNone, kFresh, kStaleUsable, kExpired };

const char* TierName(Tier tier);

// Ages (seconds since the last successful probe result) below
// `fresh_for_s` are fresh; below `usable_for_s` stale-usable; above,
// expired. Registered per source: an expensive probe with a long
// deadline (PJRT init, health exec) earns a wider fresh window than a
// file read.
struct TierPolicy {
  int fresh_for_s = 120;
  int usable_for_s = 480;
};

// Pure tier rule, unit-testable without a store or a clock.
Tier TierForAge(double age_s, const TierPolicy& policy);

struct Snapshot;

// Content fingerprint of one successful probe result (FNV-1a over the
// label payload and, for device sources, the captured device facts).
// The health state machine (healthsm/) compares consecutive successful
// probes' fingerprints: a source whose facts alternate — 4 chips, then
// 2, then 4 — is flapping even though every probe "succeeds". Measured
// google.com/tpu.health.* values (probe-ms, matmul-tflops, ...) are
// excluded — they legitimately move between re-measures; only the
// structural verdicts (ok / device-<i>-ok / devices-consistent /
// *-degraded / chip count) participate. Never 0 (0 means "no
// fingerprint" to the tracker).
uint64_t SnapshotFingerprint(const Snapshot& snapshot);

// Full-content fingerprint for no-op pass detection (cmd/ PassPlan):
// unlike SnapshotFingerprint it hashes EVERY label — including the
// measured google.com/tpu.health.* values the flap fingerprint excludes
// — because a moved measurement must dirty the pass, or the fast path
// would keep re-serving a stale measurement the forced-slow daemon
// would have republished. Device facts hash the same way. The probe's
// own wall time (probe_seconds) is deliberately NOT hashed here; it is
// exported per source as SourceGeneration::probe_ms so the planner can
// fold it in only when a config actually publishes it (basic-health
// probe-ms). Memoized by the store at PutOk time, so the render loop
// never pays for the hash. Never 0.
uint64_t FullSnapshotFingerprint(const Snapshot& snapshot);

// Cheap per-source dirtiness digest for the pass planner. `generation`
// bumps on every store write (PutOk / PutError / InvalidateAll) — the
// "something landed" counter journaled when a pass is forced slow;
// `content_fingerprint` is the memoized FullSnapshotFingerprint of
// last_ok (0: none yet), which identical re-probes keep stable so a
// healthy steady state plans clean; `tier` is the CURRENT age-derived
// tier (a fresh→stale-usable lapse must dirty the pass even though no
// probe landed).
struct SourceGeneration {
  std::string source;
  uint64_t generation = 0;
  uint64_t content_fingerprint = 0;
  Tier tier = Tier::kNone;
  bool has_snapshot = false;
  bool failing = false;       // last probe errored
  long long probe_ms = 0;     // last_ok probe latency, ms-rounded
};

// One successful probe result. Device sources carry an initialized,
// inert manager view (sched/sources.cc SnapshotManager: every call
// answers from captured data, Init/Shutdown are no-ops); label sources
// (the health exec) carry a label payload instead.
struct Snapshot {
  uint64_t version = 0;  // store-global, bumps per PutOk
  std::chrono::steady_clock::time_point taken_at;
  resource::ManagerPtr manager;  // device sources
  lm::Labels labels;             // label sources
  double probe_seconds = 0;      // how long the probe took
};

// Read-side view of one source, copied under the lock.
struct SourceView {
  bool registered = false;
  bool settled = false;  // at least one result (success or failure)
  bool device_source = false;
  std::optional<Snapshot> last_ok;
  double age_s = -1;  // since last_ok (-1: never succeeded)
  Tier tier = Tier::kNone;
  std::string last_error;
  // Construction-shaped errors (bad fixture path, invalid flags) are
  // fatal regardless of --fail-on-init-error, matching the old
  // factory's "unable to create resource manager" exit.
  bool fatal_error = false;
  int consecutive_failures = 0;
  double backoff_s = 0;  // current failure backoff window (0: healthy)
};

class SnapshotStore {
 public:
  // Defines source order (preferred first — the ladder walks it) and
  // the staleness policy. Must be called before workers start.
  void Register(const std::string& source, const TierPolicy& policy,
                bool device_source);

  void PutOk(const std::string& source, Snapshot snapshot);
  void PutError(const std::string& source, const std::string& error,
                bool fatal = false);
  // Invalidates every snapshot (SIGHUP config regen: stale facts from
  // the previous configuration must not outlive it).
  void InvalidateAll();

  // Event-driven pass loop hook: called (outside the store lock, from
  // the writing probe worker's thread) whenever a write MOVES what the
  // pass planner's signature digests — new content fingerprint, a
  // failing<->ok flip, first settle, InvalidateAll. An identical
  // healthy re-probe deliberately does not fire it: that is what keeps
  // a quiet daemon at zero passes while probe workers keep their own
  // cadence. The callback must be thread-safe (the daemon passes
  // WakeupMux::Notify).
  void SetMovementCallback(std::function<void()> callback);

  // Seconds until the earliest fresh->stale-usable or stale->expired
  // boundary of any source holding a snapshot (-1: none pending). The
  // event-driven loop folds this into its deadline so an age-driven
  // tier change still dirties a pass with no probe write to announce it.
  double SecondsUntilTierChange() const;

  void SetBackoff(const std::string& source, double backoff_s);

  SourceView View(const std::string& source) const;
  std::vector<std::string> Sources() const;        // registration order
  std::vector<std::string> DeviceSources() const;  // registration order

  // The exported generation vector (registration order): one mutex
  // acquisition, no journaling, no snapshot copies — the pass planner
  // calls this every pass, including the sub-millisecond no-op ones.
  std::vector<SourceGeneration> Generations() const;

  // True once every registered source has settled (has at least one
  // result). Waits at most `timeout`; used by the FIRST rewrite so a
  // fast probe round yields full labels immediately while a wedged
  // probe cannot hold the rewrite past the budget.
  bool AllSettled() const;
  bool WaitAllSettled(std::chrono::milliseconds timeout) const;

  // Test hook: shifts a source's last success `seconds` into the past
  // so tier transitions are testable without real sleeps.
  void AgeForTest(const std::string& source, double seconds);

 private:
  struct State {
    TierPolicy policy;
    bool device_source = false;
    bool settled = false;
    std::optional<Snapshot> last_ok;
    // Dirtiness bookkeeping (Generations()): write counter + the
    // memoized full-content fingerprint of last_ok.
    uint64_t generation = 0;
    uint64_t content_fingerprint = 0;
    std::string last_error;
    bool fatal_error = false;
    int consecutive_failures = 0;
    double backoff_s = 0;
    // Last tier observed by a reader — tier is a function of age, so
    // transitions surface at read time; View() journals the change
    // (obs/journal.h "tier-change" events). Mutable: observation
    // bookkeeping, not logical state.
    mutable Tier last_seen_tier = Tier::kNone;
  };

  mutable std::mutex mu_;
  mutable std::condition_variable settled_cv_;
  std::vector<std::string> order_;
  std::map<std::string, State> states_;
  uint64_t next_version_ = 1;
  std::function<void()> movement_callback_;
};

}  // namespace sched
}  // namespace tfd

#include "tfd/sched/broker.h"

#include <algorithm>
#include <chrono>
#include <condition_variable>
#include <mutex>
#include <random>
#include <thread>

#include "tfd/fault/fault.h"
#include "tfd/healthsm/healthsm.h"
#include "tfd/lm/schema.h"
#include "tfd/obs/journal.h"
#include "tfd/obs/metrics.h"
#include "tfd/util/logging.h"
#include "tfd/util/strings.h"
#include "tfd/util/time.h"

namespace tfd {
namespace sched {

double BackoffWithJitter(int consecutive_failures, int initial_s, int max_s,
                         double unit_random) {
  if (initial_s < 1) initial_s = 1;
  if (max_s < initial_s) max_s = initial_s;
  int exponent = std::max(0, consecutive_failures - 1);
  // 2^31 s is already beyond any cap; avoid shift overflow outright.
  double base = exponent >= 31
                    ? static_cast<double>(max_s)
                    : std::min<double>(max_s,
                                       static_cast<double>(initial_s) *
                                           (1u << exponent));
  double jitter = std::clamp(unit_random, 0.0, 1.0);
  return base * (1.0 + 0.25 * jitter);
}

struct BrokerControl {
  std::shared_ptr<SnapshotStore> store;
  std::mutex mu;
  std::condition_variable cv;
  bool stop = false;
  int workers_done = 0;
  // Serializes device-touching probes (exclusive chips).
  std::mutex device_mu;
  std::vector<std::thread> threads;
};

namespace {

// Feeds the health state machine (healthsm/) with this probe round's
// verdict: the per-source observation (with the snapshot's content
// fingerprint, so a source whose facts alternate registers as
// flapping), plus one per-chip observation for every health-exec
// device line ("google.com/tpu.health.device-<i>-ok"), so a single
// flaky chip quarantines alone instead of tainting the whole source.
void ObserveProbeHealth(const ProbeSpec& spec, bool ok,
                        const Snapshot& snapshot, int interval_s) {
  healthsm::HealthTracker& tracker = healthsm::Default();
  double now = WallClockSeconds();
  uint64_t fingerprint = ok ? SnapshotFingerprint(snapshot) : 0;
  // The cadence rides along so the tracker's ghost release can tell a
  // slowly-probed key (hourly health exec, chip lines fed once per exec
  // run) from one that vanished from the probe stream.
  tracker.Observe(spec.name, ok, fingerprint, now, interval_s);
  if (!ok) return;
  constexpr size_t kPrefixLen = sizeof(lm::kHealthDevicePrefix) - 1;
  for (const auto& [key, value] : snapshot.labels) {
    if (!HasPrefix(key, lm::kHealthDevicePrefix)) continue;
    std::string suffix = key.substr(kPrefixLen);  // "<i>-ok"
    constexpr char kOkSuffix[] = "-ok";
    if (suffix.size() <= sizeof(kOkSuffix) - 1 ||
        suffix.compare(suffix.size() - (sizeof(kOkSuffix) - 1),
                       sizeof(kOkSuffix) - 1, kOkSuffix) != 0) {
      continue;
    }
    std::string chip = suffix.substr(0, suffix.size() - 3);
    tracker.Observe(healthsm::ChipKey(chip), value == "true", 0, now,
                    interval_s);
  }
}

// One probe invocation + its metrics + the store write. Shared by the
// oneshot round and the daemon workers; a free function over the
// control block because a detached (wedged) worker may outlive the
// broker object itself. Returns whether the probe succeeded; on
// success *success_interval_s (when non-null) receives the next-probe
// cadence, resolved against spec.interval_for before the snapshot is
// moved into the store.
bool RunProbeOnce(BrokerControl& control, const ProbeSpec& spec,
                  int* success_interval_s = nullptr) {
  obs::Registry& reg = obs::Default();
  reg.GetCounter("tfd_probe_attempts_total",
                 "Probe invocations, per source (steady-state ticks "
                 "included; cache hits inside a backend count as cheap "
                 "successes).",
                 {{"source", spec.name}})
      ->Inc();
  obs::DefaultJournal().Record("probe-start", spec.name,
                               "probe " + spec.name + " starting");
  Snapshot snapshot;
  bool fatal = false;
  auto t0 = std::chrono::steady_clock::now();
  Status s = Status::Ok();
  // Fault point "probe.<source>": fail/errno become a probe failure
  // (exercising the backoff + degradation ladder), a hang has already
  // slept inside Check (stalling THIS worker, never the rewrite loop —
  // which is the decoupling the scheduler exists to prove), and crash
  // never returns (the warm-restart drill).
  fault::Action injected = fault::Check(spec.fault_point.c_str());
  if (injected.kind == fault::Action::Kind::kFail ||
      injected.kind == fault::Action::Kind::kErrno) {
    s = Status::Error(injected.message);
  } else {
    std::unique_lock<std::mutex> device_lock(control.device_mu,
                                             std::defer_lock);
    if (spec.exclusive) device_lock.lock();
    s = spec.probe(&snapshot, &fatal);
  }
  double seconds = obs::SecondsSince(t0);
  reg.GetHistogram("tfd_probe_duration_seconds",
                   "Wall time of one probe invocation, per source.",
                   obs::DurationBuckets(), {{"source", spec.name}})
      ->Observe(seconds);
  if (s.ok()) {
    snapshot.probe_seconds = seconds;
    int next_interval_s =
        spec.interval_for ? spec.interval_for(snapshot) : spec.interval_s;
    if (success_interval_s != nullptr) {
      *success_interval_s = next_interval_s;
    }
    ObserveProbeHealth(spec, true, snapshot, next_interval_s);
    control.store->PutOk(spec.name, std::move(snapshot));
    obs::DefaultJournal().Record(
        "probe-ok", spec.name, "probe " + spec.name + " succeeded",
        {{"duration_s", std::to_string(seconds)}});
    return true;
  }
  reg.GetCounter("tfd_probe_failures_total",
                 "Probe invocations that failed, per source.",
                 {{"source", spec.name}})
      ->Inc();
  // Declare the worst-case failure cadence, not the nominal interval:
  // after a failure the worker sleeps a backoff of up to backoff_max_s,
  // and the tracker's ghost release keys off this declared cadence — a
  // still-probed, still-failing quarantined source must not be released
  // as "no longer observed" mid-backoff.
  ObserveProbeHealth(spec, false, snapshot,
                     std::max(spec.interval_s, spec.backoff_max_s));
  control.store->PutError(spec.name, s.message(), fatal);
  obs::DefaultJournal().Record(
      "probe-fail", spec.name, "probe " + spec.name + " failed",
      {{"duration_s", std::to_string(seconds)},
       {"error", s.message()},
       {"fatal", fatal ? "true" : "false"}});
  TFD_LOG_WARNING << "probe " << spec.name << " failed: " << s.message();
  return false;
}

void WorkerLoop(std::shared_ptr<BrokerControl> control, ProbeSpec spec) {
  // Per-worker seed: jitter spreads a fleet without coordinating — two
  // daemons that failed at the same instant still re-probe at
  // different moments.
  std::mt19937 rng(static_cast<unsigned>(
      std::hash<std::thread::id>()(std::this_thread::get_id())));
  std::uniform_real_distribution<double> unit(0.0, 1.0);
  for (;;) {
    {
      std::unique_lock<std::mutex> lock(control->mu);
      if (control->stop) break;
    }
    int success_interval_s = spec.interval_s;
    bool ok = RunProbeOnce(*control, spec, &success_interval_s);
    double sleep_s;
    if (ok) {
      sleep_s = success_interval_s;
      control->store->SetBackoff(spec.name, 0);
    } else if (spec.backoff_initial_s == spec.backoff_max_s) {
      // Flat policy (the PJRT source): the tick cadence IS the retry
      // contract — the backend's own failure memo provides the real
      // backoff, and jitter would only drift re-probes out of step
      // with the rewrite passes.
      sleep_s = spec.backoff_initial_s;
      control->store->SetBackoff(spec.name, sleep_s);
    } else {
      int consecutive = control->store->View(spec.name).consecutive_failures;
      sleep_s = BackoffWithJitter(consecutive, spec.backoff_initial_s,
                                  spec.backoff_max_s, unit(rng));
      control->store->SetBackoff(spec.name, sleep_s);
      obs::DefaultJournal().Record(
          "probe-backoff", spec.name,
          "probe " + spec.name + " backing off " +
              std::to_string(sleep_s) + "s after " +
              std::to_string(consecutive) + " consecutive failure(s)",
          {{"backoff_s", std::to_string(sleep_s)},
           {"consecutive_failures", std::to_string(consecutive)}});
    }
    // Quarantine clamp (healthsm/): a flapping source re-probes at the
    // slow quarantine-cooldown cadence instead of its normal one —
    // hammering a source already proven unstable only feeds the flap
    // detector, and its labels are held at last-good anyway.
    bool quarantined =
        healthsm::Default().Quarantined(spec.name, WallClockSeconds());
    if (quarantined) {
      int cooldown_s = healthsm::Default().policy().quarantine_cooldown_s;
      if (sleep_s < cooldown_s) sleep_s = cooldown_s;
    }
    obs::Default()
        .GetGauge("tfd_probe_backoff_seconds",
                  "Current failure-backoff window, per source (0: "
                  "healthy).",
                  {{"source", spec.name}})
        ->Set(ok && !quarantined ? 0 : sleep_s);
    // Sleep in <=1s slices so stop requests and rerun_early triggers
    // (chip-count changes) interrupt a long cadence.
    auto wake_at = std::chrono::steady_clock::now() +
                   std::chrono::duration_cast<
                       std::chrono::steady_clock::duration>(
                       std::chrono::duration<double>(sleep_s));
    bool stop_seen = false;
    for (;;) {
      std::unique_lock<std::mutex> lock(control->mu);
      if (control->stop) {
        stop_seen = true;
        break;
      }
      auto now = std::chrono::steady_clock::now();
      if (now >= wake_at) break;
      auto slice = std::min<std::chrono::steady_clock::duration>(
          wake_at - now, std::chrono::seconds(1));
      control->cv.wait_for(lock, slice);
      lock.unlock();
      // A quarantined source must not short-circuit its slow cadence:
      // rerun_early (chip-count changes) is exactly the kind of signal
      // a flapping source emits every pass.
      if (spec.rerun_early && !quarantined && spec.rerun_early()) break;
    }
    if (stop_seen) break;
  }
  {
    std::lock_guard<std::mutex> lock(control->mu);
    control->workers_done++;
  }
  control->cv.notify_all();
}

}  // namespace

ProbeBroker::ProbeBroker(std::shared_ptr<SnapshotStore> store,
                         std::vector<ProbeSpec> specs)
    : control_(std::make_shared<BrokerControl>()), specs_(std::move(specs)) {
  control_->store = std::move(store);
  for (ProbeSpec& spec : specs_) {
    spec.fault_point = "probe." + spec.name;
  }
}

ProbeBroker::~ProbeBroker() { Stop(); }

void ProbeBroker::Start() {
  if (started_) return;
  started_ = true;
  for (const ProbeSpec& spec : specs_) {
    control_->threads.emplace_back(WorkerLoop, control_, spec);
  }
}

void ProbeBroker::Stop(int grace_ms) {
  if (control_ == nullptr) return;
  {
    std::lock_guard<std::mutex> lock(control_->mu);
    if (control_->stop && control_->threads.empty()) return;
    control_->stop = true;
  }
  control_->cv.notify_all();
  // Bounded join: a worker wedged inside a probe (FIFO open, hung
  // dlopen) must not block a SIGHUP reload or clean exit forever.
  {
    std::unique_lock<std::mutex> lock(control_->mu);
    control_->cv.wait_for(
        lock, std::chrono::milliseconds(grace_ms), [this] {
          return control_->workers_done ==
                 static_cast<int>(control_->threads.size());
        });
  }
  bool all_done;
  {
    std::lock_guard<std::mutex> lock(control_->mu);
    all_done = control_->workers_done ==
               static_cast<int>(control_->threads.size());
  }
  for (std::thread& thread : control_->threads) {
    if (!thread.joinable()) continue;
    if (all_done) {
      thread.join();
    } else {
      thread.detach();
    }
  }
  control_->threads.clear();
}

void ProbeBroker::RunOneRound() {
  bool device_served = false;
  for (const ProbeSpec& spec : specs_) {
    if (spec.device_source && device_served) continue;  // chain early-exit
    bool ok = RunProbeOnce(*control_, spec);
    if (spec.device_source && ok) device_served = true;
  }
}

}  // namespace sched
}  // namespace tfd

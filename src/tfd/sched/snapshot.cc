#include "tfd/sched/snapshot.h"

#include "tfd/lm/schema.h"
#include "tfd/obs/journal.h"
#include "tfd/obs/trace.h"
#include "tfd/util/strings.h"

namespace tfd {
namespace sched {

namespace {

// Labels whose content feeds the flap fingerprint. Measured values
// under google.com/tpu.health.* (matmul-tflops, hbm-gbps, probe-ms,
// ...) legitimately move between re-measures — hashing them would mark
// a healthy health exec "unstable" on every run and walk its state
// machine entry to unhealthy on perfectly good silicon. Per-chip
// device-<i>-ok lines are excluded too: each has its own healthsm chip
// entry (broker ObserveProbeHealth), and hashing them here as well
// would let a single flapping chip drag the whole source into
// quarantine. Only the source-level STRUCTURAL facts participate: the
// aggregate verdicts (ok, devices-consistent, *-degraded) and the chip
// count. Every label outside the health prefix is a hardware/identity
// fact and counts.
bool FingerprintedLabel(const std::string& key) {
  // tpu.perf.* measurements re-measure on the slow recheck cadence and
  // legitimately drift a few percent per round; only the DEBOUNCED
  // class verdict is structural. Hashing the raw numbers would mark a
  // healthy re-verification "unstable" and walk the perf source toward
  // quarantine for doing its job.
  if (HasPrefix(key, lm::kPerfPrefix)) return key == lm::kPerfClass;
  // tpu.slice.* labels move exactly when the slice's AGREED state
  // moves — member death, rejoin, an orphan self-demotion removing the
  // whole set, a debounced class change. Those are coordinated
  // transitions (already debounced member-side and leader-side), not
  // per-host probe instability, and counting them here would let one
  // chaotic-but-coherent hour (a member crash-looping, a partition
  // healing) quarantine the slice source — a PER-HOST label freeze
  // that breaks the cross-host agreement the coherence layer exists
  // for. The slice source's flap protection is the verdict protocol
  // itself: demotion needs a full agreement window of silence, orphan
  // needs a full lease of unreachability. (These keys only ever appear
  // in the slice source's snapshot; device-labeler topology labels are
  // rendered later and never enter a Snapshot's label payload.)
  if (HasPrefix(key, "google.com/tpu.slice.")) return false;
  if (!HasPrefix(key, lm::kHealthPrefix)) return true;
  if (HasPrefix(key, lm::kHealthDevicePrefix)) return false;
  const std::string fact = key.substr(sizeof(lm::kHealthPrefix) - 1);
  return fact == "ok" || fact == "devices" || fact == "devices-consistent" ||
         HasSuffix(fact, "-ok") || HasSuffix(fact, "-degraded");
}

}  // namespace

const char* TierName(Tier tier) {
  switch (tier) {
    case Tier::kNone:
      return "none";
    case Tier::kFresh:
      return "fresh";
    case Tier::kStaleUsable:
      return "stale-usable";
    case Tier::kExpired:
      return "expired";
  }
  return "none";
}

Tier TierForAge(double age_s, const TierPolicy& policy) {
  if (age_s < 0) return Tier::kNone;
  if (age_s <= policy.fresh_for_s) return Tier::kFresh;
  if (age_s <= policy.usable_for_s) return Tier::kStaleUsable;
  return Tier::kExpired;
}

namespace {

// Shared FNV-1a core of the two fingerprints: `all_labels` selects the
// full-content hash (no-op pass detection) over the structural-only one
// (healthsm flap detection, FingerprintedLabel above).
uint64_t FingerprintSnapshot(const Snapshot& snapshot, bool all_labels) {
  uint64_t hash = 1469598103934665603ULL;  // FNV-1a 64
  auto mix = [&hash](const std::string& s) {
    for (unsigned char c : s) {
      hash ^= c;
      hash *= 1099511628211ULL;
    }
    hash ^= 0x1f;  // field separator
    hash *= 1099511628211ULL;
  };
  for (const auto& [key, value] : snapshot.labels) {
    if (!all_labels && !FingerprintedLabel(key)) continue;
    mix(key);
    mix(value);
  }
  if (snapshot.manager != nullptr) {
    // SnapshotManager answers from captured data — these reads never
    // touch hardware.
    Result<std::vector<resource::DevicePtr>> devices =
        snapshot.manager->GetDevices();
    if (devices.ok()) {
      mix("devices=" + std::to_string(devices->size()));
      for (const resource::DevicePtr& device : *devices) {
        if (device == nullptr) continue;
        Result<std::string> kind = device->GetKind();
        if (kind.ok()) mix(*kind);
      }
    } else {
      mix("devices-error=" + devices.error());
    }
    Result<std::string> libtpu = snapshot.manager->GetLibtpuVersion();
    if (libtpu.ok()) mix("libtpu=" + *libtpu);
    Result<std::string> runtime = snapshot.manager->GetRuntimeVersion();
    if (runtime.ok()) mix("runtime=" + *runtime);
    Result<resource::TopologyInfo> topology =
        snapshot.manager->GetTopology();
    if (topology.ok()) {
      mix("topology=" + topology->accelerator_type + "/" +
          topology->topology);
    }
  }
  return hash == 0 ? 1 : hash;
}

}  // namespace

uint64_t SnapshotFingerprint(const Snapshot& snapshot) {
  return FingerprintSnapshot(snapshot, /*all_labels=*/false);
}

uint64_t FullSnapshotFingerprint(const Snapshot& snapshot) {
  return FingerprintSnapshot(snapshot, /*all_labels=*/true);
}

void SnapshotStore::Register(const std::string& source,
                             const TierPolicy& policy, bool device_source) {
  std::lock_guard<std::mutex> lock(mu_);
  if (states_.find(source) == states_.end()) order_.push_back(source);
  State& state = states_[source];
  state.policy = policy;
  state.device_source = device_source;
}

void SnapshotStore::SetMovementCallback(std::function<void()> callback) {
  std::lock_guard<std::mutex> lock(mu_);
  movement_callback_ = std::move(callback);
}

void SnapshotStore::PutOk(const std::string& source, Snapshot snapshot) {
  // Memoized off the lock (and off the render path): probe workers pay
  // for the hash so the per-pass planner never does.
  uint64_t content_fingerprint = FullSnapshotFingerprint(snapshot);
  std::function<void()> notify;
  bool moved = false;
  {
    std::lock_guard<std::mutex> lock(mu_);
    auto it = states_.find(source);
    if (it == states_.end()) return;  // unregistered: dropped
    // Movement = anything the pass planner's signature would see move:
    // new content, a recovery (failing -> ok), or the first snapshot.
    // An identical healthy re-probe is NOT movement — this is what
    // keeps a quiet event-driven daemon at zero passes while its probe
    // workers keep their own cadence.
    moved = it->second.content_fingerprint != content_fingerprint ||
            !it->second.last_error.empty() ||
            !it->second.last_ok.has_value();
    snapshot.version = next_version_++;
    if (snapshot.taken_at == std::chrono::steady_clock::time_point()) {
      snapshot.taken_at = std::chrono::steady_clock::now();
    }
    it->second.last_ok = std::move(snapshot);
    it->second.settled = true;
    it->second.generation++;
    it->second.content_fingerprint = content_fingerprint;
    it->second.last_error.clear();
    it->second.fatal_error = false;
    it->second.consecutive_failures = 0;
    it->second.backoff_s = 0;
    if (moved) notify = movement_callback_;
  }
  if (moved) {
    // Probe-snapshot movement is THE primary label-moving origin: mint
    // the causal change id here (before the wakeup fires) so the pass
    // this movement triggers already sees it as active.
    obs::DefaultTrace().Mint("snapshot", source, "probe snapshot moved");
  }
  settled_cv_.notify_all();
  if (notify) notify();  // outside the lock: the callback may Wait()ers
}

void SnapshotStore::PutError(const std::string& source,
                             const std::string& error, bool fatal) {
  std::function<void()> notify;
  bool moved = false;
  {
    std::lock_guard<std::mutex> lock(mu_);
    auto it = states_.find(source);
    if (it == states_.end()) return;
    // A freshly failing source (or a fatal error) moves the planner's
    // signature; a still-failing source re-failing does not.
    moved = it->second.last_error.empty() || fatal || !it->second.settled;
    it->second.settled = true;
    it->second.generation++;
    it->second.last_error = error;
    it->second.fatal_error = fatal;
    it->second.consecutive_failures++;
    if (moved) notify = movement_callback_;
  }
  if (moved) {
    // A fresh failure moves labels too (tier markers, held facts).
    obs::DefaultTrace().Mint("snapshot-error", source, error);
  }
  settled_cv_.notify_all();
  if (notify) notify();
}

void SnapshotStore::InvalidateAll() {
  std::function<void()> notify;
  {
    std::lock_guard<std::mutex> lock(mu_);
    for (auto& [name, state] : states_) {
      state.last_ok.reset();
      state.settled = false;
      state.generation++;
      state.content_fingerprint = 0;
      state.last_error.clear();
      state.fatal_error = false;
      state.consecutive_failures = 0;
      state.backoff_s = 0;
      state.last_seen_tier = Tier::kNone;
    }
    notify = movement_callback_;
  }
  obs::DefaultJournal().Record(
      "snapshots-invalidated", "",
      "every probe-source snapshot invalidated (config regen)");
  obs::DefaultTrace().Mint("config", "",
                           "snapshots invalidated (config regen)");
  if (notify) notify();
}

double SnapshotStore::SecondsUntilTierChange() const {
  std::lock_guard<std::mutex> lock(mu_);
  double soonest = -1;
  auto now = std::chrono::steady_clock::now();
  for (const auto& [name, state] : states_) {
    (void)name;
    if (!state.last_ok.has_value()) continue;
    double age =
        std::chrono::duration<double>(now - state.last_ok->taken_at)
            .count();
    double next = -1;
    if (age < state.policy.fresh_for_s) {
      next = state.policy.fresh_for_s - age;
    } else if (age < state.policy.usable_for_s) {
      next = state.policy.usable_for_s - age;
    }
    if (next >= 0 && (soonest < 0 || next < soonest)) soonest = next;
  }
  return soonest;
}

void SnapshotStore::SetBackoff(const std::string& source, double backoff_s) {
  std::lock_guard<std::mutex> lock(mu_);
  auto it = states_.find(source);
  if (it != states_.end()) it->second.backoff_s = backoff_s;
}

SourceView SnapshotStore::View(const std::string& source) const {
  std::lock_guard<std::mutex> lock(mu_);
  SourceView view;
  auto it = states_.find(source);
  if (it == states_.end()) return view;
  const State& state = it->second;
  view.registered = true;
  view.settled = state.settled;
  view.device_source = state.device_source;
  view.last_ok = state.last_ok;
  view.last_error = state.last_error;
  view.fatal_error = state.fatal_error;
  view.consecutive_failures = state.consecutive_failures;
  view.backoff_s = state.backoff_s;
  if (state.last_ok.has_value()) {
    view.age_s = std::chrono::duration<double>(
                     std::chrono::steady_clock::now() -
                     state.last_ok->taken_at)
                     .count();
  }
  view.tier = TierForAge(view.age_s, state.policy);
  // Tier is a function of age, so transitions become visible at read
  // time; journal the first reader's observation of each change (the
  // flight-recorder record the degradation ladder correlates with).
  if (state.settled && view.tier != state.last_seen_tier) {
    obs::DefaultJournal().Record(
        "tier-change", source,
        source + " snapshot tier " + TierName(state.last_seen_tier) +
            " -> " + TierName(view.tier),
        {{"from", TierName(state.last_seen_tier)},
         {"to", TierName(view.tier)},
         {"age_s", std::to_string(view.age_s)}});
    state.last_seen_tier = view.tier;
  }
  return view;
}

std::vector<std::string> SnapshotStore::Sources() const {
  std::lock_guard<std::mutex> lock(mu_);
  return order_;
}

std::vector<SourceGeneration> SnapshotStore::Generations() const {
  std::lock_guard<std::mutex> lock(mu_);
  std::vector<SourceGeneration> out;
  out.reserve(order_.size());
  auto now = std::chrono::steady_clock::now();
  for (const std::string& name : order_) {
    const State& state = states_.at(name);
    SourceGeneration gen;
    gen.source = name;
    gen.generation = state.generation;
    gen.content_fingerprint = state.content_fingerprint;
    gen.has_snapshot = state.last_ok.has_value();
    gen.failing = !state.last_error.empty();
    double age_s = -1;
    if (state.last_ok.has_value()) {
      age_s = std::chrono::duration<double>(now - state.last_ok->taken_at)
                  .count();
      gen.probe_ms =
          static_cast<long long>(state.last_ok->probe_seconds * 1000);
    }
    // Tier read WITHOUT the View() journaling: the planner's read must
    // stay cheap, and Decide()'s Views this same pass record any
    // transition for the flight recorder.
    gen.tier = TierForAge(age_s, state.policy);
    out.push_back(std::move(gen));
  }
  return out;
}

std::vector<std::string> SnapshotStore::DeviceSources() const {
  std::lock_guard<std::mutex> lock(mu_);
  std::vector<std::string> out;
  for (const std::string& name : order_) {
    if (states_.at(name).device_source) out.push_back(name);
  }
  return out;
}

bool SnapshotStore::AllSettled() const {
  std::lock_guard<std::mutex> lock(mu_);
  for (const auto& [name, state] : states_) {
    if (!state.settled) return false;
  }
  return true;
}

bool SnapshotStore::WaitAllSettled(std::chrono::milliseconds timeout) const {
  std::unique_lock<std::mutex> lock(mu_);
  return settled_cv_.wait_for(lock, timeout, [this] {
    for (const auto& [name, state] : states_) {
      if (!state.settled) return false;
    }
    return true;
  });
}

void SnapshotStore::AgeForTest(const std::string& source, double seconds) {
  std::lock_guard<std::mutex> lock(mu_);
  auto it = states_.find(source);
  if (it == states_.end() || !it->second.last_ok.has_value()) return;
  it->second.last_ok->taken_at -=
      std::chrono::duration_cast<std::chrono::steady_clock::duration>(
          std::chrono::duration<double>(seconds));
}

}  // namespace sched
}  // namespace tfd

// Crash-safe warm restart: the persisted label state.
//
// A restarted daemon used to forget everything: the first pass after a
// crash (or an OOM-kill, or a node-agent restart) re-ran the full probe
// gauntlet, so a node whose PJRT init takes 30s served NO device labels
// for that long — and a crash-looping labeler turned into a scheduling
// outage. The fix: after every successful rewrite the daemon persists
// what it published (labels + per-key provenance + the serving
// decision) to `--state-file`; on boot it loads that file and serves a
// cached-tier warm pass in milliseconds — the persisted labels, marked
// degraded with the TRUE snapshot age (persisted age + downtime) — while
// the probe brokers start from zero in the background.
//
// The file must be trustworthy after any crash, so it is:
//   - written through WriteFileAtomically (rename-into-place, dir fsync);
//   - framed with a magic + FNV-1a checksum header ("TFDSTATE1 <hex>
//     <len>") so a torn or bit-rotted payload is detected, not parsed;
//   - schema-gated (payload "schema" must match kStateSchema);
//   - node-gated (payload "node" must match this node's identity — a
//     hostPath-style volume reattached to a different node must not
//     replay a foreign node's labels);
//   - age-gated (persisted age + downtime past the usable window means
//     the facts expired while we were dead; serve a cold start instead).
// Every rejection reason is distinct, journaled by the caller, and
// counted in tfd_state_restores_total{outcome}.
#pragma once

#include <string>

#include "tfd/lm/merge.h"
#include "tfd/util/status.h"

namespace tfd {
namespace sched {

inline constexpr int kStateSchema = 1;

struct PersistedState {
  int schema = kStateSchema;
  std::string node;       // NODE_NAME env, else hostname
  double saved_at = 0;    // unix wall time of the save
  std::string source;     // serving probe source at save time
  std::string tier;       // its staleness tier
  int level = 0;          // degradation-ladder rung served
  double age_s = 0;       // serving snapshot age at save time
  lm::Labels labels;
  lm::Provenance provenance;
  // Serialized health state machine (healthsm::HealthTracker
  // SerializeJson): a chip quarantine must survive kill -9 — a crash
  // must not launder a flapping source back to trusted. Empty when
  // nothing was tracked (or the file predates the field).
  std::string healthsm_json;
  // Serialized perf characterization (perf::Cache SerializeJson): the
  // amortized micro-benchmark result, carried OPAQUELY here — it has
  // its own schema section with its OWN checksum, validated by
  // perf::ParseCharacterization at restore time, so a torn/corrupt
  // perf section is rejected independently WITHOUT discarding the
  // label payload (and vice versa: a pre-PR-9 file without the field
  // restores labels normally and triggers exactly one
  // characterization). Empty when never characterized.
  std::string perf_json;
  // Serialized slice-coordination state (slice::Coordinator
  // SerializeJson): the lease epoch, the adopted slice verdict, and the
  // join status — a kill -9'd slice LEADER must resume its still-valid
  // lease on restart instead of flapping leadership, and a restarted
  // member keeps serving the agreed slice labels through the probe
  // settle window. Carried opaquely like healthsm_json; a payload for a
  // different slice id is dropped at Configure time. Empty when slice
  // coordination is off or single-host.
  std::string slice_json;
};

// This node's identity for the foreign-node gate.
std::string NodeIdentity();

// Serializes to the framed on-disk format (header line + JSON payload).
std::string SerializeState(const PersistedState& state);

// Parses the framed format, verifying magic, checksum, and schema.
// Errors name the specific gate that failed ("torn or corrupt", ...).
Result<PersistedState> ParseState(const std::string& contents);

// Atomic save (fault point "state.write": `torn` lands a truncated,
// unverifiable file — exactly what mid-write power loss leaves).
Status SaveState(const std::string& path, const PersistedState& state);

// Load + every gate: parse/checksum/schema via ParseState, then node
// identity and age. `now_wall` is unix time; the restored age
// (state.age_s + downtime) must be <= max_age_s.
//
// `stale_healthsm_json` / `stale_perf_json` (optional): when the ONLY
// failed gate is staleness — the state is authentic, checksummed, and
// from this node, just older than the label payload's usable window —
// they receive the persisted healthsm and perf sections. Both have
// their own validity rules instead of the label payload's age gate:
// quarantine has its own clock (quarantine_until is absolute wall
// time), and a characterization is invalidated only by a
// hardware-identity fingerprint change — a crash loop longer than the
// snapshot window must neither launder a flapping chip back to
// trusted nor throw away a measurement the silicon still matches.
// Untouched on success and on every other rejection (corrupt/foreign
// state is never trusted).
// `stale_slice_json` joins them for the same reason: the slice lease's
// truth lives in the apiserver, not in this file's age — a crash loop
// longer than the snapshot window must not make a restarted leader
// forget an epoch it may still hold.
Result<PersistedState> LoadState(const std::string& path,
                                 const std::string& expect_node,
                                 double max_age_s, double now_wall,
                                 std::string* stale_healthsm_json = nullptr,
                                 std::string* stale_perf_json = nullptr,
                                 std::string* stale_slice_json = nullptr);

}  // namespace sched
}  // namespace tfd

// Wakeup multiplexer for the event-driven pass loop.
//
// The legacy loop sleeps a fixed --sleep-interval between passes, so a
// perfectly quiet daemon still plans (and journals, and pays for) one
// pass per interval forever. The multiplexer replaces that sleep with a
// poll(2) over three kernel queues plus an explicit deadline:
//
//   eventfd   — cross-thread Notify(): probe-snapshot movement (the
//               SnapshotStore's movement callback), watch-delivered CR
//               drift (k8s/watch.h), anything else that should run a
//               pass NOW. Reasons ride an atomic bitmask.
//   signalfd  — the daemon's blocked signal set (SIGHUP reload, SIGUSR1
//               dump, SIGINT/SIGTERM/SIGQUIT exit), replacing
//               sigtimedwait without changing any semantics.
//   inotify   — the local byte inputs that feed discovery: the config
//               file and the plugin directory. A change behaves like
//               SIGHUP (these are config-load-time inputs).
//
// plus a timer: the caller computes "the earliest moment any deadline
// contract owes work" (anti-entropy refresh, state-file re-save,
// snapshot tier boundary, interval cadence while degraded/suppressed)
// and Wait() returns kDeadline when it arrives. A quiet daemon
// therefore runs ZERO passes between events; every existing timed
// contract still fires on time as an explicit deadline.
//
// Thread model: Wait() is called only by the pass loop; Notify() from
// any thread.
#pragma once

#include <signal.h>

#include <atomic>
#include <cstdint>
#include <map>
#include <string>
#include <vector>

#include "tfd/util/status.h"

namespace tfd {
namespace sched {

class WakeupMux {
 public:
  enum class Reason : uint32_t {
    kSnapshot = 1,    // probe-snapshot movement (store callback)
    kWatchDrift = 2,  // watch-delivered foreign CR movement
    kInotify = 4,     // config file / plugin dir byte change
    kSignal = 8,      // a blocked signal arrived (see WakeResult.signal)
    kDeadline = 16,   // the caller's timer expired
  };

  struct WakeResult {
    uint32_t reasons = 0;  // Reason bits (a wake can carry several)
    int signal = 0;        // one collected signal (0 = none)
    std::vector<std::string> changed_paths;  // inotify hits this wake
  };

  WakeupMux() = default;
  ~WakeupMux();

  WakeupMux(const WakeupMux&) = delete;
  WakeupMux& operator=(const WakeupMux&) = delete;

  // Creates the eventfd/signalfd/inotify trio. `sigmask` must already
  // be blocked process-wide (main.cc does). Failure means the platform
  // cannot multiplex — the caller falls back to the legacy loop.
  Status Init(const sigset_t& sigmask);

  // Watches one path (file or directory) for modify/create/delete/move.
  // A file that does not exist yet is retried on every Wait(). Safe to
  // call again with the same path (no-op).
  void WatchPath(const std::string& path);

  // Thread-safe: wakes a parked Wait() and tags it with `reason`.
  void Notify(Reason reason);

  // Parks until a notification, a signal, an inotify hit, or
  // `timeout_s` elapses (<= 0: poll without blocking). Drains all ready
  // sources so one wake reports every pending reason.
  WakeResult Wait(double timeout_s);

  bool initialized() const { return event_fd_ >= 0; }

 private:
  void DrainEventFd(WakeResult* result);
  void DrainSignalFd(WakeResult* result);
  void DrainInotify(WakeResult* result);
  void ArmPendingPaths();

  int event_fd_ = -1;
  int signal_fd_ = -1;
  int inotify_fd_ = -1;
  std::atomic<uint32_t> pending_reasons_{0};
  std::map<int, std::string> watch_paths_;       // wd -> path
  std::vector<std::string> unarmed_paths_;       // not yet watchable
};

}  // namespace sched
}  // namespace tfd

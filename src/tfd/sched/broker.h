// Asynchronous probe broker: one worker per probe source.
//
// Each source (PJRT enumeration, GCE metadata, device-health exec, the
// mock/null test backends) gets its own worker thread with its own
// re-probe cadence, retry budget, and exponential backoff with jitter;
// results land in the SnapshotStore (sched/snapshot.h) that the label
// loop renders from. The decoupling is the point: a wedged libtpu (or a
// FIFO-swapped fixture, or a 4-minute health exec) stalls ITS worker,
// never the rewrite cadence.
//
// Two lifecycles:
//   Start()/Stop()  — daemon mode. Workers are real threads; Stop()
//                     signals them and joins with a bounded grace,
//                     detaching any worker wedged inside a probe (the
//                     worker holds only shared_ptr state, so detaching
//                     is safe — its late writes land in a store the
//                     next config load no longer reads).
//   RunOneRound()   — --oneshot. Probes run synchronously on the
//                     calling thread in registration order, stopping at
//                     the first device source that succeeds (the old
//                     fallback chain's early-exit), then label sources.
//                     No threads are ever created.
//
// Backoff: after a failure the worker sleeps
// BackoffWithJitter(consecutive_failures, initial, max, u) seconds —
// initial * 2^(n-1) clamped to max, stretched by up to +25% jitter so a
// fleet of daemons whose chips were grabbed by the same job does not
// re-probe in lockstep. The PJRT source sets initial == max == the
// sleep interval: its real backoff lives in the watchdog's failure memo
// (pjrt_watchdog.cc), which makes per-tick re-probes instant, keeps the
// memoized-failure log visible, and preserves the chip-grab guarantees
// the backend tests pin down.
#pragma once

#include <functional>
#include <memory>
#include <string>
#include <vector>

#include "tfd/sched/snapshot.h"
#include "tfd/util/status.h"

namespace tfd {
namespace sched {

struct ProbeSpec {
  std::string name;
  // "probe.<name>", precomputed by the ProbeBroker constructor so the
  // disarmed fault check on the probe path stays a single relaxed
  // atomic load (no per-attempt string build).
  std::string fault_point;
  // Fills `out` (manager or labels payload) on success. `fatal` set
  // true marks a construction-shaped error (see SourceView::fatal_error).
  std::function<Status(Snapshot* out, bool* fatal)> probe;
  int interval_s = 60;         // re-probe cadence after success
  int backoff_initial_s = 60;  // first failure backoff window
  int backoff_max_s = 900;     // backoff cap
  // Optional per-result cadence override, computed from the successful
  // snapshot before it lands in the store (the health source re-measures
  // a ran-but-unhealthy exec sooner than a healthy one).
  std::function<int(const Snapshot&)> interval_for;
  bool device_source = true;   // participates in the degradation ladder
  // Device-touching probes (PJRT, health exec) serialize on a shared
  // lock: TPU access is exclusive, and the health exec's own jax client
  // must never race the watchdog child for the chips.
  bool exclusive = false;
  // Checked once per second while sleeping between probes; returning
  // true re-probes immediately (the health source re-runs when the
  // enumerated chip count changes).
  std::function<bool()> rerun_early;
};

// Pure backoff rule, unit-tested for its bounds: with base =
// min(max_s, initial_s * 2^(consecutive_failures-1)), returns
// base * (1 + 0.25 * unit_random) — never below base, never above
// 1.25 * base. unit_random must be in [0, 1).
double BackoffWithJitter(int consecutive_failures, int initial_s, int max_s,
                         double unit_random);

// Shared worker state; lives at namespace scope so a detached (wedged)
// worker can keep it alive after the broker object is gone.
struct BrokerControl;

class ProbeBroker {
 public:
  ProbeBroker(std::shared_ptr<SnapshotStore> store,
              std::vector<ProbeSpec> specs);
  ~ProbeBroker();  // Stop()

  ProbeBroker(const ProbeBroker&) = delete;
  ProbeBroker& operator=(const ProbeBroker&) = delete;

  // Daemon mode: one worker thread per spec.
  void Start();
  // Signals workers, joins each for up to `grace_ms` total, detaches
  // stragglers (wedged probes). Idempotent.
  void Stop(int grace_ms = 2000);

  // Oneshot mode: synchronous, in-order, early-exit after the first
  // successful device source. Never spawns a thread.
  void RunOneRound();

 private:
  std::shared_ptr<BrokerControl> control_;
  std::vector<ProbeSpec> specs_;
  bool started_ = false;
};

}  // namespace sched
}  // namespace tfd

#include "tfd/sched/sources.h"

#include <algorithm>
#include <chrono>
#include <memory>
#include <thread>

#include "tfd/gce/metadata.h"
#include "tfd/healthsm/healthsm.h"
#include "tfd/k8s/breaker.h"
#include "tfd/k8s/client.h"
#include "tfd/k8s/desync.h"
#include "tfd/lm/health_exec.h"
#include "tfd/lm/schema.h"
#include "tfd/obs/journal.h"
#include "tfd/obs/metrics.h"
#include "tfd/obs/trace.h"
#include "tfd/perf/perf.h"
#include "tfd/platform/detect.h"
#include "tfd/plugin/plugin.h"
#include "tfd/resource/factory.h"
#include "tfd/sched/state.h"
#include "tfd/slice/coord.h"
#include "tfd/slice/topology.h"
#include "tfd/util/file.h"
#include "tfd/util/http.h"
#include "tfd/util/logging.h"
#include "tfd/util/strings.h"
#include "tfd/util/subprocess.h"
#include "tfd/util/time.h"

namespace tfd {
namespace sched {

namespace {

// An initialized, inert view of one successful backend probe: every
// query answers from captured data, Init/Shutdown are no-ops, so the
// render loop can run the labeler pipeline against it on every pass
// without re-crossing the native-library boundary. Implements
// ProbeTimed so the basic-health probe-ms label reports the REAL
// init+enumeration latency, not the no-op Init's.
class SnapshotManager : public resource::Manager, public resource::ProbeTimed {
 public:
  SnapshotManager(std::string name, bool touches_devices,
                  Result<std::vector<resource::DevicePtr>> devices,
                  Result<std::string> libtpu_version,
                  Result<std::string> runtime_version,
                  Result<resource::TopologyInfo> topology,
                  double probe_seconds)
      : name_(std::move(name)),
        touches_devices_(touches_devices),
        devices_(std::move(devices)),
        libtpu_version_(std::move(libtpu_version)),
        runtime_version_(std::move(runtime_version)),
        topology_(std::move(topology)),
        probe_seconds_(probe_seconds) {}

  Status Init() override { return Status::Ok(); }
  void Shutdown() override {}

  Result<std::vector<resource::DevicePtr>> GetDevices() override {
    return devices_;
  }
  Result<std::string> GetLibtpuVersion() override { return libtpu_version_; }
  Result<std::string> GetRuntimeVersion() override {
    return runtime_version_;
  }
  Result<resource::TopologyInfo> GetTopology() override { return topology_; }
  std::string Name() const override { return name_; }
  bool TouchesDevices() const override { return touches_devices_; }
  double ProbeSeconds() const override { return probe_seconds_; }

 private:
  std::string name_;
  bool touches_devices_;
  Result<std::vector<resource::DevicePtr>> devices_;
  Result<std::string> libtpu_version_;
  Result<std::string> runtime_version_;
  Result<resource::TopologyInfo> topology_;
  double probe_seconds_;
};

Status ProbeDeviceSource(const resource::BackendCandidate& candidate,
                         Snapshot* out, bool* fatal) {
  Result<resource::ManagerPtr> made = candidate.make();
  if (!made.ok()) {
    // Construction errors (missing fixture, bad flags) were fatal in
    // the old factory regardless of --fail-on-init-error; keep that.
    *fatal = true;
    return Status::Error("unable to create resource manager: " +
                         made.error());
  }
  resource::ManagerPtr inner = *made;
  auto t0 = std::chrono::steady_clock::now();
  Status init = inner->Init();
  obs::Default()
      .GetHistogram("tfd_backend_duration_seconds",
                    "Resource-backend construction + init duration, per "
                    "backend actually used.",
                    obs::DurationBuckets(),
                    {{"backend", inner->Name()}})
      ->Observe(obs::SecondsSince(t0));
  if (!init.ok()) {
    return Status::Error("failed to initialize " + inner->Name() +
                         " backend: " + init.message());
  }
  Result<std::vector<resource::DevicePtr>> devices = inner->GetDevices();
  Result<std::string> libtpu = inner->GetLibtpuVersion();
  Result<std::string> runtime = inner->GetRuntimeVersion();
  Result<resource::TopologyInfo> topology = inner->GetTopology();
  double probe_seconds = obs::SecondsSince(t0);
  out->manager = std::make_shared<SnapshotManager>(
      inner->Name(), inner->TouchesDevices(), std::move(devices),
      std::move(libtpu), std::move(runtime), std::move(topology),
      probe_seconds);
  inner->Shutdown();
  return Status::Ok();
}

// Chip count of the newest usable device-touching snapshot, or -1.
int TouchingChipCount(const SnapshotStore& store) {
  for (const std::string& name : store.DeviceSources()) {
    SourceView view = store.View(name);
    if (!view.last_ok.has_value() || view.tier == Tier::kExpired) continue;
    const resource::ManagerPtr& manager = view.last_ok->manager;
    if (manager == nullptr || !manager->TouchesDevices()) continue;
    Result<std::vector<resource::DevicePtr>> devices = manager->GetDevices();
    if (devices.ok() && !devices->empty()) {
      return static_cast<int>(devices->size());
    }
  }
  return -1;
}

// ---- cached perf characterization (perf/) --------------------------------

// The hardware-identity fingerprint the cached characterization is
// keyed by, read from the newest usable device-touching snapshot
// (family from the first device's kind, chip count, topology string,
// libtpu version). Empty when no device snapshot can answer yet.
// `family_out` (optional) receives the family short name for the
// rated-spec lookup.
std::string CurrentPerfFingerprint(const SnapshotStore& store,
                                   std::string* family_out = nullptr) {
  for (const std::string& name : store.DeviceSources()) {
    SourceView view = store.View(name);
    if (!view.last_ok.has_value() || view.tier == Tier::kExpired) continue;
    const resource::ManagerPtr& manager = view.last_ok->manager;
    if (manager == nullptr || !manager->TouchesDevices()) continue;
    Result<std::vector<resource::DevicePtr>> devices = manager->GetDevices();
    if (!devices.ok() || devices->empty()) continue;
    std::string family;
    if ((*devices)[0] != nullptr) {
      Result<std::string> kind = (*devices)[0]->GetKind();
      if (kind.ok()) {
        Result<slice::FamilySpec> spec = slice::FamilyFromDeviceKind(*kind);
        if (spec.ok()) family = spec->family;
      }
    }
    std::string topology;
    Result<resource::TopologyInfo> topo = manager->GetTopology();
    if (topo.ok()) {
      topology = topo->topology.empty() ? topo->accelerator_type
                                        : topo->topology;
    }
    std::string libtpu;
    Result<std::string> lib = manager->GetLibtpuVersion();
    if (lib.ok()) libtpu = *lib;
    if (family_out != nullptr) *family_out = family;
    return perf::Fingerprint(family, static_cast<int>(devices->size()),
                             topology, libtpu);
  }
  return "";
}

// Quarantined chip ids ("health/chip-<i>" healthsm keys), exported to
// the measurement exec as TFD_PERF_EXCLUDE_CHIPS so a chip the health
// ladder already distrusts is EXCLUDED from the aggregate
// characterization — its sickness belongs to its quarantine record,
// not to the node's published class.
std::string QuarantinedChipIds(double now_s) {
  constexpr char kChipKeyPrefix[] = "health/chip-";
  std::vector<std::string> ids;
  for (const std::string& key : healthsm::Default().QuarantinedKeys(now_s)) {
    if (key.rfind(kChipKeyPrefix, 0) == 0) {
      ids.push_back(key.substr(sizeof(kChipKeyPrefix) - 1));
    }
  }
  return JoinStrings(ids, ",");
}

// One perf probe tick: serve the cached characterization when its
// fingerprint still matches the hardware and no recheck is due
// (zero-measurement steady state), else measure — once — under the
// duty-cycle budget. The probe runs on the broker's exclusive lock, so
// a measurement can never race the PJRT watchdog or the health exec
// for the chips.
Status RunPerfProbe(const config::Config& config,
                    const SnapshotStore& store,
                    const std::map<std::string, perf::RatedSpec>& rated,
                    Snapshot* out) {
  const config::Flags& flags = config.flags;
  perf::Cache& cache = perf::Default();
  double now = WallClockSeconds();
  std::optional<perf::Characterization> current = cache.Get();
  std::string family;
  std::string fingerprint = CurrentPerfFingerprint(store, &family);
  if (fingerprint.empty()) {
    if (current.has_value()) {
      // Device workers haven't settled yet (warm-restart cold probes,
      // wedged PJRT) but a cached characterization exists — it was
      // node-gated by the state file, so serve it rather than dropping
      // the perf labels for the settle window; the fingerprint gate
      // re-judges it the moment a device snapshot lands (rerun_early).
      out->labels = perf::BuildLabels(*current);
      return Status::Ok();
    }
    return Status::Error(
        "no device-touching backend snapshot to characterize against");
  }

  std::string reason;
  if (current.has_value() && current->fingerprint != fingerprint) {
    // The cached numbers describe hardware this node no longer has:
    // drop them NOW, before the duty gate — a duty-deferred
    // re-measurement must not keep republishing a different chip's
    // class for the rest of the duty gap (the snapshot below is
    // replaced by an empty label set on the deferral path for the
    // same reason).
    cache.Invalidate();
    healthsm::Default().ResetClassRank("perf");
    current.reset();
    reason = "fingerprint-changed";
    // No label is vouching for a class anymore: the gauge must say so
    // (-1 = none published) instead of advertising the old hardware's
    // class until the re-measure lands.
    obs::Default()
        .GetGauge("tfd_perf_class",
                  "Published performance class: 0 gold, 1 silver, "
                  "2 degraded; -1 while no characterization is published.")
        ->Set(-1);
  } else if (!current.has_value()) {
    reason = "never-characterized";
  } else if (now - current->measured_at >= flags.perf_recheck_interval_s) {
    reason = "recheck-due";
  }

  if (reason.empty()) {
    // Amortized steady state: republish the cached characterization.
    // No device touched, no exec run, nothing journaled — the snapshot
    // content is byte-stable so the pass planner stays clean too.
    out->labels = perf::BuildLabels(*current);
    return Status::Ok();
  }

  if (!cache.AllowedNow(now, flags.perf_duty_cycle_pct)) {
    // Once per owed EPISODE, not per retry tick: a duty gap that
    // outlasts the recheck interval would otherwise drip one event
    // per short-cadence retry for hours and flush the journal ring.
    if (cache.NoteDeferral(reason + "|" + fingerprint)) {
      obs::Default()
          .GetCounter("tfd_perf_deferrals_total",
                      "Perf measurement episodes deferred by the "
                      "--perf-duty-cycle-pct budget (one per owed "
                      "episode, not per retry tick).")
          ->Inc();
      obs::DefaultJournal().Record(
          "perf-deferred", "perf",
          "characterization owed (" + reason +
              ") but deferred: duty-cycle budget exhausted",
          {{"reason", reason}, {"fingerprint", fingerprint}});
    }
    if (current.has_value()) {
      // A recheck-due deferral still serves the (fingerprint-valid)
      // cached facts.
      out->labels = perf::BuildLabels(*current);
      return Status::Ok();
    }
    // No valid characterization to serve: publish an EMPTY perf
    // snapshot so the store stops serving whatever the previous
    // (invalidated) one claimed — no labels beats a different chip's
    // labels — and retry on the short owed cadence.
    return Status::Ok();
  }

  std::string exclude = QuarantinedChipIds(now);
  std::string command = flags.perf_exec;
  {
    // Env rides in via an export prefix like the health exec's chip
    // count: RunCommandCapture runs `sh -c`, so this scopes to the
    // child without mutating the daemon's environment.
    std::string exports;
    if (!exclude.empty()) {
      exports += "export TFD_PERF_EXCLUDE_CHIPS=" + exclude + "; ";
    }
    if (!family.empty()) {
      exports += "export TFD_PERF_FAMILY=" + family + "; ";
    }
    command = exports + command;
  }
  auto t0 = std::chrono::steady_clock::now();
  Result<std::string> text =
      RunCommandCapture(command, flags.perf_exec_timeout_s);
  double seconds = obs::SecondsSince(t0);
  // A failed exec consumed the chips too: it spends duty budget, so a
  // crash-looping measurement command cannot grind the TPU.
  cache.NoteMeasurement(WallClockSeconds(), seconds);
  Result<std::map<std::string, double>> measured =
      text.ok() ? perf::ParseExecOutput(*text)
                : Result<std::map<std::string, double>>::Error(
                      "perf exec failed: " + text.error());
  if (!measured.ok()) {
    if (reason == "fingerprint-changed") {
      // The old characterization is already invalidated and its labels
      // describe different hardware: publish the EMPTY set (replacing
      // the stale snapshot) rather than erroring, which would leave
      // the store serving the previous chip's class until expiry.
      obs::DefaultJournal().Record(
          "perf-measure-failed", "perf",
          "re-characterization after fingerprint change failed; "
          "dropping stale perf labels: " + measured.error(),
          {{"reason", reason},
           {"fingerprint", fingerprint},
           {"error", measured.error()}});
      return Status::Ok();
    }
    // recheck-due / never-characterized: the store's existing snapshot
    // (if any) is still fingerprint-valid — fail the probe normally
    // (backoff + probe-fail journal) and keep serving it.
    return Status::Error(measured.error());
  }

  perf::Characterization c;
  c.fingerprint = fingerprint;
  c.family = family;
  c.measured_at = WallClockSeconds();
  c.measure_seconds = seconds;
  auto value_of = [&measured](const char* key) {
    auto it = measured->find(key);
    return it == measured->end() ? -1.0 : it->second;
  };
  c.matmul_tflops = value_of("matmul-tflops");
  c.hbm_gbps = value_of("hbm-gbps");
  c.ici_gbps = value_of("ici-gbps");
  auto spec = rated.find(family);
  if (spec != rated.end()) {
    c.matmul_pct = perf::PctOfRated(c.matmul_tflops,
                                    spec->second.matmul_tflops);
    c.hbm_pct = perf::PctOfRated(c.hbm_gbps, spec->second.hbm_gbps);
  }
  const int prev_rank =
      current.has_value() ? current->class_rank : -1;
  int raw_rank = perf::ClassifyPct(c.matmul_pct, c.hbm_pct, prev_rank);
  // Fleet-relative floor (ROADMAP #4a): the aggregator's published p10
  // makes "degraded" mean "below THIS fleet's floor" even when the
  // static rated-spec gates pass — gray degradation. Read per
  // measurement (measurements are rare by the amortization contract);
  // a missing/garbled floor file disables the floor loudly, never the
  // measurement.
  if (!flags.perf_fleet_floor_source.empty()) {
    Result<std::string> floor_text =
        ReadFile(flags.perf_fleet_floor_source);
    Result<perf::FleetFloor> floor =
        floor_text.ok()
            ? perf::ParseFleetFloor(*floor_text)
            : Result<perf::FleetFloor>::Error(floor_text.error());
    if (floor.ok()) {
      int floored = perf::ApplyFleetFloor(raw_rank, c.matmul_tflops,
                                          c.hbm_gbps, *floor);
      if (floored != raw_rank) {
        obs::Default()
            .GetCounter("tfd_perf_fleet_floor_demotions_total",
                        "Classifications demoted to degraded by the "
                        "fleet-relative p10 floor "
                        "(--perf-fleet-floor-source).")
            ->Inc();
        obs::DefaultJournal().Record(
            "perf-fleet-floor", "perf",
            "measured below the fleet p10 floor: class " +
                std::string(perf::ClassName(raw_rank)) + " -> degraded",
            {{"matmul_tflops", Fixed3(c.matmul_tflops)},
             {"hbm_gbps", Fixed3(c.hbm_gbps)},
             {"matmul_floor", Fixed3(floor->matmul_p10_tflops)},
             {"hbm_floor", Fixed3(floor->hbm_p10_gbps)}});
        raw_rank = floored;
      }
    } else {
      TFD_LOG_WARNING << "perf-fleet-floor-source "
                      << flags.perf_fleet_floor_source << " unusable ("
                      << floor.error() << "); fleet floor disabled";
    }
  }
  // The health-ladder demotion debounce: one throttled measurement
  // never moves the published class; `unhealthy_after` consecutive
  // demotion verdicts do (and promotions need `recover_after`).
  c.class_rank =
      healthsm::Default().ObserveClassRank("perf", raw_rank, fingerprint, now);
  cache.Set(c);

  obs::Registry& reg = obs::Default();
  reg.GetCounter("tfd_perf_measures_total",
                 "Perf characterization measurement rounds actually run "
                 "(the amortization contract: one per hardware "
                 "fingerprint plus slow rechecks).")
      ->Inc();
  reg.GetHistogram("tfd_perf_measure_duration_seconds",
                   "Wall time of one perf characterization exec.",
                   obs::DurationBuckets())
      ->Observe(seconds);
  reg.GetGauge("tfd_perf_class",
               "Published performance class: 0 gold, 1 silver, "
               "2 degraded; -1 while no characterization is published.")
      ->Set(c.class_rank);
  auto fmt3 = [](double v) { return Fixed3(v); };
  obs::DefaultJournal().Record(
      "perf-measure", "perf",
      "characterized " + fingerprint + " in " + fmt3(seconds) + "s (" +
          reason + "): class " + perf::ClassName(c.class_rank),
      {{"reason", reason},
       {"fingerprint", fingerprint},
       {"duration_s", fmt3(seconds)},
       {"matmul_tflops", fmt3(c.matmul_tflops)},
       {"hbm_gbps", fmt3(c.hbm_gbps)},
       {"ici_gbps", fmt3(c.ici_gbps)},
       {"pct_of_rated", fmt3(c.matmul_pct)},
       {"raw_class", perf::ClassName(raw_rank)},
       {"class", perf::ClassName(c.class_rank)},
       {"excluded_chips", exclude}});
  if (prev_rank >= 0 && c.class_rank != prev_rank) {
    reg.GetCounter("tfd_perf_class_changes_total",
                   "Published performance-class changes, by direction.",
                   {{"direction",
                     c.class_rank > prev_rank ? "demote" : "promote"}})
        ->Inc();
    obs::DefaultJournal().Record(
        "perf-class-change", "perf",
        std::string("performance class ") + perf::ClassName(prev_rank) +
            " -> " + perf::ClassName(c.class_rank),
        {{"from", perf::ClassName(prev_rank)},
         {"to", perf::ClassName(c.class_rank)},
         {"pct_of_rated", fmt3(c.matmul_pct)},
         {"fingerprint", fingerprint}});
  }
  out->labels = perf::BuildLabels(c);
  return Status::Ok();
}

// ---- slice coherence (slice/coord.h) -------------------------------------

// The coordinator's blackboard transport over the hardened k8s client.
// Everything PRs 4/7 built for the sink is inherited: per-request
// deadlines, the k8s.* fault points, request counting, and a circuit
// breaker — its OWN instance (coordination traffic must not trip the
// label sink's circuit, or vice versa) with the same thresholds, plus
// the 429 Retry-After deferral with the fleet desync spread.
class K8sCoordStore : public slice::DocStore {
 public:
  explicit K8sCoordStore(const config::Flags& flags)
      : deadline_ms_(flags.sink_request_deadline_s * 1000) {
    // Cooldown capped at the lease duration: the lease is the
    // protocol's own time constant — a member that orphaned at one
    // lease of silence must probe for the healed blackboard at the
    // same cadence, not sit out the label sink's (longer) cooldown
    // while its peers count it dead.
    breaker_.Configure(
        {flags.sink_breaker_failures,
         static_cast<double>(std::min(flags.sink_breaker_cooldown_s,
                                      flags.slice_lease_duration_s))});
  }

  Status Get(const std::string& name, slice::CoordDoc* doc,
             bool* server_alive) override {
    *server_alive = false;
    Result<k8s::ClusterConfig> cluster = Admit(server_alive);
    if (!cluster.ok()) return cluster.status();
    k8s::WriteOutcome outcome;
    Result<k8s::CoordDocResult> got =
        k8s::GetCoordConfigMap(*cluster, name, server_alive, &outcome);
    Settle(got.ok(), *server_alive, outcome);
    if (!got.ok()) return got.status();
    doc->found = got->found;
    doc->resource_version = got->resource_version;
    doc->data = got->data;
    return Status::Ok();
  }

  Status Patch(const std::string& name,
               const std::map<std::string, std::string>& updates,
               const std::string& precondition_rv, bool create_if_missing,
               bool* conflict, bool* server_alive) override {
    *conflict = false;
    *server_alive = false;
    Result<k8s::ClusterConfig> cluster = Admit(server_alive);
    if (!cluster.ok()) return cluster.status();
    k8s::WriteOutcome outcome;
    Status wrote = k8s::PatchCoordConfigMap(
        *cluster, name, updates, precondition_rv, create_if_missing,
        conflict, server_alive, &outcome);
    // A precondition conflict is the protocol WORKING (a rival writer
    // moved the doc), not a sink failure — it must not feed the
    // breaker's failure streak.
    Settle(wrote.ok() || *conflict, *server_alive, outcome);
    return wrote;
  }

 private:
  Result<k8s::ClusterConfig> Admit(bool* server_alive) {
    if (!breaker_.Allow()) {
      // A deferral is server-directed pacing: the apiserver is ALIVE,
      // and the coordinator's partition/orphan logic must know that.
      *server_alive = breaker_.deferred();
      return Result<k8s::ClusterConfig>::Error(
          breaker_.deferred() ? "slice blackboard write deferred "
                                "(server Retry-After)"
                              : "slice blackboard circuit breaker open");
    }
    Result<k8s::ClusterConfig> cluster = k8s::LoadInClusterConfig();
    if (!cluster.ok()) {
      breaker_.RecordTransientFailure();
      return cluster;
    }
    cluster->request_deadline_ms = deadline_ms_;
    return cluster;
  }

  void Settle(bool ok, bool server_alive,
              const k8s::WriteOutcome& outcome) {
    if (ok) {
      breaker_.RecordSuccess();
    } else if (outcome.retry_after_s > 0) {
      breaker_.Defer(
          k8s::desync::SpreadRetryAfterS(outcome.retry_after_s,
                                         k8s::desync::NodeKey()),
          outcome.apf_rejected ? "APF Retry-After" : "Retry-After");
    } else {
      (void)server_alive;
      breaker_.RecordTransientFailure();
    }
  }

  k8s::CircuitBreaker breaker_;
  int deadline_ms_ = 0;
};

// Peer-relay transport (--slice-relay): GET the peer's live member
// report from its introspection server. Deliberately tight timeouts —
// the fetch runs inside the slice tick, and a peer that is ALSO
// unreachable must cost ~a second, not a sink deadline. A failure here
// is never blackboard contact and never feeds any breaker: "peer
// unreachable too" is an expected answer during a real partition.
class HttpPeerChannel : public slice::PeerChannel {
 public:
  Result<std::string> FetchReport(const std::string& addr) override {
    http::RequestOptions options;
    options.timeout_ms = 1000;
    options.deadline_ms = 1500;
    Result<http::Response> got = http::Request(
        "GET", "http://" + addr + "/debug/slice-report", "", options);
    if (!got.ok()) return Result<std::string>::Error(got.error());
    if (got->status != 200) {
      return Result<std::string>::Error(
          "peer report fetch: HTTP " + std::to_string(got->status));
    }
    return got->body;
  }
};

// This host's view for the member report: shape + freshness from the
// serving-preference device snapshot, healthsm quarantine, the health
// exec's verdict, and the debounced perf class. All already-debounced
// inputs — the report never flaps faster than the layers beneath it.
slice::MemberReport BuildLocalReport(const SnapshotStore& store,
                                     const config::Flags& flags,
                                     const slice::SliceIdentity& identity,
                                     double now) {
  slice::MemberReport report;
  report.host = NodeIdentity();
  report.worker_id = identity.worker_id;
  report.reported_at = now;

  bool device_fresh = false;
  for (const std::string& name : store.DeviceSources()) {
    SourceView view = store.View(name);
    if (!view.last_ok.has_value() || view.tier == Tier::kExpired) continue;
    const resource::ManagerPtr& manager = view.last_ok->manager;
    if (manager == nullptr) continue;
    int chips = 0;
    if (Result<std::vector<resource::DevicePtr>> devices =
            manager->GetDevices();
        devices.ok()) {
      chips = static_cast<int>(devices->size());
    }
    std::string topo;
    if (Result<resource::TopologyInfo> t = manager->GetTopology();
        t.ok()) {
      topo = t->topology.empty() ? t->accelerator_type : t->topology;
    }
    report.shape = "chips=" + std::to_string(chips) +
                   (topo.empty() ? "" : ";topo=" + topo);
    device_fresh = view.tier == Tier::kFresh;
    break;  // store order is serving preference
  }
  bool quarantined = !healthsm::Default().QuarantinedKeys(now).empty();
  bool health_bad = false;
  SourceView health = store.View("health");
  if (health.registered && health.last_ok.has_value() &&
      health.tier != Tier::kExpired) {
    auto it = health.last_ok->labels.find(lm::kHealthOk);
    health_bad =
        it != health.last_ok->labels.end() && it->second == "false";
  }
  report.healthy = device_fresh && !quarantined && !health_bad;
  // Lifecycle fast path: a preemption notice / draining taint the
  // lifecycle source has published rides into the report so the leader
  // can degrade the slice BEFORE this host vanishes. Read from the
  // store (already-debounced upstream), not re-probed here.
  SourceView lifecycle = store.View("lifecycle");
  if (lifecycle.registered && lifecycle.last_ok.has_value() &&
      lifecycle.tier != Tier::kExpired) {
    const lm::Labels& l = lifecycle.last_ok->labels;
    report.preempting =
        l.count(lm::kLifecyclePreemptImminent) > 0 ||
        l.count(lm::kLifecycleDraining) > 0;
  }
  if (flags.perf_characterize) {
    if (std::optional<perf::Characterization> c = perf::Default().Get()) {
      report.perf_class = perf::ClassName(c->class_rank);
    }
  }
  // Peer-relay addr (--slice-relay): where peers fetch this host's live
  // report (/debug/slice-report) when its blackboard copy goes stale.
  // The wildcard/empty bind host is substituted with the node identity
  // — the name a peer can actually route to.
  if (flags.slice_relay && !flags.introspection_addr.empty()) {
    std::string addr = flags.introspection_addr;
    size_t colon = addr.rfind(':');
    std::string host =
        colon == std::string::npos ? addr : addr.substr(0, colon);
    if (host.empty() || host == "0.0.0.0") {
      addr = report.host +
             (colon == std::string::npos ? "" : addr.substr(colon));
    }
    report.addr = addr;
  }
  return report;
}

// Slice identity from the live metadata server (when plausible) plus
// the env overrides — resolved once per config load.
slice::SliceIdentity ResolveSliceIdentity(const config::Flags& flags) {
  std::map<std::string, std::string> tpu_env;
  std::string accel;
  if (platform::MetadataPlausible(flags.metadata_endpoint)) {
    gce::MetadataClient client(flags.metadata_endpoint);
    if (Result<std::map<std::string, std::string>> env = client.TpuEnv();
        env.ok()) {
      tpu_env = *env;
    }
    if (Result<std::string> a = client.AcceleratorType(); a.ok()) {
      accel = *a;
    }
  }
  return slice::DeriveSliceIdentity(tpu_env, accel,
                                    slice::SliceEnvFromProcess());
}

}  // namespace

std::vector<ProbeSpec> BuildProbeSpecs(
    const config::Config& config,
    const std::shared_ptr<SnapshotStore>& store) {
  const config::Flags& flags = config.flags;
  const int sleep_s = flags.sleep_interval_s;
  const bool full_health = flags.device_health == "full";
  std::vector<ProbeSpec> specs;

  for (const resource::BackendCandidate& candidate :
       resource::BackendCandidates(config)) {
    // Probe deadline budget: a tick that legitimately blocks this long
    // (watchdog child at its deadline, health exec holding the shared
    // device lock) must not age the snapshot out of `fresh`.
    int deadline_s = 0;
    if (candidate.name == "pjrt") {
      deadline_s = flags.pjrt_init_timeout_s +
                   (full_health ? flags.health_exec_timeout_s : 0);
    } else if (candidate.name == "metadata") {
      deadline_s = 10;  // a handful of link-local GETs with timeouts
    }
    // 4 ticks of slack before "fresh" lapses: a probe tick slipping a
    // second or two under CI load must not flap the degraded labels on
    // a healthy node (the soak's labels_stable contract).
    TierPolicy policy;
    policy.fresh_for_s = 4 * sleep_s + deadline_s;
    policy.usable_for_s = flags.snapshot_usable_for_s > 0
                              ? flags.snapshot_usable_for_s
                              : policy.fresh_for_s + 6 * sleep_s;
    store->Register(candidate.name, policy, /*device_source=*/true);

    ProbeSpec spec;
    spec.name = candidate.name;
    resource::BackendCandidate captured = candidate;
    spec.probe = [captured](Snapshot* out, bool* fatal) {
      return ProbeDeviceSource(captured, out, fatal);
    };
    // Per-tick probing mirrors the old per-pass backend construction;
    // the backends' own caches (PJRT snapshot cache + failure memo)
    // decide when hardware is actually touched, so chip-grab counts,
    // the per-pass metadata overlay refresh, and the memoized-failure
    // logging all behave exactly as before — just off the rewrite
    // thread. The broker-level backoff therefore stays flat at the
    // tick cadence for pjrt; sources without an internal memo
    // (metadata) get the exponential treatment.
    spec.interval_s = sleep_s;
    spec.backoff_initial_s = sleep_s;
    spec.backoff_max_s =
        candidate.name == "pjrt" ? sleep_s : std::max(60, 8 * sleep_s);
    spec.device_source = true;
    spec.exclusive = candidate.name == "pjrt";
    specs.push_back(std::move(spec));
  }

  if (full_health) {
    TierPolicy policy;
    policy.fresh_for_s = flags.health_exec_interval_s +
                         flags.health_exec_timeout_s + 4 * sleep_s;
    policy.usable_for_s = policy.fresh_for_s + 6 * sleep_s;
    store->Register("health", policy, /*device_source=*/false);

    // The labeler's old in-pass cache keyed staleness on the exec
    // command implicitly (statics) and on the chip count explicitly;
    // here the interval drives re-runs and the chip count re-probes
    // early through rerun_early.
    auto last_chips = std::make_shared<int>(-1);
    config::Config config_copy = config;
    std::shared_ptr<SnapshotStore> store_ref = store;
    ProbeSpec spec;
    spec.name = "health";
    spec.probe = [config_copy, store_ref, last_chips](Snapshot* out,
                                                      bool* /*fatal*/) {
      int chips = TouchingChipCount(*store_ref);
      if (chips < 0) {
        return Status::Error(
            "no device-touching backend snapshot to measure");
      }
      *last_chips = chips;
      out->labels = lm::RunHealthExec(config_copy, chips);
      return Status::Ok();
    };
    spec.interval_s = flags.health_exec_interval_s;
    // A failed/unhealthy probe retries much sooner than a good one
    // re-measures (same 300s rule the in-pass cache used): transient
    // causes — a training job briefly holding the exclusive chips, a
    // probe OOM — must not mark a healthy node unhealthy for a whole
    // --health-exec-interval. A ran-but-unhealthy exec still publishes
    // its ok=false labels; interval_for just re-measures it sooner.
    const int interval_s = flags.health_exec_interval_s;
    spec.interval_for = [interval_s](const Snapshot& snapshot) {
      auto it = snapshot.labels.find(lm::kHealthOk);
      bool unhealthy = it != snapshot.labels.end() && it->second == "false";
      return unhealthy ? std::min(300, interval_s) : interval_s;
    };
    spec.backoff_initial_s =
        std::min(300, std::max(1, flags.health_exec_interval_s));
    spec.backoff_max_s = std::max(flags.health_exec_interval_s,
                                  spec.backoff_initial_s);
    spec.device_source = false;
    spec.exclusive = true;  // the exec's jax client needs the chips
    // Fires when the enumerated chip count CHANGES — including from
    // "no device snapshot yet" (-1) to the first real count, so the
    // startup race against the device workers costs ~a second, not a
    // whole backoff window.
    spec.rerun_early = [store_ref, last_chips] {
      int chips = TouchingChipCount(*store_ref);
      return chips >= 0 && chips != *last_chips;
    };
    specs.push_back(std::move(spec));
  }

  if (flags.perf_characterize) {
    // The perf snapshot's freshness must span the whole recheck
    // cadence (hours): between measurements the worker republishes the
    // cached characterization, and a republish tick slipping under
    // load must not flap the degraded markers.
    TierPolicy policy;
    policy.fresh_for_s = flags.perf_recheck_interval_s +
                         flags.perf_exec_timeout_s + 4 * sleep_s;
    policy.usable_for_s = policy.fresh_for_s + flags.perf_recheck_interval_s;
    store->Register("perf", policy, /*device_source=*/false);

    // Rated specs resolved once per config load: the baked table,
    // overridden by --rated-specs-file when it parses. A broken
    // override keeps the baked copy and says so — a perf source with
    // no rated context still publishes the measured numbers, just no
    // pct-of-rated, which would silently misclassify everything.
    auto rated = std::make_shared<std::map<std::string, perf::RatedSpec>>(
        perf::BakedRatedSpecs());
    if (!flags.rated_specs_file.empty()) {
      Result<std::string> text = ReadFile(flags.rated_specs_file);
      Result<std::map<std::string, perf::RatedSpec>> parsed =
          text.ok() ? perf::ParseRatedSpecs(*text)
                    : Result<std::map<std::string, perf::RatedSpec>>::Error(
                          text.error());
      if (parsed.ok()) {
        *rated = *parsed;
      } else {
        TFD_LOG_ERROR << "rated-specs-file " << flags.rated_specs_file
                      << " unusable (" << parsed.error()
                      << "); keeping the baked table";
      }
    }

    config::Config config_copy = config;
    std::shared_ptr<SnapshotStore> store_ref = store;
    ProbeSpec spec;
    spec.name = "perf";
    spec.probe = [config_copy, store_ref, rated](Snapshot* out,
                                                 bool* /*fatal*/) {
      return RunPerfProbe(config_copy, *store_ref, *rated, out);
    };
    // The nominal cadence is the slow recheck interval; a tick that
    // still OWES a measurement (duty-deferred, or waiting out the
    // device-snapshot startup race) retries at a short cadence
    // instead.
    spec.interval_s = flags.perf_recheck_interval_s;
    const int recheck_s = flags.perf_recheck_interval_s;
    spec.interval_for = [recheck_s](const Snapshot& /*snapshot*/) {
      std::optional<perf::Characterization> c = perf::Default().Get();
      bool owed = !c.has_value() ||
                  WallClockSeconds() - c->measured_at >= recheck_s;
      return owed ? std::min(60, recheck_s) : recheck_s;
    };
    spec.backoff_initial_s = sleep_s;
    spec.backoff_max_s = std::max(60, 8 * sleep_s);
    spec.device_source = false;
    spec.exclusive = true;  // micro-benchmarks need the chips
    // Re-run the probe as soon as the hardware-identity fingerprint
    // visible in the device snapshots stops matching the cached
    // characterization (topology change, driver update, first device
    // snapshot after a cold boot). A STALE cache fires immediately and
    // duty-independently — the probe must at least invalidate it and
    // stop the old hardware's labels from serving, even when the
    // re-measurement itself is duty-deferred; once the cache is empty,
    // further fires wait for the duty budget (the probe's own Ok
    // return then owns the short retry cadence), so a flapping
    // fingerprint cannot turn this 1s-cadence check into a measurement
    // storm or a journal flood.
    const int duty_pct = flags.perf_duty_cycle_pct;
    spec.rerun_early = [store_ref, duty_pct] {
      std::optional<perf::Characterization> c = perf::Default().Get();
      std::string fingerprint = CurrentPerfFingerprint(*store_ref);
      if (fingerprint.empty()) return false;
      if (c.has_value()) return c->fingerprint != fingerprint;
      // Empty cache: a measurement is owed, but a FAILING probe (a
      // misconfigured exec, e.g. the slim image without python3) must
      // ride the worker's exponential backoff — a fast-failing exec's
      // duty gap is milliseconds, and breaking the backoff sleep every
      // 1s slice would spawn it (and journal probe-fail) at ~1 Hz
      // forever.
      if (store_ref->View("perf").consecutive_failures > 0) return false;
      return perf::Default().AllowedNow(WallClockSeconds(), duty_pct);
    };
    specs.push_back(std::move(spec));
  }

  if (!flags.plugin_dir.empty()) {
    // Probe-plugin SDK (plugin/plugin.h): every accepted plugin mounts
    // as its own label source "plugin.<name>", so it inherits the
    // broker's scheduling/deadline/backoff, the store's staleness
    // tiers, healthsm quarantine, the journal, warm-restart label
    // state, and the probe.plugin.<name> fault point — exactly like a
    // first-party source. Discovery (one handshake exec per candidate,
    // each under a short kill deadline) happens here, once per config
    // load, so a broken plugin is rejected loudly at startup/SIGHUP,
    // never mid-round.
    for (const plugin::DiscoveredPlugin& discovered :
         plugin::DiscoverPlugins(flags)) {
      TierPolicy policy;
      // Freshness spans the plugin's own cadence plus its deadline and
      // the usual 4 ticks of slack; a slow-interval plugin (hourly
      // burn-in) keeps serving its last round between runs.
      policy.fresh_for_s =
          discovered.interval_s + discovered.deadline_s + 4 * sleep_s;
      policy.usable_for_s = flags.snapshot_usable_for_s > 0
                                ? flags.snapshot_usable_for_s
                                : policy.fresh_for_s + 6 * sleep_s;
      const std::string source_name =
          plugin::kSourcePrefix + discovered.handshake.name;
      store->Register(source_name, policy, /*device_source=*/false);

      std::shared_ptr<SnapshotStore> store_ref = store;
      ProbeSpec spec;
      spec.name = source_name;
      spec.probe = [discovered, store_ref](Snapshot* out,
                                           bool* /*fatal*/) {
        // Bounded wait for the FIRST device probe round before the
        // first plugin round: TFD_CHIP_COUNT must carry the real
        // enumeration, not a startup-race unknown — a plugin label
        // derived from the unknown would publish a degenerate first
        // value and the governor's hold-down would then pin it for a
        // whole flap window. Settle normally lands in milliseconds;
        // a wedged device worker stops blocking after 5s (the plugin
        // then sees chip count -1 / no TFD_CHIP_COUNT, by contract).
        for (int i = 0; i < 50; i++) {
          bool device_settled = false;
          for (const std::string& name : store_ref->DeviceSources()) {
            if (store_ref->View(name).settled) {
              device_settled = true;
              break;
            }
          }
          if (device_settled) break;
          std::this_thread::sleep_for(std::chrono::milliseconds(100));
        }
        // The enumerated chip count rides into the round's env
        // (TFD_CHIP_COUNT) like the health exec's, so a device-facing
        // plugin can cross-check enumeration without the chips.
        return plugin::RunPluginRound(discovered,
                                      TouchingChipCount(*store_ref),
                                      &out->labels);
      };
      spec.interval_s = discovered.interval_s;
      // Crash-loop containment: failures ride the exponential backoff
      // (the healthsm evidence the supervisor feeds per bad round
      // owns the quarantine decision).
      spec.backoff_initial_s = std::max(1, sleep_s);
      spec.backoff_max_s = std::max(60, 8 * sleep_s);
      spec.device_source = false;
      spec.exclusive = false;  // plugins never get the device lock
      specs.push_back(std::move(spec));
    }
  }

  if (flags.lifecycle_watch && !flags.oneshot) {
    // Preemption-aware lifecycle fast path (ROADMAP #3): the GCE
    // preemption notice gives ~30s of warning — a 60s probe cadence
    // would miss most of it, so this source ticks fast (10s or the
    // sleep interval, whichever is shorter) and its labels are
    // governor-exempt edge triggers: PRESENT only while the condition
    // holds, absent on a normal node (steady-state label sets stay
    // byte-identical with the feature on). The node-taint check rides
    // the k8s client but only once per sleep interval — the fast
    // cadence belongs to the link-local metadata endpoint, not the
    // apiserver.
    const int lifecycle_tick_s = std::min(10, sleep_s);
    TierPolicy policy;
    policy.fresh_for_s = 4 * sleep_s + 10;
    policy.usable_for_s = flags.snapshot_usable_for_s > 0
                              ? flags.snapshot_usable_for_s
                              : policy.fresh_for_s + 6 * sleep_s;
    store->Register("lifecycle", policy, /*device_source=*/false);

    config::Flags flags_copy = flags;
    // Taint-check cache: (last checked wall time, last verdict) shared
    // across rounds so the apiserver sees one GET per sleep interval.
    auto taint_state = std::make_shared<std::pair<double, bool>>(0.0, false);
    // Preemption verdict memo: a failed metadata read keeps the
    // PREVIOUS verdict (same contract as the taint check below) — a
    // transient metadata blip after the notice landed must not clear
    // preempt-imminent and un-degrade a dying slice mid warning
    // window. Only an explicit FALSE (which a live endpoint always
    // serves, preemptible or not) clears it.
    auto preempt_state = std::make_shared<bool>(false);
    auto taint_check_failing = std::make_shared<bool>(false);
    auto last_state = std::make_shared<int>(-1);  // journal on transitions
    ProbeSpec spec;
    spec.name = "lifecycle";
    spec.probe = [flags_copy, taint_state, preempt_state,
                  taint_check_failing, last_state](Snapshot* out,
                                                   bool* /*fatal*/) {
      lm::Labels labels;
      if (platform::MetadataPlausible(flags_copy.metadata_endpoint)) {
        gce::MetadataClient client(flags_copy.metadata_endpoint);
        if (Result<bool> preempted = client.Preempted(); preempted.ok()) {
          *preempt_state = *preempted;
        }
      }
      bool preempting = *preempt_state;
      if (preempting) {
        labels[lm::kLifecyclePreemptImminent] = "true";
      }
      double now = WallClockSeconds();
      if (flags_copy.use_node_feature_api &&
          now - taint_state->first >= flags_copy.sleep_interval_s) {
        if (Result<k8s::ClusterConfig> cluster =
                k8s::LoadInClusterConfig();
            cluster.ok()) {
          cluster->request_deadline_ms =
              flags_copy.sink_request_deadline_s * 1000;
          bool draining = false;
          bool alive = false;
          Status checked = k8s::GetNodeDraining(*cluster, &draining, &alive);
          // Success or failure, the next check waits a sleep interval
          // (the one-GET-per-interval apiserver cadence holds even
          // under a persistent failure).
          taint_state->first = now;
          if (checked.ok()) {
            taint_state->second = draining;
            *taint_check_failing = false;
          } else if (!*taint_check_failing) {
            // A failed check keeps the PREVIOUS verdict: a transient
            // apiserver blip must neither set nor clear the draining
            // label. Logged once per failure streak — a standing RBAC
            // gap (core `nodes get` is a separate grant from the
            // nodefeatures rules) must not be invisible.
            *taint_check_failing = true;
            TFD_LOG_WARNING << "lifecycle taint check: "
                            << checked.message()
                            << " (keeping previous draining verdict)";
          }
        }
      }
      if (taint_state->second) {
        labels[lm::kLifecycleDraining] = "true";
      }
      int state = preempting ? 2 : (taint_state->second ? 1 : 0);
      obs::Default()
          .GetGauge("tfd_lifecycle_state",
                    "Node lifecycle: 0 normal, 1 draining (taint/"
                    "unschedulable), 2 preemption notice received.")
          ->Set(state);
      if (state != *last_state) {
        if (*last_state >= 0 || state > 0) {
          obs::DefaultJournal().Record(
              "lifecycle-change", "lifecycle",
              state == 2   ? "preemption notice received"
              : state == 1 ? "node draining"
                           : "lifecycle normal",
              {{"state", std::to_string(state)}});
          // A lifecycle edge is a label-moving origin (the governor-
          // exempt fast path): mint the change id so the preempt label
          // write — and the slice demotion it triggers — is traceable.
          obs::DefaultTrace().Mint(
              "lifecycle", "lifecycle",
              state == 2   ? "preemption notice"
              : state == 1 ? "node draining"
                           : "lifecycle cleared");
        }
        *last_state = state;
      }
      out->labels = labels;
      return Status::Ok();
    };
    spec.interval_s = lifecycle_tick_s;
    spec.backoff_initial_s = lifecycle_tick_s;
    spec.backoff_max_s = std::max(60, 4 * sleep_s);
    spec.device_source = false;
    spec.exclusive = false;  // metadata + apiserver HTTP only
    specs.push_back(std::move(spec));
  }

  if (flags.slice_coordination && !flags.oneshot) {
    // Multi-host slice coherence: the coordinator is configured every
    // load (state survives a SIGHUP of the same slice) and the "slice"
    // worker ticks it at the rewrite cadence. A host with no derivable
    // slice identity stays single-host — Configure() sets the gauge
    // and no source is registered, so nothing slice-scoped is ever
    // published on a guess.
    slice::SliceIdentity identity = ResolveSliceIdentity(flags);
    // The coordination tick is the LEASE's cadence, not the rewrite's:
    // the holder renews only inside Tick, so ticking slower than the
    // lease (default 30s lease under the default 60s rewrite interval)
    // would leave the lease expired between renewals and churn
    // leadership/epochs every round. A third of the lease gives two
    // missed renewals of margin before failover.
    const int slice_tick_s =
        std::min(sleep_s,
                 std::max(1, flags.slice_lease_duration_s / 3));
    slice::CoordPolicy coord_policy;
    coord_policy.lease_duration_s = flags.slice_lease_duration_s;
    coord_policy.agreement_timeout_s =
        flags.slice_agreement_timeout_s > 0
            ? flags.slice_agreement_timeout_s
            : 2 * slice_tick_s;
    // Rejoin hysteresis: default to 2x the agreement timeout — long
    // enough that a member crash-looping at the detection cadence
    // cannot flap healthy-hosts once per restart, short enough that a
    // genuinely recovered host is re-counted within ~2 detection
    // windows.
    coord_policy.rejoin_dwell_s =
        flags.slice_rejoin_dwell_s > 0
            ? flags.slice_rejoin_dwell_s
            : 2 * coord_policy.agreement_timeout_s;
    // Partition-tolerant fast convergence (ISSUE 19): relay and
    // succession straight from the flags; the hedge additionally needs
    // the CR sink (there is no cross-node label FILE to proxy to). The
    // succession threshold keys off the real renewal cadence — the
    // slice tick — not the lease duration.
    coord_policy.relay = flags.slice_relay;
    coord_policy.succession = flags.slice_succession;
    coord_policy.hedge = flags.sink_hedge && flags.use_node_feature_api;
    coord_policy.renew_cadence_s = slice_tick_s;
    slice::Default().Configure(identity, NodeIdentity(), coord_policy);
    // Configure() may substitute the state file's restored identity
    // when live derivation had NO name evidence (metadata server down
    // at boot) — re-read the coordinator's answer.
    identity = slice::Default().identity();
    if (!identity.valid) {
      TFD_LOG_INFO << "slice coordination enabled but no slice identity "
                      "is derivable from metadata/env; staying in "
                      "single-host mode";
    } else {
      TFD_LOG_INFO << "slice coordination: slice " << identity.slice_id
                   << " worker " << identity.worker_id << "/"
                   << identity.num_hosts << " (identity from "
                   << identity.source << ")";
      // The verdict republishes every tick; freshness mirrors the
      // device sources' slack so one slipped tick never flaps the
      // degradation markers.
      TierPolicy policy;
      policy.fresh_for_s = 4 * sleep_s + 10;
      policy.usable_for_s = flags.snapshot_usable_for_s > 0
                                ? flags.snapshot_usable_for_s
                                : policy.fresh_for_s + 6 * sleep_s;
      store->Register("slice", policy, /*device_source=*/false);

      auto coord_store = std::make_shared<K8sCoordStore>(flags);
      auto peer_channel = std::make_shared<HttpPeerChannel>();
      config::Flags flags_copy = flags;
      std::shared_ptr<SnapshotStore> store_ref = store;
      ProbeSpec spec;
      spec.name = "slice";
      spec.probe = [coord_store, peer_channel, store_ref, flags_copy,
                    identity](Snapshot* out, bool* /*fatal*/) {
        // Until the first device probe round settles, this host's view
        // is UNKNOWN, not unhealthy — a freshly (re)started member
        // must not report itself sick and degrade the whole slice for
        // a boot second (a resumed leader would even WRITE that false
        // verdict). Error out instead: no report, no labels, the
        // blackboard's standing state carries until we can actually
        // answer (~one worker round).
        bool device_settled = false;
        for (const std::string& name : store_ref->DeviceSources()) {
          if (store_ref->View(name).settled) {
            device_settled = true;
            break;
          }
        }
        if (!device_settled) {
          return Status::Error(
              "waiting for the first device probe round before "
              "reporting to the slice");
        }
        double now = WallClockSeconds();
        slice::MemberReport local =
            BuildLocalReport(*store_ref, flags_copy, identity, now);
        // Tick NEVER fails on transport: an orphaned member must
        // publish an EMPTY slice snapshot (self-demotion to
        // single-host labels), not let a stale one keep serving from
        // the store until expiry.
        slice::Coordinator::TickResult result = slice::Default().Tick(
            coord_store.get(), local, now,
            flags_copy.slice_relay ? peer_channel.get() : nullptr);
        out->labels = result.labels;
        // Hedged publishes (--sink-hedge): the coordinator hands the
        // leader one entry per (severed member, verdict change); the
        // SSA write rides the hedge field manager so the member's own
        // apply reclaims its CR on heal. A failed hedge is logged and
        // dropped — the NEXT verdict change re-hedges (newest-wins
        // coalescing; a queue of stale verdicts would be worse than
        // none), and the member's own sink remains the source of truth.
        for (const slice::Coordinator::HedgedPublish& hedge :
             result.hedges) {
          Result<k8s::ClusterConfig> cluster = k8s::LoadInClusterConfig();
          if (!cluster.ok()) {
            TFD_LOG_WARNING << "slice hedge for " << hedge.host
                            << " skipped: " << cluster.error();
            break;
          }
          cluster->request_deadline_ms =
              flags_copy.sink_request_deadline_s * 1000;
          bool alive = false;
          Status hedged = k8s::HedgeNodeFeatureLabels(
              *cluster, hedge.host, hedge.labels, &alive);
          if (!hedged.ok()) {
            TFD_LOG_WARNING << "slice hedge for " << hedge.host << ": "
                            << hedged.message();
          }
        }
        return Status::Ok();
      };
      spec.interval_s = slice_tick_s;
      spec.backoff_initial_s = slice_tick_s;
      spec.backoff_max_s =
          std::max(flags.slice_lease_duration_s, 8 * slice_tick_s);
      spec.device_source = false;
      spec.exclusive = false;  // pure HTTP; never touches the chips
      specs.push_back(std::move(spec));
    }
  }

  return specs;
}

}  // namespace sched
}  // namespace tfd

#include "tfd/sched/sources.h"

#include <algorithm>
#include <chrono>

#include "tfd/lm/health_exec.h"
#include "tfd/lm/schema.h"
#include "tfd/obs/metrics.h"
#include "tfd/resource/factory.h"

namespace tfd {
namespace sched {

namespace {

// An initialized, inert view of one successful backend probe: every
// query answers from captured data, Init/Shutdown are no-ops, so the
// render loop can run the labeler pipeline against it on every pass
// without re-crossing the native-library boundary. Implements
// ProbeTimed so the basic-health probe-ms label reports the REAL
// init+enumeration latency, not the no-op Init's.
class SnapshotManager : public resource::Manager, public resource::ProbeTimed {
 public:
  SnapshotManager(std::string name, bool touches_devices,
                  Result<std::vector<resource::DevicePtr>> devices,
                  Result<std::string> libtpu_version,
                  Result<std::string> runtime_version,
                  Result<resource::TopologyInfo> topology,
                  double probe_seconds)
      : name_(std::move(name)),
        touches_devices_(touches_devices),
        devices_(std::move(devices)),
        libtpu_version_(std::move(libtpu_version)),
        runtime_version_(std::move(runtime_version)),
        topology_(std::move(topology)),
        probe_seconds_(probe_seconds) {}

  Status Init() override { return Status::Ok(); }
  void Shutdown() override {}

  Result<std::vector<resource::DevicePtr>> GetDevices() override {
    return devices_;
  }
  Result<std::string> GetLibtpuVersion() override { return libtpu_version_; }
  Result<std::string> GetRuntimeVersion() override {
    return runtime_version_;
  }
  Result<resource::TopologyInfo> GetTopology() override { return topology_; }
  std::string Name() const override { return name_; }
  bool TouchesDevices() const override { return touches_devices_; }
  double ProbeSeconds() const override { return probe_seconds_; }

 private:
  std::string name_;
  bool touches_devices_;
  Result<std::vector<resource::DevicePtr>> devices_;
  Result<std::string> libtpu_version_;
  Result<std::string> runtime_version_;
  Result<resource::TopologyInfo> topology_;
  double probe_seconds_;
};

Status ProbeDeviceSource(const resource::BackendCandidate& candidate,
                         Snapshot* out, bool* fatal) {
  Result<resource::ManagerPtr> made = candidate.make();
  if (!made.ok()) {
    // Construction errors (missing fixture, bad flags) were fatal in
    // the old factory regardless of --fail-on-init-error; keep that.
    *fatal = true;
    return Status::Error("unable to create resource manager: " +
                         made.error());
  }
  resource::ManagerPtr inner = *made;
  auto t0 = std::chrono::steady_clock::now();
  Status init = inner->Init();
  obs::Default()
      .GetHistogram("tfd_backend_duration_seconds",
                    "Resource-backend construction + init duration, per "
                    "backend actually used.",
                    obs::DurationBuckets(),
                    {{"backend", inner->Name()}})
      ->Observe(obs::SecondsSince(t0));
  if (!init.ok()) {
    return Status::Error("failed to initialize " + inner->Name() +
                         " backend: " + init.message());
  }
  Result<std::vector<resource::DevicePtr>> devices = inner->GetDevices();
  Result<std::string> libtpu = inner->GetLibtpuVersion();
  Result<std::string> runtime = inner->GetRuntimeVersion();
  Result<resource::TopologyInfo> topology = inner->GetTopology();
  double probe_seconds = obs::SecondsSince(t0);
  out->manager = std::make_shared<SnapshotManager>(
      inner->Name(), inner->TouchesDevices(), std::move(devices),
      std::move(libtpu), std::move(runtime), std::move(topology),
      probe_seconds);
  inner->Shutdown();
  return Status::Ok();
}

// Chip count of the newest usable device-touching snapshot, or -1.
int TouchingChipCount(const SnapshotStore& store) {
  for (const std::string& name : store.DeviceSources()) {
    SourceView view = store.View(name);
    if (!view.last_ok.has_value() || view.tier == Tier::kExpired) continue;
    const resource::ManagerPtr& manager = view.last_ok->manager;
    if (manager == nullptr || !manager->TouchesDevices()) continue;
    Result<std::vector<resource::DevicePtr>> devices = manager->GetDevices();
    if (devices.ok() && !devices->empty()) {
      return static_cast<int>(devices->size());
    }
  }
  return -1;
}

}  // namespace

std::vector<ProbeSpec> BuildProbeSpecs(
    const config::Config& config,
    const std::shared_ptr<SnapshotStore>& store) {
  const config::Flags& flags = config.flags;
  const int sleep_s = flags.sleep_interval_s;
  const bool full_health = flags.device_health == "full";
  std::vector<ProbeSpec> specs;

  for (const resource::BackendCandidate& candidate :
       resource::BackendCandidates(config)) {
    // Probe deadline budget: a tick that legitimately blocks this long
    // (watchdog child at its deadline, health exec holding the shared
    // device lock) must not age the snapshot out of `fresh`.
    int deadline_s = 0;
    if (candidate.name == "pjrt") {
      deadline_s = flags.pjrt_init_timeout_s +
                   (full_health ? flags.health_exec_timeout_s : 0);
    } else if (candidate.name == "metadata") {
      deadline_s = 10;  // a handful of link-local GETs with timeouts
    }
    // 4 ticks of slack before "fresh" lapses: a probe tick slipping a
    // second or two under CI load must not flap the degraded labels on
    // a healthy node (the soak's labels_stable contract).
    TierPolicy policy;
    policy.fresh_for_s = 4 * sleep_s + deadline_s;
    policy.usable_for_s = flags.snapshot_usable_for_s > 0
                              ? flags.snapshot_usable_for_s
                              : policy.fresh_for_s + 6 * sleep_s;
    store->Register(candidate.name, policy, /*device_source=*/true);

    ProbeSpec spec;
    spec.name = candidate.name;
    resource::BackendCandidate captured = candidate;
    spec.probe = [captured](Snapshot* out, bool* fatal) {
      return ProbeDeviceSource(captured, out, fatal);
    };
    // Per-tick probing mirrors the old per-pass backend construction;
    // the backends' own caches (PJRT snapshot cache + failure memo)
    // decide when hardware is actually touched, so chip-grab counts,
    // the per-pass metadata overlay refresh, and the memoized-failure
    // logging all behave exactly as before — just off the rewrite
    // thread. The broker-level backoff therefore stays flat at the
    // tick cadence for pjrt; sources without an internal memo
    // (metadata) get the exponential treatment.
    spec.interval_s = sleep_s;
    spec.backoff_initial_s = sleep_s;
    spec.backoff_max_s =
        candidate.name == "pjrt" ? sleep_s : std::max(60, 8 * sleep_s);
    spec.device_source = true;
    spec.exclusive = candidate.name == "pjrt";
    specs.push_back(std::move(spec));
  }

  if (full_health) {
    TierPolicy policy;
    policy.fresh_for_s = flags.health_exec_interval_s +
                         flags.health_exec_timeout_s + 4 * sleep_s;
    policy.usable_for_s = policy.fresh_for_s + 6 * sleep_s;
    store->Register("health", policy, /*device_source=*/false);

    // The labeler's old in-pass cache keyed staleness on the exec
    // command implicitly (statics) and on the chip count explicitly;
    // here the interval drives re-runs and the chip count re-probes
    // early through rerun_early.
    auto last_chips = std::make_shared<int>(-1);
    config::Config config_copy = config;
    std::shared_ptr<SnapshotStore> store_ref = store;
    ProbeSpec spec;
    spec.name = "health";
    spec.probe = [config_copy, store_ref, last_chips](Snapshot* out,
                                                      bool* /*fatal*/) {
      int chips = TouchingChipCount(*store_ref);
      if (chips < 0) {
        return Status::Error(
            "no device-touching backend snapshot to measure");
      }
      *last_chips = chips;
      out->labels = lm::RunHealthExec(config_copy, chips);
      return Status::Ok();
    };
    spec.interval_s = flags.health_exec_interval_s;
    // A failed/unhealthy probe retries much sooner than a good one
    // re-measures (same 300s rule the in-pass cache used): transient
    // causes — a training job briefly holding the exclusive chips, a
    // probe OOM — must not mark a healthy node unhealthy for a whole
    // --health-exec-interval. A ran-but-unhealthy exec still publishes
    // its ok=false labels; interval_for just re-measures it sooner.
    const int interval_s = flags.health_exec_interval_s;
    spec.interval_for = [interval_s](const Snapshot& snapshot) {
      auto it = snapshot.labels.find(lm::kHealthOk);
      bool unhealthy = it != snapshot.labels.end() && it->second == "false";
      return unhealthy ? std::min(300, interval_s) : interval_s;
    };
    spec.backoff_initial_s =
        std::min(300, std::max(1, flags.health_exec_interval_s));
    spec.backoff_max_s = std::max(flags.health_exec_interval_s,
                                  spec.backoff_initial_s);
    spec.device_source = false;
    spec.exclusive = true;  // the exec's jax client needs the chips
    // Fires when the enumerated chip count CHANGES — including from
    // "no device snapshot yet" (-1) to the first real count, so the
    // startup race against the device workers costs ~a second, not a
    // whole backoff window.
    spec.rerun_early = [store_ref, last_chips] {
      int chips = TouchingChipCount(*store_ref);
      return chips >= 0 && chips != *last_chips;
    };
    specs.push_back(std::move(spec));
  }

  return specs;
}

}  // namespace sched
}  // namespace tfd

#include "tfd/sched/wakeup.h"

#include <poll.h>
#include <string.h>
#include <sys/eventfd.h>
#include <sys/inotify.h>
#include <sys/signalfd.h>
#include <unistd.h>

#include <algorithm>

namespace tfd {
namespace sched {

namespace {
constexpr uint32_t kInotifyMask = IN_MODIFY | IN_CLOSE_WRITE | IN_CREATE |
                                  IN_DELETE | IN_MOVED_TO | IN_MOVED_FROM |
                                  IN_MOVE_SELF | IN_DELETE_SELF;
}  // namespace

WakeupMux::~WakeupMux() {
  if (event_fd_ >= 0) close(event_fd_);
  if (signal_fd_ >= 0) close(signal_fd_);
  if (inotify_fd_ >= 0) close(inotify_fd_);
}

Status WakeupMux::Init(const sigset_t& sigmask) {
  event_fd_ = eventfd(0, EFD_NONBLOCK | EFD_CLOEXEC);
  if (event_fd_ < 0) {
    return Status::Error(std::string("eventfd: ") + strerror(errno));
  }
  signal_fd_ = signalfd(-1, &sigmask, SFD_NONBLOCK | SFD_CLOEXEC);
  if (signal_fd_ < 0) {
    return Status::Error(std::string("signalfd: ") + strerror(errno));
  }
  inotify_fd_ = inotify_init1(IN_NONBLOCK | IN_CLOEXEC);
  if (inotify_fd_ < 0) {
    return Status::Error(std::string("inotify_init1: ") + strerror(errno));
  }
  return Status::Ok();
}

void WakeupMux::WatchPath(const std::string& path) {
  if (path.empty() || inotify_fd_ < 0) return;
  for (const auto& [wd, existing] : watch_paths_) {
    (void)wd;
    if (existing == path) return;
  }
  if (std::find(unarmed_paths_.begin(), unarmed_paths_.end(), path) !=
      unarmed_paths_.end()) {
    return;
  }
  int wd = inotify_add_watch(inotify_fd_, path.c_str(), kInotifyMask);
  if (wd >= 0) {
    watch_paths_[wd] = path;
  } else {
    // Not there yet (a config file created later): re-armed per Wait().
    unarmed_paths_.push_back(path);
  }
}

void WakeupMux::ArmPendingPaths() {
  for (auto it = unarmed_paths_.begin(); it != unarmed_paths_.end();) {
    int wd = inotify_add_watch(inotify_fd_, it->c_str(), kInotifyMask);
    if (wd >= 0) {
      watch_paths_[wd] = *it;
      it = unarmed_paths_.erase(it);
    } else {
      ++it;
    }
  }
}

void WakeupMux::Notify(Reason reason) {
  pending_reasons_.fetch_or(static_cast<uint32_t>(reason),
                            std::memory_order_relaxed);
  if (event_fd_ >= 0) {
    uint64_t one = 1;
    // Best-effort: a full counter still wakes the poller.
    (void)!write(event_fd_, &one, sizeof(one));
  }
}

void WakeupMux::DrainEventFd(WakeResult* result) {
  uint64_t value = 0;
  while (read(event_fd_, &value, sizeof(value)) > 0) {
  }
  result->reasons |= pending_reasons_.exchange(0, std::memory_order_relaxed);
}

void WakeupMux::DrainSignalFd(WakeResult* result) {
  signalfd_siginfo info;
  // One signal per wake: the loop handles it (reload/exit/dump), then
  // the next Wait() collects any further queued signal immediately
  // (the fd stays readable, so poll returns at once).
  ssize_t n = read(signal_fd_, &info, sizeof(info));
  if (n == static_cast<ssize_t>(sizeof(info))) {
    result->reasons |= static_cast<uint32_t>(Reason::kSignal);
    result->signal = static_cast<int>(info.ssi_signo);
  }
}

void WakeupMux::DrainInotify(WakeResult* result) {
  char buf[4096] __attribute__((aligned(__alignof__(inotify_event))));
  while (true) {
    ssize_t len = read(inotify_fd_, buf, sizeof(buf));
    if (len <= 0) break;
    for (char* p = buf; p < buf + len;) {
      auto* event = reinterpret_cast<inotify_event*>(p);
      auto it = watch_paths_.find(event->wd);
      if (it != watch_paths_.end()) {
        result->reasons |= static_cast<uint32_t>(Reason::kInotify);
        if (std::find(result->changed_paths.begin(),
                      result->changed_paths.end(),
                      it->second) == result->changed_paths.end()) {
          result->changed_paths.push_back(it->second);
        }
        if (event->mask & (IN_DELETE_SELF | IN_MOVE_SELF | IN_IGNORED)) {
          // The watched inode is gone; re-arm by path when (if) it
          // reappears — an atomic rename-over (WriteFileAtomically's
          // pattern) lands here on every rewrite of the file.
          unarmed_paths_.push_back(it->second);
          watch_paths_.erase(it);
        }
      }
      p += sizeof(inotify_event) + event->len;
    }
  }
}

WakeupMux::WakeResult WakeupMux::Wait(double timeout_s) {
  WakeResult result;
  ArmPendingPaths();
  // A Notify() that raced in before this Wait still has its eventfd
  // byte pending, so poll returns immediately — no lost wakeups.
  pollfd fds[3];
  fds[0] = {event_fd_, POLLIN, 0};
  fds[1] = {signal_fd_, POLLIN, 0};
  fds[2] = {inotify_fd_, POLLIN, 0};
  int timeout_ms =
      timeout_s <= 0 ? 0
                     : static_cast<int>(std::min(timeout_s * 1000.0,
                                                 2147483000.0));
  int ready = poll(fds, 3, timeout_ms);
  if (ready <= 0) {
    // Timeout (or EINTR, folded into a deadline pass: spurious at
    // worst — the planner decides whether any work is owed).
    result.reasons |= static_cast<uint32_t>(Reason::kDeadline);
    // Collect any reason that raced in without an eventfd write.
    result.reasons |=
        pending_reasons_.exchange(0, std::memory_order_relaxed);
    return result;
  }
  if (fds[0].revents & POLLIN) DrainEventFd(&result);
  if (fds[1].revents & POLLIN) DrainSignalFd(&result);
  if (fds[2].revents & POLLIN) DrainInotify(&result);
  if (result.reasons == 0) {
    // poll woke for something we could not attribute (e.g. an inotify
    // event for an already-forgotten wd): treat as a deadline check.
    result.reasons = static_cast<uint32_t>(Reason::kDeadline);
  }
  return result;
}

}  // namespace sched
}  // namespace tfd

#include "tfd/sched/state.h"

#include <string.h>
#include <unistd.h>

#include <cstdio>
#include <cstdlib>

#include "tfd/fault/fault.h"
#include "tfd/util/file.h"
#include "tfd/util/jsonlite.h"
#include "tfd/util/strings.h"

namespace tfd {
namespace sched {

namespace {

constexpr char kMagic[] = "TFDSTATE1";

}  // namespace

std::string NodeIdentity() {
  if (const char* node = std::getenv("NODE_NAME")) {
    if (*node != '\0') return node;
  }
  char host[256] = {0};
  if (gethostname(host, sizeof(host) - 1) == 0 && host[0] != '\0') {
    return host;
  }
  return "unknown";
}

std::string SerializeState(const PersistedState& state) {
  std::string payload = "{\"schema\":" + std::to_string(state.schema) +
                        ",\"node\":" + jsonlite::Quote(state.node) +
                        ",\"saved_at\":" + Fixed3(state.saved_at) +
                        ",\"source\":" + jsonlite::Quote(state.source) +
                        ",\"tier\":" + jsonlite::Quote(state.tier) +
                        ",\"level\":" + std::to_string(state.level) +
                        ",\"age_s\":" + Fixed3(state.age_s) +
                        ",\"labels\":" +
                        jsonlite::SerializeStringMap(state.labels) +
                        ",\"provenance\":{";
  bool first = true;
  for (const auto& [key, from] : state.provenance) {
    if (!first) payload += ",";
    first = false;
    payload += jsonlite::Quote(key) + ":{\"labeler\":" +
               jsonlite::Quote(from.labeler) + ",\"source\":" +
               jsonlite::Quote(from.source) + ",\"tier\":" +
               jsonlite::Quote(from.tier) + ",\"age_s\":" +
               Fixed3(from.age_s) + "}";
  }
  payload += "}";
  // Health state machine state rides along (quarantine must survive
  // kill -9). Embedded as a raw JSON object; absent/empty means none.
  if (!state.healthsm_json.empty()) {
    payload += ",\"healthsm\":" + state.healthsm_json;
  }
  // Perf characterization rides along as its OWN schema section: the
  // object carries an inner checksum (perf::SerializeCharacterization)
  // so its integrity is judged independently of this outer frame.
  if (!state.perf_json.empty()) {
    payload += ",\"perf\":" + state.perf_json;
  }
  // Slice-coordination state rides along (a kill -9'd leader must
  // resume its lease without a leadership flap). Opaque, like healthsm.
  if (!state.slice_json.empty()) {
    payload += ",\"slice\":" + state.slice_json;
  }
  payload += "}";
  return std::string(kMagic) + " " + HexU64(Fnv1a64(payload)) + " " +
         std::to_string(payload.size()) + "\n" + payload;
}

Result<PersistedState> ParseState(const std::string& contents) {
  using R = Result<PersistedState>;
  size_t newline = contents.find('\n');
  if (newline == std::string::npos) {
    return R::Error("state file torn or corrupt (no header line)");
  }
  std::string header = contents.substr(0, newline);
  std::string payload = contents.substr(newline + 1);
  char checksum_hex[32] = {0};
  unsigned long long length = 0;
  char magic[16] = {0};
  if (sscanf(header.c_str(), "%15s %31s %llu", magic, checksum_hex,
             &length) != 3 ||
      std::string(magic) != kMagic) {
    return R::Error("state file has an unrecognized header (not " +
                    std::string(kMagic) + ")");
  }
  if (payload.size() != length) {
    return R::Error("state file torn or corrupt (payload " +
                    std::to_string(payload.size()) + " bytes, header says " +
                    std::to_string(length) + ")");
  }
  if (HexU64(Fnv1a64(payload)) != checksum_hex) {
    return R::Error("state file torn or corrupt (checksum mismatch)");
  }
  Result<jsonlite::ValuePtr> parsed = jsonlite::Parse(payload);
  if (!parsed.ok()) {
    return R::Error("state payload unparseable: " + parsed.error());
  }
  const jsonlite::Value& root = **parsed;
  jsonlite::ValuePtr schema = root.Get("schema");
  if (!schema || schema->kind != jsonlite::Value::Kind::kNumber) {
    return R::Error("state payload missing schema");
  }
  if (static_cast<int>(schema->number_value) != kStateSchema) {
    return R::Error("state schema " +
                    std::to_string(static_cast<int>(schema->number_value)) +
                    " unsupported (want " + std::to_string(kStateSchema) +
                    ")");
  }
  PersistedState state;
  auto get_string = [&root](const char* key, std::string* out) {
    jsonlite::ValuePtr v = root.Get(key);
    if (v && v->kind == jsonlite::Value::Kind::kString) {
      *out = v->string_value;
    }
  };
  auto get_number = [&root](const char* key, double* out) {
    jsonlite::ValuePtr v = root.Get(key);
    if (v && v->kind == jsonlite::Value::Kind::kNumber) {
      *out = v->number_value;
    }
  };
  get_string("node", &state.node);
  get_string("source", &state.source);
  get_string("tier", &state.tier);
  get_number("saved_at", &state.saved_at);
  get_number("age_s", &state.age_s);
  double level = 0;
  get_number("level", &level);
  state.level = static_cast<int>(level);
  jsonlite::ValuePtr labels = root.Get("labels");
  if (!labels || labels->kind != jsonlite::Value::Kind::kObject) {
    return R::Error("state payload missing labels");
  }
  for (const auto& [key, value] : labels->object_items) {
    if (value->kind != jsonlite::Value::Kind::kString) {
      return R::Error("state label '" + key + "' is not a string");
    }
    state.labels[key] = value->string_value;
  }
  if (state.labels.empty()) {
    return R::Error("state payload carries no labels");
  }
  jsonlite::ValuePtr provenance = root.Get("provenance");
  if (provenance && provenance->kind == jsonlite::Value::Kind::kObject) {
    for (const auto& [key, value] : provenance->object_items) {
      if (value->kind != jsonlite::Value::Kind::kObject) continue;
      lm::LabelProvenance from;
      jsonlite::ValuePtr field = value->Get("labeler");
      if (field && field->kind == jsonlite::Value::Kind::kString) {
        from.labeler = field->string_value;
      }
      field = value->Get("source");
      if (field && field->kind == jsonlite::Value::Kind::kString) {
        from.source = field->string_value;
      }
      field = value->Get("tier");
      if (field && field->kind == jsonlite::Value::Kind::kString) {
        from.tier = field->string_value;
      }
      field = value->Get("age_s");
      if (field && field->kind == jsonlite::Value::Kind::kNumber) {
        from.age_s = field->number_value;
      }
      state.provenance[key] = from;
    }
  }
  jsonlite::ValuePtr healthsm = root.Get("healthsm");
  if (healthsm && healthsm->kind == jsonlite::Value::Kind::kObject) {
    state.healthsm_json = jsonlite::Serialize(*healthsm);
  }
  // The perf section is carried opaquely, NOT validated here: its own
  // checksum gate (perf::ParseCharacterization) decides its fate at
  // restore time, so a corrupt perf section can be rejected without
  // discarding the label payload this parse just accepted. A non-object
  // value still rides through — the inner gate is the one that
  // journals the rejection.
  jsonlite::ValuePtr perf = root.Get("perf");
  if (perf) state.perf_json = jsonlite::Serialize(*perf);
  jsonlite::ValuePtr slice = root.Get("slice");
  if (slice && slice->kind == jsonlite::Value::Kind::kObject) {
    state.slice_json = jsonlite::Serialize(*slice);
  }
  return state;
}

Status SaveState(const std::string& path, const PersistedState& state) {
  std::string framed = SerializeState(state);
  if (fault::Action injected = fault::Check("state.write")) {
    if (injected.kind == fault::Action::Kind::kTorn) {
      // Mid-write power loss: a non-atomic partial write lands at the
      // destination — precisely what the checksum gate must catch on
      // the next boot. Deliberately bypasses the atomic writer.
      FILE* f = fopen(path.c_str(), "w");
      if (f != nullptr) {
        fwrite(framed.data(), 1, framed.size() / 2, f);
        fclose(f);
      }
      return Status::Ok();  // the daemon believes the save worked
    }
    if (injected.kind == fault::Action::Kind::kErrno) {
      return Status::Error("state save failed: " + path + ": " +
                           strerror(injected.errno_value) + " (injected)");
    }
    if (injected.kind == fault::Action::Kind::kFail) {
      return Status::Error("state save failed: " + injected.message);
    }
  }
  return WriteFileAtomically(path, framed);
}

Result<PersistedState> LoadState(const std::string& path,
                                 const std::string& expect_node,
                                 double max_age_s, double now_wall,
                                 std::string* stale_healthsm_json,
                                 std::string* stale_perf_json,
                                 std::string* stale_slice_json) {
  using R = Result<PersistedState>;
  Result<std::string> contents = ReadFile(path);
  if (!contents.ok()) return R::Error(contents.error());
  Result<PersistedState> state = ParseState(*contents);
  if (!state.ok()) return state;
  if (!expect_node.empty() && state->node != expect_node) {
    return R::Error("state file is from node '" + state->node +
                    "', this is '" + expect_node +
                    "' (refusing foreign labels)");
  }
  double downtime_s = now_wall - state->saved_at;
  if (downtime_s < 0) downtime_s = 0;  // clock stepped back across boot
  double restored_age_s = state->age_s + downtime_s;
  if (restored_age_s > max_age_s) {
    if (stale_healthsm_json != nullptr) {
      *stale_healthsm_json = state->healthsm_json;
    }
    if (stale_perf_json != nullptr) {
      *stale_perf_json = state->perf_json;
    }
    if (stale_slice_json != nullptr) {
      *stale_slice_json = state->slice_json;
    }
    return R::Error("state snapshot age " +
                    std::to_string(static_cast<long long>(restored_age_s)) +
                    "s exceeds the usable window (" +
                    std::to_string(static_cast<long long>(max_age_s)) +
                    "s); facts expired while down");
  }
  state->age_s = restored_age_s;
  return state;
}

}  // namespace sched
}  // namespace tfd

// Probe-source construction: maps the configured backends (and the
// device-health exec) onto broker ProbeSpecs + store registrations.
//
// Device sources come from resource::BackendCandidates(config) — the
// same ordered candidate list the old fallback chain used (pjrt before
// metadata before null), so the degradation ladder walks exactly the
// order --backend=auto used to try synchronously. Each probe constructs
// a FRESH manager, Init()s it (the PJRT watchdog's snapshot cache and
// failure memo make steady-state re-probes instant and chip-free), and
// captures the result into an inert SnapshotManager the render loop can
// use any number of times without re-touching hardware.
//
// The health source (--device-health=full only) runs the health exec on
// its own cadence with the measured chip count from the newest
// device-touching snapshot, re-running early when that count changes —
// the same staleness rules the labeler's in-pass cache used, now off
// the rewrite path.
#pragma once

#include <memory>
#include <vector>

#include "tfd/config/config.h"
#include "tfd/sched/broker.h"
#include "tfd/sched/snapshot.h"

namespace tfd {
namespace sched {

// Registers every source (with its staleness policy) in `store` and
// returns the matching broker specs. Call once per config load.
std::vector<ProbeSpec> BuildProbeSpecs(
    const config::Config& config,
    const std::shared_ptr<SnapshotStore>& store);

}  // namespace sched
}  // namespace tfd

// Assert-based unit tests for the tfd core, run as one binary by the pytest
// tier-1 harness (tests/test_unit_cpp.py). Covers the pure-logic layers the
// reference covers with table-driven Go tests (internal/lm/*_test.go,
// internal/resource/*_test.go): yamllite, the slice-shape grammar, the
// family table, config precedence, label generation per strategy, sharing,
// and the fallback decorator.
#include <arpa/inet.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <sys/wait.h>
#include <unistd.h>

#include <atomic>
#include <cassert>
#include <condition_variable>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <fstream>
#include <iostream>
#include <limits>
#include <mutex>
#include <thread>

#include "tfd/agg/agg.h"
#include "tfd/config/config.h"
#include "tfd/config/yamllite.h"
#include "tfd/fault/fault.h"
#include "tfd/gce/metadata.h"
#include "tfd/healthsm/healthsm.h"
#include "tfd/k8s/breaker.h"
#include "tfd/k8s/client.h"
#include "tfd/k8s/desync.h"
#include "tfd/k8s/watch.h"
#include "tfd/lm/fragments.h"
#include "tfd/lm/governor.h"
#include "tfd/lm/labels.h"
#include "tfd/lm/merge.h"
#include "tfd/lm/schema.h"
#include "tfd/lm/slice_strategy.h"
#include "tfd/lm/tpu_labeler.h"
#include "tfd/obs/journal.h"
#include "tfd/obs/metrics.h"
#include "tfd/obs/server.h"
#include "tfd/placement/placement.h"
#include "tfd/remedy/remedy.h"
#include "tfd/perf/perf.h"
#include "tfd/pjrt/pjrt_binding.h"
#include "tfd/platform/detect.h"
#include "tfd/plugin/plugin.h"
#include "tfd/resource/factory.h"
#include "tfd/resource/types.h"
#include "tfd/sched/broker.h"
#include "tfd/sched/snapshot.h"
#include "tfd/sched/state.h"
#include "tfd/sched/wakeup.h"
#include "tfd/slice/coord.h"
#include "tfd/slice/shape.h"
#include "tfd/util/time.h"
#include "tfd/slice/topology.h"
#include "tfd/util/file.h"
#include "tfd/util/http.h"
#include "tfd/util/jsonlite.h"
#include "tfd/util/logging.h"
#include "tfd/util/strings.h"
#include "tfd/util/subprocess.h"

namespace tfd {
namespace {

int g_failures = 0;
int g_checks = 0;

#define CHECK_TRUE(cond)                                              \
  do {                                                                \
    g_checks++;                                                       \
    if (!(cond)) {                                                    \
      g_failures++;                                                   \
      std::cerr << "FAIL " << __FILE__ << ":" << __LINE__ << ": "     \
                << #cond << std::endl;                                \
    }                                                                 \
  } while (0)

#define CHECK_EQ(a, b)                                                 \
  do {                                                                 \
    g_checks++;                                                        \
    auto va = (a);                                                     \
    auto vb = (b);                                                     \
    if (!(va == vb)) {                                                 \
      g_failures++;                                                    \
      std::cerr << "FAIL " << __FILE__ << ":" << __LINE__ << ": "      \
                << #a << " == " << #b << " (got '" << va << "' vs '"   \
                << vb << "')" << std::endl;                            \
    }                                                                  \
  } while (0)

std::string WriteTemp(const std::string& contents) {
  static int counter = 0;
  std::string path = "/tmp/tfd-unit-" + std::to_string(getpid()) + "-" +
                     std::to_string(counter++) + ".yaml";
  std::ofstream out(path);
  out << contents;
  return path;
}

void TestStrings() {
  CHECK_EQ(TrimSpace("  a b \n"), "a b");
  CHECK_EQ(JoinStrings({"a", "b"}, "x"), "axb");
  CHECK_EQ(SanitizeLabelValue("Google Compute Engine"),
           "Google-Compute-Engine");
  CHECK_EQ(SanitizeLabelValue("ct5lp-hightpu-4t"), "ct5lp-hightpu-4t");
  CHECK_EQ(ReplaceAll("a.b.c", ".", "-"), "a-b-c");

  // StrictLabelValue: apiserver-valid output even from hostile input —
  // alphanumeric ends after sanitize+truncate (advisor r2, medium).
  CHECK_EQ(StrictLabelValue("ok-value"), "ok-value");
  CHECK_EQ(StrictLabelValue("-leading.and.trailing_"),
           "leading.and.trailing");
  CHECK_EQ(StrictLabelValue("---"), "");
  CHECK_EQ(StrictLabelValue(""), "");
  // 63-char cap applied before end-trim: 62 'a's then '-' then more text
  // truncates at 63 ('a'*62 + '-') and trims to the 62 'a's.
  CHECK_EQ(StrictLabelValue(std::string(62, 'a') + "-tail"),
           std::string(62, 'a'));

  int v = -1;
  CHECK_TRUE(ParseNonNegInt("3", &v) && v == 3);
  CHECK_TRUE(ParseNonNegInt("0", &v) && v == 0);
  CHECK_TRUE(ParseNonNegInt("2147483647", &v) && v == 2147483647);
  CHECK_TRUE(!ParseNonNegInt("3abc", &v));   // stoi would return 3
  CHECK_TRUE(!ParseNonNegInt("-3", &v));
  CHECK_TRUE(!ParseNonNegInt("", &v));
  CHECK_TRUE(!ParseNonNegInt(" 3", &v));
  CHECK_TRUE(!ParseNonNegInt("2147483648", &v));
}

void TestYamlLite() {
  auto doc = yamllite::Parse(R"(
version: v1
flags:
  oneshot: true
  sleepInterval: 60s   # comment
  outputFile: "/tmp/x y"
sharing:
  timeSlicing:
    resources:
    - name: google.com/tpu
      replicas: 2
    - name: other
      rename: tpu-shared
      replicas: 4
)");
  CHECK_TRUE(doc.ok());
  if (!doc.ok()) {
    std::cerr << "yaml parse error: " << doc.error() << std::endl;
    return;
  }
  const yamllite::Node& root = **doc;
  CHECK_EQ(root.Get("version")->AsString().value(), "v1");
  CHECK_EQ(root.Get("flags")->Get("oneshot")->AsBool().value(), true);
  CHECK_EQ(root.Get("flags")->Get("sleepInterval")->AsString().value(),
           "60s");
  CHECK_EQ(root.Get("flags")->Get("outputFile")->AsString().value(),
           "/tmp/x y");
  auto resources =
      root.Get("sharing")->Get("timeSlicing")->Get("resources");
  CHECK_TRUE(resources != nullptr);
  CHECK_EQ(static_cast<int>(resources->list_items.size()), 2);
  CHECK_EQ(resources->list_items[0]->Get("name")->AsString().value(),
           "google.com/tpu");
  CHECK_EQ(resources->list_items[1]->Get("replicas")->AsInt().value(), 4);

  // Errors.
  CHECK_TRUE(!yamllite::Parse("a: {flow: no}").ok());
  CHECK_TRUE(!yamllite::Parse("\tb: 1").ok());
}

void TestShapeGrammar() {
  auto s = slice::ParseShape("2x2x1");
  CHECK_TRUE(s.ok());
  CHECK_EQ(s->NumChips(), 4);
  CHECK_EQ(s->ToString(), "2x2x1");
  CHECK_EQ(slice::ParseShape("4x4")->NumChips(), 16);
  CHECK_TRUE(!slice::ParseShape("4").ok());
  CHECK_TRUE(!slice::ParseShape("1x2x3x4").ok());
  CHECK_TRUE(!slice::ParseShape("0x2").ok());
  CHECK_TRUE(!slice::ParseShape("2xax1").ok());
}

void TestFamilyTable() {
  auto v5e = slice::LookupFamily("v5e");
  CHECK_TRUE(v5e.ok());
  CHECK_EQ(v5e->product, "tpu-v5e");
  CHECK_EQ(v5e->hbm_mib, 16384LL);
  CHECK_TRUE(slice::LookupFamily("v9").ok() == false);

  auto from_kind = slice::FamilyFromDeviceKind("TPU v5 lite");
  CHECK_TRUE(from_kind.ok());
  CHECK_EQ(from_kind->family, "v5e");
  CHECK_EQ(slice::FamilyFromDeviceKind("TPU v4")->family, "v4");
  CHECK_EQ(slice::FamilyFromDeviceKind("TPU v5p")->family, "v5p");
  CHECK_EQ(slice::FamilyFromDeviceKind("TPU v5")->family, "v5p");

  // Accelerator types: v2/v3/v4/v5p count TensorCores, v5e/v6e count chips.
  auto v2 = slice::ParseAcceleratorType("v2-8");
  CHECK_TRUE(v2.ok());
  CHECK_EQ(v2->num_chips, 4);
  CHECK_EQ(v2->num_cores, 8);
  auto v5lite = slice::ParseAcceleratorType("v5litepod-16");
  CHECK_TRUE(v5lite.ok());
  CHECK_EQ(v5lite->num_chips, 16);
  CHECK_EQ(v5lite->spec.family, "v5e");
  auto v5p = slice::ParseAcceleratorType("v5p-128");
  CHECK_TRUE(v5p.ok());
  CHECK_EQ(v5p->num_chips, 64);
  CHECK_TRUE(!slice::ParseAcceleratorType("v2-7").ok());
  CHECK_TRUE(!slice::ParseAcceleratorType("x100-8").ok());

  // Default topologies: must match Google's published shapes, including the
  // ascending-with-1s-last convention ("2x2x1", not "1x2x2").
  CHECK_EQ(slice::DefaultTopology(*slice::LookupFamily("v5e"), 16)
               ->ToString(),
           "4x4");
  CHECK_EQ(slice::DefaultTopology(*slice::LookupFamily("v5e"), 8)
               ->ToString(),
           "2x4");
  CHECK_EQ(slice::DefaultTopology(*slice::LookupFamily("v5e"), 1)
               ->ToString(),
           "1x1");
  const slice::FamilySpec v4spec = *slice::LookupFamily("v4");
  CHECK_EQ(slice::DefaultTopology(v4spec, 4)->ToString(), "2x2x1");
  CHECK_EQ(slice::DefaultTopology(v4spec, 8)->ToString(), "2x2x2");
  CHECK_EQ(slice::DefaultTopology(v4spec, 16)->ToString(), "2x2x4");
  CHECK_EQ(slice::DefaultTopology(v4spec, 32)->ToString(), "2x4x4");
  CHECK_EQ(slice::DefaultTopology(v4spec, 64)->ToString(), "4x4x4");
  CHECK_EQ(slice::DefaultTopology(v4spec, 128)->ToString(), "4x4x8");
  CHECK_EQ(slice::DefaultTopology(v4spec, 256)->ToString(), "4x8x8");
  CHECK_EQ(slice::DefaultTopology(*slice::LookupFamily("v5p"), 64)
               ->ToString(),
           "4x4x4");
}

void TestIciWrap() {
  // Table-driven over Google-published v4/v5p slice shapes (Cloud TPU
  // system-architecture docs: torus links — incl. twisted tori — exist
  // only when every dimension is a multiple of 4; everything else is a
  // mesh). The old ">= 64 chips" heuristic would wrongly wrap custom
  // shapes like 2x8x8.
  const slice::FamilySpec v4 = *slice::LookupFamily("v4");
  const slice::FamilySpec v5p = *slice::LookupFamily("v5p");
  struct Case {
    const slice::FamilySpec& family;
    const char* shape;
    bool wrap;
  };
  const Case cases[] = {
      {v4, "2x2x1", false},    // v4-8
      {v4, "2x2x2", false},    // v4-16: mesh, not a torus
      {v4, "2x2x4", false},    // v4-32
      {v4, "2x4x4", false},    // v4-64
      {v4, "4x4x4", true},     // v4-128: one full cube
      {v4, "4x4x8", true},     // v4-256: twisted torus — still wrapped
      {v4, "4x8x8", true},     // v4-512
      {v4, "8x8x8", true},     // v4-1024
      {v4, "8x8x12", true},    // v4-1536
      {v4, "8x8x16", true},    // v4-2048
      {v4, "8x16x16", true},   // v4-4096
      {v4, "2x8x8", false},    // 128 chips but a 2-dim: mesh (old
                               // heuristic said true)
      {v5p, "2x2x1", false},   // v5p-8
      {v5p, "4x4x4", true},    // v5p-128
      {v5p, "4x4x8", true},    // v5p-256
      {v5p, "4x4x12", true},   // v5p-384
      {v5p, "4x8x8", true},    // v5p-512
      {v5p, "2x2x16", false},  // 64 chips, custom column: mesh
  };
  for (const Case& c : cases) {
    Result<slice::Shape> shape = slice::ParseShape(c.shape);
    CHECK_TRUE(shape.ok());
    bool wrap = slice::ComputeIciWrap(c.family, *shape);
    if (wrap != c.wrap) {
      g_failures++;
      std::cerr << "ICI wrap mismatch for " << c.family.family << " "
                << c.shape << ": got " << wrap << ", want " << c.wrap
                << "\n";
    }
    g_checks++;
  }
  // 2D families: only the full pod is a torus.
  const slice::FamilySpec v5e = *slice::LookupFamily("v5e");
  CHECK_TRUE(!slice::ComputeIciWrap(v5e, *slice::ParseShape("4x4")));
  CHECK_TRUE(!slice::ComputeIciWrap(v5e, *slice::ParseShape("8x16")));
  CHECK_TRUE(slice::ComputeIciWrap(v5e, *slice::ParseShape("16x16")));
  const slice::FamilySpec v2 = *slice::LookupFamily("v2");
  CHECK_TRUE(!slice::ComputeIciWrap(v2, *slice::ParseShape("4x4")));
  CHECK_TRUE(slice::ComputeIciWrap(v2, *slice::ParseShape("16x16")));
  const slice::FamilySpec v3 = *slice::LookupFamily("v3");
  CHECK_TRUE(slice::ComputeIciWrap(v3, *slice::ParseShape("32x32")));
  CHECK_TRUE(!slice::ComputeIciWrap(v3, *slice::ParseShape("16x16")));
}

void TestParserRobustness() {
  // Hostile-input sweep over every hand-rolled parser: all of them sit
  // on untrusted surfaces (metadata attributes an agent rewrites, config
  // files, the probe child's pipe), so malformed input must come back as
  // an error Result — never a crash, hang, or UB. The CI sanitizer job
  // runs this same sweep under ASan/UBSan, which is where lifetime or
  // overflow bugs in the parsers would actually surface.
  const std::vector<std::string> corpus = {
      "", " ", "\n", std::string("\0x", 2), "{", "}", "[", "]",
      "{\"a\":", "[1,",
      "{\"a\" 1}", "\"unterminated", "nul", "tru", "-", "1e",
      "0x10", "{\"a\":1}}", "\xff\xfe", "\"\\u12\"", "\"\\q\"",
      ": : :", "- - -", "a\n  b: c\n x", "key: [unclosed",
      "4x", "x4", "4x4x4x4", "0x4", "-1x4", "4xx4", "99999999999x2",
      "1h2", "5", "-5s", "h", "99999999999999999999s",
      "v5litepod-", "-8", "v99-8", "v5p-3", "v5litepod-0",
      "ct-hightpu-4t", "ct5lp-hightpu-t", "ct5lp-hightpu-99999999999t",
  };
  for (const std::string& text : corpus) {
    // Each parser either errors or yields a well-defined value; the
    // CHECKs only count the calls — the sanitizer asserts the rest.
    (void)jsonlite::Parse(text);
    (void)yamllite::Parse(text);
    (void)slice::ParseShape(text);
    (void)config::ParseDurationSeconds(text);
    (void)slice::ParseAcceleratorType(text);
    (void)slice::ParseGkeMachineType(text);
    (void)gce::ParseTpuEnv(text);
    int v = 0;
    (void)ParseNonNegInt(text, &v);
    g_checks++;
  }
  // The deep-nesting guard: a 4 KiB bracket bomb must error via the
  // depth cap (jsonlite.cc:51), not recurse to a stack overflow.
  CHECK_TRUE(!jsonlite::Parse(std::string(4096, '[')).ok());
  // And specific malformed inputs really are rejected, not silently
  // coerced.
  CHECK_TRUE(!slice::ParseShape("4xx4").ok());
  CHECK_TRUE(!slice::ParseAcceleratorType("v5p-3").ok());
  CHECK_TRUE(!slice::ParseGkeMachineType("ct5lp-hightpu-t").ok());
  CHECK_TRUE(!config::ParseDurationSeconds("-5s").ok());
}

void TestDuration() {
  CHECK_EQ(config::ParseDurationSeconds("60s").value(), 60);
  CHECK_EQ(config::ParseDurationSeconds("1m30s").value(), 90);
  CHECK_EQ(config::ParseDurationSeconds("2h").value(), 7200);
  CHECK_EQ(config::ParseDurationSeconds("45").value(), 45);
  CHECK_TRUE(!config::ParseDurationSeconds("abc").ok());
}

void TestConfigPrecedence() {
  std::string config_path = WriteTemp(R"(
version: v1
flags:
  oneshot: true
  sliceStrategy: mixed
  sleepInterval: 10s
)");
  // CLI wins over file; file fills the rest.
  setenv("TFD_SLEEP_INTERVAL", "30s", 1);  // env wins over file
  std::vector<std::string> args = {"tfd", "--slice-strategy=single",
                                   "--config-file", config_path};
  std::vector<char*> argv;
  for (auto& a : args) argv.push_back(a.data());
  auto loaded = config::Load(static_cast<int>(argv.size()), argv.data());
  unsetenv("TFD_SLEEP_INTERVAL");
  CHECK_TRUE(loaded.ok());
  if (loaded.ok()) {
    CHECK_EQ(loaded->config.flags.slice_strategy, "single");  // CLI
    CHECK_EQ(loaded->config.flags.sleep_interval_s, 30);      // env
    CHECK_EQ(loaded->config.flags.oneshot, true);             // file
  }
  remove(config_path.c_str());

  // Invalid strategy rejected.
  std::vector<std::string> bad = {"tfd", "--slice-strategy=bogus"};
  std::vector<char*> badv;
  for (auto& a : bad) badv.push_back(a.data());
  CHECK_TRUE(!config::Load(static_cast<int>(badv.size()), badv.data()).ok());
}

config::Config MockedConfig(const std::string& fixture,
                            const std::string& strategy) {
  config::Config c;
  c.flags.backend = "mock";
  c.flags.mock_topology_file = WriteTemp(fixture);
  c.flags.slice_strategy = strategy;
  return c;
}

const char kV5e4Fixture[] = R"(
libtpuVersion: 0.0.34
runtimeVersion: "0.68"
acceleratorType: v5litepod-4
topology: 2x2
chipsPerHost: 4
numHosts: 1
workerId: 0
chips:
- kind: TPU v5 lite
  count: 4
)";

void TestResourceLabelsNone() {
  config::Config c = MockedConfig(kV5e4Fixture, "none");
  auto manager = resource::NewMockManager(c.flags.mock_topology_file);
  CHECK_TRUE(manager.ok());
  auto labeler = lm::NewTpuLabeler(*manager, c);
  CHECK_TRUE(labeler.ok());
  auto labels = (*labeler)->GetLabels();
  CHECK_TRUE(labels.ok());
  const lm::Labels& l = *labels;
  CHECK_EQ(l.at("google.com/tpu.count"), "4");
  CHECK_EQ(l.at("google.com/tpu.replicas"), "4");
  CHECK_EQ(l.at("google.com/tpu.product"), "tpu-v5e");
  CHECK_EQ(l.at("google.com/tpu.memory"), "16384");
  CHECK_EQ(l.at("google.com/tpu.family"), "v5e");
  CHECK_EQ(l.at("google.com/tpu.generation"), "5");
  CHECK_EQ(l.at("google.com/tpu.cores"), "1");
  CHECK_EQ(l.at("google.com/libtpu.version.major"), "0");
  CHECK_EQ(l.at("google.com/libtpu.version.patch"), "34");
  CHECK_EQ(l.at("google.com/tpu.runtime.major"), "0");
  CHECK_EQ(l.at("google.com/tpu.runtime.minor"), "68");
  CHECK_EQ(l.at("google.com/tpu.slice.capable"), "true");
  CHECK_EQ(l.at("google.com/tpu.backend"), "mock");
  CHECK_EQ(l.at("google.com/tpu.accelerator-type"), "v5litepod-4");
  CHECK_EQ(l.at("google.com/tpu.topology"), "2x2");
  // Strategy none: no slice strategy/shape labels.
  CHECK_TRUE(l.find("google.com/tpu.slice.strategy") == l.end());
  CHECK_TRUE(l.find("google.com/tpu.slice.shape") == l.end());
  remove(c.flags.mock_topology_file.c_str());
}

void TestResourceLabelsSingle() {
  config::Config c = MockedConfig(kV5e4Fixture, "single");
  auto manager = resource::NewMockManager(c.flags.mock_topology_file);
  CHECK_TRUE(manager.ok());
  auto labeler = lm::NewTpuLabeler(*manager, c);
  CHECK_TRUE(labeler.ok());
  auto labels = (*labeler)->GetLabels();
  CHECK_TRUE(labels.ok());
  const lm::Labels& l = *labels;
  CHECK_EQ(l.at("google.com/tpu.slice.strategy"), "single");
  CHECK_EQ(l.at("google.com/tpu.slice.shape"), "2x2");
  CHECK_EQ(l.at("google.com/tpu.slice.hosts"), "1");
  CHECK_EQ(l.at("google.com/tpu.slice.chips-per-host"), "4");
  CHECK_EQ(l.at("google.com/tpu.slice.worker-id"), "0");
  CHECK_EQ(l.at("google.com/tpu.count"), "4");
  remove(c.flags.mock_topology_file.c_str());
}

void TestResourceLabelsMixed() {
  config::Config c = MockedConfig(kV5e4Fixture, "mixed");
  auto manager = resource::NewMockManager(c.flags.mock_topology_file);
  CHECK_TRUE(manager.ok());
  auto labeler = lm::NewTpuLabeler(*manager, c);
  CHECK_TRUE(labeler.ok());
  auto labels = (*labeler)->GetLabels();
  CHECK_TRUE(labels.ok());
  const lm::Labels& l = *labels;
  CHECK_EQ(l.at("google.com/tpu.slice.strategy"), "mixed");
  CHECK_EQ(l.at("google.com/tpu-2x2.count"), "4");
  CHECK_EQ(l.at("google.com/tpu-2x2.product"), "tpu-v5e-SLICE-2x2");
  CHECK_EQ(l.at("google.com/tpu-2x2.memory"), "16384");
  CHECK_EQ(l.at("google.com/tpu.count"), "4");  // whole-chip labels remain
  remove(c.flags.mock_topology_file.c_str());
}

void TestInvalidSliceDegradation() {
  // Topology says 4x4 (16 chips) but accelerator type is 4 chips → the
  // single strategy must degrade to SLICE-INVALID, not fail (reference
  // mig-strategy.go:243-262 analogue).
  const char* fixture = R"(
acceleratorType: v5litepod-4
topology: 4x4
chipsPerHost: 4
numHosts: 1
chips:
- kind: TPU v5 lite
  count: 4
)";
  config::Config c = MockedConfig(fixture, "single");
  auto manager = resource::NewMockManager(c.flags.mock_topology_file);
  CHECK_TRUE(manager.ok());
  auto labeler = lm::NewTpuLabeler(*manager, c);
  CHECK_TRUE(labeler.ok());
  auto labels = (*labeler)->GetLabels();
  CHECK_TRUE(labels.ok());
  const lm::Labels& l = *labels;
  CHECK_EQ(l.at("google.com/tpu.product"), "SLICE-INVALID");
  CHECK_EQ(l.at("google.com/tpu.count"), "0");
  CHECK_EQ(l.at("google.com/tpu.replicas"), "0");
  CHECK_EQ(l.at("google.com/tpu.slice.shape"), "SLICE-INVALID");
  remove(c.flags.mock_topology_file.c_str());
}

void TestSharing() {
  config::Config c = MockedConfig(kV5e4Fixture, "none");
  config::SharedResource shared;
  shared.name = "google.com/tpu";
  shared.replicas = 2;
  c.sharing.time_slicing.push_back(shared);
  auto manager = resource::NewMockManager(c.flags.mock_topology_file);
  CHECK_TRUE(manager.ok());
  auto labeler = lm::NewTpuLabeler(*manager, c);
  CHECK_TRUE(labeler.ok());
  auto labels = (*labeler)->GetLabels();
  CHECK_TRUE(labels.ok());
  CHECK_EQ(labels->at("google.com/tpu.replicas"), "8");
  CHECK_EQ(labels->at("google.com/tpu.product"), "tpu-v5e-SHARED");

  // Renamed resources do not get the -SHARED suffix (resource.go:182-226).
  c.sharing.time_slicing[0].rename = "tpu-shared";
  auto manager2 = resource::NewMockManager(c.flags.mock_topology_file);
  auto labeler2 = lm::NewTpuLabeler(*manager2, c);
  CHECK_TRUE(labeler2.ok());
  auto labels2 = (*labeler2)->GetLabels();
  CHECK_EQ(labels2->at("google.com/tpu.product"), "tpu-v5e");
  CHECK_EQ(labels2->at("google.com/tpu.replicas"), "8");
  remove(c.flags.mock_topology_file.c_str());
}

void TestClientOptionParsing() {
  using pjrt::ClientOption;
  // Inference: integer / bool / float / string.
  auto r = pjrt::ParseClientOption("rank=4294967295");
  CHECK_TRUE(r.ok() && r->type == ClientOption::Type::kInt64);
  CHECK_EQ(r->int64_value, 4294967295LL);
  r = pjrt::ParseClientOption("negative=-3");
  CHECK_TRUE(r.ok() && r->type == ClientOption::Type::kInt64 &&
             r->int64_value == -3);
  r = pjrt::ParseClientOption("flag=true");
  CHECK_TRUE(r.ok() && r->type == ClientOption::Type::kBool &&
             r->bool_value);
  r = pjrt::ParseClientOption("ratio=0.5");
  CHECK_TRUE(r.ok() && r->type == ClientOption::Type::kFloat);
  r = pjrt::ParseClientOption("topology=v5e:1x1x1");
  CHECK_TRUE(r.ok() && r->type == ClientOption::Type::kString);
  CHECK_EQ(r->string_value, "v5e:1x1x1");
  // Values may contain '=' (only the first splits).
  r = pjrt::ParseClientOption("kv=a=b");
  CHECK_TRUE(r.ok() && r->string_value == "a=b");

  // Explicit prefixes override inference.
  r = pjrt::ParseClientOption("tag=str:123");
  CHECK_TRUE(r.ok() && r->type == ClientOption::Type::kString &&
             r->string_value == "123");
  r = pjrt::ParseClientOption("level=int:7");
  CHECK_TRUE(r.ok() && r->type == ClientOption::Type::kInt64 &&
             r->int64_value == 7);
  r = pjrt::ParseClientOption("b=bool:false");
  CHECK_TRUE(r.ok() && r->type == ClientOption::Type::kBool &&
             !r->bool_value);
  r = pjrt::ParseClientOption("f=float:2");
  CHECK_TRUE(r.ok() && r->type == ClientOption::Type::kFloat);

  // Inference edge cases: only plain decimal shapes infer numeric —
  // nan/inf/hex stay strings; integer-shaped overflow is a loud error.
  r = pjrt::ParseClientOption("tag=nan");
  CHECK_TRUE(r.ok() && r->type == ClientOption::Type::kString);
  r = pjrt::ParseClientOption("tag=inf");
  CHECK_TRUE(r.ok() && r->type == ClientOption::Type::kString);
  r = pjrt::ParseClientOption("tag=0x10");
  CHECK_TRUE(r.ok() && r->type == ClientOption::Type::kString);
  r = pjrt::ParseClientOption("tag=1e9");
  CHECK_TRUE(r.ok() && r->type == ClientOption::Type::kString);
  CHECK_TRUE(!pjrt::ParseClientOption("x=18446744073709551615").ok());
  // Decimal-shaped float overflow errors loudly; explicit float: takes
  // subnormals (glibc ERANGE must not reject a representable value).
  CHECK_TRUE(!pjrt::ParseClientOption(
      "x=" + std::string(40, '9') + ".0").ok());
  r = pjrt::ParseClientOption("x=float:1e-43");
  CHECK_TRUE(r.ok() && r->type == ClientOption::Type::kFloat);

  // Malformed.
  CHECK_TRUE(!pjrt::ParseClientOption("novalue").ok());
  CHECK_TRUE(!pjrt::ParseClientOption("=v").ok());
  CHECK_TRUE(!pjrt::ParseClientOption("x=int:abc").ok());
  CHECK_TRUE(!pjrt::ParseClientOption("x=bool:2").ok());
  CHECK_TRUE(!pjrt::ParseClientOption("x=float:nope").ok());

  // NamedValue views carry types and sizes per the C-API convention.
  auto parsed = pjrt::ParseClientOptions(
      {"session_id=abc", "rank=1", "on=true", "r=0.5"});
  CHECK_TRUE(parsed.ok());
  auto nvs = pjrt::ToNamedValues(*parsed);
  CHECK_EQ(static_cast<int>(nvs.size()), 4);
  CHECK_TRUE(nvs[0].type == PJRT_NamedValue_kString &&
             nvs[0].value_size == 3);
  CHECK_TRUE(nvs[1].type == PJRT_NamedValue_kInt64 &&
             nvs[1].value_size == 1);
  CHECK_TRUE(nvs[2].type == PJRT_NamedValue_kBool && nvs[2].bool_value);
  CHECK_TRUE(nvs[3].type == PJRT_NamedValue_kFloat);
}

void TestSharingDevicesSelector() {
  // The reference's devices union (replicas.go:45-60): "all", a count, or
  // a device-ref list. All three load (validated, warned, ignored);
  // malformed selectors are config errors.
  auto load_with = [](const std::string& devices_yaml) {
    std::string path = WriteTemp(
        "version: v1\nsharing:\n  timeSlicing:\n    resources:\n"
        "    - name: google.com/tpu\n" + devices_yaml +
        "      replicas: 2\n");
    std::vector<std::string> args = {"tfd", "--config-file", path};
    std::vector<char*> argv;
    for (auto& a : args) argv.push_back(a.data());
    auto loaded = config::Load(static_cast<int>(argv.size()), argv.data());
    remove(path.c_str());
    return loaded;
  };

  auto all = load_with("      devices: all\n");
  CHECK_TRUE(all.ok());
  if (all.ok()) {
    CHECK_EQ(static_cast<int>(all->config.sharing.time_slicing.size()), 1);
    CHECK_EQ(all->config.sharing.time_slicing[0].replicas, 2);
  }

  auto count = load_with("      devices: 2\n");
  CHECK_TRUE(count.ok());

  auto list = load_with(
      "      devices:\n      - 0\n      - TPU-ab12cd\n");
  CHECK_TRUE(list.ok());
  if (list.ok()) {
    CHECK_EQ(list->config.sharing.time_slicing[0].replicas, 2);
  }

  auto bad_scalar = load_with("      devices: some\n");
  CHECK_TRUE(!bad_scalar.ok());
  // The reference union only admits a POSITIVE count.
  CHECK_TRUE(!load_with("      devices: 0\n").ok());
  CHECK_TRUE(!load_with("      devices: -3\n").ok());
  // Explicit-null is unset (sigs.k8s.io/yaml unmarshal semantics).
  CHECK_TRUE(load_with("      devices:\n").ok());
  auto bad_map = load_with("      devices:\n        nested: map\n");
  CHECK_TRUE(!bad_map.ok());
  if (!bad_map.ok()) {
    CHECK_TRUE(bad_map.status().message().find("devices") !=
               std::string::npos);
  }
}

void TestNullManager() {
  // The end state of every degradation path: zero devices, loud errors
  // on identity getters, and it never touches hardware.
  auto null = resource::NewNullManager();
  CHECK_TRUE(null->Init().ok());
  CHECK_EQ(null->Name(), "null");
  CHECK_TRUE(!null->TouchesDevices());
  auto devices = null->GetDevices();
  CHECK_TRUE(devices.ok() && devices->empty());
  CHECK_TRUE(!null->GetLibtpuVersion().ok());
  CHECK_TRUE(!null->GetRuntimeVersion().ok());
  CHECK_TRUE(!null->GetTopology().ok());
  null->Shutdown();
}

void TestPlatformDetect() {
  // OnGce: driven through the DMI-file parameter, not the live host.
  std::string gce = WriteTemp("Google Compute Engine\n");
  CHECK_TRUE(platform::OnGce(gce));
  std::string metal = WriteTemp("Some Vendor Board\n");
  CHECK_TRUE(!platform::OnGce(metal));
  CHECK_TRUE(!platform::OnGce("/nonexistent/dmi"));
  remove(gce.c_str());
  remove(metal.c_str());

  // Search order: an override path always wins and comes first.
  auto paths = platform::LibtpuSearchPaths("/custom/libtpu.so");
  CHECK_TRUE(!paths.empty());
  CHECK_EQ(paths[0], "/custom/libtpu.so");
  CHECK_EQ(static_cast<int>(paths.size()), 1);
  CHECK_TRUE(platform::LibtpuSearchPaths("").size() >= 1);

  // HasLibtpu with an unloadable override: false, resolved path
  // untouched (callers log it only on success).
  std::string resolved = "unchanged";
  CHECK_TRUE(!platform::HasLibtpu("/nonexistent/libtpu.so", &resolved));
  CHECK_EQ(resolved, "unchanged");

  // MetadataPlausible: an explicit endpoint is always plausible.
  CHECK_TRUE(platform::MetadataPlausible("127.0.0.1:1"));
}

void TestFallbackDecorator() {
  const char* fixture = R"(
initError: simulated init failure
chips:
- kind: TPU v5 lite
  count: 4
)";
  std::string path = WriteTemp(fixture);
  auto inner = resource::NewMockManager(path);
  CHECK_TRUE(inner.ok());
  // Raw manager fails Init.
  CHECK_TRUE(!(*inner)->Init().ok());
  // Decorated manager degrades to null: Init OK, zero devices.
  auto wrapped = resource::NewFallbackToNullOnInitError(*inner);
  CHECK_TRUE(wrapped->Init().ok());
  auto devices = wrapped->GetDevices();
  CHECK_TRUE(devices.ok());
  CHECK_EQ(static_cast<int>(devices->size()), 0);
  CHECK_EQ(wrapped->Name(), "null");
  remove(path.c_str());
}

void TestFallbackChain() {
  std::string bad = WriteTemp(
      "initError: chips busy\nchips:\n- kind: TPU v5 lite\n  count: 4\n");
  std::string good = WriteTemp(kV5e4Fixture);
  auto first = resource::NewMockManager(bad);
  auto second = resource::NewMockManager(good);
  CHECK_TRUE(first.ok());
  CHECK_TRUE(second.ok());
  auto chain = resource::NewFallbackChain({*first, *second});
  CHECK_TRUE(chain->Init().ok());
  auto devices = chain->GetDevices();
  CHECK_TRUE(devices.ok());
  CHECK_EQ(static_cast<int>(devices->size()), 4);

  // All candidates failing → Init fails.
  auto first2 = resource::NewMockManager(bad);
  auto chain2 = resource::NewFallbackChain({*first2});
  CHECK_TRUE(!chain2->Init().ok());
  remove(bad.c_str());
  remove(good.c_str());
}

void TestBoolParsing() {
  // Empty env values must not silently mean true (TFD_ONESHOT= in a
  // manifest is an operator mistake, not an opt-in).
  setenv("TFD_ONESHOT", "", 1);
  std::vector<std::string> args = {"tfd"};
  std::vector<char*> argv;
  for (auto& a : args) argv.push_back(a.data());
  auto loaded = config::Load(static_cast<int>(argv.size()), argv.data());
  unsetenv("TFD_ONESHOT");
  CHECK_TRUE(!loaded.ok());
}

void TestTpuEnvParse() {
  auto env = gce::ParseTpuEnv(
      "ACCELERATOR_TYPE: 'v5p-128'\n"
      "CHIPS_PER_HOST_BOUNDS: '2,2,1'\n"
      "HOST_BOUNDS: '4,4,1'\n"
      "WORKER_ID: '3'\n"
      "ZONE: us-east5-a\n");
  CHECK_EQ(env["ACCELERATOR_TYPE"], "v5p-128");
  CHECK_EQ(env["CHIPS_PER_HOST_BOUNDS"], "2,2,1");
  CHECK_EQ(env["WORKER_ID"], "3");
  CHECK_EQ(env["ZONE"], "us-east5-a");
}

void TestLabelFormatting() {
  lm::Labels labels;
  labels["b"] = "2";
  labels["a"] = "1";
  CHECK_EQ(lm::FormatLabels(labels), "a=1\nb=2\n");  // sorted, deterministic
}

void TestAtomicWrite() {
  std::string dir = "/tmp/tfd-unit-atomic-" + std::to_string(getpid());
  std::string path = dir + "/labels";
  CHECK_TRUE(WriteFileAtomically(path, "x=1\n").ok());
  auto contents = ReadFile(path);
  CHECK_TRUE(contents.ok());
  CHECK_EQ(*contents, "x=1\n");
  CHECK_TRUE(WriteFileAtomically(path, "x=2\n").ok());
  CHECK_EQ(*ReadFile(path), "x=2\n");
  std::string cmd = "rm -rf " + dir;
  CHECK_TRUE(system(cmd.c_str()) == 0);

  // Error paths stay errors, not silent no-ops: an unwritable target
  // directory (scratch-dir creation fails under a plain file) and a
  // missing read target.
  std::string file_as_dir = WriteTemp("not a directory");
  Status s = WriteFileAtomically(file_as_dir + "/labels", "x=1\n");
  CHECK_TRUE(!s.ok());
  CHECK_TRUE(s.message().find("scratch dir") != std::string::npos);
  remove(file_as_dir.c_str());
  CHECK_TRUE(!ReadFile("/nonexistent/tfd-labels").ok());
}

void TestUrlParsing() {
  auto url = http::ParseUrl("https://10.0.0.1:6443/api");
  CHECK_TRUE(url.ok());
  CHECK_EQ(url->host, "10.0.0.1");
  CHECK_TRUE(url->port == 6443 && url->tls);
  CHECK_EQ(url->path, "/api");

  url = http::ParseUrl("http://example.com");
  CHECK_TRUE(url.ok());
  CHECK_EQ(url->host, "example.com");
  CHECK_TRUE(url->port == 80 && !url->tls);
  CHECK_EQ(url->path, "/");

  // Bracketed IPv6, with and without a port.
  url = http::ParseUrl("https://[fd00::1]:6443/apis");
  CHECK_TRUE(url.ok());
  CHECK_EQ(url->host, "fd00::1");
  CHECK_TRUE(url->port == 6443);
  url = http::ParseUrl("https://[fd00::1]/apis");
  CHECK_TRUE(url.ok());
  CHECK_EQ(url->host, "fd00::1");
  CHECK_TRUE(url->port == 443);

  // Unbracketed IPv6 literal: the whole hostport is the host (splitting
  // at the last colon would yield host "fd00:" port 1).
  url = http::ParseUrl("https://fd00::1");
  CHECK_TRUE(url.ok());
  CHECK_EQ(url->host, "fd00::1");
  CHECK_TRUE(url->port == 443);

  CHECK_TRUE(!http::ParseUrl("ftp://x").ok());
  CHECK_TRUE(!http::ParseUrl("https://[fd00::1/x").ok());
  CHECK_TRUE(!http::ParseUrl("https:///x").ok());
}

void TestJsonNonFiniteSerialization() {
  // JSON has no nan/inf tokens; Serialize must degrade to null rather
  // than emit an invalid document on the CR write path.
  auto value = std::make_shared<jsonlite::Value>();
  value->kind = jsonlite::Value::Kind::kNumber;
  value->number_value = std::numeric_limits<double>::quiet_NaN();
  CHECK_EQ(jsonlite::Serialize(*value), "null");
  value->number_value = std::numeric_limits<double>::infinity();
  CHECK_EQ(jsonlite::Serialize(*value), "null");
  value->number_value = 42.0;
  CHECK_EQ(jsonlite::Serialize(*value), "42");
}

void TestGkeIdentity() {
  // The published GKE machine-type table (GKE docs "TPUs in GKE").
  struct Case {
    const char* machine;
    const char* family;
    int chips;
  };
  const Case cases[] = {
      {"ct4p-hightpu-4t", "v4", 4},    {"ct5lp-hightpu-1t", "v5e", 1},
      {"ct5lp-hightpu-4t", "v5e", 4},  {"ct5lp-hightpu-8t", "v5e", 8},
      {"ct5l-hightpu-8t", "v5e", 8},   {"ct5p-hightpu-4t", "v5p", 4},
      {"ct6e-standard-1t", "v6e", 1},  {"ct6e-standard-4t", "v6e", 4},
      {"ct6e-standard-8t", "v6e", 8},
  };
  for (const Case& c : cases) {
    Result<slice::GkeMachineType> parsed =
        slice::ParseGkeMachineType(c.machine);
    CHECK_TRUE(parsed.ok());
    CHECK_EQ(parsed->spec.family, c.family);
    CHECK_EQ(parsed->chips_per_host, c.chips);
  }
  CHECK_TRUE(!slice::ParseGkeMachineType("n2-standard-8").ok());
  CHECK_TRUE(!slice::ParseGkeMachineType("ct9z-hightpu-4t").ok());
  CHECK_TRUE(!slice::ParseGkeMachineType("ct5lp-hightpu-4x").ok());
  CHECK_TRUE(!slice::ParseGkeMachineType("ct5lp").ok());

  CHECK_EQ(slice::FamilyFromGkeAccelerator("tpu-v4-podslice")->family, "v4");
  CHECK_EQ(slice::FamilyFromGkeAccelerator("tpu-v5-lite-podslice")->family,
           "v5e");
  CHECK_EQ(slice::FamilyFromGkeAccelerator("tpu-v5-lite-device")->family,
           "v5e");
  CHECK_EQ(slice::FamilyFromGkeAccelerator("tpu-v5p-slice")->family, "v5p");
  CHECK_EQ(slice::FamilyFromGkeAccelerator("tpu-v6e-slice")->family, "v6e");
  CHECK_TRUE(!slice::FamilyFromGkeAccelerator("nvidia-tesla-t4").ok());
}

void TestForkedCapture() {
  // Normal path: output + exit code transported, no error mapping.
  int code = -1;
  Result<std::string> out = RunForkedCapture(
      [](int fd) {
        const char msg[] = "{\"ok\":true}";
        (void)!write(fd, msg, sizeof(msg) - 1);
        return 3;
      },
      5, "test child", &code);
  CHECK_TRUE(out.ok());
  CHECK_EQ(*out, "{\"ok\":true}");
  CHECK_EQ(code, 3);

  // Hang path: the PJRT-init-shaped failure — child blocks without ever
  // writing; the deadline must kill it and surface an error.
  code = -1;
  out = RunForkedCapture(
      [](int) {
        while (true) sleep(3600);
        return 0;
      },
      1, "hanging child", &code);
  CHECK_TRUE(!out.ok());
  CHECK_TRUE(out.error().find("timed out") != std::string::npos);

  // Close-then-hang: EOF on the pipe must not bypass the deadline.
  out = RunForkedCapture(
      [](int fd) {
        close(fd);
        while (true) sleep(3600);
        return 0;
      },
      1, "eof-then-hang child", &code);
  CHECK_TRUE(!out.ok());
  CHECK_TRUE(out.error().find("timed out") != std::string::npos);
}

// Serves exactly one TCP connection with a canned byte payload from a
// forked child; returns the bound port. Waits for the child in the caller
// via waitpid (pid out-param).
int ServeOnce(const std::string& payload, pid_t* pid) {
  // NOTE: no side effects inside assert() — the suite builds with NDEBUG.
  int listener = socket(AF_INET, SOCK_STREAM, 0);
  if (listener < 0) return -1;
  int one = 1;
  setsockopt(listener, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr = {};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  addr.sin_port = 0;
  socklen_t len = sizeof(addr);
  if (bind(listener, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
      listen(listener, 1) != 0 ||
      getsockname(listener, reinterpret_cast<sockaddr*>(&addr), &len) != 0) {
    close(listener);
    return -1;
  }
  *pid = fork();
  if (*pid < 0) {
    close(listener);
    return -1;
  }
  if (*pid == 0) {
    int conn = accept(listener, nullptr, nullptr);
    if (conn >= 0) {
      char buf[4096];
      (void)!read(conn, buf, sizeof(buf));  // drain the request headers
      (void)!write(conn, payload.data(), payload.size());
      close(conn);
    }
    _exit(0);
  }
  close(listener);
  return ntohs(addr.sin_port);
}

void TestMetadataErrorKinds() {
  using ErrorKind = gce::MetadataClient::ErrorKind;
  auto get_kind = [](const std::string& payload) {
    pid_t pid = -1;
    int port = ServeOnce(payload, &pid);
    CHECK_TRUE(port > 0);
    gce::MetadataClient client("127.0.0.1:" + std::to_string(port), 2000);
    Result<std::string> r = client.Get("instance/attributes/tpu-env");
    CHECK_TRUE(!r.ok());
    int status = 0;
    waitpid(pid, &status, 0);
    return client.last_error_kind();
  };

  // Transport: nothing listens on the hermetic poison port.
  gce::MetadataClient down("127.0.0.1:1", 500);
  CHECK_TRUE(!down.Get("instance/id").ok());
  CHECK_TRUE(down.last_error_kind() == ErrorKind::kTransport);

  // 404: server up, key absent (the GKE shape).
  CHECK_TRUE(get_kind("HTTP/1.1 404 Not Found\r\nContent-Length: 0"
                      "\r\nConnection: close\r\n\r\n") ==
             ErrorKind::kNotFound);

  // Transient 5xx: server answering; rungs stay worth trying.
  CHECK_TRUE(get_kind("HTTP/1.1 503 Unavailable\r\nContent-Length: 0"
                      "\r\nConnection: close\r\n\r\n") ==
             ErrorKind::kHttpStatus);

  // A garbage-speaking endpoint answered — NOT a transport failure (the
  // pin planner must keep trying its remaining rungs). Before the
  // structured signal this was misclassified by substring matching.
  CHECK_TRUE(get_kind("not http at all") == ErrorKind::kHttpStatus);

  // Accept-then-close without a byte: something IS listening (a proxy
  // starting up), so remaining rungs fail fast and stay worth trying.
  CHECK_TRUE(get_kind("") == ErrorKind::kHttpStatus);

  // Success resets the kind.
  pid_t pid = -1;
  int port = ServeOnce(
      "HTTP/1.1 200 OK\r\nContent-Length: 2\r\nConnection: close\r\n\r\nok",
      &pid);
  gce::MetadataClient ok_client("127.0.0.1:" + std::to_string(port), 2000);
  Result<std::string> r = ok_client.Get("instance/id");
  CHECK_TRUE(r.ok());
  CHECK_EQ(*r, "ok");
  CHECK_TRUE(ok_client.last_error_kind() == ErrorKind::kNone);
  int status = 0;
  waitpid(pid, &status, 0);
}

// ---- obs: metrics registry + exposition + introspection server ----------

void TestMetricsRegistry() {
  obs::Registry reg;
  obs::Counter* c = reg.GetCounter("tfd_test_total", "help text");
  c->Inc();
  c->Inc(2.5);
  CHECK_EQ(c->Value(), 3.5);
  // Same (name, labels) -> same instrument.
  CHECK_TRUE(reg.GetCounter("tfd_test_total", "help text") == c);
  // Counters never go down, and NaN increments are dropped.
  c->Inc(-5);
  c->Inc(std::numeric_limits<double>::quiet_NaN());
  CHECK_EQ(c->Value(), 3.5);

  obs::Gauge* g = reg.GetGauge("tfd_test_gauge", "a gauge");
  g->Set(42);
  g->Set(-1.5);
  CHECK_EQ(g->Value(), -1.5);

  // Type mismatch on a registered name: a detached instrument, never a
  // crash or a corrupted family.
  obs::Gauge* orphan = reg.GetGauge("tfd_test_total", "not a counter");
  orphan->Set(99);
  CHECK_EQ(c->Value(), 3.5);

  std::string text = reg.Exposition();
  CHECK_TRUE(text.find("# HELP tfd_test_total help text\n") !=
             std::string::npos);
  CHECK_TRUE(text.find("# TYPE tfd_test_total counter\n") !=
             std::string::npos);
  CHECK_TRUE(text.find("tfd_test_total 3.5\n") != std::string::npos);
  CHECK_TRUE(text.find("99") == std::string::npos);  // orphan not rendered
  CHECK_TRUE(obs::ValidateExposition(text).ok());

  // Two children of one family render under ONE HELP/TYPE block.
  reg.GetCounter("tfd_multi", "multi", {{"k", "a"}})->Inc();
  reg.GetCounter("tfd_multi", "multi", {{"k", "b"}})->Inc();
  text = reg.Exposition();
  size_t first = text.find("# TYPE tfd_multi counter");
  CHECK_TRUE(first != std::string::npos);
  CHECK_TRUE(text.find("# TYPE tfd_multi counter", first + 1) ==
             std::string::npos);
  CHECK_TRUE(text.find("tfd_multi{k=\"a\"} 1\n") != std::string::npos);
  CHECK_TRUE(text.find("tfd_multi{k=\"b\"} 1\n") != std::string::npos);
  CHECK_TRUE(obs::ValidateExposition(text).ok());
}

void TestMetricsEscaping() {
  obs::Registry reg;
  reg.GetGauge("tfd_escape", "help with \\ backslash\nand newline",
               {{"path", "a\\b \"quoted\"\nnext"}})
      ->Set(1);
  std::string text = reg.Exposition();
  CHECK_TRUE(text.find("help with \\\\ backslash\\nand newline") !=
             std::string::npos);
  CHECK_TRUE(text.find("{path=\"a\\\\b \\\"quoted\\\"\\nnext\"}") !=
             std::string::npos);
  CHECK_TRUE(obs::ValidateExposition(text).ok());

  // Hostile names sanitize into the Prometheus grammar instead of
  // producing an unscrapeable page.
  reg.GetCounter("9bad name!", "x", {{"bad key", "v"}})->Inc();
  CHECK_TRUE(obs::ValidateExposition(reg.Exposition()).ok());
  CHECK_TRUE(reg.Exposition().find("_9bad_name_") != std::string::npos);
}

void TestMetricsHistogram() {
  obs::Registry reg;
  obs::Histogram* h = reg.GetHistogram("tfd_lat_seconds", "latency",
                                       {0.01, 0.1, 1.0}, {{"op", "x"}});
  h->Observe(0.005);
  h->Observe(0.05);
  h->Observe(0.5);
  h->Observe(5.0);            // above the last bound -> +Inf only
  h->Observe(0.1);            // exactly on a bound counts into it
  h->Observe(std::numeric_limits<double>::quiet_NaN());  // dropped
  CHECK_EQ(h->TotalCount(), 5ULL);
  CHECK_EQ(h->CumulativeCount(0), 1ULL);
  CHECK_EQ(h->CumulativeCount(1), 3ULL);
  CHECK_EQ(h->CumulativeCount(2), 4ULL);

  std::string text = reg.Exposition();
  CHECK_TRUE(text.find("# TYPE tfd_lat_seconds histogram\n") !=
             std::string::npos);
  CHECK_TRUE(text.find(
                 "tfd_lat_seconds_bucket{op=\"x\",le=\"0.01\"} 1\n") !=
             std::string::npos);
  CHECK_TRUE(text.find("tfd_lat_seconds_bucket{op=\"x\",le=\"+Inf\"} 5\n") !=
             std::string::npos);
  CHECK_TRUE(text.find("tfd_lat_seconds_count{op=\"x\"} 5\n") !=
             std::string::npos);
  CHECK_TRUE(obs::ValidateExposition(text).ok());

  // A caller-supplied `le` label cannot collide with the generated one.
  reg.GetHistogram("tfd_le_clash", "x", {1.0}, {{"le", "evil"}})
      ->Observe(0.5);
  CHECK_TRUE(obs::ValidateExposition(reg.Exposition()).ok());
  CHECK_TRUE(reg.Exposition().find("exported_le=\"evil\"") !=
             std::string::npos);

  // Unsorted/duplicate/non-finite bounds are repaired at construction.
  obs::Histogram* odd = reg.GetHistogram(
      "tfd_odd", "x",
      {5.0, 1.0, 1.0, std::numeric_limits<double>::infinity()});
  odd->Observe(3.0);
  CHECK_EQ(odd->upper_bounds().size(), 2ULL);
  CHECK_TRUE(obs::ValidateExposition(reg.Exposition()).ok());

  // Sample-name collisions are renamed away at registration: a counter
  // named like the histogram's generated _bucket series would emit
  // ambiguous lines, so it registers under a trailing-underscore name —
  // and repeat registration lands on the SAME instrument.
  obs::Counter* clash = reg.GetCounter("tfd_lat_seconds_bucket", "clash");
  clash->Inc();
  CHECK_TRUE(reg.GetCounter("tfd_lat_seconds_bucket", "clash") == clash);
  std::string collided = reg.Exposition();
  CHECK_TRUE(collided.find("# TYPE tfd_lat_seconds_bucket_ counter") !=
             std::string::npos);
  CHECK_TRUE(obs::ValidateExposition(collided).ok());
  // And the reverse: a new histogram whose generated names would hit an
  // existing plain family gets renamed too.
  reg.GetCounter("tfd_plain_sum", "plain")->Inc();
  reg.GetHistogram("tfd_plain", "h", {1.0})->Observe(0.5);
  CHECK_TRUE(obs::ValidateExposition(reg.Exposition()).ok());
  CHECK_TRUE(reg.Exposition().find("tfd_plain__bucket") !=
             std::string::npos);
}

void TestValidateExposition() {
  // The checker must bite: hand-made invalid documents are rejected.
  CHECK_TRUE(!obs::ValidateExposition("no trailing newline").ok());
  CHECK_TRUE(!obs::ValidateExposition("orphan_sample 1\n").ok());
  CHECK_TRUE(
      !obs::ValidateExposition("# TYPE m counter\nm{x=\"a\",x=\"b\"} 1\n")
           .ok());
  CHECK_TRUE(!obs::ValidateExposition("# TYPE m counter\nm -1\n").ok());
  CHECK_TRUE(!obs::ValidateExposition("# TYPE m counter\nm notanum\n").ok());
  CHECK_TRUE(!obs::ValidateExposition("# TYPE m bogus\nm 1\n").ok());
  CHECK_TRUE(
      !obs::ValidateExposition("# TYPE m counter\n# TYPE m counter\nm 1\n")
           .ok());
  // Histogram invariants: monotone buckets, +Inf present and == _count.
  CHECK_TRUE(!obs::ValidateExposition(
                  "# TYPE h histogram\n"
                  "h_bucket{le=\"1\"} 5\nh_bucket{le=\"2\"} 3\n"
                  "h_bucket{le=\"+Inf\"} 5\nh_sum 1\nh_count 5\n")
                  .ok());
  CHECK_TRUE(!obs::ValidateExposition(
                  "# TYPE h histogram\n"
                  "h_bucket{le=\"1\"} 1\nh_sum 1\nh_count 1\n")
                  .ok());
  CHECK_TRUE(!obs::ValidateExposition(
                  "# TYPE h histogram\n"
                  "h_bucket{le=\"1\"} 1\nh_bucket{le=\"+Inf\"} 2\n"
                  "h_sum 1\nh_count 3\n")
                  .ok());
  // And a well-formed document passes.
  CHECK_TRUE(obs::ValidateExposition(
                 "# HELP h some text\n# TYPE h histogram\n"
                 "h_bucket{le=\"1\"} 1\nh_bucket{le=\"+Inf\"} 2\n"
                 "h_sum 1.5\nh_count 2\n"
                 "# TYPE c counter\nc{a=\"b\"} 0\n")
                 .ok());
  // Exact-named families win over histogram suffix attribution: a
  // standalone counter that happens to be called x_bucket needs no le.
  CHECK_TRUE(obs::ValidateExposition(
                 "# TYPE x_bucket counter\nx_bucket 3\n")
                 .ok());
}

void TestMetricsExemplars() {
  // OpenMetrics exemplars (ISSUE 16): an Observe with a change-id
  // label lands on the bucket line as ` # {change_id="42"} v`, last
  // write per bucket wins, and the validator enforces the placement
  // and size rules. The Python twin runs the same cases in
  // tests/test_metrics.py.
  obs::Registry reg;
  obs::Histogram* h = reg.GetHistogram("tfd_stage_seconds", "stage",
                                       {0.1, 1.0}, {{"stage", "plan"}});
  h->Observe(0.05, {{"change_id", "42"}});
  h->Observe(0.5);                          // exemplar-free stays bare
  h->Observe(5.0, {{"change_id", "43"}});   // +Inf bucket exemplar
  std::string text = reg.Exposition();
  CHECK_TRUE(text.find("tfd_stage_seconds_bucket{stage=\"plan\","
                       "le=\"0.1\"} 1 # {change_id=\"42\"} 0.05\n") !=
             std::string::npos);
  CHECK_TRUE(text.find("le=\"1\"} 2\n") != std::string::npos);
  CHECK_TRUE(text.find("le=\"+Inf\"} 3 # {change_id=\"43\"} 5\n") !=
             std::string::npos);
  CHECK_TRUE(obs::ValidateExposition(text).ok());
  // Last write wins within a bucket.
  h->Observe(0.06, {{"change_id", "44"}});
  CHECK_TRUE(reg.Exposition().find("# {change_id=\"44\"} 0.06") !=
             std::string::npos);
  CHECK_TRUE(obs::ValidateExposition(reg.Exposition()).ok());

  // Placement: exemplars ride counter and histogram-bucket lines ONLY.
  CHECK_TRUE(obs::ValidateExposition(
                 "# TYPE c counter\nc 1 # {change_id=\"1\"} 1\n")
                 .ok());
  CHECK_TRUE(!obs::ValidateExposition(
                  "# TYPE g gauge\ng 1 # {change_id=\"1\"} 1\n")
                  .ok());
  CHECK_TRUE(!obs::ValidateExposition(
                  "# TYPE h histogram\n"
                  "h_bucket{le=\"+Inf\"} 1\nh_sum 1\n"
                  "h_count 1 # {change_id=\"1\"} 1\n")
                  .ok());
  // The 128-rune exemplar label budget (the OpenMetrics limit).
  std::string big(140, 'x');
  CHECK_TRUE(!obs::ValidateExposition("# TYPE c counter\nc 1 # {a=\"" +
                                      big + "\"} 1\n")
                  .ok());
}

void TestListenAddrParse() {
  Result<obs::ListenAddr> a = obs::ParseListenAddr(":8081");
  CHECK_TRUE(a.ok());
  CHECK_EQ(a->host, "");
  CHECK_EQ(a->port, 8081);
  a = obs::ParseListenAddr("127.0.0.1:9");
  CHECK_TRUE(a.ok());
  CHECK_EQ(a->host, "127.0.0.1");
  CHECK_EQ(a->port, 9);
  a = obs::ParseListenAddr("127.0.0.1:0");  // ephemeral (tests)
  CHECK_TRUE(a.ok());
  CHECK_TRUE(!obs::ParseListenAddr("").ok());
  CHECK_TRUE(!obs::ParseListenAddr("8081").ok());
  CHECK_TRUE(!obs::ParseListenAddr(":huh").ok());
  CHECK_TRUE(!obs::ParseListenAddr(":70000").ok());
  CHECK_TRUE(!obs::ParseListenAddr("metadata.google.internal:1").ok());
}

void TestIntrospectionServer() {
  obs::Registry reg;
  reg.GetCounter("tfd_e2e_total", "served over http")->Inc(7);

  obs::ServerOptions options;
  options.addr = "127.0.0.1:0";
  options.stale_after_s = 1;
  Result<std::unique_ptr<obs::IntrospectionServer>> server =
      obs::IntrospectionServer::Start(options, &reg);
  CHECK_TRUE(server.ok());
  std::string base =
      "http://127.0.0.1:" + std::to_string((*server)->port());
  http::RequestOptions ropt;
  ropt.timeout_ms = 3000;

  Result<http::Response> r = http::Request("GET", base + "/healthz", "", ropt);
  CHECK_TRUE(r.ok());
  CHECK_EQ(r->status, 200);
  CHECK_EQ(r->body, "ok\n");

  // Not ready before the first successful rewrite; ready after; not
  // ready again once the last success is older than stale_after_s.
  r = http::Request("GET", base + "/readyz", "", ropt);
  CHECK_TRUE(r.ok());
  CHECK_EQ(r->status, 503);
  (*server)->RecordRewrite(true);
  r = http::Request("GET", base + "/readyz", "", ropt);
  CHECK_TRUE(r.ok());
  CHECK_EQ(r->status, 200);
  (*server)->RecordRewrite(false);  // last rewrite failed -> 503 instantly
  r = http::Request("GET", base + "/readyz", "", ropt);
  CHECK_TRUE(r.ok());
  CHECK_EQ(r->status, 503);
  (*server)->RecordRewrite(true);
  usleep(1300 * 1000);  // stale_after_s = 1
  r = http::Request("GET", base + "/readyz", "", ropt);
  CHECK_TRUE(r.ok());
  CHECK_EQ(r->status, 503);

  r = http::Request("GET", base + "/metrics", "", ropt);
  CHECK_TRUE(r.ok());
  CHECK_EQ(r->status, 200);
  CHECK_TRUE(r->body.find("tfd_e2e_total 7\n") != std::string::npos);
  CHECK_TRUE(obs::ValidateExposition(r->body).ok());

  r = http::Request("GET", base + "/nope", "", ropt);
  CHECK_TRUE(r.ok());
  CHECK_EQ(r->status, 404);
  r = http::Request("POST", base + "/metrics", "x", ropt);
  CHECK_TRUE(r.ok());
  CHECK_EQ(r->status, 405);

  (*server)->Stop();
  // Stopped server: connection refused, and Stop is idempotent.
  r = http::Request("GET", base + "/healthz", "", ropt);
  CHECK_TRUE(!r.ok());
  (*server)->Stop();
}

void TestReadyzAllExpired() {
  // "Degraded-but-serving is ready; expired-everything is not": with
  // rewrites succeeding and fresh, SetAllExpired alone must flip
  // /readyz, and clearing it must restore readiness.
  obs::Registry reg;
  obs::ServerOptions options;
  options.addr = "127.0.0.1:0";
  options.stale_after_s = 60;
  Result<std::unique_ptr<obs::IntrospectionServer>> server =
      obs::IntrospectionServer::Start(options, &reg);
  CHECK_TRUE(server.ok());
  std::string base =
      "http://127.0.0.1:" + std::to_string((*server)->port());
  http::RequestOptions ropt;
  ropt.timeout_ms = 3000;

  (*server)->RecordRewrite(true);
  Result<http::Response> r =
      http::Request("GET", base + "/readyz", "", ropt);
  CHECK_TRUE(r.ok());
  CHECK_EQ(r->status, 200);
  (*server)->SetAllExpired(true);
  r = http::Request("GET", base + "/readyz", "", ropt);
  CHECK_TRUE(r.ok());
  CHECK_EQ(r->status, 503);
  CHECK_TRUE(r->body.find("expired") != std::string::npos);
  CHECK_TRUE(http::Request("GET", base + "/healthz", "", ropt)->status ==
             200);  // liveness unaffected
  (*server)->SetAllExpired(false);
  r = http::Request("GET", base + "/readyz", "", ropt);
  CHECK_TRUE(r.ok());
  CHECK_EQ(r->status, 200);
  (*server)->Stop();
}

// ---- probe scheduler (sched/) --------------------------------------------

void TestSnapshotTierTransitions() {
  // Pure tier rule first.
  sched::TierPolicy policy;
  policy.fresh_for_s = 10;
  policy.usable_for_s = 30;
  CHECK_TRUE(sched::TierForAge(-1, policy) == sched::Tier::kNone);
  CHECK_TRUE(sched::TierForAge(0, policy) == sched::Tier::kFresh);
  CHECK_TRUE(sched::TierForAge(10, policy) == sched::Tier::kFresh);
  CHECK_TRUE(sched::TierForAge(10.5, policy) == sched::Tier::kStaleUsable);
  CHECK_TRUE(sched::TierForAge(30, policy) == sched::Tier::kStaleUsable);
  CHECK_TRUE(sched::TierForAge(31, policy) == sched::Tier::kExpired);
  CHECK_EQ(std::string(sched::TierName(sched::Tier::kStaleUsable)),
           "stale-usable");

  // Store transitions, driven through the test clock shift.
  sched::SnapshotStore store;
  store.Register("pjrt", policy, /*device_source=*/true);
  store.Register("metadata", policy, /*device_source=*/true);
  store.Register("health", policy, /*device_source=*/false);
  CHECK_EQ(store.Sources().size(), size_t{3});
  CHECK_EQ(store.DeviceSources().size(), size_t{2});
  CHECK_TRUE(!store.AllSettled());

  sched::SourceView view = store.View("pjrt");
  CHECK_TRUE(view.registered && !view.settled);
  CHECK_TRUE(view.tier == sched::Tier::kNone);

  sched::Snapshot snapshot;
  snapshot.manager = resource::NewNullManager();
  store.PutOk("pjrt", snapshot);
  view = store.View("pjrt");
  CHECK_TRUE(view.settled && view.last_ok.has_value());
  CHECK_TRUE(view.tier == sched::Tier::kFresh);
  CHECK_TRUE(view.age_s >= 0 && view.age_s < 5);

  store.AgeForTest("pjrt", 15);
  CHECK_TRUE(store.View("pjrt").tier == sched::Tier::kStaleUsable);
  store.AgeForTest("pjrt", 20);  // cumulative: 35s old
  CHECK_TRUE(store.View("pjrt").tier == sched::Tier::kExpired);

  // Failures settle a source and count up without clearing the last
  // success; a new success resets the failure run.
  store.PutError("metadata", "boom");
  store.PutError("metadata", "boom again");
  view = store.View("metadata");
  CHECK_TRUE(view.settled && !view.last_ok.has_value());
  CHECK_EQ(view.consecutive_failures, 2);
  CHECK_EQ(view.last_error, "boom again");
  CHECK_TRUE(!view.fatal_error);
  store.PutError("metadata", "cannot even construct", /*fatal=*/true);
  CHECK_TRUE(store.View("metadata").fatal_error);
  store.PutOk("metadata", sched::Snapshot{});
  view = store.View("metadata");
  CHECK_EQ(view.consecutive_failures, 0);
  CHECK_TRUE(!view.fatal_error && view.last_error.empty());

  // Versions are store-global and monotone.
  CHECK_TRUE(store.View("metadata").last_ok->version >
             store.View("pjrt").last_ok->version);

  store.PutOk("health", sched::Snapshot{});
  CHECK_TRUE(store.AllSettled());
  CHECK_TRUE(store.WaitAllSettled(std::chrono::milliseconds(1)));

  // SIGHUP path: invalidation drops every result and settles nothing.
  store.InvalidateAll();
  CHECK_TRUE(!store.AllSettled());
  CHECK_TRUE(store.View("pjrt").tier == sched::Tier::kNone);
  CHECK_TRUE(!store.WaitAllSettled(std::chrono::milliseconds(1)));

  // Unregistered sources are inert: no crash, nothing stored.
  store.PutOk("bogus", sched::Snapshot{});
  CHECK_TRUE(!store.View("bogus").registered);
}

void TestBackoffJitterBounds() {
  // base = min(max, initial * 2^(n-1)); result in [base, 1.25 * base].
  for (int n = 1; n <= 40; n++) {
    for (double u : {0.0, 0.33, 0.999}) {
      double d = sched::BackoffWithJitter(n, 2, 900, u);
      double base = 2.0;
      for (int i = 1; i < n && base < 900; i++) base *= 2;
      if (base > 900) base = 900;
      CHECK_TRUE(d >= base - 1e-9);
      CHECK_TRUE(d <= 1.25 * base + 1e-9);
    }
  }
  // Monotone in the failure count until the cap.
  CHECK_TRUE(sched::BackoffWithJitter(2, 60, 900, 0) >
             sched::BackoffWithJitter(1, 60, 900, 0));
  CHECK_EQ(sched::BackoffWithJitter(1, 60, 900, 0.0), 60.0);
  CHECK_EQ(sched::BackoffWithJitter(5, 60, 900, 0.0), 900.0);  // capped
  // Degenerate inputs: clamped, never zero, never overflowing.
  CHECK_TRUE(sched::BackoffWithJitter(1, 0, 0, 0.0) >= 1.0);
  CHECK_TRUE(sched::BackoffWithJitter(1000000, 1, 900, 0.999) <=
             1.25 * 900 + 1e-9);
  CHECK_TRUE(sched::BackoffWithJitter(3, 60, 900, 2.0) <=
             1.25 * 240 + 1e-9);  // out-of-range jitter clamped
}

void TestProbeBrokerOneRound() {
  // Early-exit: once a device source succeeds, later device sources are
  // not probed (the old fallback chain's semantics), but label sources
  // still run.
  auto store = std::make_shared<sched::SnapshotStore>();
  sched::TierPolicy policy{10, 30};
  store->Register("a", policy, true);
  store->Register("b", policy, true);
  store->Register("labels", policy, false);
  int a_runs = 0, b_runs = 0, label_runs = 0;
  std::vector<sched::ProbeSpec> specs(3);
  specs[0].name = "a";
  specs[0].device_source = true;
  specs[0].probe = [&a_runs](sched::Snapshot*, bool*) {
    a_runs++;
    return Status::Error("a down");
  };
  specs[1].name = "b";
  specs[1].device_source = true;
  specs[1].probe = [&b_runs](sched::Snapshot* out, bool*) {
    b_runs++;
    out->manager = resource::NewNullManager();
    return Status::Ok();
  };
  specs[2].name = "labels";
  specs[2].device_source = false;
  specs[2].probe = [&label_runs](sched::Snapshot* out, bool*) {
    label_runs++;
    out->labels["google.com/tpu.health.ok"] = "true";
    return Status::Ok();
  };
  {
    sched::ProbeBroker broker(store, specs);
    broker.RunOneRound();
  }
  CHECK_EQ(a_runs, 1);
  CHECK_EQ(b_runs, 1);
  CHECK_EQ(label_runs, 1);
  CHECK_TRUE(!store->View("a").last_ok.has_value());
  CHECK_TRUE(store->View("b").last_ok.has_value());
  CHECK_EQ(store->View("labels").last_ok->labels.size(), size_t{1});

  // Second round on a fresh store with "a" healthy: "b" is skipped.
  store->InvalidateAll();
  specs[0].probe = [&a_runs](sched::Snapshot* out, bool*) {
    a_runs++;
    out->manager = resource::NewNullManager();
    return Status::Ok();
  };
  {
    sched::ProbeBroker broker(store, specs);
    broker.RunOneRound();
  }
  CHECK_EQ(a_runs, 2);
  CHECK_EQ(b_runs, 1);  // unchanged: early-exit
  CHECK_TRUE(!store->View("b").settled);
}

void TestProbeBrokerWorkers() {
  // Daemon mode: workers re-probe on their own cadence, failures set
  // the backoff state, and Stop() joins healthy workers promptly.
  auto store = std::make_shared<sched::SnapshotStore>();
  sched::TierPolicy policy{10, 30};
  store->Register("good", policy, true);
  store->Register("bad", policy, true);
  std::atomic<int> good_runs{0}, bad_runs{0};
  std::vector<sched::ProbeSpec> specs(2);
  specs[0].name = "good";
  specs[0].interval_s = 0;  // re-probe immediately
  specs[0].probe = [&good_runs](sched::Snapshot* out, bool*) {
    good_runs++;
    out->manager = resource::NewNullManager();
    usleep(10 * 1000);
    return Status::Ok();
  };
  specs[1].name = "bad";
  specs[1].backoff_initial_s = 0;
  specs[1].backoff_max_s = 1;
  specs[1].probe = [&bad_runs](sched::Snapshot*, bool*) {
    bad_runs++;
    usleep(10 * 1000);
    return Status::Error("still down");
  };
  auto t0 = std::chrono::steady_clock::now();
  {
    sched::ProbeBroker broker(store, specs);
    broker.Start();
    while (good_runs.load() < 3 &&
           std::chrono::steady_clock::now() - t0 < std::chrono::seconds(10)) {
      usleep(20 * 1000);
    }
    broker.Stop();
  }
  double elapsed = std::chrono::duration<double>(
                       std::chrono::steady_clock::now() - t0)
                       .count();
  CHECK_TRUE(good_runs.load() >= 3);
  CHECK_TRUE(bad_runs.load() >= 1);
  CHECK_TRUE(elapsed < 10);  // Stop() did not hang on healthy workers
  CHECK_TRUE(store->View("good").last_ok.has_value());
  sched::SourceView bad = store->View("bad");
  CHECK_TRUE(bad.settled && !bad.last_ok.has_value());
  CHECK_TRUE(bad.consecutive_failures >= 1);
}

void TestJournalCapacityDropOrdering() {
  // Bounded ring: drop-oldest, monotone seq, stable ordering.
  obs::Journal journal(3, /*metrics=*/false);
  CHECK_EQ(journal.capacity(), size_t{3});
  journal.Record("a", "s1", "first");
  journal.Record("b", "s2", "second");
  journal.Record("a", "s3", "third");
  CHECK_EQ(journal.dropped_total(), uint64_t{0});
  journal.Record("c", "s4", "fourth");  // evicts "first"
  CHECK_EQ(journal.dropped_total(), uint64_t{1});

  std::vector<obs::Event> events = journal.Snapshot();
  CHECK_EQ(events.size(), size_t{3});
  CHECK_EQ(events[0].message, "second");
  CHECK_EQ(events[2].message, "fourth");
  // seq is journal-global and monotone across drops.
  CHECK_EQ(events[0].seq, uint64_t{2});
  CHECK_EQ(events[1].seq, uint64_t{3});
  CHECK_EQ(events[2].seq, uint64_t{4});

  // Type filter + newest-n limit compose.
  journal.Record("a", "s5", "fifth");
  std::vector<obs::Event> only_a = journal.Snapshot(0, "a");
  CHECK_EQ(only_a.size(), size_t{2});
  CHECK_EQ(only_a.back().message, "fifth");
  CHECK_EQ(journal.Snapshot(1, "a").size(), size_t{1});
  CHECK_EQ(journal.Snapshot(1, "a")[0].message, "fifth");

  // Shrinking capacity drops oldest and counts the drops.
  journal.SetCapacity(1);
  CHECK_EQ(journal.Snapshot().size(), size_t{1});
  CHECK_TRUE(journal.dropped_total() >= 3);
}

void TestJournalGenerationCorrelation() {
  obs::Journal journal(8, /*metrics=*/false);
  journal.Record("pre", "", "before any rewrite");
  uint64_t g1 = journal.BeginRewrite();
  journal.Record("in1", "", "inside first rewrite");
  uint64_t g2 = journal.BeginRewrite();
  journal.Record("in2", "", "inside second rewrite");
  CHECK_TRUE(g2 == g1 + 1);
  std::vector<obs::Event> events = journal.Snapshot();
  CHECK_EQ(events[0].generation, uint64_t{0});
  CHECK_EQ(events[1].generation, g1);
  CHECK_EQ(events[2].generation, g2);
  // The correlation id is mirrored into the JSON log lines.
  CHECK_EQ(log::CurrentGeneration(), g2);
}

void TestTraceRecorderLifecycle() {
  // Mint -> stage -> publish-ack: the causal-trace ring's state
  // machine, bounded like the journal.
  obs::TraceRecorder trace(3, /*metrics=*/false);
  CHECK_EQ(trace.capacity(), size_t{3});
  CHECK_EQ(trace.LatestActiveChange(), uint64_t{0});
  uint64_t c1 = trace.Mint("snapshot", "tpu", "moved", 10.0);
  uint64_t c2 = trace.Mint("lifecycle", "lifecycle", "preempt", 11.0);
  CHECK_EQ(c1, uint64_t{1});
  CHECK_EQ(c2, uint64_t{2});
  CHECK_EQ(trace.active(), size_t{2});
  CHECK_EQ(trace.LatestActiveChange(), c2);
  CHECK_EQ(trace.LatestChange(), c2);

  // Stage stamps land on every ACTIVE record, first-wins.
  trace.Stage("plan", 12.0);
  trace.Stage("plan", 13.0);  // duplicate: must not move the mark
  // through_change bounds the ack: a change minted concurrently with
  // the publishing pass (id > what the pass captured at BeginRewrite)
  // was not in its content and must stay active for the next pass.
  uint64_t c3 = trace.Mint("snapshot", "tpu", "mid-pass", 13.5);
  trace.MarkPublished(9, 14.0, c2);
  CHECK_EQ(trace.active(), size_t{1});
  CHECK_EQ(trace.LatestActiveChange(), c3);
  trace.MarkPublished(10, 14.5);  // default: retire everything active
  CHECK_EQ(trace.active(), size_t{0});
  CHECK_EQ(trace.LatestActiveChange(), uint64_t{0});
  // A published record no longer accumulates stages.
  trace.Stage("render", 15.0);
  std::string json = trace.RenderJson();
  CHECK_TRUE(json.find("\"plan\":12.000000") != std::string::npos);
  CHECK_TRUE(json.find("13.000000") == std::string::npos);
  CHECK_TRUE(json.find("\"render\"") == std::string::npos);
  CHECK_TRUE(json.find("\"publish-acked\":14.000000") !=
             std::string::npos);
  CHECK_TRUE(json.find("\"generation\":9") != std::string::npos);

  // Ring bound: drop-oldest, counted; change ids stay monotone.
  trace.Mint("a", "", "", 20.0);
  trace.Mint("b", "", "", 21.0);
  CHECK_EQ(trace.dropped_total(), uint64_t{2});
  // The evicted record no longer renders (filter by its change id).
  CHECK_TRUE(trace.RenderJson(0, c1).find("\"records\":[]") !=
             std::string::npos);
  // Shrinking capacity drops oldest and counts the drops.
  trace.SetCapacity(1);
  CHECK_EQ(trace.dropped_total(), uint64_t{4});
  // The filtered render and the n-limit compose.
  uint64_t c5 = trace.LatestChange();
  std::string filtered = trace.RenderJson(1, c5);
  CHECK_TRUE(filtered.find("\"change\":" + std::to_string(c5)) !=
             std::string::npos);

  // Hostile bytes sanitize at ingestion (the fuzz target's oracle).
  obs::TraceRecorder hostile(2, /*metrics=*/false);
  hostile.Mint("or\x80igin", "s\xffrc", std::string("de\0tail", 7), 1.0);
  hostile.Stage(std::string("st\xc0\xafage"), 2.0);
  std::string doc = hostile.RenderJson();
  CHECK_TRUE(jsonlite::Parse(doc).ok());
  CHECK_EQ(jsonlite::SanitizeUtf8(doc), doc);
  CHECK_TRUE(jsonlite::Parse(hostile.RenderChromeTrace()).ok());
}

// The cross-language parity pin: this literal is ALSO embedded in
// tests/test_trace.py, where tpufd.trace.TraceRecorder replays the
// same scripted sequence — both implementations must reproduce it
// byte-for-byte, so the C++ recorder and the Python twin can never
// drift apart silently.
constexpr const char* kTraceGoldenJson =
    "{\"capacity\":4,\"dropped_total\":0,\"active\":1,\"minted_total\":2,"
    "\"records\":[{\"change\":1,\"generation\":7,\"minted_ts\":100.000000,"
    "\"origin\":\"snapshot\",\"source\":\"tpu\",\"detail\":\"probe "
    "snapshot moved\",\"published\":true,\"stages\":{\"plan\":100.250000,"
    "\"render\":100.500000,\"govern\":100.625000,\"publish\":101.000000,"
    "\"publish-acked\":101.125000}},{\"change\":2,\"generation\":0,"
    "\"minted_ts\":102.500000,\"origin\":\"slice-verdict\","
    "\"source\":\"slice\",\"detail\":\"verdict moved: 3/4 healthy "
    "(degraded)\",\"published\":false,\"stages\":{\"plan\":102.750000}}]}";

void TestTraceRecorderGoldenParity() {
  obs::TraceRecorder trace(4, /*metrics=*/false);
  CHECK_EQ(trace.Mint("snapshot", "tpu", "probe snapshot moved", 100.0),
           uint64_t{1});
  trace.Stage("plan", 100.25);
  trace.Stage("render", 100.5);
  trace.Stage("govern", 100.625);
  trace.Stage("publish", 101.0);
  trace.MarkPublished(7, 101.125);
  CHECK_EQ(trace.Mint("slice-verdict", "slice",
                      "verdict moved: 3/4 healthy (degraded)", 102.5),
           uint64_t{2});
  trace.Stage("plan", 102.75);
  CHECK_EQ(trace.RenderJson(), std::string(kTraceGoldenJson));

  // The Chrome rendering: valid JSON, complete events with integer
  // microsecond ts/dur, one slice per stage interval, tid = change.
  std::string chrome = trace.RenderChromeTrace();
  Result<jsonlite::ValuePtr> doc = jsonlite::Parse(chrome);
  CHECK_TRUE(doc.ok());
  if (doc.ok()) {
    jsonlite::ValuePtr events = (*doc)->Get("traceEvents");
    CHECK_EQ(events->array_items.size(), size_t{6});
    const jsonlite::Value& first = *events->array_items[0];
    CHECK_EQ(first.Get("name")->string_value, "plan");
    CHECK_EQ(first.Get("ph")->string_value, "X");
    CHECK_EQ(first.Get("ts")->number_value, 100000000.0);
    CHECK_EQ(first.Get("dur")->number_value, 250000.0);
    CHECK_EQ(first.Get("tid")->number_value, 1.0);
    CHECK_EQ(first.GetPath("args.generation")->string_value, "7");
    const jsonlite::Value& last = *events->array_items[5];
    CHECK_EQ(last.Get("name")->string_value, "plan");
    CHECK_EQ(last.Get("tid")->number_value, 2.0);
    CHECK_EQ(last.Get("cat")->string_value, "slice-verdict");
  }
}

// The SLO-engine cross-language parity pin: this literal is ALSO
// embedded in tests/test_trace.py, where tpufd.trace.StageSlo replays
// the same scripted fold/expire sequence — byte-for-byte, like the
// trace golden above.
constexpr const char* kSloGoldenJson =
    "{\"window_s\":60,\"samples\":2,\"folded_total\":3,\"retired_total\":1,"
    "\"last_change\":3,\"stages\":{\"plan\":{\"count\":1,\"p50_ms\":0.500,"
    "\"p99_ms\":0.500},\"render\":{\"count\":1,\"p50_ms\":40.090,"
    "\"p99_ms\":40.090},\"publish\":{\"count\":1,\"p50_ms\":2922.162,"
    "\"p99_ms\":2922.162}},\"serialized\":"
    "\"plan=0:1;render=46:1;publish=91:1\"}";

void TestStageSloGoldenParity() {
  obs::StageSlo slo(/*window_s=*/60);
  slo.Fold(1,
           {{"plan", 100.25},
            {"render", 12.5},
            {"publish", 480.0},
            {"publish-acked", 500.0}},
           100.0);
  slo.Fold(2, {{"plan", 0.0}, {"publish", 2900.0}}, 130.0);
  // Unknown stages never enter the sketches; a fold with ONLY unknown
  // stages would not count.
  slo.Fold(3, {{"render", 40.0}, {"junk", 5.0}}, 150.0);
  // Retire-oldest: the t=100 sample ages out (publish-acked empties
  // with it and drops from the document entirely).
  slo.Expire(170.0);
  CHECK_EQ(slo.RenderJson(), std::string(kSloGoldenJson));
  CHECK_EQ(slo.Serialize(), "plan=0:1;render=46:1;publish=91:1");
  CHECK_EQ(slo.samples(), int64_t{2});
  CHECK_EQ(slo.retired_total(), int64_t{1});

  // The serialized annotation round-trips through the aggregator's
  // parser into the same sketches the node holds.
  agg::StageSketches parsed = agg::ParseStageSketches(slo.Serialize());
  agg::StageSketches held = slo.Snapshot();
  CHECK_EQ(parsed.size(), held.size());
  for (const auto& [stage, sketch] : held) {
    CHECK_TRUE(parsed[stage] == sketch);
  }

  // Shrinking the window expires eagerly on the next touch; draining
  // everything leaves an empty serialization ("" = no annotation).
  slo.SetWindow(5);
  slo.Expire(170.0);
  CHECK_EQ(slo.samples(), int64_t{0});
  CHECK_EQ(slo.retired_total(), int64_t{3});
  CHECK_EQ(slo.Serialize(), "");
  CHECK_EQ(slo.folded_total(), int64_t{3});  // history, not window

  // A fold with no known stage counts nothing.
  obs::StageSlo quiet(60);
  quiet.Fold(9, {{"junk", 1.0}}, 10.0);
  CHECK_EQ(quiet.folded_total(), int64_t{0});
  CHECK_EQ(quiet.Serialize(), "");
}

void TestStageDurationsMs() {
  // The slicing rule shared with RenderChromeTrace: prev-stamp ->
  // stage-stamp intervals, minted_ts first, clamped at 0 against clock
  // steps, "govern" folded into "render", unknown stages dropped. The
  // SAME grids are pinned in tests/test_trace.py against
  // tpufd.trace.stage_durations_ms.
  obs::TraceRecord record;
  record.minted_ts = 100.0;
  record.stages = {{"plan", 100.25},
                   {"render", 100.5},
                   {"govern", 100.625},
                   {"publish", 101.0},
                   {"publish-acked", 101.125}};
  std::map<std::string, double> ms = obs::StageDurationsMs(record);
  CHECK_EQ(Fixed3(ms["plan"]), "250.000");
  CHECK_EQ(Fixed3(ms["render"]), "375.000");  // render 250 + govern 125
  CHECK_EQ(Fixed3(ms["publish"]), "375.000");
  CHECK_EQ(Fixed3(ms["publish-acked"]), "125.000");
  CHECK_EQ(ms.size(), size_t{4});

  obs::TraceRecord stepped;
  stepped.minted_ts = 10.0;
  stepped.stages = {{"plan", 9.0}, {"publish", 10.5}, {"junk", 11.0}};
  ms = obs::StageDurationsMs(stepped);
  CHECK_EQ(Fixed3(ms["plan"]), "0.000");  // clock step clamps, not -1000
  CHECK_EQ(Fixed3(ms["publish"]), "500.000");
  CHECK_EQ(ms.size(), size_t{2});
}

void TestJournalChangeCorrelation() {
  // Satellite (ISSUE 15): every journal event carries the change id
  // its pass was carrying, wired through BeginRewrite — so
  // /debug/journal joins to /debug/trace without timestamp heuristics.
  obs::Journal journal(8, /*metrics=*/false);
  journal.Record("pre", "", "before any rewrite");
  journal.BeginRewrite(41);
  journal.Record("in1", "", "inside the change-41 pass");
  journal.BeginRewrite();  // no change in flight -> 0
  journal.Record("in2", "", "quiet pass");
  std::vector<obs::Event> events = journal.Snapshot();
  CHECK_EQ(events[0].change, uint64_t{0});
  CHECK_EQ(events[1].change, uint64_t{41});
  CHECK_EQ(events[2].change, uint64_t{0});
  CHECK_EQ(journal.change(), uint64_t{0});
  // The id rides the rendered event AND the json log lines.
  CHECK_TRUE(obs::EventJson(events[1]).find("\"change\":41") !=
             std::string::npos);
  journal.BeginRewrite(99);
  CHECK_EQ(log::CurrentChange(), uint64_t{99});
  CHECK_TRUE(journal.RenderJson().find("\"change\":99") !=
             std::string::npos);
  std::string line = log::FormatLine(log::Severity::kInfo, "x",
                                     log::Format::kJson,
                                     1700000000000LL, 3, 99);
  CHECK_TRUE(line.find("\"change\":99") != std::string::npos);
  log::SetCurrentChange(0);
}

void TestDebugTraceEndpoint() {
  // /debug/trace over the real server socket: n= and change= filters,
  // and the document parses as strict JSON.
  obs::Registry reg;
  obs::TraceRecorder trace(16, /*metrics=*/false);
  trace.Mint("snapshot", "tpu", "first", 50.0);
  trace.Stage("plan", 50.5);
  trace.MarkPublished(3, 51.0);
  trace.Mint("watch-drift", "cr", "second", 60.0);

  obs::ServerOptions options;
  options.addr = "127.0.0.1:0";
  options.trace = &trace;
  Result<std::unique_ptr<obs::IntrospectionServer>> server =
      obs::IntrospectionServer::Start(options, &reg);
  CHECK_TRUE(server.ok());
  std::string base =
      "http://127.0.0.1:" + std::to_string((*server)->port());
  http::RequestOptions ropt;
  ropt.timeout_ms = 3000;

  Result<http::Response> r =
      http::Request("GET", base + "/debug/trace", "", ropt);
  CHECK_TRUE(r.ok());
  CHECK_EQ(r->status, 200);
  Result<jsonlite::ValuePtr> doc = jsonlite::Parse(
      r->body.substr(0, r->body.find_last_not_of('\n') + 1));
  CHECK_TRUE(doc.ok());
  if (doc.ok()) {
    CHECK_EQ((*doc)->Get("records")->array_items.size(), size_t{2});
    CHECK_EQ((*doc)->Get("active")->number_value, 1.0);
  }
  r = http::Request("GET", base + "/debug/trace?change=1&n=5", "", ropt);
  CHECK_TRUE(r.ok());
  doc = jsonlite::Parse(r->body.substr(0, r->body.size() - 1));
  CHECK_TRUE(doc.ok());
  if (doc.ok()) {
    jsonlite::ValuePtr records = (*doc)->Get("records");
    CHECK_EQ(records->array_items.size(), size_t{1});
    CHECK_EQ(records->array_items[0]->Get("origin")->string_value,
             "snapshot");
    CHECK_EQ(records->array_items[0]->Get("generation")->number_value,
             3.0);
  }
  // The 404 catalogue names the new endpoint.
  r = http::Request("GET", base + "/nope", "", ropt);
  CHECK_TRUE(r.ok());
  CHECK_EQ(r->status, 404);
  CHECK_TRUE(r->body.find("/debug/trace") != std::string::npos);
  (*server)->Stop();
}

void TestVerdictChangeEcho() {
  // The slice blackboard echoes the leader's change id: serialized
  // only when non-zero (older docs byte-identical), parsed back, and
  // NEVER part of content equality or the published labels.
  slice::SliceVerdict verdict;
  verdict.seq = 4;
  verdict.leader = "host-a";
  verdict.computed_at = 12.5;
  verdict.hosts = 4;
  verdict.healthy_hosts = 3;
  verdict.degraded = true;
  verdict.perf_class = "silver";
  verdict.members = {"host-a", "host-b", "host-c"};
  std::string without = slice::SerializeVerdict(verdict);
  CHECK_TRUE(without.find("change") == std::string::npos);
  verdict.change = 17;
  std::string with_change = slice::SerializeVerdict(verdict);
  CHECK_TRUE(with_change.find("\"change\":17") != std::string::npos);
  Result<slice::SliceVerdict> parsed = slice::ParseVerdict(with_change);
  CHECK_TRUE(parsed.ok());
  if (parsed.ok()) {
    CHECK_EQ(parsed->change, uint64_t{17});
    slice::SliceVerdict same = *parsed;
    same.change = 99;
    CHECK_TRUE(slice::VerdictContentEquals(*parsed, same));
  }
  Result<slice::SliceVerdict> old_doc = slice::ParseVerdict(without);
  CHECK_TRUE(old_doc.ok());
  if (old_doc.ok()) CHECK_EQ(old_doc->change, uint64_t{0});
}

void TestChangeAnnotationBodies() {
  // The change-id annotation on the wire bodies: merge patch sets just
  // the one annotation key (foreign annotations survive merge-patch
  // semantics), and the watch parse extracts it back out.
  lm::Labels acked = {{"google.com/a", "1"}};
  lm::Labels desired = {{"google.com/a", "2"}};
  std::string patch = k8s::BuildMergePatch(acked, desired, "node-1",
                                           /*fix_node_name=*/false, "12",
                                           /*change_annotation=*/"37");
  CHECK_TRUE(patch.find("\"annotations\":{\"tfd.google.com/"
                        "change-id\":\"37\"}") != std::string::npos);
  CHECK_TRUE(patch.find("\"resourceVersion\":\"12\"") !=
             std::string::npos);
  // Without a change in flight the patch is byte-identical to the
  // pre-trace wire format (no annotations key at all).
  std::string plain = k8s::BuildMergePatch(acked, desired, "node-1",
                                           false, "12");
  CHECK_TRUE(plain.find("annotations") == std::string::npos);

  // The stage-SLO annotation (ISSUE 16) rides NEXT TO the change id —
  // change id first — and alone when no change is in flight. The exact
  // bytes are pinned against the Python twin in tests/test_trace.py.
  std::string with_slo = k8s::BuildMergePatch(
      acked, desired, "node-1", false, "12",
      /*change_annotation=*/"37",
      /*slo_annotation=*/"plan=0:1;publish=91:1");
  CHECK_TRUE(with_slo.find(
                 "\"annotations\":{\"tfd.google.com/change-id\":\"37\","
                 "\"tfd.google.com/stage-slo\":"
                 "\"plan=0:1;publish=91:1\"}") != std::string::npos);
  std::string slo_only = k8s::BuildMergePatch(
      acked, desired, "node-1", false, "12", "", "plan=0:1");
  CHECK_TRUE(slo_only.find("\"annotations\":{\"tfd.google.com/"
                           "stage-slo\":\"plan=0:1\"}") !=
             std::string::npos);
  CHECK_TRUE(slo_only.find("change-id") == std::string::npos);

  k8s::WatchEvent event = k8s::ParseWatchEventLine(
      "{\"type\":\"MODIFIED\",\"object\":{\"metadata\":{\"name\":"
      "\"tfd-features-for-n1\",\"resourceVersion\":\"5\","
      "\"annotations\":{\"tfd.google.com/change-id\":\"37\","
      "\"other.io/x\":\"y\"}},\"spec\":{\"labels\":{\"a\":\"1\"}}}}");
  CHECK_EQ(event.change, "37");
  k8s::WatchEvent none = k8s::ParseWatchEventLine(
      "{\"type\":\"MODIFIED\",\"object\":{\"metadata\":{\"name\":\"x\","
      "\"resourceVersion\":\"5\"},\"spec\":{\"labels\":{}}}}");
  CHECK_EQ(none.change, "");
  // A non-string annotation value reads as absent, never crashes.
  k8s::WatchEvent hostile = k8s::ParseWatchEventLine(
      "{\"type\":\"MODIFIED\",\"object\":{\"metadata\":{\"name\":\"x\","
      "\"annotations\":{\"tfd.google.com/change-id\":12}},"
      "\"spec\":{\"labels\":{}}}}");
  CHECK_EQ(hostile.change, "");

  // The stage-slo annotation extracts alongside the change id (the
  // aggregator's merge input); absent or non-string reads as "".
  k8s::WatchEvent slo_event = k8s::ParseWatchEventLine(
      "{\"type\":\"MODIFIED\",\"object\":{\"metadata\":{\"name\":\"x\","
      "\"resourceVersion\":\"5\",\"annotations\":{"
      "\"tfd.google.com/change-id\":\"37\","
      "\"tfd.google.com/stage-slo\":\"plan=0:1;publish=91:1\"}},"
      "\"spec\":{\"labels\":{\"a\":\"1\"}}}}");
  CHECK_EQ(slo_event.change, "37");
  CHECK_EQ(slo_event.stage_slo, "plan=0:1;publish=91:1");
  CHECK_EQ(none.stage_slo, "");
  k8s::WatchEvent bad_slo = k8s::ParseWatchEventLine(
      "{\"type\":\"MODIFIED\",\"object\":{\"metadata\":{\"name\":\"x\","
      "\"annotations\":{\"tfd.google.com/stage-slo\":7}},"
      "\"spec\":{\"labels\":{}}}}");
  CHECK_EQ(bad_slo.stage_slo, "");
}

void TestSanitizeUtf8() {
  // Identity on valid UTF-8, including multi-byte and 4-byte planes.
  CHECK_EQ(jsonlite::SanitizeUtf8("plain ascii"), "plain ascii");
  CHECK_EQ(jsonlite::SanitizeUtf8("caf\xc3\xa9 \xe2\x82\xac "
                                  "\xf0\x9f\x99\x82"),
           "caf\xc3\xa9 \xe2\x82\xac \xf0\x9f\x99\x82");
  // Ill-formed sequences become U+FFFD: stray continuation, stray
  // lead, overlong, surrogate encoding, truncated tail.
  const char* fffd = "\xef\xbf\xbd";
  CHECK_EQ(jsonlite::SanitizeUtf8("a\x80z"), std::string("a") + fffd + "z");
  CHECK_EQ(jsonlite::SanitizeUtf8("a\xffz"), std::string("a") + fffd + "z");
  CHECK_EQ(jsonlite::SanitizeUtf8("\xc0\xaf"),
           std::string(fffd) + fffd);  // overlong '/'
  CHECK_EQ(jsonlite::SanitizeUtf8("\xed\xa0\x80"),
           std::string(fffd) + fffd + fffd);  // UTF-8-encoded surrogate
  CHECK_EQ(jsonlite::SanitizeUtf8("tail\xc3"),
           std::string("tail") + fffd);  // truncated 2-byte seq
  // Idempotent: sanitizing sanitized text is identity (the fuzz
  // target's valid-UTF-8 oracle rides on this).
  std::string once = jsonlite::SanitizeUtf8("x\xfe\xc3(\xf5y");
  CHECK_EQ(jsonlite::SanitizeUtf8(once), once);
}

void TestJournalJsonHostileBytes() {
  // /debug/journal exposition must stay valid JSON *and* valid UTF-8
  // for ANY payload bytes (the fuzz target's oracle, pinned here
  // deterministically) — strict consumers (Python json.load) must
  // always decode what the endpoint serves.
  obs::Journal journal(4, /*metrics=*/false);
  std::string hostile = "quote\" slash\\ newline\n tab\t ctrl\x01 "
                        "high\xff\xc3(";
  journal.Record(hostile, hostile, hostile, {{hostile, hostile}});
  std::string json = journal.RenderJson();
  CHECK_EQ(jsonlite::SanitizeUtf8(json), json);  // already valid UTF-8
  Result<jsonlite::ValuePtr> doc = jsonlite::Parse(json);
  CHECK_TRUE(doc.ok());
  if (doc.ok()) {
    jsonlite::ValuePtr events = (*doc)->Get("events");
    CHECK_TRUE(events != nullptr &&
               events->kind == jsonlite::Value::Kind::kArray);
    // Round-trip: sanitized at ingestion (invalid bytes -> U+FFFD),
    // then preserved exactly.
    jsonlite::ValuePtr message = events->array_items[0]->Get("message");
    CHECK_TRUE(message != nullptr &&
               message->string_value == jsonlite::SanitizeUtf8(hostile));
  }

  // The journal metrics register in the default registry (exposition
  // stays valid — the registry sanitizes/escapes).
  obs::DefaultJournal().Record("unit-test", "", "metrics registration");
  CHECK_TRUE(obs::ValidateExposition(obs::Default().Exposition()).ok());
}

void TestLabelDiff() {
  lm::Labels prev{{"a", "1"}, {"b", "2"}, {"c", "3"}};
  lm::Labels next{{"b", "2"}, {"c", "9"}, {"d", "4"}};
  std::vector<lm::LabelDiffEntry> diff = lm::DiffLabels(prev, next);
  CHECK_EQ(diff.size(), size_t{3});
  CHECK_EQ(diff[0].key, "a");
  CHECK_EQ(std::string(lm::DiffOpName(diff[0].op)), "removed");
  CHECK_EQ(diff[0].old_value, "1");
  CHECK_EQ(diff[1].key, "c");
  CHECK_EQ(std::string(lm::DiffOpName(diff[1].op)), "changed");
  CHECK_EQ(diff[1].old_value, "3");
  CHECK_EQ(diff[1].new_value, "9");
  CHECK_EQ(diff[2].key, "d");
  CHECK_EQ(std::string(lm::DiffOpName(diff[2].op)), "added");
  CHECK_EQ(diff[2].new_value, "4");

  CHECK_TRUE(lm::DiffLabels(prev, prev).empty());
  CHECK_EQ(lm::DiffLabels({}, next).size(), next.size());
  CHECK_EQ(lm::DiffLabels(prev, {}).size(), prev.size());
}

void TestLabelKeyPrefix() {
  CHECK_EQ(lm::LabelKeyPrefix("google.com/tpu.count"), "google.com/tpu");
  CHECK_EQ(lm::LabelKeyPrefix("google.com/tfd.timestamp"),
           "google.com/tfd");
  CHECK_EQ(lm::LabelKeyPrefix("google.com/tpu.health.ok"),
           "google.com/tpu");
  CHECK_EQ(lm::LabelKeyPrefix("google.com/tpu-vm.present"),
           "google.com/tpu-vm");
  CHECK_EQ(lm::LabelKeyPrefix("noslash"), "noslash");
  CHECK_EQ(lm::LabelKeyPrefix("plain.key"), "plain");
  CHECK_EQ(lm::LabelKeyPrefix("google.com/nodot"), "google.com/nodot");
}

void TestLogFormatLine() {
  // klog: byte-compatible with the pre-journal format.
  std::string klog = log::FormatLine(log::Severity::kWarning, "hello",
                                     log::Format::kKlog,
                                     1700000000123LL, 7);
  CHECK_TRUE(klog.size() > 2 && klog[0] == 'W');
  CHECK_TRUE(klog.find(" tpu-feature-discovery: hello") !=
             std::string::npos);

  // json: one valid JSON object carrying ts / generation / severity /
  // message (the journal event schema's shared keys).
  std::string json = log::FormatLine(log::Severity::kError,
                                     "msg with \"quotes\"\nand newline",
                                     log::Format::kJson,
                                     1700000000123LL, 42);
  Result<jsonlite::ValuePtr> doc = jsonlite::Parse(json);
  CHECK_TRUE(doc.ok());
  if (doc.ok()) {
    CHECK_EQ((*doc)->Get("severity")->string_value, "error");
    CHECK_EQ((*doc)->Get("type")->string_value, "log");
    CHECK_EQ((*doc)->Get("generation")->number_value, 42.0);
    CHECK_EQ((*doc)->Get("message")->string_value,
             "msg with \"quotes\"\nand newline");
    CHECK_TRUE((*doc)->Get("ts")->number_value > 1.6e9);
  }
}

void TestDebugEndpoints() {
  // /debug/journal (filtering) and /debug/labels (handed-over document)
  // over the real server socket.
  obs::Registry reg;
  obs::Journal journal(16, /*metrics=*/false);
  journal.Record("label-diff", "mock", "added x");
  journal.Record("probe-ok", "mock", "probe ok");
  journal.Record("label-diff", "mock", "changed y");

  obs::ServerOptions options;
  options.addr = "127.0.0.1:0";
  options.journal = &journal;
  Result<std::unique_ptr<obs::IntrospectionServer>> server =
      obs::IntrospectionServer::Start(options, &reg);
  CHECK_TRUE(server.ok());
  std::string base =
      "http://127.0.0.1:" + std::to_string((*server)->port());
  http::RequestOptions ropt;
  ropt.timeout_ms = 3000;

  Result<http::Response> r =
      http::Request("GET", base + "/debug/journal", "", ropt);
  CHECK_TRUE(r.ok());
  CHECK_EQ(r->status, 200);
  Result<jsonlite::ValuePtr> doc = jsonlite::Parse(
      r->body.substr(0, r->body.find_last_not_of('\n') + 1));
  CHECK_TRUE(doc.ok());
  if (doc.ok()) {
    CHECK_EQ((*doc)->Get("events")->array_items.size(), size_t{3});
  }

  r = http::Request("GET", base + "/debug/journal?type=label-diff&n=1",
                    "", ropt);
  CHECK_TRUE(r.ok());
  doc = jsonlite::Parse(r->body.substr(0, r->body.size() - 1));
  CHECK_TRUE(doc.ok());
  if (doc.ok()) {
    jsonlite::ValuePtr events = (*doc)->Get("events");
    CHECK_EQ(events->array_items.size(), size_t{1});
    CHECK_EQ(events->array_items[0]->Get("message")->string_value,
             "changed y");
  }

  // /debug/labels: 503 before the first handover, then the document.
  r = http::Request("GET", base + "/debug/labels", "", ropt);
  CHECK_TRUE(r.ok());
  CHECK_EQ(r->status, 503);
  (*server)->SetLabelsJson("{\"generation\":1,\"labels\":{\"k\":\"v\"},"
                           "\"provenance\":{}}");
  r = http::Request("GET", base + "/debug/labels", "", ropt);
  CHECK_TRUE(r.ok());
  CHECK_EQ(r->status, 200);
  doc = jsonlite::Parse(r->body.substr(0, r->body.size() - 1));
  CHECK_TRUE(doc.ok());
  if (doc.ok()) {
    CHECK_EQ((*doc)->GetPath("labels.k")->string_value, "v");
  }
  (*server)->Stop();
}

void TestBackendCandidatesList() {
  config::Config config;
  config.flags.backend = "null";
  std::vector<resource::BackendCandidate> candidates =
      resource::BackendCandidates(config);
  CHECK_EQ(candidates.size(), size_t{1});
  CHECK_EQ(candidates[0].name, "null");
  Result<resource::ManagerPtr> made = candidates[0].make();
  CHECK_TRUE(made.ok());
  CHECK_EQ((*made)->Name(), "null");

  // Construction-shaped errors surface through the Result, per probe.
  config.flags.backend = "mock";
  config.flags.mock_topology_file = "/nonexistent/fixture.yaml";
  candidates = resource::BackendCandidates(config);
  CHECK_EQ(candidates.size(), size_t{1});
  CHECK_TRUE(!candidates[0].make().ok());

  // Explicit backends yield exactly one candidate; `make` builds a
  // FRESH manager each call (Init is one-shot per object).
  config.flags.backend = "metadata";
  candidates = resource::BackendCandidates(config);
  CHECK_EQ(candidates.size(), size_t{1});
  CHECK_EQ(candidates[0].name, "metadata");
  Result<resource::ManagerPtr> first = candidates[0].make();
  Result<resource::ManagerPtr> second = candidates[0].make();
  CHECK_TRUE(first.ok() && second.ok());
  CHECK_TRUE(first->get() != second->get());
}

// ---- fault injection / robustness (ISSUE 4) ------------------------------

void TestFaultSpecParse() {
  // The grammar the README documents, end to end.
  CHECK_TRUE(fault::Validate("").ok());
  CHECK_TRUE(fault::Validate("sink.file:errno=ENOSPC:rate=0.3,"
                             "k8s.put:http=500:count=3,"
                             "k8s.connect:hang=2s,"
                             "probe.pjrt:crash,"
                             "state.write:torn,"
                             "config.load:fail:seed=7")
                 .ok());
  CHECK_TRUE(fault::Validate("sink.file:errno=ENOSPC:hang=10ms").ok() ==
             false);  // two actions
  CHECK_TRUE(!fault::Validate("sink.file").ok());             // no action
  CHECK_TRUE(!fault::Validate("sink.file:rate=0.5").ok());    // no action
  CHECK_TRUE(!fault::Validate("sink.file:errno=EWHAT").ok());
  CHECK_TRUE(!fault::Validate("sink.file:fail:rate=1.5").ok());
  CHECK_TRUE(!fault::Validate("sink.file:fail:rate=nan").ok());
  CHECK_TRUE(!fault::Validate("sink.file:http=999").ok());
  CHECK_TRUE(!fault::Validate("sink.file:fail:count=0").ok());
  CHECK_TRUE(!fault::Validate("sink.file:fail:bogus=1").ok());
  CHECK_TRUE(!fault::Validate(":fail").ok());                 // empty point

  // Disarmed: every check is falsy (and costs one atomic load).
  fault::Disarm();
  CHECK_TRUE(!fault::Armed());
  CHECK_TRUE(!fault::Check("sink.file"));

  // count consumes per-injection; other points never match.
  CHECK_TRUE(fault::Arm("x.y:fail=boom:count=2").ok());
  CHECK_TRUE(fault::Armed());
  CHECK_TRUE(!fault::Check("x.z"));
  fault::Action first = fault::Check("x.y");
  CHECK_TRUE(first.kind == fault::Action::Kind::kFail);
  CHECK_TRUE(first.message.find("x.y") != std::string::npos);
  // The custom fail=<msg> text survives into the injected message.
  CHECK_TRUE(first.message.find("boom") != std::string::npos);
  CHECK_TRUE(fault::Check("x.y"));
  CHECK_TRUE(!fault::Check("x.y"));  // exhausted

  // Spec-order sequencing on one point: 429 then 500, then nothing.
  CHECK_TRUE(
      fault::Arm("k8s.get:http=429:count=1,k8s.get:http=500:count=1").ok());
  CHECK_EQ(fault::Check("k8s.get").http_status, 429);
  CHECK_EQ(fault::Check("k8s.get").http_status, 500);
  CHECK_TRUE(!fault::Check("k8s.get"));

  // Point/action compatibility: actions a site would ignore must not
  // arm (they would be counted as injected while doing nothing).
  CHECK_TRUE(!fault::Validate("sink.file:http=500").ok());
  CHECK_TRUE(!fault::Validate("probe.pjrt:http=500").ok());
  CHECK_TRUE(!fault::Validate("sink.file:torn").ok());
  CHECK_TRUE(fault::Validate("state.write:torn").ok());
  CHECK_TRUE(fault::Validate("k8s.put:http=500").ok());

  // rate=0 never fires; a seeded rate replays the same fire pattern.
  CHECK_TRUE(fault::Arm("r.s:fail:rate=0").ok());
  for (int i = 0; i < 20; i++) CHECK_TRUE(!fault::Check("r.s"));
  auto draw_pattern = [] {
    std::string pattern;
    for (int i = 0; i < 32; i++) {
      pattern += fault::Check("r.s") ? '1' : '0';
    }
    return pattern;
  };
  CHECK_TRUE(fault::Arm("r.s:fail:rate=0.5:seed=11").ok());
  std::string run1 = draw_pattern();
  CHECK_TRUE(fault::Arm("r.s:fail:rate=0.5:seed=11").ok());
  std::string run2 = draw_pattern();
  CHECK_EQ(run1, run2);
  CHECK_TRUE(run1.find('1') != std::string::npos);
  CHECK_TRUE(run1.find('0') != std::string::npos);

  // hang sleeps inside Check (the delay IS the fault).
  CHECK_TRUE(fault::Arm("h.i:hang=20ms").ok());
  auto t0 = std::chrono::steady_clock::now();
  CHECK_TRUE(fault::Check("h.i").kind == fault::Action::Kind::kHang);
  CHECK_TRUE(std::chrono::steady_clock::now() - t0 >=
             std::chrono::milliseconds(18));
  fault::Disarm();
  CHECK_TRUE(!fault::Check("h.i"));
}

void TestFaultSinkFile() {
  std::string dir = "/tmp/tfd-unit-fault-" + std::to_string(getpid());
  std::string path = dir + "/labels";
  lm::Labels labels{{"google.com/tpu.count", "4"}};

  // Injected ENOSPC: the write fails AND is classified transient — the
  // daemon must survive it — and the real file is never touched (a full
  // disk leaves the previous labels in place).
  CHECK_TRUE(fault::Arm("sink.file:errno=ENOSPC:count=1").ok());
  bool transient = false;
  Status s = lm::OutputToFile(labels, path, &transient);
  CHECK_TRUE(!s.ok());
  CHECK_TRUE(s.message().find("injected") != std::string::npos);
  CHECK_TRUE(transient);
  CHECK_TRUE(!FileExists(path));
  // Fault exhausted: the next write lands.
  CHECK_TRUE(lm::OutputToFile(labels, path, &transient).ok());
  CHECK_EQ(*ReadFile(path), "google.com/tpu.count=4\n");

  // EACCES is configuration, not weather: permanent.
  CHECK_TRUE(fault::Arm("sink.file:errno=EACCES:count=1").ok());
  transient = true;
  CHECK_TRUE(!lm::OutputToFile(labels, path, &transient).ok());
  CHECK_TRUE(!transient);
  fault::Disarm();
  std::string cmd = "rm -rf " + dir;
  CHECK_TRUE(system(cmd.c_str()) == 0);
}

void TestCircuitBreaker() {
  k8s::CircuitBreaker breaker(k8s::CircuitBreaker::Options{3, 30});
  CHECK_TRUE(breaker.state() == k8s::CircuitBreaker::State::kClosed);
  CHECK_TRUE(breaker.Allow());

  // Two failures: still closed (under the threshold).
  breaker.RecordTransientFailure();
  breaker.RecordTransientFailure();
  CHECK_TRUE(breaker.state() == k8s::CircuitBreaker::State::kClosed);
  CHECK_TRUE(breaker.Allow());
  // Third consecutive: open; writes skip.
  breaker.RecordTransientFailure();
  CHECK_TRUE(breaker.state() == k8s::CircuitBreaker::State::kOpen);
  CHECK_TRUE(!breaker.Allow());
  CHECK_EQ(breaker.consecutive_failures(), 3);

  // Cooldown elapses: exactly ONE half-open probe is admitted.
  breaker.AgeForTest(31);
  CHECK_TRUE(breaker.Allow());
  CHECK_TRUE(breaker.state() == k8s::CircuitBreaker::State::kHalfOpen);
  CHECK_TRUE(!breaker.Allow());  // probe in flight
  // Probe fails: straight back to open, cooldown restarted.
  breaker.RecordTransientFailure();
  CHECK_TRUE(breaker.state() == k8s::CircuitBreaker::State::kOpen);
  CHECK_TRUE(!breaker.Allow());
  // Probe succeeds after the next cooldown: closed, streak reset.
  breaker.AgeForTest(31);
  CHECK_TRUE(breaker.Allow());
  breaker.RecordSuccess();
  CHECK_TRUE(breaker.state() == k8s::CircuitBreaker::State::kClosed);
  CHECK_EQ(breaker.consecutive_failures(), 0);
  CHECK_TRUE(breaker.Allow());

  // A success mid-streak resets the consecutive count: 2 failures,
  // success, 2 failures never opens a threshold-3 breaker.
  breaker.RecordTransientFailure();
  breaker.RecordTransientFailure();
  breaker.RecordSuccess();
  breaker.RecordTransientFailure();
  breaker.RecordTransientFailure();
  CHECK_TRUE(breaker.state() == k8s::CircuitBreaker::State::kClosed);

  // A PERMANENT failure during the half-open probe must release the
  // probe slot (else Allow() wedges at false forever) and close the
  // circuit: the endpoint answered, so the breaker does not apply.
  breaker.RecordTransientFailure();
  CHECK_TRUE(breaker.state() == k8s::CircuitBreaker::State::kOpen);
  breaker.AgeForTest(31);
  CHECK_TRUE(breaker.Allow());  // half-open probe admitted
  breaker.RecordPermanentFailure();
  CHECK_TRUE(breaker.state() == k8s::CircuitBreaker::State::kClosed);
  CHECK_TRUE(breaker.Allow());
  CHECK_EQ(breaker.consecutive_failures(), 0);
}

// ---- health state machine (healthsm/) ------------------------------------

void TestSnapshotFingerprintIgnoresMeasurements() {
  // Measured health values (probe-ms, throughput numbers) move between
  // re-measures on perfectly healthy silicon; the flap fingerprint must
  // only see the structural verdicts, or every health re-measure reads
  // as content instability.
  sched::Snapshot a;
  a.labels = {{"google.com/tpu.health.ok", "true"},
              {"google.com/tpu.health.devices", "4"},
              {"google.com/tpu.health.device-0-ok", "true"},
              {"google.com/tpu.health.probe-ms", "812"},
              {"google.com/tpu.health.matmul-tflops", "918"}};
  sched::Snapshot b = a;
  b.labels["google.com/tpu.health.probe-ms"] = "977";
  b.labels["google.com/tpu.health.matmul-tflops"] = "912";
  CHECK_EQ(SnapshotFingerprint(a), SnapshotFingerprint(b));

  // A source-level structural change (aggregate verdict, chip count,
  // any non-health fact) DOES move it...
  sched::Snapshot c = a;
  c.labels["google.com/tpu.health.ok"] = "false";
  CHECK_TRUE(SnapshotFingerprint(c) != SnapshotFingerprint(a));
  sched::Snapshot d = a;
  d.labels["google.com/tpu.count"] = "2";
  CHECK_TRUE(SnapshotFingerprint(d) != SnapshotFingerprint(a));

  // ...but a per-chip device line does NOT: each chip has its own
  // healthsm entry, and hashing its verdict into the source
  // fingerprint too would let one flapping chip quarantine the whole
  // source instead of quarantining alone.
  sched::Snapshot e = a;
  e.labels["google.com/tpu.health.device-0-ok"] = "false";
  CHECK_EQ(SnapshotFingerprint(e), SnapshotFingerprint(a));
}

void TestFullSnapshotFingerprint() {
  // The pass planner's fingerprint must see what the flap fingerprint
  // deliberately ignores: a moved MEASUREMENT re-renders the pass (the
  // forced-slow daemon would republish it), even though it is not flap
  // evidence.
  sched::Snapshot a;
  a.labels = {{"google.com/tpu.health.ok", "true"},
              {"google.com/tpu.health.probe-ms", "812"},
              {"google.com/tpu.health.matmul-tflops", "918"}};
  sched::Snapshot b = a;
  b.labels["google.com/tpu.health.matmul-tflops"] = "912";
  CHECK_EQ(SnapshotFingerprint(a), SnapshotFingerprint(b));  // flap: equal
  CHECK_TRUE(sched::FullSnapshotFingerprint(a) !=
             sched::FullSnapshotFingerprint(b));  // planner: dirty
  sched::Snapshot c = a;
  CHECK_EQ(sched::FullSnapshotFingerprint(a),
           sched::FullSnapshotFingerprint(c));
  CHECK_TRUE(sched::FullSnapshotFingerprint(a) != 0);
}

void TestSnapshotStoreGenerations() {
  sched::SnapshotStore store;
  sched::TierPolicy policy;
  store.Register("pjrt", policy, /*device_source=*/true);
  store.Register("metadata", policy, /*device_source=*/true);

  std::vector<sched::SourceGeneration> gens = store.Generations();
  CHECK_EQ(gens.size(), static_cast<size_t>(2));
  CHECK_EQ(gens[0].source, "pjrt");  // registration order
  CHECK_EQ(gens[0].generation, static_cast<uint64_t>(0));
  CHECK_TRUE(!gens[0].has_snapshot);

  sched::Snapshot snap;
  snap.labels = {{"google.com/tpu.count", "4"}};
  store.PutOk("pjrt", snap);
  gens = store.Generations();
  CHECK_EQ(gens[0].generation, static_cast<uint64_t>(1));
  uint64_t first_fp = gens[0].content_fingerprint;
  CHECK_TRUE(first_fp != 0);
  CHECK_TRUE(gens[0].has_snapshot);
  CHECK_TRUE(gens[0].tier == sched::Tier::kFresh);

  // An identical re-probe bumps the generation but keeps the content
  // fingerprint — the planner's "nothing actually moved" signal.
  sched::Snapshot same;
  same.labels = {{"google.com/tpu.count", "4"}};
  store.PutOk("pjrt", same);
  gens = store.Generations();
  CHECK_EQ(gens[0].generation, static_cast<uint64_t>(2));
  CHECK_EQ(gens[0].content_fingerprint, first_fp);

  // Content movement moves the fingerprint.
  sched::Snapshot changed;
  changed.labels = {{"google.com/tpu.count", "2"}};
  store.PutOk("pjrt", changed);
  gens = store.Generations();
  CHECK_TRUE(gens[0].content_fingerprint != first_fp);

  // A failure flips `failing` (and bumps the generation) without
  // touching the last-ok fingerprint.
  uint64_t pre_fail_fp = gens[0].content_fingerprint;
  store.PutError("pjrt", "chips busy");
  gens = store.Generations();
  CHECK_TRUE(gens[0].failing);
  CHECK_EQ(gens[0].generation, static_cast<uint64_t>(4));
  CHECK_EQ(gens[0].content_fingerprint, pre_fail_fp);

  // Invalidation (config regen) zeroes the memo.
  store.InvalidateAll();
  gens = store.Generations();
  CHECK_EQ(gens[0].content_fingerprint, static_cast<uint64_t>(0));
  CHECK_TRUE(!gens[0].has_snapshot);
}

void TestPassSignature() {
  lm::PassSignature a;
  a.Mix("pjrt");
  a.MixU64(42);
  lm::PassSignature b;
  b.Mix("pjrt");
  b.MixU64(42);
  CHECK_EQ(a.Digest(), b.Digest());
  CHECK_TRUE(a.Digest() != 0);

  lm::PassSignature c;  // field boundaries matter
  c.Mix("pjr");
  c.Mix("t");
  c.MixU64(42);
  CHECK_TRUE(c.Digest() != a.Digest());

  lm::PassSignature d;  // order matters
  d.MixU64(42);
  d.Mix("pjrt");
  CHECK_TRUE(d.Digest() != a.Digest());
}

void TestFormatLabelsInto() {
  lm::Labels labels = {{"b", "2"}, {"a", "1"}, {"c", "x=y"}};
  CHECK_EQ(lm::FormatLabels(labels), "a=1\nb=2\nc=x=y\n");
  // The reused-buffer serializer produces identical bytes and keeps
  // its capacity across passes — steady state allocates nothing.
  std::string buffer;
  lm::FormatLabelsInto(labels, &buffer);
  CHECK_EQ(buffer, lm::FormatLabels(labels));
  buffer.reserve(4096);
  const size_t capacity = buffer.capacity();
  lm::FormatLabelsInto(labels, &buffer);
  CHECK_EQ(buffer, lm::FormatLabels(labels));
  CHECK_EQ(buffer.capacity(), capacity);
}

void TestTouchLabelFile() {
  std::string path = WriteTemp("a=1\n");
  struct stat before {};
  CHECK_TRUE(stat(path.c_str(), &before) == 0);
  // Matching size: touched, mtime advances (the cadence proof the
  // sleep-loop contract watches), bytes untouched.
  struct timespec old_time {};
  old_time.tv_sec = before.st_mtime - 100;
  struct timespec times[2] = {old_time, old_time};
  utimensat(AT_FDCWD, path.c_str(), times, 0);
  CHECK_TRUE(lm::TouchLabelFile(path, 4).ok());
  struct stat after {};
  CHECK_TRUE(stat(path.c_str(), &after) == 0);
  CHECK_TRUE(after.st_mtime > old_time.tv_sec);
  // Size mismatch (external truncation/tamper) and a missing file both
  // refuse, so the caller falls back to a real write.
  CHECK_TRUE(!lm::TouchLabelFile(path, 5).ok());
  unlink(path.c_str());
  CHECK_TRUE(!lm::TouchLabelFile(path, 4).ok());
}

void TestFragmentCacheTpuBuildOnce() {
  lm::FragmentCache cache;
  config::Config config;
  resource::ManagerPtr manager = resource::NewNullManager();
  long long before = lm::TpuLabelerBuilds();
  // A 10-pass no-op loop (same source, same render key, same config
  // generation) constructs the labeler pipeline exactly ONCE — the
  // per-(manager, config-generation) cache ISSUE 7 asks for.
  for (int i = 0; i < 10; i++) {
    Result<lm::Labels> labels =
        cache.TpuFragment(manager, "mock", /*render_key=*/7,
                          /*config_generation=*/1, config);
    CHECK_TRUE(labels.ok());
  }
  CHECK_EQ(lm::TpuLabelerBuilds() - before, 1LL);
  // A moved render key (dirty source) rebuilds once...
  CHECK_TRUE(cache.TpuFragment(manager, "mock", 8, 1, config).ok());
  CHECK_EQ(lm::TpuLabelerBuilds() - before, 2LL);
  // ...and so does a config reload.
  CHECK_TRUE(cache.TpuFragment(manager, "mock", 8, 2, config).ok());
  CHECK_EQ(lm::TpuLabelerBuilds() - before, 3LL);
  // Invalidate drops everything.
  cache.Invalidate();
  CHECK_TRUE(cache.TpuFragment(manager, "mock", 8, 2, config).ok());
  CHECK_EQ(lm::TpuLabelerBuilds() - before, 4LL);
}

void TestFragmentCacheHostFragment() {
  // A counting labeler: the host fragment must render once per config
  // generation, not once per pass.
  class CountingLabeler : public lm::Labeler {
   public:
    Result<lm::Labels> GetLabels() override {
      calls++;
      return lm::Labels{{"k", std::to_string(calls)}};
    }
    int calls = 0;
  };
  lm::FragmentCache cache;
  CountingLabeler labeler;
  for (int i = 0; i < 5; i++) {
    Result<lm::Labels> labels = cache.HostFragment("count", labeler, 1);
    CHECK_TRUE(labels.ok() && labels->at("k") == "1");
  }
  CHECK_EQ(labeler.calls, 1);
  Result<lm::Labels> reloaded = cache.HostFragment("count", labeler, 2);
  CHECK_TRUE(reloaded.ok() && reloaded->at("k") == "2");
  CHECK_EQ(labeler.calls, 2);
  // force_refresh (the anti-entropy host-refresh pass) re-renders AND
  // re-caches — a transiently degraded read must not stay frozen for
  // the config generation's lifetime.
  Result<lm::Labels> forced = cache.HostFragment("count", labeler, 2,
                                                 /*force_refresh=*/true);
  CHECK_TRUE(forced.ok() && forced->at("k") == "3");
  CHECK_EQ(labeler.calls, 3);
  Result<lm::Labels> cached = cache.HostFragment("count", labeler, 2);
  CHECK_TRUE(cached.ok() && cached->at("k") == "3");
  CHECK_EQ(labeler.calls, 3);
}

void TestGovernorPendingSuppressions() {
  // The pass planner's timer introspection: a suppressed flip keeps
  // PendingSuppressions() true (forcing slow passes) until a pass
  // applies clean — the held candidate becomes publishable on a TIMER,
  // with no snapshot movement to dirty the pass.
  lm::GovernorPolicy policy;
  policy.hold_down_s = 100;
  policy.churn_budget = 10;
  lm::LabelGovernor governor(policy);
  CHECK_TRUE(!governor.PendingSuppressions());

  lm::Labels previous = {{"google.com/tpu.count", "4"}};
  lm::Provenance prev_prov;
  double now = 1000;
  // Establish the published set (first appearance passes through).
  lm::Labels candidate = previous;
  lm::Provenance prov;
  std::vector<lm::SuppressedFlip> suppressed;
  governor.Apply({}, {}, false, now, &candidate, &prov, &suppressed);
  governor.CommitPublished();
  CHECK_TRUE(!governor.PendingSuppressions());

  // A flip inside the hold-down is suppressed -> pending.
  candidate = {{"google.com/tpu.count", "2"}};
  suppressed.clear();
  governor.Apply(previous, prev_prov, false, now + 10, &candidate, &prov,
                 &suppressed);
  CHECK_EQ(suppressed.size(), static_cast<size_t>(1));
  CHECK_TRUE(governor.PendingSuppressions());

  // After the hold-down expires the same flip applies clean -> cleared.
  candidate = {{"google.com/tpu.count", "2"}};
  suppressed.clear();
  governor.Apply(previous, prev_prov, false, now + 200, &candidate, &prov,
                 &suppressed);
  CHECK_EQ(suppressed.size(), static_cast<size_t>(0));
  CHECK_TRUE(!governor.PendingSuppressions());
  CHECK_EQ(candidate.at("google.com/tpu.count"), "2");
}

void TestHealthStateMachineTransitions() {
  healthsm::Policy policy;
  policy.flap_window_s = 60;
  policy.flap_threshold = 100;  // flap detection out of the way here
  policy.unhealthy_after = 2;
  policy.recover_after = 3;
  policy.quarantine_cooldown_s = 30;
  healthsm::HealthTracker tracker(policy);
  double t = 1000;
  using S = healthsm::State;

  // Unknown keys are healthy; a clean observation keeps them there.
  CHECK_TRUE(tracker.StateOf("pjrt", t) == S::kHealthy);
  CHECK_TRUE(tracker.Observe("pjrt", true, 7, t) == S::kHealthy);

  // healthy -> suspect on the first failure; clean -> straight back.
  CHECK_TRUE(tracker.Observe("pjrt", false, 0, t += 1) == S::kSuspect);
  CHECK_TRUE(tracker.Observe("pjrt", true, 7, t += 1) == S::kHealthy);

  // suspect hardens into unhealthy after unhealthy_after failures.
  CHECK_TRUE(tracker.Observe("pjrt", false, 0, t += 1) == S::kSuspect);
  CHECK_TRUE(tracker.Observe("pjrt", false, 0, t += 1) == S::kUnhealthy);
  // Further failures stay unhealthy.
  CHECK_TRUE(tracker.Observe("pjrt", false, 0, t += 1) == S::kUnhealthy);

  // unhealthy -> recovering on the first clean probe; recover_after
  // consecutive cleans close it healthy — and a failure mid-recovery
  // falls back to unhealthy.
  CHECK_TRUE(tracker.Observe("pjrt", true, 7, t += 1) == S::kRecovering);
  CHECK_TRUE(tracker.Observe("pjrt", false, 0, t += 1) == S::kUnhealthy);
  CHECK_TRUE(tracker.Observe("pjrt", true, 7, t += 1) == S::kRecovering);
  CHECK_TRUE(tracker.Observe("pjrt", true, 7, t += 1) == S::kRecovering);
  CHECK_TRUE(tracker.Observe("pjrt", true, 7, t += 1) == S::kHealthy);

  // A successful probe whose CONTENT moved is suspect, not clean: the
  // fingerprint comparison is what catches a source whose facts
  // alternate while every probe "works".
  CHECK_TRUE(tracker.Observe("pjrt", true, 8, t += 1) == S::kSuspect);
  CHECK_TRUE(tracker.Observe("pjrt", true, 8, t += 1) == S::kHealthy);
}

void TestHealthStateMachineDebounceBoundaries() {
  healthsm::Policy policy;
  policy.flap_window_s = 60;
  policy.flap_threshold = 100;
  policy.unhealthy_after = 3;
  policy.recover_after = 2;
  healthsm::HealthTracker tracker(policy);
  double t = 0;
  using S = healthsm::State;

  // Exactly unhealthy_after-1 failures stay suspect; the Nth hardens.
  CHECK_TRUE(tracker.Observe("m", false, 0, t += 1) == S::kSuspect);
  CHECK_TRUE(tracker.Observe("m", false, 0, t += 1) == S::kSuspect);
  CHECK_TRUE(tracker.Observe("m", false, 0, t += 1) == S::kUnhealthy);
  // Exactly recover_after cleans close recovery — not one sooner.
  CHECK_TRUE(tracker.Observe("m", true, 1, t += 1) == S::kRecovering);
  CHECK_TRUE(tracker.Observe("m", true, 1, t += 1) == S::kHealthy);
}

void TestHealthStateMachineFlapQuarantine() {
  healthsm::Policy policy;
  policy.flap_window_s = 10;
  policy.flap_threshold = 3;
  policy.quarantine_cooldown_s = 30;
  policy.recover_after = 2;
  healthsm::HealthTracker tracker(policy);
  double t = 1000;
  using S = healthsm::State;

  // ok/fail alternation: each flip is a transition; the third inside
  // the window quarantines.
  tracker.Observe("h", true, 5, t += 1);
  tracker.Observe("h", false, 0, t += 1);     // -> suspect (flap 1)
  tracker.Observe("h", true, 5, t += 1);      // -> healthy (flap 2)
  CHECK_TRUE(tracker.Observe("h", false, 0, t += 1) == S::kQuarantined);
  CHECK_TRUE(tracker.Quarantined("h", t));
  CHECK_EQ(tracker.QuarantinedKeys(t).size(), static_cast<size_t>(1));

  // During the cooldown even clean probes do not start recovery, and a
  // failure re-arms it.
  CHECK_TRUE(tracker.Observe("h", true, 5, t += 1) == S::kQuarantined);
  CHECK_TRUE(tracker.Observe("h", false, 0, t += 1) == S::kQuarantined);
  // Past the (re-armed) cooldown: clean -> recovering -> healthy after
  // recover_after cleans.
  t += 31;
  CHECK_TRUE(tracker.Observe("h", true, 5, t) == S::kRecovering);
  CHECK_TRUE(tracker.Observe("h", true, 5, t += 1) == S::kHealthy);
}

void TestHealthStateMachineContentFlapQuarantine() {
  // Every probe SUCCEEDS but the fingerprint alternates — the
  // FLAP_EVERY_N=1 shape. The window must fill from unstable
  // observations alone.
  healthsm::Policy policy;
  policy.flap_window_s = 100;
  policy.flap_threshold = 4;
  policy.quarantine_cooldown_s = 50;
  healthsm::HealthTracker tracker(policy);
  double t = 0;
  using S = healthsm::State;
  uint64_t fps[2] = {11, 22};
  S state = S::kHealthy;
  int observations = 0;
  for (int i = 0; i < 10 && state != S::kQuarantined; i++) {
    state = tracker.Observe("pjrt", true, fps[i % 2], t += 1);
    observations++;
  }
  CHECK_TRUE(state == S::kQuarantined);
  CHECK_TRUE(observations <= 6);  // threshold 4 fills within ~5 flips

  // Content still alternating at the slow cadence: stays quarantined
  // (every unstable observation re-arms the cooldown).
  t += 51;
  CHECK_TRUE(tracker.Observe("pjrt", true, fps[1], t) == S::kQuarantined);
  CHECK_TRUE(tracker.Quarantined("pjrt", t));
}

void TestHealthStateMachineWindowExpiry() {
  healthsm::Policy policy;
  policy.flap_window_s = 10;
  policy.flap_threshold = 3;
  healthsm::HealthTracker tracker(policy);
  double t = 0;
  using S = healthsm::State;
  // Two flap events, then a long quiet gap: the window empties, so two
  // MORE events later still do not quarantine.
  tracker.Observe("s", true, 1, t += 1);
  tracker.Observe("s", false, 0, t += 1);  // flap 1
  tracker.Observe("s", true, 1, t += 1);   // flap 2
  t += 60;                                 // window empties
  tracker.Observe("s", false, 0, t += 1);  // flap 1 (fresh window)
  CHECK_TRUE(tracker.Observe("s", true, 1, t += 1) == S::kHealthy);
  CHECK_TRUE(!tracker.Quarantined("s", t));
}

void TestHealthStateMachineMinThresholdRecovery() {
  // At the minimum flap threshold the earned-recovery transitions
  // (quarantine exit, recovering -> healthy) must not count as flap
  // evidence: the exit pair alone would refill the window and
  // re-quarantine a perfectly clean key forever.
  healthsm::Policy policy;
  policy.flap_window_s = 100;
  policy.flap_threshold = 2;
  policy.quarantine_cooldown_s = 5;
  policy.unhealthy_after = 2;
  policy.recover_after = 3;
  healthsm::HealthTracker tracker(policy);
  double t = 1000;
  using S = healthsm::State;
  tracker.Observe("p", false, 0, t += 1);  // -> suspect (flap 1)
  CHECK_TRUE(tracker.Observe("p", false, 0, t += 1) == S::kQuarantined);
  t += 6;  // past the cooldown
  CHECK_TRUE(tracker.Observe("p", true, 1, t += 1) == S::kRecovering);
  tracker.Observe("p", true, 1, t += 1);
  CHECK_TRUE(tracker.Observe("p", true, 1, t += 1) == S::kHealthy);
  // Stays healthy: no livelock from the recovery's own transitions.
  CHECK_TRUE(tracker.Observe("p", true, 1, t += 1) == S::kHealthy);
  CHECK_TRUE(!tracker.Quarantined("p", t));
}

void TestHealthStateMachineGhostRelease() {
  // A quarantined key that vanishes from the probe stream (chip
  // replaced/renumbered) can never earn clean-probe recovery; once the
  // cooldown elapses and a slow re-probe period plus a window passes
  // unobserved, the hold ends instead of pinning the dead chip's label
  // forever.
  healthsm::Policy policy;
  policy.flap_window_s = 10;
  policy.flap_threshold = 3;
  policy.quarantine_cooldown_s = 30;
  healthsm::HealthTracker tracker(policy);
  double t = 1000;
  using S = healthsm::State;
  tracker.Observe("health/chip-0", true, 0, t += 1);
  tracker.Observe("health/chip-0", false, 0, t += 1);
  tracker.Observe("health/chip-0", true, 0, t += 1);
  tracker.Observe("health/chip-0", false, 0, t += 1);
  CHECK_TRUE(tracker.Quarantined("health/chip-0", t));
  // Cooldown not yet elapsed: still held even though unobserved.
  CHECK_EQ(tracker.QuarantinedKeys(t + 20).size(), static_cast<size_t>(1));
  // Past the cooldown (30) AND unobserved for cooldown+window (40):
  // the hold releases as recovering.
  CHECK_EQ(tracker.QuarantinedKeys(t + 45).size(), static_cast<size_t>(0));
  CHECK_TRUE(tracker.StateOf("health/chip-0", t + 45) == S::kRecovering);
  // A key still being observed keeps its quarantine through the same
  // wall-clock span (failures re-arm the cooldown).
  tracker.Observe("health/chip-1", true, 0, t += 1);
  tracker.Observe("health/chip-1", false, 0, t += 1);
  tracker.Observe("health/chip-1", true, 0, t += 1);
  tracker.Observe("health/chip-1", false, 0, t += 1);
  CHECK_TRUE(tracker.Quarantined("health/chip-1", t));
  tracker.Observe("health/chip-1", false, 0, t + 20);  // re-arms cooldown
  CHECK_EQ(tracker.QuarantinedKeys(t + 45).size(), static_cast<size_t>(1));
}

void TestHealthStateMachineReloadPreservesState() {
  healthsm::Policy policy;
  policy.flap_window_s = 10;
  policy.flap_threshold = 3;
  policy.quarantine_cooldown_s = 30;
  healthsm::HealthTracker tracker(policy);
  double t = 0;
  tracker.Observe("q", true, 1, t += 1);
  tracker.Observe("q", false, 0, t += 1);
  tracker.Observe("q", true, 1, t += 1);
  tracker.Observe("q", false, 0, t += 1);
  CHECK_TRUE(tracker.Quarantined("q", t));
  // A SIGHUP-style Configure changes thresholds but never resets state.
  policy.flap_threshold = 50;
  tracker.Configure(policy);
  CHECK_TRUE(tracker.Quarantined("q", t));
  CHECK_EQ(tracker.policy().flap_threshold, 50);
}

void TestHealthStateMachineSerializeRestore() {
  healthsm::Policy policy;
  policy.flap_window_s = 10;
  policy.flap_threshold = 3;
  policy.quarantine_cooldown_s = 300;
  policy.recover_after = 2;
  healthsm::HealthTracker tracker(policy);
  double t = 5000;
  tracker.Observe("pjrt", true, 42, t += 1);
  tracker.Observe("pjrt", false, 0, t += 1);
  tracker.Observe("pjrt", true, 42, t += 1);
  tracker.Observe("pjrt", false, 0, t += 1);
  CHECK_TRUE(tracker.Quarantined("pjrt", t));
  tracker.Observe("health", false, 0, t += 1);  // a suspect rides along

  std::string serialized = tracker.SerializeJson(t);
  healthsm::HealthTracker restored(policy);
  Status s = restored.RestoreJson(serialized, t + 1);
  CHECK_TRUE(s.ok());
  // The quarantine survives (the kill -9 contract) with its deadline:
  // still quarantined now, recoverable past the cooldown.
  CHECK_TRUE(restored.Quarantined("pjrt", t + 1));
  CHECK_TRUE(restored.StateOf("health", t + 1) ==
             healthsm::State::kSuspect);
  using S = healthsm::State;
  CHECK_TRUE(restored.Observe("pjrt", true, 42, t + 2) == S::kQuarantined);
  CHECK_TRUE(restored.Observe("pjrt", true, 42, t + 400) == S::kRecovering);
  CHECK_TRUE(restored.Observe("pjrt", true, 42, t + 401) == S::kHealthy);

  // Garbage never half-applies: the tracker keeps its state.
  healthsm::HealthTracker untouched(policy);
  untouched.Observe("x", false, 0, 1);
  CHECK_TRUE(!untouched.RestoreJson("{not json", 2).ok());
  CHECK_TRUE(untouched.StateOf("x", 2) == S::kSuspect);
  CHECK_TRUE(!untouched.RestoreJson("{\"keys\":{\"x\":{\"state\":"
                                    "\"bogus\"}}}",
                                    2)
                  .ok());
  CHECK_TRUE(untouched.StateOf("x", 2) == S::kSuspect);
  // An empty string (nothing persisted) is fine and a no-op.
  CHECK_TRUE(untouched.RestoreJson("", 2).ok());
}

void TestHealthStateMachineFaultPoint() {
  // An armed healthsm.transition fault forces observations to
  // failures — the drill hook for forcing transitions on demand.
  healthsm::Policy policy;
  policy.unhealthy_after = 1;
  healthsm::HealthTracker tracker(policy);
  CHECK_TRUE(fault::Arm("healthsm.transition:fail:count=1").ok());
  CHECK_TRUE(tracker.Observe("drill", true, 1, 1) ==
             healthsm::State::kSuspect);
  // The count=1 rule is consumed: the next observation is clean.
  CHECK_TRUE(tracker.Observe("drill", true, 1, 2) ==
             healthsm::State::kHealthy);
  fault::Disarm();
}

// ---- label governor (lm/governor) ----------------------------------------

void TestLabelGovernorHoldDown() {
  lm::GovernorPolicy policy;
  policy.hold_down_s = 100;
  policy.churn_budget = 10;
  lm::LabelGovernor governor(policy);
  lm::Provenance no_prov;
  std::vector<lm::SuppressedFlip> suppressed;
  double t = 1000;

  // First appearance always passes (a first pass is all appearances).
  lm::Labels previous;
  lm::Labels candidate = {{"google.com/tpu.count", "4"},
                          {"google.com/tpu.backend", "mock"}};
  lm::Provenance prov;
  governor.Apply(previous, no_prov, false, t, &candidate, &prov,
                 &suppressed);
  governor.CommitPublished();
  CHECK_TRUE(suppressed.empty());
  CHECK_EQ(candidate["google.com/tpu.count"], "4");

  // A flip inside the hold-down window is suppressed: the published
  // value holds, the flip is reported with its would-be value.
  previous = candidate;
  candidate["google.com/tpu.count"] = "2";
  governor.Apply(previous, no_prov, false, t + 10, &candidate, &prov,
                 &suppressed);
  governor.CommitPublished();
  CHECK_EQ(suppressed.size(), static_cast<size_t>(1));
  CHECK_EQ(suppressed[0].key, "google.com/tpu.count");
  CHECK_EQ(suppressed[0].op, "changed");
  CHECK_EQ(suppressed[0].new_value, "2");
  CHECK_EQ(suppressed[0].reason, "hold-down");
  CHECK_EQ(candidate["google.com/tpu.count"], "4");

  // Past the window the same change is allowed...
  suppressed.clear();
  candidate["google.com/tpu.count"] = "2";
  governor.Apply(previous, no_prov, false, t + 200, &candidate, &prov,
                 &suppressed);
  governor.CommitPublished();
  CHECK_TRUE(suppressed.empty());
  CHECK_EQ(candidate["google.com/tpu.count"], "2");
  // ...and starts a fresh hold-down of its own.
  previous = candidate;
  candidate["google.com/tpu.count"] = "4";
  governor.Apply(previous, no_prov, false, t + 210, &candidate, &prov,
                 &suppressed);
  CHECK_EQ(suppressed.size(), static_cast<size_t>(1));
  CHECK_EQ(candidate["google.com/tpu.count"], "2");
}

void TestLabelGovernorRemovalAndReadd() {
  // Remove/add flapping is the classic churn shape: a key REMOVED
  // within its hold-down holds its value; a key RE-ADDED after a
  // governed removal is not a "first appearance".
  lm::GovernorPolicy policy;
  policy.hold_down_s = 100;
  policy.churn_budget = 10;
  lm::LabelGovernor governor(policy);
  lm::Provenance no_prov;
  std::vector<lm::SuppressedFlip> suppressed;
  lm::Labels previous;
  lm::Labels candidate = {{"google.com/tpu.health.ok", "true"}};
  lm::Provenance prov;
  governor.Apply(previous, no_prov, false, 0, &candidate, &prov,
                 &suppressed);
  governor.CommitPublished();
  previous = candidate;

  // Removal within hold-down: held — and the journaled flip cites the
  // held (previously published) value's provenance, since a removal has
  // no candidate entry of its own to cite.
  lm::Provenance prev_prov;
  prev_prov["google.com/tpu.health.ok"] = {"health", "health", "fresh", 1.0};
  candidate.clear();
  governor.Apply(previous, prev_prov, false, 10, &candidate, &prov,
                 &suppressed);
  governor.CommitPublished();
  CHECK_EQ(suppressed.size(), static_cast<size_t>(1));
  CHECK_EQ(suppressed[0].op, "removed");
  CHECK_EQ(suppressed[0].provenance.labeler, "health");
  CHECK_EQ(suppressed[0].provenance.tier, "fresh");
  CHECK_EQ(candidate["google.com/tpu.health.ok"], "true");

  // Removal after the window: allowed.
  suppressed.clear();
  candidate.clear();
  governor.Apply(previous, no_prov, false, 150, &candidate, &prov,
                 &suppressed);
  governor.CommitPublished();
  CHECK_TRUE(suppressed.empty());
  CHECK_TRUE(candidate.count("google.com/tpu.health.ok") == 0);

  // Re-add right after the allowed removal: the key is KNOWN (not a
  // first appearance) and inside the new hold-down -> suppressed.
  previous = candidate;
  candidate["google.com/tpu.health.ok"] = "false";
  suppressed.clear();
  governor.Apply(previous, no_prov, false, 160, &candidate, &prov,
                 &suppressed);
  CHECK_EQ(suppressed.size(), static_cast<size_t>(1));
  CHECK_EQ(suppressed[0].op, "added");
  CHECK_TRUE(candidate.count("google.com/tpu.health.ok") == 0);
}

void TestLabelGovernorMonotoneExemptions() {
  lm::GovernorPolicy policy;
  policy.hold_down_s = 100;
  policy.churn_budget = 10;
  lm::LabelGovernor governor(policy);
  lm::Provenance no_prov;
  std::vector<lm::SuppressedFlip> suppressed;
  lm::Provenance prov;

  // Downgrade-marker REMOVAL (recovery) is always allowed, even just
  // after the marker appeared.
  lm::Labels previous;
  lm::Labels candidate = {{"google.com/tpu.degraded", "true"},
                          {"google.com/tpu.snapshot-age-seconds", "12"},
                          {"google.com/tpu.count", "4"}};
  governor.Apply(previous, no_prov, false, 0, &candidate, &prov,
                 &suppressed);
  governor.CommitPublished();
  CHECK_TRUE(suppressed.empty());
  previous = candidate;
  candidate = {{"google.com/tpu.count", "4"}};
  governor.Apply(previous, no_prov, false, 5, &candidate, &prov,
                 &suppressed);
  governor.CommitPublished();
  CHECK_TRUE(suppressed.empty());
  CHECK_TRUE(candidate.count("google.com/tpu.degraded") == 0);
  CHECK_TRUE(candidate.count("google.com/tpu.snapshot-age-seconds") == 0);

  // A level-improved pass may change anything (metadata -> pjrt
  // convergence must not be damped).
  previous = {{"google.com/tpu.backend", "metadata"}};
  candidate = {{"google.com/tpu.backend", "pjrt"}};
  lm::LabelGovernor fresh(policy);
  fresh.NotePublished(previous, 0);
  suppressed.clear();
  fresh.Apply(previous, no_prov, true, 1, &candidate, &prov, &suppressed);
  CHECK_TRUE(suppressed.empty());
  CHECK_EQ(candidate["google.com/tpu.backend"], "pjrt");

  // Measurement keys are exempt outright — and so is the quarantine
  // annotation: healthsm's already-debounced verdict, whose re-add
  // within its own removal's hold-down must never be suppressed (it is
  // the one label explaining why everything else is held).
  CHECK_TRUE(!lm::GovernedKey("google.com/tpu.health.probe-ms"));
  CHECK_TRUE(!lm::GovernedKey("google.com/tpu.health.quarantined"));
  CHECK_TRUE(!lm::GovernedKey("google.com/tfd.timestamp"));
  CHECK_TRUE(lm::GovernedKey("google.com/tpu.count"));
  CHECK_TRUE(lm::GovernedKey("google.com/tpu-vm.present"));

  // snapshot-age mirrors tpu.degraded's outcome: a suppressed marker
  // re-add drags the age back out too (no torn pair).
  lm::LabelGovernor paired(policy);
  previous = {};
  candidate = {{"google.com/tpu.degraded", "true"},
               {"google.com/tpu.snapshot-age-seconds", "3"}};
  suppressed.clear();
  paired.Apply(previous, no_prov, false, 0, &candidate, &prov, &suppressed);
  paired.CommitPublished();
  previous = candidate;
  candidate = {};
  paired.Apply(previous, no_prov, false, 1, &candidate, &prov, &suppressed);
  paired.CommitPublished();  // marker removal: upgrade, allowed
  previous = candidate;
  candidate = {{"google.com/tpu.degraded", "true"},
               {"google.com/tpu.snapshot-age-seconds", "9"}};
  suppressed.clear();
  paired.Apply(previous, no_prov, false, 2, &candidate, &prov, &suppressed);
  CHECK_TRUE(!suppressed.empty());
  CHECK_TRUE(candidate.count("google.com/tpu.degraded") == 0);
  CHECK_TRUE(candidate.count("google.com/tpu.snapshot-age-seconds") == 0);
}

void TestLabelGovernorSliceInvalidRecovery() {
  // A degraded first pass publishes the SLICE-INVALID sentinel (plus
  // its zeroed companions); when the overlay recovers one pass later,
  // the WHOLE converging set must land — suppressing it would pin the
  // node at explicitly-invalid facts for a full hold-down window. The
  // reverse flip (INTO the sentinel) stays governed, so the hatch
  // cannot oscillate.
  lm::GovernorPolicy policy;
  policy.hold_down_s = 100;
  policy.churn_budget = 3;  // tighter than the recovery's change count
  lm::LabelGovernor governor(policy);
  lm::Provenance no_prov, prov;
  std::vector<lm::SuppressedFlip> suppressed;

  lm::Labels previous;
  lm::Labels candidate = {{"google.com/tpu.product", "SLICE-INVALID"},
                          {"google.com/tpu.slice.shape", "SLICE-INVALID"},
                          {"google.com/tpu.count", "0"},
                          {"google.com/tpu.replicas", "0"},
                          {"google.com/tpu.memory", "0"}};
  governor.Apply(previous, no_prov, false, 0, &candidate, &prov,
                 &suppressed);
  governor.CommitPublished();
  CHECK_TRUE(suppressed.empty());

  // Overlay recovers at t=1 (inside hold-down, more changes than the
  // budget): every key converges anyway.
  previous = candidate;
  candidate = {{"google.com/tpu.product", "tpu-v5p"},
               {"google.com/tpu.slice.shape", "4x4x4"},
               {"google.com/tpu.count", "4"},
               {"google.com/tpu.replicas", "4"},
               {"google.com/tpu.memory", "16384"}};
  lm::Labels recovered = candidate;
  suppressed.clear();
  governor.Apply(previous, no_prov, false, 1, &candidate, &prov,
                 &suppressed);
  governor.CommitPublished();
  CHECK_TRUE(suppressed.empty());
  CHECK_TRUE(candidate == recovered);

  // Flipping back INTO the sentinel is ordinary churn: suppressed, the
  // valid facts stay published...
  previous = candidate;
  candidate = {{"google.com/tpu.product", "SLICE-INVALID"},
               {"google.com/tpu.slice.shape", "SLICE-INVALID"},
               {"google.com/tpu.count", "0"},
               {"google.com/tpu.replicas", "0"},
               {"google.com/tpu.memory", "0"}};
  suppressed.clear();
  governor.Apply(previous, no_prov, false, 2, &candidate, &prov,
                 &suppressed);
  governor.CommitPublished();
  CHECK_EQ(suppressed.size(), 5u);
  CHECK_TRUE(candidate == recovered);

  // ...so a subsequent "recovery" pass sees no published sentinel and
  // gets no free flip either (candidate == published already).
  previous = candidate;
  candidate = recovered;
  suppressed.clear();
  governor.Apply(previous, no_prov, false, 3, &candidate, &prov,
                 &suppressed);
  CHECK_TRUE(suppressed.empty());
  CHECK_TRUE(candidate == recovered);
}

void TestLabelGovernorChurnBudgetAndCommit() {
  lm::GovernorPolicy policy;
  policy.hold_down_s = 100;
  policy.churn_budget = 2;
  lm::LabelGovernor governor(policy);
  lm::Provenance no_prov;
  std::vector<lm::SuppressedFlip> suppressed;
  lm::Provenance prov;
  lm::Labels previous = {{"google.com/tpu.a", "1"},
                         {"google.com/tpu.b", "1"},
                         {"google.com/tpu.c", "1"},
                         {"google.com/tpu.d", "1"}};
  lm::LabelGovernor seeded(policy);
  seeded.NotePublished(previous, -200);  // hold-downs long expired
  // Four keys want to change at once; the budget admits two.
  lm::Labels candidate = {{"google.com/tpu.a", "2"},
                          {"google.com/tpu.b", "2"},
                          {"google.com/tpu.c", "2"},
                          {"google.com/tpu.d", "2"}};
  seeded.Apply(previous, no_prov, false, 0, &candidate, &prov, &suppressed);
  seeded.CommitPublished();
  CHECK_EQ(suppressed.size(), static_cast<size_t>(2));
  CHECK_EQ(suppressed[0].reason, "churn-budget");
  int changed = 0;
  for (const auto& [key, value] : candidate) {
    if (value == "2") changed++;
  }
  CHECK_EQ(changed, 2);

  // Pending-change semantics: an Apply whose publish never lands (no
  // CommitPublished) must not burn the hold-down timer — the retry of
  // the SAME change passes.
  lm::LabelGovernor uncommitted(policy);
  lm::Labels prev2 = {{"google.com/tpu.x", "1"}};
  uncommitted.NotePublished(prev2, -200);
  lm::Labels cand2 = {{"google.com/tpu.x", "2"}};
  suppressed.clear();
  uncommitted.Apply(prev2, no_prov, false, 0, &cand2, &prov, &suppressed);
  CHECK_TRUE(suppressed.empty());  // allowed; sink then "fails"
  cand2 = {{"google.com/tpu.x", "2"}};
  suppressed.clear();
  uncommitted.Apply(prev2, no_prov, false, 1, &cand2, &prov, &suppressed);
  CHECK_TRUE(suppressed.empty());  // not suppressed by its own ghost
  CHECK_EQ(cand2["google.com/tpu.x"], "2");
}

void TestStateRoundTrip() {
  sched::PersistedState state;
  state.node = "unit-node";
  state.saved_at = 1000.0;
  state.source = "pjrt";
  state.tier = "fresh";
  state.level = 0;
  state.age_s = 12.5;
  state.labels = {{"google.com/tpu.count", "4"},
                  {"google.com/tpu.backend", "pjrt"}};
  lm::LabelProvenance from;
  from.labeler = "tpu";
  from.source = "pjrt";
  from.tier = "fresh";
  from.age_s = 12.5;
  state.provenance["google.com/tpu.count"] = from;
  state.healthsm_json = "{\"keys\":{}}";

  std::string framed = sched::SerializeState(state);
  CHECK_TRUE(framed.rfind("TFDSTATE1 ", 0) == 0);
  Result<sched::PersistedState> parsed = sched::ParseState(framed);
  CHECK_TRUE(parsed.ok());
  CHECK_EQ(parsed->node, "unit-node");
  CHECK_EQ(parsed->source, "pjrt");
  CHECK_EQ(parsed->labels.at("google.com/tpu.count"), "4");
  CHECK_EQ(parsed->provenance.at("google.com/tpu.count").labeler, "tpu");
  CHECK_TRUE(parsed->age_s == 12.5);

  // Torn mid-write: payload shorter than the header promises.
  std::string torn = framed.substr(0, framed.size() / 2);
  Result<sched::PersistedState> bad = sched::ParseState(torn);
  CHECK_TRUE(!bad.ok());
  CHECK_TRUE(bad.error().find("torn or corrupt") != std::string::npos);
  // Bit rot: same length, one flipped byte → checksum mismatch.
  std::string rotten = framed;
  rotten[framed.size() - 3] = rotten[framed.size() - 3] == 'x' ? 'y' : 'x';
  bad = sched::ParseState(rotten);
  CHECK_TRUE(!bad.ok());
  CHECK_TRUE(bad.error().find("checksum") != std::string::npos);
  // Not a state file at all.
  CHECK_TRUE(!sched::ParseState("{}").ok());
  CHECK_TRUE(!sched::ParseState("").ok());

  // Save/Load through a real file, with every gate.
  std::string dir = "/tmp/tfd-unit-state-" + std::to_string(getpid());
  std::string path = dir + "/state";
  CHECK_TRUE(sched::SaveState(path, state).ok());
  // Happy path: age grows by the downtime (saved_at 1000, now 1060).
  Result<sched::PersistedState> loaded =
      sched::LoadState(path, "unit-node", 600, 1060.0);
  CHECK_TRUE(loaded.ok());
  CHECK_TRUE(loaded->age_s > 72.0 && loaded->age_s < 73.0);  // 12.5 + 60
  // Foreign node: rejected by identity, not served — and the healthsm
  // payload is NOT handed out (a foreign quarantine must not transfer).
  std::string stale_health = "untouched";
  bad = sched::LoadState(path, "other-node", 600, 1060.0, &stale_health);
  CHECK_TRUE(!bad.ok());
  CHECK_TRUE(bad.error().find("foreign") != std::string::npos);
  CHECK_EQ(stale_health, "untouched");
  // Stale: the facts expired while the daemon was down — but the
  // authentic healthsm payload survives the rejection (quarantine has
  // its own clock; a long crash loop must not launder it).
  bad = sched::LoadState(path, "unit-node", 600, 1000.0 + 3600,
                         &stale_health);
  CHECK_TRUE(!bad.ok());
  CHECK_TRUE(bad.error().find("expired") != std::string::npos);
  CHECK_EQ(stale_health, "{\"keys\":{}}");
  // The injected torn write is exactly what the checksum gate catches.
  CHECK_TRUE(fault::Arm("state.write:torn:count=1").ok());
  CHECK_TRUE(sched::SaveState(path, state).ok());  // "succeeds"
  fault::Disarm();
  bad = sched::LoadState(path, "unit-node", 600, 1060.0);
  CHECK_TRUE(!bad.ok());
  CHECK_TRUE(bad.error().find("torn or corrupt") != std::string::npos);
  std::string cmd = "rm -rf " + dir;
  CHECK_TRUE(system(cmd.c_str()) == 0);
}

void TestRenameErrorDeviceIds() {
  // rename(2) over an existing DIRECTORY fails (EISDIR): the error must
  // carry both device ids — the one-line diagnosis for the cross-device
  // hostPath misconfig (EXDEV shows the ids differing).
  std::string dir = "/tmp/tfd-unit-rename-" + std::to_string(getpid());
  std::string blocked = dir + "/blocked";
  std::string cmd = "mkdir -p " + blocked;
  CHECK_TRUE(system(cmd.c_str()) == 0);
  int write_errno = 0;
  Status s = WriteFileAtomically(blocked, "x=1\n", &write_errno);
  CHECK_TRUE(!s.ok());
  CHECK_EQ(write_errno, EISDIR);
  CHECK_TRUE(s.message().find("src dev=") != std::string::npos);
  CHECK_TRUE(s.message().find("dst dev=") != std::string::npos);
  cmd = "rm -rf " + dir;
  CHECK_TRUE(system(cmd.c_str()) == 0);
}

void TestHttpDeadlineBudget() {
  // A dribbling server: one byte per 50ms, forever. Per-op socket
  // timeouts never fire — only the whole-request deadline can end this.
  int listen_fd = socket(AF_INET, SOCK_STREAM, 0);
  CHECK_TRUE(listen_fd >= 0);
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  addr.sin_port = 0;
  CHECK_TRUE(bind(listen_fd, reinterpret_cast<sockaddr*>(&addr),
                  sizeof(addr)) == 0);
  CHECK_TRUE(listen(listen_fd, 1) == 0);
  socklen_t len = sizeof(addr);
  CHECK_TRUE(getsockname(listen_fd, reinterpret_cast<sockaddr*>(&addr),
                         &len) == 0);
  int port = ntohs(addr.sin_port);
  std::thread server([listen_fd] {
    int conn = accept(listen_fd, nullptr, nullptr);
    if (conn < 0) return;
    char buf[1024];
    (void)recv(conn, buf, sizeof(buf), 0);  // swallow the request
    const char* dribble = "HTTP/1.1 200 OK\r\nContent-Length: 10000\r\n\r\n";
    for (const char* p = dribble; ; p++) {
      char c = *p ? *p : 'x';  // headers, then filler forever
      if (send(conn, &c, 1, MSG_NOSIGNAL) <= 0) break;
      if (!*p) p--;  // stick on filler
      std::this_thread::sleep_for(std::chrono::milliseconds(50));
    }
    close(conn);
  });

  http::RequestOptions options;
  options.timeout_ms = 5000;   // per-op: never fires against a dribble
  options.deadline_ms = 400;   // whole-request: must end it
  auto t0 = std::chrono::steady_clock::now();
  Result<http::Response> response = http::Request(
      "GET", "http://127.0.0.1:" + std::to_string(port) + "/", "", options);
  double elapsed_s = obs::SecondsSince(t0);
  CHECK_TRUE(!response.ok());
  CHECK_TRUE(response.error().find("deadline exceeded") !=
             std::string::npos);
  CHECK_TRUE(elapsed_s < 3.0);  // ended by the budget, not the dribble
  close(listen_fd);
  server.join();
}

void TestK8sFaultClassification() {
  // Table-driven transient/permanent classification of the CR sink
  // under injected transport and HTTP faults — the contract the daemon's
  // survive-vs-exit choice and the breaker's trip decision ride on.
  // TFD_APISERVER_URL points at a closed port so any request a fault
  // does NOT intercept fails as a real transport error (also transient).
  setenv("NODE_NAME", "unit-node", 1);
  setenv("TFD_APISERVER_URL", "http://127.0.0.1:1", 1);
  setenv("TFD_SERVICEACCOUNT_DIR", "/nonexistent-tfd-unit", 1);
  Result<k8s::ClusterConfig> cluster = k8s::LoadInClusterConfig();
  CHECK_TRUE(cluster.ok());
  lm::Labels labels{{"google.com/tpu.count", "4"}};

  struct Case {
    const char* spec;        // injected fault schedule
    bool expect_transient;   // retry (true) vs. give-up (false)
    const char* expect_in_error;
  };
  const Case kCases[] = {
      // Apiserver 5xx/429 storms: retry.
      {"k8s.get:http=500", true, "HTTP 500"},
      {"k8s.get:http=503", true, "HTTP 503"},
      {"k8s.get:http=429", true, "HTTP 429"},
      // Auth/permission rejections: give up (crash-loop visibly).
      {"k8s.get:http=403", false, "HTTP 403"},
      // Transport faults: connect timeout and mid-body reset — retry.
      {"k8s.connect:errno=ETIMEDOUT", true, "Connection timed out"},
      {"k8s.get:errno=ECONNRESET", true, "Connection reset"},
      // A 429-then-500-then-503 sequence: each call classifies alike.
      {"k8s.get:http=429:count=1,k8s.get:http=500:count=1,"
       "k8s.get:http=503:count=1",
       true, "HTTP 429"},
      // Create-race conflicts forever: retries exhaust, still transient.
      {"k8s.get:http=404:count=3,k8s.post:http=409:count=3", true,
       "attempts exhausted"},
  };
  k8s::CircuitBreaker breaker(k8s::CircuitBreaker::Options{3, 60});
  int transient_seen = 0;
  for (const Case& c : kCases) {
    CHECK_TRUE(fault::Arm(c.spec).ok());
    bool transient = !c.expect_transient;  // must be overwritten
    Status s = k8s::UpdateNodeFeature(*cluster, labels, &transient);
    CHECK_TRUE(!s.ok());
    CHECK_TRUE(transient == c.expect_transient);
    CHECK_TRUE(s.message().find(c.expect_in_error) != std::string::npos);
    // The classification drives the breaker: transient failures trip
    // it, permanent ones never do.
    if (transient) {
      breaker.RecordTransientFailure();
      transient_seen++;
    }
  }
  // 3+ consecutive transients: breaker-open — the third outcome the
  // table distinguishes (skip instantly, probe after cooldown).
  CHECK_TRUE(transient_seen >= 3);
  CHECK_TRUE(breaker.state() == k8s::CircuitBreaker::State::kOpen);
  CHECK_TRUE(!breaker.Allow());
  fault::Disarm();
  unsetenv("NODE_NAME");
  unsetenv("TFD_APISERVER_URL");
  unsetenv("TFD_SERVICEACCOUNT_DIR");
}

// ---- fleet-scale diff sink (k8s/client.cc, k8s/desync.cc) ---------------

// A scripted apiserver: accepts sequential connections (the client sends
// Connection: close, one request per connection), records every
// (method, path, body), and answers from a fixed response script. Full
// control over status/headers/body is what the conflict and Retry-After
// tests need and fault injection can't fabricate.
class ScriptedApiServer {
 public:
  struct Exchange {
    std::string method;
    std::string path;
    std::string body;
  };
  struct Reply {
    int status = 200;
    std::string body = "{}";
    std::string extra_headers;  // raw "K: v\r\n" lines
  };

  explicit ScriptedApiServer(std::vector<Reply> script)
      : script_(std::move(script)) {
    listen_fd_ = socket(AF_INET, SOCK_STREAM, 0);
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
    addr.sin_port = 0;
    bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr));
    listen(listen_fd_, 8);
    socklen_t len = sizeof(addr);
    getsockname(listen_fd_, reinterpret_cast<sockaddr*>(&addr), &len);
    port_ = ntohs(addr.sin_port);
    thread_ = std::thread([this] { Serve(); });
  }

  ~ScriptedApiServer() {
    shutdown(listen_fd_, SHUT_RDWR);
    close(listen_fd_);
    thread_.join();
  }

  int port() const { return port_; }
  std::string url() const {
    return "http://127.0.0.1:" + std::to_string(port_);
  }
  const std::vector<Exchange>& exchanges() const { return exchanges_; }
  int CountVerb(const std::string& verb) const {
    int n = 0;
    for (const Exchange& e : exchanges_) {
      if (e.method == verb) n++;
    }
    return n;
  }

 private:
  void Serve() {
    for (size_t i = 0; i < script_.size(); i++) {
      int conn = accept(listen_fd_, nullptr, nullptr);
      if (conn < 0) return;  // shut down mid-script
      std::string raw;
      char buf[4096];
      size_t body_need = std::string::npos;
      size_t header_end = std::string::npos;
      while (true) {
        if (header_end == std::string::npos) {
          header_end = raw.find("\r\n\r\n");
          if (header_end != std::string::npos) {
            size_t cl = raw.find("Content-Length: ");
            body_need = cl != std::string::npos && cl < header_end
                            ? strtoul(raw.c_str() + cl + 16, nullptr, 10)
                            : 0;
          }
        }
        if (header_end != std::string::npos &&
            raw.size() >= header_end + 4 + body_need) {
          break;
        }
        ssize_t n = recv(conn, buf, sizeof(buf), 0);
        if (n <= 0) break;
        raw.append(buf, static_cast<size_t>(n));
      }
      Exchange ex;
      size_t sp1 = raw.find(' ');
      size_t sp2 = raw.find(' ', sp1 + 1);
      if (sp1 != std::string::npos && sp2 != std::string::npos) {
        ex.method = raw.substr(0, sp1);
        ex.path = raw.substr(sp1 + 1, sp2 - sp1 - 1);
      }
      if (header_end != std::string::npos) {
        ex.body = raw.substr(header_end + 4);
      }
      exchanges_.push_back(ex);
      const Reply& reply = script_[i];
      std::string out = "HTTP/1.1 " + std::to_string(reply.status) +
                        " X\r\nContent-Length: " +
                        std::to_string(reply.body.size()) + "\r\n" +
                        reply.extra_headers + "Connection: close\r\n\r\n" +
                        reply.body;
      send(conn, out.data(), out.size(), MSG_NOSIGNAL);
      close(conn);
    }
  }

  std::vector<Reply> script_;
  std::vector<Exchange> exchanges_;
  int listen_fd_;
  int port_;
  std::thread thread_;
};

k8s::ClusterConfig ScriptedCluster(const ScriptedApiServer& server) {
  k8s::ClusterConfig cluster;
  cluster.apiserver_url = server.url();
  cluster.namespace_ = "unit";
  cluster.node_name = "unit-node";
  return cluster;
}

void TestDesyncMath() {
  // Cross-language golden pins: tests/test_fleet.py asserts the SAME
  // numbers from the tpufd.sink twin. If either side drifts, the fleet
  // soak stops simulating the schedule the daemon actually runs.
  CHECK_TRUE(k8s::desync::Fnv1a64("tpu-node-1") == 0xd4ee320a7c9868f9ULL);
  char buf[64];
  snprintf(buf, sizeof(buf), "%.12f", k8s::desync::HashUnit("tpu-node-1"));
  CHECK_EQ(std::string(buf), "0.153074774741");
  snprintf(buf, sizeof(buf), "%.6f",
           k8s::desync::PhaseOffsetS(60.0, "tpu-node-1", 10));
  CHECK_EQ(std::string(buf), "9.184486");
  snprintf(buf, sizeof(buf), "%.12f",
           k8s::desync::JitterUnit("tpu-node-1", 3));
  CHECK_EQ(std::string(buf), "0.939997208947");
  snprintf(buf, sizeof(buf), "%.6f",
           k8s::desync::JitteredIntervalS(60.0, "tpu-node-1", 3, 10));
  CHECK_EQ(std::string(buf), "65.639983");
  snprintf(buf, sizeof(buf), "%.6f",
           k8s::desync::RefreshPeriodS(150.0, "tpu-node-1", 10));
  CHECK_EQ(std::string(buf), "159.504576");
  snprintf(buf, sizeof(buf), "%.6f",
           k8s::desync::SpreadRetryAfterS(30.0, "tpu-node-1"));
  CHECK_EQ(std::string(buf), "33.595262");

  // Properties: jitter-pct 0 disables everything; bounds hold; similar
  // node names spread (the raw-FNV high-bit clustering regression).
  CHECK_EQ(k8s::desync::PhaseOffsetS(60.0, "tpu-node-1", 0), 0.0);
  CHECK_EQ(k8s::desync::JitteredIntervalS(60.0, "tpu-node-1", 3, 0), 60.0);
  CHECK_EQ(k8s::desync::RefreshPeriodS(150.0, "tpu-node-1", 0), 150.0);
  int buckets[5] = {0, 0, 0, 0, 0};
  for (int i = 0; i < 500; i++) {
    char name[32];
    snprintf(name, sizeof(name), "node-%04d", i);
    double offset = k8s::desync::PhaseOffsetS(5.0, name, 10);
    CHECK_TRUE(offset >= 0 && offset < 5.0);
    buckets[static_cast<int>(offset)]++;
    double interval = k8s::desync::JitteredIntervalS(60.0, name, i, 10);
    CHECK_TRUE(interval >= 54.0 && interval <= 66.0);
    double retry = k8s::desync::SpreadRetryAfterS(10.0, name);
    CHECK_TRUE(retry >= 10.0 && retry < 15.0);
  }
  for (int b = 0; b < 5; b++) {
    CHECK_TRUE(buckets[b] > 50);  // ~100 each when uniform
  }
}

void TestBuildMergePatch() {
  lm::Labels acked{{"a", "1"}, {"b", "2"}, {"z", "9"}};
  lm::Labels desired{{"a", "1"}, {"b", "3"}, {"c", "4"}};
  // Pinned against tpufd.sink.build_merge_patch (tests/test_fleet.py):
  // changed/added keys sorted, then removals as nulls, rv precondition
  // and node-name fix in metadata.
  CHECK_EQ(k8s::BuildMergePatch(acked, desired, "tpu-node-1", true, "17"),
           "{\"metadata\":{\"resourceVersion\":\"17\",\"labels\":"
           "{\"nfd.node.kubernetes.io/node-name\":\"tpu-node-1\"}},"
           "\"spec\":{\"labels\":{\"b\":\"3\",\"c\":\"4\",\"z\":null}}}");
  CHECK_EQ(k8s::BuildMergePatch(acked, desired, "tpu-node-1", false, ""),
           "{\"spec\":{\"labels\":{\"b\":\"3\",\"c\":\"4\","
           "\"z\":null}}}");
  // Nothing changed, nothing to fix: no patch at all.
  CHECK_EQ(k8s::BuildMergePatch(acked, acked, "tpu-node-1", false, "17"),
           "");
  // Node-name repair alone still patches (empty spec diff).
  CHECK_EQ(k8s::BuildMergePatch(acked, acked, "tpu-node-1", true, ""),
           "{\"metadata\":{\"labels\":"
           "{\"nfd.node.kubernetes.io/node-name\":\"tpu-node-1\"}},"
           "\"spec\":{\"labels\":{}}}");
}

void TestSinkPatchFlow() {
  // First write (state unknown): GET the CR, diff, PATCH. Second write
  // (state cached): ONE PATCH, zero GETs. Third write (no change): a
  // semantic-equality GET, no write — callers skip clean passes
  // upstream, so a write call with nothing to diff owes a REAL server
  // interaction (that GET is what surfaces a dead apiserver to the
  // breaker on forced-slow/chaos passes).
  ScriptedApiServer server({
      {200,
       "{\"metadata\":{\"name\":\"tfd-features-for-unit-node\","
       "\"resourceVersion\":\"5\",\"labels\":{"
       "\"nfd.node.kubernetes.io/node-name\":\"unit-node\"}},"
       "\"spec\":{\"labels\":{\"google.com/tpu.count\":\"2\"}}}"},
      {200, "{\"metadata\":{\"resourceVersion\":\"6\"}}"},
      {200, "{\"metadata\":{\"resourceVersion\":\"7\"}}"},
      {200,
       "{\"metadata\":{\"name\":\"tfd-features-for-unit-node\","
       "\"resourceVersion\":\"7\",\"labels\":{"
       "\"nfd.node.kubernetes.io/node-name\":\"unit-node\"}},"
       "\"spec\":{\"labels\":{\"google.com/tpu.count\":\"8\","
       "\"google.com/tpu.topology\":\"2x2\"}}}"},
  });
  k8s::ClusterConfig cluster = ScriptedCluster(server);
  k8s::SinkState state;
  k8s::WriteOutcome outcome;
  lm::Labels labels{{"google.com/tpu.count", "4"},
                    {"google.com/tpu.topology", "2x2"}};
  bool transient = true;
  CHECK_TRUE(k8s::UpdateNodeFeature(cluster, labels, &transient, &state,
                                    &outcome).ok());
  CHECK_EQ(outcome.gets, 1);
  CHECK_EQ(outcome.patches, 1);
  CHECK_EQ(outcome.puts, 0);
  CHECK_TRUE(state.known);
  CHECK_EQ(state.resource_version, "6");

  labels["google.com/tpu.count"] = "8";
  k8s::WriteOutcome second;
  CHECK_TRUE(k8s::UpdateNodeFeature(cluster, labels, &transient, &state,
                                    &second).ok());
  CHECK_EQ(second.gets, 0);  // zero-GET dirty write
  CHECK_EQ(second.patches, 1);
  CHECK_EQ(state.resource_version, "7");
  CHECK_TRUE(second.patch_bytes > 0 && second.patch_bytes < 200);

  k8s::WriteOutcome third;
  CHECK_TRUE(k8s::UpdateNodeFeature(cluster, labels, &transient, &state,
                                    &third).ok());
  CHECK_EQ(third.gets, 1);  // semantic-equality probe, no write
  CHECK_EQ(third.patches + third.puts + third.posts, 0);

  // Wire truth: GET, PATCH, PATCH, GET — never a PUT; the first patch
  // body is a DIFF with the rv precondition, not a full object.
  CHECK_EQ(server.exchanges().size(), static_cast<size_t>(4));
  CHECK_EQ(server.exchanges()[0].method, "GET");
  CHECK_EQ(server.exchanges()[1].method, "PATCH");
  CHECK_EQ(server.exchanges()[2].method, "PATCH");
  CHECK_EQ(server.exchanges()[3].method, "GET");
  const std::string& patch1 = server.exchanges()[1].body;
  CHECK_TRUE(patch1.find("\"resourceVersion\":\"5\"") != std::string::npos);
  CHECK_TRUE(patch1.find("\"google.com/tpu.count\":\"4\"") !=
             std::string::npos);
  CHECK_TRUE(patch1.find("apiVersion") == std::string::npos);
  // The second patch carries ONLY the changed key.
  const std::string& patch2 = server.exchanges()[2].body;
  CHECK_TRUE(patch2.find("\"google.com/tpu.count\":\"8\"") !=
             std::string::npos);
  CHECK_TRUE(patch2.find("topology") == std::string::npos);
}

void TestSinkPatchConflictReGet() {
  // The 409 contract (table-driven over the conflict position): a stale
  // resourceVersion costs exactly ONE extra GET — PATCH(409) ->
  // re-GET -> PATCH(200) — and never a full-object PUT.
  struct Case {
    const char* name;
    bool start_known;  // conflict on the zero-GET patch vs the GET path
  };
  const Case kCases[] = {
      {"zero-get patch conflicts", true},
      {"fresh-get patch conflicts", false},
  };
  for (const Case& c : kCases) {
    std::vector<ScriptedApiServer::Reply> script;
    if (!c.start_known) {
      script.push_back(
          {200,
           "{\"metadata\":{\"resourceVersion\":\"8\",\"labels\":{"
           "\"nfd.node.kubernetes.io/node-name\":\"unit-node\"}},"
           "\"spec\":{\"labels\":{\"k\":\"old\"}}}"});
    }
    script.push_back({409, "{\"message\":\"conflict\"}"});
    script.push_back(
        {200,
         "{\"metadata\":{\"resourceVersion\":\"9\",\"labels\":{"
         "\"nfd.node.kubernetes.io/node-name\":\"unit-node\"}},"
         "\"spec\":{\"labels\":{\"k\":\"theirs\"}}}"});
    script.push_back({200, "{\"metadata\":{\"resourceVersion\":\"10\"}}"});
    ScriptedApiServer server(std::move(script));
    k8s::ClusterConfig cluster = ScriptedCluster(server);
    k8s::SinkState state;
    if (c.start_known) {
      state.known = true;
      state.resource_version = "7";  // stale on purpose
      state.acked = {{"k", "old"}};
    }
    k8s::WriteOutcome outcome;
    bool transient = true;
    lm::Labels labels{{"k", "new"}};
    Status s = k8s::UpdateNodeFeature(cluster, labels, &transient, &state,
                                      &outcome);
    CHECK_TRUE(s.ok());
    // Exactly one extra GET beyond what the path already owed.
    CHECK_EQ(outcome.gets, c.start_known ? 1 : 2);
    CHECK_EQ(outcome.patches, 2);
    CHECK_EQ(server.CountVerb("PUT"), 0);
    CHECK_EQ(state.resource_version, "10");
    // The re-GET re-diffed against the server's moved content: the
    // winning patch overwrites "theirs", preconditioned on ITS rv.
    const std::string& final_patch = server.exchanges().back().body;
    CHECK_TRUE(final_patch.find("\"resourceVersion\":\"9\"") !=
               std::string::npos);
    CHECK_TRUE(final_patch.find("\"k\":\"new\"") != std::string::npos);
  }
}

void TestSinkPatchFallbacks() {
  // 404 under a zero-GET patch: the CR was deleted externally — fall
  // back to the create path (GET 404 -> POST), state re-learned.
  {
    ScriptedApiServer server({
        {404, "{\"message\":\"gone\"}"},
        {404, "{\"message\":\"gone\"}"},
        {201, "{\"metadata\":{\"resourceVersion\":\"1\"}}"},
    });
    k8s::ClusterConfig cluster = ScriptedCluster(server);
    k8s::SinkState state;
    state.known = true;
    state.resource_version = "44";
    state.acked = {{"k", "old"}};
    bool transient = true;
    k8s::WriteOutcome outcome;
    CHECK_TRUE(k8s::UpdateNodeFeature(cluster, {{"k", "new"}}, &transient,
                                      &state, &outcome).ok());
    CHECK_EQ(outcome.patches, 1);
    CHECK_EQ(outcome.posts, 1);
    CHECK_EQ(state.resource_version, "1");
    // The create body is the FULL CR (it must carry the node-name
    // metadata label the NFD master attributes by).
    CHECK_TRUE(server.exchanges().back().body.find(
                   "nfd.node.kubernetes.io/node-name") !=
               std::string::npos);
  }
  // 415: the apiserver doesn't speak merge-patch — fall back to the
  // reference GET->mutate->PUT, and REMEMBER it: the next write skips
  // the doomed PATCH entirely.
  {
    ScriptedApiServer server({
        {415, "{\"message\":\"no merge-patch\"}"},
        {200,
         "{\"metadata\":{\"resourceVersion\":\"3\",\"labels\":{"
         "\"nfd.node.kubernetes.io/node-name\":\"unit-node\"}},"
         "\"spec\":{\"labels\":{\"k\":\"old\"}},\"apiVersion\":\"x\"}"},
        {200, "{\"metadata\":{\"resourceVersion\":\"4\"}}"},
        {200,
         "{\"metadata\":{\"resourceVersion\":\"4\",\"labels\":{"
         "\"nfd.node.kubernetes.io/node-name\":\"unit-node\"}},"
         "\"spec\":{\"labels\":{\"k\":\"new\"}},\"apiVersion\":\"x\"}"},
        {200, "{\"metadata\":{\"resourceVersion\":\"5\"}}"},
    });
    k8s::ClusterConfig cluster = ScriptedCluster(server);
    k8s::SinkState state;
    state.known = true;
    state.resource_version = "3";
    state.acked = {{"k", "old"}};
    bool transient = true;
    k8s::WriteOutcome outcome;
    CHECK_TRUE(k8s::UpdateNodeFeature(cluster, {{"k", "new"}}, &transient,
                                      &state, &outcome).ok());
    CHECK_TRUE(state.patch_unsupported);
    CHECK_EQ(outcome.patches, 1);
    CHECK_EQ(outcome.puts, 1);
    // The PUT body is the mutated FETCHED object: foreign fields
    // (apiVersion here) survive.
    CHECK_TRUE(server.exchanges()[2].body.find("\"apiVersion\":\"x\"") !=
               std::string::npos);
    // Second write: straight GET -> PUT, no PATCH attempt.
    k8s::WriteOutcome second;
    CHECK_TRUE(k8s::UpdateNodeFeature(cluster, {{"k", "newer"}},
                                      &transient, &state, &second).ok());
    CHECK_EQ(second.patches, 0);
    CHECK_EQ(second.gets, 1);
    CHECK_EQ(second.puts, 1);
  }
  // A foreign NON-STRING spec.labels value: invisible to the string-map
  // diff (empty patch) but it must still be healed — the write falls
  // through to the wholesale-replace PUT, like the reference. A local
  // "no diff" no-op here would leave the junk in the CR forever.
  {
    ScriptedApiServer server({
        {200,
         "{\"metadata\":{\"resourceVersion\":\"3\",\"labels\":{"
         "\"nfd.node.kubernetes.io/node-name\":\"unit-node\"}},"
         "\"spec\":{\"labels\":{\"k\":\"v\",\"junk\":123}}}"},
        {200, "{\"metadata\":{\"resourceVersion\":\"4\"}}"},
    });
    k8s::ClusterConfig cluster = ScriptedCluster(server);
    k8s::SinkState state;
    bool transient = true;
    k8s::WriteOutcome outcome;
    CHECK_TRUE(k8s::UpdateNodeFeature(cluster, {{"k", "v"}}, &transient,
                                      &state, &outcome).ok());
    CHECK_EQ(outcome.patches, 0);
    CHECK_EQ(outcome.puts, 1);
    CHECK_TRUE(server.exchanges().back().body.find("junk") ==
               std::string::npos);  // wholesale replace dropped it
  }
}

void TestSinkConflictExhaustion() {
  // kMaxAttempts 409s in a row: the write must settle as a TRANSIENT
  // failure carrying the last conflict (journaled, breaker-visible) —
  // not fall silently out of the retry loop. Both update flavors.
  setenv("NODE_NAME", "unit-node", 1);
  setenv("TFD_APISERVER_URL", "http://127.0.0.1:1", 1);
  setenv("TFD_SERVICEACCOUNT_DIR", "/nonexistent-tfd-unit", 1);
  Result<k8s::ClusterConfig> cluster = k8s::LoadInClusterConfig();
  CHECK_TRUE(cluster.ok());
  lm::Labels labels{{"google.com/tpu.count", "4"}};
  struct Case {
    const char* spec;
    bool use_patch;
    const char* expect_in_error;
  };
  const Case kCases[] = {
      // PATCH conflicts forever (GET 200 fabricates an empty CR "{}").
      {"k8s.get:http=200:count=3,k8s.patch:http=409:count=3", true,
       "patch conflict"},
      // The reference PUT path conflicts forever.
      {"k8s.get:http=200:count=3,k8s.put:http=409:count=3", false,
       "update conflict"},
  };
  for (const Case& c : kCases) {
    CHECK_TRUE(fault::Arm(c.spec).ok());
    k8s::ClusterConfig scoped = *cluster;
    scoped.use_patch = c.use_patch;
    k8s::SinkState state;
    bool transient = false;  // must be overwritten to true
    Status s =
        k8s::UpdateNodeFeature(scoped, labels, &transient, &state);
    CHECK_TRUE(!s.ok());
    CHECK_TRUE(transient);
    CHECK_TRUE(s.message().find("attempts exhausted") != std::string::npos);
    CHECK_TRUE(s.message().find(c.expect_in_error) != std::string::npos);
  }
  fault::Disarm();
  unsetenv("NODE_NAME");
  unsetenv("TFD_APISERVER_URL");
  unsetenv("TFD_SERVICEACCOUNT_DIR");
}

void TestSinkRetryAfterAndDefer() {
  // A 429 with Retry-After + APF attribution headers: the outcome must
  // surface both (DispatchSink feeds them to the breaker's deferral),
  // and the deferral must gate Allow() in the CLOSED state without a
  // state-machine transition.
  ScriptedApiServer server({
      {429, "{\"message\":\"slow down\"}",
       "Retry-After: 7\r\n"
       "X-Kubernetes-PF-FlowSchema-UID: fs-1\r\n"
       "X-Kubernetes-PF-PriorityLevel-UID: pl-1\r\n"},
  });
  k8s::ClusterConfig cluster = ScriptedCluster(server);
  k8s::SinkState state;
  state.known = true;
  state.resource_version = "2";
  state.acked = {{"k", "old"}};
  bool transient = false;
  k8s::WriteOutcome outcome;
  Status s = k8s::UpdateNodeFeature(cluster, {{"k", "new"}}, &transient,
                                    &state, &outcome);
  CHECK_TRUE(!s.ok());
  CHECK_TRUE(transient);
  CHECK_TRUE(outcome.retry_after_s == 7.0);
  CHECK_TRUE(outcome.apf_rejected);

  k8s::CircuitBreaker breaker(k8s::CircuitBreaker::Options{3, 30});
  CHECK_TRUE(breaker.Allow());
  breaker.Defer(7.0, "Retry-After");
  CHECK_TRUE(!breaker.Allow());  // closed but deferred
  CHECK_TRUE(breaker.deferred());
  CHECK_TRUE(breaker.state() == k8s::CircuitBreaker::State::kClosed);
  breaker.AgeForTest(8.0);
  CHECK_TRUE(!breaker.deferred());
  CHECK_TRUE(breaker.Allow());
  // Deadlines only extend: a shorter later defer never shrinks one.
  breaker.Defer(10.0, "x");
  breaker.Defer(1.0, "y");
  breaker.AgeForTest(5.0);
  CHECK_TRUE(!breaker.Allow());
}

void TestHttpResponseHeaders() {
  Result<http::Response> r = http::ParseResponse(
      "HTTP/1.1 429 Too Many Requests\r\n"
      "Content-Type: application/json\r\n"
      "RETRY-AFTER:  12 \r\n"
      "X-Kubernetes-PF-FlowSchema-UID: abc\r\n"
      "\r\n"
      "{}");
  CHECK_TRUE(r.ok());
  CHECK_EQ(r->status, 429);
  CHECK_EQ(r->headers.at("retry-after"), "12");  // lowercased, trimmed
  CHECK_EQ(r->headers.at("x-kubernetes-pf-flowschema-uid"), "abc");
  CHECK_TRUE(r->RetryAfterSeconds() == 12.0);
  // HTTP-date Retry-After is not parsed: reads as "no pause named".
  Result<http::Response> date = http::ParseResponse(
      "HTTP/1.1 503 X\r\nRetry-After: Tue, 04 Aug 2026 01:00:00 GMT\r\n"
      "\r\n");
  CHECK_TRUE(date.ok());
  CHECK_TRUE(date->RetryAfterSeconds() == 0.0);
}

// ---- perf characterization (src/tfd/perf/) -------------------------------

void TestPerfClassificationGrid() {
  // The parity grid: tests/test_perf.py runs tpufd.perfmodel.classify
  // over the SAME cases — any drift between the C++ and Python
  // thresholds fails one of the two suites.
  struct Case {
    double matmul, hbm;
    int prev;
    int want;
  };
  const Case cases[] = {
      {95, 80, -1, perf::kRankGold},
      {95, 65, -1, perf::kRankSilver},   // hbm under the gold bar
      {89, 80, -1, perf::kRankSilver},
      {95, -1, -1, perf::kRankGold},     // unknown hbm: matmul gates
      {-1, 80, -1, perf::kRankSilver},   // unknown matmul: never gold
      {49, 80, -1, perf::kRankDegraded},
      {95, 45, -1, perf::kRankDegraded},
      // Hysteresis: leaving a class needs the margin cleared.
      {89, 80, perf::kRankGold, perf::kRankGold},
      {86, 80, perf::kRankGold, perf::kRankSilver},
      {91, 80, perf::kRankSilver, perf::kRankSilver},
      {94, 80, perf::kRankSilver, perf::kRankGold},
      {49, 80, perf::kRankSilver, perf::kRankSilver},
      {46, 80, perf::kRankSilver, perf::kRankDegraded},
      {51, 80, perf::kRankDegraded, perf::kRankDegraded},
      {54, 80, perf::kRankDegraded, perf::kRankSilver},
      {95, 80, perf::kRankDegraded, perf::kRankGold},
  };
  for (const Case& c : cases) {
    CHECK_EQ(perf::ClassifyPct(c.matmul, c.hbm, c.prev), c.want);
  }
  CHECK_EQ(std::string(perf::ClassName(perf::kRankGold)), "gold");
  CHECK_EQ(perf::ClassRankFromName("degraded"), perf::kRankDegraded);
  CHECK_EQ(perf::ClassRankFromName("platinum"), -1);
  // pct-of-rated math mirrors tpufd.health.pct_of_rated.
  CHECK_TRUE(perf::PctOfRated(98.5, 197.0) == 50.0);
  CHECK_TRUE(perf::PctOfRated(100, 0) == -1);
  CHECK_TRUE(perf::PctOfRated(-1, 197.0) == -1);
}

void TestPerfRatedSpecs() {
  const std::map<std::string, perf::RatedSpec>& baked =
      perf::BakedRatedSpecs();
  CHECK_EQ(baked.size(), 6u);
  CHECK_TRUE(baked.at("v5e").matmul_tflops == 197.0);
  CHECK_TRUE(baked.at("v5p").hbm_gbps == 2765.0);

  Result<std::map<std::string, perf::RatedSpec>> parsed =
      perf::ParseRatedSpecs(
          "{\"families\":{\"v5e\":{\"matmul_tflops\":197.0,"
          "\"hbm_gbps\":819.0}}}");
  CHECK_TRUE(parsed.ok());
  CHECK_TRUE(parsed->at("v5e").hbm_gbps == 819.0);
  CHECK_TRUE(!perf::ParseRatedSpecs("{}").ok());
  CHECK_TRUE(!perf::ParseRatedSpecs("{\"families\":{}}").ok());
  CHECK_TRUE(!perf::ParseRatedSpecs(
                  "{\"families\":{\"v5e\":{\"matmul_tflops\":-1,"
                  "\"hbm_gbps\":819}}}")
                  .ok());

  // Parity with the checked-in single source of truth: the baked table
  // must match tpufd/rated_specs.json value for value (the tier-1 run
  // executes from the repo root; a manual run from elsewhere skips).
  for (const char* path :
       {"tpufd/rated_specs.json", "../tpufd/rated_specs.json"}) {
    if (!FileExists(path)) continue;
    Result<std::string> text = ReadFile(path);
    CHECK_TRUE(text.ok());
    Result<std::map<std::string, perf::RatedSpec>> file_specs =
        perf::ParseRatedSpecs(*text);
    CHECK_TRUE(file_specs.ok());
    CHECK_EQ(file_specs->size(), baked.size());
    for (const auto& [family, spec] : *file_specs) {
      CHECK_TRUE(baked.count(family) == 1);
      CHECK_TRUE(baked.at(family).matmul_tflops == spec.matmul_tflops);
      CHECK_TRUE(baked.at(family).hbm_gbps == spec.hbm_gbps);
    }
    break;
  }
}

void TestPerfSerializeRoundTrip() {
  perf::Characterization c;
  c.fingerprint = "v5e/4/2x2/2.9.0";
  c.family = "v5e";
  c.measured_at = 1234.5;
  c.measure_seconds = 61.25;
  c.matmul_tflops = 193.25;
  c.hbm_gbps = 650.5;
  c.ici_gbps = 40.125;
  c.matmul_pct = 98.1;
  c.hbm_pct = 79.4;
  c.class_rank = perf::kRankGold;
  std::string json = perf::SerializeCharacterization(c);
  Result<perf::Characterization> parsed = perf::ParseCharacterization(json);
  CHECK_TRUE(parsed.ok());
  CHECK_EQ(parsed->fingerprint, "v5e/4/2x2/2.9.0");
  CHECK_EQ(parsed->family, "v5e");
  CHECK_TRUE(parsed->matmul_tflops == 193.25);
  CHECK_TRUE(parsed->ici_gbps == 40.125);
  CHECK_EQ(parsed->class_rank, perf::kRankGold);

  // A tampered field fails the perf section's OWN checksum: the gate
  // that lets a corrupt perf payload be rejected independently of the
  // label payload.
  std::string tampered = json;
  size_t pos = tampered.find("193.250");
  CHECK_TRUE(pos != std::string::npos);
  tampered.replace(pos, 7, "250.193");
  Result<perf::Characterization> bad =
      perf::ParseCharacterization(tampered);
  CHECK_TRUE(!bad.ok());
  CHECK_TRUE(bad.error().find("checksum") != std::string::npos);

  CHECK_TRUE(!perf::ParseCharacterization("{").ok());
  CHECK_TRUE(!perf::ParseCharacterization("{}").ok());
  // Unknown class names and schemas are distinct, loud rejections.
  std::string unknown_class = json;
  pos = unknown_class.find("\"gold\"");
  unknown_class.replace(pos, 6, "\"plat\"");
  CHECK_TRUE(!perf::ParseCharacterization(unknown_class).ok());

  // Cache round trip incl. the empty (pre-PR-9) payload.
  perf::Cache cache;
  CHECK_TRUE(cache.RestoreJson("").ok());
  CHECK_TRUE(!cache.Get().has_value());
  CHECK_TRUE(cache.RestoreJson(json).ok());
  CHECK_TRUE(cache.Get().has_value());
  CHECK_EQ(cache.Get()->class_rank, perf::kRankGold);
  CHECK_EQ(cache.SerializeJson(), json);
  // Garbage never clobbers a good cache.
  CHECK_TRUE(!cache.RestoreJson("garbage").ok());
  CHECK_TRUE(cache.Get().has_value());
  cache.Invalidate();
  CHECK_EQ(cache.SerializeJson(), "");
}

void TestPerfExecParse() {
  Result<std::map<std::string, double>> parsed = perf::ParseExecOutput(
      "matmul-tflops=193.2\nhbm-gbps=650\nici-gbps=40.5\n"
      "bogus line\nunknown-key=7\n");
  CHECK_TRUE(parsed.ok());
  CHECK_TRUE(parsed->at("matmul-tflops") == 193.2);
  CHECK_TRUE(parsed->at("hbm-gbps") == 650.0);
  CHECK_TRUE(parsed->at("ici-gbps") == 40.5);
  CHECK_EQ(parsed->size(), 3u);
  CHECK_TRUE(!perf::ParseExecOutput("").ok());
  CHECK_TRUE(!perf::ParseExecOutput("nothing useful\n").ok());
  // ici alone is context, not a characterization.
  CHECK_TRUE(!perf::ParseExecOutput("ici-gbps=40\n").ok());
}

void TestPerfDutyCycle() {
  // First measurement is always allowed.
  CHECK_TRUE(perf::MeasureAllowed(100, 0, 0, 1));
  // 60s measurement at 1% duty: next start >= end + 60*(100-1) = +5940.
  CHECK_TRUE(!perf::MeasureAllowed(1000 + 5939, 1000, 60, 1));
  CHECK_TRUE(perf::MeasureAllowed(1000 + 5940, 1000, 60, 1));
  // 50% duty: gap equals the measurement itself.
  CHECK_TRUE(!perf::MeasureAllowed(1059, 1000, 60, 50));
  CHECK_TRUE(perf::MeasureAllowed(1060, 1000, 60, 50));
  // 100% duty disables the bound.
  CHECK_TRUE(perf::MeasureAllowed(1000, 1000, 60, 100));
  perf::Cache cache;
  CHECK_TRUE(cache.AllowedNow(0, 1));
  cache.NoteMeasurement(1000, 60);
  CHECK_TRUE(!cache.AllowedNow(1001, 1));
  CHECK_TRUE(cache.AllowedNow(7000, 1));
}

void TestPerfLabels() {
  perf::Characterization c;
  c.matmul_tflops = 193.2;
  c.hbm_gbps = 650.4;
  c.ici_gbps = 40.0;
  c.matmul_pct = 98.07;
  c.class_rank = perf::kRankGold;
  std::map<std::string, std::string> labels = perf::BuildLabels(c);
  CHECK_EQ(labels.at(lm::kPerfMatmulTflops), "193");
  CHECK_EQ(labels.at(lm::kPerfHbmGbps), "650");
  CHECK_EQ(labels.at(lm::kPerfIciGbps), "40");
  CHECK_EQ(labels.at(lm::kPerfPctOfRated), "98");
  CHECK_EQ(labels.at(lm::kPerfClass), "gold");
  // Unmeasured fields stay absent rather than publishing zeros.
  perf::Characterization sparse;
  sparse.matmul_tflops = 0.43;  // small-but-real CI measurement
  sparse.class_rank = perf::kRankSilver;
  labels = perf::BuildLabels(sparse);
  CHECK_EQ(labels.at(lm::kPerfMatmulTflops), "0.43");
  CHECK_TRUE(labels.count(lm::kPerfHbmGbps) == 0);
  CHECK_TRUE(labels.count(lm::kPerfPctOfRated) == 0);
  CHECK_EQ(labels.at(lm::kPerfClass), "silver");
  CHECK_EQ(labels.size(), 2u);
}

void TestPerfStateSectionIndependence() {
  // The perf payload rides the state file as its OWN schema section: a
  // pre-perf file restores labels normally with no perf payload, and a
  // corrupt perf section is rejected alone — the label payload
  // survives.
  sched::PersistedState state;
  state.node = "unit-node";
  state.saved_at = 1000.0;
  state.source = "mock";
  state.tier = "fresh";
  state.labels = {{"google.com/tpu.count", "4"}};

  // Forward compat: no perf section at all (pre-PR-9 writer).
  std::string framed = sched::SerializeState(state);
  Result<sched::PersistedState> parsed = sched::ParseState(framed);
  CHECK_TRUE(parsed.ok());
  CHECK_EQ(parsed->perf_json, "");

  perf::Characterization c;
  c.fingerprint = "v2/4/2x2/-";
  c.family = "v2";
  c.measured_at = 900;
  c.matmul_tflops = 44;
  c.class_rank = perf::kRankGold;
  state.perf_json = perf::SerializeCharacterization(c);
  framed = sched::SerializeState(state);
  parsed = sched::ParseState(framed);
  CHECK_TRUE(parsed.ok());
  CHECK_TRUE(!parsed->perf_json.empty());
  CHECK_TRUE(perf::ParseCharacterization(parsed->perf_json).ok());

  // Corrupt the perf section's CONTENT (outer frame recomputed, so the
  // file-level checksum passes — the inner gate must catch it without
  // failing the labels).
  sched::PersistedState corrupt = state;
  size_t pos = corrupt.perf_json.find("\"v2\"");
  CHECK_TRUE(pos != std::string::npos);
  corrupt.perf_json.replace(pos, 4, "\"v3\"");
  framed = sched::SerializeState(corrupt);
  parsed = sched::ParseState(framed);
  CHECK_TRUE(parsed.ok());  // labels fine
  CHECK_EQ(parsed->labels.at("google.com/tpu.count"), "4");
  Result<perf::Characterization> inner =
      perf::ParseCharacterization(parsed->perf_json);
  CHECK_TRUE(!inner.ok());
  CHECK_TRUE(inner.error().find("checksum") != std::string::npos);

  // The stale-rejection path hands the perf section out like the
  // healthsm one: a characterization's validity is its fingerprint,
  // not the label payload's age.
  std::string dir = "/tmp/tfd-unit-perf-state-" + std::to_string(getpid());
  std::string path = dir + "/state";
  CHECK_TRUE(sched::SaveState(path, state).ok());
  std::string stale_health, stale_perf;
  Result<sched::PersistedState> stale = sched::LoadState(
      path, "unit-node", 600, 1000.0 + 3600, &stale_health, &stale_perf);
  CHECK_TRUE(!stale.ok());
  // The transport may reformat the JSON (jsonlite round trip); the
  // canonical-field checksum must still validate and the payload must
  // be semantically intact.
  Result<perf::Characterization> stale_parsed =
      perf::ParseCharacterization(stale_perf);
  CHECK_TRUE(stale_parsed.ok());
  CHECK_EQ(stale_parsed->fingerprint, "v2/4/2x2/-");
  CHECK_TRUE(stale_parsed->matmul_tflops == 44.0);
  // ...but a FOREIGN node's perf section is never handed out.
  stale_perf = "untouched";
  stale = sched::LoadState(path, "other-node", 600, 1000.0 + 3600,
                           &stale_health, &stale_perf);
  CHECK_TRUE(!stale.ok());
  CHECK_EQ(stale_perf, "untouched");
  std::string cmd = "rm -rf " + dir;
  CHECK_TRUE(system(cmd.c_str()) == 0);
}

void TestGovernorPerfClassDemotion() {
  lm::LabelGovernor governor(lm::GovernorPolicy{300, 6});
  lm::Labels previous = {{lm::kPerfClass, "gold"},
                         {"google.com/tpu.count", "4"}};
  lm::Provenance prev_prov;
  governor.NotePublished(previous, 1000.0);

  // A demotion inside the hold-down window passes (conservative
  // direction; the characterization pipeline already debounced it).
  lm::Labels candidate = previous;
  candidate[lm::kPerfClass] = "degraded";
  lm::Provenance provenance;
  std::vector<lm::SuppressedFlip> suppressed;
  governor.Apply(previous, prev_prov, false, 1010.0, &candidate,
                 &provenance, &suppressed);
  CHECK_TRUE(suppressed.empty());
  CHECK_EQ(candidate.at(lm::kPerfClass), "degraded");
  governor.CommitPublished();

  // The promotion straight back inside the hold-down is governed.
  lm::Labels degraded_set = candidate;
  lm::Labels promote = degraded_set;
  promote[lm::kPerfClass] = "gold";
  suppressed.clear();
  governor.Apply(degraded_set, prev_prov, false, 1020.0, &promote,
                 &provenance, &suppressed);
  CHECK_EQ(suppressed.size(), 1u);
  CHECK_EQ(candidate.at(lm::kPerfClass), "degraded");
  CHECK_EQ(promote.at(lm::kPerfClass), "degraded");  // held
  // Past the hold-down, the promotion lands.
  promote[lm::kPerfClass] = "gold";
  suppressed.clear();
  governor.Apply(degraded_set, prev_prov, false, 1400.0, &promote,
                 &provenance, &suppressed);
  CHECK_TRUE(suppressed.empty());
  CHECK_EQ(promote.at(lm::kPerfClass), "gold");
}

void TestHealthsmClassRankDebounce() {
  healthsm::Policy policy;
  policy.flap_window_s = 300;
  policy.flap_threshold = 6;
  policy.unhealthy_after = 2;
  policy.recover_after = 3;
  healthsm::HealthTracker tracker(policy);

  const std::string fp = "v2/4/2x2/-";
  // First characterization publishes immediately.
  CHECK_EQ(tracker.ObserveClassRank("perf", perf::kRankGold, fp, 1000),
           perf::kRankGold);
  // One throttled round never demotes...
  CHECK_EQ(tracker.ObserveClassRank("perf", perf::kRankDegraded, fp, 1010),
           perf::kRankGold);
  // ...agreement dissolves the streak...
  CHECK_EQ(tracker.ObserveClassRank("perf", perf::kRankGold, fp, 1020),
           perf::kRankGold);
  CHECK_EQ(tracker.ObserveClassRank("perf", perf::kRankDegraded, fp, 1030),
           perf::kRankGold);
  // ...two consecutive demotion verdicts land it (unhealthy_after=2).
  CHECK_EQ(tracker.ObserveClassRank("perf", perf::kRankDegraded, fp, 1040),
           perf::kRankDegraded);
  // Promotion is earned: recover_after=3 consecutive gold verdicts.
  CHECK_EQ(tracker.ObserveClassRank("perf", perf::kRankGold, fp, 1050),
           perf::kRankDegraded);
  CHECK_EQ(tracker.ObserveClassRank("perf", perf::kRankGold, fp, 1060),
           perf::kRankDegraded);
  CHECK_EQ(tracker.ObserveClassRank("perf", perf::kRankGold, fp, 1070),
           perf::kRankGold);
  // A candidate switch mid-streak restarts the count.
  CHECK_EQ(tracker.ObserveClassRank("perf", perf::kRankSilver, fp, 1080),
           perf::kRankGold);
  CHECK_EQ(tracker.ObserveClassRank("perf", perf::kRankDegraded, fp, 1090),
           perf::kRankGold);
  CHECK_EQ(tracker.ObserveClassRank("perf", perf::kRankDegraded, fp, 1100),
           perf::kRankDegraded);

  // The debounce state serializes with the tracker: a half-built
  // streak survives kill -9.
  healthsm::HealthTracker restored;
  CHECK_EQ(restored.ObserveClassRank("perf", perf::kRankGold, fp, 1000),
           perf::kRankGold);
  CHECK_EQ(restored.ObserveClassRank("perf", perf::kRankDegraded, fp, 1010),
           perf::kRankGold);  // streak of 1 pending
  std::string json = restored.SerializeJson(1010);
  healthsm::HealthTracker fresh;
  CHECK_TRUE(fresh.RestoreJson(json, 1020).ok());
  // One more demotion verdict completes the restored streak.
  CHECK_EQ(fresh.ObserveClassRank("perf", perf::kRankDegraded, fp, 1030),
           perf::kRankDegraded);
  // ...but a DIFFERENT hardware fingerprint voids restored history:
  // the replacement chip's first verdict publishes immediately instead
  // of being debounced against the old chip's class (the rank state
  // can outlive the perf cache across a swap).
  healthsm::HealthTracker swapped;
  CHECK_TRUE(swapped.RestoreJson(json, 1020).ok());
  CHECK_EQ(swapped.ObserveClassRank("perf", perf::kRankGold,
                                    "v2/4/2x2/new", 1030),
           perf::kRankGold);

  // Fingerprint change: ResetClassRank forgets history, the next
  // verdict publishes immediately.
  tracker.ResetClassRank("perf");
  CHECK_EQ(tracker.ObserveClassRank("perf", perf::kRankSilver, fp, 1200),
           perf::kRankSilver);
}

// ---- slice coherence (slice/coord.h) -------------------------------------

void TestSliceIdentityDerivation() {
  using Env = std::map<std::string, std::string>;

  // Env override wins over everything.
  {
    slice::SliceIdentity id = slice::DeriveSliceIdentity(
        {{"TPU_NAME", "metadata-name"}, {"WORKER_ID", "9"}}, "v5p-128",
        {{"TFD_SLICE_ID", "my-slice"},
         {"TFD_SLICE_WORKER_ID", "3"},
         {"TFD_SLICE_HOSTS", "16"}});
    CHECK_TRUE(id.valid);
    CHECK_EQ(id.source, std::string("env"));
    CHECK_EQ(id.worker_id, 3);
    CHECK_EQ(id.num_hosts, 16);
    CHECK_EQ(id.raw_name, std::string("my-slice"));
  }
  // tpu-env: TPU_NAME + WORKER_ID + HOST_BOUNDS product.
  {
    slice::SliceIdentity id = slice::DeriveSliceIdentity(
        {{"TPU_NAME", "train-pod"},
         {"WORKER_ID", "2"},
         {"HOST_BOUNDS", "2,2,1"}},
        "", Env{});
    CHECK_TRUE(id.valid);
    CHECK_EQ(id.source, std::string("tpu-env"));
    CHECK_EQ(id.num_hosts, 4);
    CHECK_EQ(id.worker_id, 2);
  }
  // Hosts derived from the accelerator type + family chips-per-host
  // when HOST_BOUNDS is absent: v5p-128 = 64 chips / 4 per host = 16.
  {
    slice::SliceIdentity id = slice::DeriveSliceIdentity(
        {{"TPU_NAME", "big"}, {"WORKER_ID", "0"}}, "v5p-128", Env{});
    CHECK_TRUE(id.valid);
    CHECK_EQ(id.num_hosts, 16);
  }
  // CHIPS_PER_HOST_BOUNDS overrides the family default: 16 chips at
  // 2x2x1 per host = 4 hosts.
  {
    slice::SliceIdentity id = slice::DeriveSliceIdentity(
        {{"ACCELERATOR_TYPE", "v5litepod-16"},
         {"TPU_NAME", "lite"},
         {"WORKER_ID", "1"},
         {"CHIPS_PER_HOST_BOUNDS", "2,2,1"}},
        "", Env{});
    CHECK_TRUE(id.valid);
    CHECK_EQ(id.num_hosts, 4);
  }
  // GKE: the webhook-injected worker-hostname list is the shared name.
  {
    slice::SliceIdentity a = slice::DeriveSliceIdentity(
        Env{}, "v5litepod-16",
        {{"TPU_WORKER_HOSTNAMES", "h0,h1,h2,h3"},
         {"TPU_WORKER_ID", "1"},
         {"TFD_SLICE_HOSTS", "4"}});
    slice::SliceIdentity b = slice::DeriveSliceIdentity(
        Env{}, "v5litepod-16",
        {{"TPU_WORKER_HOSTNAMES", "h0,h1,h2,h3"},
         {"TPU_WORKER_ID", "2"},
         {"TFD_SLICE_HOSTS", "4"}});
    CHECK_TRUE(a.valid && b.valid);
    CHECK_EQ(a.slice_id, b.slice_id);  // same slice, every member
    CHECK_EQ(a.source, std::string("gke-env"));
    slice::SliceIdentity other = slice::DeriveSliceIdentity(
        Env{}, "v5litepod-16",
        {{"TPU_WORKER_HOSTNAMES", "g0,g1,g2,g3"},
         {"TPU_WORKER_ID", "0"},
         {"TFD_SLICE_HOSTS", "4"}});
    CHECK_TRUE(other.slice_id != a.slice_id);  // different slice
  }
  // Multislice: MEGASCALE_SLICE_ID separates the job's slices.
  {
    slice::SliceIdentity s0 = slice::DeriveSliceIdentity(
        {{"TPU_NAME", "ms"},
         {"WORKER_ID", "0"},
         {"HOST_BOUNDS", "2,1,1"},
         {"MEGASCALE_SLICE_ID", "0"}},
        "", Env{});
    slice::SliceIdentity s1 = slice::DeriveSliceIdentity(
        {{"TPU_NAME", "ms"},
         {"WORKER_ID", "0"},
         {"HOST_BOUNDS", "2,1,1"},
         {"MEGASCALE_SLICE_ID", "1"}},
        "", Env{});
    CHECK_TRUE(s0.valid && s1.valid);
    CHECK_TRUE(s0.slice_id != s1.slice_id);
  }
  // Missing metadata -> single-host fallback, never a guessed slice.
  CHECK_TRUE(!slice::DeriveSliceIdentity(Env{}, "", Env{}).valid);
  // Shape alone (no shared NAME) must not invent an identity: two
  // distinct v5e-64 slices in one cluster would collide.
  CHECK_TRUE(!slice::DeriveSliceIdentity(
                  {{"ACCELERATOR_TYPE", "v5litepod-64"},
                   {"WORKER_ID", "0"},
                   {"HOST_BOUNDS", "4,2,1"}},
                  "", Env{})
                  .valid);
  // A single-host slice needs no coordination.
  CHECK_TRUE(!slice::DeriveSliceIdentity(
                  {{"TPU_NAME", "tiny"}, {"WORKER_ID", "0"}},
                  "v5litepod-4", Env{})
                  .valid);
  // Worker id out of range is evidence of broken metadata, not a slice.
  CHECK_TRUE(!slice::DeriveSliceIdentity(
                  {{"TPU_NAME", "t"},
                   {"WORKER_ID", "7"},
                   {"HOST_BOUNDS", "2,1,1"}},
                  "", Env{})
                  .valid);
  // Sanitization: case, hostile characters, and collision resistance.
  {
    std::string a = slice::SanitizeSliceId("My/Pod:0");
    for (char c : a) {
      CHECK_TRUE((c >= 'a' && c <= 'z') || (c >= '0' && c <= '9') ||
                 c == '-');
    }
    CHECK_TRUE(slice::SanitizeSliceId("tpu/a") !=
               slice::SanitizeSliceId("tpu:a"));
    CHECK_EQ(slice::SanitizeSliceId("x"), slice::SanitizeSliceId("x"));
    // Cross-language pins (tpufd/slicecoord.py derives the SAME ids —
    // the textbook-FNV suffix included; change one side, change both).
    CHECK_EQ(slice::SanitizeSliceId("My/Pod:0"),
             std::string("my-pod-0-ca4412d5"));
    CHECK_EQ(slice::SanitizeSliceId("train-pod"),
             std::string("train-pod-724677df"));
  }
}

void TestSliceDocSerialization() {
  slice::MemberReport report;
  report.host = "host-3";
  report.worker_id = 3;
  report.healthy = true;
  report.shape = "chips=4;topo=4x4";
  report.perf_class = "gold";
  report.reported_at = 1234.5;
  Result<slice::MemberReport> parsed =
      slice::ParseReport(slice::SerializeReport(report));
  CHECK_TRUE(parsed.ok());
  CHECK_EQ(parsed->host, report.host);
  CHECK_EQ(parsed->worker_id, 3);
  CHECK_TRUE(parsed->healthy);
  CHECK_EQ(parsed->shape, report.shape);
  CHECK_EQ(parsed->perf_class, std::string("gold"));
  CHECK_TRUE(!slice::ParseReport("{}").ok());       // no host
  CHECK_TRUE(!slice::ParseReport("garbage").ok());
  CHECK_TRUE(!slice::ParseReport("[1,2]").ok());

  slice::Lease lease{"host-0", 7, 1000.0, 30};
  Result<slice::Lease> lease2 =
      slice::ParseLease(slice::SerializeLease(lease));
  CHECK_TRUE(lease2.ok());
  CHECK_EQ(lease2->holder, std::string("host-0"));
  CHECK_EQ(static_cast<int>(lease2->epoch), 7);
  CHECK_TRUE(!slice::LeaseExpired(*lease2, 1030.0));
  CHECK_TRUE(slice::LeaseExpired(*lease2, 1030.5));
  CHECK_TRUE(slice::LeaseExpired(slice::Lease{}, 0));  // empty = expired

  slice::SliceVerdict verdict;
  verdict.seq = 9;
  verdict.leader = "host-0";
  verdict.computed_at = 2000;
  verdict.hosts = 4;
  verdict.healthy_hosts = 3;
  verdict.degraded = true;
  verdict.perf_class = "silver";
  verdict.members = {"host-0", "host-1", "host-2"};
  Result<slice::SliceVerdict> verdict2 =
      slice::ParseVerdict(slice::SerializeVerdict(verdict));
  CHECK_TRUE(verdict2.ok());
  CHECK_TRUE(slice::VerdictContentEquals(verdict, *verdict2));
  CHECK_EQ(static_cast<int>(verdict2->seq), 9);
  CHECK_TRUE(!slice::ParseVerdict("{}").ok());  // no hosts
}

void TestSliceVerdictMerge() {
  slice::SliceIdentity identity;
  identity.valid = true;
  identity.slice_id = "testslice";
  identity.num_hosts = 4;
  slice::CoordPolicy policy;
  policy.lease_duration_s = 10;
  policy.agreement_timeout_s = 5;

  auto report = [](const std::string& host, bool healthy, double at,
                   const std::string& cls = "") {
    slice::MemberReport r;
    r.host = host;
    r.healthy = healthy;
    r.reported_at = at;
    r.perf_class = cls;
    return r;
  };

  // This grid is the cross-language parity pin: tests/test_slice.py
  // runs the SAME scenarios through tpufd/slicecoord.py and asserts
  // the same expected values — change one side, change both.
  // All healthy, all fresh.
  {
    slice::SliceVerdict v = slice::MergeVerdict(
        identity, "a",
        {report("a", true, 100, "gold"), report("b", true, 99, "gold"),
         report("c", true, 98, "silver"), report("d", true, 100, "gold")},
        policy, 100);
    CHECK_EQ(v.healthy_hosts, 4);
    CHECK_TRUE(!v.degraded);
    CHECK_EQ(v.perf_class, std::string("silver"));  // worst wins
    CHECK_EQ(static_cast<int>(v.members.size()), 4);
  }
  // A stale report is a host the slice cannot vouch for.
  {
    slice::SliceVerdict v = slice::MergeVerdict(
        identity, "a",
        {report("a", true, 100), report("b", true, 94),
         report("c", true, 100), report("d", true, 100)},
        policy, 100);
    CHECK_EQ(v.healthy_hosts, 3);
    CHECK_TRUE(v.degraded);
    CHECK_EQ(static_cast<int>(v.members.size()), 3);
    CHECK_EQ(v.perf_class, std::string(""));  // nobody measured
  }
  // A present-but-unhealthy member counts present, not healthy.
  {
    slice::SliceVerdict v = slice::MergeVerdict(
        identity, "a",
        {report("a", true, 100, "gold"), report("b", false, 100, "degraded"),
         report("c", true, 100, "gold"), report("d", true, 100, "gold")},
        policy, 100);
    CHECK_EQ(v.healthy_hosts, 3);
    CHECK_TRUE(v.degraded);
    CHECK_EQ(static_cast<int>(v.members.size()), 4);
    CHECK_EQ(v.perf_class, std::string("degraded"));
  }
  // A lone bootstrap report: 1/4 healthy, degraded.
  {
    slice::SliceVerdict v = slice::MergeVerdict(
        identity, "a", {report("a", true, 100)}, policy, 100);
    CHECK_EQ(v.healthy_hosts, 1);
    CHECK_TRUE(v.degraded);
  }
  // Labels are pure functions of the verdict fields (never of who
  // computed it): leader/seq must not move a byte.
  {
    slice::SliceVerdict v1 = slice::MergeVerdict(
        identity, "a", {report("a", true, 100), report("b", true, 100)},
        policy, 100);
    slice::SliceVerdict v2 = v1;
    v2.leader = "b";
    v2.seq = 99;
    v2.computed_at = 777;
    lm::Labels l1 = slice::BuildSliceLabels(identity, v1);
    lm::Labels l2 = slice::BuildSliceLabels(identity, v2);
    CHECK_TRUE(l1 == l2);
    CHECK_EQ(l1[lm::kSliceId], std::string("testslice"));
    CHECK_EQ(l1[lm::kSliceHosts], std::string("4"));
    CHECK_EQ(l1[lm::kSliceHealthyHosts], std::string("2"));
    CHECK_EQ(l1[lm::kSliceDegraded], std::string("true"));
    CHECK_EQ(l1.count(lm::kSliceClass), 0u);  // no class claimed
  }
}

// In-memory DocStore with injectable partition, for the lease-edge
// suite: real resourceVersion semantics (precondition 409s), merge
// updates, create race detection.
class MemoryDocStore : public slice::DocStore {
 public:
  bool fail_transport = false;
  bool alive_on_fail = false;  // true = "server answered 429/5xx"
  bool fail_patch = false;     // writes throttled, reads fine (alive)

  Status Get(const std::string& name, slice::CoordDoc* doc,
             bool* alive) override {
    if (fail_transport) {
      *alive = alive_on_fail;
      return Status::Error("injected transport failure");
    }
    *alive = true;
    auto it = docs.find(name);
    if (it == docs.end()) {
      doc->found = false;
      return Status::Ok();
    }
    doc->found = true;
    doc->resource_version = std::to_string(it->second.rv);
    doc->data = it->second.data;
    return Status::Ok();
  }

  Status Patch(const std::string& name,
               const std::map<std::string, std::string>& updates,
               const std::string& precondition_rv, bool create_if_missing,
               bool* conflict, bool* alive) override {
    *conflict = false;
    if (fail_transport) {
      *alive = alive_on_fail;
      return Status::Error("injected transport failure");
    }
    if (fail_patch) {
      // Writes bounce (429/brownout) while reads keep working — the
      // one-way degradation the asymmetric-partition tests exercise.
      *alive = true;
      return Status::Error("injected write throttle");
    }
    *alive = true;
    auto it = docs.find(name);
    if (create_if_missing) {
      // Pure create: a doc that appeared since the caller's GET is a
      // lost bootstrap race, never a merge target.
      if (it != docs.end()) {
        *conflict = true;
        return Status::Error("create conflict");
      }
      Doc doc;
      doc.rv = 1;
      doc.data = updates;
      docs[name] = doc;
      return Status::Ok();
    }
    if (it == docs.end()) return Status::Error("missing");
    if (!precondition_rv.empty() &&
        precondition_rv != std::to_string(it->second.rv)) {
      *conflict = true;
      return Status::Error("conflict");
    }
    for (const auto& [key, value] : updates) it->second.data[key] = value;
    it->second.rv++;
    return Status::Ok();
  }

  struct Doc {
    uint64_t rv = 0;
    std::map<std::string, std::string> data;
  };
  std::map<std::string, Doc> docs;
};

slice::SliceIdentity TwoHostIdentity() {
  slice::SliceIdentity identity;
  identity.valid = true;
  identity.slice_id = "unit-slice";
  identity.raw_name = "unit-slice";
  identity.num_hosts = 2;
  identity.worker_id = 0;
  return identity;
}

slice::MemberReport LocalReportFor(const std::string& host, bool healthy,
                                   double at) {
  slice::MemberReport r;
  r.host = host;
  r.healthy = healthy;
  r.reported_at = at;
  r.shape = "chips=4";
  return r;
}

void TestSliceLeaseStateMachine() {
  MemoryDocStore store;
  slice::CoordPolicy policy;
  policy.lease_duration_s = 10;
  policy.agreement_timeout_s = 5;

  slice::SliceIdentity id_a = TwoHostIdentity();
  slice::SliceIdentity id_b = TwoHostIdentity();
  id_b.worker_id = 1;
  slice::Coordinator a;
  slice::Coordinator b;
  a.Configure(id_a, "host-a", policy);
  b.Configure(id_b, "host-b", policy);

  // Bootstrap: first tick creates the blackboard and takes the lease.
  slice::Coordinator::TickResult ra =
      a.Tick(&store, LocalReportFor("host-a", true, 100), 100);
  CHECK_TRUE(ra.mode == slice::CoordMode::kLeader);
  CHECK_EQ(ra.labels[lm::kSliceHealthyHosts], std::string("1"));
  CHECK_EQ(ra.labels[lm::kSliceDegraded], std::string("true"));

  // Second host joins as a follower; its local healthy view is NOT
  // interleaved — it publishes the adopted (1/2) verdict verbatim.
  slice::Coordinator::TickResult rb =
      b.Tick(&store, LocalReportFor("host-b", true, 101), 101);
  CHECK_TRUE(rb.mode == slice::CoordMode::kFollower);
  CHECK_TRUE(rb.labels == ra.labels);

  // The leader's next tick counts host-b; the follower adopts the new
  // verdict: byte-identical on both.
  ra = a.Tick(&store, LocalReportFor("host-a", true, 102), 102);
  CHECK_EQ(ra.labels[lm::kSliceHealthyHosts], std::string("2"));
  CHECK_EQ(ra.labels[lm::kSliceDegraded], std::string("false"));
  rb = b.Tick(&store, LocalReportFor("host-b", true, 103), 103);
  CHECK_TRUE(rb.labels == ra.labels);

  // Leader death: host-a stops ticking; once the lease expires host-b
  // acquires it (epoch bump) and the verdict drops the stale member.
  rb = b.Tick(&store, LocalReportFor("host-b", true, 113), 113);
  CHECK_TRUE(rb.mode == slice::CoordMode::kLeader);
  CHECK_EQ(rb.labels[lm::kSliceHealthyHosts], std::string("1"));
  CHECK_EQ(rb.labels[lm::kSliceDegraded], std::string("true"));
  {
    Result<slice::Lease> lease =
        slice::ParseLease(store.docs[slice::CoordDocName("unit-slice")]
                              .data[slice::kLeaseKey]);
    CHECK_TRUE(lease.ok());
    CHECK_EQ(lease->holder, std::string("host-b"));
    CHECK_EQ(static_cast<int>(lease->epoch), 2);
  }

  // The old leader comes back: it sees the fresh lease, steps down to
  // follower, and adopts the new verdict — no split brain, no flap.
  ra = a.Tick(&store, LocalReportFor("host-a", true, 114), 114);
  CHECK_TRUE(ra.mode == slice::CoordMode::kFollower);
  // One more leader round counts host-a healthy again; both converge.
  rb = b.Tick(&store, LocalReportFor("host-b", true, 115), 115);
  CHECK_EQ(rb.labels[lm::kSliceHealthyHosts], std::string("2"));
  ra = a.Tick(&store, LocalReportFor("host-a", true, 116), 116);
  CHECK_TRUE(ra.labels == rb.labels);

  // Acquisition race: expire the lease, then two fresh coordinators
  // race — the rv precondition lets exactly one win.
  {
    MemoryDocStore race_store;
    slice::Coordinator c1;
    slice::Coordinator c2;
    c1.Configure(id_a, "host-a", policy);
    c2.Configure(id_b, "host-b", policy);
    c1.Tick(&race_store, LocalReportFor("host-a", true, 200), 200);
    c2.Tick(&race_store, LocalReportFor("host-b", true, 201), 201);
    // Both see the lease expired at t=300; c2 ticks first and wins.
    slice::Coordinator::TickResult r2 =
        c2.Tick(&race_store, LocalReportFor("host-b", true, 300), 300);
    CHECK_TRUE(r2.mode == slice::CoordMode::kLeader);
    slice::Coordinator::TickResult r1 =
        c1.Tick(&race_store, LocalReportFor("host-a", true, 300.5), 300.5);
    CHECK_TRUE(r1.mode == slice::CoordMode::kFollower ||
               r1.mode == slice::CoordMode::kLeader);
    Result<slice::Lease> lease = slice::ParseLease(
        race_store.docs[slice::CoordDocName("unit-slice")]
            .data[slice::kLeaseKey]);
    CHECK_TRUE(lease.ok());
  }
}

void TestSliceOrphanAndRejoin() {
  MemoryDocStore store;
  slice::CoordPolicy policy;
  policy.lease_duration_s = 10;
  policy.agreement_timeout_s = 5;
  slice::SliceIdentity id_b = TwoHostIdentity();
  id_b.worker_id = 1;
  slice::Coordinator a;
  slice::Coordinator b;
  a.Configure(TwoHostIdentity(), "host-a", policy);
  b.Configure(id_b, "host-b", policy);
  a.Tick(&store, LocalReportFor("host-a", true, 100), 100);
  b.Tick(&store, LocalReportFor("host-b", true, 100), 100);
  slice::Coordinator::TickResult rb =
      b.Tick(&store, LocalReportFor("host-b", true, 101), 101);
  CHECK_TRUE(!rb.labels.empty());

  // Partition host-b: within the grace window it keeps serving the
  // ADOPTED labels unchanged...
  store.fail_transport = true;
  rb = b.Tick(&store, LocalReportFor("host-b", true, 105), 105);
  CHECK_TRUE(rb.mode != slice::CoordMode::kOrphaned);
  CHECK_TRUE(!rb.labels.empty());
  // ...but past a lease duration it SELF-DEMOTES: empty labels, never
  // a stale slice view.
  rb = b.Tick(&store, LocalReportFor("host-b", true, 120), 120);
  CHECK_TRUE(rb.mode == slice::CoordMode::kOrphaned);
  CHECK_TRUE(rb.labels.empty());

  // A 429-paced apiserver is ALIVE: pacing never orphans.
  {
    MemoryDocStore paced;
    slice::Coordinator c;
    c.Configure(TwoHostIdentity(), "host-a", policy);
    c.Tick(&paced, LocalReportFor("host-a", true, 100), 100);
    paced.fail_transport = true;
    paced.alive_on_fail = true;  // server answered (throttle), no route loss
    slice::Coordinator::TickResult rc =
        c.Tick(&paced, LocalReportFor("host-a", true, 200), 200);
    CHECK_TRUE(rc.mode != slice::CoordMode::kOrphaned);
    CHECK_TRUE(!rc.labels.empty());
  }

  // Heal the partition: host-b re-joins and re-adopts the agreement.
  store.fail_transport = false;
  rb = b.Tick(&store, LocalReportFor("host-b", true, 130), 130);
  CHECK_TRUE(rb.mode != slice::CoordMode::kOrphaned);
  CHECK_TRUE(!rb.labels.empty());
}

void TestSliceCoordSerializeRestore() {
  MemoryDocStore store;
  slice::CoordPolicy policy;
  policy.lease_duration_s = 10;
  policy.agreement_timeout_s = 5;
  slice::Coordinator a;
  a.Configure(TwoHostIdentity(), "host-a", policy);
  a.Tick(&store, LocalReportFor("host-a", true, 100), 100);
  CHECK_TRUE(a.mode() == slice::CoordMode::kLeader);
  std::string json = a.SerializeJson(101);
  CHECK_TRUE(!json.empty());

  // kill -9 + restart: the restored coordinator resumes the SAME lease
  // epoch on its first tick — holder is still host-a and the lease is
  // still valid, so no epoch bump, no leadership flap.
  slice::Coordinator a2;
  CHECK_TRUE(a2.RestoreJson(json, 102).ok());
  a2.Configure(TwoHostIdentity(), "host-a", policy);
  slice::Coordinator::TickResult r =
      a2.Tick(&store, LocalReportFor("host-a", true, 103), 103);
  CHECK_TRUE(r.mode == slice::CoordMode::kLeader);
  {
    Result<slice::Lease> lease =
        slice::ParseLease(store.docs[slice::CoordDocName("unit-slice")]
                              .data[slice::kLeaseKey]);
    CHECK_TRUE(lease.ok());
    CHECK_EQ(static_cast<int>(lease->epoch), 1);  // resumed, not re-won
  }

  // Garbage is rejected without touching state.
  slice::Coordinator c;
  CHECK_TRUE(!c.RestoreJson("not json", 100).ok());
  CHECK_TRUE(!c.RestoreJson("{\"schema\":9}", 100).ok());
  CHECK_TRUE(c.RestoreJson("", 100).ok());  // nothing persisted: fine

  // A restored payload for a DIFFERENT slice is dropped at Configure:
  // leadership/verdict from a repurposed node must not leak in.
  slice::Coordinator d;
  CHECK_TRUE(d.RestoreJson(json, 102).ok());
  slice::SliceIdentity other = TwoHostIdentity();
  other.slice_id = "other-slice";
  d.Configure(other, "host-a", policy);
  MemoryDocStore fresh;
  slice::Coordinator::TickResult rd =
      d.Tick(&fresh, LocalReportFor("host-a", true, 103), 103);
  Result<slice::Lease> lease =
      slice::ParseLease(fresh.docs[slice::CoordDocName("other-slice")]
                            .data[slice::kLeaseKey]);
  CHECK_TRUE(lease.ok());
  CHECK_EQ(static_cast<int>(lease->epoch), 1);  // started clean
  CHECK_TRUE(rd.mode == slice::CoordMode::kLeader);

  // The state-file carry: slice_json rides PersistedState opaquely and
  // survives the frame round trip.
  sched::PersistedState state;
  state.node = "host-a";
  state.saved_at = 1000;
  state.labels["google.com/tpu.count"] = "4";
  state.slice_json = json;
  Result<sched::PersistedState> parsed =
      sched::ParseState(sched::SerializeState(state));
  CHECK_TRUE(parsed.ok());
  CHECK_EQ(parsed->slice_json.empty(), false);
  slice::Coordinator e;
  CHECK_TRUE(e.RestoreJson(parsed->slice_json, 1001).ok());
}

// Scripted peer-relay transport: addr -> canned /debug/slice-report
// body, plus a kill switch for "nothing of the member is reachable".
class FakePeerChannel : public slice::PeerChannel {
 public:
  std::map<std::string, std::string> responses;
  bool fail = false;
  int fetches = 0;

  Result<std::string> FetchReport(const std::string& addr) override {
    fetches++;
    if (fail) return Result<std::string>::Error("connection refused");
    auto it = responses.find(addr);
    if (it == responses.end()) {
      return Result<std::string>::Error("connection refused");
    }
    return it->second;
  }
};

slice::MemberReport AddrReportFor(const std::string& host, bool healthy,
                                  double at, const std::string& addr) {
  slice::MemberReport r = LocalReportFor(host, healthy, at);
  r.addr = addr;
  return r;
}

void TestSliceRelayConfirmOrRelay() {
  slice::CoordPolicy policy;
  policy.lease_duration_s = 10;
  policy.agreement_timeout_s = 5;
  policy.renew_cadence_s = 1;  // stale_after = max(5/2, 1*1.5) = 2.5s
  slice::SliceIdentity id_b = TwoHostIdentity();
  id_b.worker_id = 1;
  FakePeerChannel peers;
  MemoryDocStore store;
  slice::Coordinator a;
  slice::Coordinator b;
  a.Configure(TwoHostIdentity(), "host-a", policy);
  b.Configure(id_b, "host-b", policy);
  a.Tick(&store, LocalReportFor("host-a", true, 100), 100, &peers);
  b.Tick(&store, AddrReportFor("host-b", true, 100.5, "127.0.0.1:9901"),
         100.5);
  slice::Coordinator::TickResult ra =
      a.Tick(&store, LocalReportFor("host-a", true, 101), 101, &peers);
  CHECK_EQ(ra.labels[lm::kSliceHealthyHosts], std::string("2"));
  CHECK_EQ(peers.fetches, 0);  // fresh-reported members are never probed

  // host-b goes silent on the blackboard but stays reachable direct:
  // past stale_after the leader probes its addr, gets the live report,
  // and relays it (origin stamp verbatim, relayed_by marked) — the
  // slice holds 2/2 without waiting out the agreement ageing.
  peers.responses["127.0.0.1:9901"] = slice::SerializeReport(
      AddrReportFor("host-b", true, 103.6, "127.0.0.1:9901"));
  ra = a.Tick(&store, LocalReportFor("host-a", true, 104), 104, &peers);
  CHECK_TRUE(peers.fetches > 0);
  CHECK_EQ(ra.labels[lm::kSliceHealthyHosts], std::string("2"));
  {
    Result<slice::MemberReport> relayed = slice::ParseReport(
        store.docs[slice::CoordDocName("unit-slice")]
            .data[std::string(slice::kReportKeyPrefix) + "host-b"]);
    CHECK_TRUE(relayed.ok());
    CHECK_EQ(relayed->relayed_by, std::string("host-a"));
    CHECK_TRUE(relayed->reported_at == 103.6);  // never re-stamped
  }

  // A reachable peer with NOTHING fresher is NOT evicted: the live
  // copy renews at tick cadence and can tie the blackboard stamp, so
  // ordinary ageing stays the arbiter for reachable-but-silent.
  // (Regression pin: an equal-stamp answer once confirmed-stale'd
  // perfectly live members.)
  ra = a.Tick(&store, LocalReportFor("host-a", true, 106.5), 106.5,
              &peers);
  CHECK_EQ(ra.labels[lm::kSliceHealthyHosts], std::string("2"));

  // Confirm-or-relay: stale on the board AND unreachable direct is
  // confirmed-stale — excluded from this merge at ~3.9s of the 5s
  // ageing window, ahead of the timeout.
  peers.fail = true;
  ra = a.Tick(&store, LocalReportFor("host-a", true, 107.5), 107.5,
              &peers);
  CHECK_EQ(ra.labels[lm::kSliceHealthyHosts], std::string("1"));
  CHECK_EQ(ra.labels[lm::kSliceDegraded], std::string("true"));

  // Failed-probe cache: while host-b's board stamp hasn't moved, the
  // next tick re-confirms stale WITHOUT paying another probe (a frozen
  // peer's hung connect must not stall every tick).
  {
    const int before = peers.fetches;
    ra = a.Tick(&store, LocalReportFor("host-a", true, 108), 108, &peers);
    CHECK_EQ(peers.fetches, before);
    CHECK_EQ(ra.labels[lm::kSliceHealthyHosts], std::string("1"));
  }

  // host-b renews directly (stamp moves -> cache invalidated), then
  // goes silent again and answers GARBAGE: reachable-but-gibberish is
  // no liveness proof — same fast exclusion as no answer.
  peers.fail = false;
  b.Tick(&store, AddrReportFor("host-b", true, 108.5, "127.0.0.1:9901"),
         108.5);
  ra = a.Tick(&store, LocalReportFor("host-a", true, 109), 109, &peers);
  CHECK_EQ(ra.labels[lm::kSliceHealthyHosts], std::string("2"));
  peers.responses["127.0.0.1:9901"] = "not a report";
  {
    const int before = peers.fetches;
    ra = a.Tick(&store, LocalReportFor("host-a", true, 111.5), 111.5,
                &peers);
    CHECK_TRUE(peers.fetches > before);
    CHECK_EQ(ra.labels[lm::kSliceHealthyHosts], std::string("1"));
  }

  // The liveness lift (1-CPU collapse regression pin): host-b renews
  // at 115, then only ANSWERS probes with that same stamp. At t=120.5
  // the board copy is 5.5s old — past the agreement window — but the
  // equal-stamp answer proves it alive at probe time, so the merge
  // counts it. Nothing is written: the board keeps the origin's claim.
  b.Tick(&store, AddrReportFor("host-b", true, 115, "127.0.0.1:9901"),
         115);
  peers.responses["127.0.0.1:9901"] = slice::SerializeReport(
      AddrReportFor("host-b", true, 115, "127.0.0.1:9901"));
  ra = a.Tick(&store, LocalReportFor("host-a", true, 120.5), 120.5,
              &peers);
  CHECK_EQ(ra.labels[lm::kSliceHealthyHosts], std::string("2"));
  {
    Result<slice::MemberReport> board = slice::ParseReport(
        store.docs[slice::CoordDocName("unit-slice")]
            .data[std::string(slice::kReportKeyPrefix) + "host-b"]);
    CHECK_TRUE(board.ok());
    CHECK_TRUE(board->reported_at == 115);  // lift never re-stamps
  }

  // Cooldown expiry: a failure cached at t=123 suppresses re-probes
  // (t=124), but past 2x the agreement window the host gets another
  // chance — and a live answer resurrects it however old the board
  // copy is.
  peers.fail = true;
  ra = a.Tick(&store, LocalReportFor("host-a", true, 123), 123, &peers);
  CHECK_EQ(ra.labels[lm::kSliceHealthyHosts], std::string("1"));
  {
    const int before = peers.fetches;
    ra = a.Tick(&store, LocalReportFor("host-a", true, 124), 124, &peers);
    CHECK_EQ(peers.fetches, before);
  }
  peers.fail = false;
  peers.responses["127.0.0.1:9901"] = slice::SerializeReport(
      AddrReportFor("host-b", true, 133.5, "127.0.0.1:9901"));
  ra = a.Tick(&store, LocalReportFor("host-a", true, 134), 134, &peers);
  CHECK_EQ(ra.labels[lm::kSliceHealthyHosts], std::string("2"));

  // With relay off the same silence just ages out normally — and no
  // probe is ever attempted.
  {
    slice::CoordPolicy off = policy;
    off.relay = false;
    MemoryDocStore store2;
    FakePeerChannel peers2;
    slice::Coordinator a2;
    slice::Coordinator b2;
    a2.Configure(TwoHostIdentity(), "host-a", off);
    b2.Configure(id_b, "host-b", off);
    a2.Tick(&store2, LocalReportFor("host-a", true, 100), 100, &peers2);
    b2.Tick(&store2,
            AddrReportFor("host-b", true, 100.5, "127.0.0.1:9901"),
            100.5);
    slice::Coordinator::TickResult r2 =
        a2.Tick(&store2, LocalReportFor("host-a", true, 104), 104,
                &peers2);
    CHECK_EQ(peers2.fetches, 0);
    CHECK_EQ(r2.labels[lm::kSliceHealthyHosts], std::string("2"));
  }
}

// A peer-relay transport that parks in FetchReport until released —
// the shape of a probe against a frozen (SIGSTOPped) member whose TCP
// backlog accepts the connect but never answers.
class BlockingPeerChannel : public slice::PeerChannel {
 public:
  Result<std::string> FetchReport(const std::string&) override {
    blocked.store(true);
    std::unique_lock<std::mutex> lock(mu);
    cv.wait(lock, [this] { return released; });
    return Result<std::string>::Error("connection timed out");
  }
  void Release() {
    {
      std::lock_guard<std::mutex> lock(mu);
      released = true;
    }
    cv.notify_all();
  }
  std::atomic<bool> blocked{false};
  std::mutex mu;
  std::condition_variable cv;
  bool released = false;
};

// The probe-serving surface must be wait-free with respect to the tick:
// Tick() holds the coordinator lock across blackboard I/O and peer
// probes (seconds under a partition), and a peer's FetchReport of THIS
// host lands on LocalReportJson() from the introspection thread. If
// that read waited out the tick, the prober would time out and
// confirm-stale a perfectly live member — the exact cascade a partial
// partition triggers when every member is probing the same frozen host.
void TestSliceReportSurfaceWaitFreeUnderTick() {
  slice::CoordPolicy policy;
  policy.lease_duration_s = 10;
  policy.agreement_timeout_s = 5;
  policy.renew_cadence_s = 1;
  slice::SliceIdentity id_b = TwoHostIdentity();
  id_b.worker_id = 1;
  MemoryDocStore store;
  BlockingPeerChannel peers;
  slice::Coordinator a;
  slice::Coordinator b;
  a.Configure(TwoHostIdentity(), "host-a", policy);
  b.Configure(id_b, "host-b", policy);
  a.Tick(&store, LocalReportFor("host-a", true, 100), 100);
  b.Tick(&store, AddrReportFor("host-b", true, 100, "127.0.0.1:9901"),
         100);

  // host-b's board report is now stale at t=104: host-a's tick probes
  // it and parks inside the channel, holding the tick lock.
  std::thread ticker([&] {
    a.Tick(&store, LocalReportFor("host-a", true, 104), 104, &peers);
  });
  while (!peers.blocked.load()) {
    std::this_thread::yield();
  }
  // The report surface answers NOW, mid-tick, with the stash from the
  // blocked tick itself (stashed before any I/O).
  std::string served = a.LocalReportJson();
  CHECK_TRUE(!served.empty());
  Result<slice::MemberReport> parsed = slice::ParseReport(served);
  CHECK_TRUE(parsed.ok());
  CHECK_EQ(parsed->host, std::string("host-a"));
  CHECK_TRUE(parsed->reported_at == 104.0);
  peers.Release();
  ticker.join();
}

void TestSliceSuccession() {
  slice::CoordPolicy policy;
  policy.lease_duration_s = 10;   // cadence = 10/3 = 3; missed_after 4
  policy.agreement_timeout_s = 5;
  slice::SliceIdentity id_b = TwoHostIdentity();
  id_b.worker_id = 1;
  MemoryDocStore store;
  slice::Coordinator a;
  slice::Coordinator b;
  a.Configure(TwoHostIdentity(), "host-a", policy);
  b.Configure(id_b, "host-b", policy);
  a.Tick(&store, LocalReportFor("host-a", true, 100), 100);
  b.Tick(&store, LocalReportFor("host-b", true, 100.5), 100.5);
  a.Tick(&store, LocalReportFor("host-a", true, 101), 101);
  {
    // The verdict pre-declares the line of succession: every healthy
    // member except the leader, sorted.
    Result<slice::SliceVerdict> v = slice::ParseVerdict(
        store.docs[slice::CoordDocName("unit-slice")]
            .data[slice::kVerdictKey]);
    CHECK_TRUE(v.ok());
    CHECK_EQ(static_cast<int>(v->successors.size()), 1);
    CHECK_EQ(v->successors[0], std::string("host-b"));
  }

  // host-a dies after renewing at t=101 (lease runs to 111). Within
  // the missed-renewal threshold the follower keeps following...
  slice::Coordinator::TickResult rb =
      b.Tick(&store, LocalReportFor("host-b", true, 104), 104);
  CHECK_TRUE(rb.mode == slice::CoordMode::kFollower);
  // ...but at renewal age 5.5 (> cadence + cadence/2 = 4, lease NOT
  // yet expired) the first-listed successor promotes, epoch-fenced.
  rb = b.Tick(&store, LocalReportFor("host-b", true, 106.5), 106.5);
  CHECK_TRUE(rb.mode == slice::CoordMode::kLeader);
  CHECK_EQ(rb.labels[lm::kSliceHealthyHosts], std::string("1"));
  {
    Result<slice::Lease> lease =
        slice::ParseLease(store.docs[slice::CoordDocName("unit-slice")]
                              .data[slice::kLeaseKey]);
    CHECK_TRUE(lease.ok());
    CHECK_EQ(lease->holder, std::string("host-b"));
    CHECK_EQ(static_cast<int>(lease->epoch), 2);
  }

  // Succession off: the same timeline waits for full lease expiry.
  {
    slice::CoordPolicy off = policy;
    off.succession = false;
    MemoryDocStore store2;
    slice::Coordinator a2;
    slice::Coordinator b2;
    a2.Configure(TwoHostIdentity(), "host-a", off);
    b2.Configure(id_b, "host-b", off);
    a2.Tick(&store2, LocalReportFor("host-a", true, 100), 100);
    b2.Tick(&store2, LocalReportFor("host-b", true, 100.5), 100.5);
    a2.Tick(&store2, LocalReportFor("host-a", true, 101), 101);
    slice::Coordinator::TickResult r2 =
        b2.Tick(&store2, LocalReportFor("host-b", true, 106.5), 106.5);
    CHECK_TRUE(r2.mode == slice::CoordMode::kFollower);  // lease valid
    r2 = b2.Tick(&store2, LocalReportFor("host-b", true, 111.5), 111.5);
    CHECK_TRUE(r2.mode == slice::CoordMode::kLeader);  // expiry backstop
  }

  // A dead first successor is skipped: promotion needs a FRESH report,
  // so the first LIVE name in the sorted line takes the lease.
  {
    slice::SliceIdentity id3 = TwoHostIdentity();
    id3.num_hosts = 3;
    slice::SliceIdentity id3b = id3;
    id3b.worker_id = 1;
    slice::SliceIdentity id3c = id3;
    id3c.worker_id = 2;
    MemoryDocStore store3;
    slice::Coordinator a3;
    slice::Coordinator b3;
    slice::Coordinator c3;
    a3.Configure(id3, "host-a", policy);
    b3.Configure(id3b, "host-b", policy);
    c3.Configure(id3c, "host-c", policy);
    a3.Tick(&store3, LocalReportFor("host-a", true, 100), 100);
    b3.Tick(&store3, LocalReportFor("host-b", true, 100.2), 100.2);
    c3.Tick(&store3, LocalReportFor("host-c", true, 100.4), 100.4);
    a3.Tick(&store3, LocalReportFor("host-a", true, 101), 101);
    // host-a AND host-b die. At t=106 host-b's report is 5.8s stale:
    // ineligible — host-c, second in line, succeeds.
    slice::Coordinator::TickResult r3 =
        c3.Tick(&store3, LocalReportFor("host-c", true, 106), 106);
    CHECK_TRUE(r3.mode == slice::CoordMode::kLeader);
    Result<slice::Lease> lease =
        slice::ParseLease(store3.docs[slice::CoordDocName("unit-slice")]
                              .data[slice::kLeaseKey]);
    CHECK_TRUE(lease.ok());
    CHECK_EQ(lease->holder, std::string("host-c"));
    CHECK_EQ(static_cast<int>(lease->epoch), 2);
  }
}

void TestSliceAsymmetricPartition() {
  slice::CoordPolicy policy;
  policy.lease_duration_s = 10;
  policy.agreement_timeout_s = 5;
  policy.renew_cadence_s = 1;
  slice::SliceIdentity id_b = TwoHostIdentity();
  id_b.worker_id = 1;

  // Blackboard-but-not-peers: every direct probe would fail, but the
  // reports are FRESH — fresh members are never probed, so a flaky
  // fetch path cannot evict (or demote) a live member.
  {
    MemoryDocStore store;
    FakePeerChannel broken;
    broken.fail = true;
    slice::Coordinator a;
    slice::Coordinator b;
    a.Configure(TwoHostIdentity(), "host-a", policy);
    b.Configure(id_b, "host-b", policy);
    a.Tick(&store, LocalReportFor("host-a", true, 100), 100, &broken);
    b.Tick(&store, AddrReportFor("host-b", true, 100.5, "127.0.0.1:1"),
           100.5);
    slice::Coordinator::TickResult ra =
        a.Tick(&store, LocalReportFor("host-a", true, 101), 101, &broken);
    CHECK_EQ(broken.fetches, 0);
    CHECK_EQ(ra.labels[lm::kSliceHealthyHosts], std::string("2"));
    slice::Coordinator::TickResult rb =
        b.Tick(&store, AddrReportFor("host-b", true, 101.5, "127.0.0.1:1"),
               101.5);
    CHECK_TRUE(rb.mode == slice::CoordMode::kFollower);  // no demotion
    CHECK_TRUE(rb.labels == ra.labels);
  }

  // Peers-but-not-blackboard: the fleet keeps the severed member
  // counted THROUGH the relay, while the member itself — whose only
  // liveness is that relayed copy — still self-demotes at the lease:
  // a relayed report is a peer vouching for it, never its own
  // blackboard contact.
  {
    MemoryDocStore store;
    MemoryDocStore dead;
    dead.fail_transport = true;
    FakePeerChannel peers;
    slice::Coordinator a;
    slice::Coordinator b;
    a.Configure(TwoHostIdentity(), "host-a", policy);
    b.Configure(id_b, "host-b", policy);
    a.Tick(&store, LocalReportFor("host-a", true, 100), 100, &peers);
    b.Tick(&store, AddrReportFor("host-b", true, 100.5, "127.0.0.1:2"),
           100.5);
    a.Tick(&store, LocalReportFor("host-a", true, 101), 101, &peers);
    // host-b loses the apiserver; its peers still reach it.
    peers.responses["127.0.0.1:2"] = slice::SerializeReport(
        AddrReportFor("host-b", true, 103.6, "127.0.0.1:2"));
    slice::Coordinator::TickResult ra =
        a.Tick(&store, LocalReportFor("host-a", true, 104), 104, &peers);
    CHECK_EQ(ra.labels[lm::kSliceHealthyHosts], std::string("2"));
    peers.responses["127.0.0.1:2"] = slice::SerializeReport(
        AddrReportFor("host-b", true, 111.6, "127.0.0.1:2"));
    ra = a.Tick(&store, LocalReportFor("host-a", true, 112), 112, &peers);
    CHECK_EQ(ra.labels[lm::kSliceHealthyHosts], std::string("2"));
    // host-b itself, cut off past the lease duration, self-demotes —
    // even though a FRESH relayed copy of its own report sits on the
    // blackboard the whole time.
    slice::Coordinator::TickResult rb = b.Tick(
        &dead, AddrReportFor("host-b", true, 112.5, "127.0.0.1:2"),
        112.5);
    CHECK_TRUE(rb.mode == slice::CoordMode::kOrphaned);
    CHECK_TRUE(rb.labels.empty());
  }

  // Reads fine, writes throttled (brownout): blackboard CONTACT is
  // what anchors self-trust, so the member never self-demotes — it
  // keeps publishing the adopted verdict while its own report goes
  // stale on the board (where the peers' relay keeps the fleet from
  // degrading it in the meantime).
  {
    MemoryDocStore store;
    slice::Coordinator a;
    slice::Coordinator b;
    a.Configure(TwoHostIdentity(), "host-a", policy);
    b.Configure(id_b, "host-b", policy);
    a.Tick(&store, LocalReportFor("host-a", true, 100), 100);
    b.Tick(&store, LocalReportFor("host-b", true, 100.5), 100.5);
    a.Tick(&store, LocalReportFor("host-a", true, 101), 101);
    slice::Coordinator::TickResult rb =
        b.Tick(&store, LocalReportFor("host-b", true, 101.5), 101.5);
    lm::Labels adopted = rb.labels;
    CHECK_TRUE(!adopted.empty());
    store.fail_patch = true;
    for (double t = 102.5; t < 118; t += 1.0) {
      rb = b.Tick(&store, LocalReportFor("host-b", true, t), t);
      CHECK_TRUE(rb.mode != slice::CoordMode::kOrphaned);
      CHECK_TRUE(rb.labels == adopted);
    }
  }
}

void TestSliceHedgedPublish() {
  slice::CoordPolicy policy;
  policy.lease_duration_s = 10;
  policy.agreement_timeout_s = 5;
  policy.renew_cadence_s = 1;
  slice::SliceIdentity id_b = TwoHostIdentity();
  id_b.worker_id = 1;
  MemoryDocStore store;
  FakePeerChannel peers;
  slice::Coordinator a;
  slice::Coordinator b;
  a.Configure(TwoHostIdentity(), "host-a", policy);
  b.Configure(id_b, "host-b", policy);
  a.Tick(&store, LocalReportFor("host-a", true, 100), 100, &peers);
  b.Tick(&store, AddrReportFor("host-b", true, 100.5, "127.0.0.1:3"),
         100.5);
  slice::Coordinator::TickResult ra =
      a.Tick(&store, LocalReportFor("host-a", true, 101), 101, &peers);
  CHECK_TRUE(ra.hedges.empty());  // nobody severed

  // host-b's report now arrives only by relay: the leader proxies its
  // agreed publish — the hedged labels ARE the leader's own bytes.
  peers.responses["127.0.0.1:3"] = slice::SerializeReport(
      AddrReportFor("host-b", true, 103.6, "127.0.0.1:3"));
  ra = a.Tick(&store, LocalReportFor("host-a", true, 104), 104, &peers);
  CHECK_EQ(static_cast<int>(ra.hedges.size()), 1);
  CHECK_EQ(ra.hedges[0].host, std::string("host-b"));
  CHECK_TRUE(ra.hedges[0].labels == ra.labels);

  // Same verdict, still severed: coalesced — one hedge per (host,
  // verdict seq), deferred hedges never queue.
  peers.responses["127.0.0.1:3"] = slice::SerializeReport(
      AddrReportFor("host-b", true, 105.6, "127.0.0.1:3"));
  ra = a.Tick(&store, LocalReportFor("host-a", true, 106.2), 106.2,
              &peers);
  CHECK_TRUE(ra.hedges.empty());

  // The verdict MOVES while host-b is severed (it reports unhealthy
  // through the relay): the new content is hedged exactly once.
  peers.responses["127.0.0.1:3"] = slice::SerializeReport(
      AddrReportFor("host-b", false, 107.6, "127.0.0.1:3"));
  ra = a.Tick(&store, LocalReportFor("host-a", true, 108.2), 108.2,
              &peers);
  CHECK_EQ(static_cast<int>(ra.hedges.size()), 1);
  CHECK_EQ(ra.hedges[0].labels.at(lm::kSliceHealthyHosts),
           std::string("1"));

  // Heal: host-b writes its own report again — the hedge entry sheds,
  // so a FUTURE severance hedges afresh.
  b.Tick(&store, AddrReportFor("host-b", true, 109, "127.0.0.1:3"), 109);
  ra = a.Tick(&store, LocalReportFor("host-a", true, 109.5), 109.5,
              &peers);
  CHECK_TRUE(ra.hedges.empty());  // own report: not severed
  peers.responses["127.0.0.1:3"] = slice::SerializeReport(
      AddrReportFor("host-b", true, 111.6, "127.0.0.1:3"));
  ra = a.Tick(&store, LocalReportFor("host-a", true, 112.1), 112.1,
              &peers);
  CHECK_EQ(static_cast<int>(ra.hedges.size()), 1);  // re-severed

  // --sink-hedge=false: relay still keeps the member counted, but the
  // leader never writes on its behalf.
  {
    slice::CoordPolicy off = policy;
    off.hedge = false;
    MemoryDocStore store2;
    FakePeerChannel peers2;
    slice::Coordinator a2;
    slice::Coordinator b2;
    a2.Configure(TwoHostIdentity(), "host-a", off);
    b2.Configure(id_b, "host-b", off);
    a2.Tick(&store2, LocalReportFor("host-a", true, 100), 100, &peers2);
    b2.Tick(&store2, AddrReportFor("host-b", true, 100.5, "127.0.0.1:4"),
            100.5);
    peers2.responses["127.0.0.1:4"] = slice::SerializeReport(
        AddrReportFor("host-b", true, 103.6, "127.0.0.1:4"));
    slice::Coordinator::TickResult r2 =
        a2.Tick(&store2, LocalReportFor("host-a", true, 104), 104,
                &peers2);
    CHECK_EQ(r2.labels[lm::kSliceHealthyHosts], std::string("2"));
    CHECK_TRUE(r2.hedges.empty());
  }
}

void TestGovernorSliceKeys() {
  // The verdict keys are exempt from per-key hold-down (cross-host
  // coherence contract; anti-flap lives in the verdict protocol +
  // healthsm on the slice source)...
  CHECK_TRUE(!lm::GovernedKey(lm::kSliceId));
  CHECK_TRUE(!lm::GovernedKey(lm::kSliceHealthyHosts));
  CHECK_TRUE(!lm::GovernedKey(lm::kSliceDegraded));
  // ...except the class, which is governed like tpu.perf.class.
  CHECK_TRUE(lm::GovernedKey(lm::kSliceClass));
  // tpu.slice.hosts stays key-governed (the topology labeler publishes
  // it too; key-waiving it would tear it from its governed siblings) —
  // but changes whose provenance names the slice-coord labeler carry
  // the cross-host contract and bypass the hold-down.
  CHECK_TRUE(lm::GovernedKey(lm::kSliceHosts));
  {
    lm::GovernorPolicy policy;
    policy.hold_down_s = 300;
    policy.churn_budget = 6;
    lm::LabelGovernor governor(policy);
    lm::Labels previous = {{lm::kSliceHosts, "4"}};
    lm::LabelProvenance topo_prov;
    topo_prov.labeler = "tpu";
    lm::LabelProvenance coord_prov;
    coord_prov.labeler = lm::kSliceCoordLabeler;
    lm::Provenance prev_topo = {{lm::kSliceHosts, topo_prov}};
    lm::Provenance prev_coord = {{lm::kSliceHosts, coord_prov}};
    governor.NotePublished(previous, 1000);
    // A TOPOLOGY-owned value change inside the hold-down is
    // suppressed...
    lm::Labels candidate = {{lm::kSliceHosts, "2"}};
    lm::Provenance provenance = {{lm::kSliceHosts, topo_prov}};
    std::vector<lm::SuppressedFlip> suppressed;
    governor.Apply(previous, prev_topo, false, 1001, &candidate,
                   &provenance, &suppressed);
    CHECK_EQ(suppressed.size(), 1u);
    CHECK_EQ(candidate[lm::kSliceHosts], std::string("4"));
    // ...a coordination-owned REMOVAL (orphan self-demotion, judged by
    // the previously published value's provenance) passes...
    lm::Labels demoted;
    lm::Provenance demoted_prov;
    std::vector<lm::SuppressedFlip> suppressed2;
    governor.Apply(previous, prev_coord, false, 1002, &demoted,
                   &demoted_prov, &suppressed2);
    CHECK_EQ(suppressed2.size(), 0u);
    CHECK_EQ(demoted.count(lm::kSliceHosts), 0u);
    // ...and so does a coordination-owned re-addition/change.
    lm::Labels readded = {{lm::kSliceHosts, "4"}};
    lm::Provenance readd_prov = {{lm::kSliceHosts, coord_prov}};
    std::vector<lm::SuppressedFlip> suppressed3;
    governor.Apply(previous, prev_coord, false, 1003, &readded,
                   &readd_prov, &suppressed3);
    lm::Labels changed = {{lm::kSliceHosts, "8"}};
    lm::Provenance changed_prov = {{lm::kSliceHosts, coord_prov}};
    std::vector<lm::SuppressedFlip> suppressed4;
    governor.Apply(previous, prev_coord, false, 1004, &changed,
                   &changed_prov, &suppressed4);
    CHECK_EQ(suppressed4.size(), 0u);
    CHECK_EQ(changed[lm::kSliceHosts], std::string("8"));
  }

  lm::GovernorPolicy policy;
  policy.hold_down_s = 300;
  policy.churn_budget = 6;
  lm::LabelGovernor governor(policy);

  lm::Labels previous = {{lm::kSliceClass, "gold"},
                         {lm::kSliceDegraded, "false"},
                         {lm::kSliceHealthyHosts, "4"}};
  lm::Provenance prev_prov;
  governor.NotePublished(previous, 1000);
  // Burn the class key's hold-down with a recent change.
  {
    lm::Labels candidate = previous;
    candidate[lm::kSliceClass] = "silver";
    lm::Provenance provenance;
    std::vector<lm::SuppressedFlip> suppressed;
    governor.Apply(previous, prev_prov, false, 1001, &candidate,
                   &provenance, &suppressed);
    governor.CommitPublished();
    CHECK_EQ(suppressed.size(), 0u);  // first flip passes (budget)
    previous = candidate;
  }
  // A DEMOTION inside the hold-down window bypasses (conservative
  // direction, already debounced at the members + leader)...
  {
    lm::Labels candidate = previous;
    candidate[lm::kSliceClass] = "degraded";
    candidate[lm::kSliceDegraded] = "true";
    candidate[lm::kSliceHealthyHosts] = "3";
    lm::Provenance provenance;
    std::vector<lm::SuppressedFlip> suppressed;
    governor.Apply(previous, prev_prov, false, 1002, &candidate,
                   &provenance, &suppressed);
    governor.CommitPublished();
    CHECK_EQ(suppressed.size(), 0u);
    CHECK_EQ(candidate[lm::kSliceClass], std::string("degraded"));
    // The exempt verdict keys moved freely with it: coherent, all at
    // once.
    CHECK_EQ(candidate[lm::kSliceDegraded], std::string("true"));
    CHECK_EQ(candidate[lm::kSliceHealthyHosts], std::string("3"));
    previous = candidate;
  }
  // ...but a PROMOTION inside the window is governed (held down).
  {
    lm::Labels candidate = previous;
    candidate[lm::kSliceClass] = "gold";
    lm::Provenance provenance;
    std::vector<lm::SuppressedFlip> suppressed;
    governor.Apply(previous, prev_prov, false, 1003, &candidate,
                   &provenance, &suppressed);
    CHECK_EQ(suppressed.size(), 1u);
    CHECK_EQ(candidate[lm::kSliceClass], std::string("degraded"));
  }
}

// ---- probe-plugin SDK (plugin/plugin.h) -----------------------------------

void TestPluginHandshakeGrid() {
  // This grid is the cross-language parity pin: tests/test_plugin.py
  // runs the SAME documents through tpufd/plugin.py — change one side,
  // change both.
  {
    Result<plugin::Handshake> hs = plugin::ParseHandshake(
        R"({"contract": "tfd.probe/v1", "name": "libtpu-caps",
            "label_prefix": "google.com/tpu.plugin.libtpu.",
            "interval_s": 300, "deadline_s": 20})");
    CHECK_TRUE(hs.ok());
    CHECK_EQ(hs->name, std::string("libtpu-caps"));
    CHECK_EQ(hs->label_prefix,
             std::string("google.com/tpu.plugin.libtpu."));
    CHECK_EQ(hs->interval_s, 300);
    CHECK_EQ(hs->deadline_s, 20);
  }
  // Hints optional; the health-port plugin legitimately declares the
  // first-party tpu.health. namespace.
  {
    Result<plugin::Handshake> hs = plugin::ParseHandshake(
        R"({"contract": "tfd.probe/v1", "name": "device-health",
            "label_prefix": "google.com/tpu.health."})");
    CHECK_TRUE(hs.ok());
    CHECK_EQ(hs->interval_s, 0);
    CHECK_EQ(hs->deadline_s, 0);
  }
  // The forward-compat contract: an unknown version is a DISTINCT,
  // loud rejection naming both versions — never parse garbage.
  {
    Result<plugin::Handshake> hs = plugin::ParseHandshake(
        R"({"contract": "tfd.probe/v2", "name": "future",
            "label_prefix": "google.com/tpu.plugin.future."})");
    CHECK_TRUE(!hs.ok());
    CHECK_TRUE(hs.error().find("unknown contract version") !=
               std::string::npos);
    CHECK_TRUE(hs.error().find("tfd.probe/v2") != std::string::npos);
    CHECK_TRUE(hs.error().find("tfd.probe/v1") != std::string::npos);
  }
  // Missing contract is the same rejection (empty version named).
  CHECK_TRUE(!plugin::ParseHandshake(
                  R"({"name": "x", "label_prefix": "google.com/x."})")
                  .ok());
  // Garbage / non-object / oversize.
  CHECK_TRUE(!plugin::ParseHandshake("not json").ok());
  CHECK_TRUE(!plugin::ParseHandshake("[1,2]").ok());
  CHECK_TRUE(!plugin::ParseHandshake(
                  std::string(plugin::kMaxHandshakeBytes + 1, ' '))
                  .ok());
  // Name rules: charset, length, alnum ends.
  for (const char* bad : {"", "Upper", "has_underscore", "-lead",
                          "trail-", "waaaaaaaaaaaaaaaaaaaaaaaaaay-"
                                    "too-long-plugin-name"}) {
    std::string doc = std::string(R"({"contract": "tfd.probe/v1",
        "name": ")") + bad +
        R"(", "label_prefix": "google.com/tpu.plugin.x."})";
    CHECK_TRUE(!plugin::ParseHandshake(doc).ok());
  }
  // Prefix rules: domain, trailing dot, key-char validity, length.
  for (const char* bad :
       {"", "nvidia.com/gpu.", "google.com/", "google.com/tpu.plugin.x",
        "google.com/bad prefix.", "google.com/-lead."}) {
    std::string doc = std::string(R"({"contract": "tfd.probe/v1",
        "name": "x", "label_prefix": ")") + bad + R"("})";
    CHECK_TRUE(!plugin::ParseHandshake(doc).ok());
  }
  // Hint bounds.
  CHECK_TRUE(!plugin::ParseHandshake(
                  R"({"contract": "tfd.probe/v1", "name": "x",
          "label_prefix": "google.com/tpu.plugin.x.",
          "interval_s": 86401})")
                  .ok());
  CHECK_TRUE(!plugin::ParseHandshake(
                  R"({"contract": "tfd.probe/v1", "name": "x",
          "label_prefix": "google.com/tpu.plugin.x.",
          "deadline_s": -1})")
                  .ok());
}

void TestPluginRoundValidationGrid() {
  plugin::Handshake hs;
  hs.contract = plugin::kContractV1;
  hs.name = "x";
  hs.label_prefix = "google.com/tpu.plugin.x.";

  // A clean round: labels under the prefix + free-form facts.
  {
    plugin::RoundOutput out;
    Status s = plugin::ParseRoundOutput(
        R"({"labels": {"google.com/tpu.plugin.x.ok": "true",
                       "google.com/tpu.plugin.x.version": "1.2.3"},
            "facts": {"free": "form", "n": "2"}})",
        hs, 32, &out);
    CHECK_TRUE(s.ok());
    CHECK_EQ(out.labels.size(), 2u);
    CHECK_EQ(out.labels["google.com/tpu.plugin.x.ok"],
             std::string("true"));
    CHECK_EQ(out.facts, 2);
    CHECK_EQ(out.violations.size(), 0u);
  }
  // Facts-only round: legal, empty label set.
  {
    plugin::RoundOutput out;
    CHECK_TRUE(plugin::ParseRoundOutput(R"({"facts": {"a": "b"}})", hs,
                                        32, &out)
                   .ok());
    CHECK_EQ(out.labels.size(), 0u);
  }
  // Garbage: rejected whole.
  {
    plugin::RoundOutput out;
    CHECK_TRUE(
        !plugin::ParseRoundOutput("}{ not json", hs, 32, &out).ok());
    CHECK_EQ(out.violations.size(), 1u);
    CHECK_EQ(out.violations[0].kind, std::string("garbage"));
  }
  // Oversize: rejected whole before parsing.
  {
    plugin::RoundOutput out;
    CHECK_TRUE(!plugin::ParseRoundOutput(
                    std::string(plugin::kMaxRoundOutputBytes + 1, 'x'),
                    hs, 32, &out)
                    .ok());
    CHECK_EQ(out.violations[0].kind, std::string("oversize"));
  }
  // Label budget: the RAW count is gated (padding with droppable keys
  // must not sneak a spammer under the budget), round rejected WHOLE.
  {
    plugin::RoundOutput out;
    Status s = plugin::ParseRoundOutput(
        R"({"labels": {"google.com/tpu.plugin.x.a": "1",
                       "google.com/tpu.plugin.x.b": "2",
                       "google.com/evil.escape": "3"}})",
        hs, 2, &out);
    CHECK_TRUE(!s.ok());
    CHECK_EQ(out.violations[0].kind, std::string("label-budget"));
    CHECK_EQ(out.labels.size(), 0u);
  }
  // Namespace escape: the offending keys are DROPPED (and named), the
  // round's valid labels still publish.
  {
    plugin::RoundOutput out;
    Status s = plugin::ParseRoundOutput(
        R"({"labels": {"google.com/tpu.plugin.x.good": "1",
                       "google.com/tpu.perf.class": "gold",
                       "google.com/tpu.plugin.other.key": "2"}})",
        hs, 32, &out);
    CHECK_TRUE(s.ok());
    CHECK_EQ(out.labels.size(), 1u);
    CHECK_EQ(out.labels.count("google.com/tpu.plugin.x.good"), 1u);
    CHECK_EQ(out.violations.size(), 2u);
    CHECK_EQ(out.violations[0].kind, std::string("namespace"));
    CHECK_EQ(out.violations[1].kind, std::string("namespace"));
  }
  // Key/value strictness: invalid suffix chars, bare-prefix key,
  // non-string values, unsalvageable values — each its own kind.
  {
    plugin::RoundOutput out;
    Status s = plugin::ParseRoundOutput(
        R"({"labels": {"google.com/tpu.plugin.x.bad key": "1",
                       "google.com/tpu.plugin.x.": "bare",
                       "google.com/tpu.plugin.x.num": 7,
                       "google.com/tpu.plugin.x.val": "@@@",
                       "google.com/tpu.plugin.x.ok": "fine value"}})",
        hs, 32, &out);
    CHECK_TRUE(s.ok());
    CHECK_EQ(out.labels.size(), 1u);
    // StrictLabelValue: spaces become dashes.
    CHECK_EQ(out.labels["google.com/tpu.plugin.x.ok"],
             std::string("fine-value"));
    CHECK_EQ(out.violations.size(), 4u);
  }
  // Hostile bytes: ill-formed UTF-8 is sanitized before parsing, so a
  // byte-garbage doc classifies as garbage instead of crashing.
  {
    plugin::RoundOutput out;
    CHECK_TRUE(
        !plugin::ParseRoundOutput("\xff\xfe{]", hs, 32, &out).ok());
    CHECK_EQ(out.violations[0].kind, std::string("garbage"));
  }
}

void TestPluginConfAndSchedule() {
  // Conf stanza grid (twin-pinned).
  {
    Result<plugin::PluginConf> conf = plugin::ParsePluginConf(
        "# operator stanza\nenabled = true\ninterval = 5m\n"
        "deadline = 45s\n");
    CHECK_TRUE(conf.ok());
    CHECK_TRUE(conf->enabled);
    CHECK_EQ(conf->interval_s, 300);
    CHECK_EQ(conf->deadline_s, 45);
  }
  {
    Result<plugin::PluginConf> conf =
        plugin::ParsePluginConf("enabled=false\n");
    CHECK_TRUE(conf.ok());
    CHECK_TRUE(!conf->enabled);
  }
  CHECK_TRUE(plugin::ParsePluginConf("").ok());  // absent == defaults
  CHECK_TRUE(!plugin::ParsePluginConf("nonsense\n").ok());
  CHECK_TRUE(!plugin::ParsePluginConf("interval = soon\n").ok());
  CHECK_TRUE(!plugin::ParsePluginConf("color = red\n").ok());

  // The hint trust rule: a plugin can make itself CHEAPER, never
  // hotter. Deadline hints only lower; interval hints only slow.
  plugin::Handshake hs;
  plugin::PluginConf conf;
  hs.deadline_s = 5;
  CHECK_EQ(plugin::EffectiveDeadlineS(hs, conf, 30), 5);   // lower ok
  hs.deadline_s = 120;
  CHECK_EQ(plugin::EffectiveDeadlineS(hs, conf, 30), 30);  // raise capped
  hs.deadline_s = 0;
  CHECK_EQ(plugin::EffectiveDeadlineS(hs, conf, 30), 30);  // default
  conf.deadline_s = 120;  // the operator's stanza is trusted
  hs.deadline_s = 0;
  CHECK_EQ(plugin::EffectiveDeadlineS(hs, conf, 30), 120);
  hs.deadline_s = 600;  // ...and still caps the plugin's own hint
  CHECK_EQ(plugin::EffectiveDeadlineS(hs, conf, 30), 120);

  hs = plugin::Handshake();
  conf = plugin::PluginConf();
  hs.interval_s = 3600;
  CHECK_EQ(plugin::EffectiveIntervalS(hs, conf, 60), 3600);  // slower ok
  hs.interval_s = 1;
  CHECK_EQ(plugin::EffectiveIntervalS(hs, conf, 60), 60);    // faster capped
  conf.interval_s = 10;  // operator may quicken...
  CHECK_EQ(plugin::EffectiveIntervalS(hs, conf, 60), 10);
  hs.interval_s = 86400;  // ...even below the plugin's own slow hint
  conf.interval_s = 300;
  CHECK_EQ(plugin::EffectiveIntervalS(hs, conf, 60), 300);
}

// Writes an executable plugin script; returns its path.
std::string WritePluginScript(const std::string& dir,
                              const std::string& file,
                              const std::string& body) {
  std::string path = dir + "/" + file;
  std::ofstream out(path);
  out << "#!/bin/sh\n" << body;
  out.close();
  chmod(path.c_str(), 0755);
  return path;
}

void TestPluginDiscovery() {
  std::string dir = "/tmp/tfd-unit-plugin-" + std::to_string(getpid());
  mkdir(dir.c_str(), 0755);
  config::Flags flags;
  flags.plugin_dir = dir;
  flags.plugin_timeout_s = 5;
  flags.sleep_interval_s = 7;
  flags.plugin_label_budget = 9;

  // A good plugin, an unknown-contract plugin (rejected loudly AT
  // DISCOVERY), a name duplicate, a prefix overlap, a disabled one,
  // and a non-executable bystander.
  WritePluginScript(dir, "aaa-good",
                    "if [ \"$TFD_PLUGIN_OP\" = handshake ]; then\n"
                    "  echo '{\"contract\": \"tfd.probe/v1\", \"name\":"
                    " \"good\", \"label_prefix\":"
                    " \"google.com/tpu.plugin.good.\","
                    " \"interval_s\": 120, \"deadline_s\": 2}'\n"
                    "fi\n");
  WritePluginScript(dir, "bbb-future",
                    "echo '{\"contract\": \"tfd.probe/v2\", \"name\":"
                    " \"future\", \"label_prefix\":"
                    " \"google.com/tpu.plugin.future.\"}'\n");
  WritePluginScript(dir, "ccc-dup",
                    "echo '{\"contract\": \"tfd.probe/v1\", \"name\":"
                    " \"good\", \"label_prefix\":"
                    " \"google.com/tpu.plugin.dup.\"}'\n");
  WritePluginScript(dir, "ddd-overlap",
                    "echo '{\"contract\": \"tfd.probe/v1\", \"name\":"
                    " \"overlap\", \"label_prefix\":"
                    " \"google.com/tpu.plugin.good.sub.\"}'\n");
  WritePluginScript(dir, "eee-disabled",
                    "echo '{\"contract\": \"tfd.probe/v1\", \"name\":"
                    " \"disabled\", \"label_prefix\":"
                    " \"google.com/tpu.plugin.disabled.\"}'\n");
  {
    std::ofstream conf(dir + "/eee-disabled.conf");
    conf << "enabled = false\n";
  }
  {
    std::ofstream plain(dir + "/README.txt");  // not executable: skipped
    plain << "not a plugin\n";
  }

  std::vector<plugin::DiscoveredPlugin> found =
      plugin::DiscoverPlugins(flags);
  CHECK_EQ(found.size(), 1u);
  CHECK_EQ(found[0].handshake.name, std::string("good"));
  // Hints applied through the trust rule: deadline 2 < timeout 5,
  // interval 120 > sleep default 7; the budget rides along.
  CHECK_EQ(found[0].deadline_s, 2);
  CHECK_EQ(found[0].interval_s, 120);
  CHECK_EQ(found[0].label_budget, 9);

  // A missing plugin dir reports an error and discovers nothing.
  config::Flags missing = flags;
  missing.plugin_dir = dir + "/nonexistent";
  std::string error;
  CHECK_EQ(plugin::DiscoverPlugins(missing, &error).size(), 0u);
  CHECK_TRUE(!error.empty());

  std::string cleanup = "rm -rf " + dir;
  CHECK_TRUE(system(cleanup.c_str()) == 0);
}

void TestPluginRoundContainment() {
  std::string dir = "/tmp/tfd-unit-plugin-round-" + std::to_string(getpid());
  mkdir(dir.c_str(), 0755);
  healthsm::Default().Reset();

  plugin::DiscoveredPlugin p;
  p.handshake.contract = plugin::kContractV1;
  p.handshake.name = "drill";
  p.handshake.label_prefix = "google.com/tpu.plugin.drill.";
  p.deadline_s = 1;
  p.interval_s = 60;
  p.label_budget = 4;

  // Clean round: validated labels land, chip count rides the env.
  p.path = WritePluginScript(
      dir, "clean",
      "echo \"{\\\"labels\\\": {\\\"google.com/tpu.plugin.drill.chips\\\""
      ": \\\"$TFD_CHIP_COUNT\\\"}}\"\n");
  {
    lm::Labels labels;
    Status s = plugin::RunPluginRound(p, 4, &labels);
    CHECK_TRUE(s.ok());
    CHECK_EQ(labels["google.com/tpu.plugin.drill.chips"],
             std::string("4"));
  }
  // Crash rounds: non-zero exit fails the round (twice — a loop).
  p.path = WritePluginScript(dir, "crash", "exit 3\n");
  {
    lm::Labels labels;
    CHECK_TRUE(!plugin::RunPluginRound(p, -1, &labels).ok());
    CHECK_TRUE(!plugin::RunPluginRound(p, -1, &labels).ok());
  }
  // Garbage round: rejected whole.
  p.path = WritePluginScript(dir, "garbage", "echo 'not json at all'\n");
  {
    lm::Labels labels;
    CHECK_TRUE(!plugin::RunPluginRound(p, -1, &labels).ok());
  }
  // Hang: killed at the 1s deadline — the containment headline. The
  // grandchild (`sleep 30 &` would outlive a naive kill) dies with the
  // process group; the round fails promptly instead of wedging.
  p.path = WritePluginScript(dir, "hang", "sleep 30\n");
  {
    auto t0 = std::chrono::steady_clock::now();
    lm::Labels labels;
    CHECK_TRUE(!plugin::RunPluginRound(p, -1, &labels).ok());
    CHECK_TRUE(obs::SecondsSince(t0) < 5.0);
  }
  // Namespace escape: offenders dropped, valid labels kept, round ok.
  p.path = WritePluginScript(
      dir, "escape",
      "echo '{\"labels\": {\"google.com/tpu.plugin.drill.ok\": \"true\","
      " \"google.com/tpu.product\": \"spoofed\"}}'\n");
  {
    lm::Labels labels;
    CHECK_TRUE(plugin::RunPluginRound(p, -1, &labels).ok());
    CHECK_EQ(labels.size(), 1u);
    CHECK_EQ(labels.count("google.com/tpu.product"), 0u);
  }
  // Label spam: over-budget round rejected whole.
  p.path = WritePluginScript(
      dir, "spam",
      "echo '{\"labels\": {\"google.com/tpu.plugin.drill.a\": \"1\","
      " \"google.com/tpu.plugin.drill.b\": \"2\","
      " \"google.com/tpu.plugin.drill.c\": \"3\","
      " \"google.com/tpu.plugin.drill.d\": \"4\","
      " \"google.com/tpu.plugin.drill.e\": \"5\"}}'\n");
  {
    lm::Labels labels;
    CHECK_TRUE(!plugin::RunPluginRound(p, -1, &labels).ok());
    CHECK_EQ(labels.size(), 0u);
  }
  // The failed/violating rounds above each fed NoteFlapEvidence: with
  // the default threshold (6) the drill source is now quarantined —
  // crash loops and contract violations EARN quarantine even though
  // the state machine alone would park in unhealthy.
  CHECK_TRUE(healthsm::Default().Quarantined(
      std::string(plugin::kSourcePrefix) + "drill", WallClockSeconds()));

  healthsm::Default().Reset();
  std::string cleanup = "rm -rf " + dir;
  CHECK_TRUE(system(cleanup.c_str()) == 0);
}

void TestHealthsmFlapEvidence() {
  healthsm::Policy policy;
  policy.flap_window_s = 100;
  policy.flap_threshold = 3;
  policy.quarantine_cooldown_s = 50;
  healthsm::HealthTracker tracker(policy);

  // Evidence alone quarantines at the threshold — no state transitions
  // needed (the crash-loop case: Observe() would sit in unhealthy).
  CHECK_TRUE(tracker.NoteFlapEvidence("plugin.x", "crash", 10) !=
             healthsm::State::kQuarantined);
  CHECK_TRUE(tracker.NoteFlapEvidence("plugin.x", "crash", 11) !=
             healthsm::State::kQuarantined);
  CHECK_TRUE(tracker.NoteFlapEvidence("plugin.x", "crash", 12) ==
             healthsm::State::kQuarantined);
  CHECK_TRUE(tracker.Quarantined("plugin.x", 12));

  // Evidence outside the window does not accumulate.
  CHECK_TRUE(tracker.NoteFlapEvidence("plugin.y", "crash", 10) !=
             healthsm::State::kQuarantined);
  CHECK_TRUE(tracker.NoteFlapEvidence("plugin.y", "crash", 200) !=
             healthsm::State::kQuarantined);
  CHECK_TRUE(tracker.NoteFlapEvidence("plugin.y", "crash", 300) !=
             healthsm::State::kQuarantined);
  CHECK_TRUE(!tracker.Quarantined("plugin.y", 300));

  // Evidence composes with Observe()'s own transition flaps: one
  // failure (healthy->suspect = 1 flap) + two evidence rounds = 3.
  CHECK_TRUE(tracker.Observe("plugin.z", false, 0, 400) ==
             healthsm::State::kSuspect);
  tracker.NoteFlapEvidence("plugin.z", "violation", 401);
  CHECK_TRUE(tracker.NoteFlapEvidence("plugin.z", "violation", 402) ==
             healthsm::State::kQuarantined);

  // Recovery from evidence-quarantine is EARNED the normal way:
  // cooldown, then recover_after consecutive cleans.
  double t = 12 + policy.quarantine_cooldown_s + 1;
  CHECK_TRUE(tracker.Observe("plugin.x", true, 7, t) ==
             healthsm::State::kRecovering);
  CHECK_TRUE(tracker.Observe("plugin.x", true, 7, t + 1) ==
             healthsm::State::kRecovering);
  CHECK_TRUE(tracker.Observe("plugin.x", true, 7, t + 2) ==
             healthsm::State::kHealthy);
}

void TestSliceRejoinDwell() {
  slice::SliceIdentity identity;
  identity.valid = true;
  identity.slice_id = "testslice";
  identity.num_hosts = 4;
  slice::CoordPolicy policy;
  policy.lease_duration_s = 10;
  policy.agreement_timeout_s = 5;
  policy.rejoin_dwell_s = 20;

  auto report = [](const std::string& host, bool healthy, double at) {
    slice::MemberReport r;
    r.host = host;
    r.healthy = healthy;
    r.reported_at = at;
    return r;
  };

  // Parity grid (tests/test_plugin.py — sic: rides the plugin PR —
  // mirrors it through tpufd/slicecoord.py merge_verdict).
  std::map<std::string, double> departed = {{"b", 95}};
  // b rejoined 5s ago (< dwell 20): present, counted a member, NOT
  // healthy, and named as dwelling.
  {
    std::vector<std::string> dwelling;
    slice::SliceVerdict v = slice::MergeVerdict(
        identity, "a",
        {report("a", true, 100), report("b", true, 100),
         report("c", true, 100), report("d", true, 100)},
        policy, 100, &departed, &dwelling);
    CHECK_EQ(v.healthy_hosts, 3);
    CHECK_TRUE(v.degraded);
    CHECK_EQ(static_cast<int>(v.members.size()), 4);
    CHECK_EQ(dwelling.size(), 1u);
    CHECK_EQ(dwelling[0], std::string("b"));
  }
  // Dwell served (now - departed >= 20): counted healthy again.
  {
    std::vector<std::string> dwelling;
    slice::SliceVerdict v = slice::MergeVerdict(
        identity, "a",
        {report("a", true, 116), report("b", true, 116),
         report("c", true, 116), report("d", true, 116)},
        policy, 116, &departed, &dwelling);
    CHECK_EQ(v.healthy_hosts, 4);
    CHECK_TRUE(!v.degraded);
    CHECK_EQ(dwelling.size(), 0u);
  }
  // An UNHEALTHY rejoiner is not double-counted (dwell only suppresses
  // healthy claims), and dwell off (0) is a no-op.
  {
    std::vector<std::string> dwelling;
    slice::SliceVerdict v = slice::MergeVerdict(
        identity, "a", {report("a", true, 100), report("b", false, 100)},
        policy, 100, &departed, &dwelling);
    CHECK_EQ(v.healthy_hosts, 1);
    CHECK_EQ(dwelling.size(), 0u);
  }
  {
    slice::CoordPolicy no_dwell = policy;
    no_dwell.rejoin_dwell_s = 0;
    slice::SliceVerdict v = slice::MergeVerdict(
        identity, "a", {report("a", true, 100), report("b", true, 100)},
        no_dwell, 100, &departed, nullptr);
    CHECK_EQ(v.healthy_hosts, 2);
  }

  // Lease-machine scenario: a crash-looping member cannot flap
  // healthy-hosts once per restart — the leader dwells.
  {
    MemoryDocStore store;
    slice::CoordPolicy live = policy;
    // A long lease keeps host-a the leader across the synthetic time
    // jumps: the scenario under test is the DWELL, not a failover.
    live.lease_duration_s = 60;
    live.agreement_timeout_s = 5;
    live.rejoin_dwell_s = 20;
    slice::SliceIdentity id_a = TwoHostIdentity();
    slice::SliceIdentity id_b = TwoHostIdentity();
    id_b.worker_id = 1;
    slice::Coordinator a;
    slice::Coordinator b;
    a.Configure(id_a, "host-a", live);
    b.Configure(id_b, "host-b", live);

    a.Tick(&store, LocalReportFor("host-a", true, 100), 100);
    b.Tick(&store, LocalReportFor("host-b", true, 101), 101);
    slice::Coordinator::TickResult r =
        a.Tick(&store, LocalReportFor("host-a", true, 102), 102);
    CHECK_EQ(r.labels[lm::kSliceHealthyHosts], std::string("2"));

    // host-b dies: its report ages out, the leader drops it.
    r = a.Tick(&store, LocalReportFor("host-a", true, 110), 110);
    CHECK_EQ(r.labels[lm::kSliceHealthyHosts], std::string("1"));

    // host-b crash-loops back: fresh healthy report, but the leader
    // DWELLS — healthy-hosts stays 1 (no flap per restart).
    b.Tick(&store, LocalReportFor("host-b", true, 112), 112);
    r = a.Tick(&store, LocalReportFor("host-a", true, 113), 113);
    CHECK_EQ(r.labels[lm::kSliceHealthyHosts], std::string("1"));

    // It dies AGAIN inside the dwell and returns: still 1 — the
    // departure clock refreshed, so the crash loop never re-counts.
    r = a.Tick(&store, LocalReportFor("host-a", true, 120), 120);
    CHECK_EQ(r.labels[lm::kSliceHealthyHosts], std::string("1"));
    b.Tick(&store, LocalReportFor("host-b", true, 122), 122);
    r = a.Tick(&store, LocalReportFor("host-a", true, 123), 123);
    CHECK_EQ(r.labels[lm::kSliceHealthyHosts], std::string("1"));

    // Now it stays up through the dwell (20s past its last absence at
    // 120): re-counted, exactly one upward transition.
    b.Tick(&store, LocalReportFor("host-b", true, 141), 141);
    r = a.Tick(&store, LocalReportFor("host-a", true, 142), 142);
    CHECK_EQ(r.labels[lm::kSliceHealthyHosts], std::string("2"));
    CHECK_EQ(r.labels[lm::kSliceDegraded], std::string("false"));
  }

  // The dwell clock survives a leader kill -9: departed_at rides
  // slice_json, so a restarted leader resumes mid-dwell instead of
  // re-counting the crash-looper on its first merge.
  {
    slice::Coordinator original;
    original.Configure(TwoHostIdentity(), "host-a", policy);
    MemoryDocStore store;
    original.Tick(&store, LocalReportFor("host-a", true, 100), 100);
    slice::Coordinator::TickResult r =
        original.Tick(&store, LocalReportFor("host-a", true, 102), 102);
    // Make host-b known then absent: simulate by writing its report
    // into the doc directly and ticking through fresh/stale.
    bool conflict = false;
    bool alive = false;
    slice::MemberReport rb = LocalReportFor("host-b", true, 103);
    store.Patch(slice::CoordDocName("unit-slice"),
                {{std::string(slice::kReportKeyPrefix) + "host-b",
                  slice::SerializeReport(rb)}},
                "", false, &conflict, &alive);
    r = original.Tick(&store, LocalReportFor("host-a", true, 104), 104);
    CHECK_EQ(r.labels[lm::kSliceHealthyHosts], std::string("2"));
    // b goes stale (departs), then rejoins at 115.
    r = original.Tick(&store, LocalReportFor("host-a", true, 112), 112);
    CHECK_EQ(r.labels[lm::kSliceHealthyHosts], std::string("1"));
    std::string saved = original.SerializeJson(112);
    CHECK_TRUE(saved.find("departed") != std::string::npos);

    slice::Coordinator resumed;
    CHECK_TRUE(resumed.RestoreJson(saved, 113).ok());
    resumed.Configure(TwoHostIdentity(), "host-a", policy);
    rb = LocalReportFor("host-b", true, 115);
    store.Patch(slice::CoordDocName("unit-slice"),
                {{std::string(slice::kReportKeyPrefix) + "host-b",
                  slice::SerializeReport(rb)}},
                "", false, &conflict, &alive);
    r = resumed.Tick(&store, LocalReportFor("host-a", true, 116), 116);
    // Mid-dwell (departed ~112, dwell 20): the restored leader still
    // refuses to re-count the rejoiner.
    CHECK_EQ(r.labels[lm::kSliceHealthyHosts], std::string("1"));
  }
}

// ---- event-driven core (ISSUE 12): SSA ladder, watch, wakeup mux ---------

// Chunk-encodes body parts for a Transfer-Encoding: chunked reply; part
// boundaries become chunk boundaries, so a multi-part body exercises
// the client's incremental de-chunker across reads.
std::string ChunkEncode(const std::vector<std::string>& parts) {
  std::string out;
  for (const std::string& part : parts) {
    char size[16];
    snprintf(size, sizeof(size), "%zx\r\n", part.size());
    out += size;
    out += part;
    out += "\r\n";
  }
  out += "0\r\n\r\n";
  return out;
}

void TestRequestStreamChunked() {
  // Three chunks, with an event line SPLIT across chunk boundaries: the
  // streaming client must reassemble exactly the bytes a buffered read
  // would have seen.
  ScriptedApiServer server({
      {200,
       ChunkEncode({"line-one\nli", "ne-two\nline", "-three\n"}),
       "Transfer-Encoding: chunked\r\n"},
  });
  std::string collected;
  int head_status = 0;
  http::StreamHandler handler;
  handler.on_response = [&](const http::Response& head) {
    head_status = head.status;
    return true;
  };
  handler.on_data = [&](const char* data, size_t len) {
    collected.append(data, len);
    return true;
  };
  http::RequestOptions options;
  Status s = http::RequestStream("GET", server.url() + "/stream", "",
                                 options, handler);
  CHECK_TRUE(s.ok());
  CHECK_EQ(head_status, 200);
  CHECK_EQ(collected, "line-one\nline-two\nline-three\n");

  // Aborting mid-stream from on_data is a clean stop, not an error.
  ScriptedApiServer abort_server({
      {200, ChunkEncode({"a\n", "b\n", "c\n"}),
       "Transfer-Encoding: chunked\r\n"},
  });
  int lines_seen = 0;
  http::StreamHandler aborting;
  aborting.on_response = [](const http::Response&) { return true; };
  aborting.on_data = [&](const char* data, size_t len) {
    (void)data;
    (void)len;
    return ++lines_seen < 1;
  };
  CHECK_TRUE(http::RequestStream("GET", abort_server.url() + "/s", "",
                                 options, aborting)
                 .ok());
  CHECK_EQ(lines_seen, 1);
}

void TestWatchEventParse() {
  // Grid pinned cross-language: tests/test_fleet.py parses the SAME
  // lines through tpufd.sink.parse_watch_event and must agree.
  k8s::WatchEvent added = k8s::ParseWatchEventLine(
      "{\"type\":\"ADDED\",\"object\":{\"metadata\":{\"resourceVersion\":"
      "\"5\"},\"spec\":{\"labels\":{\"google.com/tpu.count\":\"4\"}}}}");
  CHECK_TRUE(added.type == k8s::WatchEvent::Type::kAdded);
  CHECK_EQ(added.resource_version, "5");
  CHECK_TRUE(added.has_labels);
  CHECK_EQ(added.labels.at("google.com/tpu.count"), "4");

  k8s::WatchEvent modified = k8s::ParseWatchEventLine(
      "{\"type\":\"MODIFIED\",\"object\":{\"metadata\":{\"resourceVersion"
      "\":\"6\"},\"spec\":{\"labels\":{\"a\":\"1\",\"junk\":7}}}}");
  CHECK_TRUE(modified.type == k8s::WatchEvent::Type::kModified);
  CHECK_EQ(modified.resource_version, "6");
  // Non-string values read as absent (the client.cc ExtractSpecLabels
  // rule).
  CHECK_EQ(modified.labels.size(), static_cast<size_t>(1));

  k8s::WatchEvent deleted = k8s::ParseWatchEventLine(
      "{\"type\":\"DELETED\",\"object\":{\"metadata\":{\"resourceVersion\""
      ":\"7\"},\"spec\":{\"labels\":{}}}}");
  CHECK_TRUE(deleted.type == k8s::WatchEvent::Type::kDeleted);

  k8s::WatchEvent bookmark = k8s::ParseWatchEventLine(
      "{\"type\":\"BOOKMARK\",\"object\":{\"metadata\":{\"resourceVersion"
      "\":\"41\"}}}");
  CHECK_TRUE(bookmark.type == k8s::WatchEvent::Type::kBookmark);
  CHECK_EQ(bookmark.resource_version, "41");
  CHECK_TRUE(!bookmark.has_labels);

  k8s::WatchEvent gone = k8s::ParseWatchEventLine(
      "{\"type\":\"ERROR\",\"object\":{\"kind\":\"Status\",\"code\":410,"
      "\"message\":\"too old resource version\"}}");
  CHECK_TRUE(gone.type == k8s::WatchEvent::Type::kError);
  CHECK_EQ(gone.error_code, 410);

  // Hostile/unknown input degrades to kUnknown, never throws.
  CHECK_TRUE(k8s::ParseWatchEventLine("not json").type ==
             k8s::WatchEvent::Type::kUnknown);
  CHECK_TRUE(k8s::ParseWatchEventLine("{}").type ==
             k8s::WatchEvent::Type::kUnknown);
  CHECK_TRUE(k8s::ParseWatchEventLine(
                 "{\"type\":\"PATCHED\",\"object\":{}}")
                 .type == k8s::WatchEvent::Type::kUnknown);
  CHECK_TRUE(k8s::ParseWatchEventLine("{\"type\":\"ADDED\"}").type ==
             k8s::WatchEvent::Type::kAdded);
}

void TestSinkApplyLadder() {
  // Rung 1 — server-side apply: ONE self-contained PATCH of the full
  // desired object under ?fieldManager=tfd&force=true. No GET, ever.
  {
    ScriptedApiServer server({
        {200, "{\"metadata\":{\"resourceVersion\":\"3\"}}"},
        {200, "{\"metadata\":{\"resourceVersion\":\"4\"}}"},
    });
    k8s::ClusterConfig cluster = ScriptedCluster(server);
    cluster.use_apply = true;
    k8s::SinkState state;
    k8s::WriteOutcome outcome;
    lm::Labels labels{{"google.com/tpu.count", "4"}};
    bool transient = true;
    CHECK_TRUE(k8s::UpdateNodeFeature(cluster, labels, &transient, &state,
                                      &outcome)
                   .ok());
    CHECK_EQ(outcome.gets, 0);
    CHECK_EQ(outcome.applies, 1);
    CHECK_EQ(outcome.patches, 1);
    CHECK_EQ(outcome.puts, 0);
    CHECK_TRUE(state.known);
    CHECK_EQ(state.resource_version, "3");
    labels["google.com/tpu.count"] = "8";
    k8s::WriteOutcome second;
    CHECK_TRUE(k8s::UpdateNodeFeature(cluster, labels, &transient, &state,
                                      &second)
                   .ok());
    CHECK_EQ(second.gets, 0);
    CHECK_EQ(second.applies, 1);
    CHECK_EQ(server.exchanges().size(), static_cast<size_t>(2));
    const ScriptedApiServer::Exchange& first = server.exchanges()[0];
    CHECK_EQ(first.method, "PATCH");
    CHECK_TRUE(first.path.find("fieldManager=tfd") != std::string::npos);
    CHECK_TRUE(first.path.find("force=true") != std::string::npos);
    // The apply body is the FULL desired object (JSON is valid YAML),
    // including the NFD node-name attribution label.
    CHECK_TRUE(first.body.find("\"apiVersion\":\"nfd.k8s-sigs.io/"
                               "v1alpha1\"") != std::string::npos);
    CHECK_TRUE(first.body.find("\"google.com/tpu.count\":\"4\"") !=
               std::string::npos);
    CHECK_TRUE(first.body.find("nfd.node.kubernetes.io/node-name") !=
               std::string::npos);
  }

  // Rung 2 — apply rejected (415): demote to the merge-patch diff flow
  // in the SAME call, and REMEMBER per-process (the second write goes
  // straight to merge patch, no apply attempt).
  {
    ScriptedApiServer server({
        {415, "{}"},
        {200,
         "{\"metadata\":{\"name\":\"tfd-features-for-unit-node\","
         "\"resourceVersion\":\"5\",\"labels\":{"
         "\"nfd.node.kubernetes.io/node-name\":\"unit-node\"}},"
         "\"spec\":{\"labels\":{\"google.com/tpu.count\":\"2\"}}}"},
        {200, "{\"metadata\":{\"resourceVersion\":\"6\"}}"},
        {200, "{\"metadata\":{\"resourceVersion\":\"7\"}}"},
    });
    k8s::ClusterConfig cluster = ScriptedCluster(server);
    cluster.use_apply = true;
    k8s::SinkState state;
    k8s::WriteOutcome outcome;
    lm::Labels labels{{"google.com/tpu.count", "4"}};
    bool transient = true;
    CHECK_TRUE(k8s::UpdateNodeFeature(cluster, labels, &transient, &state,
                                      &outcome)
                   .ok());
    CHECK_TRUE(state.apply_unsupported);
    CHECK_EQ(outcome.applies, 1);
    CHECK_EQ(outcome.gets, 1);
    CHECK_EQ(outcome.patches, 2);  // the rejected apply + the merge patch
    labels["google.com/tpu.count"] = "8";
    k8s::WriteOutcome second;
    CHECK_TRUE(k8s::UpdateNodeFeature(cluster, labels, &transient, &state,
                                      &second)
                   .ok());
    CHECK_EQ(second.applies, 0);  // remembered: no more apply attempts
    CHECK_EQ(second.gets, 0);     // the diff flow's zero-GET dirty write
    CHECK_EQ(second.patches, 1);
    CHECK_EQ(server.exchanges().size(), static_cast<size_t>(4));
    CHECK_TRUE(server.exchanges()[0].path.find("fieldManager") !=
               std::string::npos);
    CHECK_EQ(server.exchanges()[1].method, "GET");
    CHECK_TRUE(server.exchanges()[2].path.find("fieldManager") ==
               std::string::npos);
    CHECK_TRUE(server.exchanges()[3].body.find("\"8\"") !=
               std::string::npos);
  }

  // Rung 3 — apply AND merge patch rejected: the reference GET+PUT
  // bottom rung. Foreign METADATA survives the PUT (mutate-fetched),
  // but foreign spec.labels are clobbered wholesale — the documented
  // tradeoff of losing SSA field ownership.
  {
    const char* foreign_cr =
        "{\"metadata\":{\"name\":\"tfd-features-for-unit-node\","
        "\"resourceVersion\":\"8\",\"labels\":{"
        "\"nfd.node.kubernetes.io/node-name\":\"unit-node\"},"
        "\"annotations\":{\"foreign/note\":\"keep-me\"}},"
        "\"spec\":{\"labels\":{\"foreign.io/label\":\"clobbered\"}}}";
    ScriptedApiServer server({
        {415, "{}"},           // apply rejected
        {200, foreign_cr},     // GET (merge-patch attempt's read)
        {415, "{}"},           // merge patch rejected too
        {200, foreign_cr},     // GET (PUT attempt's read)
        {200, "{\"metadata\":{\"resourceVersion\":\"9\"}}"},  // PUT
    });
    k8s::ClusterConfig cluster = ScriptedCluster(server);
    cluster.use_apply = true;
    k8s::SinkState state;
    k8s::WriteOutcome outcome;
    lm::Labels labels{{"google.com/tpu.count", "4"}};
    bool transient = true;
    CHECK_TRUE(k8s::UpdateNodeFeature(cluster, labels, &transient, &state,
                                      &outcome)
                   .ok());
    CHECK_TRUE(state.apply_unsupported);
    CHECK_TRUE(state.patch_unsupported);
    CHECK_EQ(outcome.puts, 1);
    CHECK_EQ(server.exchanges().size(), static_cast<size_t>(5));
    const std::string& put_body = server.exchanges()[4].body;
    CHECK_EQ(server.exchanges()[4].method, "PUT");
    // Foreign metadata survives; foreign spec.labels do not.
    CHECK_TRUE(put_body.find("keep-me") != std::string::npos);
    CHECK_TRUE(put_body.find("clobbered") == std::string::npos);
    CHECK_TRUE(put_body.find("\"google.com/tpu.count\":\"4\"") !=
               std::string::npos);
  }

  // Transient classification: a 500 on the apply is transient (the
  // breaker's food), a 403 is not.
  for (int status : {500, 403}) {
    ScriptedApiServer server({{status, "{}"}});
    k8s::ClusterConfig cluster = ScriptedCluster(server);
    cluster.use_apply = true;
    k8s::SinkState state;
    bool transient = (status == 403);  // primed opposite
    CHECK_TRUE(!k8s::UpdateNodeFeature(cluster,
                                       {{"google.com/tpu.count", "1"}},
                                       &transient, &state, nullptr)
                    .ok());
    CHECK_EQ(transient, status == 500);
  }
}

void TestWatcherResyncAndDrift() {
  // The watcher's whole contract against a scripted stream:
  //   list -> watch(events incl. a self-echo, foreign drift, 410) ->
  //   exactly ONE re-list -> re-watch (clean rotation) -> re-watch.
  std::string cr_listed =
      "{\"metadata\":{\"name\":\"tfd-features-for-unit-node\","
      "\"resourceVersion\":\"5\"},"
      "\"spec\":{\"labels\":{\"google.com/tpu.count\":\"4\"}}}";
  ScriptedApiServer server({
      {200, cr_listed},  // initial list
      {200,
       ChunkEncode({
           // Self-echo: OUR published key intact, a foreign manager's
           // key present — not drift under SSA ownership.
           "{\"type\":\"MODIFIED\",\"object\":{\"metadata\":{"
           "\"resourceVersion\":\"6\"},\"spec\":{\"labels\":{"
           "\"google.com/tpu.count\":\"4\",\"foreign.io/x\":\"1\"}}}}\n",
           // Foreign drift: our key MOVED.
           "{\"type\":\"MODIFIED\",\"object\":{\"metadata\":{"
           "\"resourceVersion\":\"7\"},\"spec\":{\"labels\":{"
           "\"google.com/tpu.count\":\"2\"}}}}\n",
           // Compaction: resync owed.
           "{\"type\":\"ERROR\",\"object\":{\"kind\":\"Status\","
           "\"code\":410}}\n",
       }),
       "Transfer-Encoding: chunked\r\n"},
      {200, cr_listed},  // the ONE re-list
      {200, ChunkEncode({"{\"type\":\"BOOKMARK\",\"object\":{\"metadata\""
                         ":{\"resourceVersion\":\"9\"}}}\n"}),
       "Transfer-Encoding: chunked\r\n"},  // clean rotation
  });
  k8s::ClusterConfig cluster = ScriptedCluster(server);
  std::atomic<int> drifts{0};
  std::atomic<int> healthy_flips{0};
  k8s::WatcherOptions options;
  options.timeout_s = 1;
  options.read_timeout_ms = 10000;
  k8s::NodeFeatureWatcher watcher(
      cluster, options,
      [](lm::Labels* out) {
        (*out)["google.com/tpu.count"] = "4";
        return true;
      },
      [&](const std::string& reason) {
        (void)reason;
        drifts.fetch_add(1);
      },
      [&](bool healthy) {
        if (healthy) healthy_flips.fetch_add(1);
      });
  watcher.Start();
  for (int i = 0; i < 100; i++) {
    if (watcher.relists() >= 2 && drifts.load() >= 1 &&
        watcher.sessions() >= 2) {
      break;
    }
    usleep(50 * 1000);
  }
  watcher.Stop();
  CHECK_EQ(drifts.load(), 1);  // the echo did NOT read as drift
  CHECK_EQ(watcher.relists(), static_cast<uint64_t>(2));  // 410 -> one
  CHECK_TRUE(watcher.sessions() >= 2);
  CHECK_TRUE(healthy_flips.load() >= 1);
  // Wire truth: GET, WATCH, GET, WATCH ... — the 410 cost exactly one
  // extra GET, and every watch carries watch=true + bookmarks.
  CHECK_EQ(server.exchanges()[0].method, "GET");
  CHECK_TRUE(server.exchanges()[1].path.find("watch=true") !=
             std::string::npos);
  CHECK_TRUE(server.exchanges()[1].path.find("allowWatchBookmarks=true") !=
             std::string::npos);
  CHECK_TRUE(server.exchanges()[1].path.find("resourceVersion=5") !=
             std::string::npos);
  CHECK_EQ(server.exchanges()[2].method, "GET");
  CHECK_TRUE(server.exchanges()[2].path.find("watch=true") ==
             std::string::npos);
}

void TestWakeupMux() {
  sigset_t mask;
  sigemptyset(&mask);
  sigaddset(&mask, SIGUSR2);
  sigprocmask(SIG_BLOCK, &mask, nullptr);
  sched::WakeupMux mux;
  CHECK_TRUE(mux.Init(mask).ok());
  CHECK_TRUE(mux.initialized());
  using Reason = sched::WakeupMux::Reason;

  // Pure timeout -> deadline reason.
  sched::WakeupMux::WakeResult wake = mux.Wait(0.02);
  CHECK_EQ(wake.reasons, static_cast<uint32_t>(Reason::kDeadline));

  // A notify BEFORE the wait is not lost (eventfd holds the byte).
  mux.Notify(Reason::kSnapshot);
  wake = mux.Wait(1.0);
  CHECK_TRUE(wake.reasons & static_cast<uint32_t>(Reason::kSnapshot));

  // Cross-thread notify wakes a parked wait; combined reasons merge.
  std::thread notifier([&mux] {
    usleep(20 * 1000);
    mux.Notify(Reason::kWatchDrift);
    mux.Notify(Reason::kSnapshot);
  });
  wake = mux.Wait(2.0);
  notifier.join();
  // (Both may land in one wake or two; drain the second if needed.)
  uint32_t seen = wake.reasons;
  if (!(seen & static_cast<uint32_t>(Reason::kSnapshot)) ||
      !(seen & static_cast<uint32_t>(Reason::kWatchDrift))) {
    seen |= mux.Wait(0.2).reasons;
  }
  CHECK_TRUE(seen & static_cast<uint32_t>(Reason::kWatchDrift));
  CHECK_TRUE(seen & static_cast<uint32_t>(Reason::kSnapshot));

  // inotify: modify, then ATOMIC-RENAME-OVER (the WriteFileAtomically
  // pattern every config rewrite uses), then modify the new inode — the
  // watch must survive the inode swap via the re-arm path.
  char dir_template[] = "/tmp/tfd-wakeup-XXXXXX";
  std::string dir = mkdtemp(dir_template);
  std::string config_path = dir + "/config.yaml";
  WriteFileAtomically(config_path, "a: 1\n");
  mux.WatchPath(config_path);
  {
    std::ofstream out(config_path, std::ios::app);
    out << "b: 2\n";
  }
  wake = mux.Wait(2.0);
  CHECK_TRUE(wake.reasons & static_cast<uint32_t>(Reason::kInotify));
  CHECK_TRUE(!wake.changed_paths.empty());
  CHECK_EQ(wake.changed_paths[0], config_path);
  WriteFileAtomically(config_path, "c: 3\n");  // rename-over
  wake = mux.Wait(2.0);
  CHECK_TRUE(wake.reasons & static_cast<uint32_t>(Reason::kInotify));
  mux.Wait(0.05);  // drain + re-arm the fresh inode
  {
    std::ofstream out(config_path, std::ios::app);
    out << "d: 4\n";
  }
  wake = mux.Wait(2.0);
  CHECK_TRUE(wake.reasons & static_cast<uint32_t>(Reason::kInotify));

  // A blocked signal surfaces through the signalfd with its number.
  raise(SIGUSR2);
  wake = mux.Wait(2.0);
  CHECK_TRUE(wake.reasons & static_cast<uint32_t>(Reason::kSignal));
  CHECK_EQ(wake.signal, SIGUSR2);

  unlink(config_path.c_str());
  rmdir(dir.c_str());
  sigprocmask(SIG_UNBLOCK, &mask, nullptr);
}

void TestSnapshotMovementNotify() {
  sched::SnapshotStore store;
  sched::TierPolicy policy;
  policy.fresh_for_s = 100;
  policy.usable_for_s = 200;
  store.Register("mock", policy, /*device_source=*/true);
  std::atomic<int> notifies{0};
  store.SetMovementCallback([&notifies] { notifies.fetch_add(1); });

  sched::Snapshot snap;
  snap.labels = {{"google.com/tpu.count", "4"}};
  store.PutOk("mock", snap);
  CHECK_EQ(notifies.load(), 1);  // first snapshot is movement

  // The quiet-daemon contract: an identical healthy re-probe is NOT
  // movement (generation bumps, callback does not fire).
  sched::Snapshot same;
  same.labels = {{"google.com/tpu.count", "4"}};
  store.PutOk("mock", same);
  CHECK_EQ(notifies.load(), 1);

  sched::Snapshot changed;
  changed.labels = {{"google.com/tpu.count", "2"}};
  store.PutOk("mock", changed);
  CHECK_EQ(notifies.load(), 2);  // content moved

  store.PutError("mock", "chips busy");
  CHECK_EQ(notifies.load(), 3);  // ok -> failing flips the signature
  store.PutError("mock", "chips busy again");
  CHECK_EQ(notifies.load(), 3);  // still-failing re-fail: no movement
  sched::Snapshot recovered;
  recovered.labels = {{"google.com/tpu.count", "2"}};
  store.PutOk("mock", recovered);
  CHECK_EQ(notifies.load(), 4);  // failing -> ok flips back

  store.InvalidateAll();
  CHECK_EQ(notifies.load(), 5);

  // Tier-boundary timer: a fresh snapshot's next change is the fresh
  // window's edge; an aged one reports the usable edge; expired = none.
  sched::Snapshot fresh;
  fresh.labels = {{"google.com/tpu.count", "2"}};
  store.PutOk("mock", fresh);
  double next = store.SecondsUntilTierChange();
  CHECK_TRUE(next > 95 && next <= 100);
  store.AgeForTest("mock", 150);
  next = store.SecondsUntilTierChange();
  CHECK_TRUE(next > 45 && next <= 50);
  store.AgeForTest("mock", 100);  // now past usable (age 250)
  CHECK_EQ(store.SecondsUntilTierChange(), -1.0);
}

// ---- cluster inventory aggregator (agg/, ISSUE 13) -----------------------

void TestAggSketchParity() {
  // The SAME grid is pinned in tests/test_agg.py against tpufd.agg —
  // bucket boundaries come from repeated IEEE-double multiplication,
  // so both languages must agree bit-for-bit.
  struct { double value; int bucket; } grid[] = {
      {0.0, 0},   {0.25, 0},  {0.5, 0},   {0.51, 1}, {1.0, 8},
      {10.0, 32}, {100.0, 56}, {197.0, 63}, {459.0, 72}, {819.0, 78},
      {1e6, 127},
  };
  for (const auto& row : grid) {
    CHECK_EQ(agg::SketchBucketIndex(row.value), row.bucket);
  }
  CHECK_EQ(Fixed3(agg::SketchBucketValue(0)), "0.500");
  CHECK_EQ(Fixed3(agg::SketchBucketValue(1)), "0.550");
  CHECK_EQ(Fixed3(agg::SketchBucketValue(10)), "1.297");
  CHECK_EQ(Fixed3(agg::SketchBucketValue(50)), "58.695");
  CHECK_EQ(Fixed3(agg::SketchBucketValue(127)), "90331.874");

  agg::QuantileSketch sketch;
  CHECK_EQ(sketch.Quantile(0.5), -1.0);  // empty
  for (int i = 1; i <= 100; i++) {
    sketch.Add(static_cast<double>(i * 7 % 97 + 3));
  }
  CHECK_EQ(Fixed3(sketch.Quantile(0.10)), "11.613");
  CHECK_EQ(Fixed3(sketch.Quantile(0.50)), "53.359");
  CHECK_EQ(Fixed3(sketch.Quantile(0.90)), "94.530");

  // Removable: retiring every value empties it; removing from an empty
  // bucket is clamped, never negative.
  agg::QuantileSketch small;
  small.Add(10.0);
  small.Add(20.0);
  small.Remove(10.0);
  small.Remove(10.0);  // already gone: clamped
  CHECK_EQ(small.count(), 1);
  CHECK_EQ(Fixed3(small.Quantile(0.5)), Fixed3(agg::SketchBucketValue(
                                            agg::SketchBucketIndex(20.0))));
  // Mergeable: merge == adding both streams.
  agg::QuantileSketch a, b, both;
  for (int i = 0; i < 50; i++) {
    a.Add(i + 1.0);
    both.Add(i + 1.0);
  }
  for (int i = 50; i < 100; i++) {
    b.Add(i + 1.0);
    both.Add(i + 1.0);
  }
  a.Merge(b);
  CHECK_TRUE(a == both);
  // Unmergeable: retiring a merged sketch restores the other stream —
  // the per-node retire -> republish -> aggregator-unmerge loop the
  // windowed SLO view rides on. Same pins in tests/test_agg.py.
  both.Unmerge(b);
  agg::QuantileSketch a_alone;
  for (int i = 0; i < 50; i++) a_alone.Add(i + 1.0);
  CHECK_TRUE(both == a_alone);

  // FractionAbove: the burn evaluator's over-budget mass. Pinned in
  // tests/test_agg.py with the same values.
  agg::QuantileSketch over;
  over.Add(10.0);
  over.Add(20.0);
  over.Add(3000.0);
  over.Add(3000.0);
  CHECK_EQ(Fixed3(over.FractionAbove(1200.0)), "0.500");
  CHECK_EQ(Fixed3(over.FractionAbove(5.0)), "1.000");
  CHECK_EQ(Fixed3(over.FractionAbove(1e9)), "0.000");
  CHECK_EQ(Fixed3(agg::QuantileSketch().FractionAbove(1.0)), "0.000");

  // AddBucketCount (the deserialization primitive): out-of-range
  // buckets and non-positive counts are ignored, never fatal.
  agg::QuantileSketch direct;
  direct.AddBucketCount(5, 3);
  direct.AddBucketCount(-1, 2);
  direct.AddBucketCount(agg::kSketchBuckets, 2);
  direct.AddBucketCount(4, 0);
  direct.AddBucketCount(4, -7);
  CHECK_EQ(direct.count(), 3);
  CHECK_EQ(direct.bucket_counts()[5], 3);
}

void TestSloSerializationParity() {
  // The annotation encoding (SerializeStageSketches): kSloStages
  // order, empty sketches skipped, sparse ascending bucket:count. The
  // SAME goldens are pinned in tests/test_agg.py.
  agg::StageSketches stages;
  stages["plan"].Add(100.25);
  stages["plan"].Add(0.0);
  stages["publish"].Add(2900.0);
  std::string text = agg::SerializeStageSketches(stages);
  CHECK_EQ(text, "plan=0:1,56:1;publish=91:1");
  // Round trip: parse -> serialize reproduces the bytes, and the
  // sketches match bucket-for-bucket.
  agg::StageSketches parsed = agg::ParseStageSketches(text);
  CHECK_EQ(agg::SerializeStageSketches(parsed), text);
  CHECK_TRUE(parsed["plan"] == stages["plan"]);
  CHECK_TRUE(parsed["publish"] == stages["publish"]);
  CHECK_EQ(agg::SerializeStageSketches({}), "");

  // Tolerant parse: the annotation arrives from arbitrary nodes —
  // unknown stages and malformed tokens skip, never throw. Pins match
  // tests/test_agg.py.
  agg::StageSketches junk = agg::ParseStageSketches("junk=1:2;plan=5:3");
  CHECK_EQ(junk.size(), size_t{1});
  CHECK_EQ(junk["plan"].bucket_counts()[5], 3);
  agg::StageSketches partial =
      agg::ParseStageSketches("plan=abc:1,8:2,:,9");
  CHECK_EQ(partial["plan"].count(), 2);
  CHECK_EQ(partial["plan"].bucket_counts()[8], 2);
  CHECK_TRUE(agg::ParseStageSketches("plan=").empty());
  CHECK_TRUE(agg::ParseStageSketches("").empty());
  CHECK_TRUE(agg::ParseStageSketches(";;").empty());
  // A repeated stage accumulates (merge semantics, not last-wins).
  agg::StageSketches twice = agg::ParseStageSketches("plan=0:1;plan=1:1");
  CHECK_EQ(twice["plan"].count(), 2);
}

void TestSloBudgetsFromSpec() {
  // The default table is DERIVED from the cluster protocol budgets
  // (scripts/bench_gate.py CLUSTER_STAGE_BUDGETS_MS: hold=1200,
  // fanout=100): plan/publish = hold, render = fanout, publish-acked =
  // hold+fanout. bench_gate --slo cross-checks the same derivation.
  std::map<std::string, double> defaults = agg::DefaultSloBudgetsMs();
  CHECK_EQ(Fixed3(defaults["plan"]), "1200.000");
  CHECK_EQ(Fixed3(defaults["render"]), "100.000");
  CHECK_EQ(Fixed3(defaults["publish"]), "1200.000");
  CHECK_EQ(Fixed3(defaults["publish-acked"]), "1300.000");
  CHECK_EQ(defaults.size(), size_t{4});
  // Operator overrides (TFD_SLO_BUDGETS_MS): unknown stages and
  // malformed numbers are ignored; "" = the defaults. Same grid in
  // tests/test_agg.py.
  std::map<std::string, double> tuned = agg::SloBudgetsMsFromSpec(
      "publish=2500,junk=5,render=nope,plan=90");
  CHECK_EQ(Fixed3(tuned["publish"]), "2500.000");
  CHECK_EQ(Fixed3(tuned["plan"]), "90.000");
  CHECK_EQ(Fixed3(tuned["render"]), "100.000");
  CHECK_EQ(Fixed3(tuned["publish-acked"]), "1300.000");
  CHECK_EQ(tuned.size(), size_t{4});
  CHECK_TRUE(agg::SloBudgetsMsFromSpec("") == defaults);
}

void TestBurnEvaluatorParity() {
  // The multi-window burn scenario, scripted on an injected clock: a
  // sketch whose mass sits far over the publish budget asserts on the
  // first tick (fast mean 1.0, slow mean 1.0); replacing it with a
  // healthy sketch clears once the fast window drains. The SAME script
  // runs in tests/test_agg.py — edge times must match exactly.
  agg::BurnEvaluator burn(agg::SloBudgetsMsFromSpec(""),
                          /*fast_window_s=*/10.0, /*slow_window_s=*/40.0);
  agg::StageSketches hot;
  for (int i = 0; i < 4; i++) hot["publish"].Add(3000.0);
  std::vector<std::pair<double, bool>> edges;  // (t, burning)
  for (int t = 0; t < 50; t += 5) {
    for (const agg::BurnEvaluator::Edge& e :
         burn.Note(static_cast<double>(t), hot)) {
      CHECK_EQ(e.stage, "publish");
      edges.emplace_back(static_cast<double>(t), e.burning);
    }
  }
  CHECK_EQ(edges.size(), size_t{1});
  CHECK_EQ(edges[0].first, 0.0);
  CHECK_TRUE(edges[0].second);
  CHECK_TRUE(burn.burning("publish"));
  CHECK_EQ(burn.BurningStages().size(), size_t{1});

  agg::StageSketches cool;
  for (int i = 0; i < 20; i++) cool["publish"].Add(10.0);
  for (int t = 50; t < 90; t += 5) {
    for (const agg::BurnEvaluator::Edge& e :
         burn.Note(static_cast<double>(t), cool)) {
      CHECK_TRUE(!e.burning);
      edges.emplace_back(static_cast<double>(t), e.burning);
    }
  }
  CHECK_EQ(edges.size(), size_t{2});
  CHECK_EQ(edges[1].first, 55.0);  // two clean fast-window ticks
  CHECK_TRUE(!burn.burning("publish"));
  CHECK_TRUE(burn.BurningStages().empty());
  // A never-seen stage stays untracked (no spurious clear edges).
  CHECK_TRUE(!burn.burning("plan"));
}

void TestAggIncrementalRollups() {
  // The SAME 6-node fleet and golden label set are pinned in
  // tests/test_agg.py.
  std::map<std::string, lm::Labels> fleet = {
      {"n0",
       {{lm::kSliceId, "s-a"}, {lm::kSliceDegraded, "false"},
        {lm::kPerfClass, "gold"}, {"google.com/tpu.count", "4"},
        {lm::kPerfMatmulTflops, "180.5"}, {lm::kPerfHbmGbps, "700"}}},
      {"n1",
       {{lm::kSliceId, "s-a"}, {lm::kSliceDegraded, "false"},
        {lm::kPerfClass, "silver"}, {"google.com/tpu.count", "4"},
        {lm::kPerfMatmulTflops, "150.25"}, {lm::kPerfHbmGbps, "650"}}},
      {"n2",
       {{lm::kSliceId, "s-b"}, {lm::kSliceDegraded, "true"},
        {lm::kPerfClass, "degraded"}, {"google.com/tpu.count", "8"},
        {lm::kPerfMatmulTflops, "80"}, {lm::kPerfHbmGbps, "300"},
        {lm::kMultisliceSliceId, "0"}}},
      {"n3",
       {{lm::kSliceId, "s-b"}, {lm::kSliceDegraded, "true"},
        {"google.com/tpu.count", "8"}, {lm::kMultisliceSliceId, "1"}}},
      {"n4",
       {{lm::kLifecyclePreemptImminent, "true"},
        {"google.com/tpu.count", "4"}, {lm::kPerfClass, "gold"},
        {lm::kPerfMatmulTflops, "190"}, {lm::kPerfHbmGbps, "800"}}},
      {"n5", {{"google.com/tpu.count", "junk"}, {lm::kPerfClass, "bronze"}}},
  };
  agg::InventoryStore store;
  for (const auto& [node, labels] : fleet) {
    CHECK_TRUE(store.Apply(node, labels));
  }
  lm::Labels golden = {
      {"google.com/tpu.capacity.degraded", "8"},
      {"google.com/tpu.capacity.gold", "8"},
      {"google.com/tpu.capacity.silver", "4"},
      {"google.com/tpu.capacity.total-chips", "28"},
      {"google.com/tpu.capacity.unclassed", "8"},
      {"google.com/tpu.fleet.nodes", "6"},
      {"google.com/tpu.fleet.perf.hbm-p10", "326.342"},
      {"google.com/tpu.fleet.perf.hbm-p50", "699.542"},
      {"google.com/tpu.fleet.perf.matmul-p10", "85.936"},
      {"google.com/tpu.fleet.perf.matmul-p50", "152.241"},
      {"google.com/tpu.fleet.preempting", "1"},
      {"google.com/tpu.multislice.groups", "2"},
      {"google.com/tpu.slice-inventory.degraded-slices", "1"},
      {"google.com/tpu.slice-inventory.healthy-slices", "1"},
      {"google.com/tpu.slice-inventory.slices", "2"},
  };
  CHECK_TRUE(store.BuildOutputLabels() == golden);

  // A delta that cannot move any rollup (probe-ms-style noise) returns
  // false: nothing to publish.
  lm::Labels noisy = fleet["n0"];
  noisy["google.com/tpu.health.probe-ms"] = "17";
  CHECK_TRUE(!store.Apply("n0", noisy));
  CHECK_TRUE(store.BuildOutputLabels() == golden);

  // A real delta retires the OLD contribution and applies the new one:
  // n4's preemption notice clears, gold capacity stays, preempting
  // drops to 0 and the fleet gains a healthy unsliced node.
  lm::Labels healed = fleet["n4"];
  healed.erase(lm::kLifecyclePreemptImminent);
  CHECK_TRUE(store.Apply("n4", healed));
  lm::Labels after = store.BuildOutputLabels();
  CHECK_EQ(after["google.com/tpu.fleet.preempting"], "0");
  CHECK_EQ(after["google.com/tpu.capacity.gold"], "8");

  // Remove retires everything; a second remove of the same node is a
  // no-op.
  CHECK_TRUE(store.Remove("n2"));
  CHECK_TRUE(!store.Remove("n2"));
  after = store.BuildOutputLabels();
  CHECK_EQ(after["google.com/tpu.fleet.nodes"], "5");
  CHECK_EQ(after["google.com/tpu.capacity.degraded"], "0");
  CHECK_EQ(after["google.com/tpu.multislice.groups"], "1");
  // s-b still has n3 (degraded vote): still one degraded slice.
  CHECK_EQ(after["google.com/tpu.slice-inventory.degraded-slices"], "1");

  // The incremental state must equal a from-scratch rebuild — and the
  // steady path above never took one.
  CHECK_EQ(store.full_recomputes(), 0u);
  lm::Labels incremental = store.BuildOutputLabels();
  store.RecomputeAll();
  CHECK_TRUE(store.BuildOutputLabels() == incremental);
  CHECK_EQ(store.full_recomputes(), 1u);
}

void TestAggFlushController() {
  agg::FlushController flush(2.0);
  CHECK_TRUE(!flush.dirty());
  CHECK_TRUE(!flush.ShouldFlush(100.0));
  flush.NoteDirty(100.0);
  CHECK_TRUE(flush.dirty());
  CHECK_EQ(flush.DueAt(), 102.0);
  // Later events inside the window do NOT extend it — bounded
  // staleness, not a quiet-period timer (a steady drizzle cannot
  // starve the publish).
  flush.NoteDirty(101.9);
  CHECK_EQ(flush.DueAt(), 102.0);
  CHECK_TRUE(!flush.ShouldFlush(101.99));
  CHECK_TRUE(flush.ShouldFlush(102.0));
  flush.NoteFlushed();
  CHECK_TRUE(!flush.dirty());
  flush.NoteDirty(110.0);
  CHECK_EQ(flush.DueAt(), 112.0);

  // ReArm restores a consumed window after a failed publish. Clean ->
  // the original start; already re-dirtied by a mid-publish event ->
  // the EARLIER of the two (the retry owes the original staleness).
  flush.NoteFlushed();
  flush.ReArm(110.0);
  CHECK_TRUE(flush.dirty());
  CHECK_EQ(flush.DueAt(), 112.0);
  flush.NoteFlushed();
  flush.NoteDirty(111.5);  // landed while the failed publish was in flight
  flush.ReArm(110.0);
  CHECK_EQ(flush.DueAt(), 112.0);
  flush.ReArm(115.0);  // never later than an open window's start
  CHECK_EQ(flush.DueAt(), 112.0);
}

void TestPerfFleetFloor() {
  // Parse grid — pinned in tests/test_agg.py against
  // tpufd.perfmodel.parse_fleet_floor.
  Result<perf::FleetFloor> both = perf::ParseFleetFloor(
      "{\"matmul_p10_tflops\":150.5,\"hbm_p10_gbps\":600}");
  CHECK_TRUE(both.ok());
  CHECK_EQ(Fixed3(both->matmul_p10_tflops), "150.500");
  CHECK_EQ(Fixed3(both->hbm_p10_gbps), "600.000");
  Result<perf::FleetFloor> one =
      perf::ParseFleetFloor("{\"matmul_p10_tflops\":100}");
  CHECK_TRUE(one.ok());
  CHECK_EQ(one->hbm_p10_gbps, -1.0);
  CHECK_TRUE(one->valid());
  Result<perf::FleetFloor> none = perf::ParseFleetFloor("{}");
  CHECK_TRUE(none.ok());
  CHECK_TRUE(!none->valid());
  CHECK_TRUE(!perf::ParseFleetFloor("garbage").ok());
  CHECK_TRUE(!perf::ParseFleetFloor("[1]").ok());

  // Apply semantics: below either floor -> degraded, even from gold;
  // unmeasured (-1) values and unset (-1) floors never trigger.
  perf::FleetFloor floor;
  floor.matmul_p10_tflops = 150;
  floor.hbm_p10_gbps = 600;
  CHECK_EQ(perf::ApplyFleetFloor(perf::kRankGold, 180, 700, floor),
           perf::kRankGold);
  CHECK_EQ(perf::ApplyFleetFloor(perf::kRankGold, 140, 700, floor),
           perf::kRankDegraded);  // gray degradation: gold by rated spec
  CHECK_EQ(perf::ApplyFleetFloor(perf::kRankSilver, 180, 550, floor),
           perf::kRankDegraded);
  CHECK_EQ(perf::ApplyFleetFloor(perf::kRankGold, -1, -1, floor),
           perf::kRankGold);  // unmeasured never triggers
  perf::FleetFloor unset;
  CHECK_EQ(perf::ApplyFleetFloor(perf::kRankSilver, 1, 1, unset),
           perf::kRankSilver);
}

void TestSlicePreemptingMember() {
  // The report round-trips the lifecycle verdict (absent on old
  // reports reads as false)...
  slice::MemberReport report;
  report.host = "host-2";
  report.worker_id = 2;
  report.healthy = true;
  report.preempting = true;
  report.reported_at = 500;
  Result<slice::MemberReport> parsed =
      slice::ParseReport(slice::SerializeReport(report));
  CHECK_TRUE(parsed.ok());
  CHECK_TRUE(parsed->preempting);
  Result<slice::MemberReport> legacy = slice::ParseReport(
      "{\"host\":\"h\",\"healthy\":true,\"at\":500}");
  CHECK_TRUE(legacy.ok());
  CHECK_TRUE(!legacy->preempting);

  // ...and the leader folds it into a PROACTIVE degraded verdict: the
  // preempting member is present (a member, its class counts) but not
  // healthy — placement stops landing on a dying slice before the
  // host actually vanishes. Twin-pinned in test_slice.py.
  slice::SliceIdentity identity;
  identity.valid = true;
  identity.slice_id = "s";
  identity.num_hosts = 2;
  slice::CoordPolicy policy;
  policy.agreement_timeout_s = 60;
  slice::MemberReport peer;
  peer.host = "host-1";
  peer.healthy = true;
  peer.reported_at = 995;
  peer.perf_class = "gold";
  report.perf_class = "silver";
  report.reported_at = 995;
  slice::SliceVerdict verdict = slice::MergeVerdict(
      identity, "host-1", {peer, report}, policy, 1000.0);
  CHECK_EQ(verdict.healthy_hosts, 1);
  CHECK_TRUE(verdict.degraded);
  CHECK_EQ(verdict.members.size(), 2u);
  CHECK_EQ(verdict.perf_class, std::string("silver"));  // still counted
}

void TestGetNodeDraining() {
  // Unschedulable spec.
  {
    ScriptedApiServer server({{200,
                               "{\"spec\":{\"unschedulable\":true}}"}});
    k8s::ClusterConfig config;
    config.apiserver_url = server.url();
    config.node_name = "node-1";
    bool draining = false;
    bool alive = false;
    Status s = k8s::GetNodeDraining(config, &draining, &alive);
    CHECK_TRUE(s.ok());
    CHECK_TRUE(alive);
    CHECK_TRUE(draining);
  }
  // Autoscaler taint.
  {
    ScriptedApiServer server(
        {{200,
          "{\"spec\":{\"taints\":[{\"key\":"
          "\"ToBeDeletedByClusterAutoscaler\",\"effect\":"
          "\"NoSchedule\"}]}}"}});
    k8s::ClusterConfig config;
    config.apiserver_url = server.url();
    config.node_name = "node-1";
    bool draining = false;
    bool alive = false;
    CHECK_TRUE(k8s::GetNodeDraining(config, &draining, &alive).ok());
    CHECK_TRUE(draining);
  }
  // Healthy node: unrelated taints do not read as draining; a missing
  // Node object (404) is "not draining", not an error.
  {
    ScriptedApiServer server(
        {{200,
          "{\"spec\":{\"taints\":[{\"key\":\"google.com/tpu\","
          "\"effect\":\"NoSchedule\"}]}}"},
         {404, "{}"}});
    k8s::ClusterConfig config;
    config.apiserver_url = server.url();
    config.node_name = "node-1";
    bool draining = true;
    bool alive = false;
    CHECK_TRUE(k8s::GetNodeDraining(config, &draining, &alive).ok());
    CHECK_TRUE(!draining);
    draining = true;
    CHECK_TRUE(k8s::GetNodeDraining(config, &draining, &alive).ok());
    CHECK_TRUE(!draining);
  }
}

void TestRemedyEligibilityPrimitives() {
  // The scheduler's-eye eligibility predicate and the gray-degradation
  // detector (remedy/remedy.h; Python twin tpufd/remedy.py pins the
  // same grid in tests/test_remedy.py).
  lm::Labels ok = {{"google.com/tpu.count", "4"}};
  CHECK_TRUE(remedy::Eligible(&ok));
  CHECK_TRUE(!remedy::Eligible(nullptr));  // deleted CR
  lm::Labels bad = ok;
  bad["google.com/tpu.perf.class"] = "degraded";
  CHECK_TRUE(!remedy::Eligible(&bad));
  lm::Labels sliced = ok;
  sliced["google.com/tpu.slice.degraded"] = "true";
  CHECK_TRUE(!remedy::Eligible(&sliced));
  lm::Labels preempt = ok;
  preempt["google.com/tpu.lifecycle.preempt-imminent"] = "true";
  CHECK_TRUE(!remedy::Eligible(&preempt));

  // Gray: a chip-level degraded verdict while the headline class is
  // NOT degraded. A degraded headline means the node is already
  // fenced by the rest of the stack — not gray.
  lm::Labels gray = ok;
  gray["google.com/tpu.perf.chip0.class"] = "degraded";
  CHECK_TRUE(remedy::GrayDegraded(gray));
  CHECK_TRUE(!remedy::GrayDegraded(ok));
  lm::Labels loud = gray;
  loud["google.com/tpu.perf.class"] = "degraded";
  CHECK_TRUE(!remedy::GrayDegraded(loud));
  // Non-class chip keys (e.g. tpu.perf.chip0.gflops) are not verdicts.
  lm::Labels metric = ok;
  metric["google.com/tpu.perf.chip0.gflops"] = "degraded";
  CHECK_TRUE(!remedy::GrayDegraded(metric));

  // Deterministic jitter: same key -> same unit value, in [0, 1).
  double j = remedy::BackoffJitterUnit("n2", 1);
  CHECK_TRUE(j >= 0.0 && j < 1.0);
  CHECK_EQ(j, remedy::BackoffJitterUnit("n2", 1));
  CHECK_TRUE(j != remedy::BackoffJitterUnit("n2", 2));
}

void TestRemedyBackoffAndHeal() {
  remedy::RemedyConfig cfg;
  cfg.window_s = 60.0;
  cfg.flap_threshold = 2;
  cfg.heal_dwell_s = 10.0;
  cfg.cooldown_s = 1.0;
  cfg.backoff_base_s = 4.0;
  cfg.backoff_max_s = 30.0;
  remedy::RemedyEngine e(cfg);
  lm::Labels ok = {{"google.com/tpu.count", "4"}};
  lm::Labels bad = ok;
  bad["google.com/tpu.perf.class"] = "degraded";

  e.ObserveNode("n1", &ok, 0.0);
  e.ObserveNode("n1", &bad, 1.0);
  e.ObserveNode("n1", &ok, 2.0);
  e.ObserveNode("n1", &bad, 3.0);  // second down-flip -> crash-loop

  auto [actions, blocked] = e.Tick(4.0);
  CHECK_EQ(actions.size(), 1u);
  CHECK_EQ(actions[0].kind, "cordon");
  CHECK_EQ(actions[0].evidence, "crash-loop");
  // The write fails: exponential backoff (base 4s) arms, the intent is
  // dropped, and the next tick inside the backoff is rate-limited.
  e.NoteActionResult("n1", "cordon", false, 4.1);
  CHECK_EQ(e.write_failures(), 1);
  auto [actions2, blocked2] = e.Tick(5.0);
  CHECK_TRUE(actions2.empty());
  CHECK_EQ(blocked2.size(), 1u);
  CHECK_EQ(blocked2[0].second, "node-rate-limit");
  // After the backoff window (4s * <1.5 jitter factor <= 6s) the
  // still-active evidence re-emits the same cordon; this one lands.
  auto [actions3, blocked3] = e.Tick(11.0);
  CHECK_EQ(actions3.size(), 1u);
  CHECK_EQ(actions3[0].kind, "cordon");
  e.NoteActionResult("n1", "cordon", true, 11.1);
  CHECK_EQ(e.CordonedNodes().size(), 1u);
  CHECK_EQ(e.ActionCount("cordon"), 1);  // failures don't count

  // Heal: evidence retracted (flips age out of the window) and stays
  // retracted for heal_dwell_s -> automatic rollback.
  e.ObserveNode("n1", &ok, 70.0);
  auto [actions4, blocked4] = e.Tick(70.5);
  CHECK_TRUE(actions4.empty());  // dwell not yet served
  auto [actions5, blocked5] = e.Tick(81.0);
  CHECK_EQ(actions5.size(), 1u);
  CHECK_EQ(actions5[0].kind, "uncordon");
  e.NoteActionResult("n1", "uncordon", true, 81.1);
  CHECK_EQ(e.rollbacks(), 1);
  CHECK_TRUE(e.CordonedNodes().empty());
}

void TestRemedyParityGolden() {
  // The scripted scenario from tests/test_remedy.py, replayed through
  // the C++ engine; the final RenderJson() must equal the SAME literal
  // the Python twin pins. Every semantic change lands in both engines
  // or this golden fails on one side.
  remedy::RemedyConfig cfg;
  cfg.window_s = 60.0;
  cfg.flap_threshold = 3;
  cfg.heal_dwell_s = 10.0;
  cfg.cooldown_s = 5.0;
  cfg.backoff_base_s = 1.0;
  cfg.backoff_max_s = 30.0;
  cfg.max_concurrent_cordons = 3;
  cfg.domain_cap = 1;
  cfg.rebuild_cooldown_s = 30.0;
  remedy::RemedyEngine e(cfg);

  const lm::Labels kOk = {{"google.com/tpu.count", "4"}};
  lm::Labels kBad = kOk;
  kBad["google.com/tpu.perf.class"] = "degraded";
  lm::Labels kGray = kOk;
  kGray["google.com/tpu.perf.chip0.class"] = "degraded";
  lm::Labels kPre = kOk;
  kPre["google.com/tpu.lifecycle.preempt-imminent"] = "true";
  auto dom = [](lm::Labels labels, const char* d) {
    labels["google.com/tpu.topology.domain"] = d;
    return labels;
  };

  // t=0 baseline: n1/n2/n5 plain, n3/n4 in rack-a, n6 in rack-b.
  for (const char* n : {"n1", "n2", "n5"}) e.ObserveNode(n, &kOk, 0.0);
  for (const char* n : {"n3", "n4"}) {
    lm::Labels l = dom(kOk, "rack-a");
    e.ObserveNode(n, &l, 0.0);
  }
  {
    lm::Labels l = dom(kOk, "rack-b");
    e.ObserveNode("n6", &l, 0.0);
  }
  // Crash-loop flapping on n1/n3/n4/n6 (down-flips at t=1, 3, 5).
  int i = 0;
  for (double t : {1.0, 2.0, 3.0, 4.0, 5.0}) {
    const lm::Labels& flat = (i % 2 == 0) ? kBad : kOk;
    e.ObserveNode("n1", &flat, t);
    lm::Labels a = dom(flat, "rack-a");
    lm::Labels b = dom(flat, "rack-b");
    e.ObserveNode("n3", &a, t);
    e.ObserveNode("n4", &a, t);
    e.ObserveNode("n6", &b, t);
    i++;
  }
  e.ObserveNode("n2", &kGray, 5.5);
  e.ObserveNode("n5", &kPre, 5.5);

  // Tick 1: cordons n1/n2/n3, budget blocks n4+n6, drain n5.
  auto [a1, b1] = e.Tick(6.0);
  CHECK_EQ(a1.size(), 4u);
  CHECK_EQ(a1[0].kind + ":" + a1[0].node, "cordon:n1");
  CHECK_EQ(a1[1].kind + ":" + a1[1].node, "cordon:n2");
  CHECK_EQ(a1[2].kind + ":" + a1[2].node, "cordon:n3");
  CHECK_EQ(a1[3].kind + ":" + a1[3].node, "drain-recommend:n5");
  e.NoteActionResult("n1", "cordon", true, 6.1);
  e.NoteActionResult("n2", "cordon", false, 6.1);  // write failure
  e.NoteActionResult("n3", "cordon", true, 6.1);
  e.NoteActionResult("n5", "drain-recommend", true, 6.1);

  // Tick 2: n2 rate-limited, n4 domain-capped behind n3, n6 cordons.
  auto [a2, b2] = e.Tick(7.0);
  CHECK_EQ(a2.size(), 1u);
  CHECK_EQ(a2[0].kind + ":" + a2[0].node, "cordon:n6");
  CHECK_EQ(b2.size(), 2u);
  CHECK_EQ(b2[0].first + "/" + b2[0].second, "n2/node-rate-limit");
  CHECK_EQ(b2[1].first + "/" + b2[1].second, "n4/domain-cap");
  e.NoteActionResult("n6", "cordon", true, 7.1);

  // Tick 3: a burning SLO stage defers n4's cordon.
  {
    lm::Labels burn = {{"google.com/tpu.slo.publish.burn", "true"}};
    e.ObserveInventory(burn, 7.5);
  }
  auto [a3, b3] = e.Tick(8.0);
  CHECK_TRUE(a3.empty());
  CHECK_EQ(b3.size(), 1u);
  CHECK_EQ(b3[0].first + "/" + b3[0].second, "n4/slo-burn");

  // Tick 4: burn clears, budget re-blocks n4; queued demand triggers
  // a rebuild recommendation (predicted capacity 0 < 20 chips).
  e.ObserveInventory({}, 9.0);
  e.ObserveDemand(20, 9.0);
  auto [a4, b4] = e.Tick(9.5);
  CHECK_EQ(a4.size(), 1u);
  CHECK_EQ(a4[0].kind, "rebuild-recommend");
  CHECK_EQ(b4.size(), 1u);
  CHECK_EQ(b4[0].first + "/" + b4[0].second, "n4/disruption-budget");
  e.NoteActionResult("", "rebuild-recommend", true, 9.6);

  // t=70: n1 heals for good; n3/n6 stay gray-degraded.
  e.ObserveNode("n1", &kOk, 70.0);
  e.ObserveNode("n2", &kOk, 70.0);
  {
    lm::Labels a = dom(kGray, "rack-a");
    lm::Labels b = dom(kGray, "rack-b");
    e.ObserveNode("n3", &a, 70.0);
    e.ObserveNode("n6", &b, 70.0);
  }
  auto [a5, b5] = e.Tick(70.5);
  CHECK_EQ(a5.size(), 1u);
  CHECK_EQ(a5[0].kind, "rebuild-recommend");
  e.NoteActionResult("", "rebuild-recommend", true, 70.6);

  // Tick 6: n1's evidence stayed retracted for the heal dwell.
  auto [a6, b6] = e.Tick(81.0);
  CHECK_EQ(a6.size(), 1u);
  CHECK_EQ(a6[0].kind + ":" + a6[0].node, "uncordon:n1");
  e.NoteActionResult("n1", "uncordon", true, 81.1);

  // Gray returns on n2; the cordon intent is abandoned mid-batch
  // (epoch fence) without state change.
  e.ObserveNode("n2", &kGray, 82.0);
  auto [a7, b7] = e.Tick(82.5);
  CHECK_EQ(a7.size(), 1u);
  CHECK_EQ(a7[0].kind + ":" + a7[0].node, "cordon:n2");
  CHECK_EQ(e.AbandonPending(), 1);

  CHECK_EQ(
      e.RenderJson(),
      "{\"actions\":{\"cordon\":3,\"drain-recommend\":1,"
      "\"rebuild-recommend\":2,\"uncordon\":1},\"blocked\":{"
      "\"disruption-budget\":3,\"domain-cap\":1,\"node-rate-limit\":1,"
      "\"slo-burn\":1},\"cordoned\":[\"n3\",\"n6\"],\"nodes\":{\"n1\":{"
      "\"cordoned\":false,\"domain\":\"\",\"evidence\":[],\"flips\":0},"
      "\"n2\":{\"cordoned\":false,\"domain\":\"\",\"evidence\":["
      "\"gray\"],\"flips\":0},\"n3\":{\"cordoned\":true,\"domain\":"
      "\"rack-a\",\"evidence\":[\"gray\"],\"flips\":0},\"n4\":{"
      "\"cordoned\":false,\"domain\":\"rack-a\",\"evidence\":[],"
      "\"flips\":0},\"n5\":{\"cordoned\":false,\"domain\":\"\","
      "\"evidence\":[\"preempt\"],\"flips\":0},\"n6\":{\"cordoned\":"
      "true,\"domain\":\"rack-b\",\"evidence\":[\"gray\"],\"flips\":0}}"
      ",\"rollbacks\":1,\"write_failures\":1}");
}

void TestPatchNodeUnschedulable() {
  // Cordon: ONE merge patch of spec.unschedulable to the core nodes
  // endpoint, nothing else on the wire.
  {
    ScriptedApiServer server({{200, "{}"}});
    k8s::ClusterConfig config;
    config.apiserver_url = server.url();
    bool alive = false;
    k8s::WriteOutcome outcome;
    Status s = k8s::PatchNodeUnschedulable(config, "node-1", true, &alive,
                                           &outcome);
    CHECK_TRUE(s.ok());
    CHECK_TRUE(alive);
    CHECK_EQ(outcome.patches, 1);
    CHECK_EQ(server.exchanges().size(), 1u);
    CHECK_EQ(server.exchanges()[0].method, "PATCH");
    CHECK_EQ(server.exchanges()[0].path, "/api/v1/nodes/node-1");
    CHECK_EQ(server.exchanges()[0].body,
             "{\"spec\":{\"unschedulable\":true}}");
  }
  // Uncordon flips the literal; a 5xx is an error with an ALIVE server
  // (pacing/overload must not read as a partition).
  {
    ScriptedApiServer server({{200, "{}"}, {503, "{}"}});
    k8s::ClusterConfig config;
    config.apiserver_url = server.url();
    bool alive = false;
    CHECK_TRUE(
        k8s::PatchNodeUnschedulable(config, "node-1", false, &alive, nullptr)
            .ok());
    CHECK_EQ(server.exchanges()[0].body,
             "{\"spec\":{\"unschedulable\":false}}");
    Status s =
        k8s::PatchNodeUnschedulable(config, "node-1", true, &alive, nullptr);
    CHECK_TRUE(!s.ok());
    CHECK_TRUE(alive);
  }
}

void TestAggWatchEventName() {
  // metadata.name now rides every parsed watch event — load-bearing at
  // collection scope, where one stream carries every object. Pinned in
  // tests/test_agg.py against tpufd.sink.parse_watch_event.
  k8s::WatchEvent event = k8s::ParseWatchEventLine(
      "{\"type\":\"MODIFIED\",\"object\":{\"metadata\":{\"name\":"
      "\"tfd-features-for-node-7\",\"resourceVersion\":\"12\"},"
      "\"spec\":{\"labels\":{\"a\":\"1\"}}}}");
  CHECK_EQ(event.name, "tfd-features-for-node-7");
  CHECK_EQ(event.resource_version, "12");
  k8s::WatchEvent nameless = k8s::ParseWatchEventLine(
      "{\"type\":\"BOOKMARK\",\"object\":{\"metadata\":"
      "{\"resourceVersion\":\"40\"}}}");
  CHECK_EQ(nameless.name, "");
}

void TestAggShardIndexOf() {
  // Pinned assignment: fnv1a64("tpu-node-1") == 0xd4ee320a7c9868f9
  // (tests/test_agg.py pins the same constant through tpufd.sink, so
  // an L1 shard and the Python twins can never disagree on ownership).
  CHECK_EQ(agg::ShardIndexOf("tpu-node-1", 0), 0);
  CHECK_EQ(agg::ShardIndexOf("tpu-node-1", 1), 0);
  CHECK_EQ(agg::ShardIndexOf("tpu-node-1", 4),
           static_cast<int>(0xd4ee320a7c9868f9ULL % 4));
  for (int i = 0; i < 50; i++) {
    std::string node = "node-" + std::to_string(i);
    int shard = agg::ShardIndexOf(node, 5);
    CHECK_TRUE(shard >= 0 && shard < 5);
    CHECK_EQ(shard, agg::ShardIndexOf(node, 5));
  }
}

void TestAggPartialLabelsRoundtrip() {
  agg::InventoryStore store;
  agg::StageSketches st;
  st["plan"].Add(42.0);
  st["publish"].Add(850.0);
  CHECK_TRUE(store.Apply("n0",
                         {{lm::kPerfClass, "gold"},
                          {"google.com/tpu.count", "4"},
                          {lm::kSliceId, "s-a"},
                          {lm::kPerfMatmulTflops, "180.5"},
                          {lm::kPerfHbmGbps, "700"}},
                         agg::SerializeStageSketches(st)));
  // A 0-chip node leaves a ZERO-valued capacity entry (erase-at-zero is
  // a retire-path rule, not a store invariant); the wire format must
  // carry it verbatim or the root's no-op equality check would flap.
  CHECK_TRUE(store.Apply("zero", {{"google.com/tpu.count", "0"}}));

  lm::Labels wire = agg::SerializePartialLabels(store.Partial(), "2/8");
  CHECK_EQ(wire[lm::kAggTier], std::string(lm::kAggTierPartial));
  CHECK_EQ(wire[lm::kAggShard], "2/8");
  CHECK_EQ(wire[lm::kAggNodes], "2");
  CHECK_EQ(wire[lm::kAggPreempting], "0");
  agg::RollupState parsed;
  CHECK_TRUE(agg::ParsePartialLabels(wire, &parsed));
  CHECK_TRUE(parsed == store.Partial());

  // The published rollup label set is NOT a partial (no tier marker):
  // the parser refuses rather than ingesting scalars as contributions.
  agg::RollupState reject;
  CHECK_TRUE(!agg::ParsePartialLabels(store.BuildOutputLabels(), &reject));
  CHECK_TRUE(!agg::ParsePartialLabels({}, &reject));
}

// The shared fleet generator for the tree-merge tests: mixed classes,
// slices with degraded verdicts, preempting nodes, perf samples and
// per-node stage sketches — every rollup family exercised.
lm::Labels ShardTestNodeLabels(int i) {
  lm::Labels labels;
  labels["google.com/tpu.count"] = std::to_string(4 + (i % 3) * 2);
  if (i % 4 == 0) {
    labels[lm::kPerfClass] = "gold";
  } else if (i % 4 == 1) {
    labels[lm::kPerfClass] = "silver";
  } else if (i % 4 == 2) {
    labels[lm::kPerfClass] = "degraded";
  }
  labels[lm::kSliceId] = "s-" + std::to_string(i % 5);
  if (i % 7 == 0) labels[lm::kSliceDegraded] = "true";
  if (i % 11 == 0) labels[lm::kLifecyclePreemptImminent] = "true";
  if (i % 6 == 0) labels[lm::kMultisliceSliceId] = std::to_string(i % 2);
  labels[lm::kPerfMatmulTflops] = std::to_string(90 + i * 4) + ".25";
  labels[lm::kPerfHbmGbps] = std::to_string(300 + i * 17);
  return labels;
}

void TestAggShardMergeTree() {
  // Satellite contract: merging N partial sketches equals the flat
  // single-aggregator state BIT-identically — integer bucket counts
  // make merge associative — including unmerge-then-remerge when a
  // shard's partial is retired and re-admitted.
  const int kNodes = 48;
  const int kShards = 3;
  agg::InventoryStore flat;
  std::vector<agg::InventoryStore> shards(kShards);
  for (int i = 0; i < kNodes; i++) {
    std::string node = "merge-node-" + std::to_string(i);
    lm::Labels labels = ShardTestNodeLabels(i);
    agg::StageSketches st;
    st["plan"].Add(40.0 + i * 3.1);
    st["publish-acked"].Add(900.0 + i * 11.0);
    std::string slo = agg::SerializeStageSketches(st);
    CHECK_TRUE(flat.Apply(node, labels, slo));
    CHECK_TRUE(shards[agg::ShardIndexOf(node, kShards)].Apply(node, labels,
                                                              slo));
  }
  for (int s = 0; s < kShards; s++) {
    CHECK_TRUE(shards[s].nodes() > 0);  // the fleet spans every shard
  }

  // L1 -> L2 over the WIRE: each shard's partial serializes to labels
  // and parses back at the root, exactly as in production.
  agg::ShardMergeStore merge;
  for (int s = 0; s < kShards; s++) {
    lm::Labels partial_wire = agg::SerializePartialLabels(
        shards[s].Partial(),
        std::to_string(s) + "/" + std::to_string(kShards));
    agg::RollupState parsed;
    CHECK_TRUE(agg::ParsePartialLabels(partial_wire, &parsed));
    CHECK_TRUE(parsed == shards[s].Partial());
    CHECK_TRUE(merge.ApplyPartial(
        "tfd-inventory-shard-" + std::to_string(s), parsed));
  }

  // Tree == flat: byte-identical published labels AND bit-identical
  // sketches underneath (bucket-count equality, not quantile equality).
  CHECK_TRUE(merge.BuildOutputLabels() == flat.BuildOutputLabels());
  CHECK_TRUE(merge.merged().matmul == flat.Partial().matmul);
  CHECK_TRUE(merge.merged().hbm == flat.Partial().hbm);
  CHECK_TRUE(merge.merged().stage == flat.Partial().stage);

  // Unmerge-then-remerge: a shard leader churns, its partial is retired
  // (Sketch Unmerge, counter-map subtract) and re-admitted — the root
  // must land back on the identical state, without a recompute.
  agg::RollupState shard1 = shards[1].Partial();
  CHECK_TRUE(merge.RemovePartial("tfd-inventory-shard-1"));
  CHECK_TRUE(!(merge.BuildOutputLabels() == flat.BuildOutputLabels()));
  CHECK_TRUE(merge.ApplyPartial("tfd-inventory-shard-1", shard1));
  CHECK_TRUE(merge.BuildOutputLabels() == flat.BuildOutputLabels());
  CHECK_TRUE(merge.merged().matmul == flat.Partial().matmul);
  CHECK_TRUE(merge.merged().stage == flat.Partial().stage);

  // Re-applying an identical partial is a no-op (nothing to publish);
  // removing an unknown shard likewise.
  CHECK_TRUE(!merge.ApplyPartial("tfd-inventory-shard-1", shard1));
  CHECK_TRUE(!merge.RemovePartial("tfd-inventory-shard-9"));

  // The steady path never recomputed, at either tier — and a forced
  // from-scratch rebuild equals the incremental state.
  CHECK_EQ(merge.full_recomputes(), 0u);
  CHECK_EQ(flat.full_recomputes(), 0u);
  lm::Labels incremental = merge.BuildOutputLabels();
  merge.RecomputeAll();
  CHECK_TRUE(merge.BuildOutputLabels() == incremental);
  CHECK_EQ(merge.full_recomputes(), 1u);
}

void TestPlacementIndexContract() {
  // The SimScheduler eligibility contract (tpufd/cluster.py),
  // replicated by placement::PlacementIndex and pinned here; the
  // Python twin runs the same scenario in tests/test_placement.py.
  CHECK_EQ(placement::ClassRank("gold"), 3);
  CHECK_EQ(placement::ClassRank("silver"), 2);
  CHECK_EQ(placement::ClassRank("degraded"), 1);
  CHECK_EQ(placement::ClassRank(""), 0);
  CHECK_EQ(placement::ClassRank("bronze"), 0);
  CHECK_EQ(placement::JobMinRank("gold"), 3);
  CHECK_EQ(placement::JobMinRank("any"), 0);
  CHECK_EQ(placement::JobMinRank("bronze"), -1);

  placement::PlacementIndex index;
  index.ApplyNode("a-gold", {{lm::kPerfClass, "gold"},
                             {"google.com/tpu.count", "4"},
                             {lm::kSliceId, "s1"}});
  index.ApplyNode("b-gold-big", {{lm::kPerfClass, "gold"},
                                 {"google.com/tpu.count", "8"}});
  index.ApplyNode("c-silver", {{lm::kPerfClass, "silver"},
                               {"google.com/tpu.count", "8"},
                               {lm::kSliceId, "s2"}});
  index.ApplyNode("d-degraded", {{lm::kPerfClass, "degraded"},
                                 {"google.com/tpu.count", "16"}});
  index.ApplyNode("e-preempt", {{lm::kPerfClass, "gold"},
                                {"google.com/tpu.count", "8"},
                                {lm::kLifecyclePreemptImminent, "true"}});
  CHECK_EQ(index.nodes(), 5u);
  CHECK_EQ(index.eligible(), 3u);  // degraded + preempting filtered

  // Preference order: highest class, then most free, then name.
  placement::PlacementQuery q;
  q.wanted = "any";
  q.chips = 4;
  q.limit = 8;
  placement::PlacementResult r = index.Query(q);
  CHECK_EQ(r.status, "placed");
  CHECK_EQ(r.candidates.size(), 3u);
  CHECK_EQ(r.candidates[0].node, "b-gold-big");
  CHECK_EQ(r.candidates[1].node, "a-gold");
  CHECK_EQ(r.candidates[2].node, "c-silver");

  // The class floor filters below-rank candidates.
  q.wanted = "gold";
  r = index.Query(q);
  CHECK_EQ(r.candidates.size(), 2u);

  // The chips filter.
  q.wanted = "any";
  q.chips = 8;
  r = index.Query(q);
  CHECK_EQ(r.candidates.size(), 2u);
  CHECK_EQ(r.candidates[0].node, "b-gold-big");

  // Worst-of-members: ONE member's degraded verdict blocks the whole
  // slice — including members whose own labels still read healthy.
  index.ApplyNode("f-verdict", {{lm::kSliceId, "s1"},
                                {lm::kSliceDegraded, "true"},
                                {"google.com/tpu.count", "4"}});
  CHECK_EQ(index.blocked_slices(), 1u);
  q.chips = 4;
  r = index.Query(q);
  for (const placement::Candidate& c : r.candidates) {
    CHECK_TRUE(c.node != "a-gold" && c.node != "f-verdict");
  }
  // The verdict clears: the slice unblocks without a rebuild.
  index.ApplyNode("f-verdict",
                  {{lm::kSliceId, "s1"}, {"google.com/tpu.count", "4"}});
  CHECK_EQ(index.blocked_slices(), 0u);
  r = index.Query(q);
  bool has_a = false;
  for (const placement::Candidate& c : r.candidates) {
    if (c.node == "a-gold") has_a = true;
  }
  CHECK_TRUE(has_a);

  // A slice-requiring (multislice) query only returns slice members.
  q.slice = true;
  r = index.Query(q);
  CHECK_TRUE(!r.candidates.empty());
  for (const placement::Candidate& c : r.candidates) {
    CHECK_TRUE(!c.slice_id.empty());
  }
  q.slice = false;

  // Cluster admission from the aggregator's capacity-by-class rollup.
  std::string prefix = lm::kCapacityPrefix;
  index.ApplyInventory({{prefix + "gold", "8"},
                        {prefix + "silver", "0"},
                        {prefix + "unclassed", "4"},
                        {prefix + "degraded", "16"}});
  q.wanted = "gold";
  q.chips = 9;
  CHECK_EQ(index.Query(q).status, "no-capacity");
  q.chips = 8;
  CHECK_EQ(index.Query(q).status, "placed");
  // Degraded capacity never admits anything (rank 1 < every floor the
  // bucket table serves), and non-digit capacity reads as 0.
  index.ApplyInventory({{prefix + "gold", "junk"}});
  q.chips = 1;
  CHECK_EQ(index.Query(q).status, "no-capacity");
  // Inventory deleted: empty admits everything again.
  index.ApplyInventory({});
  CHECK_EQ(index.Query(q).status, "placed");

  CHECK_TRUE(index.RemoveNode("b-gold-big"));
  CHECK_TRUE(!index.RemoveNode("b-gold-big"));
  q.wanted = "any";
  q.chips = 100;
  CHECK_EQ(index.Query(q).status, "no-candidate");
}

void TestPlacementProtocol() {
  placement::PlacementQuery q;
  CHECK_EQ(placement::ParsePlacementBody(
               "{\"class\":\"gold\",\"chips\":4,\"slice\":true,"
               "\"limit\":3}",
               &q),
           "");
  CHECK_EQ(q.wanted, "gold");
  CHECK_EQ(q.chips, 4);
  CHECK_TRUE(q.slice);
  CHECK_EQ(q.limit, 3);
  CHECK_EQ(placement::ParsePlacementBody("{}", &q), "");
  CHECK_EQ(q.wanted, "any");
  CHECK_EQ(q.chips, 1);
  CHECK_TRUE(!q.slice);
  CHECK_TRUE(!placement::ParsePlacementBody("", &q).empty());
  CHECK_TRUE(!placement::ParsePlacementBody("[]", &q).empty());
  CHECK_TRUE(
      !placement::ParsePlacementBody("{\"class\":\"bronze\"}", &q).empty());
  CHECK_TRUE(!placement::ParsePlacementBody("{\"chips\":-1}", &q).empty());
  CHECK_TRUE(!placement::ParsePlacementBody("{\"chips\":1.5}", &q).empty());
  CHECK_TRUE(!placement::ParsePlacementBody("{\"limit\":0}", &q).empty());
  CHECK_TRUE(!placement::ParsePlacementBody("{\"slice\":1}", &q).empty());

  placement::PlacementResult result;
  result.status = "placed";
  result.candidates.push_back({"n1", "gold", 4, "s1"});
  CHECK_EQ(placement::RenderPlacementResult(result),
           "{\"status\":\"placed\",\"candidates\":[{\"node\":\"n1\","
           "\"class\":\"gold\",\"free\":4,\"slice\":\"s1\"}]}");
  result.candidates.clear();
  result.status = "no-candidate";
  CHECK_EQ(placement::RenderPlacementResult(result),
           "{\"status\":\"no-candidate\",\"candidates\":[]}");

  // ISSUE 18: the explain request surface. Defaults off, strict types,
  // and the job id (the audit-ring join key) rides along.
  CHECK_EQ(placement::ParsePlacementBody(
               "{\"explain\":true,\"job\":\"train-77\"}", &q),
           "");
  CHECK_TRUE(q.explain);
  CHECK_EQ(q.job, "train-77");
  CHECK_EQ(placement::ParsePlacementBody("{}", &q), "");
  CHECK_TRUE(!q.explain);
  CHECK_EQ(q.job, "");
  CHECK_TRUE(!placement::ParsePlacementBody("{\"explain\":1}", &q).empty());
  CHECK_TRUE(!placement::ParsePlacementBody("{\"job\":7}", &q).empty());

  // The explain section APPENDS to the same document — a non-explain
  // answer's bytes stay untouched (pay-for-what-you-use, asserted
  // byte-for-byte by scripts/placement_smoke.py --explain too).
  result.status = "no-candidate";
  result.explained = true;
  result.explanation.reasons["insufficient-chips"] = 1;
  result.explanation.reasons["slice-member-degraded"] = 1;
  result.explanation.rejected = 2;
  result.explanation.rejections.push_back(
      {"n2", "insufficient-chips", "", "ch-2"});
  result.explanation.rejections.push_back(
      {"n3", "slice-member-degraded", "n9", "ch-9"});
  result.explanation.counterfactual = "why not";
  result.explanation.change_ids = {"ch-2", "ch-9"};
  CHECK_EQ(placement::RenderPlacementResult(result),
           "{\"status\":\"no-candidate\",\"candidates\":[],"
           "\"explain\":{\"reasons\":{\"insufficient-chips\":1,"
           "\"slice-member-degraded\":1},\"rejected\":2,\"rejections\":["
           "{\"node\":\"n2\",\"reason\":\"insufficient-chips\","
           "\"change\":\"ch-2\"},"
           "{\"node\":\"n3\",\"reason\":\"slice-member-degraded\","
           "\"member\":\"n9\",\"change\":\"ch-9\"}],"
           "\"counterfactual\":\"why not\","
           "\"change_ids\":[\"ch-2\",\"ch-9\"]}}");
}

void TestPlacementExplain() {
  // The rejection-taxonomy walk (ISSUE 18), pinned against
  // tpufd.placement.explain / tpufd.cluster.explain_decision — the
  // Python grids run the same scenario in tests/test_placement.py.
  const std::string count = "google.com/tpu.count";
  placement::PlacementIndex index;
  index.ApplyNode("xa-gold-big",
                  {{lm::kPerfClass, "gold"}, {count, "16"},
                   {lm::kSliceId, "xs-1"}},
                  "ch-a");
  index.ApplyNode("xb-gold-small",
                  {{lm::kPerfClass, "gold"}, {count, "4"}}, "ch-b");
  index.ApplyNode("xc-degraded",
                  {{lm::kPerfClass, "degraded"}, {count, "8"}}, "ch-c");
  index.ApplyNode("xd-silver",
                  {{lm::kPerfClass, "silver"}, {count, "8"}}, "ch-d");
  index.ApplyNode("xe-preempt",
                  {{lm::kPerfClass, "gold"}, {count, "8"},
                   {lm::kLifecyclePreemptImminent, "true"}},
                  "ch-e");
  index.ApplyNode("xf-drain",
                  {{lm::kPerfClass, "gold"}, {count, "8"},
                   {lm::kLifecycleDraining, "true"}},
                  "ch-f");
  // xg-m0's own claim blocks itself (member = self) AND its healthy
  // peer xg-m1 (member = xg-m0, change = xg-m0's write).
  index.ApplyNode("xg-m0",
                  {{lm::kPerfClass, "gold"}, {count, "8"},
                   {lm::kSliceId, "xs-2"}, {lm::kSliceDegraded, "true"}},
                  "ch-g0");
  index.ApplyNode("xg-m1",
                  {{lm::kPerfClass, "gold"}, {count, "8"},
                   {lm::kSliceId, "xs-2"}},
                  "ch-g1");

  placement::PlacementQuery q;
  q.wanted = "gold";
  q.chips = 8;
  q.explain = true;
  placement::PlacementResult r = index.Query(q);
  CHECK_EQ(r.status, "placed");
  CHECK_EQ(r.candidates[0].node, "xa-gold-big");
  placement::PlacementExplanation ex = index.Explain(q, r);
  CHECK_EQ(ex.rejected, 7);
  CHECK_EQ(ex.reasons["perf-degraded"], 1);
  CHECK_EQ(ex.reasons["class-floor"], 1);
  CHECK_EQ(ex.reasons["lifecycle-preempt"], 1);
  CHECK_EQ(ex.reasons["lifecycle-draining"], 1);
  CHECK_EQ(ex.reasons["slice-member-degraded"], 2);
  CHECK_EQ(ex.reasons["insufficient-chips"], 1);
  CHECK_EQ(ex.counterfactual, "");  // placed: nothing to counterfact
  // Rejections are name-ordered; the slice entries name the blocking
  // member (self for the claimer, the first claimer for the peer) and
  // join the change-id of the write that created the condition.
  std::map<std::string, placement::Rejection> by_node;
  for (const placement::Rejection& rej : ex.rejections) {
    by_node[rej.node] = rej;
  }
  CHECK_EQ(by_node["xg-m0"].reason, "slice-member-degraded");
  CHECK_EQ(by_node["xg-m0"].member, "xg-m0");
  CHECK_EQ(by_node["xg-m0"].change, "ch-g0");
  CHECK_EQ(by_node["xg-m1"].reason, "slice-member-degraded");
  CHECK_EQ(by_node["xg-m1"].member, "xg-m0");
  CHECK_EQ(by_node["xg-m1"].change, "ch-g0");  // the BLOCKING write
  CHECK_EQ(by_node["xb-gold-small"].reason, "insufficient-chips");
  CHECK_EQ(by_node["xd-silver"].reason, "class-floor");
  // change_ids: sorted, deduped (xg-m1 contributed ch-g0, not ch-g1).
  const std::vector<std::string> want_ids = {"ch-b", "ch-c", "ch-d",
                                             "ch-e", "ch-f", "ch-g0"};
  CHECK_TRUE(ex.change_ids == want_ids);

  // Precedence: a node's OWN basic reason beats a peer's slice claim,
  // and class-floor beats the peer claim too.
  index.ApplyNode("xh-preempt-in-xs2",
                  {{lm::kPerfClass, "gold"}, {count, "8"},
                   {lm::kSliceId, "xs-2"},
                   {lm::kLifecyclePreemptImminent, "true"}},
                  "ch-h");
  index.ApplyNode("xi-silver-in-xs2",
                  {{lm::kPerfClass, "silver"}, {count, "8"},
                   {lm::kSliceId, "xs-2"}},
                  "ch-i");
  r = index.Query(q);
  ex = index.Explain(q, r);
  for (const placement::Rejection& rej : ex.rejections) by_node[rej.node] = rej;
  CHECK_EQ(by_node["xh-preempt-in-xs2"].reason, "lifecycle-preempt");
  CHECK_EQ(by_node["xi-silver-in-xs2"].reason, "class-floor");
  index.RemoveNode("xh-preempt-in-xs2");
  index.RemoveNode("xi-silver-in-xs2");

  // A viable node beyond the answer is SKIPPED, not rejected: the
  // taxonomy explains infeasibility, not ranking.
  q.wanted = "any";
  q.chips = 4;
  q.limit = 1;
  r = index.Query(q);
  ex = index.Explain(q, r);
  bool saw_viable_loser = false;
  for (const placement::Rejection& rej : ex.rejections) {
    if (rej.node == "xb-gold-small") saw_viable_loser = true;
  }
  CHECK_TRUE(!saw_viable_loser);

  // Unplaceable counterfactual: the pinned string names the best
  // rejected node and what would have to change, with the change join.
  q.wanted = "gold";
  q.chips = 64;
  r = index.Query(q);
  CHECK_EQ(r.status, "no-candidate");
  ex = index.Explain(q, r);
  CHECK_EQ(ex.counterfactual,
           "insufficient-chips: needs 48 more free chip(s); best node "
           "xa-gold-big has 16 free (change ch-a)");

  // Slice-blocked counterfactual.
  placement::PlacementIndex slice_only;
  slice_only.ApplyNode("ya-m0",
                       {{lm::kPerfClass, "gold"}, {count, "8"},
                        {lm::kSliceId, "ys-1"},
                        {lm::kSliceDegraded, "true"}},
                       "ch-y0");
  q.chips = 8;
  r = slice_only.Query(q);
  ex = slice_only.Explain(q, r);
  CHECK_EQ(ex.counterfactual,
           "slice-member-degraded: slice ys-1 blocked by member "
           "ya-m0's degraded-slice verdict (change ch-y0)");

  // Class-floor counterfactual ("unclassed" when no class published).
  placement::PlacementIndex floor_only;
  floor_only.ApplyNode("za", {{count, "8"}});
  r = floor_only.Query(q);
  ex = floor_only.Explain(q, r);
  CHECK_EQ(ex.counterfactual,
           "class-floor: needs class >= gold; best node za is unclassed");

  // no-capacity counterfactual is query-wide and joins the INVENTORY
  // change; every node rejects as capacity-admission.
  const std::string prefix = lm::kCapacityPrefix;
  index.ApplyInventory({{prefix + "gold", "0"}}, "ch-inv");
  q.chips = 1;
  r = index.Query(q);
  CHECK_EQ(r.status, "no-capacity");
  ex = index.Explain(q, r);
  CHECK_EQ(ex.counterfactual,
           "capacity-admission: inventory admits fewer than 1 chip(s) "
           "at class floor gold (change ch-inv)");
  CHECK_EQ(ex.reasons["capacity-admission"], ex.rejected);
  CHECK_TRUE(ex.change_ids == std::vector<std::string>{"ch-inv"});
  index.ApplyInventory({});

  // Empty-index counterfactuals, slice-shaped and not.
  placement::PlacementIndex empty;
  r = empty.Query(q);
  ex = empty.Explain(q, r);
  CHECK_EQ(ex.counterfactual, "no candidate nodes in index");
  q.slice = true;
  r = empty.Query(q);
  ex = empty.Explain(q, r);
  CHECK_EQ(ex.counterfactual, "no slice-member nodes in index");
  q.slice = false;

  // Non-members are structurally out of scope for a multislice query
  // (not rejections), and the inline sample is bounded while the
  // counts cover EVERY rejected node.
  placement::PlacementIndex big;
  for (int i = 0; i < 40; i++) {
    char name[16];
    snprintf(name, sizeof(name), "bn-%02d", i);
    big.ApplyNode(name, {{lm::kPerfClass, "degraded"}, {count, "8"}});
  }
  big.ApplyNode("bs-member", {{lm::kPerfClass, "gold"}, {count, "4"},
                              {lm::kSliceId, "bs-1"}});
  q.wanted = "gold";
  q.chips = 8;
  q.slice = true;
  r = big.Query(q);
  ex = big.Explain(q, r);
  CHECK_EQ(ex.rejected, 1);  // the 40 non-members never enter the walk
  CHECK_EQ(ex.reasons["insufficient-chips"], 1);
  q.slice = false;
  r = big.Query(q);
  ex = big.Explain(q, r);
  CHECK_EQ(ex.rejected, 41);
  CHECK_EQ(static_cast<int>(ex.rejections.size()),
           placement::PlacementExplanation::kMaxRejections);
  CHECK_EQ(ex.reasons["perf-degraded"], 40);
}

void TestDecisionRing() {
  // Bounded drop-oldest audit ring (ISSUE 18): capacity, filters, the
  // n bound, and the eviction join.
  placement::DecisionRing ring(3);
  for (int i = 0; i < 5; i++) {
    placement::DecisionRecord record;
    record.t = 1.0 + i;
    record.outcome = i % 2 == 0 ? "placed" : "rejected";
    record.job = "j-" + std::to_string(i);
    record.node = i % 2 == 0 ? "n-keep" : "";
    record.reason = i % 2 == 0 ? "placed" : "no-candidate";
    ring.Push(std::move(record));
  }
  CHECK_EQ(ring.size(), 3u);
  CHECK_EQ(ring.appended(), 5u);
  CHECK_EQ(ring.dropped(), 2u);
  std::string doc = ring.RenderJson(0, "", "");
  CHECK_TRUE(doc.find("\"capacity\":3") != std::string::npos);
  CHECK_TRUE(doc.find("\"appended\":5") != std::string::npos);
  CHECK_TRUE(doc.find("\"dropped\":2") != std::string::npos);
  CHECK_TRUE(doc.find("\"job\":\"j-0\"") == std::string::npos);  // dropped
  CHECK_TRUE(doc.find("\"job\":\"j-2\"") != std::string::npos);
  CHECK_TRUE(doc.find("\"seq\":4") != std::string::npos);
  // Filters are exact; n bounds the filtered tail.
  doc = ring.RenderJson(0, "j-3", "");
  CHECK_TRUE(doc.find("\"job\":\"j-3\"") != std::string::npos);
  CHECK_TRUE(doc.find("\"job\":\"j-2\"") == std::string::npos);
  doc = ring.RenderJson(1, "", "");
  CHECK_TRUE(doc.find("\"seq\":4") != std::string::npos);
  CHECK_TRUE(doc.find("\"seq\":3") == std::string::npos);
  doc = ring.RenderJson(0, "", "n-keep");
  CHECK_TRUE(doc.find("\"seq\":2") != std::string::npos);
  CHECK_TRUE(doc.find("\"seq\":3") == std::string::npos);

  // Eviction joins the placements the transition invalidated: the
  // placed decisions naming the node since its last eviction, oldest
  // first, carrying the change-id of the evicting write.
  placement::DecisionRing ring2(16);
  for (int i = 0; i < 2; i++) {
    placement::DecisionRecord record;
    record.outcome = "placed";
    record.job = "ej-" + std::to_string(i);
    record.node = "ev-node";
    ring2.Push(std::move(record));
  }
  CHECK_TRUE(!ring2.EvictNode("other-node", "deleted", "", 9.0));
  CHECK_TRUE(ring2.EvictNode("ev-node", "perf-degraded", "ch-evict", 9.0));
  doc = ring2.RenderJson(0, "", "ev-node");
  CHECK_TRUE(doc.find("\"outcome\":\"evicted\"") != std::string::npos);
  CHECK_TRUE(doc.find("\"reason\":\"perf-degraded\"") != std::string::npos);
  CHECK_TRUE(doc.find("\"jobs\":[\"ej-0\",\"ej-1\"]") != std::string::npos);
  CHECK_TRUE(doc.find("\"change_ids\":[\"ch-evict\"]") != std::string::npos);
  // The eviction closed those placements: a second transition has
  // nothing left to close.
  CHECK_TRUE(!ring2.EvictNode("ev-node", "deleted", "", 10.0));
  // A job filter matches evicted records through their jobs list.
  doc = ring2.RenderJson(0, "ej-1", "");
  CHECK_TRUE(doc.find("\"outcome\":\"evicted\"") != std::string::npos);
}

}  // namespace
}  // namespace tfd

int main(int argc, char** argv) {
  // Exposition-checker mode for CI's metrics-lint step: validate a scraped
  // /metrics document with the same checker the unit tests assert with.
  if (argc == 3 && std::string(argv[1]) == "--validate-exposition") {
    std::ifstream in(argv[2]);
    if (!in) {
      std::cerr << "cannot open " << argv[2] << std::endl;
      return 2;
    }
    std::string text((std::istreambuf_iterator<char>(in)),
                     std::istreambuf_iterator<char>());
    tfd::Status s = tfd::obs::ValidateExposition(text);
    if (!s.ok()) {
      std::cerr << "invalid exposition: " << s.message() << std::endl;
      return 1;
    }
    std::cerr << "exposition OK (" << text.size() << " bytes)" << std::endl;
    return 0;
  }
  tfd::TestStrings();
  tfd::TestYamlLite();
  tfd::TestShapeGrammar();
  tfd::TestFamilyTable();
  tfd::TestIciWrap();
  tfd::TestParserRobustness();
  tfd::TestDuration();
  tfd::TestConfigPrecedence();
  tfd::TestResourceLabelsNone();
  tfd::TestResourceLabelsSingle();
  tfd::TestResourceLabelsMixed();
  tfd::TestInvalidSliceDegradation();
  tfd::TestSharing();
  tfd::TestClientOptionParsing();
  tfd::TestSharingDevicesSelector();
  tfd::TestNullManager();
  tfd::TestPlatformDetect();
  tfd::TestFallbackDecorator();
  tfd::TestFallbackChain();
  tfd::TestBoolParsing();
  tfd::TestTpuEnvParse();
  tfd::TestLabelFormatting();
  tfd::TestAtomicWrite();
  tfd::TestUrlParsing();
  tfd::TestJsonNonFiniteSerialization();
  tfd::TestGkeIdentity();
  tfd::TestForkedCapture();
  tfd::TestMetadataErrorKinds();
  tfd::TestMetricsRegistry();
  tfd::TestMetricsEscaping();
  tfd::TestMetricsHistogram();
  tfd::TestValidateExposition();
  tfd::TestMetricsExemplars();
  tfd::TestListenAddrParse();
  tfd::TestIntrospectionServer();
  tfd::TestReadyzAllExpired();
  tfd::TestSnapshotTierTransitions();
  tfd::TestBackoffJitterBounds();
  tfd::TestProbeBrokerOneRound();
  tfd::TestProbeBrokerWorkers();
  tfd::TestBackendCandidatesList();
  tfd::TestJournalCapacityDropOrdering();
  tfd::TestJournalGenerationCorrelation();
  tfd::TestTraceRecorderLifecycle();
  tfd::TestTraceRecorderGoldenParity();
  tfd::TestStageSloGoldenParity();
  tfd::TestStageDurationsMs();
  tfd::TestJournalChangeCorrelation();
  tfd::TestDebugTraceEndpoint();
  tfd::TestVerdictChangeEcho();
  tfd::TestChangeAnnotationBodies();
  tfd::TestSanitizeUtf8();
  tfd::TestJournalJsonHostileBytes();
  tfd::TestLabelDiff();
  tfd::TestLabelKeyPrefix();
  tfd::TestLogFormatLine();
  tfd::TestDebugEndpoints();
  tfd::TestFaultSpecParse();
  tfd::TestFaultSinkFile();
  tfd::TestCircuitBreaker();
  tfd::TestSnapshotFingerprintIgnoresMeasurements();
  tfd::TestFullSnapshotFingerprint();
  tfd::TestSnapshotStoreGenerations();
  tfd::TestPassSignature();
  tfd::TestFormatLabelsInto();
  tfd::TestTouchLabelFile();
  tfd::TestFragmentCacheTpuBuildOnce();
  tfd::TestFragmentCacheHostFragment();
  tfd::TestGovernorPendingSuppressions();
  tfd::TestHealthStateMachineTransitions();
  tfd::TestHealthStateMachineDebounceBoundaries();
  tfd::TestHealthStateMachineFlapQuarantine();
  tfd::TestHealthStateMachineContentFlapQuarantine();
  tfd::TestHealthStateMachineWindowExpiry();
  tfd::TestHealthStateMachineMinThresholdRecovery();
  tfd::TestHealthStateMachineGhostRelease();
  tfd::TestHealthStateMachineReloadPreservesState();
  tfd::TestHealthStateMachineSerializeRestore();
  tfd::TestHealthStateMachineFaultPoint();
  tfd::TestLabelGovernorHoldDown();
  tfd::TestLabelGovernorRemovalAndReadd();
  tfd::TestLabelGovernorMonotoneExemptions();
  tfd::TestLabelGovernorSliceInvalidRecovery();
  tfd::TestLabelGovernorChurnBudgetAndCommit();
  tfd::TestStateRoundTrip();
  tfd::TestRenameErrorDeviceIds();
  tfd::TestHttpDeadlineBudget();
  tfd::TestK8sFaultClassification();
  tfd::TestDesyncMath();
  tfd::TestBuildMergePatch();
  tfd::TestSinkPatchFlow();
  tfd::TestSinkPatchConflictReGet();
  tfd::TestSinkPatchFallbacks();
  tfd::TestSinkConflictExhaustion();
  tfd::TestSinkRetryAfterAndDefer();
  tfd::TestHttpResponseHeaders();
  tfd::TestPerfClassificationGrid();
  tfd::TestPerfRatedSpecs();
  tfd::TestPerfSerializeRoundTrip();
  tfd::TestPerfExecParse();
  tfd::TestPerfDutyCycle();
  tfd::TestPerfLabels();
  tfd::TestPerfStateSectionIndependence();
  tfd::TestGovernorPerfClassDemotion();
  tfd::TestHealthsmClassRankDebounce();
  tfd::TestSliceIdentityDerivation();
  tfd::TestSliceDocSerialization();
  tfd::TestSliceVerdictMerge();
  tfd::TestSliceLeaseStateMachine();
  tfd::TestSliceOrphanAndRejoin();
  tfd::TestSliceCoordSerializeRestore();
  tfd::TestSliceRelayConfirmOrRelay();
  tfd::TestSliceReportSurfaceWaitFreeUnderTick();
  tfd::TestSliceSuccession();
  tfd::TestSliceAsymmetricPartition();
  tfd::TestSliceHedgedPublish();
  tfd::TestGovernorSliceKeys();
  tfd::TestPluginHandshakeGrid();
  tfd::TestPluginRoundValidationGrid();
  tfd::TestPluginConfAndSchedule();
  tfd::TestPluginDiscovery();
  tfd::TestPluginRoundContainment();
  tfd::TestHealthsmFlapEvidence();
  tfd::TestSliceRejoinDwell();
  tfd::TestRequestStreamChunked();
  tfd::TestWatchEventParse();
  tfd::TestSinkApplyLadder();
  tfd::TestWatcherResyncAndDrift();
  tfd::TestWakeupMux();
  tfd::TestSnapshotMovementNotify();
  tfd::TestAggSketchParity();
  tfd::TestSloSerializationParity();
  tfd::TestSloBudgetsFromSpec();
  tfd::TestBurnEvaluatorParity();
  tfd::TestAggIncrementalRollups();
  tfd::TestAggFlushController();
  tfd::TestAggWatchEventName();
  tfd::TestAggShardIndexOf();
  tfd::TestAggPartialLabelsRoundtrip();
  tfd::TestAggShardMergeTree();
  tfd::TestPlacementIndexContract();
  tfd::TestPlacementProtocol();
  tfd::TestPlacementExplain();
  tfd::TestDecisionRing();
  tfd::TestPerfFleetFloor();
  tfd::TestSlicePreemptingMember();
  tfd::TestGetNodeDraining();
  tfd::TestRemedyEligibilityPrimitives();
  tfd::TestRemedyBackoffAndHeal();
  tfd::TestRemedyParityGolden();
  tfd::TestPatchNodeUnschedulable();

  std::cerr << tfd::g_checks << " checks, " << tfd::g_failures << " failures"
            << std::endl;
  return tfd::g_failures == 0 ? 0 : 1;
}

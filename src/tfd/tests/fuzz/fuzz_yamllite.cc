// libFuzzer target for the hand-rolled YAML subset parser — the
// config-file attack surface (an operator-supplied file reaches
// yamllite::Parse before any validation). Built with clang's
// -fsanitize=fuzzer in the sanitizer CI job; under gcc the standalone
// driver (standalone_driver.cc) replays the seed corpus + deterministic
// mutations, so `ninja fuzzers` works everywhere.
//
// Reference anchor: GFD's config surface is fuzzed implicitly through
// sigs.k8s.io/yaml's own fuzzers; a hand-rolled parser must bring its own.
#include <cstddef>
#include <cstdint>
#include <string>

#include "tfd/config/yamllite.h"

extern "C" int LLVMFuzzerTestOneInput(const uint8_t* data, size_t size) {
  std::string text(reinterpret_cast<const char*>(data), size);
  auto doc = tfd::yamllite::Parse(text);
  if (doc.ok()) {
    // Walk the tree the way config.cc does: lookups + scalar coercions
    // must be safe on anything that parsed.
    const tfd::yamllite::Node& root = **doc;
    for (const auto& [key, child] : root.map_items) {
      (void)child->AsString();
      (void)child->AsInt();
      (void)child->AsBool();
      (void)child->IsNull();
      for (const auto& item : child->list_items) {
        (void)item->AsString();
      }
    }
  }
  return 0;
}

// libFuzzer target for the hand-rolled JSON parser — the watchdog
// probe-pipe surface (the parent parses whatever the killed-or-crashed
// probe child managed to write) and the k8s apiserver response surface.
// See fuzz_yamllite.cc for the engine/driver arrangement.
#include <cstddef>
#include <cstdint>
#include <string>

#include "tfd/util/jsonlite.h"

extern "C" int LLVMFuzzerTestOneInput(const uint8_t* data, size_t size) {
  std::string text(reinterpret_cast<const char*>(data), size);
  auto doc = tfd::jsonlite::Parse(text);
  if (doc.ok()) {
    // Anything that parsed must round-trip through the serializer (the
    // NodeFeature CR writer) and survive the lookups the watchdog does.
    (void)tfd::jsonlite::Serialize(**doc);
    (void)(*doc)->Get("devices");
    (void)(*doc)->GetPath("metadata.resourceVersion");
  }
  return 0;
}

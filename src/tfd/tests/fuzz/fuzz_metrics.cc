// libFuzzer target for the metrics registry + exposition path
// (src/tfd/obs/metrics.cc). The input is interpreted as a little metric
// program — one instrument op per line, `kind;name;label-key;label-val;
// value` — driven against a fresh Registry; the oracle is the registry's
// own contract: whatever hostile names/labels/values went in, Exposition()
// must render VALID Prometheus text (ValidateExposition, the same checker
// the unit tests and the CI metrics-lint step run). See fuzz_yamllite.cc
// for the engine/driver arrangement.
#include <cstddef>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <string>
#include <vector>

#include "tfd/obs/metrics.h"

namespace {

std::vector<std::string> SplitLine(const std::string& line) {
  std::vector<std::string> fields;
  size_t start = 0;
  while (fields.size() < 4) {
    size_t semi = line.find(';', start);
    if (semi == std::string::npos) break;
    fields.push_back(line.substr(start, semi - start));
    start = semi + 1;
  }
  fields.push_back(line.substr(start));
  return fields;
}

}  // namespace

extern "C" int LLVMFuzzerTestOneInput(const uint8_t* data, size_t size) {
  std::string text(reinterpret_cast<const char*>(data), size);
  tfd::obs::Registry registry;

  size_t pos = 0;
  int ops = 0;
  while (pos < text.size() && ops < 256) {
    size_t eol = text.find('\n', pos);
    if (eol == std::string::npos) eol = text.size();
    std::string line = text.substr(pos, eol - pos);
    pos = eol + 1;
    if (line.empty()) continue;
    ops++;

    std::vector<std::string> f = SplitLine(line);
    char kind = f[0].empty() ? 'c' : f[0][0];
    std::string name = f.size() > 1 ? f[1] : "m";
    tfd::obs::Labels labels;
    if (f.size() > 3 && !f[2].empty()) labels.push_back({f[2], f[3]});
    double value = f.size() > 4 ? std::strtod(f[4].c_str(), nullptr) : 1.0;

    switch (kind) {
      case 'g':
        registry.GetGauge(name, "fuzzed gauge " + name, labels)->Set(value);
        break;
      case 'h': {
        // Bucket bounds derived from the value keep the shape diverse
        // (including degenerate negative/duplicate bounds).
        std::vector<double> bounds = {value, value * 2, 1.0, 1.0, -value};
        registry.GetHistogram(name, "fuzzed histogram " + name, bounds,
                              labels)->Observe(value);
        break;
      }
      default:
        registry.GetCounter(name, "fuzzed counter " + name, labels)
            ->Inc(value);
        break;
    }
  }

  std::string exposition = registry.Exposition();
  tfd::Status valid = tfd::obs::ValidateExposition(exposition);
  if (!valid.ok()) {
    fprintf(stderr, "registry rendered invalid exposition: %s\n---\n%s---\n",
            valid.message().c_str(), exposition.c_str());
    abort();
  }
  return 0;
}

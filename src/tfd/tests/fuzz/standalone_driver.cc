// Standalone driver for the fuzz targets when libFuzzer is unavailable
// (gcc builds; libFuzzer ships with clang only). Replays every corpus
// file given on the command line, then feeds deterministic mutations of
// each seed — byte flips, truncations, splices — so `ninja fuzzers` plus
// the corpus gives a meaningful (if shallow) regression sweep under
// ASan/UBSan on any toolchain. With clang, CMake links the real engine
// and this file is not compiled in.
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <fstream>
#include <string>
#include <vector>

extern "C" int LLVMFuzzerTestOneInput(const uint8_t* data, size_t size);

namespace {

// xorshift — deterministic across runs and platforms (no std::rand).
uint64_t g_state = 0x9e3779b97f4a7c15ull;
uint64_t NextRand() {
  g_state ^= g_state << 13;
  g_state ^= g_state >> 7;
  g_state ^= g_state << 17;
  return g_state;
}

void Run(const std::string& input) {
  LLVMFuzzerTestOneInput(
      reinterpret_cast<const uint8_t*>(input.data()), input.size());
}

}  // namespace

int main(int argc, char** argv) {
  int mutations = 256;  // per seed; override with FUZZ_MUTATIONS
  if (const char* env = getenv("FUZZ_MUTATIONS")) mutations = atoi(env);

  std::vector<std::string> seeds;
  for (int i = 1; i < argc; i++) {
    std::ifstream in(argv[i], std::ios::binary);
    if (!in) {
      fprintf(stderr, "cannot read seed %s\n", argv[i]);
      return 2;
    }
    seeds.emplace_back(std::istreambuf_iterator<char>(in),
                       std::istreambuf_iterator<char>());
  }
  if (seeds.empty()) seeds.push_back("");

  long executions = 0;
  for (const std::string& seed : seeds) {
    Run(seed);
    executions++;
    for (int m = 0; m < mutations; m++) {
      std::string mutated = seed;
      switch (NextRand() % 4) {
        case 0:  // byte flip(s)
          for (int k = 0; k < 4 && !mutated.empty(); k++) {
            mutated[NextRand() % mutated.size()] =
                static_cast<char>(NextRand());
          }
          break;
        case 1:  // truncate
          if (!mutated.empty()) mutated.resize(NextRand() % mutated.size());
          break;
        case 2:  // splice with another seed
          mutated += seeds[NextRand() % seeds.size()];
          break;
        case 3:  // insert random run
          mutated.insert(mutated.empty() ? 0 : NextRand() % mutated.size(),
                         std::string(NextRand() % 64, '\xff'));
          break;
      }
      Run(mutated);
      executions++;
    }
  }
  printf("standalone fuzz sweep: %ld executions over %zu seeds OK\n",
         executions, seeds.size());
  return 0;
}

// libFuzzer target for the flight recorder's JSON exposition
// (/debug/journal): hostile event payloads — huge label values, embedded
// quotes/newlines, non-UTF8 bytes — must never produce output the JSON
// grammar (our own jsonlite parser as the oracle) rejects, and the ring
// buffer must stay bounded under any append pattern. See
// fuzz_yamllite.cc for the engine/driver arrangement.
#include <cstddef>
#include <cstdint>
#include <string>

#include "tfd/obs/journal.h"
#include "tfd/util/jsonlite.h"

extern "C" int LLVMFuzzerTestOneInput(const uint8_t* data, size_t size) {
  std::string text(reinterpret_cast<const char*>(data), size);
  // Metrics disabled: hostile event types must not grow the process
  // registry across iterations (the journal itself is the target).
  tfd::obs::Journal journal(/*capacity=*/8, /*metrics=*/false);
  size_t third = text.size() / 3;
  std::string type = text.substr(0, third);
  std::string source = text.substr(third, third);
  std::string rest = text.substr(2 * third);
  journal.BeginRewrite();
  journal.Record(type, source, rest, {{rest, text}, {"value", type}});
  journal.Record("label-diff", source, text,
                 {{"key", text}, {"old", rest}, {"new", type}});
  for (int i = 0; i < 12; i++) journal.Record(type, source, rest);

  // Whatever the payload, the rendered document must be valid JSON
  // (this is exactly what /debug/journal serves), valid UTF-8 (strict
  // consumers like Python json.load must decode it — SanitizeUtf8 is
  // idempotent, so sanitizing an already-clean document is identity),
  // the ring bounded, and the filtered render valid too.
  std::string json = journal.RenderJson();
  auto doc = tfd::jsonlite::Parse(json);
  if (!doc.ok()) __builtin_trap();
  if (tfd::jsonlite::SanitizeUtf8(json) != json) __builtin_trap();
  if (journal.Snapshot().size() > journal.capacity()) __builtin_trap();
  auto filtered = tfd::jsonlite::Parse(journal.RenderJson(2, type));
  if (!filtered.ok()) __builtin_trap();
  (void)tfd::obs::EventJson(journal.Snapshot(1).front());
  return 0;
}

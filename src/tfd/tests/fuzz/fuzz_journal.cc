// libFuzzer target for the flight recorder's JSON exposition
// (/debug/journal) AND the causal-trace recorder's (/debug/trace + the
// Perfetto dump): hostile event payloads and trace stage names — huge
// values, embedded quotes/newlines, non-UTF8 bytes — must never
// produce output the JSON grammar (our own jsonlite parser as the
// oracle) rejects, and both ring buffers must stay bounded under any
// append pattern. See fuzz_yamllite.cc for the engine/driver
// arrangement.
#include <cstddef>
#include <cstdint>
#include <string>

#include "tfd/obs/journal.h"
#include "tfd/obs/trace.h"
#include "tfd/util/jsonlite.h"

extern "C" int LLVMFuzzerTestOneInput(const uint8_t* data, size_t size) {
  std::string text(reinterpret_cast<const char*>(data), size);
  // Metrics disabled: hostile event types must not grow the process
  // registry across iterations (the journal itself is the target).
  tfd::obs::Journal journal(/*capacity=*/8, /*metrics=*/false);
  size_t third = text.size() / 3;
  std::string type = text.substr(0, third);
  std::string source = text.substr(third, third);
  std::string rest = text.substr(2 * third);
  journal.BeginRewrite();
  journal.Record(type, source, rest, {{rest, text}, {"value", type}});
  journal.Record("label-diff", source, text,
                 {{"key", text}, {"old", rest}, {"new", type}});
  for (int i = 0; i < 12; i++) journal.Record(type, source, rest);

  // Whatever the payload, the rendered document must be valid JSON
  // (this is exactly what /debug/journal serves), valid UTF-8 (strict
  // consumers like Python json.load must decode it — SanitizeUtf8 is
  // idempotent, so sanitizing an already-clean document is identity),
  // the ring bounded, and the filtered render valid too.
  std::string json = journal.RenderJson();
  auto doc = tfd::jsonlite::Parse(json);
  if (!doc.ok()) __builtin_trap();
  if (tfd::jsonlite::SanitizeUtf8(json) != json) __builtin_trap();
  if (journal.Snapshot().size() > journal.capacity()) __builtin_trap();
  auto filtered = tfd::jsonlite::Parse(journal.RenderJson(2, type));
  if (!filtered.ok()) __builtin_trap();
  (void)tfd::obs::EventJson(journal.Snapshot(1).front());

  // The causal-trace recorder under the same hostile bytes: origins,
  // sources, details, and — the ISSUE 15 satellite — STAGE NAMES all
  // carry attacker-influenced content (a probe error string becomes a
  // mint detail; a plugin could try to smuggle bytes into a stage).
  // Both renderings must stay valid strict-UTF-8 JSON and the ring
  // bounded.
  tfd::obs::TraceRecorder trace(/*capacity=*/4, /*metrics=*/false);
  trace.Mint(type, source, rest, 1.0);
  trace.Stage(rest, 2.0);
  trace.Stage(text, 3.0);
  trace.MarkPublished(1, 4.0);
  trace.Mint(rest, type, text, 5.0);
  trace.Stage(type, 6.0);
  for (int i = 0; i < 8; i++) trace.Mint(type, source, rest, 7.0 + i);
  std::string trace_json = trace.RenderJson();
  auto trace_doc = tfd::jsonlite::Parse(trace_json);
  if (!trace_doc.ok()) __builtin_trap();
  if (tfd::jsonlite::SanitizeUtf8(trace_json) != trace_json) {
    __builtin_trap();
  }
  if (trace.active() > trace.capacity()) __builtin_trap();
  auto chrome = tfd::jsonlite::Parse(trace.RenderChromeTrace());
  if (!chrome.ok()) __builtin_trap();
  auto trace_filtered = tfd::jsonlite::Parse(trace.RenderJson(2, 1));
  if (!trace_filtered.ok()) __builtin_trap();
  return 0;
}

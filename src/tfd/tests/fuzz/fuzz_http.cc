// libFuzzer target for the HTTP-facing parsers: the raw response parser
// (status line, headers, chunked decoding — fed by whatever a metadata
// server or apiserver sends back), URL parsing, and the tpu-env
// attribute-bag grammar that rides on metadata responses. See
// fuzz_yamllite.cc for the engine/driver arrangement.
#include <cstddef>
#include <cstdint>
#include <string>

#include "tfd/gce/metadata.h"
#include "tfd/util/http.h"

extern "C" int LLVMFuzzerTestOneInput(const uint8_t* data, size_t size) {
  std::string text(reinterpret_cast<const char*>(data), size);
  (void)tfd::http::ParseResponse(text);
  (void)tfd::http::ParseUrl(text);
  (void)tfd::gce::ParseTpuEnv(text);
  return 0;
}

#include "tfd/healthsm/healthsm.h"

#include <algorithm>

#include "tfd/fault/fault.h"
#include "tfd/obs/journal.h"
#include "tfd/obs/metrics.h"
#include "tfd/util/jsonlite.h"
#include "tfd/util/logging.h"
#include "tfd/util/strings.h"

namespace tfd {
namespace healthsm {

namespace {

constexpr const char* kStateNames[] = {"healthy", "suspect", "unhealthy",
                                       "quarantined", "recovering"};

State StateFromName(const std::string& name, bool* ok) {
  *ok = true;
  for (int i = 0; i < 5; i++) {
    if (name == kStateNames[i]) return static_cast<State>(i);
  }
  *ok = false;
  return State::kHealthy;
}

obs::Gauge* StateGauge(const std::string& key) {
  return obs::Default().GetGauge(
      "tfd_health_state",
      "Debounced health state per probe source / chip: 0 healthy, "
      "1 suspect, 2 unhealthy, 3 quarantined (labels held at "
      "last-good), 4 recovering.",
      {{"source", key}});
}

}  // namespace

const char* StateName(State state) {
  return kStateNames[static_cast<int>(state)];
}

int StateGaugeValue(State state) { return static_cast<int>(state); }

std::string ChipKey(const std::string& chip_id) {
  return "health/chip-" + chip_id;
}

HealthTracker::HealthTracker(Policy policy) { Configure(policy); }

void HealthTracker::Configure(Policy policy) {
  std::lock_guard<std::mutex> lock(mu_);
  if (policy.flap_window_s < 1) policy.flap_window_s = 1;
  if (policy.flap_threshold < 2) policy.flap_threshold = 2;
  if (policy.quarantine_cooldown_s < 1) policy.quarantine_cooldown_s = 1;
  if (policy.unhealthy_after < 1) policy.unhealthy_after = 1;
  if (policy.recover_after < 1) policy.recover_after = 1;
  policy_ = policy;
}

Policy HealthTracker::policy() const {
  std::lock_guard<std::mutex> lock(mu_);
  return policy_;
}

void HealthTracker::PruneWindowLocked(Entry* entry, double now_s) const {
  while (!entry->flap_times.empty() &&
         entry->flap_times.front() < now_s - policy_.flap_window_s) {
    entry->flap_times.pop_front();
  }
}

void HealthTracker::NoteFlapLocked(const std::string& key, Entry* entry,
                                   double now_s) {
  entry->flap_times.push_back(now_s);
  PruneWindowLocked(entry, now_s);
  if (entry->state == State::kQuarantined) return;  // already held
  if (static_cast<int>(entry->flap_times.size()) < policy_.flap_threshold) {
    return;
  }
  entry->quarantine_until = now_s + policy_.quarantine_cooldown_s;
  entry->consecutive_clean = 0;
  const size_t flap_count = entry->flap_times.size();
  // The window's events are CONSUMED by the quarantine they caused:
  // otherwise, with a cooldown shorter than the window, the
  // quarantined->recovering exit transition would land in the
  // still-populated window and instantly re-quarantine — recovery
  // could never begin until the whole window drained. Re-quarantining
  // after recovery requires fresh evidence.
  entry->flap_times.clear();
  obs::Default()
      .GetCounter("tfd_quarantines_total",
                  "Keys quarantined by the health state machine "
                  "(flapping past --health-flap-threshold inside "
                  "--health-flap-window).",
                  {{"source", key}})
      ->Inc();
  TransitionLocked(key, entry, State::kQuarantined,
                   std::to_string(flap_count) + " transitions in " +
                       std::to_string(policy_.flap_window_s) +
                       "s; holding last-good labels for " +
                       std::to_string(policy_.quarantine_cooldown_s) + "s",
                   now_s);
}

void HealthTracker::TransitionLocked(const std::string& key, Entry* entry,
                                     State to, const std::string& reason,
                                     double now_s) {
  if (entry->state == to) return;
  const State from_state = entry->state;
  const char* from = StateName(from_state);
  entry->state = to;
  StateGauge(key)->Set(StateGaugeValue(to));
  obs::Default()
      .GetCounter("tfd_health_transitions_total",
                  "Health state-machine transitions.",
                  {{"from", from}, {"to", StateName(to)}})
      ->Inc();
  obs::DefaultJournal().Record(
      "health-transition", key,
      "health " + key + " " + from + " -> " + StateName(to) +
          (reason.empty() ? "" : ": " + reason),
      {{"key", key},
       {"from", from},
       {"to", StateName(to)},
       {"reason", reason}});
  TFD_LOG_WARNING << "health " << key << " " << from << " -> "
                  << StateName(to) << (reason.empty() ? "" : " (" + reason +
                                       ")");
  // The transition itself is a flap event — except entering quarantine
  // (must not feed its own detector) and the earned-recovery edges
  // (quarantine exit, recovery completion): those only happen after
  // the cooldown plus consecutive clean probes, and counting them
  // refills the window they just drained — at the minimum
  // --health-flap-threshold=2 the quarantined -> recovering -> healthy
  // pair alone would re-quarantine a perfectly clean key forever.
  const bool earned_recovery =
      from_state == State::kQuarantined ||
      (from_state == State::kRecovering && to == State::kHealthy);
  if (to != State::kQuarantined && !earned_recovery) {
    NoteFlapLocked(key, entry, now_s);
  }
}

State HealthTracker::Observe(const std::string& key, bool ok,
                             uint64_t fingerprint, double now_s,
                             double interval_s) {
  // Drill hook: an armed `healthsm.transition` fail/errno turns this
  // observation into a failure, driving transitions on demand.
  if (fault::Action injected = fault::Check("healthsm.transition")) {
    if (injected.kind == fault::Action::Kind::kFail ||
        injected.kind == fault::Action::Kind::kErrno) {
      ok = false;
    }
  }
  std::lock_guard<std::mutex> lock(mu_);
  Entry& entry = entries_[key];
  entry.last_observed = now_s;
  if (interval_s > 0) entry.observe_interval_s = interval_s;
  PruneWindowLocked(&entry, now_s);

  // Classify: failure / unstable (success whose content fingerprint
  // moved) / clean.
  bool unstable = false;
  if (ok && fingerprint != 0) {
    unstable = entry.has_fingerprint && fingerprint != entry.last_fingerprint;
    entry.last_fingerprint = fingerprint;
    entry.has_fingerprint = true;
  }
  bool clean = ok && !unstable;

  if (clean) {
    entry.consecutive_failures = 0;
    entry.consecutive_clean++;
    switch (entry.state) {
      case State::kHealthy:
        break;
      case State::kSuspect:
        TransitionLocked(key, &entry, State::kHealthy, "probe clean", now_s);
        break;
      case State::kUnhealthy:
        entry.consecutive_clean = 1;
        entry.from_quarantine = false;
        TransitionLocked(key, &entry, State::kRecovering, "probe clean",
                         now_s);
        break;
      case State::kRecovering:
        if (entry.consecutive_clean >= policy_.recover_after) {
          entry.from_quarantine = false;
          entry.quarantine_until = 0;
          TransitionLocked(key, &entry, State::kHealthy,
                           std::to_string(entry.consecutive_clean) +
                               " consecutive clean probes",
                           now_s);
        }
        break;
      case State::kQuarantined:
        // Recovery must be earned AFTER the cooldown; clean probes
        // during it do not count toward the streak. Past it, the first
        // clean probe starts recovering, and the streak continues there
        // until recover_after consecutive cleans close it healthy.
        if (now_s < entry.quarantine_until) {
          entry.consecutive_clean = 0;
        } else {
          entry.from_quarantine = true;
          TransitionLocked(key, &entry, State::kRecovering,
                           "cooldown elapsed; probe clean", now_s);
        }
        break;
    }
  } else {
    const char* why = ok ? "content changed between successful probes"
                         : "probe failed";
    entry.consecutive_clean = 0;
    entry.consecutive_failures++;
    switch (entry.state) {
      case State::kHealthy:
        entry.consecutive_failures = 1;
        TransitionLocked(key, &entry, State::kSuspect, why, now_s);
        break;
      case State::kSuspect:
        if (entry.consecutive_failures >= policy_.unhealthy_after) {
          TransitionLocked(key, &entry, State::kUnhealthy, why, now_s);
        } else if (unstable) {
          NoteFlapLocked(key, &entry, now_s);
        }
        break;
      case State::kUnhealthy:
        // Staying unhealthy on failures is NOT a flap; repeated
        // instability is.
        if (unstable) NoteFlapLocked(key, &entry, now_s);
        break;
      case State::kRecovering:
        if (entry.from_quarantine) {
          // The documented contract: a failure or content flip midway
          // through an EARNED recovery re-arms the cooldown — the key
          // goes straight back to quarantined (hold + annotation
          // return) instead of dropping to unhealthy, where a fresh
          // threshold of flap evidence would be needed to re-quarantine
          // a source that plainly never stopped flapping.
          entry.quarantine_until = now_s + policy_.quarantine_cooldown_s;
          obs::Default()
              .GetCounter("tfd_quarantines_total",
                          "Keys quarantined by the health state machine "
                          "(flapping past --health-flap-threshold inside "
                          "--health-flap-window).",
                          {{"source", key}})
              ->Inc();
          TransitionLocked(key, &entry, State::kQuarantined,
                           std::string(why) + " during earned recovery; "
                                              "cooldown re-armed",
                           now_s);
        } else {
          TransitionLocked(key, &entry, State::kUnhealthy, why, now_s);
        }
        break;
      case State::kQuarantined:
        // Still misbehaving: re-arm the cooldown.
        entry.quarantine_until = now_s + policy_.quarantine_cooldown_s;
        break;
    }
  }
  StateGauge(key)->Set(StateGaugeValue(entry.state));
  return entry.state;
}

int HealthTracker::ObserveClassRank(const std::string& key, int rank,
                                    const std::string& fingerprint,
                                    double now_s) {
  std::lock_guard<std::mutex> lock(mu_);
  Entry& entry = entries_[key];
  if (entry.published_rank >= 0 && entry.rank_fingerprint != fingerprint) {
    // The history describes different silicon (the rank state can
    // outlive the perf cache — torn perf section, feature toggled off
    // and on — across a hardware swap): void it rather than debounce
    // the new chip's first verdict against the old chip's class.
    entry.published_rank = -1;
    entry.candidate_rank = -1;
    entry.candidate_streak = 0;
  }
  entry.rank_fingerprint = fingerprint;
  if (entry.published_rank < 0) {
    // First characterization: publish immediately — there is no
    // previous class to defend, and withholding the first verdict
    // would leave the node classless for a whole debounce streak.
    entry.published_rank = rank;
    entry.candidate_rank = -1;
    entry.candidate_streak = 0;
    return rank;
  }
  if (rank == entry.published_rank) {
    entry.candidate_rank = -1;  // agreement dissolves any streak
    entry.candidate_streak = 0;
    return entry.published_rank;
  }
  if (rank == entry.candidate_rank) {
    entry.candidate_streak++;
  } else {
    entry.candidate_rank = rank;
    entry.candidate_streak = 1;
  }
  const int needed = rank > entry.published_rank ? policy_.unhealthy_after
                                                 : policy_.recover_after;
  if (entry.candidate_streak < needed) return entry.published_rank;
  entry.published_rank = rank;
  entry.candidate_rank = -1;
  entry.candidate_streak = 0;
  // Deliberately NO NoteFlapLocked here: the published class is part
  // of the source's content fingerprint (snapshot.cc keeps kPerfClass
  // fingerprinted), so the broker's Observe() of the same probe round
  // already registers the change as an unstable observation — one flap
  // event per change. Noting it here too would double-count every
  // legitimate, debounced class move and quarantine the source at
  // HALF the configured threshold.
  return rank;
}

State HealthTracker::NoteFlapEvidence(const std::string& key,
                                      const std::string& reason,
                                      double now_s) {
  std::lock_guard<std::mutex> lock(mu_);
  Entry& entry = entries_[key];
  PruneWindowLocked(&entry, now_s);
  TFD_LOG_WARNING << "health " << key << ": misbehavior evidence ("
                  << reason << "), "
                  << (entry.flap_times.size() + 1) << "/"
                  << policy_.flap_threshold << " in window";
  NoteFlapLocked(key, &entry, now_s);
  StateGauge(key)->Set(StateGaugeValue(entry.state));
  return entry.state;
}

void HealthTracker::ResetClassRank(const std::string& key) {
  std::lock_guard<std::mutex> lock(mu_);
  auto it = entries_.find(key);
  if (it == entries_.end()) return;
  it->second.published_rank = -1;
  it->second.candidate_rank = -1;
  it->second.candidate_streak = 0;
  it->second.rank_fingerprint.clear();
}

State HealthTracker::StateOf(const std::string& key, double now_s) const {
  (void)now_s;
  std::lock_guard<std::mutex> lock(mu_);
  auto it = entries_.find(key);
  return it == entries_.end() ? State::kHealthy : it->second.state;
}

bool HealthTracker::Quarantined(const std::string& key, double now_s) const {
  return StateOf(key, now_s) == State::kQuarantined;
}

std::vector<std::string> HealthTracker::QuarantinedKeys(double now_s) {
  std::lock_guard<std::mutex> lock(mu_);
  std::vector<std::string> out;
  for (auto& [key, entry] : entries_) {
    if (entry.state != State::kQuarantined) continue;
    // Ghost release (see header): the key vanished from the probe
    // stream, so it can never earn recovery — stop holding its labels.
    // The unobserved threshold is max(cooldown, 2x the key's own
    // observation cadence) PLUS a flap window: a quarantined source
    // still probed at the slow cooldown cadence — or a chip line fed
    // only once per hourly health-exec run — must never trip it
    // between ticks.
    const double unobserved_for =
        std::max<double>(policy_.quarantine_cooldown_s,
                         2.0 * entry.observe_interval_s) +
        policy_.flap_window_s;
    if (now_s >= entry.quarantine_until &&
        now_s - entry.last_observed >= unobserved_for) {
      TransitionLocked(key, &entry, State::kRecovering,
                       "cooldown elapsed and key no longer observed; "
                       "releasing hold",
                       now_s);
      continue;
    }
    out.push_back(key);
  }
  return out;
}

std::string HealthTracker::SerializeJson(double now_s) const {
  (void)now_s;
  std::lock_guard<std::mutex> lock(mu_);
  std::string out = "{\"keys\":{";
  bool first = true;
  for (const auto& [key, entry] : entries_) {
    if (!first) out += ",";
    first = false;
    char until[32];
    snprintf(until, sizeof(until), "%.3f", entry.quarantine_until);
    out += jsonlite::Quote(key) + ":{\"state\":" +
           jsonlite::Quote(StateName(entry.state)) + ",\"fails\":" +
           std::to_string(entry.consecutive_failures) + ",\"clean\":" +
           std::to_string(entry.consecutive_clean) + ",\"fp\":\"" +
           HexU64(entry.last_fingerprint) + "\",\"has_fp\":" +
           (entry.has_fingerprint ? "true" : "false") + ",\"fromq\":" +
           (entry.from_quarantine ? "true" : "false") + ",\"iv\":" +
           std::to_string(entry.observe_interval_s) + ",\"rank\":" +
           std::to_string(entry.published_rank) + ",\"cand\":" +
           std::to_string(entry.candidate_rank) + ",\"streak\":" +
           std::to_string(entry.candidate_streak) + ",\"rfp\":" +
           jsonlite::Quote(entry.rank_fingerprint) + ",\"until\":" +
           until + ",\"flaps\":[";
    bool first_flap = true;
    for (double t : entry.flap_times) {
      if (!first_flap) out += ",";
      first_flap = false;
      char buf[32];
      snprintf(buf, sizeof(buf), "%.3f", t);
      out += buf;
    }
    out += "]}";
  }
  return out + "}}";
}

Status HealthTracker::RestoreJson(const std::string& json, double now_s) {
  if (json.empty()) return Status::Ok();
  Result<jsonlite::ValuePtr> parsed = jsonlite::Parse(json);
  if (!parsed.ok()) {
    return Status::Error("health state unparseable: " + parsed.error());
  }
  jsonlite::ValuePtr keys = (*parsed)->Get("keys");
  if (!keys || keys->kind != jsonlite::Value::Kind::kObject) {
    return Status::Error("health state missing keys object");
  }
  std::map<std::string, Entry> restored;
  for (const auto& [key, value] : keys->object_items) {
    if (value->kind != jsonlite::Value::Kind::kObject) {
      return Status::Error("health state entry '" + key +
                           "' is not an object");
    }
    Entry entry;
    jsonlite::ValuePtr state = value->Get("state");
    if (!state || state->kind != jsonlite::Value::Kind::kString) {
      return Status::Error("health state entry '" + key + "' has no state");
    }
    bool known = false;
    entry.state = StateFromName(state->string_value, &known);
    if (!known) {
      return Status::Error("health state entry '" + key +
                           "' names unknown state '" + state->string_value +
                           "'");
    }
    auto number = [&value](const char* name, double dflt) {
      jsonlite::ValuePtr v = value->Get(name);
      return (v && v->kind == jsonlite::Value::Kind::kNumber)
                 ? v->number_value
                 : dflt;
    };
    entry.consecutive_failures = static_cast<int>(number("fails", 0));
    entry.consecutive_clean = static_cast<int>(number("clean", 0));
    entry.quarantine_until = number("until", 0);
    // Class-rank debounce state (perf class hook): a half-built
    // demotion streak survives a crash instead of granting the chip a
    // fresh debounce budget. Absent fields (pre-PR-9 payloads) default
    // to "no rank tracked".
    entry.published_rank = static_cast<int>(number("rank", -1));
    entry.candidate_rank = static_cast<int>(number("cand", -1));
    entry.candidate_streak = static_cast<int>(number("streak", 0));
    jsonlite::ValuePtr rfp = value->Get("rfp");
    if (rfp && rfp->kind == jsonlite::Value::Kind::kString) {
      entry.rank_fingerprint = rfp->string_value;
    }
    // Restored cadence keeps the ghost-release threshold honest before
    // the first post-restart observation re-declares it: a slow source
    // must not be released as a ghost just because the daemon rebooted.
    entry.observe_interval_s = number("iv", 0);
    // A restored entry gets a fresh observation stamp (not the
    // pre-crash one): the key earns a full flap window to reappear in
    // the probe stream before the ghost release may fire.
    entry.last_observed = now_s;
    jsonlite::ValuePtr fp = value->Get("fp");
    if (fp && fp->kind == jsonlite::Value::Kind::kString) {
      entry.last_fingerprint =
          strtoull(fp->string_value.c_str(), nullptr, 16);
    }
    jsonlite::ValuePtr has_fp = value->Get("has_fp");
    entry.has_fingerprint = has_fp &&
                            has_fp->kind == jsonlite::Value::Kind::kBool &&
                            has_fp->bool_value;
    jsonlite::ValuePtr fromq = value->Get("fromq");
    entry.from_quarantine = fromq &&
                            fromq->kind == jsonlite::Value::Kind::kBool &&
                            fromq->bool_value;
    jsonlite::ValuePtr flaps = value->Get("flaps");
    if (flaps && flaps->kind == jsonlite::Value::Kind::kArray) {
      for (const jsonlite::ValuePtr& t : flaps->array_items) {
        if (t->kind == jsonlite::Value::Kind::kNumber) {
          entry.flap_times.push_back(t->number_value);
        }
      }
    }
    restored[key] = std::move(entry);
  }
  std::lock_guard<std::mutex> lock(mu_);
  entries_ = std::move(restored);
  for (auto& [key, entry] : entries_) {
    PruneWindowLocked(&entry, now_s);
    StateGauge(key)->Set(StateGaugeValue(entry.state));
  }
  return Status::Ok();
}

void HealthTracker::Reset() {
  std::lock_guard<std::mutex> lock(mu_);
  entries_.clear();
}

HealthTracker& Default() {
  static HealthTracker* tracker = new HealthTracker();
  return *tracker;
}

}  // namespace healthsm
}  // namespace tfd

// Debounced per-source health state machine with flap detection and
// chip quarantine.
//
// PR 2's degradation ladder keeps a wedged probe from stalling the
// rewrite cadence, but every probe RESULT still flowed straight into
// labels: a TPU whose health exec alternates ok/fail — a flaky ICI
// link, a thermal throttle, a neighbor briefly holding the exclusive
// chips — flipped `google.com/tpu.health.*` (and the degradation
// markers) on every rewrite, thrashing any scheduler that selects on
// them. The reference's steady-state contract treats label churn as an
// outage of its own; this tracker puts a debounced state machine in
// front of every health-bearing fact:
//
//   healthy -> suspect -> unhealthy -> quarantined -> recovering
//
// One entry per KEY: a probe source ("pjrt", "metadata", "health") fed
// by the broker after every probe, or a chip ("health/chip-<i>") fed
// from the health exec's per-device label lines. Observations are
// classified three ways:
//   - failure   — the probe errored (or an armed `healthsm.transition`
//                 fault forced one);
//   - unstable  — the probe SUCCEEDED but its content fingerprint
//                 changed since the last success (a source whose facts
//                 alternate — 4 chips, then 2, then 4 — is flapping
//                 even though every probe "works");
//   - clean     — success with stable content.
//
// Flap detection: every state transition (except the earned-recovery
// edges — quarantine exit and recovery completion, which are
// hysteresis doing its job) AND every unstable observation lands in a
// per-key sliding window (`--health-flap-window` seconds). `--health-flap-threshold` events inside the window mark the
// key flapping and quarantine it for `--quarantine-cooldown`: the label
// pipeline holds the key's facts at their last-good values (annotated
// `google.com/tpu.health.quarantined=true`), and the broker drops the
// source to the slow quarantine-cooldown re-probe cadence. Recovery is
// deliberately earned: after the cooldown elapses, K consecutive clean
// probes (K = recover_after, default 3) walk quarantined -> recovering
// -> healthy; any failure or unstable observation mid-recovery re-arms
// the cooldown.
//
// Every transition is journaled ("health-transition") and counted
// (tfd_health_transitions_total{from,to}); the per-key state is gauged
// (tfd_health_state{source}: 0 healthy, 1 suspect, 2 unhealthy,
// 3 quarantined, 4 recovering) and quarantine entries counted
// (tfd_quarantines_total{source}). SIGHUP reloads Reconfigure() the
// thresholds without resetting state — the silicon's health did not
// change because our config did — and the whole tracker serializes
// into the warm-restart state file (sched/state.h), so a quarantine
// survives kill -9: a crash must not launder a flapping chip back to
// trusted.
//
// Time is caller-supplied unix wall seconds (WallClockSeconds() in the
// daemon, synthetic values in tests — no sleeps needed to cross a
// window), which is also what lets deadlines round-trip through the
// state file.
#pragma once

#include <cstdint>
#include <deque>
#include <map>
#include <mutex>
#include <string>
#include <vector>

#include "tfd/util/status.h"

namespace tfd {
namespace healthsm {

enum class State { kHealthy, kSuspect, kUnhealthy, kQuarantined, kRecovering };

const char* StateName(State state);
// The tfd_health_state gauge encoding (0..4, order above).
int StateGaugeValue(State state);

struct Policy {
  // Sliding window for flap counting (--health-flap-window).
  int flap_window_s = 300;
  // Transitions/unstable observations inside the window that mark the
  // key flapping and quarantine it (--health-flap-threshold).
  int flap_threshold = 6;
  // How long a quarantined key stays held before recovery may begin;
  // also the slow re-probe cadence the broker drops the source to
  // (--quarantine-cooldown).
  int quarantine_cooldown_s = 600;
  // Consecutive failures that harden suspect into unhealthy.
  int unhealthy_after = 2;
  // Consecutive clean probes that close recovering back to healthy
  // (and, after the cooldown, walk quarantined out).
  int recover_after = 3;
};

// Key under which a health-exec per-device line is tracked
// ("health/chip-<id>").
std::string ChipKey(const std::string& chip_id);

class HealthTracker {
 public:
  explicit HealthTracker(Policy policy = Policy());

  // SIGHUP reload: thresholds change, per-key state survives (like the
  // sink breaker's Configure).
  void Configure(Policy policy);
  Policy policy() const;

  // Feeds one observation for `key`. `ok` is the probe verdict;
  // `fingerprint` hashes the successful result's content (0 = no
  // fingerprint: only ok/fail is classified); `interval_s` is the
  // cadence the caller will observe this key at next (0 = unknown),
  // which scales the ghost-release threshold so a slow source (the
  // hourly health exec and its chip lines) is never mistaken for a
  // vanished one. Returns the post-observation state. Fault point
  // "healthsm.transition": an armed fail/errno action forces this
  // observation to a failure.
  State Observe(const std::string& key, bool ok, uint64_t fingerprint,
                double now_s, double interval_s = 0);

  // Perf class-demotion hook: rank transitions routed through the
  // ladder's debounce policy. `rank` is this measurement round's RAW
  // class (perf::kRankGold..kRankDegraded, larger = worse); the return
  // value is the rank the caller may PUBLISH. A demotion (rank above
  // the published one) must repeat for `unhealthy_after` consecutive
  // observations before it lands — one thermal blip never moves the
  // class — and a promotion must repeat for `recover_after` (recovery
  // is earned, mirroring the quarantine exit). Flap accounting for
  // published changes rides the NORMAL content-fingerprint path (the
  // class participates in the source's flap fingerprint, so the
  // broker's Observe() of the same round registers one unstable
  // observation per change — this method adds none of its own, or
  // every change would double-count and quarantine at half the
  // threshold); a class that churns past --health-flap-threshold
  // therefore still quarantines the source. Rank state rides the same
  // Entry as Observe()'s and serializes with it, so a half-built
  // demotion streak survives kill -9 instead of resetting.
  // `fingerprint` names the hardware identity the observation
  // describes: rank history self-invalidates when it changes, because
  // debouncing NEW silicon's first verdict against OLD silicon's
  // published class (possible when the rank state outlives the perf
  // cache — a torn perf section, a disabled-then-re-enabled feature —
  // across a hardware swap) would pin a replaced chip's class on its
  // healthy successor for recover_after slow rechecks.
  int ObserveClassRank(const std::string& key, int rank,
                       const std::string& fingerprint, double now_s);
  // Forgets the key's rank history (hardware-identity fingerprint
  // changed: the next rank observation describes DIFFERENT silicon and
  // publishes immediately instead of debouncing against the old
  // chip's class).
  void ResetClassRank(const std::string& key);

  // Extra flap evidence from OUTSIDE the probe-verdict stream — the
  // plugin supervisor's containment hook (plugin/plugin.cc). Observe()
  // only notes flaps on state TRANSITIONS and content instability, so
  // a plugin that fails the same way every round (crash loop, garbage
  // output) parks in `unhealthy` and never reaches quarantine, and a
  // plugin whose rounds SUCCEED minus dropped namespace violations
  // looks perfectly clean. Each misbehaving round calls this once:
  // --health-flap-threshold misbehaviors inside --health-flap-window
  // quarantine the key exactly like transition-sourced evidence (same
  // window, same counters, same journal). `reason` rides the log line.
  // Returns the post-evidence state.
  State NoteFlapEvidence(const std::string& key, const std::string& reason,
                         double now_s);

  State StateOf(const std::string& key, double now_s) const;
  bool Quarantined(const std::string& key, double now_s) const;
  // Keys currently quarantined, in key order. Also releases ghost
  // quarantines: a quarantined key that stopped being observed (chip
  // replaced/renumbered, exec's device list shrank) can never earn the
  // clean-probe recovery, so once the cooldown has elapsed AND no
  // observation has arrived for max(cooldown, 2x the key's own
  // observation cadence) plus a flap window (a still-probed key never
  // goes quiet that long — the 2x covers one missed tick of even the
  // hourly health exec), the key transitions to recovering and its
  // hold ends — otherwise a dead chip's label and the quarantined=true
  // annotation would be pinned forever.
  std::vector<std::string> QuarantinedKeys(double now_s);

  // Warm-restart round trip (rides inside sched::PersistedState).
  // Serialization is a JSON object; Restore tolerates an empty string
  // (nothing persisted) and errors on garbage without touching state.
  std::string SerializeJson(double now_s) const;
  Status RestoreJson(const std::string& json, double now_s);

  // Test hook: drops every entry (a fresh tracker without rebuilding
  // the process-global one).
  void Reset();

 private:
  struct Entry {
    State state = State::kHealthy;
    int consecutive_failures = 0;
    int consecutive_clean = 0;
    uint64_t last_fingerprint = 0;
    bool has_fingerprint = false;
    double quarantine_until = 0;     // wall time; meaningful when quarantined
    // The current recovering spell exits a quarantine: a failure or
    // content flip mid-recovery re-arms the cooldown (straight back to
    // quarantined) instead of falling to unhealthy.
    bool from_quarantine = false;
    double last_observed = 0;        // wall time of the latest Observe()
    double observe_interval_s = 0;   // caller-declared cadence (0 unknown)
    std::deque<double> flap_times;   // transition/unstable wall times
    // Class-rank debounce (ObserveClassRank): the published rank, the
    // candidate streak working toward replacing it (-1: none), and
    // the hardware-identity fingerprint the history describes (a
    // mismatch voids the history).
    int published_rank = -1;
    int candidate_rank = -1;
    int candidate_streak = 0;
    std::string rank_fingerprint;
  };

  void TransitionLocked(const std::string& key, Entry* entry, State to,
                        const std::string& reason, double now_s);
  void NoteFlapLocked(const std::string& key, Entry* entry, double now_s);
  void PruneWindowLocked(Entry* entry, double now_s) const;

  mutable std::mutex mu_;
  Policy policy_;
  std::map<std::string, Entry> entries_;
};

// The process-wide tracker (the analogue of obs::Default()): survives
// SIGHUP reloads, shared by the broker workers and the rewrite loop.
HealthTracker& Default();

}  // namespace healthsm
}  // namespace tfd

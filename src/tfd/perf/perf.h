// Cached perf-characterization source: measured tpu.perf.* class labels
// with amortized micro-benchmarks.
//
// Schedulers select on `tpu.product`, but what they actually want is
// what this node can SUSTAIN: a chip that enumerates cleanly yet
// delivers half its rated matmul throughput is exactly the node a
// latency-critical serving workload must avoid. This subsystem extends
// the burn-in/matmul probe discipline (tpufd/health.py, bench.py
// pct_of_rated) into a first-class probe source that publishes
//
//   google.com/tpu.perf.matmul-tflops   measured bf16 MXU throughput
//   google.com/tpu.perf.hbm-gbps        measured HBM stream bandwidth
//   google.com/tpu.perf.ici-gbps        measured ICI all-reduce bw
//   google.com/tpu.perf.pct-of-rated    matmul as % of the family peak
//   google.com/tpu.perf.class           gold | silver | degraded
//
// The perf discipline is AMORTIZATION: measurement must cost ~zero in
// steady state. Characterize once (the `--perf-exec` micro-benchmarks,
// device-exclusive via the broker's serialization), persist the result
// in the warm-restart state file (own schema section with its OWN
// checksum, so a torn perf section is rejected without discarding the
// label payload), and on every later boot restore it in milliseconds
// with zero re-measurement. The cached characterization is invalidated
// ONLY by a hardware-identity fingerprint change (family / chip count /
// topology / libtpu version) — never by time alone; re-VERIFICATION
// runs on the slow `--perf-recheck-interval` cadence, and every
// measurement pass is additionally bounded by `--perf-duty-cycle-pct`:
// after a measurement that took D seconds, the next one may not start
// for D * (100/pct - 1) seconds, so characterization can never consume
// more than pct% of wall-clock TPU time no matter how often something
// asks for it.
//
// Classification (mirrored bit-for-bit by tpufd/perfmodel.py — the
// parity tests pin the two against each other):
//   gold      matmul >= 90% of rated AND hbm >= 70% of rated
//             (healthy silicon: the MXU probe reaches ~95%+ of rated,
//             the HBM stream 75-90% — see tpufd/health.py's measured
//             band notes);
//   degraded  matmul < 50% OR hbm < 50% (the DEGRADED_PCT floor:
//             genuinely sick silicon, never normal stream efficiency);
//   silver    everything between.
// A 3-point hysteresis margin is applied against the PREVIOUS class so
// a chip sitting on a boundary cannot flap, and the published class is
// additionally debounced through the healthsm ladder
// (HealthTracker::ObserveClassRank): a demotion needs
// `unhealthy_after` consecutive measurements to agree, a promotion
// `recover_after` — a thermally-throttling chip therefore DEMOTES its
// class once instead of flapping it, and repeated published-class
// churn feeds the source's flap window like any other instability.
#pragma once

#include <map>
#include <mutex>
#include <optional>
#include <string>
#include <vector>

#include "tfd/util/status.h"

namespace tfd {
namespace perf {

inline constexpr int kPerfSchema = 1;

// Class ranks order by desirability; larger = worse. The governor's
// demotion bypass and the healthsm debounce both compare ranks.
inline constexpr int kRankGold = 0;
inline constexpr int kRankSilver = 1;
inline constexpr int kRankDegraded = 2;

// Threshold constants, mirrored by tpufd/perfmodel.py (parity-pinned).
inline constexpr double kGoldMatmulPct = 90.0;
inline constexpr double kGoldHbmPct = 70.0;
inline constexpr double kDegradedPct = 50.0;
inline constexpr double kHysteresisPct = 3.0;

const char* ClassName(int rank);               // "gold"|"silver"|"degraded"
int ClassRankFromName(const std::string& name);  // -1 unknown

// Per-family rated peaks (bf16 TFLOP/s, HBM GB/s) from Google's
// published Cloud TPU system-architecture tables. The baked table must
// match the checked-in tpufd/rated_specs.json byte-for-value — the
// JSON is the single source of truth both language halves consume
// (tpufd/health.py + tpufd/perfmodel.py load it directly; the C++
// parity test pins this table against it), and `--rated-specs-file`
// lets a deployment override the baked copy without a rebuild.
struct RatedSpec {
  double matmul_tflops = 0;
  double hbm_gbps = 0;
};

const std::map<std::string, RatedSpec>& BakedRatedSpecs();

// Parses a rated_specs.json document:
//   {"families": {"v5e": {"matmul_tflops": 197.0, "hbm_gbps": 819.0}}}
Result<std::map<std::string, RatedSpec>> ParseRatedSpecs(
    const std::string& json_text);

// measured / rated * 100, or -1 when the family (or its rating) is
// unknown — the C++ twin of tpufd.health.pct_of_rated.
double PctOfRated(double measured, double rated);

// Raw classification from the measured percentages (-1 = unknown):
// unknown matmul classifies silver (never vouch gold for an unmeasured
// chip, never condemn it either); unknown hbm leaves only the matmul
// gates. `prev_rank` (-1 = none) applies the hysteresis margin: a
// boundary crossing must clear the threshold by kHysteresisPct in the
// direction of CHANGE, so a chip sitting exactly on a threshold keeps
// its class.
int ClassifyPct(double matmul_pct, double hbm_pct, int prev_rank);

// One completed characterization: the measured numbers, their derived
// context, and the hardware-identity fingerprint they describe.
struct Characterization {
  int schema = kPerfSchema;
  std::string fingerprint;  // family/chips/topology/libtpu
  std::string family;       // "" when unknown (no rated context)
  double measured_at = 0;   // unix wall time the measurement finished
  double measure_seconds = 0;
  double matmul_tflops = -1;  // -1: not measured
  double hbm_gbps = -1;
  double ici_gbps = -1;
  double matmul_pct = -1;  // -1: no rated context
  double hbm_pct = -1;
  int class_rank = kRankSilver;  // the DEBOUNCED published class
};

// Hardware-identity fingerprint: the ONLY thing that invalidates a
// cached characterization. Human-readable on purpose — it is journaled
// as the re-characterization reason.
std::string Fingerprint(const std::string& family, int chip_count,
                        const std::string& topology,
                        const std::string& libtpu_version);

// Serialization for the state-file perf section: a JSON object whose
// "sum" field is an FNV-1a checksum over the canonical field string,
// so a torn/hand-edited perf section fails ITS OWN gate and is
// rejected independently of the (outer-checksummed) label payload.
std::string SerializeCharacterization(const Characterization& c);
Result<Characterization> ParseCharacterization(const std::string& json);

// Parses `--perf-exec` stdout: "matmul-tflops=..." / "hbm-gbps=..." /
// "ici-gbps=..." lines (unknown keys ignored, loudly). Errors when no
// recognized measurement is present.
Result<std::map<std::string, double>> ParseExecOutput(
    const std::string& text);

// The five published labels for one characterization.
std::map<std::string, std::string> BuildLabels(const Characterization& c);

// Fleet-relative perf floor (--perf-fleet-floor-source, ROADMAP #4a):
// the aggregator publishes the fleet's measured p10 floors
// (tpu.fleet.perf.*); a node consuming them classifies `degraded` when
// it measures BELOW its fleet's p10 even while clearing 50%-of-rated —
// the gray-degradation case a static rated-spec table cannot catch.
// Mirrored by tpufd/perfmodel.py (parse_fleet_floor/apply_fleet_floor,
// parity-pinned).
struct FleetFloor {
  double matmul_p10_tflops = -1;  // -1 = no floor published
  double hbm_p10_gbps = -1;
  bool valid() const {
    return matmul_p10_tflops >= 0 || hbm_p10_gbps >= 0;
  }
};

// Parses the floor-source document:
//   {"matmul_p10_tflops": 150.0, "hbm_p10_gbps": 600.0}
// (either key optional). Errors on garbage; absent keys stay -1.
Result<FleetFloor> ParseFleetFloor(const std::string& json_text);

// Applies the floor to a raw classification: a measured value below
// either floor demotes to kRankDegraded; everything else passes
// through. A -1 (unmeasured) value never triggers a floor.
int ApplyFleetFloor(int rank, double matmul_tflops, double hbm_gbps,
                    const FleetFloor& floor);

// Duty-cycle gate (pure, unit-tested): may a measurement start now?
// After a measurement of `last_seconds` that ended at `last_end`, the
// next may not start before last_end + last_seconds * (100/pct - 1);
// a never-measured cache is always allowed.
bool MeasureAllowed(double now, double last_end, double last_seconds,
                    int duty_cycle_pct);

// Process-wide characterization cache (the analogue of
// healthsm::Default()): written by the perf probe worker, read by the
// state saver on the rewrite thread, seeded by the warm-restart loader
// before any probe runs. Survives SIGHUP (the silicon did not change
// because our config did).
class Cache {
 public:
  std::optional<Characterization> Get() const;
  void Set(const Characterization& c);
  void Invalidate();  // fingerprint changed: the cached numbers lie

  // Duty-cycle bookkeeping, fed by the probe after every measurement.
  void NoteMeasurement(double end_wall, double seconds);
  bool AllowedNow(double now, int duty_cycle_pct) const;

  // Deferral-episode dedup: true the FIRST time `key` (reason +
  // fingerprint) is noted since the last measurement/restore — the
  // probe retries an owed measurement on a short cadence, and a long
  // duty gap must journal ONE perf-deferred episode, not one per
  // retry tick (hours of 60s ticks would flush the flight recorder).
  bool NoteDeferral(const std::string& key);

  // State-file round trip. Restore tolerates an empty string (nothing
  // persisted — a pre-PR-9 state file) and errors on garbage or a
  // checksum mismatch WITHOUT touching the current state.
  std::string SerializeJson() const;
  Status RestoreJson(const std::string& json);

  void Reset();  // test hook

 private:
  mutable std::mutex mu_;
  std::optional<Characterization> value_;
  double last_measure_end_ = 0;
  double last_measure_seconds_ = 0;
  std::string last_deferral_key_;  // NoteDeferral episode dedup
};

Cache& Default();

}  // namespace perf
}  // namespace tfd

#include "tfd/perf/perf.h"

#include <cstdio>
#include <cstdlib>

#include "tfd/util/jsonlite.h"
#include "tfd/util/logging.h"
#include "tfd/util/strings.h"

namespace tfd {
namespace perf {

namespace {

// The checksummed canonical form: every field that carries meaning, in
// a fixed order with a fixed float format, so parse→recompute→compare
// is byte-stable regardless of how the JSON transport reformats.
std::string CanonicalFields(const Characterization& c) {
  return std::to_string(c.schema) + "|" + c.fingerprint + "|" + c.family +
         "|" + Fixed3(c.measured_at) + "|" +
         Fixed3(c.measure_seconds) + "|" + Fixed3(c.matmul_tflops) +
         "|" + Fixed3(c.hbm_gbps) + "|" + Fixed3(c.ici_gbps) + "|" +
         Fixed3(c.matmul_pct) + "|" + Fixed3(c.hbm_pct) + "|" +
         std::to_string(c.class_rank);
}

}  // namespace

const char* ClassName(int rank) {
  switch (rank) {
    case kRankGold:
      return "gold";
    case kRankSilver:
      return "silver";
    case kRankDegraded:
      return "degraded";
  }
  return "silver";
}

int ClassRankFromName(const std::string& name) {
  if (name == "gold") return kRankGold;
  if (name == "silver") return kRankSilver;
  if (name == "degraded") return kRankDegraded;
  return -1;
}

const std::map<std::string, RatedSpec>& BakedRatedSpecs() {
  // Must stay value-identical to tpufd/rated_specs.json (the checked-in
  // source of truth; TestRatedSpecsParity pins this).
  static const std::map<std::string, RatedSpec> specs = {
      {"v2", {46.0, 700.0}},    {"v3", {123.0, 900.0}},
      {"v4", {275.0, 1228.0}},  {"v5e", {197.0, 819.0}},
      {"v5p", {459.0, 2765.0}}, {"v6e", {918.0, 1640.0}},
  };
  return specs;
}

Result<std::map<std::string, RatedSpec>> ParseRatedSpecs(
    const std::string& json_text) {
  using R = Result<std::map<std::string, RatedSpec>>;
  Result<jsonlite::ValuePtr> parsed = jsonlite::Parse(json_text);
  if (!parsed.ok()) {
    return R::Error("rated specs unparseable: " + parsed.error());
  }
  jsonlite::ValuePtr families = (*parsed)->Get("families");
  if (!families || families->kind != jsonlite::Value::Kind::kObject) {
    return R::Error("rated specs missing 'families' object");
  }
  std::map<std::string, RatedSpec> out;
  for (const auto& [family, value] : families->object_items) {
    if (value->kind != jsonlite::Value::Kind::kObject) {
      return R::Error("rated spec for '" + family + "' is not an object");
    }
    RatedSpec spec;
    jsonlite::ValuePtr matmul = value->Get("matmul_tflops");
    jsonlite::ValuePtr hbm = value->Get("hbm_gbps");
    if (!matmul || matmul->kind != jsonlite::Value::Kind::kNumber ||
        !hbm || hbm->kind != jsonlite::Value::Kind::kNumber) {
      return R::Error("rated spec for '" + family +
                      "' needs numeric matmul_tflops and hbm_gbps");
    }
    spec.matmul_tflops = matmul->number_value;
    spec.hbm_gbps = hbm->number_value;
    if (spec.matmul_tflops <= 0 || spec.hbm_gbps <= 0) {
      return R::Error("rated spec for '" + family + "' must be positive");
    }
    out[family] = spec;
  }
  if (out.empty()) return R::Error("rated specs list no families");
  return out;
}

double PctOfRated(double measured, double rated) {
  if (rated <= 0 || measured < 0) return -1;
  return 100.0 * measured / rated;
}

int ClassifyPct(double matmul_pct, double hbm_pct, int prev_rank) {
  // Raw thresholds first; hysteresis below only defends the CURRENT
  // class against boundary jitter.
  auto raw = [](double matmul, double hbm) {
    if (matmul >= 0 && matmul < kDegradedPct) return kRankDegraded;
    if (hbm >= 0 && hbm < kDegradedPct) return kRankDegraded;
    if (matmul >= kGoldMatmulPct && (hbm < 0 || hbm >= kGoldHbmPct)) {
      return kRankGold;
    }
    return kRankSilver;
  };
  int rank = raw(matmul_pct, hbm_pct);
  if (prev_rank < 0 || rank == prev_rank) return rank;
  // Hysteresis: to LEAVE the previous class, the measurement must clear
  // the crossed boundary by the margin — shifting the inputs toward the
  // previous class by the margin must still produce the new class.
  double toward = rank > prev_rank ? kHysteresisPct : -kHysteresisPct;
  int confirmed = raw(matmul_pct < 0 ? matmul_pct : matmul_pct + toward,
                      hbm_pct < 0 ? hbm_pct : hbm_pct + toward);
  // A margin-shifted reading that no longer crosses in the same
  // direction means the chip is sitting on the boundary: keep the
  // previous class.
  bool still_crosses =
      rank > prev_rank ? confirmed > prev_rank : confirmed < prev_rank;
  return still_crosses ? rank : prev_rank;
}

Result<FleetFloor> ParseFleetFloor(const std::string& json_text) {
  Result<jsonlite::ValuePtr> parsed = jsonlite::Parse(json_text);
  if (!parsed.ok()) {
    return Result<FleetFloor>::Error("fleet floor parse: " +
                                     parsed.error());
  }
  if ((*parsed)->kind != jsonlite::Value::Kind::kObject) {
    return Result<FleetFloor>::Error("fleet floor: not a JSON object");
  }
  FleetFloor floor;
  auto number = [&](const char* key, double* out) {
    jsonlite::ValuePtr v = (*parsed)->Get(key);
    if (v && v->kind == jsonlite::Value::Kind::kNumber &&
        v->number_value >= 0) {
      *out = v->number_value;
    }
  };
  number("matmul_p10_tflops", &floor.matmul_p10_tflops);
  number("hbm_p10_gbps", &floor.hbm_p10_gbps);
  return floor;
}

int ApplyFleetFloor(int rank, double matmul_tflops, double hbm_gbps,
                    const FleetFloor& floor) {
  // An unmeasured value (-1) never triggers a floor, and an unset
  // floor (-1) never demotes: the floor only ever makes a MEASURED
  // value stricter, in the conservative direction.
  if (floor.matmul_p10_tflops >= 0 && matmul_tflops >= 0 &&
      matmul_tflops < floor.matmul_p10_tflops) {
    return kRankDegraded;
  }
  if (floor.hbm_p10_gbps >= 0 && hbm_gbps >= 0 &&
      hbm_gbps < floor.hbm_p10_gbps) {
    return kRankDegraded;
  }
  return rank;
}

std::string Fingerprint(const std::string& family, int chip_count,
                        const std::string& topology,
                        const std::string& libtpu_version) {
  return (family.empty() ? "unknown" : family) + "/" +
         std::to_string(chip_count) + "/" +
         (topology.empty() ? "-" : topology) + "/" +
         (libtpu_version.empty() ? "-" : libtpu_version);
}

std::string SerializeCharacterization(const Characterization& c) {
  return "{\"schema\":" + std::to_string(c.schema) +
         ",\"sum\":\"" + HexU64(Fnv1a64(CanonicalFields(c))) + "\"" +
         ",\"fingerprint\":" + jsonlite::Quote(c.fingerprint) +
         ",\"family\":" + jsonlite::Quote(c.family) +
         ",\"measured_at\":" + Fixed3(c.measured_at) +
         ",\"measure_seconds\":" + Fixed3(c.measure_seconds) +
         ",\"matmul_tflops\":" + Fixed3(c.matmul_tflops) +
         ",\"hbm_gbps\":" + Fixed3(c.hbm_gbps) +
         ",\"ici_gbps\":" + Fixed3(c.ici_gbps) +
         ",\"matmul_pct\":" + Fixed3(c.matmul_pct) +
         ",\"hbm_pct\":" + Fixed3(c.hbm_pct) +
         ",\"class\":" + jsonlite::Quote(ClassName(c.class_rank)) + "}";
}

Result<Characterization> ParseCharacterization(const std::string& json) {
  using R = Result<Characterization>;
  Result<jsonlite::ValuePtr> parsed = jsonlite::Parse(json);
  if (!parsed.ok()) {
    return R::Error("perf section unparseable: " + parsed.error());
  }
  const jsonlite::Value& root = **parsed;
  auto number = [&root](const char* key, double* out) {
    jsonlite::ValuePtr v = root.Get(key);
    if (!v || v->kind != jsonlite::Value::Kind::kNumber) return false;
    *out = v->number_value;
    return true;
  };
  auto text = [&root](const char* key, std::string* out) {
    jsonlite::ValuePtr v = root.Get(key);
    if (!v || v->kind != jsonlite::Value::Kind::kString) return false;
    *out = v->string_value;
    return true;
  };
  Characterization c;
  double schema = 0;
  if (!number("schema", &schema)) {
    return R::Error("perf section missing schema");
  }
  if (static_cast<int>(schema) != kPerfSchema) {
    return R::Error("perf schema " +
                    std::to_string(static_cast<int>(schema)) +
                    " unsupported (want " + std::to_string(kPerfSchema) +
                    ")");
  }
  c.schema = static_cast<int>(schema);
  std::string sum, cls;
  if (!text("sum", &sum)) return R::Error("perf section missing checksum");
  if (!text("fingerprint", &c.fingerprint) || c.fingerprint.empty()) {
    return R::Error("perf section missing fingerprint");
  }
  text("family", &c.family);
  number("measured_at", &c.measured_at);
  number("measure_seconds", &c.measure_seconds);
  number("matmul_tflops", &c.matmul_tflops);
  number("hbm_gbps", &c.hbm_gbps);
  number("ici_gbps", &c.ici_gbps);
  number("matmul_pct", &c.matmul_pct);
  number("hbm_pct", &c.hbm_pct);
  if (!text("class", &cls)) return R::Error("perf section missing class");
  c.class_rank = ClassRankFromName(cls);
  if (c.class_rank < 0) {
    return R::Error("perf section names unknown class '" + cls + "'");
  }
  if (HexU64(Fnv1a64(CanonicalFields(c))) != sum) {
    return R::Error("perf section torn or corrupt (checksum mismatch)");
  }
  return c;
}

Result<std::map<std::string, double>> ParseExecOutput(
    const std::string& text) {
  using R = Result<std::map<std::string, double>>;
  std::map<std::string, double> out;
  for (const std::string& line : SplitString(text, '\n')) {
    std::string trimmed = TrimSpace(line);
    if (trimmed.empty()) continue;
    size_t eq = trimmed.find('=');
    if (eq == std::string::npos || eq == 0) {
      TFD_LOG_WARNING << "perf exec: ignoring malformed line: " << trimmed;
      continue;
    }
    std::string key = trimmed.substr(0, eq);
    if (key != "matmul-tflops" && key != "hbm-gbps" && key != "ici-gbps") {
      TFD_LOG_WARNING << "perf exec: ignoring unknown measurement: " << key;
      continue;
    }
    char* end = nullptr;
    std::string value = trimmed.substr(eq + 1);
    double parsed = strtod(value.c_str(), &end);
    if (end == value.c_str() || parsed < 0) {
      TFD_LOG_WARNING << "perf exec: ignoring non-numeric value: "
                      << trimmed;
      continue;
    }
    out[key] = parsed;
  }
  if (out.count("matmul-tflops") == 0 && out.count("hbm-gbps") == 0) {
    return R::Error("perf exec produced no recognized measurement "
                    "(want matmul-tflops= / hbm-gbps= / ici-gbps= lines)");
  }
  return out;
}

std::map<std::string, std::string> BuildLabels(const Characterization& c) {
  // Throughput label values mirror tpufd.health's fmt(): whole numbers
  // at TPU scale, two significant digits below 10 (a small-but-real CI
  // measurement must never read "0" = probe failure).
  auto fmt = [](double v) -> std::string {
    if (v >= 10) return std::to_string(static_cast<long long>(v));
    char buf[32];
    snprintf(buf, sizeof(buf), "%.2g", v);
    return buf;
  };
  std::map<std::string, std::string> labels;
  if (c.matmul_tflops >= 0) {
    labels["google.com/tpu.perf.matmul-tflops"] = fmt(c.matmul_tflops);
  }
  if (c.hbm_gbps >= 0) {
    labels["google.com/tpu.perf.hbm-gbps"] = fmt(c.hbm_gbps);
  }
  if (c.ici_gbps >= 0) {
    labels["google.com/tpu.perf.ici-gbps"] = fmt(c.ici_gbps);
  }
  if (c.matmul_pct >= 0) {
    labels["google.com/tpu.perf.pct-of-rated"] =
        std::to_string(static_cast<long long>(c.matmul_pct + 0.5));
  }
  labels["google.com/tpu.perf.class"] = ClassName(c.class_rank);
  return labels;
}

bool MeasureAllowed(double now, double last_end, double last_seconds,
                    int duty_cycle_pct) {
  if (last_end <= 0 || last_seconds <= 0) return true;  // first ever
  if (duty_cycle_pct >= 100) return true;
  if (duty_cycle_pct < 1) duty_cycle_pct = 1;
  double required_gap =
      last_seconds * (100.0 / duty_cycle_pct - 1.0);
  return now - last_end >= required_gap;
}

std::optional<Characterization> Cache::Get() const {
  std::lock_guard<std::mutex> lock(mu_);
  return value_;
}

void Cache::Set(const Characterization& c) {
  std::lock_guard<std::mutex> lock(mu_);
  value_ = c;
}

void Cache::Invalidate() {
  std::lock_guard<std::mutex> lock(mu_);
  value_.reset();
}

void Cache::NoteMeasurement(double end_wall, double seconds) {
  std::lock_guard<std::mutex> lock(mu_);
  last_measure_end_ = end_wall;
  last_measure_seconds_ = seconds;
  last_deferral_key_.clear();  // a fresh attempt opens a fresh episode
}

bool Cache::AllowedNow(double now, int duty_cycle_pct) const {
  std::lock_guard<std::mutex> lock(mu_);
  return MeasureAllowed(now, last_measure_end_, last_measure_seconds_,
                        duty_cycle_pct);
}

bool Cache::NoteDeferral(const std::string& key) {
  std::lock_guard<std::mutex> lock(mu_);
  if (last_deferral_key_ == key) return false;
  last_deferral_key_ = key;
  return true;
}

std::string Cache::SerializeJson() const {
  std::lock_guard<std::mutex> lock(mu_);
  if (!value_.has_value()) return "";
  return SerializeCharacterization(*value_);
}

Status Cache::RestoreJson(const std::string& json) {
  if (json.empty()) return Status::Ok();  // pre-PR-9 state file
  Result<Characterization> parsed = ParseCharacterization(json);
  if (!parsed.ok()) return Status::Error(parsed.error());
  std::lock_guard<std::mutex> lock(mu_);
  value_ = *parsed;
  // The restored measurement's duty bookkeeping starts clean: the
  // measurement happened a process lifetime ago, so the next REAL
  // measurement (fingerprint change, recheck due) is not duty-blocked
  // by it.
  return Status::Ok();
}

void Cache::Reset() {
  std::lock_guard<std::mutex> lock(mu_);
  value_.reset();
  last_measure_end_ = 0;
  last_measure_seconds_ = 0;
  last_deferral_key_.clear();
}

Cache& Default() {
  static Cache* cache = new Cache();
  return *cache;
}

}  // namespace perf
}  // namespace tfd

// GCE instance-metadata client.
//
// The structural analogue of the reference's sysfs/PCI-config probing
// (internal/vgpu/pciutil.go) and DMI reads: on TPU VMs the interesting
// hardware identity (accelerator-type, topology, worker id, multi-slice
// membership) lives in the metadata server, not in PCI config space.
//
// Plain HTTP/1.1 over a blocking socket — metadata.google.internal
// (169.254.254.169.254...) is link-local; no TLS involved, so no external
// HTTP library is needed. The endpoint is overridable (--metadata-endpoint /
// GCE_METADATA_HOST) so tests can run a fake server — the hermetic-harness
// improvement SURVEY.md §4 calls for.
#pragma once

#include <map>
#include <string>

#include "tfd/util/status.h"

namespace tfd {
namespace gce {

class MetadataClient {
 public:
  // `endpoint`: "host[:port]". Empty → $GCE_METADATA_HOST or
  // metadata.google.internal. Timeouts are per-request, in milliseconds.
  explicit MetadataClient(std::string endpoint = "", int timeout_ms = 1500);

  // GET /computeMetadata/v1/<path> with Metadata-Flavor: Google.
  // `path` example: "instance/attributes/accelerator-type".
  Result<std::string> Get(const std::string& path) const;

  // True if the metadata server answers at all (cheap liveness probe).
  bool Available() const;

  // Convenience wrappers over well-known keys (empty string if absent):
  Result<std::string> MachineType() const;    // leaf of instance/machine-type
  // TPU accelerator type, e.g. "v5litepod-16". Checks
  // instance/attributes/accelerator-type (TPU VMs).
  Result<std::string> AcceleratorType() const;
  // The "tpu-env" attribute: a newline-separated KEY: 'value' bag with
  // ACCELERATOR_TYPE, TOPOLOGY, WORKER_ID, HOST_BOUNDS, ... present on TPU
  // VMs. Parsed into a map.
  Result<std::map<std::string, std::string>> TpuEnv() const;
  Result<std::string> InstanceId() const;
  Result<bool> Preemptible() const;

  const std::string& endpoint() const { return endpoint_; }

 private:
  std::string endpoint_;
  int timeout_ms_;
};

// Parses the tpu-env attribute format: lines of KEY: 'value' (value quoting
// optional). Exposed for unit tests.
std::map<std::string, std::string> ParseTpuEnv(const std::string& text);

}  // namespace gce
}  // namespace tfd

// GCE instance-metadata client.
//
// The structural analogue of the reference's sysfs/PCI-config probing
// (internal/vgpu/pciutil.go) and DMI reads: on TPU VMs the interesting
// hardware identity (accelerator-type, topology, worker id, multi-slice
// membership) lives in the metadata server, not in PCI config space.
//
// Plain HTTP/1.1 over a blocking socket — metadata.google.internal
// (169.254.254.169.254...) is link-local; no TLS involved, so no external
// HTTP library is needed. The endpoint is overridable (--metadata-endpoint /
// GCE_METADATA_HOST) so tests can run a fake server — the hermetic-harness
// improvement SURVEY.md §4 calls for.
#pragma once

#include <map>
#include <string>

#include "tfd/util/status.h"

namespace tfd {
namespace gce {

class MetadataClient {
 public:
  // Classifies the most recent Get() failure. Callers that stack multiple
  // metadata rungs (the watchdog's pin planner) branch on this instead of
  // matching error-message substrings: a kTransport failure (nothing
  // answered at all) means every further request would pay its own connect
  // timeout for nothing, while kNotFound/kHttpStatus (and a garbage- or
  // oversized-answer kHttpStatus) prove the server is reachable.
  enum class ErrorKind {
    kNone,        // last Get succeeded
    kTransport,   // resolve/connect failed: nothing listening at all
    kHttpStatus,  // endpoint reached but answered badly (non-200/404
                  // status, garbage, or closed without a byte)
    kNotFound,    // HTTP 404: server up, key absent (the GKE shape)
  };

  // `endpoint`: "host[:port]". Empty → $GCE_METADATA_HOST or
  // metadata.google.internal. Timeouts are per-request, in milliseconds.
  explicit MetadataClient(std::string endpoint = "", int timeout_ms = 1500);

  // GET /computeMetadata/v1/<path> with Metadata-Flavor: Google.
  // `path` example: "instance/attributes/accelerator-type".
  Result<std::string> Get(const std::string& path) const;

  // Kind of the most recent Get() outcome (including Gets made internally
  // by the convenience wrappers; wrappers that fall back across several
  // keys report the LAST request's kind).
  ErrorKind last_error_kind() const { return last_error_kind_; }

  // True if the metadata server answers at all (cheap liveness probe).
  bool Available() const;

  // Convenience wrappers over well-known keys (empty string if absent):
  Result<std::string> MachineType() const;    // leaf of instance/machine-type
  // TPU accelerator type, e.g. "v5litepod-16". Checks
  // instance/attributes/accelerator-type (TPU VMs).
  Result<std::string> AcceleratorType() const;
  // The "tpu-env" attribute: a newline-separated KEY: 'value' bag with
  // ACCELERATOR_TYPE, TOPOLOGY, WORKER_ID, HOST_BOUNDS, ... present on TPU
  // VMs. Parsed into a map.
  Result<std::map<std::string, std::string>> TpuEnv() const;
  Result<std::string> InstanceId() const;
  Result<bool> Preemptible() const;
  // instance/preempted: TRUE once GCE has issued the preemption notice
  // (the fast-path input of the lifecycle probe). A 404 — the key is
  // absent on non-preemptible shapes — reads as false, not an error.
  Result<bool> Preempted() const;

  const std::string& endpoint() const { return endpoint_; }

 private:
  std::string endpoint_;
  int timeout_ms_;
  // Mutable: Get() is logically const (no client state changes) but records
  // its outcome for the caller; the client is used single-threaded.
  mutable ErrorKind last_error_kind_ = ErrorKind::kNone;
};

// Parses the tpu-env attribute format: lines of KEY: 'value' (value quoting
// optional). Exposed for unit tests.
std::map<std::string, std::string> ParseTpuEnv(const std::string& text);

}  // namespace gce
}  // namespace tfd

#include "tfd/gce/metadata.h"

#include <cstdlib>

#include "tfd/util/http.h"
#include "tfd/util/strings.h"

namespace tfd {
namespace gce {

namespace {

constexpr char kDefaultEndpoint[] = "metadata.google.internal";

}  // namespace

MetadataClient::MetadataClient(std::string endpoint, int timeout_ms)
    : endpoint_(std::move(endpoint)), timeout_ms_(timeout_ms) {
  if (endpoint_.empty()) {
    if (const char* env = std::getenv("GCE_METADATA_HOST")) endpoint_ = env;
  }
  if (endpoint_.empty()) endpoint_ = kDefaultEndpoint;
}

Result<std::string> MetadataClient::Get(const std::string& path) const {
  http::RequestOptions options;
  options.timeout_ms = timeout_ms_;
  options.headers["Metadata-Flavor"] = "Google";
  bool server_reached = false;
  options.server_reached = &server_reached;
  Result<http::Response> resp = http::Request(
      "GET", "http://" + endpoint_ + "/computeMetadata/v1/" + path, "",
      options);
  if (!resp.ok()) {
    // A garbage-speaking or close-without-a-byte endpoint still proves
    // something is listening; only resolve/connect failure is transport.
    last_error_kind_ =
        server_reached ? ErrorKind::kHttpStatus : ErrorKind::kTransport;
    return Result<std::string>::Error(resp.error());
  }
  if (resp->status == 404) {
    last_error_kind_ = ErrorKind::kNotFound;
    return Result<std::string>::Error("metadata key not found: " + path);
  }
  if (resp->status != 200) {
    last_error_kind_ = ErrorKind::kHttpStatus;
    return Result<std::string>::Error("metadata GET " + path + ": HTTP " +
                                      std::to_string(resp->status));
  }
  last_error_kind_ = ErrorKind::kNone;
  return resp->body;
}

bool MetadataClient::Available() const {
  // instance/id exists on every GCE VM.
  return Get("instance/id").ok();
}

Result<std::string> MetadataClient::MachineType() const {
  Result<std::string> full = Get("instance/machine-type");
  if (!full.ok()) return full;
  std::vector<std::string> parts = SplitString(TrimSpace(*full), '/');
  return parts.back();
}

Result<std::string> MetadataClient::AcceleratorType() const {
  Result<std::string> t = Get("instance/attributes/accelerator-type");
  if (t.ok()) return TrimSpace(*t);
  // Fall back to the tpu-env bag.
  Result<std::map<std::string, std::string>> env = TpuEnv();
  if (env.ok()) {
    auto it = env->find("ACCELERATOR_TYPE");
    if (it != env->end()) return it->second;
  }
  return t;
}

Result<std::map<std::string, std::string>> MetadataClient::TpuEnv() const {
  Result<std::string> raw = Get("instance/attributes/tpu-env");
  if (!raw.ok()) {
    return Result<std::map<std::string, std::string>>::Error(raw.error());
  }
  return ParseTpuEnv(*raw);
}

Result<std::string> MetadataClient::InstanceId() const {
  Result<std::string> id = Get("instance/id");
  if (!id.ok()) return id;
  return TrimSpace(*id);
}

Result<bool> MetadataClient::Preemptible() const {
  Result<std::string> v = Get("instance/scheduling/preemptible");
  if (!v.ok()) return Result<bool>::Error(v.error());
  return ToLower(TrimSpace(*v)) == "true";
}

Result<bool> MetadataClient::Preempted() const {
  // instance/preempted flips to TRUE the moment GCE issues the
  // preemption notice (the ~30s ACPI-G2 warning window) and a 404 on a
  // non-preemptible shape just means "no": both read as not-preempted.
  Result<std::string> v = Get("instance/preempted");
  if (!v.ok()) {
    if (last_error_kind_ == ErrorKind::kNotFound) return false;
    return Result<bool>::Error(v.error());
  }
  return ToLower(TrimSpace(*v)) == "true";
}

std::map<std::string, std::string> ParseTpuEnv(const std::string& text) {
  // Format: one "KEY: 'value'" per line (value quoting optional).
  std::map<std::string, std::string> out;
  for (const std::string& line : SplitString(text, '\n')) {
    size_t colon = line.find(':');
    if (colon == std::string::npos) continue;
    std::string key = TrimSpace(line.substr(0, colon));
    std::string value = TrimSpace(line.substr(colon + 1));
    if (value.size() >= 2 && value.front() == '\'' && value.back() == '\'') {
      value = value.substr(1, value.size() - 2);
    }
    if (!key.empty()) out[key] = value;
  }
  return out;
}

}  // namespace gce
}  // namespace tfd

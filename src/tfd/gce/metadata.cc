#include "tfd/gce/metadata.h"

#include <arpa/inet.h>
#include <netdb.h>
#include <poll.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cstdlib>

#include "tfd/util/strings.h"

namespace tfd {
namespace gce {

namespace {

constexpr char kDefaultEndpoint[] = "metadata.google.internal";

struct FdCloser {
  int fd;
  ~FdCloser() {
    if (fd >= 0) close(fd);
  }
};

// One blocking HTTP/1.1 GET. The timeout applies per socket operation
// (connect/send/recv), not to the whole request. Returns the raw response.
Result<std::string> HttpGet(const std::string& host, int port,
                            const std::string& path, int timeout_ms) {
  addrinfo hints{};
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = SOCK_STREAM;
  addrinfo* res = nullptr;
  std::string port_str = std::to_string(port);
  int rc = getaddrinfo(host.c_str(), port_str.c_str(), &hints, &res);
  if (rc != 0) {
    return Result<std::string>::Error("resolve " + host + ": " +
                                      gai_strerror(rc));
  }
  int fd = -1;
  for (addrinfo* ai = res; ai != nullptr; ai = ai->ai_next) {
    fd = socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
    if (fd < 0) continue;
    timeval tv{};
    tv.tv_sec = timeout_ms / 1000;
    tv.tv_usec = (timeout_ms % 1000) * 1000;
    setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
    setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
    if (connect(fd, ai->ai_addr, ai->ai_addrlen) == 0) break;
    close(fd);
    fd = -1;
  }
  freeaddrinfo(res);
  if (fd < 0) {
    return Result<std::string>::Error("connect to " + host + ":" + port_str +
                                      " failed: " + strerror(errno));
  }
  FdCloser closer{fd};

  std::string request = "GET " + path +
                        " HTTP/1.1\r\nHost: " + host +
                        "\r\nMetadata-Flavor: Google\r\n"
                        "Connection: close\r\n\r\n";
  size_t off = 0;
  while (off < request.size()) {
    ssize_t n = send(fd, request.data() + off, request.size() - off, 0);
    if (n <= 0) {
      return Result<std::string>::Error("send failed: " +
                                        std::string(strerror(errno)));
    }
    off += static_cast<size_t>(n);
  }

  std::string response;
  char buf[4096];
  while (true) {
    ssize_t n = recv(fd, buf, sizeof(buf), 0);
    if (n < 0) {
      return Result<std::string>::Error("recv failed: " +
                                        std::string(strerror(errno)));
    }
    if (n == 0) break;
    response.append(buf, static_cast<size_t>(n));
    if (response.size() > 4 * 1024 * 1024) {
      return Result<std::string>::Error("metadata response too large");
    }
  }
  return response;
}

// Minimal HTTP response parse: status line + headers + body. Handles
// chunked transfer-encoding (the GCE server uses Content-Length, but a fake
// test server may not).
Result<std::string> ParseHttpResponse(const std::string& raw, int* status) {
  size_t header_end = raw.find("\r\n\r\n");
  if (header_end == std::string::npos) {
    return Result<std::string>::Error("malformed HTTP response");
  }
  std::string headers = raw.substr(0, header_end);
  std::string body = raw.substr(header_end + 4);
  size_t sp = headers.find(' ');
  if (sp == std::string::npos) {
    return Result<std::string>::Error("malformed HTTP status line");
  }
  *status = atoi(headers.c_str() + sp + 1);
  if (ToLower(headers).find("transfer-encoding: chunked") !=
      std::string::npos) {
    std::string decoded;
    size_t pos = 0;
    while (pos < body.size()) {
      size_t eol = body.find("\r\n", pos);
      if (eol == std::string::npos) break;
      long chunk = strtol(body.substr(pos, eol - pos).c_str(), nullptr, 16);
      if (chunk <= 0) break;
      decoded += body.substr(eol + 2, static_cast<size_t>(chunk));
      pos = eol + 2 + static_cast<size_t>(chunk) + 2;
    }
    body = decoded;
  }
  return body;
}

}  // namespace

MetadataClient::MetadataClient(std::string endpoint, int timeout_ms)
    : endpoint_(std::move(endpoint)), timeout_ms_(timeout_ms) {
  if (endpoint_.empty()) {
    if (const char* env = std::getenv("GCE_METADATA_HOST")) endpoint_ = env;
  }
  if (endpoint_.empty()) endpoint_ = kDefaultEndpoint;
}

Result<std::string> MetadataClient::Get(const std::string& path) const {
  std::string host = endpoint_;
  int port = 80;
  size_t colon = host.rfind(':');
  if (colon != std::string::npos && host.find(']') == std::string::npos) {
    port = atoi(host.c_str() + colon + 1);
    host = host.substr(0, colon);
  }
  Result<std::string> raw =
      HttpGet(host, port, "/computeMetadata/v1/" + path, timeout_ms_);
  if (!raw.ok()) return raw;
  int status = 0;
  Result<std::string> body = ParseHttpResponse(*raw, &status);
  if (!body.ok()) return body;
  if (status == 404) {
    return Result<std::string>::Error("metadata key not found: " + path);
  }
  if (status != 200) {
    return Result<std::string>::Error("metadata GET " + path + ": HTTP " +
                                      std::to_string(status));
  }
  return body;
}

bool MetadataClient::Available() const {
  // instance/id exists on every GCE VM.
  return Get("instance/id").ok();
}

Result<std::string> MetadataClient::MachineType() const {
  Result<std::string> full = Get("instance/machine-type");
  if (!full.ok()) return full;
  std::vector<std::string> parts = SplitString(TrimSpace(*full), '/');
  return parts.back();
}

Result<std::string> MetadataClient::AcceleratorType() const {
  Result<std::string> t = Get("instance/attributes/accelerator-type");
  if (t.ok()) return TrimSpace(*t);
  // Fall back to the tpu-env bag.
  Result<std::map<std::string, std::string>> env = TpuEnv();
  if (env.ok()) {
    auto it = env->find("ACCELERATOR_TYPE");
    if (it != env->end()) return it->second;
  }
  return t;
}

Result<std::map<std::string, std::string>> MetadataClient::TpuEnv() const {
  Result<std::string> raw = Get("instance/attributes/tpu-env");
  if (!raw.ok()) {
    return Result<std::map<std::string, std::string>>::Error(raw.error());
  }
  return ParseTpuEnv(*raw);
}

Result<std::string> MetadataClient::InstanceId() const {
  Result<std::string> id = Get("instance/id");
  if (!id.ok()) return id;
  return TrimSpace(*id);
}

Result<bool> MetadataClient::Preemptible() const {
  Result<std::string> v = Get("instance/scheduling/preemptible");
  if (!v.ok()) return Result<bool>::Error(v.error());
  return ToLower(TrimSpace(*v)) == "true";
}

std::map<std::string, std::string> ParseTpuEnv(const std::string& text) {
  // Format: one "KEY: 'value'" per line (value quoting optional).
  std::map<std::string, std::string> out;
  for (const std::string& line : SplitString(text, '\n')) {
    size_t colon = line.find(':');
    if (colon == std::string::npos) continue;
    std::string key = TrimSpace(line.substr(0, colon));
    std::string value = TrimSpace(line.substr(colon + 1));
    if (value.size() >= 2 && value.front() == '\'' && value.back() == '\'') {
      value = value.substr(1, value.size() - 2);
    }
    if (!key.empty()) out[key] = value;
  }
  return out;
}

}  // namespace gce
}  // namespace tfd

// In-daemon introspection HTTP server: /healthz, /readyz, /metrics,
// /debug/journal, /debug/labels, /debug/trace, /debug/slo.
//
// A minimal single-threaded GET-only HTTP/1.1 server: one background
// thread runs a poll(2) loop over the listen socket and a small fixed
// budget of connections (the idiom mirror of util/http.cc's client —
// hand-rolled, zero link deps). Kubelet probes and a Prometheus scrape
// are its whole traffic model: tiny requests, tiny responses, loopback
// or pod-network peers.
//
// Lifecycle is SIGHUP-safe by construction: the daemon creates the
// server after each config load and destroys it (Stop joins the thread
// and closes the socket) before reloading, so an addr change via SIGHUP
// rebinds cleanly (SO_REUSEADDR covers the TIME_WAIT window). The
// registry it renders lives in obs::Default() and survives reloads, so
// scraped counters stay monotone across SIGHUP.
//
// Readiness contract (/readyz): 200 iff the LAST label rewrite succeeded
// AND its success is fresher than `stale_after_s` (the daemon wires
// 2 x sleep-interval, widened by the health-exec budget when
// --device-health=full legitimately blocks a pass); everything else —
// never rewrote, last rewrite failed, rewrites stale — is 503, so a
// wedged or erroring daemon drops out of service without dying.
#pragma once

#include <functional>
#include <memory>
#include <string>

#include "tfd/obs/journal.h"
#include "tfd/obs/metrics.h"
#include "tfd/obs/slo.h"
#include "tfd/obs/trace.h"
#include "tfd/util/status.h"

namespace tfd {
namespace obs {

// Parses a listen address "host:port" (empty host = all interfaces, e.g.
// ":8081"; host must be an IPv4 literal otherwise). Port 0 binds an
// ephemeral port (tests). Exposed for config validation and unit tests.
struct ListenAddr {
  std::string host;  // "" = INADDR_ANY
  int port = 0;
};
Result<ListenAddr> ParseListenAddr(const std::string& text);

struct ServerOptions {
  std::string addr;        // "host:port" per ParseListenAddr
  int stale_after_s = 120; // /readyz freshness window
  // Flight recorder behind /debug/journal?n=&type= (null hides the
  // endpoint; the daemon passes obs::DefaultJournal()).
  Journal* journal = nullptr;
  // Causal-trace recorder behind /debug/trace?n=&change= (null hides
  // the endpoint; the daemon passes obs::DefaultTrace()).
  TraceRecorder* trace = nullptr;
  // Windowed stage-SLO tracker behind /debug/slo (null hides the
  // endpoint; the daemon passes obs::DefaultSlo()). Each read expires
  // the window first, so a quiet daemon's view still ages out.
  StageSlo* slo = nullptr;
  // Live member-report provider behind /debug/slice-report (null hides
  // the endpoint): peers fetch this during a partial partition to relay
  // this host's report onto the slice blackboard (--slice-relay). The
  // daemon wires slice::Default().LocalReportJson; an empty return is
  // served as 503 (no report built yet).
  std::function<std::string()> slice_report;
};

class IntrospectionServer {
 public:
  ~IntrospectionServer();

  // Binds, listens, and starts the serving thread. The registry must
  // outlive the server (the daemon passes obs::Default()).
  static Result<std::unique_ptr<IntrospectionServer>> Start(
      const ServerOptions& options, Registry* registry);

  // Joins the serving thread and closes every socket. Idempotent.
  void Stop();

  // The bound port (resolves :0 for tests).
  int port() const { return port_; }

  // Called by the daemon loop after every rewrite attempt; drives /readyz.
  void RecordRewrite(bool ok);

  // Degradation-ladder input (sched/): when EVERY probe source's
  // snapshot is expired the daemon still rewrites (best-effort labels)
  // but must drop out of service — "degraded-but-serving is ready;
  // expired-everything is not". Called per rewrite alongside
  // RecordRewrite.
  void SetAllExpired(bool all_expired);

  // Pre-rendered /debug/labels document (current labels + per-key
  // provenance), handed over by the daemon loop after every successful
  // rewrite — built from the SAME merged map the sink wrote, so the
  // endpoint agrees with the emitted label file byte-for-byte.
  void SetLabelsJson(std::string json);

 private:
  IntrospectionServer() = default;
  void Loop();
  struct Conn;
  // Serves one fully-read request, filling the conn's output buffer.
  void HandleRequest(Conn* conn);

  Registry* registry_ = nullptr;
  Journal* journal_ = nullptr;
  TraceRecorder* trace_ = nullptr;
  StageSlo* slo_ = nullptr;
  std::function<std::string()> slice_report_;
  int stale_after_s_ = 120;
  int listen_fd_ = -1;
  int wake_fds_[2] = {-1, -1};  // self-pipe: Stop() wakes the poll loop
  int port_ = 0;
  class Impl;
  std::unique_ptr<Impl> impl_;
};

}  // namespace obs
}  // namespace tfd

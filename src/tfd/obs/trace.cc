#include "tfd/obs/trace.h"

#include <chrono>
#include <cstdio>

#include "tfd/obs/metrics.h"
#include "tfd/util/jsonlite.h"

namespace tfd {
namespace obs {

namespace {

double WallNow() {
  return std::chrono::duration<double>(
             std::chrono::system_clock::now().time_since_epoch())
      .count();
}

// Fixed 6-decimal rendering: the Python twin formats f"{ts:.6f}", so
// the parity pin can compare rendered documents byte-for-byte.
std::string FormatTs(double s) {
  char buf[40];
  snprintf(buf, sizeof(buf), "%.6f", s);
  return buf;
}

// Microseconds for the Chrome trace "ts"/"dur" fields — half-up
// rounding matched by the twin's int(t * 1e6 + 0.5).
long long Micros(double s) { return static_cast<long long>(s * 1e6 + 0.5); }

std::string RecordJson(const TraceRecord& record) {
  std::string out = "{\"change\":" + std::to_string(record.change) +
                    ",\"generation\":" + std::to_string(record.generation) +
                    ",\"minted_ts\":" + FormatTs(record.minted_ts) +
                    ",\"origin\":" + jsonlite::Quote(record.origin) +
                    ",\"source\":" + jsonlite::Quote(record.source) +
                    ",\"detail\":" + jsonlite::Quote(record.detail) +
                    ",\"published\":" +
                    (record.published ? "true" : "false") + ",\"stages\":{";
  bool first = true;
  for (const auto& [stage, ts] : record.stages) {
    if (!first) out += ",";
    first = false;
    out += jsonlite::Quote(stage) + ":" + FormatTs(ts);
  }
  return out + "}}";
}

}  // namespace

TraceRecorder::TraceRecorder(size_t capacity, bool metrics)
    : capacity_(capacity == 0 ? 1 : capacity), metrics_(metrics) {}

void TraceRecorder::UpdateGauge() const {
  if (!metrics_) return;
  size_t active = 0;
  for (const TraceRecord& record : records_) {
    if (!record.published) active++;
  }
  Default()
      .GetGauge("tfd_trace_active",
                "Trace records minted but not yet publish-acked "
                "(label changes in flight through the pass pipeline).")
      ->Set(static_cast<double>(active));
}

void TraceRecorder::SetCapacity(size_t capacity) {
  uint64_t dropped_now = 0;
  {
    std::lock_guard<std::mutex> lock(mu_);
    capacity_ = capacity == 0 ? 1 : capacity;
    while (records_.size() > capacity_) {
      records_.pop_front();
      dropped_++;
      dropped_now++;
    }
    UpdateGauge();
  }
  if (metrics_ && dropped_now > 0) {
    Default()
        .GetCounter("tfd_trace_dropped_total",
                    "Trace records evicted by the bounded ring buffer "
                    "(drop-oldest).")
        ->Inc(static_cast<double>(dropped_now));
  }
}

size_t TraceRecorder::capacity() const {
  std::lock_guard<std::mutex> lock(mu_);
  return capacity_;
}

uint64_t TraceRecorder::Mint(const std::string& origin,
                             const std::string& source,
                             const std::string& detail, double now_s) {
  TraceRecord record;
  record.minted_ts = now_s < 0 ? WallNow() : now_s;
  // Sanitize at ingestion, like the journal: origins and details can
  // carry probe error bytes, but /debug/trace and the Perfetto dump
  // must stay decodable by strict UTF-8 consumers.
  record.origin = jsonlite::SanitizeUtf8(origin);
  record.source = jsonlite::SanitizeUtf8(source);
  record.detail = jsonlite::SanitizeUtf8(detail);
  bool dropped = false;
  uint64_t change;
  {
    std::lock_guard<std::mutex> lock(mu_);
    change = next_change_++;
    record.change = change;
    if (records_.size() >= capacity_) {
      records_.pop_front();
      dropped_++;
      dropped = true;
    }
    records_.push_back(std::move(record));
    UpdateGauge();
  }
  if (metrics_ && dropped) {
    Default()
        .GetCounter("tfd_trace_dropped_total",
                    "Trace records evicted by the bounded ring buffer "
                    "(drop-oldest).")
        ->Inc();
  }
  return change;
}

void TraceRecorder::Stage(const std::string& stage, double now_s) {
  std::string name = jsonlite::SanitizeUtf8(stage);
  double now = now_s < 0 ? WallNow() : now_s;
  std::lock_guard<std::mutex> lock(mu_);
  for (TraceRecord& record : records_) {
    if (record.published) continue;
    bool seen = false;
    for (const auto& [existing, ts] : record.stages) {
      (void)ts;
      if (existing == name) {
        seen = true;
        break;
      }
    }
    if (!seen) record.stages.emplace_back(name, now);
  }
}

std::vector<TraceRecord> TraceRecorder::MarkPublished(
    uint64_t generation, double now_s, uint64_t through_change) {
  double now = now_s < 0 ? WallNow() : now_s;
  std::vector<TraceRecord> retired;
  std::lock_guard<std::mutex> lock(mu_);
  for (TraceRecord& record : records_) {
    if (record.published || record.change > through_change) continue;
    record.published = true;
    record.generation = generation;
    record.stages.emplace_back("publish-acked", now);
    retired.push_back(record);
  }
  UpdateGauge();
  return retired;
}

uint64_t TraceRecorder::LatestActiveChange() const {
  std::lock_guard<std::mutex> lock(mu_);
  uint64_t latest = 0;
  for (const TraceRecord& record : records_) {
    if (!record.published && record.change > latest) {
      latest = record.change;
    }
  }
  return latest;
}

uint64_t TraceRecorder::LatestChange() const {
  std::lock_guard<std::mutex> lock(mu_);
  return next_change_ - 1;
}

size_t TraceRecorder::active() const {
  std::lock_guard<std::mutex> lock(mu_);
  size_t active = 0;
  for (const TraceRecord& record : records_) {
    if (!record.published) active++;
  }
  return active;
}

uint64_t TraceRecorder::dropped_total() const {
  std::lock_guard<std::mutex> lock(mu_);
  return dropped_;
}

std::vector<TraceRecord> TraceRecorder::Snapshot(size_t n,
                                                 uint64_t change) const {
  std::lock_guard<std::mutex> lock(mu_);
  std::vector<TraceRecord> out;
  for (const TraceRecord& record : records_) {
    if (change != 0 && record.change != change) continue;
    out.push_back(record);
  }
  if (n > 0 && out.size() > n) {
    out.erase(out.begin(), out.end() - static_cast<ptrdiff_t>(n));
  }
  return out;
}

std::string TraceRecorder::RenderJson(size_t n, uint64_t change) const {
  std::vector<TraceRecord> records = Snapshot(n, change);
  uint64_t capacity;
  uint64_t dropped;
  uint64_t minted;
  size_t active = 0;
  {
    std::lock_guard<std::mutex> lock(mu_);
    capacity = capacity_;
    dropped = dropped_;
    minted = next_change_ - 1;
    for (const TraceRecord& record : records_) {
      if (!record.published) active++;
    }
  }
  std::string out = "{\"capacity\":" + std::to_string(capacity) +
                    ",\"dropped_total\":" + std::to_string(dropped) +
                    ",\"active\":" + std::to_string(active) +
                    ",\"minted_total\":" + std::to_string(minted) +
                    ",\"records\":[";
  for (size_t i = 0; i < records.size(); i++) {
    if (i) out += ",";
    out += RecordJson(records[i]);
  }
  return out + "]}";
}

std::string TraceRecorder::RenderChromeTrace() const {
  std::vector<TraceRecord> records = Snapshot(0, 0);
  std::string out = "{\"displayTimeUnit\":\"ms\",\"traceEvents\":[";
  bool first = true;
  for (const TraceRecord& record : records) {
    double prev = record.minted_ts;
    for (const auto& [stage, ts] : record.stages) {
      double start = prev;
      double end = ts > prev ? ts : prev;
      prev = end;
      if (!first) out += ",";
      first = false;
      out += "{\"name\":" + jsonlite::Quote(stage) +
             ",\"cat\":" + jsonlite::Quote(record.origin) +
             ",\"ph\":\"X\",\"ts\":" + std::to_string(Micros(start)) +
             ",\"dur\":" + std::to_string(Micros(end) - Micros(start)) +
             ",\"pid\":1,\"tid\":" + std::to_string(record.change) +
             ",\"args\":{\"change\":" +
             jsonlite::Quote(std::to_string(record.change)) +
             ",\"origin\":" + jsonlite::Quote(record.origin) +
             ",\"source\":" + jsonlite::Quote(record.source) +
             ",\"generation\":" +
             jsonlite::Quote(std::to_string(record.generation)) + "}}";
    }
  }
  return out + "]}";
}

TraceRecorder& DefaultTrace() {
  static TraceRecorder* trace = new TraceRecorder();
  return *trace;
}

}  // namespace obs
}  // namespace tfd

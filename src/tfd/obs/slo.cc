#include "tfd/obs/slo.h"

#include <chrono>

#include "tfd/util/jsonlite.h"
#include "tfd/util/strings.h"

namespace tfd {
namespace obs {

namespace {

double WallNow() {
  return std::chrono::duration<double>(
             std::chrono::system_clock::now().time_since_epoch())
      .count();
}

bool KnownSloStage(const std::string& stage) {
  for (const char* name : agg::kSloStages) {
    if (stage == name) return true;
  }
  return false;
}

}  // namespace

std::map<std::string, double> StageDurationsMs(const TraceRecord& record) {
  std::map<std::string, double> out;
  double prev = record.minted_ts;
  for (const auto& [stage, ts] : record.stages) {
    double end = ts > prev ? ts : prev;
    double ms = (end - prev) * 1000.0;
    prev = end;
    if (stage == "govern") {
      out["render"] += ms;
    } else if (KnownSloStage(stage)) {
      out[stage] += ms;
    }
  }
  return out;
}

StageSlo::StageSlo(int window_s)
    : window_s_(window_s < 1 ? 1 : window_s) {}

void StageSlo::SetWindow(int window_s) {
  std::lock_guard<std::mutex> lock(mu_);
  window_s_ = window_s < 1 ? 1 : window_s;
}

int StageSlo::window_s() const {
  std::lock_guard<std::mutex> lock(mu_);
  return window_s_;
}

void StageSlo::ExpireLocked(double now) {
  while (!samples_.empty() && samples_.front().ts <= now - window_s_) {
    for (const auto& [stage, ms] : samples_.front().stages) {
      auto it = sketches_.find(stage);
      if (it == sketches_.end()) continue;
      it->second.Remove(ms);
      if (it->second.count() <= 0) sketches_.erase(it);
    }
    samples_.pop_front();
    retired_++;
  }
}

void StageSlo::Fold(uint64_t change,
                    const std::map<std::string, double>& stage_ms,
                    double now_s) {
  double now = now_s < 0 ? WallNow() : now_s;
  std::lock_guard<std::mutex> lock(mu_);
  Sample sample;
  sample.ts = now;
  for (const char* name : agg::kSloStages) {
    auto it = stage_ms.find(name);
    if (it == stage_ms.end()) continue;
    sketches_[name].Add(it->second);
    sample.stages.emplace_back(name, it->second);
  }
  if (!sample.stages.empty()) {
    samples_.push_back(std::move(sample));
    folded_++;
    if (change > last_change_) last_change_ = change;
  }
  ExpireLocked(now);
}

void StageSlo::Expire(double now_s) {
  double now = now_s < 0 ? WallNow() : now_s;
  std::lock_guard<std::mutex> lock(mu_);
  ExpireLocked(now);
}

int64_t StageSlo::folded_total() const {
  std::lock_guard<std::mutex> lock(mu_);
  return folded_;
}

int64_t StageSlo::retired_total() const {
  std::lock_guard<std::mutex> lock(mu_);
  return retired_;
}

int64_t StageSlo::samples() const {
  std::lock_guard<std::mutex> lock(mu_);
  return static_cast<int64_t>(samples_.size());
}

agg::StageSketches StageSlo::Snapshot() const {
  std::lock_guard<std::mutex> lock(mu_);
  return sketches_;
}

std::string StageSlo::Serialize() const {
  std::lock_guard<std::mutex> lock(mu_);
  return agg::SerializeStageSketches(sketches_);
}

std::string StageSlo::RenderJson() const {
  std::lock_guard<std::mutex> lock(mu_);
  std::string out = "{\"window_s\":" + std::to_string(window_s_) +
                    ",\"samples\":" + std::to_string(samples_.size()) +
                    ",\"folded_total\":" + std::to_string(folded_) +
                    ",\"retired_total\":" + std::to_string(retired_) +
                    ",\"last_change\":" + std::to_string(last_change_) +
                    ",\"stages\":{";
  bool first = true;
  for (const char* name : agg::kSloStages) {
    auto it = sketches_.find(name);
    if (it == sketches_.end() || it->second.count() <= 0) continue;
    if (!first) out += ",";
    first = false;
    out += jsonlite::Quote(name);
    out += ":{\"count\":" + std::to_string(it->second.count()) +
           ",\"p50_ms\":" + Fixed3(it->second.Quantile(0.50)) +
           ",\"p99_ms\":" + Fixed3(it->second.Quantile(0.99)) + "}";
  }
  out += "},\"serialized\":" +
         jsonlite::Quote(agg::SerializeStageSketches(sketches_)) + "}";
  return out;
}

void StageSlo::Clear() {
  std::lock_guard<std::mutex> lock(mu_);
  samples_.clear();
  sketches_.clear();
  folded_ = 0;
  retired_ = 0;
  last_change_ = 0;
}

StageSlo& DefaultSlo() {
  static StageSlo* slo = new StageSlo();
  return *slo;
}

}  // namespace obs
}  // namespace tfd

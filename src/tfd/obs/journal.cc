#include "tfd/obs/journal.h"

#include <chrono>
#include <cstdio>

#include "tfd/obs/metrics.h"
#include "tfd/util/jsonlite.h"
#include "tfd/util/logging.h"

namespace tfd {
namespace obs {

namespace {

double WallNow() {
  return std::chrono::duration<double>(
             std::chrono::system_clock::now().time_since_epoch())
      .count();
}

std::string FormatSeconds(double s) {
  char buf[32];
  snprintf(buf, sizeof(buf), "%.3f", s);
  return buf;
}

}  // namespace

std::string EventJson(const Event& event) {
  std::string out = "{\"seq\":" + std::to_string(event.seq) +
                    ",\"ts\":" + FormatSeconds(event.wall_time_s) +
                    ",\"generation\":" + std::to_string(event.generation) +
                    ",\"change\":" + std::to_string(event.change) +
                    ",\"type\":" + jsonlite::Quote(event.type) +
                    ",\"source\":" + jsonlite::Quote(event.source) +
                    ",\"message\":" + jsonlite::Quote(event.message) +
                    ",\"fields\":{";
  bool first = true;
  for (const auto& [k, v] : event.fields) {
    if (!first) out += ",";
    first = false;
    out += jsonlite::Quote(k) + ":" + jsonlite::Quote(v);
  }
  return out + "}}";
}

Journal::Journal(size_t capacity, bool metrics)
    : capacity_(capacity == 0 ? 1 : capacity), metrics_(metrics) {}

void Journal::SetCapacity(size_t capacity) {
  uint64_t dropped_now = 0;
  {
    std::lock_guard<std::mutex> lock(mu_);
    capacity_ = capacity == 0 ? 1 : capacity;
    while (events_.size() > capacity_) {
      events_.pop_front();
      dropped_++;
      dropped_now++;
    }
  }
  if (metrics_ && dropped_now > 0) {
    Default()
        .GetCounter("tfd_journal_dropped_total",
                    "Journal events evicted by the bounded ring buffer "
                    "(drop-oldest).")
        ->Inc(static_cast<double>(dropped_now));
  }
}

size_t Journal::capacity() const {
  std::lock_guard<std::mutex> lock(mu_);
  return capacity_;
}

void Journal::Record(
    const std::string& type, const std::string& source,
    const std::string& message,
    std::vector<std::pair<std::string, std::string>> fields) {
  Event event;
  event.wall_time_s = WallNow();
  // Sanitize at ingestion: payloads can carry arbitrary bytes (probe
  // error strings from a wedged libtpu), but /debug/journal and the
  // SIGUSR1 dump must stay decodable by strict UTF-8 consumers
  // (Python json.load) — jsonlite::Quote escapes but does not validate.
  event.type = jsonlite::SanitizeUtf8(type);
  event.source = jsonlite::SanitizeUtf8(source);
  event.message = jsonlite::SanitizeUtf8(message);
  event.fields.reserve(fields.size());
  for (auto& [k, v] : fields) {
    event.fields.emplace_back(jsonlite::SanitizeUtf8(k),
                              jsonlite::SanitizeUtf8(v));
  }
  bool dropped = false;
  {
    std::lock_guard<std::mutex> lock(mu_);
    event.seq = next_seq_++;
    event.generation = generation_;
    event.change = change_;
    if (events_.size() >= capacity_) {
      events_.pop_front();
      dropped_++;
      dropped = true;
    }
    events_.push_back(std::move(event));
  }
  if (!metrics_) return;
  Registry& reg = Default();
  // The sanitized type also labels the counter — raw bytes must not
  // reach the exposition through the metrics side door.
  reg.GetCounter("tfd_journal_events_total",
                 "Flight-recorder events appended to the journal, by "
                 "event type.",
                 {{"type", jsonlite::SanitizeUtf8(type)}})
      ->Inc();
  Counter* dropped_counter = reg.GetCounter(
      "tfd_journal_dropped_total",
      "Journal events evicted by the bounded ring buffer (drop-oldest).");
  if (dropped) dropped_counter->Inc();
}

uint64_t Journal::BeginRewrite(uint64_t change) {
  uint64_t generation;
  {
    std::lock_guard<std::mutex> lock(mu_);
    generation = ++generation_;
    change_ = change;
  }
  log::SetCurrentGeneration(generation);
  log::SetCurrentChange(change);
  return generation;
}

uint64_t Journal::generation() const {
  std::lock_guard<std::mutex> lock(mu_);
  return generation_;
}

uint64_t Journal::change() const {
  std::lock_guard<std::mutex> lock(mu_);
  return change_;
}

std::vector<Event> Journal::Snapshot(size_t n,
                                     const std::string& type) const {
  std::lock_guard<std::mutex> lock(mu_);
  std::vector<Event> out;
  for (const Event& event : events_) {
    if (!type.empty() && event.type != type) continue;
    out.push_back(event);
  }
  if (n > 0 && out.size() > n) {
    out.erase(out.begin(), out.end() - static_cast<ptrdiff_t>(n));
  }
  return out;
}

uint64_t Journal::dropped_total() const {
  std::lock_guard<std::mutex> lock(mu_);
  return dropped_;
}

uint64_t Journal::next_seq() const {
  std::lock_guard<std::mutex> lock(mu_);
  return next_seq_;
}

std::string Journal::RenderJson(size_t n, const std::string& type) const {
  std::vector<Event> events = Snapshot(n, type);
  uint64_t capacity;
  uint64_t dropped;
  uint64_t generation;
  uint64_t change;
  {
    std::lock_guard<std::mutex> lock(mu_);
    capacity = capacity_;
    dropped = dropped_;
    generation = generation_;
    change = change_;
  }
  std::string out = "{\"capacity\":" + std::to_string(capacity) +
                    ",\"dropped_total\":" + std::to_string(dropped) +
                    ",\"generation\":" + std::to_string(generation) +
                    ",\"change\":" + std::to_string(change) +
                    ",\"events\":[";
  for (size_t i = 0; i < events.size(); i++) {
    if (i) out += ",";
    out += EventJson(events[i]);
  }
  return out + "]}";
}

Journal& DefaultJournal() {
  static Journal* journal = new Journal();
  return *journal;
}

}  // namespace obs
}  // namespace tfd

// Node-side fleet SLO engine: windowed per-stage latency sketches.
//
// PR 14's TraceRecorder decomposes every closed change into per-stage
// timestamps; this module SPENDS that instrument. Each change the sink
// publish-acks folds its stage durations (plan / render / publish /
// publish-acked, milliseconds) into one removable+mergeable quantile
// sketch per stage (agg/agg.h QuantileSketch — the same digest the
// aggregator's perf floors use), WINDOWED by retire-oldest: every fold
// also expires samples older than --slo-window seconds, so the view is
// "the last N minutes", not "since boot". A node that was slow
// yesterday and healed stops indicting itself.
//
// Exported three ways:
//   - /debug/slo (obs/server.cc): RenderJson — window, per-stage
//     count/p50/p99 and the serialized sketch set (byte-parity-pinned
//     against the tpufd.trace.StageSlo twin);
//   - the tfd.google.com/stage-slo CR ANNOTATION (kSloAnnotation,
//     next to the change-id annotation, never spec.labels): Serialize
//     — the aggregator parses and merges every node's contribution
//     into the fleet tpu.obs.stage.* percentiles and burns them
//     against budgets (agg::BurnEvaluator);
//   - the SIGUSR1 post-mortem dump ("slo" section, next to the trace
//     ring and published labels).
//
// Quiet-daemon contract: a pass that publishes nothing folds nothing —
// the tracker costs nothing when nothing moves (the BENCH_r07/r11
// steady no-op gates stay untouched).
#pragma once

#include <cstdint>
#include <deque>
#include <map>
#include <mutex>
#include <string>
#include <utility>
#include <vector>

#include "tfd/agg/agg.h"
#include "tfd/obs/trace.h"

namespace tfd {
namespace obs {

// The CR annotation key the serialized stage sketches ride outward on
// (metadata.annotations — NEVER spec.labels; latency digests must not
// become scheduler-visible eligibility input).
inline constexpr char kSloAnnotation[] = "tfd.google.com/stage-slo";

// Per-stage durations (ms) of one closed trace record, sliced by the
// same interval rule as RenderChromeTrace: each stage's duration runs
// from the previous stamp (minted_ts first) to its own stamp, clamped
// at 0 against clock steps. "govern" is folded into "render" — the
// SLO vocabulary is the four agg::kSloStages; unknown stages are
// dropped.
std::map<std::string, double> StageDurationsMs(const TraceRecord& record);

class StageSlo {
 public:
  static constexpr int kDefaultWindowS = 600;

  explicit StageSlo(int window_s = kDefaultWindowS);

  // Reconfigurable at a config load (--slo-window); shrinking expires
  // eagerly on the next Fold/Expire.
  void SetWindow(int window_s);
  int window_s() const;

  // Folds one closed change's stage durations (ms) and expires
  // anything older than the window. `now_s` < 0 uses the wall clock
  // (tests inject fixed times for the parity pins).
  void Fold(uint64_t change, const std::map<std::string, double>& stage_ms,
            double now_s = -1);

  // Retire-oldest pass without a fold (the introspection reads call
  // this so a quiet daemon's view still ages out).
  void Expire(double now_s = -1);

  int64_t folded_total() const;
  int64_t retired_total() const;
  int64_t samples() const;

  // Copy of the current per-stage sketches (empty stages absent).
  agg::StageSketches Snapshot() const;

  // The annotation payload (agg::SerializeStageSketches of the
  // current window; "" when empty).
  std::string Serialize() const;

  // {"window_s":..,"samples":..,"folded_total":..,"retired_total":..,
  //  "last_change":..,"stages":{"plan":{"count":..,"p50_ms":..,
  //  "p99_ms":..},..},"serialized":".."} — what /debug/slo serves and
  //  the SIGUSR1 dump embeds; byte-parity with the Python twin.
  std::string RenderJson() const;

  void Clear();

 private:
  struct Sample {
    double ts = 0;
    std::vector<std::pair<std::string, double>> stages;  // (stage, ms)
  };

  void ExpireLocked(double now);

  mutable std::mutex mu_;
  int window_s_;
  std::deque<Sample> samples_;
  agg::StageSketches sketches_;
  int64_t folded_ = 0;
  int64_t retired_ = 0;
  uint64_t last_change_ = 0;
};

// The process-wide tracker (the analogue of DefaultTrace()): survives
// SIGHUP reloads so the window spans the reload itself.
StageSlo& DefaultSlo();

}  // namespace obs
}  // namespace tfd

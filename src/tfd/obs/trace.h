// Causal label-propagation tracing: a bounded, lock-cheap recorder of
// CHANGE-IDs — one monotone id minted at the origin of every
// label-moving event (probe-snapshot movement, slice verdict adoption,
// lifecycle edge, watch-drift heal, config regeneration) — plus the
// per-stage timestamps the change accumulates as it flows through the
// pass pipeline (plan → render → govern → publish → publish-acked).
//
// The journal (obs/journal.h) answers WHY a node carries its labels;
// the metrics say HOW MUCH happened. Neither can decompose the
// headline latency (BENCH_cluster's label-to-placement p99) into
// per-hop budgets, because the causal chain crosses processes: probe
// edge → daemon pass → apiserver → aggregator → scheduler. The change
// id is the join key for that chain: it rides outward as a CR
// ANNOTATION on SSA writes (annotations, not labels — the schema and
// scheduler eligibility are untouched), is echoed by the slice
// blackboard verdict and the aggregator's inventory object, and is
// carried by journal events (Event::change), --log-format=json lines,
// and the /debug/trace introspection endpoint alongside the existing
// rewrite generation.
//
// Bounded by construction, like the journal: fixed capacity
// (--trace-capacity, default 256), drop-oldest with drops counted in
// tfd_trace_dropped_total, and tfd_trace_active gauging the records
// minted but not yet publish-acked. Lock-cheap: one mutex, O(1) mints,
// O(active) stage stamps — and a quiet daemon mints nothing, so
// tracing is free when nothing moves (the steady-state no-op contract
// bench_gate enforces).
//
// Exported two ways: JSON on /debug/trace?n=&change= (and folded into
// the SIGUSR1 post-mortem dump), and a Chrome trace-event document
// (Perfetto-loadable) via RenderChromeTrace — written to --trace-dump
// on SIGUSR1. tpufd/trace.py is the byte-parity-pinned Python twin.
#pragma once

#include <cstdint>
#include <deque>
#include <mutex>
#include <string>
#include <utility>
#include <vector>

namespace tfd {
namespace obs {

// The CR annotation key the latest active change id rides outward on
// (metadata.annotations — NEVER spec.labels; the change id must not
// become scheduler-visible eligibility input).
inline constexpr char kChangeAnnotation[] = "tfd.google.com/change-id";

// One traced change. `stages` is an append-ordered (name, wall time)
// list — first-wins per stage name, so the list is monotone in stamp
// time. All strings are sanitized at ingestion (hostile probe bytes
// must not break /debug/trace exposition — fuzz_journal.cc pins it).
struct TraceRecord {
  uint64_t change = 0;      // monotone, minted at the origin
  uint64_t generation = 0;  // rewrite generation that published it
  double minted_ts = 0;     // unix time, sub-second resolution
  std::string origin;       // "snapshot", "slice-verdict", "lifecycle",
                            // "watch-drift", "config", ...
  std::string source;       // probe source / "" when not applicable
  std::string detail;       // one human-readable line
  bool published = false;   // publish-acked by the sink
  std::vector<std::pair<std::string, double>> stages;
};

class TraceRecorder {
 public:
  static constexpr size_t kDefaultCapacity = 256;

  // `metrics` wires tfd_trace_{active,dropped_total} into
  // obs::Default(); the fuzz target disables it so hostile inputs
  // cannot grow the process registry.
  explicit TraceRecorder(size_t capacity = kDefaultCapacity,
                         bool metrics = true);

  // Capacity is reconfigurable at a config load (--trace-capacity);
  // shrinking drops oldest records (counted as drops).
  void SetCapacity(size_t capacity);
  size_t capacity() const;

  // Mints a new change id at a label-moving origin. `now_s` < 0 uses
  // the wall clock (tests inject fixed times for the parity pins).
  uint64_t Mint(const std::string& origin, const std::string& source,
                const std::string& detail, double now_s = -1);

  // Stamps `stage` on every ACTIVE (not yet published) record that
  // does not already carry it — the pass pipeline calls this once per
  // stage boundary and every in-flight change accumulates the
  // timestamp (first-wins: a change spanning two passes keeps the
  // FIRST pass's stamps; the pass that publishes it acks it below).
  void Stage(const std::string& stage, double now_s = -1);

  // The sink acked a write: every active record with change id <=
  // `through_change` is stamped with the terminal "publish-acked"
  // stage, tagged with the publishing rewrite `generation`, and
  // retired from the active set. The pass passes the change it
  // captured at BeginRewrite time — a change a probe worker mints
  // CONCURRENTLY with the pass was not in its render, must not be
  // acked by it, and stays active for the pass its movement wakes.
  // The default (max) retires everything active (tests, fuzz).
  // Returns copies of the records retired by THIS call (terminal
  // "publish-acked" stamp included) — the caller folds their stage
  // durations into the SLO sketches (obs/slo.h) and mints the
  // publish-acked histogram samples with change-id exemplars.
  std::vector<TraceRecord> MarkPublished(uint64_t generation,
                                         double now_s = -1,
                                         uint64_t through_change = ~0ull);

  // Highest change id minted but not yet publish-acked (0 = none):
  // what BeginRewrite() and the CR annotation carry.
  uint64_t LatestActiveChange() const;
  // Highest change id ever minted (0 = none yet).
  uint64_t LatestChange() const;

  size_t active() const;
  uint64_t dropped_total() const;

  // {"capacity":..,"dropped_total":..,"active":..,"minted_total":..,
  //  "records":[..]} — what /debug/trace serves and the SIGUSR1 dump
  // embeds. `n` keeps the newest n records (0 = all retained);
  // `change` non-zero filters to that exact change id.
  std::string RenderJson(size_t n = 0, uint64_t change = 0) const;

  // Chrome trace-event JSON (Perfetto/chrome://tracing loadable): one
  // complete ("ph":"X") event per stage interval, tid = change id, so
  // each change renders as its own track of plan/render/govern/publish
  // slices. Written to --trace-dump on SIGUSR1.
  std::string RenderChromeTrace() const;

 private:
  std::vector<TraceRecord> Snapshot(size_t n, uint64_t change) const;
  void UpdateGauge() const;  // call with mu_ held

  mutable std::mutex mu_;
  size_t capacity_;
  bool metrics_;
  std::deque<TraceRecord> records_;
  uint64_t next_change_ = 1;
  uint64_t dropped_ = 0;
};

// The process-wide recorder (the analogue of DefaultJournal()):
// survives SIGHUP reloads so in-flight changes span the reload itself.
TraceRecorder& DefaultTrace();

}  // namespace obs
}  // namespace tfd

#include "tfd/obs/metrics.h"

#include <algorithm>
#include <cctype>
#include <cmath>
#include <cstdio>
#include <cstdlib>
#include <limits>
#include <map>
#include <sstream>

namespace tfd {
namespace obs {

namespace {

enum MetricType { kCounter = 0, kGauge = 1, kHistogram = 2 };

const char* TypeName(int type) {
  switch (type) {
    case kCounter: return "counter";
    case kGauge: return "gauge";
    default: return "histogram";
  }
}

// Prometheus metric-name grammar: [a-zA-Z_:][a-zA-Z0-9_:]*. Sanitizing at
// registration (instead of rejecting) keeps the exposition valid for any
// input — hostile names from the fuzzer included — at the cost of
// possibly merging two degenerate names; real call sites use literals.
std::string SanitizeMetricName(const std::string& name) {
  std::string out;
  out.reserve(name.size());
  for (char c : name) {
    bool ok = std::isalnum(static_cast<unsigned char>(c)) || c == '_' ||
              c == ':';
    out.push_back(ok ? c : '_');
  }
  if (out.empty()) out = "_";
  if (std::isdigit(static_cast<unsigned char>(out[0]))) out.insert(0, "_");
  return out;
}

// Label names additionally exclude ':' (reserved for recording rules).
std::string SanitizeLabelName(const std::string& name) {
  std::string out = SanitizeMetricName(name);
  std::replace(out.begin(), out.end(), ':', '_');
  return out;
}

// Escaping for label VALUES: \ " and newline (text format 0.0.4).
std::string EscapeLabelValue(const std::string& v) {
  std::string out;
  out.reserve(v.size());
  for (char c : v) {
    if (c == '\\') out += "\\\\";
    else if (c == '"') out += "\\\"";
    else if (c == '\n') out += "\\n";
    else out.push_back(c);
  }
  return out;
}

// Escaping for HELP text: only \ and newline (quotes are legal there).
std::string EscapeHelp(const std::string& v) {
  std::string out;
  out.reserve(v.size());
  for (char c : v) {
    if (c == '\\') out += "\\\\";
    else if (c == '\n') out += "\\n";
    else out.push_back(c);
  }
  return out;
}

std::string FormatValue(double v) {
  if (std::isnan(v)) return "NaN";
  if (std::isinf(v)) return v > 0 ? "+Inf" : "-Inf";
  // %.17g round-trips every double; trim the noise for the common exact
  // cases (counters, millisecond-scale durations) via shortest-exact.
  char buf[64];
  for (int prec = 6; prec <= 17; prec++) {
    snprintf(buf, sizeof(buf), "%.*g", prec, v);
    if (std::strtod(buf, nullptr) == v) break;
  }
  return buf;
}

Labels SanitizeLabels(const Labels& labels) {
  Labels out;
  out.reserve(labels.size());
  for (const auto& [k, v] : labels) out.emplace_back(SanitizeLabelName(k), v);
  return out;
}

std::string RenderLabels(const Labels& labels, const char* extra_key,
                         const std::string& extra_value) {
  if (labels.empty() && extra_key == nullptr) return "";
  std::string out = "{";
  bool first = true;
  for (const auto& [k, v] : labels) {
    if (!first) out += ",";
    first = false;
    out += k + "=\"" + EscapeLabelValue(v) + "\"";
  }
  if (extra_key != nullptr) {
    if (!first) out += ",";
    out += std::string(extra_key) + "=\"" + extra_value + "\"";
  }
  out += "}";
  return out;
}

}  // namespace

void Counter::Inc(double v) {
  if (!(v > 0)) return;  // counters only go up; NaN/negative dropped
  double cur = value_.load(std::memory_order_relaxed);
  while (!value_.compare_exchange_weak(cur, cur + v,
                                       std::memory_order_relaxed)) {
  }
}

Histogram::Histogram(std::vector<double> upper_bounds) {
  std::sort(upper_bounds.begin(), upper_bounds.end());
  for (double b : upper_bounds) {
    if (!std::isfinite(b)) continue;  // +Inf is implicit, NaN is nonsense
    if (!upper_bounds_.empty() && upper_bounds_.back() == b) continue;
    upper_bounds_.push_back(b);
  }
  counts_.reserve(upper_bounds_.size());
  for (size_t i = 0; i < upper_bounds_.size(); i++) {
    counts_.push_back(std::make_unique<std::atomic<unsigned long long>>(0));
  }
  exemplars_.resize(upper_bounds_.size() + 1);  // trailing slot = +Inf
}

void Histogram::Observe(double v) {
  if (std::isnan(v)) return;
  size_t i = std::lower_bound(upper_bounds_.begin(), upper_bounds_.end(), v) -
             upper_bounds_.begin();
  if (i < counts_.size()) {
    counts_[i]->fetch_add(1, std::memory_order_relaxed);
  } else {
    overflow_.fetch_add(1, std::memory_order_relaxed);
  }
  double cur = sum_.load(std::memory_order_relaxed);
  while (!sum_.compare_exchange_weak(cur, cur + v,
                                     std::memory_order_relaxed)) {
  }
  count_.fetch_add(1, std::memory_order_relaxed);
}

void Histogram::Observe(double v, const Labels& exemplar) {
  if (std::isnan(v)) return;
  Observe(v);
  size_t i = std::lower_bound(upper_bounds_.begin(), upper_bounds_.end(), v) -
             upper_bounds_.begin();  // == counts_.size() -> the +Inf slot
  std::lock_guard<std::mutex> lock(exemplar_mu_);
  exemplars_[i] = Exemplar{SanitizeLabels(exemplar), v, true};
}

unsigned long long Histogram::CumulativeCount(size_t i) const {
  unsigned long long total = 0;
  for (size_t j = 0; j <= i && j < counts_.size(); j++) {
    total += counts_[j]->load(std::memory_order_relaxed);
  }
  return total;
}

Histogram::Snapshot Histogram::TakeSnapshot() const {
  Snapshot snap;
  snap.cumulative.reserve(counts_.size());
  unsigned long long running = 0;
  for (const auto& count : counts_) {
    running += count->load(std::memory_order_relaxed);
    snap.cumulative.push_back(running);
  }
  snap.total = running + overflow_.load(std::memory_order_relaxed);
  snap.sum = sum_.load(std::memory_order_relaxed);
  {
    std::lock_guard<std::mutex> lock(exemplar_mu_);
    snap.exemplars = exemplars_;
  }
  return snap;
}

std::vector<double> DurationBuckets() {
  return {0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
          0.5,    1,     2.5,    5,     10,   30,    60,   120, 300};
}

struct Registry::Child {
  Labels labels;
  std::unique_ptr<Counter> counter;
  std::unique_ptr<Gauge> gauge;
  std::unique_ptr<Histogram> histogram;
};

struct Registry::Family {
  std::string name;
  std::string help;
  int type = kCounter;
  std::vector<std::unique_ptr<Child>> children;
};

Registry::Registry() = default;
Registry::~Registry() = default;

Registry::Child* Registry::GetChild(const std::string& raw_name,
                                    const std::string& help, int type,
                                    const Labels& raw_labels,
                                    const std::vector<double>* upper_bounds) {
  std::string name = SanitizeMetricName(raw_name);
  Labels labels = SanitizeLabels(raw_labels);
  // Dedupe (last wins) and, on histograms, free the reserved `le` label —
  // a caller-supplied `le` would collide with the generated bucket label.
  Labels deduped;
  for (auto& [k, v] : labels) {
    std::string key = (type == kHistogram && k == "le") ? "exported_le" : k;
    bool replaced = false;
    for (auto& [dk, dv] : deduped) {
      if (dk == key) {
        dv = v;
        replaced = true;
        break;
      }
    }
    if (!replaced) deduped.emplace_back(key, v);
  }
  labels = std::move(deduped);

  // Sample-name collision guard: a family must not emit sample lines
  // that collide with another family's — neither a plain metric named
  // like an existing histogram's generated h_bucket/_sum/_count series,
  // nor a new histogram whose generated names hit an existing family.
  // Such output is ambiguous to every consumer, so the newcomer is
  // renamed (trailing '_') until its names are free. The loop re-runs
  // the exact-name lookup after each rename, so repeat registrations of
  // a renamed metric land on the SAME family, not a fresh one.
  auto series_names = [](const std::string& n, int t) {
    std::vector<std::string> names = {n};
    if (t == kHistogram) {
      names.push_back(n + "_bucket");
      names.push_back(n + "_sum");
      names.push_back(n + "_count");
    }
    return names;
  };
  Family* family = nullptr;
  while (true) {
    for (auto& f : families_) {
      if (f->name == name) {
        family = f.get();
        break;
      }
    }
    if (family != nullptr) break;  // exact reuse (type checked below)
    bool collides = false;
    for (const auto& f : families_) {
      for (const std::string& theirs : series_names(f->name, f->type)) {
        for (const std::string& ours : series_names(name, type)) {
          if (ours == theirs) collides = true;
        }
      }
    }
    if (!collides) break;
    name += "_";
  }
  if (family == nullptr) {
    families_.push_back(std::make_unique<Family>());
    family = families_.back().get();
    family->name = name;
    family->help = help;
    family->type = type;
  }
  if (family->type != type) return nullptr;  // caller hands out an orphan

  for (auto& child : family->children) {
    if (child->labels == labels) return child.get();
  }
  family->children.push_back(std::make_unique<Child>());
  Child* child = family->children.back().get();
  child->labels = std::move(labels);
  switch (type) {
    case kCounter:
      child->counter = std::make_unique<Counter>();
      break;
    case kGauge:
      child->gauge = std::make_unique<Gauge>();
      break;
    default:
      child->histogram = std::make_unique<Histogram>(
          upper_bounds != nullptr ? *upper_bounds : DurationBuckets());
      break;
  }
  return child;
}

Counter* Registry::GetCounter(const std::string& name, const std::string& help,
                              const Labels& labels) {
  std::lock_guard<std::mutex> lock(mu_);
  Child* child = GetChild(name, help, kCounter, labels, nullptr);
  if (child != nullptr) return child->counter.get();
  orphan_counters_.push_back(std::make_unique<Counter>());
  return orphan_counters_.back().get();
}

Gauge* Registry::GetGauge(const std::string& name, const std::string& help,
                          const Labels& labels) {
  std::lock_guard<std::mutex> lock(mu_);
  Child* child = GetChild(name, help, kGauge, labels, nullptr);
  if (child != nullptr) return child->gauge.get();
  orphan_gauges_.push_back(std::make_unique<Gauge>());
  return orphan_gauges_.back().get();
}

Histogram* Registry::GetHistogram(const std::string& name,
                                  const std::string& help,
                                  std::vector<double> upper_bounds,
                                  const Labels& labels) {
  std::lock_guard<std::mutex> lock(mu_);
  Child* child = GetChild(name, help, kHistogram, labels, &upper_bounds);
  if (child != nullptr) return child->histogram.get();
  orphan_histograms_.push_back(
      std::make_unique<Histogram>(std::move(upper_bounds)));
  return orphan_histograms_.back().get();
}

std::string Registry::Exposition() const {
  std::lock_guard<std::mutex> lock(mu_);
  std::string out;
  for (const auto& f : families_) {
    out += "# HELP " + f->name + " " + EscapeHelp(f->help) + "\n";
    out += "# TYPE " + f->name + " " + TypeName(f->type) + "\n";
    for (const auto& child : f->children) {
      if (f->type == kCounter) {
        out += f->name + RenderLabels(child->labels, nullptr, "") + " " +
               FormatValue(child->counter->Value()) + "\n";
      } else if (f->type == kGauge) {
        out += f->name + RenderLabels(child->labels, nullptr, "") + " " +
               FormatValue(child->gauge->Value()) + "\n";
      } else {
        const Histogram& h = *child->histogram;
        const Histogram::Snapshot snap = h.TakeSnapshot();
        auto exemplar_suffix = [&snap](size_t i) -> std::string {
          if (i >= snap.exemplars.size() || !snap.exemplars[i].set) {
            return "";
          }
          const Histogram::Exemplar& e = snap.exemplars[i];
          std::string labels = RenderLabels(e.labels, nullptr, "");
          if (labels.empty()) labels = "{}";
          return " # " + labels + " " + FormatValue(e.value);
        };
        for (size_t i = 0; i < h.upper_bounds().size(); i++) {
          out += f->name + "_bucket" +
                 RenderLabels(child->labels, "le",
                              FormatValue(h.upper_bounds()[i])) +
                 " " + std::to_string(snap.cumulative[i]) +
                 exemplar_suffix(i) + "\n";
        }
        out += f->name + "_bucket" +
               RenderLabels(child->labels, "le", "+Inf") + " " +
               std::to_string(snap.total) +
               exemplar_suffix(h.upper_bounds().size()) + "\n";
        out += f->name + "_sum" + RenderLabels(child->labels, nullptr, "") +
               " " + FormatValue(snap.sum) + "\n";
        out += f->name + "_count" + RenderLabels(child->labels, nullptr, "") +
               " " + std::to_string(snap.total) + "\n";
      }
    }
  }
  return out;
}

Registry& Default() {
  // Meyers singleton (destroyed at exit, LeakSanitizer-clean): safe
  // because the daemon stops the introspection server — the only other
  // thread touching the registry — before Main returns.
  static Registry registry;
  return registry;
}

// ---- exposition validation ----------------------------------------------

namespace {

bool ValidMetricName(const std::string& s) {
  if (s.empty()) return false;
  if (std::isdigit(static_cast<unsigned char>(s[0]))) return false;
  for (char c : s) {
    if (!(std::isalnum(static_cast<unsigned char>(c)) || c == '_' ||
          c == ':')) {
      return false;
    }
  }
  return true;
}

// Parses `metric_name{labels} value` — optionally followed by an
// OpenMetrics exemplar (` # {labels} value`, no timestamp: this build
// never emits one) — into its parts. Returns false (with *error set)
// on any grammar violation.
struct Sample {
  std::string name;
  std::map<std::string, std::string> labels;
  double value = 0;
  bool has_exemplar = false;
  std::map<std::string, std::string> exemplar_labels;
  double exemplar_value = 0;
};

// Parses a `{k="v",...}` block starting at line[*i] == '{'; leaves *i
// just past the closing brace.
bool ParseLabelBlock(const std::string& line, size_t* pos,
                     std::map<std::string, std::string>* labels,
                     std::string* error) {
  size_t i = *pos + 1;  // past '{'
  while (i < line.size() && line[i] != '}') {
    size_t key_start = i;
    while (i < line.size() && line[i] != '=') i++;
    std::string key = line.substr(key_start, i - key_start);
    if (!ValidMetricName(key) || key.find(':') != std::string::npos) {
      *error = "invalid label name '" + key + "' in: " + line;
      return false;
    }
    if (i + 1 >= line.size() || line[i + 1] != '"') {
      *error = "label value not quoted in: " + line;
      return false;
    }
    i += 2;
    std::string value;
    while (i < line.size() && line[i] != '"') {
      if (line[i] == '\\') {
        if (i + 1 >= line.size()) {
          *error = "dangling escape in: " + line;
          return false;
        }
        char esc = line[i + 1];
        if (esc != '\\' && esc != '"' && esc != 'n') {
          *error = "invalid escape \\" + std::string(1, esc) +
                   " in: " + line;
          return false;
        }
        value.push_back(esc == 'n' ? '\n' : esc);
        i += 2;
      } else {
        value.push_back(line[i++]);
      }
    }
    if (i >= line.size()) {
      *error = "unterminated label value in: " + line;
      return false;
    }
    i++;  // closing quote
    if (labels->count(key) != 0) {
      *error = "duplicate label '" + key + "' in: " + line;
      return false;
    }
    (*labels)[key] = value;
    if (i < line.size() && line[i] == ',') i++;
  }
  if (i >= line.size()) {
    *error = "unterminated label set in: " + line;
    return false;
  }
  *pos = i + 1;  // past '}'
  return true;
}

bool ParseValueText(const std::string& value_text, const std::string& line,
                    double* out, std::string* error) {
  if (value_text == "+Inf") {
    *out = std::numeric_limits<double>::infinity();
  } else if (value_text == "-Inf") {
    *out = -std::numeric_limits<double>::infinity();
  } else if (value_text == "NaN") {
    *out = std::numeric_limits<double>::quiet_NaN();
  } else {
    char* end = nullptr;
    *out = std::strtod(value_text.c_str(), &end);
    if (end == value_text.c_str() || *end != '\0') {
      *error = "unparseable value '" + value_text + "' in: " + line;
      return false;
    }
  }
  return true;
}

bool ParseSample(const std::string& line, Sample* out, std::string* error) {
  size_t i = 0;
  while (i < line.size() &&
         (std::isalnum(static_cast<unsigned char>(line[i])) ||
          line[i] == '_' || line[i] == ':')) {
    i++;
  }
  out->name = line.substr(0, i);
  if (!ValidMetricName(out->name)) {
    *error = "invalid metric name in sample: " + line;
    return false;
  }
  if (i < line.size() && line[i] == '{') {
    if (!ParseLabelBlock(line, &i, &out->labels, error)) return false;
  }
  if (i >= line.size() || line[i] != ' ') {
    *error = "missing value separator in: " + line;
    return false;
  }
  std::string rest = line.substr(i + 1);
  std::string value_text = rest;
  // OpenMetrics exemplar section: `<value> # {labels} <exemplar-value>`.
  // The split is safe on the raw value text — a value can never contain
  // a quoted string, so " # " there is unambiguous.
  size_t hash = rest.find(" # ");
  if (hash != std::string::npos) {
    value_text = rest.substr(0, hash);
    std::string exemplar = rest.substr(hash + 3);
    if (exemplar.empty() || exemplar[0] != '{') {
      *error = "exemplar without label set in: " + line;
      return false;
    }
    size_t j = 0;
    if (!ParseLabelBlock(exemplar, &j, &out->exemplar_labels, error)) {
      return false;
    }
    if (j >= exemplar.size() || exemplar[j] != ' ') {
      *error = "exemplar missing value in: " + line;
      return false;
    }
    std::string exemplar_value = exemplar.substr(j + 1);
    if (exemplar_value.empty() ||
        exemplar_value.find(' ') != std::string::npos) {
      // An exemplar timestamp is legal OpenMetrics but this build never
      // emits one (determinism); strict about OUR output.
      *error = "malformed exemplar value in: " + line;
      return false;
    }
    if (!ParseValueText(exemplar_value, line, &out->exemplar_value, error)) {
      return false;
    }
    // The OpenMetrics exemplar length budget: label names + values
    // combined must not exceed 128 characters.
    size_t runes = 0;
    for (const auto& [k, v] : out->exemplar_labels) {
      runes += k.size() + v.size();
    }
    if (runes > 128) {
      *error = "exemplar label set over the 128-character budget in: " +
               line;
      return false;
    }
    out->has_exemplar = true;
  }
  if (value_text.empty() || value_text.find(' ') != std::string::npos) {
    // A trailing timestamp is legal Prometheus but this build never emits
    // one; flagging it keeps the validator strict about OUR output.
    *error = "malformed value field in: " + line;
    return false;
  }
  return ParseValueText(value_text, line, &out->value, error);
}

// The family a sample belongs to: an exactly-named family wins (a
// counter that happens to be called h_bucket is its own family), else a
// histogram series suffix attributes to its base. The registry prevents
// the ambiguous case (an h_bucket family next to a histogram h) at
// registration, so exact-first is unambiguous for registry output.
std::string BaseFamily(const std::string& name,
                       const std::map<std::string, std::string>& types) {
  if (types.count(name) != 0) return name;
  for (const char* suffix : {"_bucket", "_sum", "_count"}) {
    size_t n = std::string(suffix).size();
    if (name.size() > n && name.compare(name.size() - n, n, suffix) == 0) {
      std::string base = name.substr(0, name.size() - n);
      auto it = types.find(base);
      if (it != types.end() && it->second == "histogram") return base;
    }
  }
  return name;
}

}  // namespace

Status ValidateExposition(const std::string& text) {
  if (!text.empty() && text.back() != '\n') {
    return Status::Error("exposition must end with a newline");
  }
  std::map<std::string, std::string> types;  // family -> type
  // (family, serialized labels minus le) -> last cumulative bucket value,
  // for monotonicity; and the +Inf tracking for the _count cross-check.
  std::map<std::string, double> last_bucket;
  std::map<std::string, double> last_le;
  std::map<std::string, double> inf_bucket;
  std::map<std::string, double> counts;

  std::istringstream in(text);
  std::string line;
  while (std::getline(in, line)) {
    if (line.empty()) continue;
    if (line[0] == '#') {
      std::istringstream header(line);
      std::string hash, kind, name;
      header >> hash >> kind >> name;
      if (kind != "HELP" && kind != "TYPE") continue;  // comment
      if (!ValidMetricName(name)) {
        return Status::Error("invalid family name in: " + line);
      }
      if (kind == "TYPE") {
        std::string type;
        header >> type;
        if (type != "counter" && type != "gauge" && type != "histogram" &&
            type != "summary" && type != "untyped") {
          return Status::Error("invalid type in: " + line);
        }
        if (types.count(name) != 0) {
          return Status::Error("duplicate TYPE for " + name);
        }
        types[name] = type;
      }
      continue;
    }
    Sample sample;
    std::string error;
    if (!ParseSample(line, &sample, &error)) return Status::Error(error);
    std::string family = BaseFamily(sample.name, types);
    auto type_it = types.find(family);
    if (type_it == types.end()) {
      return Status::Error("sample for undeclared family: " + line);
    }
    if (sample.has_exemplar) {
      // OpenMetrics: exemplars attach to counters and histogram
      // buckets only — never gauges, _sum/_count, or untyped series.
      bool bucket_line = type_it->second == "histogram" &&
                         sample.name == family + "_bucket";
      if (!bucket_line && type_it->second != "counter") {
        return Status::Error("exemplar on a non-counter/non-bucket line: " +
                             line);
      }
    }
    if (type_it->second == "counter" &&
        !(sample.value >= 0 || std::isnan(sample.value))) {
      return Status::Error("negative counter: " + line);
    }
    if (type_it->second == "histogram" &&
        sample.name == family + "_bucket") {
      auto le_it = sample.labels.find("le");
      if (le_it == sample.labels.end()) {
        return Status::Error("histogram bucket without le: " + line);
      }
      double le;
      if (le_it->second == "+Inf") {
        le = std::numeric_limits<double>::infinity();
      } else {
        char* end = nullptr;
        le = std::strtod(le_it->second.c_str(), &end);
        if (end == le_it->second.c_str() || *end != '\0') {
          return Status::Error("unparseable le in: " + line);
        }
      }
      std::string series = family + "|";
      for (const auto& [k, v] : sample.labels) {
        if (k != "le") series += k + "=" + v + ";";
      }
      auto last = last_bucket.find(series);
      if (last != last_bucket.end()) {
        if (le <= last_le[series]) {
          return Status::Error("bucket le not increasing: " + line);
        }
        if (sample.value < last->second) {
          return Status::Error("bucket counts not cumulative: " + line);
        }
      }
      last_bucket[series] = sample.value;
      last_le[series] = le;
      if (std::isinf(le)) inf_bucket[series] = sample.value;
    }
    if (type_it->second == "histogram" && sample.name == family + "_count") {
      std::string series = family + "|";
      for (const auto& [k, v] : sample.labels) series += k + "=" + v + ";";
      counts[series] = sample.value;
    }
  }
  for (const auto& [series, count] : counts) {
    auto it = inf_bucket.find(series);
    if (it == inf_bucket.end()) {
      return Status::Error("histogram series without +Inf bucket: " + series);
    }
    if (it->second != count) {
      return Status::Error("+Inf bucket != _count for: " + series);
    }
  }
  return Status::Ok();
}

}  // namespace obs
}  // namespace tfd

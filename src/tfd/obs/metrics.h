// In-process metrics registry + Prometheus text exposition, no deps.
//
// The reference daemon is opaque at runtime: operators pair it with a
// separate dcgm-exporter for telemetry and infer liveness from pod logs.
// This build makes the daemon itself scrapeable (ROADMAP north star:
// per-node label-rewrite health for large fleets). The registry is sized
// for a single-writer daemon: the main loop (and the PJRT watchdog, which
// runs on the main thread) update instruments; the introspection server
// thread (obs/server.h) renders Exposition() concurrently — all values
// are atomics, so a scrape never blocks a labeling pass.
//
// Exposition follows the Prometheus text format (version 0.0.4): one
// `# HELP`/`# TYPE` block per family, label values escaped (\\, \", \n),
// histograms rendered as cumulative `_bucket{le=...}` series ending in
// `+Inf` plus `_sum`/`_count`. Families and children render in
// registration order, so output is deterministic — the same property the
// label file has (sorted labels), and what the golden-style tests and
// the CI metrics-lint rely on.
#pragma once

#include <atomic>
#include <chrono>
#include <memory>
#include <mutex>
#include <string>
#include <utility>
#include <vector>

#include "tfd/util/status.h"

namespace tfd {
namespace obs {

// Seconds elapsed since `t0` on the steady clock — the one timing
// helper behind every duration histogram (rewrite passes, labelers,
// backend probes, broker probes).
inline double SecondsSince(std::chrono::steady_clock::time_point t0) {
  return std::chrono::duration<double>(std::chrono::steady_clock::now() - t0)
      .count();
}

// Label set for one child of a metric family, in render order.
using Labels = std::vector<std::pair<std::string, std::string>>;

class Counter {
 public:
  void Inc(double v = 1.0);
  double Value() const { return value_.load(std::memory_order_relaxed); }

 private:
  std::atomic<double> value_{0.0};
};

class Gauge {
 public:
  void Set(double v) { value_.store(v, std::memory_order_relaxed); }
  double Value() const { return value_.load(std::memory_order_relaxed); }

 private:
  std::atomic<double> value_{0.0};
};

class Histogram {
 public:
  explicit Histogram(std::vector<double> upper_bounds);
  // NaN observations are dropped (they would poison _sum forever and
  // cannot be bucketed); +/-inf land in the +Inf bucket like any other
  // out-of-range value.
  void Observe(double v);
  // Observe plus an OpenMetrics exemplar: `exemplar` (e.g.
  // {{"change_id","42"}}) is remembered for the bucket `v` lands in
  // (last write wins) and rendered after that bucket's sample as
  // `... # {change_id="42"} <v>` — one click from a fleet-p99 spike to
  // the exact change and its journal/Perfetto trail. The exemplar
  // store is mutex-guarded (labels are strings); the exemplar-free
  // Observe above stays lock-free for the hot path.
  void Observe(double v, const Labels& exemplar);

  struct Exemplar {
    Labels labels;
    double value = 0;
    bool set = false;
  };

  const std::vector<double>& upper_bounds() const { return upper_bounds_; }
  // One coherent read of the whole histogram: cumulative counts per
  // finite bucket plus the grand total (the +Inf bucket AND _count —
  // derived from the same per-bucket snapshot, never from the separate
  // count_ atomic, so a concurrent Observe can never yield exposition
  // where +Inf != _count or buckets regress). Exposition() and the
  // tests both read through this.
  struct Snapshot {
    std::vector<unsigned long long> cumulative;  // per finite bucket
    unsigned long long total = 0;                // +Inf bucket == _count
    double sum = 0;
    // Per finite bucket plus one trailing entry for +Inf; .set=false
    // where no exemplar was ever observed.
    std::vector<Exemplar> exemplars;
  };
  Snapshot TakeSnapshot() const;
  unsigned long long CumulativeCount(size_t i) const;
  unsigned long long TotalCount() const {
    return count_.load(std::memory_order_relaxed);
  }
  double Sum() const { return sum_.load(std::memory_order_relaxed); }

 private:
  std::vector<double> upper_bounds_;  // sorted, deduped, finite
  std::vector<std::unique_ptr<std::atomic<unsigned long long>>> counts_;
  std::atomic<unsigned long long> overflow_{0};  // > last bound (+Inf)
  std::atomic<double> sum_{0.0};
  std::atomic<unsigned long long> count_{0};
  mutable std::mutex exemplar_mu_;
  std::vector<Exemplar> exemplars_;  // finite buckets + [+Inf] last
};

// Buckets sized for label-pass work: sub-millisecond file rewrites up to
// multi-minute health execs (--health-exec-timeout default 240s).
std::vector<double> DurationBuckets();

// A family registry. Get* registers on first use and returns the same
// instrument for the same (name, labels) thereafter, so call sites need
// no setup phase — the daemon's hot loop just calls
// Default().GetCounter("tfd_rewrites_total", ...)->Inc().
//
// Names are sanitized to the Prometheus grammar at registration
// ([a-zA-Z_:][a-zA-Z0-9_:]* for metrics, no ':' for label names), so
// Exposition() output is valid by construction regardless of input —
// the property fuzz_metrics.cc leans on. A name registered as one type
// and requested as another returns a detached instrument (never
// rendered) instead of crashing or corrupting the family.
class Registry {
 public:
  Registry();
  ~Registry();  // out-of-line: Family/Child are incomplete here

  Counter* GetCounter(const std::string& name, const std::string& help,
                      const Labels& labels = {});
  Gauge* GetGauge(const std::string& name, const std::string& help,
                  const Labels& labels = {});
  Histogram* GetHistogram(const std::string& name, const std::string& help,
                          std::vector<double> upper_bounds,
                          const Labels& labels = {});

  // Renders every family in registration order.
  std::string Exposition() const;

 private:
  struct Child;
  struct Family;
  Child* GetChild(const std::string& name, const std::string& help, int type,
                  const Labels& labels,
                  const std::vector<double>* upper_bounds);

  mutable std::mutex mu_;
  std::vector<std::unique_ptr<Family>> families_;
  // Type-mismatch orphans: alive for the process, never rendered.
  std::vector<std::unique_ptr<Counter>> orphan_counters_;
  std::vector<std::unique_ptr<Gauge>> orphan_gauges_;
  std::vector<std::unique_ptr<Histogram>> orphan_histograms_;
};

// The process-wide registry the daemon's instruments live in. Counters
// survive SIGHUP config reloads (the introspection server restarts; the
// registry does not), keeping scraped series monotone across reloads.
Registry& Default();

// Validates Prometheus text exposition: HELP/TYPE lines well-formed, every
// sample matches the line grammar with a parseable value, samples only for
// families with a declared TYPE, histogram buckets cumulative-monotone with
// a +Inf bucket matching _count. OpenMetrics exemplars
// (` # {change_id="42"} 0.0043`) are accepted — well-formed label set,
// parseable value, combined label length within the 128-rune budget —
// but ONLY on counter and histogram-bucket lines; anywhere else they
// are rejected. Used by the unit tests, fuzz_metrics.cc (as the oracle
// over Registry output), and the CI metrics-lint step (via
// `tfd_unit_tests --validate-exposition <file>`).
Status ValidateExposition(const std::string& text);

}  // namespace obs
}  // namespace tfd

// In-daemon flight recorder: a bounded, lock-protected ring buffer of
// structured events.
//
// The daemon's metrics (obs/metrics.h) say HOW MUCH happened; the labels
// say WHAT the node looks like right now. Neither can answer the ops
// question PR 2's degradation ladder made acute: WHY does this node carry
// these labels — which probe source produced each key, at which staleness
// tier, and when did it last change? The journal records the causal
// chain: probe lifecycle (start/ok/fail/backoff per source), snapshot
// tier transitions, degradation-ladder level changes, per-rewrite spans
// (duration + per-labeler timings), sink writes (file and NodeFeature CR,
// including conflict retries), SIGHUP reloads, SIGUSR1 dumps, and label
// diffs (added/removed/changed keys with old→new values and the
// labeler/source/tier that produced each).
//
// Bounded by construction: fixed capacity (--journal-capacity, default
// 512), drop-oldest, with the drops counted in tfd_journal_dropped_total
// — a wedged node that loops through probe failures for a week holds a
// window of recent history at constant memory, never an unbounded log.
// Every append also bumps tfd_journal_events_total{type}.
//
// Correlation: every label rewrite pass calls BeginRewrite(), and every
// event recorded until the next pass carries that generation — so an
// operator (or scripts/soak.py --require-journal) can join a label diff
// to the rewrite span, probe results, and sink write that produced it.
// The same generation rides in --log-format=json log lines
// (log::SetCurrentGeneration), joining free-text logs to the journal.
//
// Exposed on the introspection server as /debug/journal?n=&type= (JSON)
// and folded into the SIGUSR1 post-mortem dump. Like the metrics
// registry, DefaultJournal() is process-global and survives SIGHUP
// config reloads — the flight recorder must cover the reload itself.
#pragma once

#include <cstdint>
#include <deque>
#include <mutex>
#include <string>
#include <utility>
#include <vector>

namespace tfd {
namespace obs {

// One recorded event. `fields` is a small ordered key→value payload
// (label diffs carry key/op/old/new/provenance; rewrite spans carry
// per-labeler timings; ...). All strings may contain arbitrary bytes —
// the JSON renderers escape, and the fuzz target (fuzz_journal.cc)
// pins that hostile payloads cannot break /debug/journal exposition.
struct Event {
  uint64_t seq = 0;        // journal-global, monotone, never reused
  double wall_time_s = 0;  // unix time, sub-second resolution
  uint64_t generation = 0; // rewrite-generation correlation id
  uint64_t change = 0;     // causal change-id (obs/trace.h; 0 = none)
  std::string type;        // "probe-ok", "label-diff", "rewrite", ...
  std::string source;      // probe source / sink / "" when not applicable
  std::string message;     // one human-readable line
  std::vector<std::pair<std::string, std::string>> fields;
};

// Renders one event as a JSON object (the schema --log-format=json log
// lines reuse: ts/generation/type/message + the structured extras).
std::string EventJson(const Event& event);

class Journal {
 public:
  static constexpr size_t kDefaultCapacity = 512;

  // `metrics` wires tfd_journal_events_total{type} /
  // tfd_journal_dropped_total into obs::Default(); the fuzz target
  // disables it so hostile event types cannot grow the registry.
  explicit Journal(size_t capacity = kDefaultCapacity, bool metrics = true);

  // Capacity is reconfigurable at a config load (--journal-capacity);
  // shrinking drops oldest events (counted as drops).
  void SetCapacity(size_t capacity);
  size_t capacity() const;

  // Appends an event, assigning seq / wall time / current generation.
  // Thread-safe: probe workers, the render loop, and the sink layers all
  // record concurrently.
  void Record(const std::string& type, const std::string& source,
              const std::string& message,
              std::vector<std::pair<std::string, std::string>> fields = {});

  // Starts a new rewrite generation (the correlation id) and mirrors it
  // into log::SetCurrentGeneration for --log-format=json. Returns the
  // new generation. `change` is the causal change-id this pass carries
  // (obs/trace.h LatestActiveChange; 0 = nothing in flight): every
  // event recorded until the next pass rides it, so /debug/journal
  // output joins to /debug/trace without timestamp heuristics.
  uint64_t BeginRewrite(uint64_t change = 0);
  uint64_t generation() const;
  uint64_t change() const;

  // The newest `n` events (0 = all retained), oldest-first, optionally
  // filtered by exact type. Copied under the lock — renderers never
  // block an append for long.
  std::vector<Event> Snapshot(size_t n = 0,
                              const std::string& type = "") const;

  uint64_t dropped_total() const;
  uint64_t next_seq() const;

  // {"capacity":..,"dropped_total":..,"generation":..,"events":[..]} —
  // what /debug/journal serves and the SIGUSR1 dump embeds.
  std::string RenderJson(size_t n = 0, const std::string& type = "") const;

 private:
  mutable std::mutex mu_;
  size_t capacity_;
  bool metrics_;
  std::deque<Event> events_;
  uint64_t next_seq_ = 1;
  uint64_t dropped_ = 0;
  uint64_t generation_ = 0;
  uint64_t change_ = 0;
};

// The process-wide journal (the analogue of obs::Default() for metrics):
// survives SIGHUP reloads so the recorder covers the reload itself.
Journal& DefaultJournal();

}  // namespace obs
}  // namespace tfd

#include "tfd/obs/server.h"

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <poll.h>
#include <string.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <chrono>
#include <mutex>
#include <thread>
#include <vector>

#include "tfd/util/logging.h"
#include "tfd/util/strings.h"

namespace tfd {
namespace obs {

namespace {

// Small fixed limits: the traffic model is kubelet probes + one scraper.
constexpr int kMaxConns = 16;
constexpr size_t kMaxRequestBytes = 8192;
constexpr int kConnDeadlineS = 10;
constexpr int kPollTickMs = 1000;

std::string HttpResponse(int status, const std::string& reason,
                         const std::string& content_type,
                         const std::string& body,
                         const std::string& extra_header = "") {
  std::string out = "HTTP/1.1 " + std::to_string(status) + " " + reason +
                    "\r\n";
  out += "Content-Type: " + content_type + "\r\n";
  out += "Content-Length: " + std::to_string(body.size()) + "\r\n";
  if (!extra_header.empty()) out += extra_header + "\r\n";
  out += "Connection: close\r\n\r\n";
  out += body;
  return out;
}

void SetNonBlockingCloexec(int fd) {
  fcntl(fd, F_SETFL, fcntl(fd, F_GETFL, 0) | O_NONBLOCK);
  fcntl(fd, F_SETFD, fcntl(fd, F_GETFD, 0) | FD_CLOEXEC);
}

}  // namespace

Result<ListenAddr> ParseListenAddr(const std::string& text) {
  ListenAddr out;
  size_t colon = text.rfind(':');
  if (colon == std::string::npos) {
    return Result<ListenAddr>::Error(
        "introspection address '" + text +
        "' must be host:port (e.g. :8081 or 127.0.0.1:8081)");
  }
  out.host = text.substr(0, colon);
  std::string port = text.substr(colon + 1);
  int value = -1;
  if (!ParseNonNegInt(port, &value) || value > 65535) {
    return Result<ListenAddr>::Error("invalid introspection port '" + port +
                                     "'");
  }
  out.port = value;
  if (!out.host.empty()) {
    in_addr addr{};
    if (inet_pton(AF_INET, out.host.c_str(), &addr) != 1) {
      return Result<ListenAddr>::Error(
          "introspection host '" + out.host +
          "' must be an IPv4 literal or empty (all interfaces)");
    }
  }
  return out;
}

struct IntrospectionServer::Conn {
  int fd = -1;
  std::string in;
  std::string out;
  size_t out_off = 0;
  std::chrono::steady_clock::time_point opened;
  bool responding = false;
};

class IntrospectionServer::Impl {
 public:
  std::thread thread;
  std::atomic<bool> stopping{false};

  // /readyz state, written by the daemon thread via RecordRewrite /
  // SetAllExpired.
  std::mutex mu;
  bool ever_succeeded = false;
  bool last_ok = false;
  bool all_expired = false;
  std::chrono::steady_clock::time_point last_success;
  std::string labels_json;  // /debug/labels document (see SetLabelsJson)

  std::vector<Conn> conns;
};

Result<std::unique_ptr<IntrospectionServer>> IntrospectionServer::Start(
    const ServerOptions& options, Registry* registry) {
  using R = Result<std::unique_ptr<IntrospectionServer>>;
  Result<ListenAddr> addr = ParseListenAddr(options.addr);
  if (!addr.ok()) return R::Error(addr.error());

  int fd = socket(AF_INET, SOCK_STREAM | SOCK_CLOEXEC, 0);
  if (fd < 0) return R::Error(std::string("socket: ") + strerror(errno));
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));

  sockaddr_in sa{};
  sa.sin_family = AF_INET;
  sa.sin_port = htons(static_cast<uint16_t>(addr->port));
  if (addr->host.empty()) {
    sa.sin_addr.s_addr = htonl(INADDR_ANY);
  } else {
    inet_pton(AF_INET, addr->host.c_str(), &sa.sin_addr);
  }
  if (bind(fd, reinterpret_cast<sockaddr*>(&sa), sizeof(sa)) != 0) {
    std::string err = strerror(errno);
    close(fd);
    return R::Error("bind " + options.addr + ": " + err);
  }
  if (listen(fd, 16) != 0) {
    std::string err = strerror(errno);
    close(fd);
    return R::Error("listen " + options.addr + ": " + err);
  }
  SetNonBlockingCloexec(fd);

  sockaddr_in bound{};
  socklen_t bound_len = sizeof(bound);
  getsockname(fd, reinterpret_cast<sockaddr*>(&bound), &bound_len);

  auto server = std::unique_ptr<IntrospectionServer>(new IntrospectionServer());
  server->registry_ = registry;
  server->journal_ = options.journal;
  server->trace_ = options.trace;
  server->slo_ = options.slo;
  server->slice_report_ = options.slice_report;
  server->stale_after_s_ = options.stale_after_s;
  server->listen_fd_ = fd;
  server->port_ = ntohs(bound.sin_port);
  if (pipe(server->wake_fds_) != 0) {
    close(fd);
    return R::Error(std::string("pipe: ") + strerror(errno));
  }
  SetNonBlockingCloexec(server->wake_fds_[0]);
  SetNonBlockingCloexec(server->wake_fds_[1]);
  server->impl_ = std::make_unique<Impl>();
  IntrospectionServer* raw = server.get();
  server->impl_->thread = std::thread([raw] { raw->Loop(); });
  return server;
}

IntrospectionServer::~IntrospectionServer() { Stop(); }

void IntrospectionServer::Stop() {
  if (impl_ == nullptr) return;
  if (!impl_->stopping.exchange(true)) {
    // Wake the poll loop; a full pipe still wakes it (POLLIN is already
    // pending), so the write result is irrelevant.
    ssize_t ignored = write(wake_fds_[1], "x", 1);
    (void)ignored;
  }
  if (impl_->thread.joinable()) impl_->thread.join();
  for (Conn& conn : impl_->conns) {
    if (conn.fd >= 0) close(conn.fd);
  }
  impl_->conns.clear();
  if (listen_fd_ >= 0) close(listen_fd_);
  listen_fd_ = -1;
  for (int& fd : wake_fds_) {
    if (fd >= 0) close(fd);
    fd = -1;
  }
}

void IntrospectionServer::RecordRewrite(bool ok) {
  std::lock_guard<std::mutex> lock(impl_->mu);
  impl_->last_ok = ok;
  if (ok) {
    impl_->ever_succeeded = true;
    impl_->last_success = std::chrono::steady_clock::now();
  }
}

void IntrospectionServer::SetAllExpired(bool all_expired) {
  std::lock_guard<std::mutex> lock(impl_->mu);
  impl_->all_expired = all_expired;
}

void IntrospectionServer::SetLabelsJson(std::string json) {
  std::lock_guard<std::mutex> lock(impl_->mu);
  impl_->labels_json = std::move(json);
}

void IntrospectionServer::HandleRequest(Conn* conn) {
  conn->responding = true;
  size_t line_end = conn->in.find("\r\n");
  if (line_end == std::string::npos) line_end = conn->in.find('\n');
  std::string request_line = conn->in.substr(0, line_end);
  size_t sp1 = request_line.find(' ');
  size_t sp2 = request_line.rfind(' ');
  if (sp1 == std::string::npos || sp2 <= sp1) {
    conn->out = HttpResponse(400, "Bad Request", "text/plain",
                             "malformed request line\n");
    return;
  }
  std::string method = request_line.substr(0, sp1);
  std::string path = request_line.substr(sp1 + 1, sp2 - sp1 - 1);
  std::string query;
  size_t qmark = path.find('?');
  if (qmark != std::string::npos) {
    query = path.substr(qmark + 1);
    path = path.substr(0, qmark);
  }

  if (method != "GET") {
    conn->out = HttpResponse(405, "Method Not Allowed", "text/plain",
                             "only GET is served\n", "Allow: GET");
    return;
  }
  if (path == "/healthz") {
    conn->out = HttpResponse(200, "OK", "text/plain", "ok\n");
  } else if (path == "/readyz") {
    bool ready;
    std::string why;
    {
      std::lock_guard<std::mutex> lock(impl_->mu);
      if (!impl_->ever_succeeded) {
        ready = false;
        why = "no label rewrite has succeeded yet\n";
      } else if (!impl_->last_ok) {
        ready = false;
        why = "last label rewrite failed\n";
      } else if (impl_->all_expired) {
        ready = false;
        why = "every probe-source snapshot is expired; serving "
              "best-effort labels only\n";
      } else {
        auto age = std::chrono::steady_clock::now() - impl_->last_success;
        ready = age <= std::chrono::seconds(stale_after_s_);
        if (!ready) {
          why = "last successful rewrite is older than " +
                std::to_string(stale_after_s_) + "s\n";
        }
      }
    }
    conn->out = ready
                    ? HttpResponse(200, "OK", "text/plain", "ready\n")
                    : HttpResponse(503, "Service Unavailable", "text/plain",
                                   why);
  } else if (path == "/metrics") {
    conn->out = HttpResponse(
        200, "OK", "text/plain; version=0.0.4; charset=utf-8",
        registry_->Exposition());
  } else if (path == "/debug/journal" && journal_ != nullptr) {
    // ?n=<count> (0/absent = all retained) and ?type=<event type>
    // filter the flight-recorder dump.
    size_t n = 0;
    std::string type;
    for (const std::string& param : SplitString(query, '&')) {
      size_t eq = param.find('=');
      if (eq == std::string::npos) continue;
      std::string key = param.substr(0, eq);
      std::string value = param.substr(eq + 1);
      if (key == "n") {
        int parsed = 0;
        if (ParseNonNegInt(value, &parsed)) n = static_cast<size_t>(parsed);
      } else if (key == "type") {
        type = value;
      }
    }
    conn->out = HttpResponse(200, "OK", "application/json",
                             journal_->RenderJson(n, type) + "\n");
  } else if (path == "/debug/trace" && trace_ != nullptr) {
    // ?n=<count> (0/absent = all retained) and ?change=<change-id>
    // filter the causal-trace dump (obs/trace.h).
    size_t n = 0;
    uint64_t change = 0;
    for (const std::string& param : SplitString(query, '&')) {
      size_t eq = param.find('=');
      if (eq == std::string::npos) continue;
      std::string key = param.substr(0, eq);
      std::string value = param.substr(eq + 1);
      int parsed = 0;
      if (key == "n" && ParseNonNegInt(value, &parsed)) {
        n = static_cast<size_t>(parsed);
      } else if (key == "change" && ParseNonNegInt(value, &parsed)) {
        change = static_cast<uint64_t>(parsed);
      }
    }
    conn->out = HttpResponse(200, "OK", "application/json",
                             trace_->RenderJson(n, change) + "\n");
  } else if (path == "/debug/slo" && slo_ != nullptr) {
    // Expire-then-render: the windowed view must age out even when no
    // pass has folded anything since the last read (quiet daemon).
    slo_->Expire();
    conn->out = HttpResponse(200, "OK", "application/json",
                             slo_->RenderJson() + "\n");
  } else if (path == "/debug/labels") {
    std::string body;
    {
      std::lock_guard<std::mutex> lock(impl_->mu);
      body = impl_->labels_json;
    }
    if (body.empty()) {
      conn->out = HttpResponse(503, "Service Unavailable",
                               "application/json",
                               "{\"error\":\"no rewrite has completed "
                               "yet\"}\n");
    } else {
      conn->out = HttpResponse(200, "OK", "application/json", body + "\n");
    }
  } else if (path == "/debug/slice-report" && slice_report_ != nullptr) {
    // The peer-relay fetch surface (--slice-relay): this host's LIVE
    // member report, refreshed every slice tick even when the
    // blackboard is unreachable — that is exactly when a peer needs it.
    std::string body = slice_report_();
    if (body.empty()) {
      conn->out = HttpResponse(503, "Service Unavailable",
                               "application/json",
                               "{\"error\":\"no slice report built "
                               "yet\"}\n");
    } else {
      conn->out = HttpResponse(200, "OK", "application/json", body + "\n");
    }
  } else {
    conn->out = HttpResponse(404, "Not Found", "text/plain",
                             "serves /healthz, /readyz, /metrics, "
                             "/debug/journal, /debug/labels, "
                             "/debug/trace, /debug/slo, "
                             "/debug/slice-report\n");
  }
}

void IntrospectionServer::Loop() {
  std::vector<Conn>& conns = impl_->conns;
  while (!impl_->stopping.load()) {
    std::vector<pollfd> fds;
    fds.push_back({wake_fds_[0], POLLIN, 0});
    // Stop accepting while at the connection budget; pending peers wait
    // in the listen backlog.
    const bool accepting = conns.size() < kMaxConns;
    if (accepting) {
      fds.push_back({listen_fd_, POLLIN, 0});
    }
    for (Conn& conn : conns) {
      fds.push_back({conn.fd,
                     static_cast<short>(conn.responding ? POLLOUT : POLLIN),
                     0});
    }
    int rc = poll(fds.data(), fds.size(), kPollTickMs);
    if (impl_->stopping.load()) return;
    if (rc < 0) {
      if (errno == EINTR) continue;
      TFD_LOG_WARNING << "introspection poll failed: " << strerror(errno)
                      << "; server exiting";
      return;
    }

    size_t idx = 1;
    if (accepting) {
      if (fds[idx].revents & POLLIN) {
        while (true) {
          int client = accept(listen_fd_, nullptr, nullptr);
          if (client < 0) break;
          SetNonBlockingCloexec(client);
          Conn conn;
          conn.fd = client;
          conn.opened = std::chrono::steady_clock::now();
          conns.push_back(std::move(conn));
          if (conns.size() >= kMaxConns) break;
        }
      }
      idx++;
    }

    auto now = std::chrono::steady_clock::now();
    // fds[idx..] map 1:1 onto the conns present at poll time; conns
    // accepted above have no pollfd yet and are skipped this round.
    size_t polled = fds.size() - idx;
    for (size_t c = 0; c < polled; c++, idx++) {
      Conn& conn = conns[c];
      bool drop = false;
      if (fds[idx].revents & (POLLERR | POLLHUP | POLLNVAL)) {
        drop = true;
      } else if (!conn.responding && (fds[idx].revents & POLLIN)) {
        char buf[2048];
        ssize_t n = read(conn.fd, buf, sizeof(buf));
        if (n <= 0) {
          if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) {
            // spurious wakeup
          } else {
            drop = true;  // peer closed before a full request
          }
        } else {
          conn.in.append(buf, static_cast<size_t>(n));
          if (conn.in.size() > kMaxRequestBytes) {
            conn.out = HttpResponse(431, "Request Header Fields Too Large",
                                    "text/plain", "request too large\n");
            conn.responding = true;
          } else if (conn.in.find("\r\n\r\n") != std::string::npos ||
                     conn.in.find("\n\n") != std::string::npos) {
            HandleRequest(&conn);
          }
        }
      } else if (conn.responding && (fds[idx].revents & POLLOUT)) {
        ssize_t n = send(conn.fd, conn.out.data() + conn.out_off,
                         conn.out.size() - conn.out_off, MSG_NOSIGNAL);
        if (n < 0) {
          if (errno != EAGAIN && errno != EWOULDBLOCK) drop = true;
        } else {
          conn.out_off += static_cast<size_t>(n);
          if (conn.out_off >= conn.out.size()) drop = true;  // done
        }
      }
      if (!drop &&
          now - conn.opened > std::chrono::seconds(kConnDeadlineS)) {
        drop = true;  // slowloris / dead peer
      }
      conn.fd = drop ? (close(conn.fd), -1) : conn.fd;
    }
    conns.erase(std::remove_if(conns.begin(), conns.end(),
                               [](const Conn& c) { return c.fd < 0; }),
                conns.end());
  }
}

}  // namespace obs
}  // namespace tfd

// Machine-type labeler.
//
// Reference parity: internal/lm/machine-type.go:31-52 — read the DMI product
// name, spaces→dashes, degrade to "unknown" with a warning on error.
//
// TPU-first difference: on GCE/TPU-VMs the DMI product name is just "Google
// Compute Engine"; the useful machine type (e.g. "ct5lp-hightpu-4t") comes
// from the metadata server. The labeler therefore takes an optional
// metadata getter which wins over the DMI file when it succeeds.
#pragma once

#include <functional>
#include <string>

#include "tfd/lm/labeler.h"

namespace tfd {
namespace lm {

using MachineTypeGetter = std::function<Result<std::string>()>;

// `metadata_getter` may be null (no metadata server / tests).
LabelerPtr NewMachineTypeLabeler(const std::string& machine_type_file,
                                 MachineTypeGetter metadata_getter);

}  // namespace lm
}  // namespace tfd

#include "tfd/lm/tpu_labeler.h"

#include <algorithm>
#include <atomic>
#include <chrono>
#include <map>

#include "tfd/lm/schema.h"
#include "tfd/lm/resource_labeler.h"
#include "tfd/lm/slice_strategy.h"
#include "tfd/slice/topology.h"
#include "tfd/util/logging.h"
#include "tfd/util/strings.h"

namespace tfd {
namespace lm {

namespace {

// Splits a dotted version string into up to `max_parts` numeric components.
std::vector<std::string> VersionParts(const std::string& version,
                                      size_t max_parts) {
  std::vector<std::string> parts = SplitString(TrimSpace(version), '.');
  if (parts.size() > max_parts) parts.resize(max_parts);
  return parts;
}

// Version labeler (reference newVersionLabeler, nvml.go:75-106: driver
// X.Y[.Z] → cuda.driver.major/minor/rev, CUDA runtime → major/minor).
// Here: libtpu version → libtpu.version.{major,minor,patch}; PJRT C-API
// version → tpu.runtime.{major,minor}.
LabelerPtr NewVersionLabeler(resource::Manager& manager) {
  Labels labels;
  Result<std::string> libtpu = manager.GetLibtpuVersion();
  if (libtpu.ok()) {
    std::vector<std::string> parts = VersionParts(*libtpu, 3);
    const char* keys[3] = {kLibtpuMajor, kLibtpuMinor, kLibtpuPatch};
    for (size_t i = 0; i < parts.size(); i++) labels[keys[i]] = parts[i];
  } else {
    TFD_LOG_WARNING << "unable to determine libtpu version: "
                    << libtpu.error();
  }
  Result<std::string> runtime = manager.GetRuntimeVersion();
  if (runtime.ok()) {
    std::vector<std::string> parts = VersionParts(*runtime, 2);
    const char* keys[2] = {kRuntimeMajor, kRuntimeMinor};
    for (size_t i = 0; i < parts.size(); i++) labels[keys[i]] = parts[i];
  } else {
    TFD_LOG_WARNING << "unable to determine PJRT runtime version: "
                    << runtime.error();
  }
  return std::make_unique<StaticLabeler>(std::move(labels));
}

// Slice-capability labeler (reference newMigCapabilityLabeler,
// nvml.go:110-137): true when the node's chips are part of an addressable
// slice fabric — i.e. the backend knows the slice topology or accelerator
// type. False for chips visible without any topology identity.
LabelerPtr NewSliceCapabilityLabeler(resource::Manager& manager) {
  Labels labels;
  Result<resource::TopologyInfo> topo = manager.GetTopology();
  bool capable = topo.ok() && (!topo->accelerator_type.empty() ||
                               !topo->topology.empty());
  labels[kSliceCapable] = capable ? "true" : "false";
  return std::make_unique<StaticLabeler>(std::move(labels));
}

// Topology labels shared by every strategy (emitted whenever known):
// accelerator-type, topology, ICI wrap.
LabelerPtr NewTopologyLabeler(resource::Manager& manager) {
  Result<resource::TopologyInfo> topo = manager.GetTopology();
  if (!topo.ok()) return Empty();
  Labels labels;
  if (!topo->accelerator_type.empty()) {
    labels[kAcceleratorType] = StrictLabelValue(topo->accelerator_type);
  }
  if (!topo->topology.empty()) {
    labels[kTopologyLabel] = StrictLabelValue(topo->topology);
  }
  if (!topo->accelerator_type.empty() || !topo->topology.empty()) {
    labels[kIciWrap] = topo->has_wraparound ? "true" : "false";
  }
  return std::make_unique<StaticLabeler>(std::move(labels));
}

// ICI link-count labeler: per-chip links are a hardware constant of the
// family's fabric (2D torus: 4 links, 3D: 6) — the last MIG-attribute
// analogue from SURVEY §5 (next to HBM capacity and TensorCores). Derived
// from the device product, so it survives on topology-less backends too.
LabelerPtr NewIciLinksLabeler(
    const std::vector<resource::DevicePtr>& devices) {
  // DominantProduct is the resource labeler's selection rule, so on a
  // heterogeneous host this label always matches the product the node is
  // labeled as.
  Result<std::string> dominant = DominantProduct(devices);
  if (!dominant.ok()) return Empty();
  std::string family_name = HasPrefix(*dominant, "tpu-")
                                ? dominant->substr(4)
                                : *dominant;
  Result<slice::FamilySpec> family = slice::LookupFamily(family_name);
  if (!family.ok() || family->topology_dims == 0) return Empty();
  Labels labels;
  labels[kIciLinks] = family->topology_dims == 3 ? "6" : "4";
  return std::make_unique<StaticLabeler>(std::move(labels));
}

}  // namespace

namespace {
std::atomic<long long> g_tpu_labeler_builds{0};
}  // namespace

long long TpuLabelerBuilds() { return g_tpu_labeler_builds.load(); }

Result<LabelerPtr> NewTpuLabeler(const resource::ManagerPtr& manager,
                                 const config::Config& config) {
  g_tpu_labeler_builds.fetch_add(1, std::memory_order_relaxed);
  auto probe_start = std::chrono::steady_clock::now();
  Status init = manager->Init();
  if (!init.ok()) {
    return Result<LabelerPtr>::Error("failed to initialize " +
                                     manager->Name() +
                                     " backend: " + init.message());
  }

  Result<std::vector<resource::DevicePtr>> devices = manager->GetDevices();
  if (!devices.ok()) {
    manager->Shutdown();
    return Result<LabelerPtr>::Error("error getting TPU devices: " +
                                     devices.error());
  }
  if (devices->empty()) {
    // No TPUs: contribute nothing (reference nvml.go:40-42); machine-type
    // and timestamp labels are handled at the run() level.
    manager->Shutdown();
    return LabelerPtr(Empty());
  }

  std::vector<LabelerPtr> parts;
  {
    Labels backend;
    backend[kBackendLabel] = manager->Name();
    parts.push_back(std::make_unique<StaticLabeler>(std::move(backend)));
  }
  parts.push_back(NewVersionLabeler(*manager));
  parts.push_back(NewSliceCapabilityLabeler(*manager));
  parts.push_back(NewTopologyLabeler(*manager));
  parts.push_back(NewIciLinksLabeler(*devices));
  const std::string& health_mode = config.flags.device_health;
  bool health_on = (health_mode == "basic" || health_mode == "full") &&
                   manager->TouchesDevices();
  Labels health;
  if (health_on) {
    // Basic health: the backend initialized and every chip enumerated, and
    // how long that took — a sick TPU stack shows up first as slow or
    // failing init (hence the fail path never reaches here; absence of
    // health labels on a TPU node means the probe never completed).
    // Restricted to device-touching backends: a control-plane backend
    // (metadata) must not vouch for chip health — including when auto
    // fell back to it because PJRT init failed.
    auto ms = std::chrono::duration_cast<std::chrono::milliseconds>(
                  std::chrono::steady_clock::now() - probe_start)
                  .count();
    // A pre-probed snapshot view (sched/sources.cc) answers every call
    // above from captured data in microseconds; its ProbeSeconds() is
    // the honest init+enumeration latency of the probe that produced it.
    if (auto* timed = dynamic_cast<resource::ProbeTimed*>(manager.get())) {
      ms = static_cast<long long>(timed->ProbeSeconds() * 1000);
    }
    health[kHealthOk] = "true";
    health[kHealthDevices] = std::to_string(devices->size());
    health[kHealthProbeMs] = std::to_string(ms);
  }
  Result<LabelerPtr> strategy = NewSliceStrategyLabeler(*manager, config);
  if (!strategy.ok()) {
    manager->Shutdown();
    return strategy;
  }
  parts.push_back(std::move(*strategy));
  manager->Shutdown();

  // Full-health exec labels (matmul TFLOPs, HBM GB/s, ...) are no
  // longer merged here: the probe scheduler's health worker runs the
  // exec on its own cadence (sched/sources.cc) and the daemon loop
  // merges its snapshot over these basic labels — a multi-minute
  // silicon probe must never ride the rewrite path.
  if (health_on) {
    parts.push_back(std::make_unique<StaticLabeler>(std::move(health)));
  }

  // Everything above is eagerly-computed static data; collapse it now so
  // later GetLabels() calls cannot touch the (shut-down) manager.
  LabelerPtr merged = Merge(std::move(parts));
  Result<Labels> labels = merged->GetLabels();
  if (!labels.ok()) return Result<LabelerPtr>::Error(labels.error());
  return LabelerPtr(std::make_unique<StaticLabeler>(std::move(*labels)));
}

}  // namespace lm
}  // namespace tfd

#include "tfd/lm/tpu_labeler.h"

#include <algorithm>
#include <cctype>
#include <chrono>
#include <map>

#include "tfd/lm/schema.h"
#include "tfd/lm/resource_labeler.h"
#include "tfd/lm/slice_strategy.h"
#include "tfd/slice/topology.h"
#include "tfd/util/logging.h"
#include "tfd/util/strings.h"
#include "tfd/util/subprocess.h"

namespace tfd {
namespace lm {

namespace {

// Splits a dotted version string into up to `max_parts` numeric components.
std::vector<std::string> VersionParts(const std::string& version,
                                      size_t max_parts) {
  std::vector<std::string> parts = SplitString(TrimSpace(version), '.');
  if (parts.size() > max_parts) parts.resize(max_parts);
  return parts;
}

// Version labeler (reference newVersionLabeler, nvml.go:75-106: driver
// X.Y[.Z] → cuda.driver.major/minor/rev, CUDA runtime → major/minor).
// Here: libtpu version → libtpu.version.{major,minor,patch}; PJRT C-API
// version → tpu.runtime.{major,minor}.
LabelerPtr NewVersionLabeler(resource::Manager& manager) {
  Labels labels;
  Result<std::string> libtpu = manager.GetLibtpuVersion();
  if (libtpu.ok()) {
    std::vector<std::string> parts = VersionParts(*libtpu, 3);
    const char* keys[3] = {kLibtpuMajor, kLibtpuMinor, kLibtpuPatch};
    for (size_t i = 0; i < parts.size(); i++) labels[keys[i]] = parts[i];
  } else {
    TFD_LOG_WARNING << "unable to determine libtpu version: "
                    << libtpu.error();
  }
  Result<std::string> runtime = manager.GetRuntimeVersion();
  if (runtime.ok()) {
    std::vector<std::string> parts = VersionParts(*runtime, 2);
    const char* keys[2] = {kRuntimeMajor, kRuntimeMinor};
    for (size_t i = 0; i < parts.size(); i++) labels[keys[i]] = parts[i];
  } else {
    TFD_LOG_WARNING << "unable to determine PJRT runtime version: "
                    << runtime.error();
  }
  return std::make_unique<StaticLabeler>(std::move(labels));
}

// Slice-capability labeler (reference newMigCapabilityLabeler,
// nvml.go:110-137): true when the node's chips are part of an addressable
// slice fabric — i.e. the backend knows the slice topology or accelerator
// type. False for chips visible without any topology identity.
LabelerPtr NewSliceCapabilityLabeler(resource::Manager& manager) {
  Labels labels;
  Result<resource::TopologyInfo> topo = manager.GetTopology();
  bool capable = topo.ok() && (!topo->accelerator_type.empty() ||
                               !topo->topology.empty());
  labels[kSliceCapable] = capable ? "true" : "false";
  return std::make_unique<StaticLabeler>(std::move(labels));
}

// Topology labels shared by every strategy (emitted whenever known):
// accelerator-type, topology, ICI wrap.
LabelerPtr NewTopologyLabeler(resource::Manager& manager) {
  Result<resource::TopologyInfo> topo = manager.GetTopology();
  if (!topo.ok()) return Empty();
  Labels labels;
  if (!topo->accelerator_type.empty()) {
    labels[kAcceleratorType] = StrictLabelValue(topo->accelerator_type);
  }
  if (!topo->topology.empty()) {
    labels[kTopologyLabel] = StrictLabelValue(topo->topology);
  }
  if (!topo->accelerator_type.empty() || !topo->topology.empty()) {
    labels[kIciWrap] = topo->has_wraparound ? "true" : "false";
  }
  return std::make_unique<StaticLabeler>(std::move(labels));
}

// ICI link-count labeler: per-chip links are a hardware constant of the
// family's fabric (2D torus: 4 links, 3D: 6) — the last MIG-attribute
// analogue from SURVEY §5 (next to HBM capacity and TensorCores). Derived
// from the device product, so it survives on topology-less backends too.
LabelerPtr NewIciLinksLabeler(
    const std::vector<resource::DevicePtr>& devices) {
  // DominantProduct is the resource labeler's selection rule, so on a
  // heterogeneous host this label always matches the product the node is
  // labeled as.
  Result<std::string> dominant = DominantProduct(devices);
  if (!dominant.ok()) return Empty();
  std::string family_name = HasPrefix(*dominant, "tpu-")
                                ? dominant->substr(4)
                                : *dominant;
  Result<slice::FamilySpec> family = slice::LookupFamily(family_name);
  if (!family.ok() || family->topology_dims == 0) return Empty();
  Labels labels;
  labels[kIciLinks] = family->topology_dims == 3 ? "6" : "4";
  return std::make_unique<StaticLabeler>(std::move(labels));
}

// A label key's name part (after the "google.com/" domain) must be a valid
// Kubernetes label name: alphanumeric ends, [-._a-zA-Z0-9] middle, <= 63
// chars TOTAL — and the name already starts with the fixed "tpu.health."
// (11 chars), so the probe's suffix gets at most 52. A bad key from a
// buggy probe must never reach the apiserver — an invalid label name
// fails the whole NodeFeature update.
bool ValidLabelKeySuffix(const std::string& s) {
  constexpr size_t kMax = 63 - (sizeof("tpu.health.") - 1);
  if (s.empty() || s.size() > kMax) return false;
  auto alnum = [](char c) { return isalnum(static_cast<unsigned char>(c)); };
  if (!alnum(s.front()) || !alnum(s.back())) return false;
  for (char c : s) {
    if (!alnum(c) && c != '-' && c != '_' && c != '.') return false;
  }
  return true;
}

// Runs the --health-exec command and returns the google.com/tpu.health.*
// labels parsed from its key=value stdout lines. Keys outside the health
// prefix or with invalid names are dropped with a warning (the probe must
// not be able to overwrite, say, the product label, nor crash-loop the
// daemon with an apiserver-rejected key); on any failure the ok label is
// forced to "false".
Labels RunHealthExec(const config::Config& config, int chip_count) {
  Labels out;
  // The daemon's enumerated chip count rides into the probe's
  // environment so the PROBE's published label set can carry the
  // enumeration cross-check (jax initializing fewer devices than the
  // daemon's backend enumerated — see tpufd/health.py
  // devices-consistent). Scoped to the child shell via an export
  // prefix: RunCommandCapture runs `sh -c`, so this sets the variable
  // for the whole probe command (pipelines included) without ever
  // mutating the daemon's own environment.
  std::string command = config.flags.health_exec;
  if (chip_count >= 0) {
    command = "export TFD_CHIP_COUNT=" + std::to_string(chip_count) +
              "; " + command;
  }
  Result<std::string> text =
      RunCommandCapture(command, config.flags.health_exec_timeout_s);
  if (!text.ok()) {
    TFD_LOG_WARNING << "health exec failed: " << text.error();
    out[kHealthOk] = "false";
    return out;
  }
  for (const std::string& line : SplitString(*text, '\n')) {
    std::string trimmed = TrimSpace(line);
    if (trimmed.empty()) continue;
    size_t eq = trimmed.find('=');
    if (eq == std::string::npos || eq == 0) {
      TFD_LOG_WARNING << "health exec: ignoring malformed line: " << trimmed;
      continue;
    }
    std::string key = trimmed.substr(0, eq);
    std::string value = trimmed.substr(eq + 1);
    if (!HasPrefix(key, kHealthPrefix)) {
      TFD_LOG_WARNING << "health exec: ignoring label outside "
                      << kHealthPrefix << ": " << key;
      continue;
    }
    if (!ValidLabelKeySuffix(key.substr(sizeof(kHealthPrefix) - 1))) {
      TFD_LOG_WARNING << "health exec: ignoring invalid label key: " << key;
      continue;
    }
    // Label values are capped at 63 chars by the apiserver, and must have
    // alphanumeric ends — StrictLabelValue enforces both, because an
    // invalid VALUE from a buggy probe would fail the whole NodeFeature
    // update just like an invalid key. Truncating/trimming beats failing.
    std::string strict = StrictLabelValue(value);
    if (strict.empty() && !value.empty()) {
      TFD_LOG_WARNING << "health exec: dropping label with no valid value: "
                      << key << "=" << value;
      continue;
    }
    out[key] = strict;
  }
  if (out.empty()) {
    TFD_LOG_WARNING << "health exec produced no health labels";
    out[kHealthOk] = "false";
  }
  return out;
}

// Merges the (expensive) measured-probe labels, re-running the exec only
// when the cached result is older than --health-exec-interval. The probe
// benchmarks the silicon — rerunning a matmul/HBM/all-reduce sweep every
// 60s sleep-interval would steal TPU cycles from co-located jobs and
// stall label refresh; measured throughput does not change minute to
// minute. The daemon is single-threaded, so plain statics suffice.
void MergeHealthExecLabels(const config::Config& config, Labels* health,
                           int chip_count) {
  static Labels cached;
  static std::string cached_exec;
  static int cached_chip_count = -1;
  static std::chrono::steady_clock::time_point cached_at;
  static bool have_cache = false;

  // A failed probe retries much sooner than a good one re-measures:
  // transient causes (a training job briefly holding the exclusive chips,
  // a probe OOM) should not mark a healthy node unhealthy for a whole
  // --health-exec-interval.
  int interval_s = config.flags.health_exec_interval_s;
  if (have_cache) {
    auto it = cached.find(kHealthOk);
    if (it != cached.end() && it->second == "false") {
      interval_s = std::min(interval_s, 300);
    }
  }

  auto now = std::chrono::steady_clock::now();
  // chip_count is part of the staleness key: a chip dropping from (or
  // returning to) enumeration must re-run the probe immediately, or the
  // node would republish a stale devices-consistent verdict next to a
  // contradictory tpu.health.devices for up to a full interval.
  bool stale = !have_cache || cached_exec != config.flags.health_exec ||
               cached_chip_count != chip_count ||
               now - cached_at >= std::chrono::seconds(interval_s);
  if (stale) {
    cached = RunHealthExec(config, chip_count);
    cached_exec = config.flags.health_exec;
    cached_chip_count = chip_count;
    cached_at = now;
    have_cache = true;
  }
  for (const auto& [k, v] : cached) (*health)[k] = v;
}

}  // namespace

Result<LabelerPtr> NewTpuLabeler(const resource::ManagerPtr& manager,
                                 const config::Config& config) {
  auto probe_start = std::chrono::steady_clock::now();
  Status init = manager->Init();
  if (!init.ok()) {
    return Result<LabelerPtr>::Error("failed to initialize " +
                                     manager->Name() +
                                     " backend: " + init.message());
  }

  Result<std::vector<resource::DevicePtr>> devices = manager->GetDevices();
  if (!devices.ok()) {
    manager->Shutdown();
    return Result<LabelerPtr>::Error("error getting TPU devices: " +
                                     devices.error());
  }
  if (devices->empty()) {
    // No TPUs: contribute nothing (reference nvml.go:40-42); machine-type
    // and timestamp labels are handled at the run() level.
    manager->Shutdown();
    return LabelerPtr(Empty());
  }

  std::vector<LabelerPtr> parts;
  {
    Labels backend;
    backend[kBackendLabel] = manager->Name();
    parts.push_back(std::make_unique<StaticLabeler>(std::move(backend)));
  }
  parts.push_back(NewVersionLabeler(*manager));
  parts.push_back(NewSliceCapabilityLabeler(*manager));
  parts.push_back(NewTopologyLabeler(*manager));
  parts.push_back(NewIciLinksLabeler(*devices));
  const std::string& health_mode = config.flags.device_health;
  bool health_on = (health_mode == "basic" || health_mode == "full") &&
                   manager->TouchesDevices();
  Labels health;
  if (health_on) {
    // Basic health: the backend initialized and every chip enumerated, and
    // how long that took — a sick TPU stack shows up first as slow or
    // failing init (hence the fail path never reaches here; absence of
    // health labels on a TPU node means the probe never completed).
    // Restricted to device-touching backends: a control-plane backend
    // (metadata) must not vouch for chip health — including when auto
    // fell back to it because PJRT init failed.
    auto ms = std::chrono::duration_cast<std::chrono::milliseconds>(
                  std::chrono::steady_clock::now() - probe_start)
                  .count();
    health[kHealthOk] = "true";
    health[kHealthDevices] = std::to_string(devices->size());
    health[kHealthProbeMs] = std::to_string(ms);
  }
  Result<LabelerPtr> strategy = NewSliceStrategyLabeler(*manager, config);
  if (!strategy.ok()) {
    manager->Shutdown();
    return strategy;
  }
  parts.push_back(std::move(*strategy));
  manager->Shutdown();

  if (health_on && health_mode == "full") {
    // Full health: run the measured-silicon probe (default:
    // `python3 -m tpufd health` — matmul TFLOPs, HBM GB/s, ICI
    // all-reduce GB/s) and merge its labels. The probe self-reports
    // google.com/tpu.health.ok; a failed or timed-out probe downgrades
    // ok to false rather than silently keeping basic's true — a node
    // that enumerates but cannot run a matmul is exactly the node a
    // scheduler must avoid. Runs strictly AFTER manager->Shutdown():
    // TPU access is exclusive, so the probe could never acquire the
    // chips while the daemon's own PJRT client holds them.
    MergeHealthExecLabels(config, &health,
                          static_cast<int>(devices->size()));
  }
  if (health_on) {
    parts.push_back(std::make_unique<StaticLabeler>(std::move(health)));
  }

  // Everything above is eagerly-computed static data; collapse it now so
  // later GetLabels() calls cannot touch the (shut-down) manager.
  LabelerPtr merged = Merge(std::move(parts));
  Result<Labels> labels = merged->GetLabels();
  if (!labels.ok()) return Result<LabelerPtr>::Error(labels.error());
  return LabelerPtr(std::make_unique<StaticLabeler>(std::move(*labels)));
}

}  // namespace lm
}  // namespace tfd

// Slice-shape strategies: the TPU generalization of MIG strategies.
//
// Reference parity: internal/lm/mig-strategy.go — strategy dispatch
// none/single/mixed (mig-strategy.go:84-110), `single` homogeneity
// validation with INVALID-label degradation (mig-strategy.go:181-262),
// `mixed` per-profile resources (mig-strategy.go:264-295), and the
// mig.strategy label (strategy.go:20-28).
//
// TPU semantics:
//   none   — whole-chip labels only (google.com/tpu.*), no slice labels.
//   single — the node's slice must be homogeneous and consistent: a known
//            topology whose chip count equals chips-per-host × hosts and
//            whose shape parses for the family. The primary resource is
//            overloaded with slice labels (tpu.slice.shape/hosts/
//            chips-per-host/worker-id). Inconsistent topology degrades to
//            SLICE-INVALID labels with count/replicas = 0 rather than
//            failing, exactly like MIG-INVALID.
//   mixed  — the slice's labels move to a shape-qualified resource name
//            ("google.com/tpu-4x4.*") so schedulers can target shapes as
//            distinct resources; whole-chip labels remain for MIG-enabled-
//            device parity (reference keeps full-GPU labels alongside).
#pragma once

#include "tfd/config/config.h"
#include "tfd/lm/labeler.h"
#include "tfd/resource/types.h"

namespace tfd {
namespace lm {

// Builds the strategy-dispatched resource labeler for the node
// (reference NewResourceLabeler, mig-strategy.go:45-82). Returns an empty
// labeler when the manager exposes no devices.
Result<LabelerPtr> NewSliceStrategyLabeler(resource::Manager& manager,
                                           const config::Config& config);

}  // namespace lm
}  // namespace tfd

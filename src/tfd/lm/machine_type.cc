#include "tfd/lm/machine_type.h"

#include "tfd/lm/schema.h"
#include "tfd/util/file.h"
#include "tfd/util/logging.h"
#include "tfd/util/strings.h"

namespace tfd {
namespace lm {

namespace {

class MachineTypeLabeler : public Labeler {
 public:
  MachineTypeLabeler(std::string file, MachineTypeGetter getter)
      : file_(std::move(file)), getter_(std::move(getter)) {}

  Result<Labels> GetLabels() override {
    std::string machine_type = "unknown";
    bool found = false;
    if (getter_) {
      Result<std::string> m = getter_();
      if (m.ok() && !TrimSpace(*m).empty()) {
        machine_type = TrimSpace(*m);
        found = true;
      }
    }
    if (!found && !file_.empty()) {
      Result<std::string> contents = ReadFile(file_);
      if (contents.ok() && !TrimSpace(*contents).empty()) {
        machine_type = TrimSpace(*contents);
        found = true;
      }
    }
    if (!found) {
      TFD_LOG_WARNING << "could not determine machine type (metadata "
                         "unavailable, file '"
                      << file_ << "' unreadable); defaulting to 'unknown'";
    }
    Labels labels;
    labels[kMachineLabel] = StrictLabelValue(machine_type);
    return labels;
  }

 private:
  std::string file_;
  MachineTypeGetter getter_;
};

}  // namespace

LabelerPtr NewMachineTypeLabeler(const std::string& machine_type_file,
                                 MachineTypeGetter metadata_getter) {
  return std::make_unique<MachineTypeLabeler>(machine_type_file,
                                              std::move(metadata_getter));
}

}  // namespace lm
}  // namespace tfd

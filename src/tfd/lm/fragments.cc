#include "tfd/lm/fragments.h"

#include "tfd/lm/tpu_labeler.h"

namespace tfd {
namespace lm {

void PassSignature::Mix(const std::string& field) {
  for (unsigned char c : field) {
    hash_ ^= c;
    hash_ *= 1099511628211ULL;
  }
  hash_ ^= 0x1f;  // field separator: Mix("ab"),Mix("c") != Mix("a"),Mix("bc")
  hash_ *= 1099511628211ULL;
}

void PassSignature::MixU64(uint64_t value) {
  for (int i = 0; i < 8; i++) {
    hash_ ^= (value >> (8 * i)) & 0xff;
    hash_ *= 1099511628211ULL;
  }
}

uint64_t PassSignature::Digest() const { return hash_ == 0 ? 1 : hash_; }

Result<Labels> FragmentCache::TpuFragment(const resource::ManagerPtr& manager,
                                          const std::string& source,
                                          uint64_t render_key,
                                          int config_generation,
                                          const config::Config& config) {
  if (tpu_.valid && tpu_.source == source && tpu_.key == render_key &&
      tpu_.config_generation == config_generation) {
    return tpu_.labels;
  }
  Result<LabelerPtr> labeler = NewTpuLabeler(manager, config);
  if (!labeler.ok()) return Result<Labels>::Error(labeler.error());
  Result<Labels> labels = (*labeler)->GetLabels();
  if (!labels.ok()) return labels;
  tpu_.valid = true;
  tpu_.source = source;
  tpu_.key = render_key;
  tpu_.config_generation = config_generation;
  tpu_.labels = *labels;
  return labels;
}

Result<Labels> FragmentCache::HostFragment(const std::string& name,
                                           Labeler& labeler,
                                           int config_generation,
                                           bool force_refresh) {
  auto it = host_.find(name);
  if (!force_refresh && it != host_.end() && it->second.valid &&
      it->second.config_generation == config_generation) {
    return it->second.labels;
  }
  Result<Labels> labels = labeler.GetLabels();
  if (!labels.ok()) return labels;
  Entry& entry = host_[name];
  entry.valid = true;
  entry.config_generation = config_generation;
  entry.labels = *labels;
  return labels;
}

void FragmentCache::Invalidate() {
  tpu_ = Entry();
  host_.clear();
}

}  // namespace lm
}  // namespace tfd

#include "tfd/lm/health_exec.h"

#include <cctype>

#include "tfd/lm/schema.h"
#include "tfd/util/logging.h"
#include "tfd/util/strings.h"
#include "tfd/util/subprocess.h"

namespace tfd {
namespace lm {

namespace {

// A label key's name part (after the "google.com/" domain) must be a valid
// Kubernetes label name: alphanumeric ends, [-._a-zA-Z0-9] middle, <= 63
// chars TOTAL — and the name already starts with the fixed "tpu.health."
// (11 chars), so the probe's suffix gets at most 52. A bad key from a
// buggy probe must never reach the apiserver — an invalid label name
// fails the whole NodeFeature update.
bool ValidLabelKeySuffix(const std::string& s) {
  constexpr size_t kMax = 63 - (sizeof("tpu.health.") - 1);
  if (s.empty() || s.size() > kMax) return false;
  auto alnum = [](char c) { return isalnum(static_cast<unsigned char>(c)); };
  if (!alnum(s.front()) || !alnum(s.back())) return false;
  for (char c : s) {
    if (!alnum(c) && c != '-' && c != '_' && c != '.') return false;
  }
  return true;
}

}  // namespace

Labels RunHealthExec(const config::Config& config, int chip_count) {
  Labels out;
  // The daemon's enumerated chip count rides into the probe's
  // environment so the PROBE's published label set can carry the
  // enumeration cross-check (jax initializing fewer devices than the
  // daemon's backend enumerated — see tpufd/health.py
  // devices-consistent). Scoped to the child shell via an export
  // prefix: RunCommandCapture runs `sh -c`, so this sets the variable
  // for the whole probe command (pipelines included) without ever
  // mutating the daemon's own environment.
  std::string command = config.flags.health_exec;
  if (chip_count >= 0) {
    command = "export TFD_CHIP_COUNT=" + std::to_string(chip_count) +
              "; " + command;
  }
  Result<std::string> text =
      RunCommandCapture(command, config.flags.health_exec_timeout_s);
  if (!text.ok()) {
    TFD_LOG_WARNING << "health exec failed: " << text.error();
    out[kHealthOk] = "false";
    return out;
  }
  for (const std::string& line : SplitString(*text, '\n')) {
    std::string trimmed = TrimSpace(line);
    if (trimmed.empty()) continue;
    size_t eq = trimmed.find('=');
    if (eq == std::string::npos || eq == 0) {
      TFD_LOG_WARNING << "health exec: ignoring malformed line: " << trimmed;
      continue;
    }
    std::string key = trimmed.substr(0, eq);
    std::string value = trimmed.substr(eq + 1);
    if (!HasPrefix(key, kHealthPrefix)) {
      TFD_LOG_WARNING << "health exec: ignoring label outside "
                      << kHealthPrefix << ": " << key;
      continue;
    }
    if (!ValidLabelKeySuffix(key.substr(sizeof(kHealthPrefix) - 1))) {
      TFD_LOG_WARNING << "health exec: ignoring invalid label key: " << key;
      continue;
    }
    // Label values are capped at 63 chars by the apiserver, and must have
    // alphanumeric ends — StrictLabelValue enforces both, because an
    // invalid VALUE from a buggy probe would fail the whole NodeFeature
    // update just like an invalid key. Truncating/trimming beats failing.
    std::string strict = StrictLabelValue(value);
    if (strict.empty() && !value.empty()) {
      TFD_LOG_WARNING << "health exec: dropping label with no valid value: "
                      << key << "=" << value;
      continue;
    }
    out[key] = strict;
  }
  if (out.empty()) {
    TFD_LOG_WARNING << "health exec produced no health labels";
    out[kHealthOk] = "false";
  }
  return out;
}

}  // namespace lm
}  // namespace tfd

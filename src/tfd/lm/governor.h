// Label hold-down governor: anti-flap debouncing in front of the merge.
//
// The degradation ladder and the health state machine decide WHAT the
// daemon believes; this governor decides how fast a belief may reach a
// LABEL. Schedulers select on `google.com/tpu.*` keys, and a key that
// flips every rewrite — a flapping health exec, a source whose facts
// alternate — thrashes them worse than a stale value would. So every
// governed key carries a hold-down timer: once it changes, it may not
// change again for `hold_down_s`, and a bounded churn budget caps how
// many governed keys may change inside one window at all. Suppressed
// flips hold the previously published value, are journaled
// ("flap-suppressed", full provenance of the value that WOULD have
// been published) and counted (tfd_label_flaps_suppressed_total
// {key_prefix}).
//
// Monotone-informative changes bypass the governor — suppressing them
// would withhold NEW information rather than damp noise:
//   - first appearance: a key this process has never published;
//   - tier upgrades: a pass whose degradation-ladder rung IMPROVED
//     (metadata -> pjrt convergence, restored -> live) may change
//     anything, removing a downgrade marker (tpu.degraded,
//     tpu.snapshot-age-seconds) is always allowed, and so is a pass
//     converging AWAY from a published
//     SLICE-INVALID sentinel (the slice overlay recovered — flipping
//     INTO the sentinel stays governed, so this cannot oscillate);
//   - measurement keys (tpu.health.probe-ms) and the
//     tpu.health.quarantined annotation (healthsm's already-debounced
//     verdict) are exempt outright, and tpu.snapshot-age-seconds
//     mirrors tpu.degraded's outcome rather than burning its own
//     timer (the pair is set and cleared together);
//   - the slice-coherence verdict keys (tpu.slice.id/healthy-hosts/
//     degraded) are exempt outright: their contract is byte-identical
//     values on every member of a slice, and per-host hold-down
//     timers would break it — anti-flap for them lives in the verdict
//     protocol (slice/coord.h). tpu.slice.class is governed with the
//     perf-class demotion bypass; tpu.slice.hosts is exempt only when
//     the value in play carries the slice-coord labeler's provenance
//     (the topology labeler's copy of the same key stays governed).
//
// Only `google.com/tpu*` keys are governed: the timestamp label
// (google.com/tfd.*) is cadence proof, not node identity.
#pragma once

#include <deque>
#include <map>
#include <string>
#include <vector>

#include "tfd/lm/merge.h"

namespace tfd {
namespace lm {

struct GovernorPolicy {
  // Minimum seconds between changes of one governed key
  // (--health-flap-window: the hold-down period IS the flap window).
  int hold_down_s = 300;
  // Governed (non-monotone) key changes allowed inside one hold-down
  // window across ALL keys (derived from --health-flap-threshold).
  int churn_budget = 6;
};

struct SuppressedFlip {
  std::string key;
  std::string op;         // "added" | "removed" | "changed"
  std::string old_value;  // what stays published
  std::string new_value;  // what was suppressed
  std::string reason;     // "hold-down" | "churn-budget"
  LabelProvenance provenance;  // of the suppressed candidate value
};

class LabelGovernor {
 public:
  explicit LabelGovernor(GovernorPolicy policy = GovernorPolicy());

  // SIGHUP reload: thresholds change, hold-down history survives.
  void Configure(GovernorPolicy policy);
  GovernorPolicy policy() const;

  // Governs `candidate` (the merged label set about to be published)
  // against `previous` (the last published set): suppressed keys are
  // reverted in place to their previous value/absence (provenance
  // restored from `prev_provenance`), and each suppression is reported
  // in `suppressed`. `level_improved` marks a pass whose serving rung
  // improved — its changes are monotone-informative and pass through.
  // Allowed changes are recorded as PENDING; the caller must
  // CommitPublished() once the set actually lands in the sink, so a
  // transient sink failure never burns a key's hold-down timer (the
  // retry would then suppress the very change it meant to publish).
  // A new Apply() discards any uncommitted pending changes.
  void Apply(const Labels& previous, const Provenance& prev_provenance,
             bool level_improved, double now_s, Labels* candidate,
             Provenance* provenance,
             std::vector<SuppressedFlip>* suppressed);
  void CommitPublished();

  // Timer introspection for the pass planner (cmd/ PassPlan): true
  // while the most recent Apply() suppressed at least one flip. The
  // suppressed candidate becomes publishable the moment its hold-down
  // timer or the churn budget frees — with NO snapshot movement to
  // dirty the pass — so no-op short-circuiting must stay off until a
  // pass applies with zero suppressions. Cleared by Reset().
  bool PendingSuppressions() const;

  // Seeds the history from a set published OUTSIDE Apply (the
  // warm-restart passes write to the sink directly): newly seen keys
  // start their hold-down at `now_s`.
  void NotePublished(const Labels& labels, double now_s);

  void Reset();

 private:
  GovernorPolicy policy_;
  std::map<std::string, double> last_change_;  // governed key -> wall time
  std::deque<double> window_changes_;          // budget bookkeeping
  std::map<std::string, double> pending_change_;
  int pending_budget_spend_ = 0;
  double pending_now_ = 0;
  size_t last_apply_suppressed_ = 0;
};

// True for keys the governor debounces (google.com/tpu*, minus the
// exempt measurement keys).
bool GovernedKey(const std::string& key);

// True for the downgrade-marker keys whose REMOVAL is always a tier
// upgrade (tpu.degraded, tpu.snapshot-age-seconds).
// tpu.health.quarantined is not one: it is exempt from governing
// outright (GovernedKey returns false for it).
bool DowngradeMarkerKey(const std::string& key);

}  // namespace lm
}  // namespace tfd

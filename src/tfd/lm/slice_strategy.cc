#include "tfd/lm/slice_strategy.h"

#include "tfd/lm/resource_labeler.h"
#include "tfd/lm/schema.h"
#include "tfd/slice/shape.h"
#include "tfd/slice/topology.h"
#include "tfd/util/logging.h"
#include "tfd/util/strings.h"

namespace tfd {
namespace lm {

namespace {

// Resolves the slice topology into a validated shape. The TPU analogue of
// the reference's `single` validation chain (mig-strategy.go:181-241):
//   - topology (or accelerator type) must be known,
//   - the shape must parse under the slice-shape grammar,
//   - shape chips must equal chips-per-host × hosts when both are known.
// Any failure returns an error → the caller degrades to SLICE-INVALID.
Result<slice::Shape> ResolveValidatedShape(
    const resource::TopologyInfo& topo, int local_chips) {
  std::string topology = topo.topology;
  std::optional<slice::AcceleratorType> accel;
  if (!topo.accelerator_type.empty()) {
    Result<slice::AcceleratorType> a =
        slice::ParseAcceleratorType(topo.accelerator_type);
    if (!a.ok()) return Result<slice::Shape>::Error(a.error());
    accel = *a;
  }
  if (topology.empty()) {
    if (!accel.has_value()) {
      return Result<slice::Shape>::Error(
          "slice topology unknown: neither topology nor accelerator-type "
          "available");
    }
    Result<slice::Shape> dflt =
        slice::DefaultTopology(accel->spec, accel->num_chips);
    if (!dflt.ok()) return dflt;
    topology = dflt->ToString();
  }
  Result<slice::Shape> shape = slice::ParseShape(topology);
  if (!shape.ok()) return shape;

  int shape_chips = shape->NumChips();
  if (accel.has_value() && accel->num_chips != shape_chips) {
    return Result<slice::Shape>::Error(
        "topology " + shape->ToString() + " has " +
        std::to_string(shape_chips) + " chips but accelerator type " +
        accel->raw + " has " + std::to_string(accel->num_chips));
  }
  int hosts = topo.num_hosts > 0 ? topo.num_hosts : 1;
  int chips_per_host =
      topo.chips_per_host > 0 ? topo.chips_per_host : local_chips;
  if (chips_per_host > 0 && hosts > 0 &&
      chips_per_host * hosts != shape_chips) {
    return Result<slice::Shape>::Error(
        "topology " + shape->ToString() + " (" +
        std::to_string(shape_chips) + " chips) does not match " +
        std::to_string(hosts) + " hosts x " +
        std::to_string(chips_per_host) + " chips/host");
  }
  return shape;
}

// Slice placement labels shared by single and mixed
// (hosts / chips-per-host / worker-id / shape).
Labels SliceLabels(const resource::TopologyInfo& topo,
                   const slice::Shape& shape, int local_chips) {
  Labels labels;
  labels[kSliceShape] = shape.ToString();
  labels[kSliceHosts] =
      std::to_string(topo.num_hosts > 0 ? topo.num_hosts : 1);
  labels[kSliceChipsPerHost] = std::to_string(
      topo.chips_per_host > 0 ? topo.chips_per_host : local_chips);
  if (topo.worker_id >= 0) {
    labels[kSliceWorkerId] = std::to_string(topo.worker_id);
  }
  return labels;
}

// SLICE-INVALID degradation (reference newInvalidMigStrategyLabeler,
// mig-strategy.go:243-262): explicit zeroed labels instead of failure.
LabelerPtr InvalidSliceLabeler(const std::string& resource_name,
                               const std::string& reason) {
  TFD_LOG_WARNING << "invalid slice configuration: " << reason
                  << "; emitting " << kSliceInvalid << " labels";
  Labels labels;
  const std::string p = resource_name + ".";
  labels[p + "product"] = kSliceInvalid;
  labels[p + "count"] = "0";
  labels[p + "replicas"] = "0";
  labels[p + "memory"] = "0";
  labels[kSliceShape] = kSliceInvalid;
  return std::make_unique<StaticLabeler>(std::move(labels));
}

}  // namespace

Result<LabelerPtr> NewSliceStrategyLabeler(resource::Manager& manager,
                                           const config::Config& config) {
  Result<std::vector<resource::DevicePtr>> devices = manager.GetDevices();
  if (!devices.ok()) {
    return Result<LabelerPtr>::Error("error getting TPU devices: " +
                                     devices.error());
  }
  if (devices->empty()) return LabelerPtr(Empty());
  int local_chips = static_cast<int>(devices->size());

  const std::string& strategy = config.flags.slice_strategy;
  const std::string tpu_resource = config::kTpuResourceName;

  // Whole-chip labels, always present (reference fullGPULabeler,
  // mig-strategy.go:56-63).
  Result<LabelerPtr> full =
      NewTpuResourceLabeler(tpu_resource, *devices, config.sharing);
  if (!full.ok()) return full;

  if (strategy == config::kSliceStrategyNone) {
    return full;
  }

  // Strategy label (reference strategy.go:20-28).
  Labels strategy_labels;
  strategy_labels[kSliceStrategy] = strategy;

  Result<resource::TopologyInfo> topo = manager.GetTopology();
  std::vector<LabelerPtr> parts;
  parts.push_back(std::move(*full));
  parts.push_back(
      std::make_unique<StaticLabeler>(std::move(strategy_labels)));

  if (!topo.ok()) {
    parts.push_back(InvalidSliceLabeler(tpu_resource, topo.error()));
    return Merge(std::move(parts));
  }

  Result<slice::Shape> shape = ResolveValidatedShape(*topo, local_chips);
  if (!shape.ok()) {
    parts.push_back(InvalidSliceLabeler(tpu_resource, shape.error()));
    return Merge(std::move(parts));
  }

  if (strategy == config::kSliceStrategySingle) {
    // Overload the primary resource with slice labels
    // (reference newMigStrategySingleLabeler, mig-strategy.go:181-241).
    parts.push_back(std::make_unique<StaticLabeler>(
        SliceLabels(*topo, *shape, local_chips)));
    return Merge(std::move(parts));
  }

  // mixed: shape-qualified resource (reference newMigStrategyMixedLabeler,
  // mig-strategy.go:264-295, resource name "nvidia.com/mig-<profile>").
  std::string shape_resource =
      std::string(config::kTpuResourceName) + "-" + shape->ToString();
  Result<LabelerPtr> shaped = NewShapeResourceLabeler(
      shape_resource, shape->ToString(), *devices, config.sharing);
  if (!shaped.ok()) return shaped;
  parts.push_back(std::move(*shaped));
  parts.push_back(std::make_unique<StaticLabeler>(
      SliceLabels(*topo, *shape, local_chips)));
  return Merge(std::move(parts));
}

}  // namespace lm
}  // namespace tfd

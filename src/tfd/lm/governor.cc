#include "tfd/lm/governor.h"

#include <algorithm>

#include "tfd/lm/schema.h"
#include "tfd/perf/perf.h"
#include "tfd/util/strings.h"

namespace tfd {
namespace lm {

bool GovernedKey(const std::string& key) {
  if (!HasPrefix(key, "google.com/tpu")) return false;
  // Measurement keys move every pass by design; damping them would
  // only hide the measurement. snapshot-age is handled as kDegraded's
  // paired marker, never on its own timer. The quarantine annotation is
  // healthsm's already-debounced verdict (threshold flaps to appear,
  // cooldown + clean streak to clear) — governing it can only suppress
  // the one label that explains why everything else is held.
  if (key == kHealthProbeMs) return false;
  if (key == kSnapshotAge) return false;
  if (key == kHealthQuarantined) return false;
  // tpu.perf.* measurements re-publish only per (slow) re-measure and
  // already carry the characterization pipeline's debounce; only the
  // CLASS verdict is a scheduling-facing structural fact worth
  // governing (same split as the snapshot flap fingerprint). Damping
  // the numbers would publish a demoted class next to the healthy
  // chip's held throughput — a torn pair.
  if (HasPrefix(key, kPerfPrefix)) return key == kPerfClass;
  // Slice-coherence verdict keys (slice/coord.h) are exempt from the
  // per-key hold-down: their contract is that every member of a slice
  // publishes IDENTICAL values, and per-host hold-down timers — started
  // at each host's own last change — would keep hosts disagreeing for
  // up to a whole window after every verdict move. Anti-flap for these
  // keys lives where the whole slice shares it — in the verdict
  // protocol: the leader's verdict only moves when a member's report
  // actually changes or ages out of the agreement window, and every
  // input to a report is itself debounced (device snapshot tiers,
  // healthsm quarantine, the perf class streaks). Verdict movement is
  // correspondingly excluded from the slice source's flap fingerprint
  // (sched/snapshot.cc FingerprintedLabel) — a coordinated transition
  // every member adopts identically is not per-host instability.
  // The slice CLASS is the exception: it is governed like tpu.perf.class
  // (demotions bypass below, promotions ride the hold-down).
  // tpu.slice.hosts is NOT key-exempt: the topology labeler publishes
  // it too (with or without coordination), and waiving its hold-down
  // would let a flapping topology probe flip it freely next to its
  // still-governed siblings (slice.shape, slice.chips-per-host) — a
  // torn set. Coordination-OWNED changes of it (the provenance names
  // the slice-coord labeler) bypass in Apply() instead.
  if (key == kSliceId || key == kSliceHealthyHosts ||
      key == kSliceDegraded) {
    return false;
  }
  // Lifecycle fast-path keys (tpu.lifecycle.preempt-imminent/draining)
  // are exempt like the quarantine annotation: edge-triggered,
  // conservative-direction facts whose inputs (the GCE preemption
  // notice, a kubelet taint) are already debounced upstream — a
  // governor hold-down could delay the ONE label a scheduler needs
  // within the ~30s preemption warning window.
  if (HasPrefix(key, kLifecyclePrefix)) return false;
  return true;
}

bool DowngradeMarkerKey(const std::string& key) {
  return key == kDegraded || key == kSnapshotAge;
}

LabelGovernor::LabelGovernor(GovernorPolicy policy) { Configure(policy); }

void LabelGovernor::Configure(GovernorPolicy policy) {
  if (policy.hold_down_s < 1) policy.hold_down_s = 1;
  if (policy.churn_budget < 1) policy.churn_budget = 1;
  policy_ = policy;
}

GovernorPolicy LabelGovernor::policy() const { return policy_; }

void LabelGovernor::NotePublished(const Labels& labels, double now_s) {
  for (const auto& [key, value] : labels) {
    (void)value;
    if (!GovernedKey(key)) continue;
    last_change_.emplace(key, now_s);  // only newly seen keys
  }
}

void LabelGovernor::Apply(const Labels& previous,
                          const Provenance& prev_provenance,
                          bool level_improved, double now_s,
                          Labels* candidate, Provenance* provenance,
                          std::vector<SuppressedFlip>* suppressed) {
  const size_t suppressed_before = suppressed->size();
  pending_change_.clear();  // uncommitted pass: its changes never landed
  pending_budget_spend_ = 0;
  pending_now_ = now_s;
  while (!window_changes_.empty() &&
         window_changes_.front() < now_s - policy_.hold_down_s) {
    window_changes_.pop_front();
  }

  // A pass that converges AWAY from a published SLICE-INVALID sentinel
  // (the slice labeler's explicit degradation values: the topology
  // overlay had no answer yet) is an overlay recovery — the value-level
  // analogue of a tier upgrade, carrying NEW information the governor
  // must not damp. The reverse direction gets no such pass: flipping
  // INTO the sentinel is a governed change, so a flapping overlay holds
  // at its last valid facts and this hatch never re-arms.
  bool invalid_recovery = false;
  for (const auto& [key, value] : previous) {
    if (!GovernedKey(key) || value != kSliceInvalid) continue;
    auto cand = candidate->find(key);
    if (cand == candidate->end() || cand->second != kSliceInvalid) {
      invalid_recovery = true;
      break;
    }
  }
  if (invalid_recovery) level_improved = true;

  // The union of governed keys across both sets, walked in key order so
  // suppressions journal deterministically.
  std::vector<std::string> keys;
  for (const auto& [key, value] : previous) {
    (void)value;
    if (GovernedKey(key)) keys.push_back(key);
  }
  for (const auto& [key, value] : *candidate) {
    (void)value;
    if (GovernedKey(key) && previous.count(key) == 0) keys.push_back(key);
  }
  std::sort(keys.begin(), keys.end());

  bool degraded_suppressed = false;
  for (const std::string& key : keys) {
    auto prev_it = previous.find(key);
    auto cand_it = candidate->find(key);
    bool prev_has = prev_it != previous.end();
    bool cand_has = cand_it != candidate->end();
    if (prev_has && cand_has && prev_it->second == cand_it->second) {
      continue;  // unchanged
    }
    if (!prev_has && !cand_has) continue;

    bool first_appearance =
        !prev_has && last_change_.find(key) == last_change_.end();
    bool marker_upgrade = !cand_has && DowngradeMarkerKey(key);
    // tpu.slice.hosts has two producers. The topology labeler's copy
    // is a per-host probe fact and stays governed like its siblings
    // (slice.shape, slice.chips-per-host); the coordination verdict's
    // copy carries the slice contract — identical-or-absent on every
    // member — and is exempt like the other verdict keys (see
    // GovernedKey). The provenance of the value IN PLAY (candidate's,
    // or for a removal the previously published one's) names the
    // producer this change belongs to.
    bool coord_slice_hosts = false;
    if (key == kSliceHosts) {
      const Provenance& from = cand_has ? *provenance : prev_provenance;
      auto it = from.find(key);
      coord_slice_hosts =
          it != from.end() && it->second.labeler == kSliceCoordLabeler;
    }
    // A perf-class DEMOTION (gold -> silver -> degraded) is
    // monotone-informative in the conservative direction: the
    // characterization pipeline already debounced it (hysteresis +
    // healthsm rank streaks), and holding it back would keep routing
    // latency-critical traffic to a chip proven slow. PROMOTIONS stay
    // governed — flipping back up is where flap damage lives, and the
    // debounce's recover_after streak plus this hold-down make the
    // up-down cycle strictly slower than the down leg.
    // tpu.slice.class carries the same contract slice-wide (the verdict
    // is the min of already-debounced member classes): a slice demotion
    // must land on every member promptly, a promotion earns its way
    // back through the hold-down.
    bool class_demotion = false;
    if ((key == kPerfClass || key == kSliceClass) && prev_has &&
        cand_has) {
      int was = perf::ClassRankFromName(prev_it->second);
      int now_rank = perf::ClassRankFromName(cand_it->second);
      class_demotion = was >= 0 && now_rank > was;
    }
    if (first_appearance || marker_upgrade || class_demotion ||
        coord_slice_hosts || level_improved) {
      pending_change_[key] = now_s;
      continue;
    }

    std::string reason;
    auto seen = last_change_.find(key);
    double last = seen == last_change_.end() ? now_s - 2 * policy_.hold_down_s
                                             : seen->second;
    if (now_s - last < policy_.hold_down_s) {
      reason = "hold-down";
    } else if (static_cast<int>(window_changes_.size()) +
                   pending_budget_spend_ >=
               policy_.churn_budget) {
      reason = "churn-budget";
    }
    if (reason.empty()) {
      pending_change_[key] = now_s;
      pending_budget_spend_++;
      continue;
    }

    // Suppress: hold the previously published value (or absence).
    SuppressedFlip flip;
    flip.key = key;
    flip.op = !prev_has ? "added" : (!cand_has ? "removed" : "changed");
    flip.old_value = prev_has ? prev_it->second : "";
    flip.new_value = cand_has ? cand_it->second : "";
    flip.reason = reason;
    if (cand_has) {
      auto from = provenance->find(key);
      if (from != provenance->end()) flip.provenance = from->second;
    } else {
      // A suppressed removal has no candidate entry to cite; the
      // provenance that explains the journal event is the previously
      // published value's — the one the hold keeps serving.
      auto from = prev_provenance.find(key);
      if (from != prev_provenance.end()) flip.provenance = from->second;
    }
    if (prev_has) {
      (*candidate)[key] = prev_it->second;
      auto from = prev_provenance.find(key);
      if (from != prev_provenance.end()) {
        (*provenance)[key] = from->second;
      }
    } else {
      candidate->erase(key);
      provenance->erase(key);
    }
    if (key == kDegraded) degraded_suppressed = true;
    suppressed->push_back(std::move(flip));
  }

  // tpu.snapshot-age-seconds rides with tpu.degraded: when the marker's
  // flip was suppressed, the age must mirror the held state too —
  // publishing an age without its marker (or vice versa) would be a
  // torn pair.
  if (degraded_suppressed) {
    auto prev_it = previous.find(kSnapshotAge);
    if (prev_it != previous.end()) {
      (*candidate)[kSnapshotAge] = prev_it->second;
      auto from = prev_provenance.find(kSnapshotAge);
      if (from != prev_provenance.end()) {
        (*provenance)[kSnapshotAge] = from->second;
      }
    } else {
      candidate->erase(kSnapshotAge);
      provenance->erase(kSnapshotAge);
    }
  }
  last_apply_suppressed_ = suppressed->size() - suppressed_before;
}

bool LabelGovernor::PendingSuppressions() const {
  return last_apply_suppressed_ > 0;
}

void LabelGovernor::CommitPublished() {
  for (const auto& [key, when] : pending_change_) {
    last_change_[key] = when;
  }
  for (int i = 0; i < pending_budget_spend_; i++) {
    window_changes_.push_back(pending_now_);
  }
  pending_change_.clear();
  pending_budget_spend_ = 0;
}

void LabelGovernor::Reset() {
  last_change_.clear();
  window_changes_.clear();
  pending_change_.clear();
  pending_budget_spend_ = 0;
  last_apply_suppressed_ = 0;
}

}  // namespace lm
}  // namespace tfd

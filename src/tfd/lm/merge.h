// Label-set diffing and per-key provenance.
//
// The merge pipeline (labeler.h) decides WHAT the label set is; this
// header carries the explainability companions the flight recorder
// (obs/journal.h) and /debug/labels need: which labeler/probe-source/
// staleness-tier produced each key, and what changed between two
// consecutive rewrites (added / removed / changed, with old→new values).
// The daemon journals one "label-diff" event per changed key and counts
// changes in tfd_label_changes_total{key_prefix}.
#pragma once

#include <map>
#include <string>
#include <vector>

#include "tfd/lm/labeler.h"

namespace tfd {
namespace lm {

// Where one label key came from, captured at merge time.
struct LabelProvenance {
  std::string labeler;  // "timestamp", "machine-type", "tpu", ...
  std::string source;   // probe source ("pjrt", "metadata", "health",
                        // "local" for host-derived labelers)
  std::string tier;     // snapshot tier name serving it ("fresh", ...)
  double age_s = 0;     // snapshot age at merge time (0 for local)
};

using Provenance = std::map<std::string, LabelProvenance>;

struct LabelDiffEntry {
  enum class Op { kAdded, kRemoved, kChanged };
  Op op = Op::kAdded;
  std::string key;
  std::string old_value;  // empty for kAdded
  std::string new_value;  // empty for kRemoved
};

const char* DiffOpName(LabelDiffEntry::Op op);

// Key-ordered diff between two label sets (both std::map, so the walk
// is a linear merge). Equal sets yield an empty diff.
std::vector<LabelDiffEntry> DiffLabels(const Labels& previous,
                                       const Labels& next);

// The bounded-cardinality metric prefix for a label key: everything up
// to (and excluding) the first '.' after the namespace slash —
// "google.com/tpu.count" → "google.com/tpu",
// "google.com/tfd.timestamp" → "google.com/tfd". Slash-less keys
// truncate at their first '.' ("plain.key" → "plain"); keys with no
// '.' after the slash (or at all) pass through whole.
std::string LabelKeyPrefix(const std::string& key);

}  // namespace lm
}  // namespace tfd

// Labeler core: the composable pipeline every feature source plugs into.
//
// Reference parity: internal/lm/labeler.go:28-30 (Labeler interface),
// internal/lm/labels.go:41-47 (Labels map that is itself a Labeler),
// internal/lm/list.go:25-46 (Merge combinator, later labelers win),
// internal/lm/empty.go:20 (null object).
//
// TPU-first difference: `Labels` is a std::map (sorted by key), which makes
// every sink deterministic byte-for-byte — a north-star requirement
// (BASELINE.md) that the reference's Go map iteration order cannot give.
#pragma once

#include <map>
#include <memory>
#include <string>
#include <vector>

#include "tfd/util/status.h"

namespace tfd {
namespace lm {

// Sorted key → value label set. Sorted order IS the output order.
using Labels = std::map<std::string, std::string>;

class Labeler {
 public:
  virtual ~Labeler() = default;
  virtual Result<Labels> GetLabels() = 0;
};

using LabelerPtr = std::unique_ptr<Labeler>;

// A fixed label set as a Labeler (reference: Labels.Labels()).
class StaticLabeler : public Labeler {
 public:
  explicit StaticLabeler(Labels labels) : labels_(std::move(labels)) {}
  Result<Labels> GetLabels() override { return labels_; }

 private:
  Labels labels_;
};

// Labeler that always returns no labels (reference: empty.go).
class EmptyLabeler : public Labeler {
 public:
  Result<Labels> GetLabels() override { return Labels{}; }
};

inline LabelerPtr Empty() { return std::make_unique<EmptyLabeler>(); }

// Merge: runs each labeler in order and merges the maps; on key conflict the
// later labeler wins (reference list.go:33-46). Any child error aborts.
class MergedLabeler : public Labeler {
 public:
  explicit MergedLabeler(std::vector<LabelerPtr> children)
      : children_(std::move(children)) {}

  Result<Labels> GetLabels() override {
    Labels merged;
    for (auto& child : children_) {
      Result<Labels> r = child->GetLabels();
      if (!r.ok()) return r;
      for (auto& [k, v] : *r) merged[k] = v;  // later wins
    }
    return merged;
  }

 private:
  std::vector<LabelerPtr> children_;
};

LabelerPtr Merge(std::vector<LabelerPtr> children);

}  // namespace lm
}  // namespace tfd

#include "tfd/lm/resource_labeler.h"

#include <map>

#include "tfd/util/logging.h"
#include "tfd/util/strings.h"

namespace tfd {
namespace lm {

namespace {

// Collects homogeneous device attributes; TPU hosts are homogeneous by
// construction, but we validate instead of assuming (the reference warns on
// >1 model per node, mig-strategy.go:125-152).
struct DeviceSummary {
  std::string product;
  std::string family;
  int generation = 0;
  int cores = 0;
  long long memory_mib = 0;
  int count = 0;
};

Result<DeviceSummary> Summarize(
    const std::vector<resource::DevicePtr>& devices) {
  // Heterogeneous products on one host should be impossible on real TPU
  // hardware, but a buggy backend (or exotic future host) must degrade,
  // not crash-loop the daemon: the reference WARNS on >1 model and labels
  // anyway (mig-strategy.go:125-152, where per-model labelers merge and
  // the shared label keys end up describing one model). Here the dominant
  // product group wins deterministically (largest count, then
  // lexicographically smallest product) and the anomaly is logged.
  std::map<std::string, DeviceSummary> by_product;
  for (const resource::DevicePtr& d : devices) {
    Result<std::string> product = d->GetProduct();
    if (!product.ok()) return Result<DeviceSummary>::Error(product.error());
    Result<long long> memory = d->GetTotalMemoryMiB();
    if (!memory.ok()) return Result<DeviceSummary>::Error(memory.error());
    Result<int> cores = d->GetCoreCount();
    if (!cores.ok()) return Result<DeviceSummary>::Error(cores.error());
    Result<int> generation = d->GetGeneration();
    if (!generation.ok()) {
      return Result<DeviceSummary>::Error(generation.error());
    }
    DeviceSummary& s = by_product[*product];
    if (s.count == 0) {
      s.product = *product;
      s.memory_mib = *memory;
      s.cores = *cores;
      s.generation = *generation;
    }
    s.count++;
  }
  Result<std::string> dominant = DominantProduct(devices);
  if (!dominant.ok()) return Result<DeviceSummary>::Error(dominant.error());
  if (by_product.size() > 1) {
    std::string all;
    for (const auto& [product, s] : by_product) {
      if (!all.empty()) all += ", ";
      all += product + " x" + std::to_string(s.count);
    }
    TFD_LOG_WARNING << "heterogeneous TPU products on one host (" << all
                    << "); labeling only '" << *dominant << "'";
  }
  DeviceSummary s = by_product[*dominant];
  // family = product minus the "tpu-" prefix (tpu-v5e → v5e).
  s.family = HasPrefix(s.product, "tpu-") ? s.product.substr(4) : s.product;
  return s;
}

Labels BuildLabels(const std::string& resource_name,
                   const DeviceSummary& s,
                   const config::Sharing& sharing,
                   const std::string& product_suffix) {
  // Sharing semantics mirror resource.go:182-226: replicas multiplies the
  // advertised count; the product gets "-SHARED" unless the resource is
  // renamed (a renamed resource is already distinguishable).
  int replicas = s.count;
  std::string product = s.product;
  if (!product_suffix.empty()) product += "-SLICE-" + product_suffix;
  std::optional<config::SharedResource> shared =
      sharing.Match(resource_name);
  if (shared.has_value()) {
    replicas = s.count * shared->replicas;
    if (shared->rename.empty()) {
      product += "-SHARED";
    }
  }

  Labels labels;
  const std::string p = resource_name + ".";
  labels[p + "product"] = StrictLabelValue(product);
  labels[p + "count"] = std::to_string(s.count);
  labels[p + "replicas"] = std::to_string(replicas);
  labels[p + "memory"] = std::to_string(s.memory_mib);
  labels[p + "family"] = s.family;
  labels[p + "generation"] = std::to_string(s.generation);
  labels[p + "cores"] = std::to_string(s.cores);
  return labels;
}

Result<LabelerPtr> Build(const std::string& resource_name,
                         const std::string& shape,
                         const std::vector<resource::DevicePtr>& devices,
                         const config::Sharing& sharing) {
  if (devices.empty()) return LabelerPtr(Empty());
  Result<DeviceSummary> summary = Summarize(devices);
  if (!summary.ok()) return Result<LabelerPtr>::Error(summary.error());
  return LabelerPtr(std::make_unique<StaticLabeler>(
      BuildLabels(resource_name, *summary, sharing, shape)));
}

}  // namespace

Result<std::string> DominantProduct(
    const std::vector<resource::DevicePtr>& devices) {
  std::map<std::string, int> counts;
  for (const resource::DevicePtr& device : devices) {
    Result<std::string> product = device->GetProduct();
    if (!product.ok()) return product;
    counts[*product]++;
  }
  const std::string* dominant = nullptr;
  int best = 0;
  // Ascending map order + strict > = lexicographically smallest tie-break.
  for (const auto& [product, n] : counts) {
    if (dominant == nullptr || n > best) {
      dominant = &product;
      best = n;
    }
  }
  if (dominant == nullptr) {
    return Result<std::string>::Error("no TPU devices to summarize");
  }
  return *dominant;
}

Result<LabelerPtr> NewTpuResourceLabeler(
    const std::string& resource_name,
    const std::vector<resource::DevicePtr>& devices,
    const config::Sharing& sharing) {
  return Build(resource_name, "", devices, sharing);
}

Result<LabelerPtr> NewTpuResourceLabelerWithoutSharing(
    const std::string& resource_name,
    const std::vector<resource::DevicePtr>& devices) {
  return Build(resource_name, "", devices, config::Sharing{});
}

Result<LabelerPtr> NewShapeResourceLabeler(
    const std::string& resource_name, const std::string& shape,
    const std::vector<resource::DevicePtr>& devices,
    const config::Sharing& sharing) {
  return Build(resource_name, shape, devices, sharing);
}

}  // namespace lm
}  // namespace tfd

// Per-resource label generation.
//
// Reference parity: internal/lm/resource.go — resourceLabeler produces
// <resource>.product/count/replicas/memory/... labels, applying time-slicing
// sharing (replicas multiplier + "-SHARED" product suffix unless renamed,
// resource.go:182-226). The TPU version generates, for a resource name like
// "google.com/tpu" or "google.com/tpu-4x4":
//   <resource>.product   e.g. tpu-v5e  (with -SHARED suffix when shared)
//   <resource>.count     chips attached to this host
//   <resource>.replicas  schedulable replicas (count × sharing replicas)
//   <resource>.memory    per-chip HBM MiB
//   <resource>.family    v2|v3|v4|v5e|v5p|v6e
//   <resource>.generation 2..6       (compute-capability analogue)
//   <resource>.cores     TensorCores per chip
#pragma once

#include <string>
#include <vector>

#include "tfd/config/config.h"
#include "tfd/lm/labeler.h"
#include "tfd/resource/types.h"

namespace tfd {
namespace lm {

// The dominant product among `devices` (largest count, then
// lexicographically smallest) — the ONE selection rule for everything
// keyed on "the node's product": the heterogeneous warn-and-label
// degradation here, and the ici.links label in tpu_labeler.cc. Errors
// when a device cannot report its product, or on an empty list.
Result<std::string> DominantProduct(
    const std::vector<resource::DevicePtr>& devices);

// Labels for the primary TPU resource with sharing applied
// (reference NewGPUResourceLabeler, resource.go:36-73).
Result<LabelerPtr> NewTpuResourceLabeler(
    const std::string& resource_name,
    const std::vector<resource::DevicePtr>& devices,
    const config::Sharing& sharing);

// Same, with sharing disabled (reference
// NewGPUResourceLabelerWithoutSharing, resource.go:30-33).
Result<LabelerPtr> NewTpuResourceLabelerWithoutSharing(
    const std::string& resource_name,
    const std::vector<resource::DevicePtr>& devices);

// Product override used by shape-qualified resources in the mixed strategy
// (reference NewMIGResourceLabeler builds "MODEL-MIG-<profile>" products,
// resource.go:76-111): product becomes "<product>-SLICE-<shape>".
Result<LabelerPtr> NewShapeResourceLabeler(
    const std::string& resource_name, const std::string& shape,
    const std::vector<resource::DevicePtr>& devices,
    const config::Sharing& sharing);

}  // namespace lm
}  // namespace tfd

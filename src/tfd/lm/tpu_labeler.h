// The TPU composite labeler — the NVML-labeler analogue.
//
// Reference parity: internal/lm/nvml.go:29-72 (NewNVMLLabeler): Init the
// manager, short-circuit to empty on 0 devices, then merge version +
// mig-capability + resource labelers, and Shutdown. All labels are computed
// eagerly here (as the reference does) so the returned labeler is pure data.
#pragma once

#include "tfd/config/config.h"
#include "tfd/lm/labeler.h"
#include "tfd/resource/types.h"

namespace tfd {
namespace lm {

Result<LabelerPtr> NewTpuLabeler(const resource::ManagerPtr& manager,
                                 const config::Config& config);

}  // namespace lm
}  // namespace tfd

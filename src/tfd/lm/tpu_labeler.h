// The TPU composite labeler — the NVML-labeler analogue.
//
// Reference parity: internal/lm/nvml.go:29-72 (NewNVMLLabeler): Init the
// manager, short-circuit to empty on 0 devices, then merge version +
// mig-capability + resource labelers, and Shutdown. All labels are computed
// eagerly here (as the reference does) so the returned labeler is pure data.
#pragma once

#include "tfd/config/config.h"
#include "tfd/lm/labeler.h"
#include "tfd/resource/types.h"

namespace tfd {
namespace lm {

Result<LabelerPtr> NewTpuLabeler(const resource::ManagerPtr& manager,
                                 const config::Config& config);

// Process-wide count of NewTpuLabeler invocations (label-pipeline
// builds). The fragment cache's tests assert a no-op pass loop builds
// the pipeline exactly once instead of once per pass.
long long TpuLabelerBuilds();

}  // namespace lm
}  // namespace tfd

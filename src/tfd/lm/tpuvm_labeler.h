// TPU-VM / multi-slice labeler — the vGPU-path analogue.
//
// Reference parity: internal/lm/vgpu.go:32-55 + internal/vgpu (PCI
// vendor-capability sniffing for hypervisor-hosted GPUs → vgpu.present /
// host-driver-version / host-driver-branch). On TPU the "am I virtualized,
// and what does the host say about me" facts live in GCE instance metadata,
// not PCI config space:
//   google.com/tpu-vm.present      = GCE VM with a TPU accelerator-type
//   google.com/tpu-vm.preemptible  = instance/scheduling/preemptible
//   google.com/tpu-vm.spot         = provisioning-model == SPOT
//   google.com/tpu-vm.zone         = instance zone (leaf)
// Multi-slice (DCN-connected slices, BASELINE config 5) identity comes from
// the MEGASCALE coordinates (tpu-env bag or process env):
//   google.com/tpu.multislice.present     = true|false
//   google.com/tpu.multislice.slice-id    = this slice's index
//   google.com/tpu.multislice.num-slices  = slices in the job
// Non-GCE nodes and unreachable metadata contribute no labels (empty), the
// same graceful degradation as the reference's vGPU probe on bare metal.
#pragma once

#include "tfd/config/config.h"
#include "tfd/lm/labeler.h"

namespace tfd {
namespace lm {

LabelerPtr NewTpuVmLabeler(const config::Config& config);

}  // namespace lm
}  // namespace tfd

#include "tfd/lm/timestamp.h"

#include <ctime>

#include "tfd/lm/schema.h"

namespace tfd {
namespace lm {

LabelerPtr NewTimestampLabeler(const config::Config& config) {
  if (config.flags.no_timestamp) return Empty();
  // Stamped ONCE per config load (the labeler is constructed per run
  // and answers statically), mirroring the reference's sleep-loop
  // contract: the label file's mtime advances every interval but its
  // CONTENT — including this timestamp — stays constant between
  // reloads (gpu-feature-discovery main_test.go:184-271, asserted here
  // by tests/test_cli.py). That contract is also what exempts
  // google.com/tfd.timestamp from dirtiness on the no-op fast path: a
  // per-PASS stamp would make every pass look changed, defeating the
  // byte-compare sink skip (cmd/ PassPlan) outright. Liveness is
  // proven by the mtime touch + tfd_last_rewrite_timestamp_seconds,
  // not by churning this value.
  Labels labels;
  labels[kTimestampLabel] = std::to_string(std::time(nullptr));
  return std::make_unique<StaticLabeler>(std::move(labels));
}

}  // namespace lm
}  // namespace tfd

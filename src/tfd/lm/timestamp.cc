#include "tfd/lm/timestamp.h"

#include <ctime>

#include "tfd/lm/schema.h"

namespace tfd {
namespace lm {

LabelerPtr NewTimestampLabeler(const config::Config& config) {
  if (config.flags.no_timestamp) return Empty();
  Labels labels;
  labels[kTimestampLabel] = std::to_string(std::time(nullptr));
  return std::make_unique<StaticLabeler>(std::move(labels));
}

}  // namespace lm
}  // namespace tfd

#include "tfd/lm/labeler.h"

namespace tfd {
namespace lm {

LabelerPtr Merge(std::vector<LabelerPtr> children) {
  return std::make_unique<MergedLabeler>(std::move(children));
}

}  // namespace lm
}  // namespace tfd

#include "tfd/lm/merge.h"

#include "tfd/lm/labeler.h"

namespace tfd {
namespace lm {

LabelerPtr Merge(std::vector<LabelerPtr> children) {
  return std::make_unique<MergedLabeler>(std::move(children));
}

const char* DiffOpName(LabelDiffEntry::Op op) {
  switch (op) {
    case LabelDiffEntry::Op::kAdded:
      return "added";
    case LabelDiffEntry::Op::kRemoved:
      return "removed";
    case LabelDiffEntry::Op::kChanged:
      return "changed";
  }
  return "added";
}

std::vector<LabelDiffEntry> DiffLabels(const Labels& previous,
                                       const Labels& next) {
  std::vector<LabelDiffEntry> out;
  auto p = previous.begin();
  auto n = next.begin();
  while (p != previous.end() || n != next.end()) {
    LabelDiffEntry entry;
    if (n == next.end() ||
        (p != previous.end() && p->first < n->first)) {
      entry.op = LabelDiffEntry::Op::kRemoved;
      entry.key = p->first;
      entry.old_value = p->second;
      ++p;
    } else if (p == previous.end() || n->first < p->first) {
      entry.op = LabelDiffEntry::Op::kAdded;
      entry.key = n->first;
      entry.new_value = n->second;
      ++n;
    } else {
      if (p->second != n->second) {
        entry.op = LabelDiffEntry::Op::kChanged;
        entry.key = n->first;
        entry.old_value = p->second;
        entry.new_value = n->second;
        ++p;
        ++n;
        out.push_back(std::move(entry));
        continue;
      }
      ++p;
      ++n;
      continue;
    }
    out.push_back(std::move(entry));
  }
  return out;
}

std::string LabelKeyPrefix(const std::string& key) {
  size_t slash = key.find('/');
  size_t dot = key.find('.', slash == std::string::npos ? 0 : slash + 1);
  if (dot == std::string::npos) return key;
  return key.substr(0, dot);
}

}  // namespace lm
}  // namespace tfd

// The --device-health=full measured-silicon probe exec.
//
// Runs the --health-exec command (default `python3 -m tpufd health`)
// and parses its google.com/tpu.health.* key=value stdout lines into
// labels, dropping keys outside the health prefix or with invalid
// names/values (a buggy probe must neither overwrite, say, the product
// label nor crash-loop the daemon with an apiserver-rejected key). On
// any failure the ok label is forced to "false".
//
// Lived inside the TPU labeler until the probe scheduler
// (sched/sources.cc) took over its cadence: the exec can legitimately
// run for minutes, so it belongs on the health worker, not the rewrite
// path. The oneshot round still runs it synchronously.
#pragma once

#include "tfd/config/config.h"
#include "tfd/lm/labeler.h"

namespace tfd {
namespace lm {

// `chip_count` (>= 0) rides into the probe's environment as
// TFD_CHIP_COUNT so its published labels can carry the enumeration
// cross-check (tpufd/health.py devices-consistent).
Labels RunHealthExec(const config::Config& config, int chip_count);

}  // namespace lm
}  // namespace tfd

#include "tfd/lm/tpuvm_labeler.h"

#include <cstdlib>

#include "tfd/gce/metadata.h"
#include "tfd/lm/schema.h"
#include "tfd/util/strings.h"

namespace tfd {
namespace lm {

namespace {

class TpuVmLabeler : public Labeler {
 public:
  explicit TpuVmLabeler(std::string endpoint)
      : client_(std::move(endpoint)) {}

  Result<Labels> GetLabels() override {
    Labels labels;
    if (!client_.Available()) return labels;  // not on GCE: contribute none

    Result<std::string> accel = client_.AcceleratorType();
    bool is_tpu_vm = accel.ok() && !accel->empty();
    labels[kTpuVmPresent] = is_tpu_vm ? "true" : "false";
    if (!is_tpu_vm) return labels;

    Result<bool> preemptible = client_.Preemptible();
    if (preemptible.ok()) {
      labels[kTpuVmPreemptible] = *preemptible ? "true" : "false";
    }
    Result<std::string> model =
        client_.Get("instance/scheduling/provisioning-model");
    if (model.ok()) {
      labels[kTpuVmSpot] =
          ToLower(TrimSpace(*model)) == "spot" ? "true" : "false";
    }
    Result<std::string> zone = client_.Get("instance/zone");
    if (zone.ok()) {
      std::vector<std::string> parts = SplitString(TrimSpace(*zone), '/');
      labels[kTpuVmZone] = StrictLabelValue(parts.back());
    }

    // Multi-slice coordinates: prefer the tpu-env bag, fall back to the
    // process environment (GKE injects MEGASCALE_* into multislice pods).
    std::string slice_id;
    std::string num_slices;
    Result<std::map<std::string, std::string>> env = client_.TpuEnv();
    if (env.ok()) {
      auto get = [&](const char* key) -> std::string {
        auto it = env->find(key);
        return it == env->end() ? "" : it->second;
      };
      slice_id = get("MEGASCALE_SLICE_ID");
      num_slices = get("MEGASCALE_NUM_SLICES");

      // Runtime/agent versions (the vgpu.host-driver-version/branch
      // analogue, reference internal/lm/vgpu.go:51-52): control-plane
      // version facts that survive when the chips are held by a training
      // job and the PJRT-side libtpu.version.* labels are unavailable.
      // Absent-not-empty: StrictLabelValue can trim a garbage value
      // ("---") to "", and an empty-valued version label would read as
      // "version known to be empty" rather than "unknown".
      std::string runtime_version =
          StrictLabelValue(TrimSpace(get("RUNTIME_VERSION")));
      if (!runtime_version.empty()) {
        labels[kTpuVmRuntimeVersion] = runtime_version;
      }
      // AGENT_BOOTSTRAP_IMAGE is an image ref ("gcr.io/.../agent:TAG");
      // the tag is the agent version. A ':' before the last '/' is a
      // registry port, not a tag; an OCI digest suffix ("@sha256:...")
      // is not a version — drop it (keeping any tag before it).
      std::string agent_image = TrimSpace(get("AGENT_BOOTSTRAP_IMAGE"));
      size_t at = agent_image.find('@');
      if (at != std::string::npos) agent_image = agent_image.substr(0, at);
      size_t colon = agent_image.rfind(':');
      size_t slash = agent_image.rfind('/');
      if (colon != std::string::npos &&
          (slash == std::string::npos || colon > slash) &&
          colon + 1 < agent_image.size()) {
        std::string tag = StrictLabelValue(agent_image.substr(colon + 1));
        if (!tag.empty()) labels[kTpuVmAgentVersion] = tag;
      }
    }
    if (slice_id.empty()) {
      if (const char* v = std::getenv("MEGASCALE_SLICE_ID")) slice_id = v;
    }
    if (num_slices.empty()) {
      if (const char* v = std::getenv("MEGASCALE_NUM_SLICES")) {
        num_slices = v;
      }
    }
    bool multislice = !slice_id.empty() || !num_slices.empty();
    labels[kMultislicePresent] = multislice ? "true" : "false";
    if (!slice_id.empty()) {
      labels[kMultisliceSliceId] = StrictLabelValue(slice_id);
    }
    if (!num_slices.empty()) {
      labels[kMultisliceNumSlices] = StrictLabelValue(num_slices);
    }
    return labels;
  }

 private:
  gce::MetadataClient client_;
};

}  // namespace

LabelerPtr NewTpuVmLabeler(const config::Config& config) {
  return std::make_unique<TpuVmLabeler>(config.flags.metadata_endpoint);
}

}  // namespace lm
}  // namespace tfd

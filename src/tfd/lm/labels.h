// Label output sinks.
//
// Reference parity: internal/lm/labels.go:49-138 — Output() dispatches to
// (a) stdout when no path is configured, (b) atomic file write for the NFD
// `local` source, or (c) a NodeFeature custom resource when the NodeFeature
// API is enabled (labels.go:141-184, implemented in tfd/k8s).
#pragma once

#include <string>

#include "tfd/lm/labeler.h"
#include "tfd/util/status.h"

namespace tfd {
namespace lm {

// Serializes labels as sorted "key=value\n" lines.
std::string FormatLabels(const Labels& labels);

// Writes labels to `path` atomically, or to stdout if `path` is empty
// (reference labels.go:62-65).
// On failure, `*transient` (if non-null) mirrors the CR sink's
// contract: true when retrying next interval can plausibly succeed
// without operator action (ENOSPC, EDQUOT, EIO — conditions that
// drain), false for misconfiguration (EACCES, EROFS, EXDEV) where a
// visible crash-loop beats silent retrying.
Status OutputToFile(const Labels& labels, const std::string& path,
                    bool* transient = nullptr);

}  // namespace lm
}  // namespace tfd

// Label output sinks.
//
// Reference parity: internal/lm/labels.go:49-138 — Output() dispatches to
// (a) stdout when no path is configured, (b) atomic file write for the NFD
// `local` source, or (c) a NodeFeature custom resource when the NodeFeature
// API is enabled (labels.go:141-184, implemented in tfd/k8s).
#pragma once

#include <string>

#include "tfd/lm/labeler.h"
#include "tfd/util/status.h"

namespace tfd {
namespace lm {

// Serializes labels as sorted "key=value\n" lines.
std::string FormatLabels(const Labels& labels);

// Writes labels to `path` atomically, or to stdout if `path` is empty
// (reference labels.go:62-65).
Status OutputToFile(const Labels& labels, const std::string& path);

}  // namespace lm
}  // namespace tfd

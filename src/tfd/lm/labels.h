// Label output sinks.
//
// Reference parity: internal/lm/labels.go:49-138 — Output() dispatches to
// (a) stdout when no path is configured, (b) atomic file write for the NFD
// `local` source, or (c) a NodeFeature custom resource when the NodeFeature
// API is enabled (labels.go:141-184, implemented in tfd/k8s).
#pragma once

#include <string>

#include "tfd/lm/labeler.h"
#include "tfd/util/status.h"

namespace tfd {
namespace lm {

// Serializes labels as sorted "key=value\n" lines.
std::string FormatLabels(const Labels& labels);

// One-shot serializer for the hot path: serializes into `*out`,
// reusing its capacity — the daemon keeps one pre-sized buffer across
// passes so a steady-state serialization allocates nothing after the
// first pass.
void FormatLabelsInto(const Labels& labels, std::string* out);

// Writes labels to `path` atomically, or to stdout if `path` is empty
// (reference labels.go:62-65).
// On failure, `*transient` (if non-null) mirrors the CR sink's
// contract: true when retrying next interval can plausibly succeed
// without operator action (ENOSPC, EDQUOT, EIO — conditions that
// drain), false for misconfiguration (EACCES, EROFS, EXDEV) where a
// visible crash-loop beats silent retrying.
Status OutputToFile(const Labels& labels, const std::string& path,
                    bool* transient = nullptr);

// The pre-serialized variant OutputToFile wraps: same sinks, same
// fault point, same journaling and transient classification, but the
// caller owns serialization (the pass pipeline serializes once into
// its reused buffer and hands the same bytes to the sink, the
// byte-compare skip, and /debug/labels). `label_count` only feeds the
// journal record.
Status OutputBytesToFile(const std::string& body, size_t label_count,
                         const std::string& path,
                         bool* transient = nullptr);

// Advances the label file's mtime WITHOUT rewriting it — the fast
// path's sink-write skip. The mtime advance is the rewrite-cadence
// proof the reference contract (and the soak harness) watches, at the
// cost of one utimensat instead of a write+fsync+rename+fsync. Fails
// (so the caller falls back to a real write) when the file is missing
// or its size no longer matches `expected_size` — an externally
// deleted/truncated label file must be healed by the next pass, not
// skipped over.
Status TouchLabelFile(const std::string& path, size_t expected_size);

}  // namespace lm
}  // namespace tfd

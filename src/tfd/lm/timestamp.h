// Timestamp labeler (reference internal/lm/timestamp.go:29-37):
// google.com/tfd.timestamp=<unix-seconds>, disabled by --no-timestamp.
// Like the reference, the value is fixed at construction so the label stays
// constant across sleep-loop rewrites until a config reload
// (main_test.go:266-267 asserts exactly this).
#pragma once

#include "tfd/config/config.h"
#include "tfd/lm/labeler.h"

namespace tfd {
namespace lm {

LabelerPtr NewTimestampLabeler(const config::Config& config);

}  // namespace lm
}  // namespace tfd

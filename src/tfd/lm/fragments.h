// Per-source label-fragment caches + the pass-signature hasher: the
// incremental half of the hot-path refactor (cmd/ PassPlan).
//
// A steady-state pass used to rebuild the whole labeler pipeline —
// NewTpuLabeler re-ran against the serving snapshot, the host labelers
// re-answered, the merge re-allocated — even when no snapshot
// generation had moved. The FragmentCache memoizes each labeler's
// rendered fragment keyed by what it actually depends on:
//   - the device (tpu) fragment: the serving source's full-content
//     fingerprint (sched::FullSnapshotFingerprint, plus probe-ms when a
//     basic-health config publishes it) and the config generation —
//     identical re-probes reuse the fragment, so only the DIRTY
//     source's labeler re-runs;
//   - the host-derived fragments (timestamp, machine-type, tpu-vm):
//     the config generation, plus a caller-driven force_refresh on the
//     anti-entropy cadence — their FACTS are static per VM (and the
//     timestamp label is stamped per load by contract, which is
//     exactly what keeps it from defeating no-op detection), but the
//     machine-type/tpu-vm READS are live IO whose transient failures
//     must not stay frozen in the cache until the next reload.
// The merge is then rebuilt from cached fragments; serialization
// reuses one pre-sized buffer (lm::FormatLabelsInto).
//
// PassSignature is the order-sensitive FNV-1a accumulator the planner
// digests a pass's inputs into (per-source fingerprints + tiers, the
// serve decision, the config generation, the quarantine set): equal
// digests mean the render would reproduce the published bytes, so the
// pass can short-circuit.
#pragma once

#include <map>
#include <string>

#include "tfd/config/config.h"
#include "tfd/lm/labeler.h"
#include "tfd/resource/types.h"

namespace tfd {
namespace lm {

class PassSignature {
 public:
  void Mix(const std::string& field);
  void MixU64(uint64_t value);
  // Never 0 (0 means "no signature" to the pass cache).
  uint64_t Digest() const;

 private:
  uint64_t hash_ = 1469598103934665603ULL;  // FNV-1a 64 offset basis
};

class FragmentCache {
 public:
  // The device labeler's fragment for the serving snapshot,
  // re-rendered only when (source, render_key, config_generation)
  // moved. `render_key` must capture everything the fragment depends
  // on besides the config: the serving source's content fingerprint,
  // plus its probe-ms when the config publishes basic-health labels.
  Result<Labels> TpuFragment(const resource::ManagerPtr& manager,
                             const std::string& source, uint64_t render_key,
                             int config_generation,
                             const config::Config& config);

  // A host-derived labeler's fragment (timestamp, machine-type,
  // tpu-vm). The timestamp labeler is static per config load by
  // contract; machine-type and tpu-vm carry per-VM-static FACTS read
  // through live IO (metadata HTTP, DMI file) that can transiently
  // degrade — so the caller passes `force_refresh` on its anti-entropy
  // cadence (and on forced-full passes) to re-render and re-cache, and
  // the fragment is otherwise reused within a config generation.
  Result<Labels> HostFragment(const std::string& name, Labeler& labeler,
                              int config_generation,
                              bool force_refresh = false);

  // Drops every fragment. Called at the top of each config-load run:
  // labeler instances are rebuilt per load (a failed reload re-runs
  // under the SAME generation but with a fresh timestamp), so cached
  // fragments must not outlive the instances that rendered them.
  void Invalidate();

 private:
  struct Entry {
    bool valid = false;
    std::string source;
    uint64_t key = 0;
    int config_generation = -1;
    Labels labels;
  };
  Entry tpu_;
  std::map<std::string, Entry> host_;
};

}  // namespace lm
}  // namespace tfd

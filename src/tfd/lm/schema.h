// The google.com/tpu.* label schema.
//
// Reference schema (tests/expected-output*.txt): nvidia.com/gfd.timestamp,
// cuda.driver.*, cuda.runtime.*, gpu.machine/count/replicas/product/memory/
// family/compute.*, mig.capable, mig.strategy, mig-<profile>.*.
//
// TPU mapping (BASELINE.json north star):
//   gfd.timestamp        → google.com/tfd.timestamp
//   cuda.driver.*        → google.com/libtpu.version.{major,minor,patch}
//   cuda.runtime.*       → google.com/tpu.runtime.{major,minor}  (PJRT C API)
//   gpu.machine          → google.com/tpu.machine (GCE machine type, DMI fallback)
//   gpu.count/replicas/product/memory → google.com/tpu.{count,replicas,product,memory}
//   gpu.family           → google.com/tpu.family        (v2..v6e)
//   gpu.compute.major/minor → google.com/tpu.generation (2..6)
//   mig.capable          → google.com/tpu.slice.capable
//   mig.strategy         → google.com/tpu.slice.strategy
//   mig-<profile>.*      → google.com/tpu-<shape>.*     (mixed strategy)
// TPU-only additions: tpu.cores, tpu.backend, tpu.topology, tpu.ici.wrap,
// tpu.slice.{shape,hosts,chips-per-host,worker-id}, tpu.accelerator-type,
// tpu-vm.*, tpu.multislice.*.
#pragma once

namespace tfd {
namespace lm {

inline constexpr char kPrefix[] = "google.com/";

// Core.
inline constexpr char kTimestampLabel[] = "google.com/tfd.timestamp";
inline constexpr char kMachineLabel[] = "google.com/tpu.machine";
inline constexpr char kBackendLabel[] = "google.com/tpu.backend";

// Versions.
inline constexpr char kLibtpuMajor[] = "google.com/libtpu.version.major";
inline constexpr char kLibtpuMinor[] = "google.com/libtpu.version.minor";
inline constexpr char kLibtpuPatch[] = "google.com/libtpu.version.patch";
inline constexpr char kRuntimeMajor[] = "google.com/tpu.runtime.major";
inline constexpr char kRuntimeMinor[] = "google.com/tpu.runtime.minor";

// Slice strategy.
inline constexpr char kSliceCapable[] = "google.com/tpu.slice.capable";
inline constexpr char kSliceStrategy[] = "google.com/tpu.slice.strategy";

// Topology (emitted when known).
inline constexpr char kAcceleratorType[] = "google.com/tpu.accelerator-type";
inline constexpr char kTopologyLabel[] = "google.com/tpu.topology";
inline constexpr char kIciWrap[] = "google.com/tpu.ici.wrap";
// Per-chip ICI link count — a hardware attribute of the family's fabric
// (2D torus: 4 links, 3D torus: 6), the last of SURVEY §5's
// MIG-attribute analogues (HBM GiB / TensorCores / ICI links).
inline constexpr char kIciLinks[] = "google.com/tpu.ici.links";
inline constexpr char kSliceShape[] = "google.com/tpu.slice.shape";
inline constexpr char kSliceHosts[] = "google.com/tpu.slice.hosts";
inline constexpr char kSliceChipsPerHost[] =
    "google.com/tpu.slice.chips-per-host";
inline constexpr char kSliceWorkerId[] = "google.com/tpu.slice.worker-id";

// Slice coherence (slice/coord.h, --slice-coordination): published from
// the slice's AGREED verdict only — every member of a slice carries
// byte-identical values for these keys, or none at all (a member that
// loses the coordination blackboard self-demotes by dropping them).
inline constexpr char kSliceId[] = "google.com/tpu.slice.id";
inline constexpr char kSliceHealthyHosts[] =
    "google.com/tpu.slice.healthy-hosts";
inline constexpr char kSliceDegraded[] = "google.com/tpu.slice.degraded";
// min (worst) of the member hosts' tpu.perf.class — a slice is as fast
// as its slowest host.
inline constexpr char kSliceClass[] = "google.com/tpu.slice.class";
// The provenance labeler name for coordination-published labels — the
// governor distinguishes the verdict's tpu.slice.hosts (exempt, slice
// contract) from the topology labeler's (governed, per-host fact) by
// it.
inline constexpr char kSliceCoordLabeler[] = "slice-coord";

// TPU-VM detection (vGPU-path analogue) and multi-slice identity.
inline constexpr char kTpuVmPresent[] = "google.com/tpu-vm.present";
inline constexpr char kTpuVmPreemptible[] = "google.com/tpu-vm.preemptible";
inline constexpr char kTpuVmSpot[] = "google.com/tpu-vm.spot";
inline constexpr char kTpuVmZone[] = "google.com/tpu-vm.zone";
// TPU runtime/agent versions from the control plane (tpu-env) — the
// vgpu.host-driver-version / host-driver-branch analogue (reference
// internal/lm/vgpu.go:51-52, sourced hypervisor-side in
// internal/vgpu/vgpu.go:108-153): version labels that survive on a node
// whose chips are busy (no PJRT client, so no libtpu.version.* labels).
inline constexpr char kTpuVmRuntimeVersion[] =
    "google.com/tpu-vm.runtime-version";
inline constexpr char kTpuVmAgentVersion[] =
    "google.com/tpu-vm.agent-version";
inline constexpr char kMultislicePresent[] =
    "google.com/tpu.multislice.present";
inline constexpr char kMultisliceSliceId[] =
    "google.com/tpu.multislice.slice-id";
inline constexpr char kMultisliceNumSlices[] =
    "google.com/tpu.multislice.num-slices";

// Device health. --device-health=basic: init + enumeration succeeded and
// its latency. --device-health=full additionally merges measured silicon
// labels (matmul-tflops, hbm-gbps, allreduce-gbps, ...) produced by the
// health exec (tpufd.health) under the same prefix.
inline constexpr char kHealthPrefix[] = "google.com/tpu.health.";
inline constexpr char kHealthOk[] = "google.com/tpu.health.ok";
inline constexpr char kHealthDevices[] = "google.com/tpu.health.devices";
inline constexpr char kHealthProbeMs[] = "google.com/tpu.health.probe-ms";
// Anti-flap layer (healthsm/): present while ANY health-state-machine
// key is quarantined — the flapping source's labels are held at their
// last-good values until it earns recovery.
inline constexpr char kHealthQuarantined[] =
    "google.com/tpu.health.quarantined";
// Per-chip health lines from the health exec
// ("google.com/tpu.health.device-<i>-ok=true|false"): each chip gets
// its own debounced state machine entry (healthsm::ChipKey).
inline constexpr char kHealthDevicePrefix[] =
    "google.com/tpu.health.device-";

// Measured performance classes (perf/): published by the cached
// perf-characterization source — micro-benchmark results amortized to
// one measurement per hardware-identity fingerprint, persisted in the
// warm-restart state file. `class` is gold|silver|degraded; schedulers
// route latency-critical serving to class=gold nodes.
inline constexpr char kPerfPrefix[] = "google.com/tpu.perf.";
inline constexpr char kPerfMatmulTflops[] =
    "google.com/tpu.perf.matmul-tflops";
inline constexpr char kPerfHbmGbps[] = "google.com/tpu.perf.hbm-gbps";
inline constexpr char kPerfIciGbps[] = "google.com/tpu.perf.ici-gbps";
inline constexpr char kPerfPctOfRated[] =
    "google.com/tpu.perf.pct-of-rated";
inline constexpr char kPerfClass[] = "google.com/tpu.perf.class";

// Probe plugins (plugin/plugin.h, --plugin-dir): the RECOMMENDED home
// for out-of-tree plugin label namespaces — a plugin named "foo"
// conventionally declares "google.com/tpu.plugin.foo." as its
// label_prefix. Not enforced (the device-health port legitimately
// declares the tpu.health. namespace); what IS enforced is that every
// key a plugin publishes lives under its OWN declared prefix, that no
// two plugins' prefixes overlap, and that plugin labels merge at the
// lowest precedence so first-party labels always win.
inline constexpr char kPluginNamespacePrefix[] = "google.com/tpu.plugin.";

// Preemption-aware lifecycle (sched/sources.cc "lifecycle" source,
// --lifecycle-watch): edge-triggered fast-path labels — present ONLY
// while the condition holds (absence = normal), exempt from the
// governor's hold-down like the quarantine annotation (the conservative
// direction must publish within one probe tick, and the inputs — the
// GCE preemption notice, a kubelet taint — are already debounced
// upstream). The slice leader folds a preempting member into a
// proactive tpu.slice.degraded verdict (slice/coord.h
// MemberReport.preempting).
inline constexpr char kLifecyclePrefix[] = "google.com/tpu.lifecycle.";
inline constexpr char kLifecyclePreemptImminent[] =
    "google.com/tpu.lifecycle.preempt-imminent";
inline constexpr char kLifecycleDraining[] =
    "google.com/tpu.lifecycle.draining";

// Cluster inventory rollups (agg/, --mode=aggregator): published on the
// cluster-scoped output object (NodeFeature CR "tfd-cluster-inventory"),
// never on a node. Maintained INCREMENTALLY — every watch delta retires
// the node's old contribution and applies the new one (agg/agg.h).
inline constexpr char kInventorySlices[] =
    "google.com/tpu.slice-inventory.slices";
inline constexpr char kInventoryHealthySlices[] =
    "google.com/tpu.slice-inventory.healthy-slices";
inline constexpr char kInventoryDegradedSlices[] =
    "google.com/tpu.slice-inventory.degraded-slices";
inline constexpr char kCapacityPrefix[] = "google.com/tpu.capacity.";
inline constexpr char kFleetNodes[] = "google.com/tpu.fleet.nodes";
inline constexpr char kFleetPreempting[] =
    "google.com/tpu.fleet.preempting";
inline constexpr char kMultisliceGroups[] =
    "google.com/tpu.multislice.groups";
// Fleet-relative perf floors (ROADMAP #4a): the fleet's measured
// distribution, published so on-node daemons can classify "degraded"
// as "below this fleet's p10" (--perf-fleet-floor-source).
inline constexpr char kFleetPerfPrefix[] = "google.com/tpu.fleet.perf.";
inline constexpr char kFleetMatmulP10[] =
    "google.com/tpu.fleet.perf.matmul-p10";
inline constexpr char kFleetMatmulP50[] =
    "google.com/tpu.fleet.perf.matmul-p50";
inline constexpr char kFleetHbmP10[] = "google.com/tpu.fleet.perf.hbm-p10";
inline constexpr char kFleetHbmP50[] = "google.com/tpu.fleet.perf.hbm-p50";

// Fleet SLO engine (agg/ + obs/slo.h): merged pass-stage latency
// percentiles and multi-window burn-rate verdicts, published on the
// cluster inventory object next to the perf floors. Keys are built
// from these prefixes plus the stage name (agg::kSloStages):
//   tpu.obs.stage.<stage>.{p50,p99}-ms   (Fixed3 milliseconds)
//   tpu.slo.<stage>.burn                 ("true"/"false")
inline constexpr char kObsStagePrefix[] = "google.com/tpu.obs.stage.";
inline constexpr char kSloBurnPrefix[] = "google.com/tpu.slo.";

// Sharded aggregation tree (agg/, --agg-shard / --agg-merge-shards):
// each lease-elected L1 shard publishes a PARTIAL rollup CR
// ("tfd-inventory-shard-<i>") whose spec.labels carry the shard's
// serialized aggregate — counter maps and sparse sketch buckets, not
// scalars — under these keys. The L2 root consumes the partials through
// the same collection watch, merges them O(delta) (retire old partial,
// admit new), and republishes the byte-compatible cluster inventory.
// Values are annotation-safe (alnum plus ':' ',' '-' '.' '='); slice
// and multislice ids must not contain ':' or ','.
inline constexpr char kAggPrefix[] = "google.com/tfd.agg.";
inline constexpr char kAggTier[] = "google.com/tfd.agg.tier";
inline constexpr char kAggShard[] = "google.com/tfd.agg.shard";
inline constexpr char kAggNodes[] = "google.com/tfd.agg.nodes";
inline constexpr char kAggPreempting[] = "google.com/tfd.agg.preempting";
inline constexpr char kAggSlices[] = "google.com/tfd.agg.slices";
inline constexpr char kAggCapacity[] = "google.com/tfd.agg.capacity";
inline constexpr char kAggMultislice[] = "google.com/tfd.agg.multislice";
inline constexpr char kAggMatmul[] = "google.com/tfd.agg.matmul";
inline constexpr char kAggHbm[] = "google.com/tfd.agg.hbm";
inline constexpr char kAggStageSlo[] = "google.com/tfd.agg.stage-slo";
// The kAggTier value an L1 partial carries ("partial"); the merged root
// output carries no tier key (byte-compat with the flat aggregator).
inline constexpr char kAggTierPartial[] = "partial";

// Degradation ladder (sched/): present only when the daemon is serving
// CACHED device facts because the probe source missed its cadence
// (chips held by a training job, wedged libtpu). Age is whole seconds
// since the serving snapshot's probe succeeded. Never emitted on a
// healthy node or by the metadata-only rung, so steady-state label sets
// stay byte-identical to the pre-scheduler daemon.
inline constexpr char kSnapshotAge[] =
    "google.com/tpu.snapshot-age-seconds";
inline constexpr char kDegraded[] = "google.com/tpu.degraded";

// The value used when a slice strategy's validation fails — the analogue of
// the reference's "MIG-INVALID" product (mig-strategy.go:243-262).
inline constexpr char kSliceInvalid[] = "SLICE-INVALID";

}  // namespace lm
}  // namespace tfd

#include "tfd/lm/labels.h"

#include <iostream>
#include <sstream>

#include "tfd/util/file.h"

namespace tfd {
namespace lm {

std::string FormatLabels(const Labels& labels) {
  std::ostringstream out;
  for (const auto& [k, v] : labels) {
    out << k << "=" << v << "\n";
  }
  return out.str();
}

Status OutputToFile(const Labels& labels, const std::string& path) {
  std::string body = FormatLabels(labels);
  if (path.empty()) {
    std::cout << body;
    std::cout.flush();
    return Status::Ok();
  }
  return WriteFileAtomically(path, body);
}

}  // namespace lm
}  // namespace tfd

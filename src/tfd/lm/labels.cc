#include "tfd/lm/labels.h"

#include <iostream>
#include <sstream>

#include "tfd/obs/journal.h"
#include "tfd/util/file.h"

namespace tfd {
namespace lm {

std::string FormatLabels(const Labels& labels) {
  std::ostringstream out;
  for (const auto& [k, v] : labels) {
    out << k << "=" << v << "\n";
  }
  return out.str();
}

Status OutputToFile(const Labels& labels, const std::string& path) {
  std::string body = FormatLabels(labels);
  if (path.empty()) {
    std::cout << body;
    std::cout.flush();
    obs::DefaultJournal().Record(
        "sink-write", "stdout", "wrote labels to stdout",
        {{"labels", std::to_string(labels.size())}, {"ok", "true"}});
    return Status::Ok();
  }
  Status s = WriteFileAtomically(path, body);
  obs::DefaultJournal().Record(
      "sink-write", "file",
      s.ok() ? "wrote labels to " + path
             : "label file write failed: " + s.message(),
      {{"labels", std::to_string(labels.size())},
       {"path", path},
       {"ok", s.ok() ? "true" : "false"},
       {"error", s.ok() ? "" : s.message()}});
  return s;
}

}  // namespace lm
}  // namespace tfd

#include "tfd/lm/labels.h"

#include <errno.h>
#include <string.h>

#include <iostream>
#include <sstream>

#include "tfd/fault/fault.h"
#include "tfd/obs/journal.h"
#include "tfd/util/file.h"

namespace tfd {
namespace lm {

std::string FormatLabels(const Labels& labels) {
  std::ostringstream out;
  for (const auto& [k, v] : labels) {
    out << k << "=" << v << "\n";
  }
  return out.str();
}

namespace {

// Filesystem errors worth retrying next interval: conditions that
// drain on their own. Permission/mount-shape errors are configuration
// and should crash-loop visibly instead.
bool TransientFsErrno(int err) {
  return err == ENOSPC || err == EDQUOT || err == EIO || err == EINTR ||
         err == EAGAIN || err == ENOMEM;
}

}  // namespace

Status OutputToFile(const Labels& labels, const std::string& path,
                    bool* transient) {
  if (transient != nullptr) *transient = false;
  std::string body = FormatLabels(labels);
  if (path.empty()) {
    std::cout << body;
    std::cout.flush();
    obs::DefaultJournal().Record(
        "sink-write", "stdout", "wrote labels to stdout",
        {{"labels", std::to_string(labels.size())}, {"ok", "true"}});
    return Status::Ok();
  }
  Status s;
  int write_errno = 0;
  // Fault point "sink.file": a hang has already slept (the delay is the
  // fault); errno/fail become the write error the daemon's transient
  // handling — and the chaos soak's never-torn invariant — must absorb.
  // The injected failure SKIPS the real write entirely: the previous
  // label file stays in place untouched, exactly like a full disk.
  if (fault::Action injected = fault::Check("sink.file")) {
    if (injected.kind == fault::Action::Kind::kErrno) {
      write_errno = injected.errno_value;
      s = Status::Error("write to " + path + " failed: " +
                        strerror(injected.errno_value) + " (injected)");
    } else if (injected.kind == fault::Action::Kind::kFail) {
      s = Status::Error("write to " + path + " failed: " +
                        injected.message);
    } else {
      s = WriteFileAtomically(path, body, &write_errno);
    }
  } else {
    s = WriteFileAtomically(path, body, &write_errno);
  }
  if (!s.ok() && transient != nullptr) {
    *transient = TransientFsErrno(write_errno);
  }
  obs::DefaultJournal().Record(
      "sink-write", "file",
      s.ok() ? "wrote labels to " + path
             : "label file write failed: " + s.message(),
      {{"labels", std::to_string(labels.size())},
       {"path", path},
       {"ok", s.ok() ? "true" : "false"},
       {"error", s.ok() ? "" : s.message()}});
  return s;
}

}  // namespace lm
}  // namespace tfd

#include "tfd/lm/labels.h"

#include <errno.h>
#include <fcntl.h>
#include <string.h>
#include <sys/stat.h>

#include <iostream>

#include "tfd/fault/fault.h"
#include "tfd/obs/journal.h"
#include "tfd/util/file.h"

namespace tfd {
namespace lm {

void FormatLabelsInto(const Labels& labels, std::string* out) {
  out->clear();
  size_t need = 0;
  for (const auto& [k, v] : labels) need += k.size() + v.size() + 2;
  if (out->capacity() < need) out->reserve(need);
  for (const auto& [k, v] : labels) {
    out->append(k);
    out->push_back('=');
    out->append(v);
    out->push_back('\n');
  }
}

std::string FormatLabels(const Labels& labels) {
  std::string out;
  FormatLabelsInto(labels, &out);
  return out;
}

namespace {

// Filesystem errors worth retrying next interval: conditions that
// drain on their own. Permission/mount-shape errors are configuration
// and should crash-loop visibly instead.
bool TransientFsErrno(int err) {
  return err == ENOSPC || err == EDQUOT || err == EIO || err == EINTR ||
         err == EAGAIN || err == ENOMEM;
}

}  // namespace

Status OutputToFile(const Labels& labels, const std::string& path,
                    bool* transient) {
  return OutputBytesToFile(FormatLabels(labels), labels.size(), path,
                           transient);
}

Status OutputBytesToFile(const std::string& body, size_t label_count,
                         const std::string& path, bool* transient) {
  if (transient != nullptr) *transient = false;
  if (path.empty()) {
    std::cout << body;
    std::cout.flush();
    obs::DefaultJournal().Record(
        "sink-write", "stdout", "wrote labels to stdout",
        {{"labels", std::to_string(label_count)}, {"ok", "true"}});
    return Status::Ok();
  }
  Status s;
  int write_errno = 0;
  // Fault point "sink.file": a hang has already slept (the delay is the
  // fault); errno/fail become the write error the daemon's transient
  // handling — and the chaos soak's never-torn invariant — must absorb.
  // The injected failure SKIPS the real write entirely: the previous
  // label file stays in place untouched, exactly like a full disk.
  if (fault::Action injected = fault::Check("sink.file")) {
    if (injected.kind == fault::Action::Kind::kErrno) {
      write_errno = injected.errno_value;
      s = Status::Error("write to " + path + " failed: " +
                        strerror(injected.errno_value) + " (injected)");
    } else if (injected.kind == fault::Action::Kind::kFail) {
      s = Status::Error("write to " + path + " failed: " +
                        injected.message);
    } else {
      s = WriteFileAtomically(path, body, &write_errno);
    }
  } else {
    s = WriteFileAtomically(path, body, &write_errno);
  }
  if (!s.ok() && transient != nullptr) {
    *transient = TransientFsErrno(write_errno);
  }
  obs::DefaultJournal().Record(
      "sink-write", "file",
      s.ok() ? "wrote labels to " + path
             : "label file write failed: " + s.message(),
      {{"labels", std::to_string(label_count)},
       {"path", path},
       {"ok", s.ok() ? "true" : "false"},
       {"error", s.ok() ? "" : s.message()}});
  return s;
}

Status TouchLabelFile(const std::string& path, size_t expected_size) {
  struct stat st {};
  if (stat(path.c_str(), &st) != 0) {
    return Status::Error("label file " + path + " missing: " +
                         strerror(errno));
  }
  if (!S_ISREG(st.st_mode) ||
      static_cast<size_t>(st.st_size) != expected_size) {
    return Status::Error("label file " + path +
                         " no longer matches the published bytes");
  }
  if (utimensat(AT_FDCWD, path.c_str(), nullptr, 0) != 0) {
    return Status::Error("touch of " + path + " failed: " +
                         strerror(errno));
  }
  return Status::Ok();
}

}  // namespace lm
}  // namespace tfd

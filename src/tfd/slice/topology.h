// TPU accelerator-family knowledge: the table that replaces the reference's
// compute-capability→arch-family map (internal/lm/resource.go:261-284) and
// go-nvlib's MIG profile tables. Everything the labelers need to reason
// about an accelerator type ("v5litepod-16") or a PJRT device kind
// ("TPU v5 lite") without hardware calls lives here.
#pragma once

#include <string>

#include "tfd/slice/shape.h"
#include "tfd/util/status.h"

namespace tfd {
namespace slice {

struct FamilySpec {
  std::string family;       // label value: v2, v3, v4, v5e, v5p, v6e
  std::string product;      // label value: tpu-v2, ..., tpu-v6e
  int generation = 0;       // 2..6
  long long hbm_mib = 0;    // per-chip HBM (MiB)
  int cores_per_chip = 0;   // TensorCores per chip
  int max_chips_per_host = 0;
  int topology_dims = 0;    // 2 = 2D torus, 3 = 3D torus
  // Accelerator-type counts chips (v5e/v6e) or TensorCores (v2/v3/v4/v5p):
  // "v4-8" is 8 cores = 4 chips; "v5litepod-8" is 8 chips.
  bool type_counts_cores = false;
  // Minimum chips for a 3D slice to have torus wraparound links
  // (v4/v5p: a full 4x4x4 cube, i.e. one "pod cube", wraps).
  int wrap_min_chips = 0;
};

// Parsed "v5litepod-16" / "v4-8" / "v2-8".
struct AcceleratorType {
  std::string raw;       // original string
  FamilySpec spec;
  int num_chips = 0;     // whole-slice chips (derived)
  int num_cores = 0;     // whole-slice TensorCores (derived)
};

// Family lookup by short name ("v5e") or its accelerator-type prefix
// ("v5litepod"). Unknown families error.
Result<FamilySpec> LookupFamily(const std::string& name);

// Maps a PJRT device kind string (e.g. "TPU v5 lite", "TPU v4") to a family.
Result<FamilySpec> FamilyFromDeviceKind(const std::string& kind);

// Parses a GCE accelerator-type string like "v2-8", "v4-16", "v5litepod-4",
// "v5p-128", "v6e-8".
Result<AcceleratorType> ParseAcceleratorType(const std::string& text);

// Default slice topology for `num_chips` chips of `family`, matching the
// shapes Google publishes for each slice size (e.g. v5litepod-16 → 4x4,
// v4-16 → 2x2x2). Errors when the chip count has no standard shape.
Result<Shape> DefaultTopology(const FamilySpec& family, int num_chips);

}  // namespace slice
}  // namespace tfd

// TPU accelerator-family knowledge: the table that replaces the reference's
// compute-capability→arch-family map (internal/lm/resource.go:261-284) and
// go-nvlib's MIG profile tables. Everything the labelers need to reason
// about an accelerator type ("v5litepod-16") or a PJRT device kind
// ("TPU v5 lite") without hardware calls lives here.
#pragma once

#include <string>

#include "tfd/slice/shape.h"
#include "tfd/util/status.h"

namespace tfd {
namespace slice {

struct FamilySpec {
  std::string family;       // label value: v2, v3, v4, v5e, v5p, v6e
  std::string product;      // label value: tpu-v2, ..., tpu-v6e
  int generation = 0;       // 2..6
  long long hbm_mib = 0;    // per-chip HBM (MiB)
  int cores_per_chip = 0;   // TensorCores per chip
  int max_chips_per_host = 0;
  int topology_dims = 0;    // 2 = 2D torus, 3 = 3D torus
  // Accelerator-type counts chips (v5e/v6e) or TensorCores (v2/v3/v4/v5p):
  // "v4-8" is 8 cores = 4 chips; "v5litepod-8" is 8 chips.
  bool type_counts_cores = false;
  // Chips in a full pod of this family (2D families wrap only as a full
  // pod; 0 for 3D families, whose wrap rule is per-shape — see
  // ComputeIciWrap).
  int full_pod_chips = 0;
};

// Parsed "v5litepod-16" / "v4-8" / "v2-8".
struct AcceleratorType {
  std::string raw;       // original string
  FamilySpec spec;
  int num_chips = 0;     // whole-slice chips (derived)
  int num_cores = 0;     // whole-slice TensorCores (derived)
};

// Family lookup by short name ("v5e") or its accelerator-type prefix
// ("v5litepod"). Unknown families error.
Result<FamilySpec> LookupFamily(const std::string& name);

// Maps a PJRT device kind string (e.g. "TPU v5 lite", "TPU v4") to a family.
Result<FamilySpec> FamilyFromDeviceKind(const std::string& kind);

// Parses a GCE accelerator-type string like "v2-8", "v4-16", "v5litepod-4",
// "v5p-128", "v6e-8".
Result<AcceleratorType> ParseAcceleratorType(const std::string& text);

// GKE TPU node pools don't carry the Cloud-TPU-VM metadata attributes
// (accelerator-type / tpu-env); their TPU identity lives in the published
// GKE surface instead (GKE docs "TPUs in GKE" machine-type and node-label
// tables):
//   - machine type: ct4p-hightpu-4t, ct5lp-hightpu-{1,4,8}t,
//     ct5l-hightpu-{1,4,8}t, ct5p-hightpu-4t, ct6e-standard-{1,4,8}t —
//     family code + local chip count ("-4t" = 4 TPU chips on the host)
//   - node label cloud.google.com/gke-tpu-accelerator: tpu-v4-podslice,
//     tpu-v5-lite-podslice, tpu-v5-lite-device, tpu-v5p-slice,
//     tpu-v6e-slice
struct GkeMachineType {
  FamilySpec spec;
  int chips_per_host = 0;
};
Result<GkeMachineType> ParseGkeMachineType(const std::string& machine_type);
Result<FamilySpec> FamilyFromGkeAccelerator(const std::string& value);

// Default slice topology for `num_chips` chips of `family`, matching the
// shapes Google publishes for each slice size (e.g. v5litepod-16 → 4x4,
// v4-16 → 2x2x2). Errors when the chip count has no standard shape.
Result<Shape> DefaultTopology(const FamilySpec& family, int num_chips);

// ICI wraparound links for a slice of `family` laid out as `shape`
// (the tpu.ici.wrap label).
//
// Rule (Cloud TPU v4/v5p system-architecture docs): 3D families are built
// from 4x4x4 cubes joined by optical circuit switches; the OCS closes the
// torus only when EVERY dimension is a multiple of 4 (shapes like 4x4x8
// become twisted tori — still wrapped), so a 2x2x2 v4-16 or a 2x8x8 custom
// topology is a mesh with no wrap on any axis. 2D families wrap only as a
// full pod (v2: 16x16 chips, v3: 32x32, v5e/v6e: 16x16); every sub-pod 2D
// slice is a mesh. This replaces the earlier ">= 64 chips" heuristic,
// which mislabeled non-multiple-of-4 custom topologies.
//
// A single bool, deliberately not per-axis: under both published rules
// wrap is all-or-nothing — the OCS closes every axis of a cube-aligned 3D
// slice simultaneously, and a full 2D pod wraps both axes — so no
// published shape has divergent per-axis wrap and a per-axis vector would
// be dead generality (an earlier revision carried one; nothing could ever
// observe axes differing).
bool ComputeIciWrap(const FamilySpec& family, const Shape& shape);

}  // namespace slice
}  // namespace tfd
